"""Native (C++) runtime loader.

Reference parity: the reference ships libmxnet.so found via
python/mxnet/libinfo.py find_lib_path; here the native pieces are small
per-subsystem shared objects built from native/*.cc on first use with the
system toolchain (g++), cached next to the sources. ctypes-based — no
pybind11 dependency (see also src/lib_api.cc for the reference's
ABI-stable plugin approach).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_lock = threading.Lock()
_libs = {}

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_SRC_DIR = os.path.join(_ROOT, "native")


def _build_dir():
    from . import config
    return config.get("native.build_dir") or os.path.join(_SRC_DIR, "build")


# per-library extra link flags (e.g. image codecs)
_LINK_FLAGS = {
    "mxtpu_decode": ["-ljpeg"],
}


def _python_embed_flags():
    """Compile/link flags for libraries embedding CPython (the C ABI).
    Links libpython when a shared build exists so a pure-C host works;
    otherwise symbols stay undefined and resolve from a Python host."""
    import sysconfig
    cflags = ["-I" + sysconfig.get_paths()["include"]]
    ldflags = []
    libdir = sysconfig.get_config_var("LIBDIR") or ""
    ver = sysconfig.get_config_var("LDVERSION") or ""
    if libdir and ver and os.path.exists(
            os.path.join(libdir, f"libpython{ver}.so")):
        ldflags += ["-L" + libdir, f"-lpython{ver}",
                    "-Wl,-rpath," + libdir]
    return cflags, ldflags


# extra source dependencies per library (headers the staleness check
# must consider alongside the .cc)
_EXTRA_DEPS = {
    "mxtpu_capi": ["mxtpu_c_api.h"],
}


def _build(name):
    src = os.path.join(_SRC_DIR, f"{name}.cc")
    out = os.path.join(_build_dir(), f"lib{name}.so")
    if not os.path.exists(src):
        raise FileNotFoundError(src)
    newest_src = max([os.path.getmtime(src)] +
                     [os.path.getmtime(os.path.join(_SRC_DIR, d))
                      for d in _EXTRA_DEPS.get(name, ())
                      if os.path.exists(os.path.join(_SRC_DIR, d))])
    if os.path.exists(out) and os.path.getmtime(out) >= newest_src:
        return out
    os.makedirs(_build_dir(), exist_ok=True)
    cflags, ldflags = ([], [])
    if name == "mxtpu_capi":
        cflags, ldflags = _python_embed_flags()
    cmd = (["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17"]
           + cflags + [src, "-o", out]
           + _LINK_FLAGS.get(name, []) + ldflags)
    proc = subprocess.run(cmd, capture_output=True, text=True)
    if proc.returncode != 0:
        raise RuntimeError(f"native build failed: {proc.stderr[-2000:]}")
    return out


def load(name):
    """Load (building if needed) a native library; returns ctypes CDLL or
    None when the toolchain/source is unavailable (callers fall back to
    pure python)."""
    with _lock:
        if name in _libs:
            return _libs[name]
        try:
            lib = ctypes.CDLL(_build(name))
        except (OSError, RuntimeError, FileNotFoundError):
            lib = None
        _libs[name] = lib
        return lib


def io_lib():
    lib = load("mxtpu_io")
    if lib is not None and not getattr(lib, "_sigs_set", False):
        lib.mxtpu_rio_open.restype = ctypes.c_void_p
        lib.mxtpu_rio_open.argtypes = [ctypes.c_char_p]
        lib.mxtpu_rio_count.restype = ctypes.c_int64
        lib.mxtpu_rio_count.argtypes = [ctypes.c_void_p]
        lib.mxtpu_rio_get.restype = ctypes.POINTER(ctypes.c_uint8)
        lib.mxtpu_rio_get.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                      ctypes.POINTER(ctypes.c_uint64)]
        lib.mxtpu_rio_offset.restype = ctypes.c_int64
        lib.mxtpu_rio_offset.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.mxtpu_rio_close.argtypes = [ctypes.c_void_p]
        lib.mxtpu_prefetch_create.restype = ctypes.c_void_p
        lib.mxtpu_prefetch_create.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64]
        lib.mxtpu_prefetch_next_len.restype = ctypes.c_int64
        lib.mxtpu_prefetch_next_len.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.mxtpu_prefetch_pop.restype = ctypes.c_int64
        lib.mxtpu_prefetch_pop.argtypes = [
            ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint64]
        lib.mxtpu_prefetch_destroy.argtypes = [ctypes.c_void_p]
        lib._sigs_set = True
    return lib


class NativeRecordFile:
    """mmap-backed RecordIO reader with a full in-memory index (no .idx
    sidecar needed — the native scan builds it)."""

    def __init__(self, path):
        lib = io_lib()
        if lib is None:
            raise RuntimeError("native io library unavailable")
        self._lib = lib
        self._handle = lib.mxtpu_rio_open(path.encode())
        if not self._handle:
            raise OSError(f"cannot open record file {path}")
        self._n = lib.mxtpu_rio_count(self._handle)

    def __len__(self):
        return self._n

    def read(self, i):
        """Record i's payload as bytes (copied out of the mmap)."""
        ln = ctypes.c_uint64()
        ptr = self._lib.mxtpu_rio_get(self._handle, i, ctypes.byref(ln))
        if not ptr:
            raise IndexError(i)
        return ctypes.string_at(ptr, ln.value)

    def offset(self, i):
        return self._lib.mxtpu_rio_offset(self._handle, i)

    def close(self):
        if self._handle:
            self._lib.mxtpu_rio_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass

    def prefetch_iter(self, order=None, capacity=64, workers=2):
        """Iterate (record_id, payload bytes) with native readahead
        (reference: src/io/iter_prefetcher.h)."""
        import numpy as onp
        if order is None:
            order = onp.arange(self._n, dtype=onp.int64)
        order = onp.ascontiguousarray(onp.asarray(order, dtype=onp.int64))
        n = len(order)
        pf = self._lib.mxtpu_prefetch_create(
            self._handle,
            order.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
            n, capacity, workers)
        try:
            buf = (ctypes.c_uint8 * 0)()
            buf_len = 0
            for _ in range(n):
                ln = ctypes.c_uint64()
                rec = self._lib.mxtpu_prefetch_next_len(pf, ctypes.byref(ln))
                if rec < 0:
                    break
                if ln.value > buf_len:
                    buf_len = max(int(ln.value), 2 * buf_len)
                    buf = (ctypes.c_uint8 * buf_len)()
                rec = self._lib.mxtpu_prefetch_pop(pf, buf, buf_len)
                if rec < 0:
                    break
                yield rec, ctypes.string_at(buf, ln.value)
        finally:
            self._lib.mxtpu_prefetch_destroy(pf)


def capi_lib():
    """The stable C ABI (native/mxtpu_capi.cc, header mxtpu_c_api.h) —
    reference include/mxnet/c_api.h. Loaded here only for self-testing
    from Python; real consumers are non-Python hosts that dlopen the .so
    and call MXTpuInit()."""
    lib = load("mxtpu_capi")
    if lib is not None and not getattr(lib, "_sigs_set", False):
        i64p = ctypes.POINTER(ctypes.c_int64)
        h = ctypes.c_void_p
        lib.MXTpuInit.restype = ctypes.c_int
        lib.MXTpuGetLastError.restype = ctypes.c_char_p
        lib.MXTpuRuntimeInfo.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.MXTpuRandomSeed.argtypes = [ctypes.c_int]
        lib.MXTpuNDArrayCreate.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int, i64p,
            ctypes.c_int, ctypes.POINTER(h)]
        lib.MXTpuNDArrayFree.argtypes = [h]
        lib.MXTpuNDArrayShape.argtypes = [
            h, ctypes.POINTER(ctypes.c_int), i64p]
        lib.MXTpuNDArrayDType.argtypes = [h, ctypes.POINTER(ctypes.c_int)]
        lib.MXTpuNDArraySyncCopyToCPU.argtypes = [
            h, ctypes.c_void_p, ctypes.c_uint64]
        lib.MXTpuImperativeInvoke.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(h), ctypes.c_int,
            ctypes.POINTER(ctypes.c_char_p), ctypes.POINTER(ctypes.c_char_p),
            ctypes.c_int, ctypes.POINTER(h), ctypes.POINTER(ctypes.c_int)]
        lib._sigs_set = True
    return lib


def decode_lib():
    """Native JPEG codec (native/mxtpu_decode.cc over libjpeg)."""
    lib = load("mxtpu_decode")
    if lib is not None and not getattr(lib, "_sigs_set", False):
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.mxtpu_jpeg_dims.restype = ctypes.c_int
        lib.mxtpu_jpeg_dims.argtypes = [
            u8p, ctypes.c_uint64, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_int)]
        lib.mxtpu_jpeg_decode.restype = ctypes.c_int
        lib.mxtpu_jpeg_decode.argtypes = [
            u8p, ctypes.c_uint64, u8p, ctypes.c_uint64, ctypes.c_int]
        lib.mxtpu_decode_batch.restype = ctypes.c_int
        lib.mxtpu_decode_batch.argtypes = [
            ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_int, ctypes.POINTER(u8p),
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int, ctypes.c_int,
            ctypes.POINTER(ctypes.c_int)]
        lib._sigs_set = True
    return lib


# PIL's DecompressionBombError threshold: untrusted headers must not make
# us allocate unbounded buffers (the old PIL-only path enforced this)
MAX_IMAGE_PIXELS = 178956970


def jpeg_decode(buf, gray=False):
    """Decode one JPEG to an HWC uint8 numpy array (RGB, or HW1 gray);
    returns None when the codec is unavailable or the payload isn't a
    decodable JPEG (caller falls back to PIL, which raises the
    decompression-bomb error for oversized headers)."""
    import numpy as onp
    lib = decode_lib()
    if lib is None:
        return None
    raw = onp.frombuffer(buf, dtype=onp.uint8)
    data = raw.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))
    h = ctypes.c_int()
    w = ctypes.c_int()
    c = ctypes.c_int()
    if lib.mxtpu_jpeg_dims(data, raw.size, ctypes.byref(h), ctypes.byref(w),
                           ctypes.byref(c)) != 0:
        return None
    if h.value * w.value > MAX_IMAGE_PIXELS:
        return None
    ch = 1 if gray else 3
    out = onp.empty((h.value, w.value, ch), onp.uint8)
    rc = lib.mxtpu_jpeg_decode(
        data, raw.size, out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        out.nbytes, 1 if gray else 0)
    return out if rc == 0 else None


def jpeg_decode_batch(bufs, gray=False, n_threads=None):
    """Decode a list of JPEG byte strings in parallel C threads (no GIL).
    Returns list of HWC uint8 arrays; None entries for failed payloads.
    Falls back to None when the codec is unavailable."""
    import numpy as onp
    lib = decode_lib()
    if lib is None:
        return None
    if not bufs:
        return []
    u8p = ctypes.POINTER(ctypes.c_uint8)
    ch = 1 if gray else 3
    # dims probe first; only probe-clean entries are dispatched to the C
    # thread pool (a failed payload gets no output buffer at all)
    raws, outs, live = [], [None] * len(bufs), []
    for b in bufs:
        raw = onp.frombuffer(b, dtype=onp.uint8)
        raws.append(raw)
        h = ctypes.c_int()
        w = ctypes.c_int()
        c = ctypes.c_int()
        rc = lib.mxtpu_jpeg_dims(
            raw.ctypes.data_as(u8p), raw.size, ctypes.byref(h),
            ctypes.byref(w), ctypes.byref(c))
        if rc == 0 and h.value * w.value > MAX_IMAGE_PIXELS:
            rc = -3   # bomb guard: let PIL raise its DecompressionBombError
        live.append((rc, h.value, w.value))
    idx = [i for i, (rc, _, _) in enumerate(live) if rc == 0]
    n = len(idx)
    if n:
        datas = (u8p * n)()
        lens = (ctypes.c_uint64 * n)()
        outps = (u8p * n)()
        caps = (ctypes.c_uint64 * n)()
        rcs = (ctypes.c_int * n)()
        for j, i in enumerate(idx):
            _, h, w = live[i]
            out = onp.empty((h, w, ch), onp.uint8)
            outs[i] = out
            datas[j] = raws[i].ctypes.data_as(u8p)
            lens[j] = raws[i].size
            outps[j] = out.ctypes.data_as(u8p)
            caps[j] = out.nbytes
        if n_threads is None:
            n_threads = min(8, max(1, os.cpu_count() or 1))
        lib.mxtpu_decode_batch(datas, lens, n, outps, caps,
                               1 if gray else 0, n_threads, rcs)
        for j, i in enumerate(idx):
            if rcs[j] != 0:
                outs[i] = None
    return outs

"""mx.fleet — health-plane-driven elastic mesh degradation.

Reference parity: the reference's kvstore layer treats worker failure as
a first-class event (SURVEY §4 — the parameter-server backends exist so a
job outlives a node).  Our TPU-native stack restarts at the *same* world
size: PR 3's ``resilience.run`` restores a bundle and re-enters, and
PR 11 proved TrainState bundles restore bitwise across layouts — but
nothing connected "a host died" to "pick a smaller layout and keep
going".  This module is that composition:

- :class:`HealthPlane` — per-host heartbeat lease (file-backed directory
  for the CI harness, best-effort coordination-service mirror on real
  fleets), a step-deadline watchdog that distinguishes *slow* (straggler
  gauge) from *wedged* (structured :class:`~mxnet_tpu.resilience.
  WorkerLost`), and a /healthz provider so the PR 9 ops endpoint turns
  red when the local step loop or a peer's lease goes stale.
- :func:`plan_layout` — pick the best :class:`MeshConfig` over the
  surviving devices via ``mesh_factorizations``: preserve tp and pp
  (their sharding is what the model was sized for), shrink dp, and park
  below the ``fleet.min_dp`` floor rather than thrash.
- :class:`FleetSupervisor` — the degrade/re-expand loop: on host loss it
  re-plans the layout, rebuilds the :class:`ShardedTrainStep` around the
  new mesh, restores the last *valid* bundle bitwise through the
  topology-independent checkpoint path (``TrainState.load_latest_valid``
  — a host can die mid-save and tear the primary), and keeps training;
  when the host rejoins, it re-expands at the next checkpoint boundary.

Chaos surface: the ``fleet.host_loss`` / ``fleet.slow_host`` /
``fleet.lease_lost`` injection points drive the end-to-end drill (see
tests/test_fleet.py and the ci/run.sh chaos stage): kill one host
mid-epoch → survivors degrade dp → losses stay on the uninterrupted
oracle trajectory → host returns → mesh re-expands.  Every transition is
visible as ``fleet.*`` metrics and ``fleet``-category trace spans.
"""
from __future__ import annotations

import json
import os
import threading
import time

from . import blackbox as _blackbox
from . import config as _config
from . import fault as _fault
from . import goodput as _goodput
from . import insight as _insight
from . import resilience as _resilience
from . import telemetry as _telemetry
from . import trace as _trace
from .base import MXNetError
from .parallel.mesh import MeshConfig, mesh_factorizations

__all__ = ["HealthPlane", "FleetSupervisor", "plan_layout"]

_telemetry.declare_metric(
    "fleet.peers_expected", "gauge",
    "hosts the fleet supervisor expects in the mesh at full strength")
_telemetry.declare_metric(
    "fleet.peers_alive", "gauge",
    "hosts currently holding a fresh heartbeat lease (or assumed alive "
    "in single-process drills)")
_telemetry.declare_metric(
    "fleet.stragglers", "gauge",
    "hosts past fleet.slow_fraction of the step deadline but still "
    "making progress — slow, not wedged")
_telemetry.declare_metric(
    "fleet.parked", "gauge",
    "1 while the supervisor is parked: too few devices survive to "
    "satisfy fleet.min_dp, so it waits for hosts instead of thrashing")
_telemetry.declare_metric(
    "fleet.dp_size", "gauge",
    "dp extent of the layout currently training (shrinks on degrade, "
    "returns to the target on re-expand)")
_telemetry.declare_metric(
    "fleet.degrades_total", "counter",
    "elastic degrades: host loss -> re-planned smaller layout -> "
    "bitwise bundle restore -> training continues")
_telemetry.declare_metric(
    "fleet.reexpands_total", "counter",
    "re-expansions back to the target layout after lost hosts rejoined "
    "(applied at a checkpoint boundary)")
_telemetry.declare_metric(
    "fleet.heartbeats_total", "counter",
    "heartbeat lease renewals published by this host")
_telemetry.declare_metric(
    "fleet.lease_renew_failures_total", "counter",
    "failed attempts to renew this host's own lease (fleet.lease_lost "
    "injection or an unreachable lease store)")
_telemetry.declare_metric(
    "fleet.lease_expiries_total", "counter",
    "peer leases observed stale past fleet.lease_timeout — each one is "
    "a detected host loss")


def _gauge(name, value):
    if _telemetry._active:
        _telemetry.set_gauge(name, value)


def _count(name, n=1, **labels):
    if _telemetry._active:
        _telemetry.inc(name, n, **labels)


# ---------------------------------------------------------------------------
# layout re-planning
# ---------------------------------------------------------------------------

def plan_layout(current, n_devices, min_dp=None):
    """Pick the best :class:`MeshConfig` over ``n_devices`` surviving
    devices, derived from the ``current`` (target) layout.

    Preference order (lexicographic): keep BOTH tp and pp, then keep tp
    (its sharding divides the weight matrices the model was sized for),
    then keep pp, then maximize dp.  The sp extent is always preserved —
    ring-attention geometry is part of the model's math, not capacity.
    Returns ``None`` (park) when no exact-cover factorization exists or
    the best one falls below the ``fleet.min_dp`` floor.
    """
    if min_dp is None:
        min_dp = _config.get("fleet.min_dp")
    candidates = [c for c in mesh_factorizations(n_devices,
                                                 max_sp=current.sp)
                  if c.sp == current.sp]
    if not candidates:
        return None
    best = max(candidates, key=lambda c: (
        c.tp == current.tp and c.pp == current.pp,
        c.tp == current.tp,
        c.pp == current.pp,
        c.dp))
    if best.dp < max(1, int(min_dp)):
        return None
    return best


# ---------------------------------------------------------------------------
# health plane
# ---------------------------------------------------------------------------

class HealthPlane:
    """Per-host heartbeat lease + step-deadline watchdog.

    Leases are JSON files ``host-<rank>.lease`` in ``fleet.lease_dir``
    (a directory every host can reach — the 2-process CI harness points
    it at a tmpdir), renewed every ``fleet.lease_interval`` seconds by
    :meth:`beat` (or the :meth:`start` daemon thread).  When a jax
    coordination service is up, each renewal is also mirrored into its
    key-value store best-effort — the file store stays authoritative so
    the plane works with no collective runtime at all.

    :meth:`check_peers` classifies every peer:

    - lease stale past ``fleet.lease_timeout`` → the host is LOST:
      ``fleet.lease_expiries_total`` ticks and a structured
      :class:`~mxnet_tpu.resilience.WorkerLost` (``op="lease"``) raises —
      the same escalation the dist kvstore uses for dead collectives.
    - lease fresh but its step counter stuck past ``fleet.step_deadline``
      seconds → WEDGED: ``WorkerLost(op="step_deadline")``.
    - step stuck past ``fleet.slow_fraction`` of the deadline → SLOW:
      the ``fleet.stragglers`` gauge rises, nothing is killed.

    The plane registers itself as the ``fleet`` /healthz provider: the
    ops endpoint turns red (503) when this host's own renewals fail,
    its local step loop is past the deadline, or a peer lease is stale.
    """

    def __init__(self, rank=0, nprocs=1, lease_dir=None, interval=None,
                 timeout=None):
        self.rank = int(rank)
        self.nprocs = int(nprocs)
        self.lease_dir = (lease_dir if lease_dir is not None
                          else _config.get("fleet.lease_dir"))
        self.interval = (float(interval) if interval is not None
                         else _config.get("fleet.lease_interval"))
        self.timeout = (float(timeout) if timeout is not None
                        else _config.get("fleet.lease_timeout"))
        self._step = 0
        self._step_mono = time.monotonic()
        self._renew_failing = False
        self._seen: set[int] = set()
        #: rank -> (last observed step, monotonic time it last advanced)
        self._peer_progress: dict[int, tuple[int, float]] = {}
        self._stragglers: set[int] = set()
        self._stop = threading.Event()
        self._thread = None
        self._thread_lock = threading.Lock()

    # -- lease publication ----------------------------------------------

    def _lease_path(self, rank):
        return os.path.join(self.lease_dir, f"host-{int(rank)}.lease")

    def beat(self, step=None):
        """Publish one lease renewal.  Returns True on success; a failed
        renewal (the ``fleet.lease_lost`` injection, or an unreachable
        store) is counted and flips this host's /healthz check red while
        the heartbeat keeps retrying."""
        if step is not None:
            self.note_step(step)
        payload = {"rank": self.rank, "pid": os.getpid(),
                   "step": int(self._step), "time": time.time()}
        if _fault._active and _fault.fire("fleet.lease_lost",
                                          step=step):
            self._renew_failing = True
            _count("fleet.lease_renew_failures_total")
            _fault.record("fleet.lease_renew_failure")
            return False
        try:
            if self.lease_dir:
                os.makedirs(self.lease_dir, exist_ok=True)
                path = self._lease_path(self.rank)
                tmp = f"{path}.tmp.{os.getpid()}"
                with open(tmp, "w") as f:
                    f.write(json.dumps(payload))
                os.replace(tmp, path)
            self._publish_coord(payload)
        except OSError:
            self._renew_failing = True
            _count("fleet.lease_renew_failures_total")
            _fault.record("fleet.lease_renew_failure")
            return False
        self._renew_failing = False
        _count("fleet.heartbeats_total")
        if _insight._active and self.lease_dir:
            # piggyback the insight fleet snapshot on the heartbeat
            # cadence (rate-limited by insight.snapshot_interval)
            _insight.maybe_snapshot(self.lease_dir, self.rank)
        if _blackbox._active and self.lease_dir:
            # shadow postmortem on the same cadence (rate-limited by
            # blackbox.checkpoint_interval): SIGKILL/OOM run no hook,
            # so the fleet always holds a recent bundle for this host
            _blackbox.maybe_checkpoint(self.lease_dir, self.rank,
                                       step=self._step)
        if _goodput._active and self.lease_dir:
            # goodput ledger snapshot on the same cadence (rate-limited
            # by goodput.snapshot_interval)
            _goodput.maybe_snapshot(self.lease_dir, self.rank)
        return True

    def _publish_coord(self, payload):
        """Best-effort mirror into the jax coordination service (present
        only under jax.distributed); the file store stays authoritative."""
        try:
            from jax._src import distributed as _dist
            client = getattr(_dist.global_state, "client", None)
            if client is None:
                return
            client.key_value_set(
                f"mx.fleet/lease/{self.rank}/{payload['step']}",
                json.dumps(payload))
        except Exception:   # noqa: BLE001 - strictly best-effort
            pass

    def note_step(self, step):
        """Record local training-loop progress (feeds the local watchdog
        and the step number published in the lease)."""
        step = int(step)
        if step != self._step:
            self._step = step
            self._step_mono = time.monotonic()

    # -- peer observation -----------------------------------------------

    def peers(self):
        """{rank: {"age": seconds since renewal, "step": last step}} for
        every peer lease currently on disk (own rank excluded)."""
        out = {}
        if not self.lease_dir or not os.path.isdir(self.lease_dir):
            return out
        now = time.time()
        for rank in range(self.nprocs):
            if rank == self.rank:
                continue
            try:
                with open(self._lease_path(rank)) as f:
                    lease = json.loads(f.read())
            except (OSError, ValueError):
                continue
            out[rank] = {"age": max(0.0, now - lease.get("time", 0.0)),
                         "step": int(lease.get("step", 0))}
            self._seen.add(rank)
        return out

    def check_peers(self):
        """Classify every previously-seen peer; raises
        :class:`~mxnet_tpu.resilience.WorkerLost` for the first LOST or
        WEDGED one, updates the ``fleet.stragglers`` gauge for SLOW
        ones.  Returns the ranks currently alive."""
        leases = self.peers()
        deadline = _config.get("fleet.step_deadline")
        slow_at = deadline * _config.get("fleet.slow_fraction")
        now = time.monotonic()
        alive = []
        self._stragglers.clear()
        for rank in sorted(self._seen):
            lease = leases.get(rank)
            if lease is None or lease["age"] > self.timeout:
                age = lease["age"] if lease else float("inf")
                _count("fleet.lease_expiries_total")
                _fault.record("fleet.lease_expiry")
                raise _resilience.WorkerLost(
                    op="lease", key=f"host-{rank}", rank=self.rank,
                    nprocs=self.nprocs, attempts=1,
                    last=f"lease age {age:.1f}s > fleet.lease_timeout "
                         f"{self.timeout:.1f}s")
            alive.append(rank)
            if deadline > 0:
                prev = self._peer_progress.get(rank)
                if prev is None or prev[0] != lease["step"]:
                    self._peer_progress[rank] = (lease["step"], now)
                    continue
                stuck = now - prev[1]
                if stuck > deadline:
                    raise _resilience.WorkerLost(
                        op="step_deadline", key=f"host-{rank}",
                        rank=self.rank, nprocs=self.nprocs, attempts=1,
                        last=f"peer step {lease['step']} stuck "
                             f"{stuck:.1f}s > fleet.step_deadline "
                             f"{deadline:.1f}s (wedged)")
                if stuck > slow_at > 0:
                    self._stragglers.add(rank)
        if _insight._active and self.lease_dir:
            # insight relative-slowness: a host whose step-time EWMA
            # (published in its fleet snapshot) sits past
            # insight.straggler_ratio x the fleet median is a straggler
            # even without a fleet.step_deadline configured
            ratio = _config.get("insight.straggler_ratio")
            for rank, rel in _insight.relative_slowness(
                    self.lease_dir).items():
                if rank != self.rank and rel > ratio:
                    self._stragglers.add(rank)
        _gauge("fleet.stragglers", len(self._stragglers))
        _gauge("fleet.peers_alive", len(alive) + 1)   # peers + self
        return alive

    # -- liveness (/healthz) --------------------------------------------

    def healthz(self):
        """The ``fleet`` /healthz provider (registered by :meth:`start`):
        red when own renewals fail, the local step loop is past
        ``fleet.step_deadline``, or a peer lease is stale."""
        detail = {"rank": self.rank, "step": self._step,
                  "renewing": not self._renew_failing}
        ok = not self._renew_failing
        deadline = _config.get("fleet.step_deadline")
        if deadline > 0:
            age = time.monotonic() - self._step_mono
            detail["step_age_s"] = round(age, 3)
            if age > deadline:
                ok, detail["local"] = False, "wedged"
        stale = [r for r, p in self.peers().items()
                 if p["age"] > self.timeout]
        if stale:
            ok, detail["stale_peers"] = False, stale
        detail["ok"] = ok
        return detail

    def start(self):
        """Register the /healthz provider and start the daemon renewal
        thread (one :meth:`beat` per ``fleet.lease_interval``).
        Idempotent while the thread runs; safe to call in a tight
        stop()/start() loop — every start gets a FRESH stop event, so a
        previous loop that outlived its join timeout can never be
        revived by the new start clearing a shared event (the old
        thread-leak bug: two renewal loops beating the same lease)."""
        _telemetry.register_health("fleet", self.healthz)
        with self._thread_lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            stop_evt = self._stop = threading.Event()

            def _loop():
                # close over THIS start's event: once stop() swaps in a
                # new one, this loop only ever sees its own, already-set
                # event and exits even if the join that retired it
                # timed out
                while not stop_evt.is_set():
                    self.beat()
                    stop_evt.wait(self.interval)

            self._thread = threading.Thread(
                target=_loop, name="mx-fleet-heartbeat", daemon=True)
            self._thread.start()
        return self

    def stop(self):
        """Clean exit: stop renewing, join the renewal thread, withdraw
        the lease file (so peers see a departure, not a loss),
        unregister from /healthz.  Idempotent — a double stop is a
        no-op — and a thread that fails to join inside the timeout is
        kept referenced (never orphaned with a live shared event), so
        restart loops cannot leak renewal threads."""
        with self._thread_lock:
            self._stop.set()
            thread = self._thread
        if thread is not None:
            # join OUTSIDE the lock: a start() racing this stop must
            # never deadlock behind a slow join
            thread.join(timeout=5.0)
            if not thread.is_alive():
                with self._thread_lock:
                    if self._thread is thread:
                        self._thread = None
        _telemetry.unregister_health("fleet")
        if self.lease_dir:
            try:
                os.remove(self._lease_path(self.rank))
            except OSError:
                pass


# ---------------------------------------------------------------------------
# elastic supervisor
# ---------------------------------------------------------------------------

class FleetSupervisor:
    """Elastic degrade/re-expand driver around ONE
    :class:`~mxnet_tpu.parallel.ShardedTrainStep` and its
    :class:`~mxnet_tpu.resilience.TrainState` bundle.

    The device fleet is modeled as ``n_hosts`` equal shares of the
    target layout's devices.  A host is lost either through the health
    plane (a peer's lease expired → :class:`WorkerLost`) or through the
    deterministic ``fleet.host_loss`` injection point (probed once per
    step, single-process drills).  On loss::

        plan_layout(target, surviving_devices)   # prefer tp/pp, shrink dp
        step.rebuild(plan, sync=False)           # new mesh, same math
        state.load_latest_valid()                # bitwise, torn-safe
        ... training continues ...

    Below the ``fleet.min_dp`` floor the supervisor PARKS (gauge
    ``fleet.parked``) instead of thrashing; :meth:`restore_hosts`
    unparks it.  Re-expansion back to the target layout happens at the
    next checkpoint boundary after every lost host rejoined — the bundle
    written there restores bitwise into the full mesh.  Each transition
    emits ``fleet``-category trace spans and ``fleet.*`` counters.
    """

    def __init__(self, step, state, n_hosts=1, host_index=0, min_dp=None,
                 checkpoint_every=1, health=None, stream=None):
        if step.mesh_config is None:
            raise MXNetError(
                "FleetSupervisor needs a ShardedTrainStep built from a "
                "MeshConfig (elastic re-planning re-factorizes its axes)")
        self.step = step
        self.state = state
        state.sharded_step = step
        self.target = step.mesh_config
        self.current = step.mesh_config
        self.n_hosts = int(n_hosts)
        self.host_index = int(host_index)
        if self.n_hosts < 1 or self.target.size() % self.n_hosts:
            raise MXNetError(
                f"n_hosts={n_hosts} must divide the target layout's "
                f"{self.target.size()} devices")
        self._dev_per_host = self.target.size() // self.n_hosts
        self.min_dp = (int(min_dp) if min_dp is not None
                       else _config.get("fleet.min_dp"))
        self.checkpoint_every = max(1, int(checkpoint_every))
        self.health = health
        #: streaming data plane (a mx.stream.StreamSampler or a
        #: DataLoader wrapping one): lose_host additionally reassigns
        #: the dead host's unfinished shards to the survivors
        self.stream = stream
        self._lost: set[int] = set()
        #: host -> path of the dead host's latest valid postmortem
        #: bundle (attached to the fleet.degrade decision)
        self.postmortems: dict[int, str] = {}
        self._last_lost: int | None = None
        self.parked = False
        self._park_token = None
        self.degrades = 0
        self.reexpands = 0
        if _goodput._active:
            _goodput.set_devices(self._dev_per_host)
            _goodput.set_capacity(self.current.size(), self.target.size())
        _gauge("fleet.peers_expected", self.n_hosts)
        _gauge("fleet.peers_alive", self.n_hosts)
        _gauge("fleet.dp_size", self.current.dp)
        _gauge("fleet.parked", 0)

    # -- fleet membership ------------------------------------------------

    def alive_hosts(self):
        return [h for h in range(self.n_hosts) if h not in self._lost]

    def lose_host(self, host):
        """Mark ``host`` lost and re-plan immediately (the path both the
        health plane and the ``fleet.host_loss`` injection drive)."""
        if host in self._lost or host == self.host_index:
            return
        self._lost.add(host)
        self._last_lost = int(host)
        _fault.record("fleet.host_lost")
        _gauge("fleet.peers_alive", self.n_hosts - len(self._lost))
        # the dead host can't speak for itself: pick up its latest valid
        # postmortem bundle (terminal or <=interval-stale shadow) from
        # the shared bundle dir and carry it into the degrade decision
        bdir = _config.get("blackbox.dir") \
            or (self.health.lease_dir if self.health is not None else "") \
            or _config.get("fleet.lease_dir")
        if bdir:
            bundle = _blackbox.latest_bundle(bdir, rank=host)
            if bundle:
                self.postmortems[int(host)] = bundle
        self._replan()
        # data plane follows the compute plane: the dead host's
        # unfinished shards move to the survivors exactly once, resumed
        # from its last *checkpointed* cursor (anything it served past
        # that checkpoint was never durable — those steps rolled back
        # with the bundle, so re-serving keeps the epoch multiset exact)
        if self.stream is not None:
            sdir = ((self.health.lease_dir if self.health is not None
                     else "") or _config.get("fleet.lease_dir"))
            try:
                self.stream.take_over_host(
                    host, survivors=self.alive_hosts(),
                    cursor_dir=sdir or None)
            except OSError:
                pass    # shared dir unreadable: the shards stay lost
                        # until a retried lose_host or manual reassign

    def restore_hosts(self, *hosts):
        """Mark lost hosts as rejoined (all of them by default).  The
        mesh does NOT re-expand here — that happens at the next
        checkpoint boundary, where a fresh bundle is guaranteed."""
        if hosts:
            self._lost.difference_update(int(h) for h in hosts)
        else:
            self._lost.clear()
        _gauge("fleet.peers_alive", self.n_hosts - len(self._lost))
        if self.parked:
            self.parked = False
            _gauge("fleet.parked", 0)
            if self._park_token is not None:
                _goodput.end(self._park_token)
                self._park_token = None

    # -- plan / apply ----------------------------------------------------

    def _replan(self):
        avail = self._dev_per_host * (self.n_hosts - len(self._lost))
        plan = (plan_layout(self.target, avail, min_dp=self.min_dp)
                if avail else None)
        if plan is None:
            self.parked = True
            _gauge("fleet.parked", 1)
            if _goodput._active and self._park_token is None:
                # open-ended: every parked second is badput until
                # restore_hosts() closes the bracket
                self._park_token = _goodput.begin("parked")
            _fault.record("fleet.park")
            with _trace.span("fleet.park", category="fleet",
                             devices=avail, min_dp=self.min_dp):
                pass
            return None
        if plan != self.current:
            self._apply(plan, kind="degrade")
        return plan

    def _apply(self, cfg, kind):
        """Rebuild the step around ``cfg`` and restore the newest valid
        bundle bitwise into it (step counter, RNG, optimizer state ride
        along — the run resumes exactly at the last checkpoint)."""
        # the whole transition (rebuild + recompile + bundle restore) is
        # restart badput; restart outranks the nested restore/compile
        # claims, so the ledger counts the downtime exactly once
        tok = _goodput.begin("restart") if _goodput._active else None
        with _trace.span(f"fleet.{kind}", category="fleet", dp=cfg.dp,
                         tp=cfg.tp, pp=cfg.pp, devices=cfg.size()) as sp:
            if kind == "degrade" and self._last_lost is not None:
                pm = self.postmortems.get(self._last_lost)
                if pm:
                    sp.set(postmortem=pm, postmortem_host=self._last_lost)
            with _trace.span("fleet.rebuild", category="fleet"):
                # sync=False: the dying layout's buffers may be gone;
                # all state transfers through the canonical bundle
                new_step = self.step.rebuild(cfg, sync=False)
            self.step = new_step
            self.state.sharded_step = new_step
            if self.state.exists():
                self.state.load_latest_valid()
        if tok is not None:
            _goodput.end(tok)
        if _goodput._active:
            _goodput.set_capacity(cfg.size(), self.target.size())
        self.current = cfg
        _gauge("fleet.dp_size", cfg.dp)
        if kind == "degrade":
            self.degrades += 1
            _count("fleet.degrades_total")
            _fault.record("fleet.degrade")
        else:
            self.reexpands += 1
            _count("fleet.reexpands_total")
            _fault.record("fleet.reexpand")

    def _maybe_reexpand(self):
        if (self._lost or self.parked or self.current == self.target
                or self.state.step % self.checkpoint_every):
            return
        self._apply(self.target, kind="reexpand")

    # -- the per-step probe and the drill driver -------------------------

    def probe(self, step_no=None):
        """Run once per training step: advance the heartbeat, scrape the
        health plane, and evaluate the deterministic fault points.
        Returns False while parked."""
        if self.health is not None:
            self.health.beat(step=step_no)
            try:
                self.health.check_peers()
            except _resilience.WorkerLost as e:
                # map the dead peer's rank onto its host share
                rank = int(str(e.key).rsplit("-", 1)[-1]) \
                    if "-" in str(e.key) else 0
                self.lose_host(rank)
        if _fault._active and _fault.fire("fleet.slow_host", step=step_no):
            _fault.record("fleet.straggler")
            _gauge("fleet.stragglers", 1)
        if _fault._active and _fault.fire("fleet.host_loss", step=step_no):
            survivors = [h for h in self.alive_hosts()
                         if h != self.host_index]
            if survivors:   # nobody left to lose -> ignore the probe
                self.lose_host(max(survivors))
        self._maybe_reexpand()
        return not self.parked

    def run(self, batch_fn, total_steps):
        """Drive training to ``total_steps``: probe, pull the batch FOR
        THE STEP BEING (RE)COMPUTED via ``batch_fn(step_number)``, step,
        checkpoint every ``checkpoint_every`` steps.  A degrade rolls the
        step counter back to the last checkpoint, and ``batch_fn`` being
        keyed by step number replays exactly the batches the oracle run
        sees.  Returns {step: loss} for every step computed last (the
        authoritative value per step — recomputed steps overwrite).
        Parking breaks the loop; call :meth:`restore_hosts` then
        ``run`` again to continue."""
        losses = {}
        while self.state.step < total_steps:
            self.probe(self.state.step + 1)
            if self.parked:
                break
            s = self.state.step + 1   # a degrade may have rolled us back
            loss = self.step(*batch_fn(s))
            losses[s] = loss
            self.state.step = s
            if s % self.checkpoint_every == 0 and self.state.path:
                self.state.save()
                if self.stream is not None:
                    # the cursor travels inside the bundle when the
                    # stream is the TrainState loader; the shared-dir
                    # copy (what survivors roll forward) refreshes at
                    # the same boundary either way
                    try:
                        self.stream.publish_cursor()
                    except OSError:
                        pass
        return losses

"""Low-bit inference gates (CI `quantize` stage; the PR 8 acceptance
benchmark — docs/PERFORMANCE.md "Low-bit inference").

CPU CI gates (always run):

- **fused-kernel parity**: the Pallas fused quantize->int8-dot->dequant
  kernel (interpret mode off-TPU, ``quantize.fused_matmul=on``) against
  the XLA fallback chain (``off``) — bitwise without a bias (symmetric
  int8 quantizes identically and accumulates in exact int32; zero
  padding is exact), <=1e-5 with a bias (the kernel may FMA-contract the
  epilogue mul+add).
- **int4 weight bytes**: packed group-wise int4 over a GPT's eligible
  weights must come in at <=0.15x the fp32 footprint (nibbles + scales).
- **zero recompiles**: engines with ``int8_weights`` and
  ``int4_weights,int8_kv`` must report ZERO post-warmup compiles across
  a mixed-bucket workload — low-bit storage must not change the traced
  step signature (the PR 2 detector is the oracle).

Hardware gates (TPU attached; skipped with a notice on CPU):

- int8 resnet50 inference beats bf16 (items/s — the fused path's reason
  to exist; BENCH_r05 measured the unfused chain *losing* to bf16).
- gpt2-class decode with ``int4_weights`` >= --min-decode-speedup
  (default 1.3x) tokens/s over fp32 with greedy parity on the workload.

Prints ONE JSON line (the bench.py contract).

Usage: JAX_PLATFORMS=cpu python benchmark/quantized_inference.py --assert
"""
from __future__ import annotations

import argparse
import json
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _route(mode):
    from mxnet_tpu import config
    return config.set("quantize.fused_matmul", mode)


def gate_fused_parity():
    """Pallas-vs-fallback over aligned and deliberately ragged shapes."""
    import mxnet_tpu as mx
    from mxnet_tpu import npx

    results = []
    for m, k, n, bias in [(32, 64, 16, False), (5, 33, 7, False),
                          (130, 257, 129, False), (32, 64, 16, True)]:
        rs = onp.random.RandomState(m)
        x = rs.randn(m, k).astype("float32")
        w = (rs.randn(n, k) * 0.5).astype("float32")
        w_scale = onp.abs(w).max(axis=1) / 127.0
        qw = onp.clip(onp.round(w / w_scale[:, None]), -127, 127
                      ).astype("int8")
        b = rs.randn(n).astype("float32") if bias else None
        args = (mx.np.array(x), mx.np.array(qw),
                float(onp.abs(x).max()) / 127.0, mx.np.array(w_scale))
        kw = {"bias": mx.np.array(b)} if bias else {}
        prev = _route("on")
        try:
            got = npx.quantized_dense_fused(*args, **kw).asnumpy()
        finally:
            _route(prev)
        prev = _route("off")
        try:
            ref = npx.quantized_dense_fused(*args, **kw).asnumpy()
        finally:
            _route(prev)
        if bias:  # FMA contraction inside the kernel: one ulp
            ok = bool(onp.abs(got - ref).max() <= 1e-5)
        else:
            ok = bool((got == ref).all())
        results.append({"shape": [m, k, n], "bias": bias, "ok": ok,
                        "max_abs_diff": float(onp.abs(got - ref).max())})
    return {"cases": results, "ok": all(r["ok"] for r in results)}


def _tiny_gpt(seed):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM

    mx.random.seed(seed)
    net = GPTForCausalLM(vocab_size=512, units=64, hidden_size=256,
                         num_layers=2, num_heads=4, max_length=128,
                         dropout=0.0, embed_dropout=0.0)
    net.initialize()
    net(mx.np.zeros((1, 2), dtype="int32"))
    return net


def gate_int4_bytes(max_ratio):
    import mxnet_tpu as mx

    eng = mx.serve.load(_tiny_gpt(0), max_slots=4, quantize="int4_weights")
    st = eng.stats()
    ratio = st["weight_bytes"] / st["weight_bytes_fp"]
    return {"weight_bytes_ratio": round(ratio, 4),
            "quantized_params": st["quantized_params"],
            "passthrough_params": st["passthrough_params"],
            "ok": bool(ratio <= max_ratio)}


def gate_zero_recompiles():
    import mxnet_tpu as mx

    rng = onp.random.RandomState(1)
    out = {}
    for spec in ("int8_weights", "int4_weights,int8_kv"):
        eng = mx.serve.load(_tiny_gpt(1), max_slots=4, quantize=spec,
                            warmup=True)
        for _ in range(8):  # mixed lengths across the bucket grid
            eng.submit(rng.randint(1, 512, size=rng.randint(2, 24)).tolist(),
                       max_new_tokens=8)
        eng.run()
        out[spec] = eng.stats()["post_warmup_compiles"]
    return {"post_warmup_compiles": out,
            "ok": all(v == 0 for v in out.values())}


def _decode_tokens_per_s(net, quantize, work, seed=0):
    import time

    import mxnet_tpu as mx

    eng = mx.serve.load(net, max_slots=8, quantize=quantize, seed=seed,
                        warmup=True)
    t0 = time.perf_counter()
    reqs = [eng.submit(p, max_new_tokens=n) for p, n in work]
    eng.run()
    wall = time.perf_counter() - t0
    st = eng.stats()
    return st["tokens_out"] / wall, [r.output_ids for r in reqs], st


def gate_hardware(min_decode_speedup):
    """TPU-only: the wins the fused path + weight-only storage promise."""
    import bench
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM

    peak = bench._peak_flops()
    r_bf16 = bench.bench_resnet50_infer("bf16", False, peak)
    r_int8 = bench.bench_resnet50_infer("int8", False, peak)
    infer_speedup = r_int8["items_per_s"] / r_bf16["items_per_s"]

    mx.random.seed(3)
    net = GPTForCausalLM(vocab_size=50257, units=768, hidden_size=3072,
                         num_layers=12, num_heads=12, max_length=512,
                         dropout=0.0, embed_dropout=0.0)
    net.initialize()
    net(mx.np.zeros((1, 2), dtype="int32"))
    rng = onp.random.RandomState(3)
    work = [(rng.randint(1, 50257, size=rng.randint(4, 64)).tolist(), 48)
            for _ in range(24)]
    tps_fp, out_fp, _ = _decode_tokens_per_s(net, None, work)
    tps_i4, out_i4, st4 = _decode_tokens_per_s(net, "int4_weights", work)
    matched = sum(a == b for a, b in zip(out_fp, out_i4))
    decode_speedup = tps_i4 / tps_fp
    return {
        "resnet50_int8_vs_bf16": round(infer_speedup, 3),
        "gpt2_decode_int4_vs_fp32": round(decode_speedup, 3),
        "decode_outputs_matched": f"{matched}/{len(work)}",
        "int4_weight_bytes_ratio": round(
            st4["weight_bytes"] / st4["weight_bytes_fp"], 4),
        "ok": bool(infer_speedup > 1.0
                   and decode_speedup >= min_decode_speedup
                   and matched == len(work)),
    }


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--max-int4-ratio", type=float, default=0.15)
    p.add_argument("--min-decode-speedup", type=float, default=1.3)
    p.add_argument("--assert", dest="check", action="store_true",
                   help="exit nonzero unless every gate holds")
    args = p.parse_args(argv)

    import jax
    on_tpu = jax.devices()[0].platform == "tpu"

    report = {
        "metric": "quantized_inference_gates",
        "platform": jax.devices()[0].platform,
        "fused_parity": gate_fused_parity(),
        "int4_bytes": gate_int4_bytes(args.max_int4_ratio),
        "zero_recompiles": gate_zero_recompiles(),
    }
    if on_tpu:
        report["hardware"] = gate_hardware(args.min_decode_speedup)
    else:
        report["hardware"] = "skipped (no TPU attached)"
    gates = [v for v in report.values() if isinstance(v, dict) and "ok" in v]
    report["ok"] = all(g["ok"] for g in gates)
    print(json.dumps(report))
    if args.check and not report["ok"]:
        failed = [k for k, v in report.items()
                  if isinstance(v, dict) and v.get("ok") is False]
        print(f"FAIL: gates {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

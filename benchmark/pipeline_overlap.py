#!/usr/bin/env python
"""mx.pipeline overlap benchmark (CI `pipeline` stage).

Two contracts from docs/PERFORMANCE.md:

1. OVERLAP WINS: on an input-bound synthetic workload (producer sleeps
   in C, releasing the GIL — a stand-in for decode/IO), a step loop fed
   through ``DevicePrefetcher`` with deferred loss accounting must beat
   the synchronous loop (host produce -> device_put -> compute ->
   per-step ``float(loss)``, today's default metric behavior) by the
   ``--speedup`` factor (default 1.2x items/s), and the prefetched
   loop's measured input-stall time must sit well below the baseline's
   producer wait.
2. OFF SWITCH IS FREE: with no prefetcher constructed, the hot-path
   guard hook (``pipeline._guard_depth`` read + branch, mirrored by the
   ndarray sync probes) must cost <2% on a tight eager loop — measured
   exactly like benchmark/telemetry_overhead.py, with many probes per
   op scaled down to the ~1 read a real dispatch performs.

Usage: python benchmark/pipeline_overlap.py [--speedup 1.2]
           [--budget 0.02] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

PRODUCE_MS = 3.0     # per-batch producer latency (sleep = GIL released)
HOST_MS = 3.0        # per-step host-side work (optimizer/book-keeping
                     # python overhead a real trainer.step carries); this
                     # is what the prefetch thread overlaps the produce
                     # latency WITH — sleep, so the producer thread isn't
                     # artificially starved of the GIL
BATCH = (256, 256)
STEPS = 40


def _producer(n, rs):
    for _ in range(n):
        time.sleep(PRODUCE_MS / 1000.0)
        yield rs.rand(*BATCH).astype("float32")


def _compute_fn():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        # a few chained matmuls: enough device work that produce and
        # compute are the same order of magnitude, so overlap has
        # something to hide (a pure-produce-bound loop caps the speedup
        # at produce/(produce+sync), washing out the signal)
        y = x
        for _ in range(4):
            y = jnp.tanh(y @ x.T) + x
        return jnp.sum(y) / (BATCH[0] * BATCH[0])
    return step


def _run_sync(step, n, seed):
    """Synchronous loop: produce, put, compute, and fetch the scalar loss
    every step (the pre-pipeline default: metric/grad-norm accounting
    called float() per step, serializing host and device)."""
    import jax
    import numpy as onp
    rs = onp.random.RandomState(seed)
    t0 = time.perf_counter()
    total = 0.0
    for raw in _producer(n, rs):
        x = jax.device_put(raw)
        total += float(step(x))        # per-step host sync
        time.sleep(HOST_MS / 1000.0)   # host-side step overhead
    return time.perf_counter() - t0, total


def _run_overlapped(step, n, seed):
    """Prefetched loop: H2D runs on the DevicePrefetcher thread while the
    device computes; losses drain through a DeferredWindow at the end."""
    import numpy as onp
    from mxnet_tpu import pipeline
    rs = onp.random.RandomState(seed)
    acc = []
    window = pipeline.DeferredWindow(window=STEPS + 1)
    t0 = time.perf_counter()
    pf = pipeline.DevicePrefetcher(_producer(n, rs), depth=3)
    for x in pf:
        window.push(step(x), acc.append)
        time.sleep(HOST_MS / 1000.0)   # host-side step overhead
    window.drain()                     # host syncs paid once, at the end
    return time.perf_counter() - t0, sum(acc)


def _guard_loop(a, n, probes_per_op, pipeline):
    """Tight eager loop with K disabled-guard probes per op."""
    t0 = time.perf_counter()
    out = a
    if probes_per_op == 0:
        for _ in range(n):
            out = out + a
    else:
        probe = range(probes_per_op)
        for _ in range(n):
            out = out + a
            for _ in probe:
                if pipeline._guard_depth:  # the hook pattern under test
                    pipeline.note_host_sync("bench.never")
    out._data.block_until_ready()
    return time.perf_counter() - t0


def run(speedup_floor=1.2, budget=0.02, repeats=3, json_out=False):
    import mxnet_tpu as mx
    from mxnet_tpu import pipeline, telemetry

    step = _compute_fn()
    # warmup: compile the kernel, spin up thread machinery
    _run_sync(step, 3, seed=0)
    _run_overlapped(step, 3, seed=0)

    sync_s, over_s = [], []
    loss_pairs = []
    for r in range(repeats):
        telemetry.reset()
        telemetry.enable()
        ts, lsync = _run_sync(step, STEPS, seed=r)
        t_over, lover = _run_overlapped(step, STEPS, seed=r)
        snap = telemetry.snapshot()
        telemetry.disable()
        sync_s.append(ts)
        over_s.append(t_over)
        loss_pairs.append((lsync, lover))
    stall = snap["histograms"].get("pipeline.input_stall_seconds", {})
    sync_t, over_t = statistics.median(sync_s), statistics.median(over_s)
    items_sync = STEPS / sync_t
    items_over = STEPS / over_t
    speedup = items_over / items_sync
    # same data, same math: the overlapped loop must not change results
    for lsync, lover in loss_pairs:
        assert abs(lsync - lover) <= 1e-3 * max(1.0, abs(lsync)), \
            (lsync, lover)
    # baseline producer wait is ~STEPS * PRODUCE_MS serial; the prefetch
    # stall total must be well under it (the overlap actually happened)
    baseline_wait = STEPS * PRODUCE_MS / 1000.0
    stall_total = stall.get("sum", float("inf"))

    # -- disabled-path overhead (no prefetcher constructed) --------------
    a = mx.np.ones((8, 8))
    _guard_loop(a, 200, 0, pipeline)
    base_s, probed_s = [], []
    for _ in range(7):
        base_s.append(_guard_loop(a, 2000, 0, pipeline))
        probed_s.append(_guard_loop(a, 2000, 32, pipeline))
    base = statistics.median(base_s)
    probed = statistics.median(probed_s)
    overhead = max(0.0, (probed - base) / base) / 32

    result = {
        "items_per_s_sync": items_sync,
        "items_per_s_prefetch": items_over,
        "speedup": speedup,
        "speedup_floor": speedup_floor,
        "input_stall_s": stall_total,
        "baseline_producer_wait_s": baseline_wait,
        "disabled_overhead_per_probe": overhead,
        "overhead_budget": budget,
        "ok": bool(speedup >= speedup_floor
                   and stall_total < 0.5 * baseline_wait
                   and overhead < budget),
    }
    if json_out:
        print(json.dumps(result, indent=2))
    else:
        print(f"sync:     {items_sync:8.1f} items/s  ({sync_t * 1000:.0f} ms)")
        print(f"prefetch: {items_over:8.1f} items/s  ({over_t * 1000:.0f} ms)"
              f"  -> {speedup:.2f}x (floor {speedup_floor:.2f}x)")
        print(f"input stall with prefetch: {stall_total * 1000:.1f} ms "
              f"(baseline producer wait {baseline_wait * 1000:.0f} ms)")
        print(f"disabled-path overhead: {overhead:.4%} per probe "
              f"(budget {budget:.2%})")
        print("PASS" if result["ok"] else "FAIL")
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--speedup", type=float, default=1.2,
                    help="required prefetch-on/off items/s ratio")
    ap.add_argument("--budget", type=float, default=0.02,
                    help="disabled-path per-probe overhead budget")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    result = run(speedup_floor=args.speedup, budget=args.budget,
                 repeats=args.repeats, json_out=args.json)
    sys.exit(0 if result["ok"] else 1)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Disabled-observability fast-path overhead budget (CI stages).

The contract (mxnet_tpu/telemetry.py, mxnet_tpu/trace.py and
mxnet_tpu/blackbox.py, mirroring fault.py): with the registry/recorder
off, every instrumentation hook in the stack is ONE module attribute
read + branch.  This benchmark
measures that cost against a tight eager-op loop and fails if the probes
add more than the budget (default 2%) — the guard that keeps future
instrumentation honest.  The trace-enabled path is also measured and
reported (informational: enabling tracing is a deliberate choice, only
the disabled paths are gated).

Method: time a tight eager add loop (N ops, synced once) as the
baseline, then the same loop with K extra disabled probes per iteration
(telemetry and trace each), scale the measured per-probe cost down to
the ~1 probe a real dispatch performs, and compare medians of R repeats
(medians + many probes per iteration keep the number stable on noisy CI
hosts).

Usage: python benchmark/telemetry_overhead.py [--budget 0.02] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def _loop(a, n, probes_per_op, telemetry):
    """One timed run: n eager adds, probes_per_op gated probes each."""
    t0 = time.perf_counter()
    out = a
    if probes_per_op == 0:
        for _ in range(n):
            out = out + a
    else:
        probe = range(probes_per_op)
        for _ in range(n):
            out = out + a
            for _ in probe:
                if telemetry._active:  # the hook pattern under test
                    # mxlint: disable=REG003(measures the disabled fast path; the metric must stay undeclared so no registry slot is ever touched)
                    telemetry.inc("bench.never")
    out._data.block_until_ready()
    return time.perf_counter() - t0


def _trace_loop(a, n, probes_per_op, trace):
    """Same shape, probing the mx.trace disabled gate instead."""
    t0 = time.perf_counter()
    out = a
    probe = range(probes_per_op)
    for _ in range(n):
        out = out + a
        for _ in probe:
            if trace._active:  # the hook pattern under test
                trace.emit("bench.never", 0, 0)
    out._data.block_until_ready()
    return time.perf_counter() - t0


def _blackbox_loop(a, n, probes_per_op, blackbox):
    """Same shape, probing the mx.blackbox disabled gate instead (the
    pattern every flight-recorder trigger site uses)."""
    t0 = time.perf_counter()
    out = a
    probe = range(probes_per_op)
    for _ in range(n):
        out = out + a
        for _ in probe:
            if blackbox._active:  # the hook pattern under test
                blackbox.dump(trigger="manual", reason="bench.never")
    out._data.block_until_ready()
    return time.perf_counter() - t0


def _resolve_loop(a, n, probes_per_op, resolve_blocks):
    """Same shape, probing the UNTUNED autotune.resolve_blocks fast path
    (the routing every Pallas kernel call site takes at trace time)."""
    t0 = time.perf_counter()
    out = a
    probe = range(probes_per_op)
    for _ in range(n):
        out = out + a
        for _ in probe:
            resolve_blocks("flash_attention", (256, 256, 64))
    out._data.block_until_ready()
    return time.perf_counter() - t0


def _stream_loop(a, n, probes_per_op, note_served):
    """Same shape, probing mx.stream's per-record hot-path hook (the
    exact function its read path calls once per served record)."""
    t0 = time.perf_counter()
    out = a
    probe = range(probes_per_op)
    for _ in range(n):
        out = out + a
        for _ in probe:
            note_served(1)  # gates on telemetry._active internally
    out._data.block_until_ready()
    return time.perf_counter() - t0


def _servefleet_loop(a, n, probes_per_op, servefleet):
    """Same shape, probing the mx.servefleet disabled gate instead (the
    pattern ServeEngine.step runs once per decode step when no fleet
    group exists in the process)."""
    t0 = time.perf_counter()
    out = a
    probe = range(probes_per_op)
    for _ in range(n):
        out = out + a
        for _ in probe:
            if servefleet._active:  # the hook pattern under test
                servefleet.note_step(None)
    out._data.block_until_ready()
    return time.perf_counter() - t0


def _goodput_loop(a, n, probes_per_op, goodput):
    """Same shape, probing the mx.goodput disabled gate instead (the
    pattern every ledger claim site uses)."""
    t0 = time.perf_counter()
    out = a
    probe = range(probes_per_op)
    for _ in range(n):
        out = out + a
        for _ in probe:
            if goodput._active:  # the hook pattern under test
                goodput.note("compute", 0.0)
    out._data.block_until_ready()
    return time.perf_counter() - t0


def _trace_enabled_loop(a, n, trace):
    """Eager loop with one real recorded span per op (tracing ON)."""
    t0 = time.perf_counter()
    out = a
    for _ in range(n):
        with trace.span("bench.op"):
            out = out + a
    out._data.block_until_ready()
    return time.perf_counter() - t0


def run(n=2000, probes_per_op=32, repeats=7, budget=0.02):
    import mxnet_tpu as mx
    from mxnet_tpu import blackbox, goodput, servefleet, telemetry, trace
    from mxnet_tpu.autotune.kernels import resolve_blocks, _TUNED
    from mxnet_tpu.stream import _note_served

    telemetry.disable()
    trace.disable()
    blackbox.disable()
    goodput.disable()
    assert not telemetry.active() and not trace.active() \
        and not blackbox.active() and not goodput.active()
    assert not servefleet._active, \
        "servefleet gate measures the no-fleet path"
    assert not _TUNED, "resolve_blocks gate measures the UNTUNED path"
    a = mx.np.ones((8, 8))
    _loop(a, 200, 0, telemetry)          # warmup: compile + caches hot
    resolve_blocks("flash_attention", (256, 256, 64))  # static table fill
    base_s, probed_s, tprobed_s, bprobed_s = [], [], [], []
    rprobed_s, sprobed_s, gprobed_s, fprobed_s, ton_s = [], [], [], [], []
    for _ in range(repeats):
        base_s.append(_loop(a, n, 0, telemetry))
        probed_s.append(_loop(a, n, probes_per_op, telemetry))
        tprobed_s.append(_trace_loop(a, n, probes_per_op, trace))
        bprobed_s.append(_blackbox_loop(a, n, probes_per_op, blackbox))
        rprobed_s.append(_resolve_loop(a, n, probes_per_op, resolve_blocks))
        sprobed_s.append(_stream_loop(a, n, probes_per_op, _note_served))
        gprobed_s.append(_goodput_loop(a, n, probes_per_op, goodput))
        fprobed_s.append(_servefleet_loop(a, n, probes_per_op, servefleet))
        trace.enable(buffer=max(1024, n))
        ton_s.append(_trace_enabled_loop(a, n, trace))
        trace.disable()
        trace.clear()
    base = statistics.median(base_s)
    probed = statistics.median(probed_s)
    tprobed = statistics.median(tprobed_s)
    bprobed = statistics.median(bprobed_s)
    rprobed = statistics.median(rprobed_s)
    sprobed = statistics.median(sprobed_s)
    gprobed = statistics.median(gprobed_s)
    fprobed = statistics.median(fprobed_s)
    ton = statistics.median(ton_s)
    # cost of the K probes, scaled to the ~1 probe a real dispatch adds
    per_probe = max(0.0, probed - base) / probes_per_op
    per_trace_probe = max(0.0, tprobed - base) / probes_per_op
    per_blackbox_probe = max(0.0, bprobed - base) / probes_per_op
    per_resolve_probe = max(0.0, rprobed - base) / probes_per_op
    per_stream_probe = max(0.0, sprobed - base) / probes_per_op
    per_goodput_probe = max(0.0, gprobed - base) / probes_per_op
    per_servefleet_probe = max(0.0, fprobed - base) / probes_per_op
    ratio = per_probe / base
    trace_ratio = per_trace_probe / base
    blackbox_ratio = per_blackbox_probe / base
    resolve_ratio = per_resolve_probe / base
    stream_ratio = per_stream_probe / base
    goodput_ratio = per_goodput_probe / base
    servefleet_ratio = per_servefleet_probe / base
    return {"ops": n, "probes_per_op": probes_per_op, "repeats": repeats,
            "baseline_s": round(base, 6), "probed_s": round(probed, 6),
            "trace_probed_s": round(tprobed, 6),
            "blackbox_probed_s": round(bprobed, 6),
            "resolve_probed_s": round(rprobed, 6),
            "stream_probed_s": round(sprobed, 6),
            "goodput_probed_s": round(gprobed, 6),
            "servefleet_probed_s": round(fprobed, 6),
            "trace_enabled_s": round(ton, 6),
            "per_op_probe_overhead_ns": round(per_probe / n * 1e9, 2),
            "per_op_trace_probe_overhead_ns":
                round(per_trace_probe / n * 1e9, 2),
            "per_op_blackbox_probe_overhead_ns":
                round(per_blackbox_probe / n * 1e9, 2),
            "per_op_resolve_probe_overhead_ns":
                round(per_resolve_probe / n * 1e9, 2),
            "per_op_stream_probe_overhead_ns":
                round(per_stream_probe / n * 1e9, 2),
            "per_op_goodput_probe_overhead_ns":
                round(per_goodput_probe / n * 1e9, 2),
            "per_op_servefleet_probe_overhead_ns":
                round(per_servefleet_probe / n * 1e9, 2),
            "overhead_ratio": round(ratio, 6),
            "trace_overhead_ratio": round(trace_ratio, 6),
            "blackbox_overhead_ratio": round(blackbox_ratio, 6),
            "resolve_overhead_ratio": round(resolve_ratio, 6),
            "stream_overhead_ratio": round(stream_ratio, 6),
            "goodput_overhead_ratio": round(goodput_ratio, 6),
            "servefleet_overhead_ratio": round(servefleet_ratio, 6),
            "trace_enabled_ratio": round(max(0.0, ton - base) / base, 6),
            "budget": budget,
            "ok": ratio < budget and trace_ratio < budget
                  and blackbox_ratio < budget and resolve_ratio < budget
                  and stream_ratio < budget and goodput_ratio < budget
                  and servefleet_ratio < budget}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--ops", type=int, default=2000)
    ap.add_argument("--probes-per-op", type=int, default=32)
    ap.add_argument("--repeats", type=int, default=7)
    ap.add_argument("--budget", type=float, default=0.02,
                    help="max disabled-probe cost as a fraction of the "
                         "eager loop (CI enforces the default 2%%)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)
    r = run(args.ops, args.probes_per_op, args.repeats, args.budget)
    if args.json:
        print(json.dumps(r))
    else:
        print(f"baseline eager loop   {r['baseline_s'] * 1e3:9.2f} ms "
              f"({r['ops']} ops)")
        print(f"with {r['probes_per_op']}x disabled telemetry probes/op "
              f"{r['probed_s'] * 1e3:9.2f} ms")
        print(f"with {r['probes_per_op']}x disabled trace probes/op "
              f"{r['trace_probed_s'] * 1e3:9.2f} ms")
        print(f"with {r['probes_per_op']}x disabled blackbox probes/op "
              f"{r['blackbox_probed_s'] * 1e3:9.2f} ms")
        print(f"with {r['probes_per_op']}x untuned resolve_blocks/op "
              f"{r['resolve_probed_s'] * 1e3:9.2f} ms")
        print(f"with {r['probes_per_op']}x disabled stream probes/op "
              f"{r['stream_probed_s'] * 1e3:9.2f} ms")
        print(f"with {r['probes_per_op']}x disabled goodput probes/op "
              f"{r['goodput_probed_s'] * 1e3:9.2f} ms")
        print(f"with {r['probes_per_op']}x disabled servefleet probes/op "
              f"{r['servefleet_probed_s'] * 1e3:9.2f} ms")
        print(f"with tracing ENABLED (1 span/op) "
              f"{r['trace_enabled_s'] * 1e3:9.2f} ms "
              f"(+{r['trace_enabled_ratio'] * 100:.2f}%, informational)")
        print(f"telemetry overhead ratio {r['overhead_ratio'] * 100:9.4f} % "
              f"(budget {r['budget'] * 100:g}%)")
        print(f"trace overhead ratio     "
              f"{r['trace_overhead_ratio'] * 100:9.4f} % "
              f"(budget {r['budget'] * 100:g}%)")
        print(f"blackbox overhead ratio  "
              f"{r['blackbox_overhead_ratio'] * 100:9.4f} % "
              f"(budget {r['budget'] * 100:g}%)")
        print(f"resolve_blocks ratio     "
              f"{r['resolve_overhead_ratio'] * 100:9.4f} % "
              f"(budget {r['budget'] * 100:g}%)")
        print(f"stream overhead ratio    "
              f"{r['stream_overhead_ratio'] * 100:9.4f} % "
              f"(budget {r['budget'] * 100:g}%)")
        print(f"goodput overhead ratio   "
              f"{r['goodput_overhead_ratio'] * 100:9.4f} % "
              f"(budget {r['budget'] * 100:g}%)")
        print(f"servefleet overhead ratio "
              f"{r['servefleet_overhead_ratio'] * 100:9.4f} % "
              f"(budget {r['budget'] * 100:g}%)")
    if not r["ok"]:
        print("FAIL: a disabled observability fast path exceeds the "
              "overhead budget", file=sys.stderr)
        return 1
    print("OK: disabled telemetry + trace + blackbox + untuned "
          "resolve_blocks + stream + goodput + servefleet fast paths "
          "within budget")
    return 0


if __name__ == "__main__":
    sys.exit(main())

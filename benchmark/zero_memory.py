#!/usr/bin/env python
"""ZeRO optimizer-state memory benchmark (CI `zero` stage).

Contract from docs/PERFORMANCE.md: on a >=4-way dp mesh, ``zero=1`` must
cut the PER-DEVICE optimizer-state footprint by at least ``--reduction``
(default 40%) versus the replicated baseline, while staying numerically
invisible (the loss oracle below; the exhaustive parity suite is
tests/test_zero.py).  Adam holds two fp32 slots per parameter, so an
ideal 4-way partition saves 75% — the 40% bar leaves room for padding
and non-partitionable (tp/ep-sharded) leftovers.

Bytes are measured from the arrays themselves: every optimizer-state
leaf's ``addressable_shards`` filtered to one device, so the number is
what the placement actually costs, not an estimate.  The ``memory.*``
telemetry plane (PJRT allocator live/peak) is reported alongside when
the backend provides it; the CPU backend used in CI has no allocator
stats, so that section prints n/a there and lights up on real TPUs.

Usage: python benchmark/zero_memory.py [--reduction 0.4] [--dp 4]
           [--steps 2] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

IN_UNITS = 1024
UNITS = 2048
BATCH = 16


def _make_step(zero, dp, tp=1):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import MeshConfig, make_mesh
    from mxnet_tpu.parallel.train import ShardedTrainStep

    mx.random.seed(7)
    net = nn.Dense(UNITS, in_units=IN_UNITS)
    net.initialize()

    def loss_fn(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], -1))

    if tp > 1:
        # ZeRO x TP: the weight is column-parallel over tp; zero=1 then
        # partitions the state's replicated in_units dim over dp
        cfg = MeshConfig(dp=dp, tp=tp)
        return ShardedTrainStep(
            net, loss_fn, mx.optimizer.create("adam", learning_rate=0.01),
            cfg, batch_specs=(P("dp"), P("dp")), n_labels=1, zero=zero,
            param_specs={"weight": P("tp", None), "bias": P("tp")})
    return ShardedTrainStep(
        net, loss_fn, mx.optimizer.create("adam", learning_rate=0.01),
        make_mesh({"dp": dp}), batch_specs=(P("dp"), P("dp")),
        n_labels=1, zero=zero)


def _state_bytes_on(step, device):
    """Optimizer-state bytes actually resident on ``device``."""
    import jax
    total = 0
    for s in step.states.values():
        for leaf in jax.tree_util.tree_leaves(s):
            for shard in leaf.addressable_shards:
                if shard.device == device:
                    total += shard.data.nbytes
    return total


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--reduction", type=float, default=0.40,
                    help="minimum per-device state-bytes cut (fraction)")
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--tp", type=int, default=2,
                    help="tp size for the ZeRO x TP section (skipped when "
                         "dp*tp exceeds the device count)")
    ap.add_argument("--steps", type=int, default=2)
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import numpy as onp
    import jax
    from mxnet_tpu import telemetry

    if len(jax.devices()) < args.dp:
        print(f"SKIP: needs {args.dp} devices, have {len(jax.devices())}")
        return 0

    rs = onp.random.RandomState(0)
    x = rs.randn(BATCH, IN_UNITS).astype("float32")
    y = rs.randint(0, UNITS, (BATCH,)).astype("int32")

    telemetry.enable()
    telemetry.reset()
    dev0 = jax.devices()[0]
    results = {}
    for zero in (0, 1):
        step = _make_step(zero, args.dp)
        losses = [float(step(x, y).asnumpy()) for _ in range(args.steps)]
        results[zero] = {
            "state_bytes_per_device": _state_bytes_on(step, dev0),
            "losses": losses,
        }

    # ZeRO x TP: same gate on a dp x tp mesh (needs dp*tp devices) — the
    # tensor-sharded weight's state partitions its replicated sub-axis
    tp = args.tp if len(jax.devices()) >= args.dp * args.tp else 1
    results_tp = {}
    if tp > 1:
        for zero in (0, 1):
            step = _make_step(zero, args.dp, tp=tp)
            losses = [float(step(x, y).asnumpy())
                      for _ in range(args.steps)]
            results_tp[zero] = {
                "state_bytes_per_device": _state_bytes_on(step, dev0),
                "losses": losses,
            }
    mem = telemetry.record_memory()
    counters = telemetry.counters(prefix="zero.", aggregate=True)
    telemetry.disable()

    repl = results[0]["state_bytes_per_device"]
    shard = results[1]["state_bytes_per_device"]
    reduction = 1.0 - shard / repl
    # the optimization must be numerically invisible, not just smaller
    onp.testing.assert_allclose(results[1]["losses"], results[0]["losses"],
                                rtol=1e-5, atol=1e-6)

    report = {
        "dp": args.dp,
        "replicated_state_bytes_per_device": repl,
        "zero1_state_bytes_per_device": shard,
        "reduction": reduction,
        "required_reduction": args.reduction,
        "zero_collective_bytes": counters,
        "memory_stats": mem or None,
    }
    if results_tp:
        repl_tp = results_tp[0]["state_bytes_per_device"]
        shard_tp = results_tp[1]["state_bytes_per_device"]
        reduction_tp = 1.0 - shard_tp / repl_tp
        onp.testing.assert_allclose(results_tp[1]["losses"],
                                    results_tp[0]["losses"],
                                    rtol=1e-5, atol=1e-6)
        report["zero_tp"] = {
            "dp": args.dp, "tp": tp,
            "replicated_state_bytes_per_device": repl_tp,
            "zero1_state_bytes_per_device": shard_tp,
            "reduction": reduction_tp,
        }
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(f"dp={args.dp}  optimizer-state bytes/device: "
              f"replicated={repl:,}  zero=1 {shard:,}  "
              f"(-{reduction:.1%}, bar {args.reduction:.0%})")
        if results_tp:
            print(f"dp={args.dp} tp={tp} (ZeRO x TP)  state bytes/device: "
                  f"zero=0 {repl_tp:,}  zero=1 {shard_tp:,}  "
                  f"(-{reduction_tp:.1%}, bar {args.reduction:.0%})")
        print(f"zero collective bytes: {counters}")
        print("memory.* (PJRT): "
              + (json.dumps(mem) if mem else "n/a on this backend"))

    if reduction < args.reduction:
        print(f"FAIL: reduction {reduction:.1%} < required "
              f"{args.reduction:.0%}")
        return 1
    if results_tp and reduction_tp < args.reduction:
        print(f"FAIL: ZeRO x TP reduction {reduction_tp:.1%} < required "
              f"{args.reduction:.0%}")
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Round-over-round diff of opperf JSON artifacts.

Reference analog: benchmark/opperf/ emits per-op timings but ships no
regression tooling; CI consumers diff runs by hand. This closes that loop:

    python benchmark/opperf_diff.py OPPERF_prev.json OPPERF.json \
        [--threshold 0.25] [--metric e2e_us]

Prints ops that regressed/improved by more than `threshold` (fractional),
plus ops that appeared, disappeared, or changed error status. Exits 1 if
any regression exceeds the threshold so CI can gate on it. Sub-threshold
noise is suppressed: microbench jitter on a tunneled TPU is easily ±10%,
so the default gate is 25%.
"""
from __future__ import annotations

import argparse
import json
import sys


def _load(path):
    with open(path) as f:
        rows = json.load(f)
    if isinstance(rows, dict):  # {'platform': ..., 'rows': [...]} wrapper
        rows = rows["rows"]
    return {r["op"]: r for r in rows}


def diff(prev, cur, metric="e2e_us", threshold=0.25):
    """Return (regressions, improvements, status_changes) row lists."""
    regs, imps, status = [], [], []
    for op in sorted(set(prev) | set(cur)):
        p, c = prev.get(op), cur.get(op)
        if p is None:
            status.append((op, "NEW", c.get(metric, c.get("error"))))
            continue
        if c is None:
            status.append((op, "REMOVED", p.get(metric, p.get("error"))))
            continue
        p_err, c_err = "error" in p, "error" in c
        if p_err != c_err:
            status.append((op, "NOW-ERROR" if c_err else "FIXED",
                           c.get("error", c.get(metric))))
            continue
        if p_err:  # both error: nothing to compare
            continue
        pv, cv = p.get(metric), c.get(metric)
        if pv is None or cv is None:  # artifact predates this metric
            status.append((op, "NO-METRIC", metric))
            continue
        if pv <= 0:
            continue
        rel = (cv - pv) / pv
        if rel > threshold:
            regs.append((op, pv, cv, rel))
        elif rel < -threshold:
            imps.append((op, pv, cv, rel))
    return regs, imps, status


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("prev")
    ap.add_argument("cur")
    ap.add_argument("--metric", default="e2e_us",
                    choices=["e2e_us", "dispatch_us"])
    ap.add_argument("--threshold", type=float, default=0.25)
    args = ap.parse_args()

    prev_map, cur_map = _load(args.prev), _load(args.cur)
    regs, imps, status = diff(prev_map, cur_map,
                              args.metric, args.threshold)
    for op, kind, detail in status:
        print(f"{kind:10s} {op:24s} {detail}")
    for op, pv, cv, rel in sorted(imps, key=lambda r: r[3]):
        print(f"{'IMPROVED':10s} {op:24s} {pv:10.2f} -> {cv:10.2f} "
              f"({rel:+.0%})")
    for op, pv, cv, rel in sorted(regs, key=lambda r: -r[3]):
        print(f"{'REGRESSED':10s} {op:24s} {pv:10.2f} -> {cv:10.2f} "
              f"({rel:+.0%})")
    n_err = sum(1 for op, k, _ in status
                if k == "NOW-ERROR"
                or (k == "NEW" and "error" in cur_map[op]))
    print(f"# {len(regs)} regressions, {len(imps)} improvements, "
          f"{len(status)} status changes ({args.metric}, "
          f"gate {args.threshold:.0%})")
    sys.exit(1 if (regs or n_err) else 0)


if __name__ == "__main__":
    main()

"""Continuous-batching serve throughput vs sequential decode (CI `serve`
stage; the PR 6 acceptance benchmark).

Workload: N requests with mixed prompt lengths arriving by a Poisson
process (exponential inter-arrival gaps). Two runs over the SAME model
and the SAME compiled surface (mx.serve.ServeEngine):

- **continuous**: max_slots slots, requests admitted mid-flight as slots
  free — the engine amortizes every decode step over all live requests.
- **sequential**: a max_slots=1 engine fed the whole batch up front (no
  arrival waits — the most favorable sequential framing), so the measured
  speedup is pure continuous-batching gain, not queueing-theory noise.

Reported per run: tokens/s, wall seconds, decode steps, TTFT/TPOT
p50/p95/p99 — percentiles come from the ``serve.*`` telemetry histograms
(telemetry.quantiles), not from host-side sorting, so the benchmark also
exercises the exposition path CI scrapes. ``--assert`` enforces the PR 6
acceptance bar: speedup >= --min-speedup (default 2.0) and ZERO
post-warmup recompiles in either engine.

Prints ONE JSON line (the bench.py contract).

Usage: JAX_PLATFORMS=cpu python benchmark/serve_throughput.py --assert
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(on_cpu):
    """Tiny GPT on CPU (CI smoke), gpt2-124m class on an accelerator."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM

    if on_cpu:
        cfg = dict(vocab_size=512, units=64, hidden_size=256, num_layers=2,
                   num_heads=4, max_length=128)
    else:
        cfg = dict(vocab_size=50257, units=768, hidden_size=3072,
                   num_layers=12, num_heads=12, max_length=512)
    net = GPTForCausalLM(dropout=0.0, embed_dropout=0.0, **cfg)
    net.initialize()
    net(mx.np.zeros((1, 2), dtype="int32"))
    return net, cfg


def make_workload(n, vocab, max_prompt, max_new, rate_hz, seed):
    """(prompt, max_new_tokens, arrival_offset_s) triples; Poisson
    arrivals, mixed prompt lengths across the bucket grid."""
    rng = onp.random.RandomState(seed)
    gaps = rng.exponential(1.0 / rate_hz, size=n)
    t = onp.cumsum(gaps)
    t[0] = 0.0  # first request opens the clock
    work = []
    for i in range(n):
        length = int(rng.randint(2, max_prompt + 1))
        prompt = rng.randint(1, vocab, size=length).tolist()
        new = int(rng.randint(max(1, max_new // 2), max_new + 1))
        work.append((prompt, new, float(t[i])))
    return work


def _percentiles(name):
    from mxnet_tpu import telemetry
    q = telemetry.quantiles(name)
    if not q:
        return None
    return {k: round(v, 6) for k, v in q.items()}


def run_engine(net, work, slots, arrivals, drain_window=8, seed=0):
    """Drive one engine over the workload; percentiles read back out of
    the serve.* telemetry histograms, per-phase breakdown (queue-wait /
    prefill / per-token decode) out of the mx.trace spans the engine
    records while tracing is on."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry, trace

    telemetry.reset()
    telemetry.enable()
    trace.clear()
    trace.enable()
    try:
        eng = mx.serve.load(net, max_slots=slots, drain_window=drain_window,
                            seed=seed, warmup=True)
        todo = sorted(work, key=lambda w: w[2])
        reqs, i = [], 0
        t0 = time.perf_counter()
        while i < len(todo) or eng.pending:
            now = time.perf_counter() - t0
            while i < len(todo) and (not arrivals or todo[i][2] <= now):
                prompt, new, _t = todo[i]
                reqs.append(eng.submit(prompt, max_new_tokens=new))
                i += 1
            if not eng.step() and i < len(todo):
                # idle before the next arrival: wait it out off the clock?
                # no — Poisson waits are part of the continuous story;
                # spin to the next arrival time
                time.sleep(min(1e-3, max(0.0, todo[i][2] - now)))
        eng.drain()
        wall = time.perf_counter() - t0
        st = eng.stats()
        assert st["completed"] == len(work), (st["completed"], len(work))
        return {
            "slots": slots,
            "tokens_out": st["tokens_out"],
            "tokens_per_s": st["tokens_out"] / wall,
            "wall_s": round(wall, 4),
            "decode_steps": st["steps"],
            "compiles": st["compiles"],
            "post_warmup_compiles": st["post_warmup_compiles"],
            "ttft_s": _percentiles("serve.ttft_seconds"),
            "tpot_s": _percentiles("serve.tpot_seconds"),
            "step_s": _percentiles("serve.step_seconds"),
            "phases_s": {
                phase: (q and {k: round(v, 6) for k, v in q.items()})
                for phase, q in st["phases"].items()},
        }, [r.output_ids for r in reqs]
    finally:
        trace.disable()
        trace.clear()
        telemetry.disable()
        telemetry.reset()


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--max-new", type=int, default=48)
    p.add_argument("--rate-hz", type=float, default=1000.0,
                   help="Poisson arrival rate (requests/s)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--min-speedup", type=float, default=2.0)
    p.add_argument("--assert", dest="check", action="store_true",
                   help="exit nonzero unless speedup and recompile bars hold")
    args = p.parse_args(argv)

    import jax
    on_cpu = jax.devices()[0].platform == "cpu"
    net, cfg = build_model(on_cpu)
    max_prompt = min(24, cfg["max_length"] // 4)
    work = make_workload(args.requests, cfg["vocab_size"], max_prompt,
                         args.max_new, args.rate_hz, args.seed)

    cont, cont_out = run_engine(net, work, slots=args.slots, arrivals=True,
                                seed=args.seed)
    seq, seq_out = run_engine(net, work, slots=1, arrivals=False,
                              seed=args.seed)
    # same engine, same seed, same greedy default => identical tokens;
    # any divergence means scheduling corrupted the KV cache
    matched = sum(a == b for a, b in zip(cont_out, seq_out))

    speedup = cont["tokens_per_s"] / seq["tokens_per_s"]
    recompiles = cont["post_warmup_compiles"] + seq["post_warmup_compiles"]
    ok = speedup >= args.min_speedup and recompiles == 0
    print(json.dumps({
        "metric": "serve_continuous_vs_sequential",
        "value": round(speedup, 3),
        "unit": "x tokens/s",
        "requests": args.requests,
        "outputs_matched": f"{matched}/{len(work)}",
        "post_warmup_recompiles": recompiles,
        "platform": "cpu" if on_cpu else jax.devices()[0].platform,
        "continuous": {k: v for k, v in cont.items()},
        "sequential": {k: v for k, v in seq.items()},
        "ok": ok,
    }))
    if args.check and not ok:
        print(f"FAIL: speedup {speedup:.2f}x < {args.min_speedup}x or "
              f"{recompiles} post-warmup recompiles", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Continuous-batching serve throughput vs sequential decode (CI `serve`
stage; the PR 6 acceptance benchmark).

Workload: N requests with mixed prompt lengths arriving by a Poisson
process (exponential inter-arrival gaps). Two runs over the SAME model
and the SAME compiled surface (mx.serve.ServeEngine):

- **continuous**: max_slots slots, requests admitted mid-flight as slots
  free — the engine amortizes every decode step over all live requests.
- **sequential**: a max_slots=1 engine fed the whole batch up front (no
  arrival waits — the most favorable sequential framing), so the measured
  speedup is pure continuous-batching gain, not queueing-theory noise.

Reported per run: tokens/s, wall seconds, decode steps, TTFT/TPOT
p50/p95/p99 — percentiles come from the ``serve.*`` telemetry histograms
(telemetry.quantiles), not from host-side sorting, so the benchmark also
exercises the exposition path CI scrapes. ``--assert`` enforces the PR 6
acceptance bar: speedup >= --min-speedup (default 2.0) and ZERO
post-warmup recompiles in either engine.

Prints ONE JSON line (the bench.py contract).

Usage: JAX_PLATFORMS=cpu python benchmark/serve_throughput.py --assert
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as onp

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(on_cpu):
    """Tiny GPT on CPU (CI smoke), gpt2-124m class on an accelerator."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM

    if on_cpu:
        cfg = dict(vocab_size=512, units=64, hidden_size=256, num_layers=2,
                   num_heads=4, max_length=128)
    else:
        cfg = dict(vocab_size=50257, units=768, hidden_size=3072,
                   num_layers=12, num_heads=12, max_length=512)
    net = GPTForCausalLM(dropout=0.0, embed_dropout=0.0, **cfg)
    net.initialize()
    net(mx.np.zeros((1, 2), dtype="int32"))
    return net, cfg


def make_workload(n, vocab, max_prompt, max_new, rate_hz, seed):
    """(prompt, max_new_tokens, arrival_offset_s) triples; Poisson
    arrivals, mixed prompt lengths across the bucket grid."""
    rng = onp.random.RandomState(seed)
    gaps = rng.exponential(1.0 / rate_hz, size=n)
    t = onp.cumsum(gaps)
    t[0] = 0.0  # first request opens the clock
    work = []
    for i in range(n):
        length = int(rng.randint(2, max_prompt + 1))
        prompt = rng.randint(1, vocab, size=length).tolist()
        new = int(rng.randint(max(1, max_new // 2), max_new + 1))
        work.append((prompt, new, float(t[i])))
    return work


def _percentiles(name):
    from mxnet_tpu import telemetry
    q = telemetry.quantiles(name)
    if not q:
        return None
    return {k: round(v, 6) for k, v in q.items()}


def run_engine(net, work, slots, arrivals, drain_window=8, seed=0,
               prefix_cache=False, draft=None, passes=1):
    """Drive one engine over the workload; percentiles read back out of
    the serve.* telemetry histograms, per-phase breakdown (queue-wait /
    prefill / per-token decode) out of the mx.trace spans the engine
    records while tracing is on.  Work items are (prompt, max_new,
    arrival_s) or (prompt, max_new, arrival_s, slo_class).

    ``passes > 1`` replays the workload on the SAME warm engine and
    keeps the best pass's wall clock — steady-state throughput (greedy
    decode is deterministic, so every pass emits identical tokens),
    robust to scheduler jitter on ~100ms CI walls."""
    import mxnet_tpu as mx
    from mxnet_tpu import telemetry, trace

    telemetry.reset()
    telemetry.enable()
    trace.clear()
    trace.enable()
    try:
        eng = mx.serve.load(net, max_slots=slots, drain_window=drain_window,
                            seed=seed, warmup=True,
                            prefix_cache=prefix_cache, draft=draft)
        todo = sorted(work, key=lambda w: w[2])
        best = None
        for _ in range(passes):
            reqs, i = [], 0
            t0 = time.perf_counter()
            while i < len(todo) or eng.pending:
                now = time.perf_counter() - t0
                while i < len(todo) and (not arrivals or todo[i][2] <= now):
                    item = todo[i]
                    cls = item[3] if len(item) > 3 else None
                    reqs.append(eng.submit(item[0], max_new_tokens=item[1],
                                           slo_class=cls))
                    i += 1
                if not eng.step() and i < len(todo):
                    # idle before the next arrival: wait it out off the
                    # clock? no — Poisson waits are part of the
                    # continuous story; spin to the next arrival time
                    time.sleep(min(1e-3, max(0.0, todo[i][2] - now)))
            eng.drain()
            wall = time.perf_counter() - t0
            best = wall if best is None else min(best, wall)
        wall = best
        st = eng.stats()
        assert st["completed"] == passes * len(work), \
            (st["completed"], passes, len(work))
        out = {
            "slots": slots,
            "tokens_out": st["tokens_out"] // passes,
            "tokens_per_s": st["tokens_out"] / passes / wall,
            "wall_s": round(wall, 4),
            "decode_steps": st["steps"],
            "compiles": st["compiles"],
            "post_warmup_compiles": st["post_warmup_compiles"],
            "ttft_s": _percentiles("serve.ttft_seconds"),
            "tpot_s": _percentiles("serve.tpot_seconds"),
            "step_s": _percentiles("serve.step_seconds"),
            "phases_s": {
                phase: (q and {k: round(v, 6) for k, v in q.items()})
                for phase, q in st["phases"].items()},
        }
        for extra in ("prefix", "spec", "classes"):
            if extra in st:
                out[extra] = st[extra]
        return out, [r.output_ids for r in reqs]
    finally:
        trace.disable()
        trace.clear()
        telemetry.disable()
        telemetry.reset()


def make_tenant_workload(n, tenants, vocab, prefix_len, max_new, rate_hz,
                         seed):
    """Multi-tenant shared-prefix mix: each tenant owns one shared
    ``prefix_len``-token prompt prefix; requests append a short random
    suffix.  Tenant 0 is the high-priority 'gold' class, the rest
    'bronze' — the SLO-class ordering half of the benchmark."""
    rng = onp.random.RandomState(seed)
    prefixes = [rng.randint(1, vocab, size=prefix_len).tolist()
                for _ in range(tenants)]
    gaps = rng.exponential(1.0 / rate_hz, size=n)
    t = onp.cumsum(gaps)
    t[0] = 0.0
    work = []
    for i in range(n):
        tenant = int(rng.randint(0, tenants))
        suffix = rng.randint(1, vocab,
                             size=int(rng.randint(1, 9))).tolist()
        cls = "gold" if tenant == 0 else "bronze"
        work.append((prefixes[tenant] + suffix, int(max_new),
                     float(t[i]), cls))
    return work


def tenant_main(args, net, cfg, on_cpu):
    """--tenants mode: the PR 19 acceptance benchmark.  Three runs over
    one shared-prefix multi-tenant Poisson workload:

    1. prefix cache ON   — the cache-hit-rate floor and the >=
       --min-prefix-speedup tokens/s bar versus run 2
    2. prefix cache OFF  — the baseline, also the token-parity oracle
    3. speculative (self-draft, 100%-acceptance plumbing) — greedy
       parity with run 2 and the TPOT p50 ratio

    Both runs 1 and 2 serve under gold/bronze SLO classes; under the
    Poisson overload the gold p99 TTFT must not exceed bronze's (strict
    priority admission is what the low class absorbs queueing for)."""
    import jax
    import mxnet_tpu as mx
    from mxnet_tpu import config as mxconfig
    from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM

    # the tenant workload gets its own longer-context model: prefix
    # caching pays when the shared prefix carries most of the prefill
    # compute, so the prompt is almost all prefix (full context minus
    # room for the suffix bucket) and the decode tail is short
    cfg = dict(cfg)
    cfg["max_length"] = 4 * cfg["max_length"]
    net = GPTForCausalLM(dropout=0.0, embed_dropout=0.0, **cfg)
    net.initialize()
    net(mx.np.zeros((1, 2), dtype="int32"))
    block = int(mxconfig.get("serve.prefix_block"))
    prefix_len = cfg["max_length"] - 3 * block
    # deliberate overload: arrivals far faster than service, so the
    # queue stays deep — the regime where strict-priority admission
    # (gold vs bronze p99) means anything and where wall clock measures
    # service time, not Poisson gaps
    work = make_tenant_workload(
        args.requests, args.tenants, cfg["vocab_size"], prefix_len,
        max_new=max(2, args.max_new // 24), rate_hz=args.rate_hz * 20,
        seed=args.seed)
    old_classes = mxconfig.get("serve.slo_classes")
    mxconfig.set("serve.slo_classes", "gold,bronze")
    try:
        pref, pref_out = run_engine(net, work, slots=args.slots,
                                    arrivals=True, seed=args.seed,
                                    prefix_cache=True, passes=3)
        base, base_out = run_engine(net, work, slots=args.slots,
                                    arrivals=True, seed=args.seed,
                                    passes=3)
        spec, spec_out = run_engine(net, work, slots=args.slots,
                                    arrivals=True, seed=args.seed,
                                    draft=net, passes=3)
    finally:
        mxconfig.set("serve.slo_classes", old_classes)

    prefix_parity = sum(a == b for a, b in zip(pref_out, base_out))
    spec_parity = sum(a == b for a, b in zip(spec_out, base_out))
    speedup = pref["tokens_per_s"] / base["tokens_per_s"]
    hit_rate = pref["prefix"]["hit_rate"] or 0.0
    tpot_gain = ((base["tpot_s"] or {}).get("p50", 0.0)
                 / max(1e-9, (spec["tpot_s"] or {}).get("p50", 1e-9)))
    gold_p99 = pref["classes"]["gold"]["ttft"]["p99"]
    bronze_p99 = pref["classes"]["bronze"]["ttft"]["p99"]
    recompiles = sum(r["post_warmup_compiles"] for r in (pref, base, spec))
    ok = (prefix_parity == len(work)
          and spec_parity == len(work)
          and hit_rate >= args.min_hit_rate
          and speedup >= args.min_prefix_speedup
          and tpot_gain >= args.min_spec_tpot_gain
          and gold_p99 is not None and bronze_p99 is not None
          and gold_p99 <= bronze_p99
          and recompiles == 0)
    print(json.dumps({
        "metric": "serve_multi_tenant_prefix_speedup",
        "value": round(speedup, 3),
        "unit": "x tokens/s",
        "requests": args.requests,
        "tenants": args.tenants,
        "prefix_hit_rate": round(hit_rate, 4),
        "prefix_parity": f"{prefix_parity}/{len(work)}",
        "spec_parity": f"{spec_parity}/{len(work)}",
        "spec_acceptance_rate": spec["spec"]["acceptance_rate"],
        "spec_tpot_gain": round(tpot_gain, 3),
        "gold_ttft_p99_s": gold_p99 and round(gold_p99, 6),
        "bronze_ttft_p99_s": bronze_p99 and round(bronze_p99, 6),
        "post_warmup_recompiles": recompiles,
        "platform": "cpu" if on_cpu else jax.devices()[0].platform,
        "prefix_on": pref,
        "prefix_off": base,
        "speculative": spec,
        "ok": ok,
    }))
    if args.check and not ok:
        print(f"FAIL: parity {prefix_parity}+{spec_parity}/{len(work)}, "
              f"hit_rate {hit_rate:.2f} (floor {args.min_hit_rate}), "
              f"speedup {speedup:.2f}x (floor {args.min_prefix_speedup}x), "
              f"tpot_gain {tpot_gain:.2f}x, gold p99 {gold_p99} vs bronze "
              f"{bronze_p99}, {recompiles} recompiles", file=sys.stderr)
        return 1
    return 0


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--requests", type=int, default=24)
    p.add_argument("--slots", type=int, default=8)
    p.add_argument("--max-new", type=int, default=48)
    p.add_argument("--rate-hz", type=float, default=1000.0,
                   help="Poisson arrival rate (requests/s)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--min-speedup", type=float, default=2.0)
    p.add_argument("--tenants", type=int, default=0, metavar="N",
                   help="multi-tenant shared-prefix mode: N tenants with "
                        "gold/bronze SLO classes; gates the prefix-cache "
                        "speedup, hit-rate floor, spec-decode parity and "
                        "per-class p99 TTFT ordering instead of the "
                        "continuous-vs-sequential bar")
    p.add_argument("--min-hit-rate", type=float, default=0.5,
                   help="tenants mode: prefix cache hit-rate floor")
    p.add_argument("--min-prefix-speedup", type=float, default=1.5,
                   help="tenants mode: tokens/s floor, prefix on vs off")
    p.add_argument("--min-spec-tpot-gain", type=float, default=0.0,
                   help="tenants mode: TPOT p50 ratio floor, baseline vs "
                        "speculative (self-draft)")
    p.add_argument("--assert", dest="check", action="store_true",
                   help="exit nonzero unless speedup and recompile bars hold")
    args = p.parse_args(argv)

    import jax
    on_cpu = jax.devices()[0].platform == "cpu"
    net, cfg = build_model(on_cpu)
    if args.tenants:
        return tenant_main(args, net, cfg, on_cpu)
    max_prompt = min(24, cfg["max_length"] // 4)
    work = make_workload(args.requests, cfg["vocab_size"], max_prompt,
                         args.max_new, args.rate_hz, args.seed)

    cont, cont_out = run_engine(net, work, slots=args.slots, arrivals=True,
                                seed=args.seed)
    seq, seq_out = run_engine(net, work, slots=1, arrivals=False,
                              seed=args.seed)
    # same engine, same seed, same greedy default => identical tokens;
    # any divergence means scheduling corrupted the KV cache
    matched = sum(a == b for a, b in zip(cont_out, seq_out))

    speedup = cont["tokens_per_s"] / seq["tokens_per_s"]
    recompiles = cont["post_warmup_compiles"] + seq["post_warmup_compiles"]
    ok = speedup >= args.min_speedup and recompiles == 0
    print(json.dumps({
        "metric": "serve_continuous_vs_sequential",
        "value": round(speedup, 3),
        "unit": "x tokens/s",
        "requests": args.requests,
        "outputs_matched": f"{matched}/{len(work)}",
        "post_warmup_recompiles": recompiles,
        "platform": "cpu" if on_cpu else jax.devices()[0].platform,
        "continuous": {k: v for k, v in cont.items()},
        "sequential": {k: v for k, v in seq.items()},
        "ok": ok,
    }))
    if args.check and not ok:
        print(f"FAIL: speedup {speedup:.2f}x < {args.min_speedup}x or "
              f"{recompiles} post-warmup recompiles", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

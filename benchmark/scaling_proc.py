#!/usr/bin/env python
"""Per-process scaling probe, run under tools/launch.py.

Each rank pins itself to a distinct core set BEFORE importing jax, so the
measured collective latency is communication + framework overhead — not
the core contention that pollutes the in-process virtual-mesh table
(MULTICHIP weak-scaling caveat). Prints one line per rank:

    PROC_SCALING {"rank", "n", "compute_ms", "allreduce": [...]}

Reference anchor: tools/bandwidth/measure.py + tests/nightly/
dist_sync_kvstore.py launch taxonomy.
"""
import json
import os
import time

rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
nproc = int(os.environ.get("DMLC_NUM_WORKER", "1"))
ncores = os.cpu_count() or 1
per = max(1, ncores // max(nproc, 1))
cores = {(rank * per + i) % ncores for i in range(per)}  # wraps when
os.sched_setaffinity(0, cores)                           # ranks > cores

import jax  # noqa: E402  (after affinity pinning)

from mxnet_tpu._dist_init import ensure_distributed  # noqa: E402

ensure_distributed()

import jax.numpy as jnp  # noqa: E402

from mxnet_tpu.parallel.collectives import (  # noqa: E402
    allreduce_across_processes)


def main():
    # local compute reference: jitted 512^2 matmul chain on this rank's core
    m = jnp.ones((512, 512), jnp.float32)
    f = jax.jit(lambda x: x @ x * 0.999)
    f(m).block_until_ready()
    t0 = time.perf_counter()
    out = m
    for _ in range(20):
        out = f(out)
    out.block_until_ready()
    compute_ms = (time.perf_counter() - t0) / 20 * 1e3

    rows = []
    for nfloat in (1 << 18, 1 << 22):          # 1 MiB, 16 MiB payloads
        v = jnp.ones((nfloat,), jnp.float32)
        allreduce_across_processes(v).block_until_ready()  # compile+connect
        t0 = time.perf_counter()
        iters = 8
        for _ in range(iters):
            out = allreduce_across_processes(v)
        out.block_until_ready()
        ms = (time.perf_counter() - t0) / iters * 1e3
        rows.append({"bytes": nfloat * 4, "allreduce_ms": round(ms, 3),
                     "gbps": round(nfloat * 4 * 8 / (ms / 1e3) / 1e9, 2)})

    print("PROC_SCALING " + json.dumps({
        "rank": rank, "n": nproc, "cores_per_rank": per,
        "compute_ms": round(compute_ms, 3), "allreduce": rows}),
        flush=True)


if __name__ == "__main__":
    main()

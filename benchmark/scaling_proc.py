#!/usr/bin/env python
"""Per-process scaling probe, run under tools/launch.py.

Each rank pins itself to a distinct core set BEFORE importing jax, so the
measured collective latency is communication + framework overhead — not
the core contention that pollutes the in-process virtual-mesh table
(MULTICHIP weak-scaling caveat). Prints one line per rank:

    PROC_SCALING {"rank", "n", "compute_ms", "allreduce": [...]}

``--loader-gate`` instead runs the proc-vs-thread DataLoader regression
fence (no distributed setup, no affinity pin): the spawn process pool
must deliver >= 0.8x the thread pool's throughput on the GIL-bound
python-transform dataset, or the PR that reintroduced per-epoch pool
spinup / shm churn fails CI. Prints one line and exits nonzero on
regression:

    LOADER_GATE {"ok", "ratio", "threshold", ...}

Reference anchor: tools/bandwidth/measure.py + tests/nightly/
dist_sync_kvstore.py launch taxonomy.
"""
import json
import os
import sys
import time

_LOADER_GATE = "--loader-gate" in sys.argv


def _loader_gate(workers=2, n=32, dim=2048, batch=16, threshold=0.8):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataloader import _PyBenchDataset

    ds = _PyBenchDataset(n, dim)

    def run(thread_pool, repeats=2):
        dl = DataLoader(ds, batch_size=batch, num_workers=workers,
                        thread_pool=thread_pool)
        # warm the pool first: the persistent spawn pool boots lazily and
        # its worker-import cost is a fixed startup fee, not loader
        # throughput (the thing the 0.8x fence guards)
        for _ in range(1 if thread_pool else 3):
            for _b in dl:
                pass
        best = 0.0
        for _ in range(repeats):  # best-of-N absorbs 1-core CI jitter
            t0 = time.perf_counter()
            cnt = 0
            for b in dl:
                cnt += b.shape[0]
            best = max(best, cnt / (time.perf_counter() - t0))
        if not thread_pool:
            dl._proc_pool.shutdown(wait=False, cancel_futures=True)
        return best

    thr = run(True)
    proc = run(False)
    ratio = proc / thr
    ok = ratio >= threshold
    print("LOADER_GATE " + json.dumps({
        "ok": ok, "ratio": round(ratio, 3), "threshold": threshold,
        "proc_items_per_s": round(proc, 1),
        "thread_items_per_s": round(thr, 1),
        "workers": workers, "n": n, "cpu_count": os.cpu_count()}),
        flush=True)
    return 0 if ok else 1


if _LOADER_GATE and __name__ == "__main__":
    sys.exit(_loader_gate())

if not _LOADER_GATE:
    # scaling-probe mode only: the loader gate must not pin cores or join
    # the coordinator, and neither may the spawn workers that re-execute
    # this module as __mp_main__.
    rank = int(os.environ.get("DMLC_WORKER_ID", "0"))
    nproc = int(os.environ.get("DMLC_NUM_WORKER", "1"))
    ncores = os.cpu_count() or 1
    per = max(1, ncores // max(nproc, 1))
    cores = {(rank * per + i) % ncores for i in range(per)}  # wraps when
    os.sched_setaffinity(0, cores)                           # ranks > cores

    import jax  # noqa: E402  (after affinity pinning)

    from mxnet_tpu._dist_init import ensure_distributed  # noqa: E402

    ensure_distributed()

    import jax.numpy as jnp  # noqa: E402

    from mxnet_tpu.parallel.collectives import (  # noqa: E402
        allreduce_across_processes)


def main():
    # local compute reference: jitted 512^2 matmul chain on this rank's core
    m = jnp.ones((512, 512), jnp.float32)
    f = jax.jit(lambda x: x @ x * 0.999)
    f(m).block_until_ready()
    t0 = time.perf_counter()
    out = m
    for _ in range(20):
        out = f(out)
    out.block_until_ready()
    compute_ms = (time.perf_counter() - t0) / 20 * 1e3

    rows = []
    for nfloat in (1 << 18, 1 << 22):          # 1 MiB, 16 MiB payloads
        v = jnp.ones((nfloat,), jnp.float32)
        allreduce_across_processes(v).block_until_ready()  # compile+connect
        t0 = time.perf_counter()
        iters = 8
        for _ in range(iters):
            out = allreduce_across_processes(v)
        out.block_until_ready()
        ms = (time.perf_counter() - t0) / iters * 1e3
        rows.append({"bytes": nfloat * 4, "allreduce_ms": round(ms, 3),
                     "gbps": round(nfloat * 4 * 8 / (ms / 1e3) / 1e9, 2)})

    print("PROC_SCALING " + json.dumps({
        "rank": rank, "n": nproc, "cores_per_rank": per,
        "compute_ms": round(compute_ms, 3), "allreduce": rows}),
        flush=True)


if __name__ == "__main__":
    main()

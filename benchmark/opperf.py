#!/usr/bin/env python
"""Per-op microbenchmark harness (reference: benchmark/opperf/opperf.py:56 —
runs every registered op with timing; here the focus is the two numbers the
TPU design cares about per op: eager DISPATCH overhead on the host (the
reference's 'hard part #1', SURVEY §7) and end-to-end device time).

Method: for each op, N dispatches are issued back-to-back and the chain is
synced once at the end (e2e/iter); dispatch overhead is the host time of
the issuing loop alone. Prints a table and optionally JSON.

Usage: python benchmark/opperf.py [--ops add,matmul,...] [--json out.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def _default_ops(mx, shape):
    np, npx = mx.np, mx.npx
    a = np.random.uniform(size=shape)
    b = np.random.uniform(size=shape)
    m = np.random.uniform(size=(shape[0], shape[0]))
    idx = np.array(onp.random.randint(0, shape[0], (64,)), dtype="int32")
    ops = {
        # elementwise arithmetic
        "add": lambda: a + b, "subtract": lambda: a - b,
        "multiply": lambda: a * b, "true_divide": lambda: a / b,
        "negative": lambda: -a, "power": lambda: a ** 2,
        "maximum": lambda: np.maximum(a, b),
        "minimum": lambda: np.minimum(a, b),
        "where": lambda: np.where(a > b, a, b),
        "clip": lambda: np.clip(a, 0.2, 0.8),
        # unary math
        "exp": lambda: np.exp(a), "log": lambda: np.log(a + 1),
        "sqrt": lambda: np.sqrt(a), "square": lambda: np.square(a),
        "abs": lambda: np.abs(a), "sign": lambda: np.sign(a),
        "tanh": lambda: np.tanh(a), "erf": lambda: npx.erf(a),
        "sigmoid": lambda: npx.sigmoid(a), "relu": lambda: npx.relu(a),
        "gelu": lambda: npx.leaky_relu(a, act_type="gelu"),
        # reductions
        "sum": lambda: np.sum(a), "mean": lambda: np.mean(a),
        "max": lambda: np.max(a), "min": lambda: np.min(a),
        "var": lambda: np.var(a), "argmax": lambda: np.argmax(a),
        "norm": lambda: np.linalg.norm(a),
        "softmax": lambda: npx.softmax(a),
        "log_softmax": lambda: npx.log_softmax(a),
        # linear algebra / MXU
        "matmul": lambda: np.matmul(m, m),
        "dot": lambda: np.dot(m, m),
        "einsum": lambda: np.einsum("ij,jk->ik", m, m),
        "tensordot": lambda: np.tensordot(m, m, axes=1),
        # shape / data movement
        "reshape": lambda: a.reshape(-1),
        "transpose": lambda: np.transpose(a),
        "concatenate": lambda: np.concatenate([a, b], axis=0),
        "stack": lambda: np.stack([a, b]),
        "split": lambda: np.split(a, 2, axis=0),
        "expand_dims": lambda: np.expand_dims(a, 0),
        "squeeze": lambda: np.squeeze(np.expand_dims(a, 0), 0),
        "broadcast_to": lambda: np.broadcast_to(a[:1], shape),
        "tile": lambda: np.tile(a[:8], (2, 1)),
        "take": lambda: np.take(a, idx, axis=0),
        "gather(embedding)": lambda: npx.embedding(
            idx, m, input_dim=m.shape[0], output_dim=m.shape[1]),
        "one_hot": lambda: npx.one_hot(idx, 64),
        "arange": lambda: np.arange(shape[0]),
        "zeros": lambda: np.zeros(shape),
        "cumsum": lambda: np.cumsum(a, axis=0),
        "sort": lambda: np.sort(a, axis=-1),
        "topk": lambda: npx.topk(a, k=4),
        "batch_norm-like": lambda: (a - np.mean(a)) / np.sqrt(np.var(a) + 1e-5),
        "layer_norm": lambda: npx.layer_norm(
            a, np.ones((shape[-1],)), np.zeros((shape[-1],)), axis=-1),
    }
    return ops


def run(ops=None, warmup=5, iters=100, shape=(128, 128)):
    import mxnet_tpu as mx
    table = _default_ops(mx, shape)
    if ops:
        table = {k: v for k, v in table.items() if k in ops}
    rows = []
    for name, fn in table.items():
        try:
            for _ in range(warmup):
                out = fn()
            mx.nd.waitall()
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn()
            t_dispatch = time.perf_counter() - t0
            mx.nd.waitall()
            t_e2e = time.perf_counter() - t0
            rows.append({"op": name,
                         "dispatch_us": round(t_dispatch / iters * 1e6, 2),
                         "e2e_us": round(t_e2e / iters * 1e6, 2)})
        except Exception as e:
            rows.append({"op": name, "error": repr(e)[:120]})
        del out
    return rows


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ops", default=None,
                   help="comma-separated subset (default: all)")
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--shape", default="128,128")
    p.add_argument("--json", default=None, help="also write JSON here")
    args = p.parse_args()
    shape = tuple(int(s) for s in args.shape.split(","))
    ops = set(args.ops.split(",")) if args.ops else None
    rows = run(ops=ops, iters=args.iters, shape=shape)
    print(f"{'Op':24s} {'dispatch(us)':>14s} {'e2e(us)':>12s}")
    for r in sorted(rows, key=lambda r: -r.get("e2e_us", 0)):
        if "error" in r:
            print(f"{r['op']:24s}  ERROR {r['error']}")
        else:
            print(f"{r['op']:24.24s} {r['dispatch_us']:14.2f} "
                  f"{r['e2e_us']:12.2f}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Per-op microbenchmark harness (reference: benchmark/opperf/opperf.py:56 —
runs every registered op with timing; here the focus is the two numbers the
TPU design cares about per op: eager DISPATCH overhead on the host (the
reference's 'hard part #1', SURVEY §7) and end-to-end device time).

Method: for each op, N dispatches are issued back-to-back and the chain is
synced once at the end (e2e/iter); dispatch overhead is the host time of
the issuing loop alone. Prints a table and optionally JSON.

Usage: python benchmark/opperf.py [--ops add,matmul,...] [--json out.json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp


def _default_ops(mx, shape):
    np, npx = mx.np, mx.npx
    a = np.random.uniform(size=shape)
    b = np.random.uniform(size=shape)
    m = np.random.uniform(size=(shape[0], shape[0]))
    idx = np.array(onp.random.randint(0, shape[0], (64,)), dtype="int32")
    ops = {
        # elementwise arithmetic
        "add": lambda: a + b, "subtract": lambda: a - b,
        "multiply": lambda: a * b, "true_divide": lambda: a / b,
        "negative": lambda: -a, "power": lambda: a ** 2,
        "maximum": lambda: np.maximum(a, b),
        "minimum": lambda: np.minimum(a, b),
        "where": lambda: np.where(a > b, a, b),
        "clip": lambda: np.clip(a, 0.2, 0.8),
        # unary math
        "exp": lambda: np.exp(a), "log": lambda: np.log(a + 1),
        "sqrt": lambda: np.sqrt(a), "square": lambda: np.square(a),
        "abs": lambda: np.abs(a), "sign": lambda: np.sign(a),
        "tanh": lambda: np.tanh(a), "erf": lambda: npx.erf(a),
        "sigmoid": lambda: npx.sigmoid(a), "relu": lambda: npx.relu(a),
        "gelu": lambda: npx.leaky_relu(a, act_type="gelu"),
        # reductions
        "sum": lambda: np.sum(a), "mean": lambda: np.mean(a),
        "max": lambda: np.max(a), "min": lambda: np.min(a),
        "var": lambda: np.var(a), "argmax": lambda: np.argmax(a),
        "norm": lambda: np.linalg.norm(a),
        "softmax": lambda: npx.softmax(a),
        "log_softmax": lambda: npx.log_softmax(a),
        # linear algebra / MXU
        "matmul": lambda: np.matmul(m, m),
        "dot": lambda: np.dot(m, m),
        "einsum": lambda: np.einsum("ij,jk->ik", m, m),
        "tensordot": lambda: np.tensordot(m, m, axes=1),
        # shape / data movement
        "reshape": lambda: a.reshape(-1),
        "transpose": lambda: np.transpose(a),
        "concatenate": lambda: np.concatenate([a, b], axis=0),
        "stack": lambda: np.stack([a, b]),
        "split": lambda: np.split(a, 2, axis=0),
        "expand_dims": lambda: np.expand_dims(a, 0),
        "squeeze": lambda: np.squeeze(np.expand_dims(a, 0), 0),
        "broadcast_to": lambda: np.broadcast_to(a[:1], shape),
        "tile": lambda: np.tile(a[:8], (2, 1)),
        "take": lambda: np.take(a, idx, axis=0),
        "gather(embedding)": lambda: npx.embedding(
            idx, m, input_dim=m.shape[0], output_dim=m.shape[1]),
        "one_hot": lambda: npx.one_hot(idx, 64),
        "arange": lambda: np.arange(shape[0]),
        "zeros": lambda: np.zeros(shape),
        "cumsum": lambda: np.cumsum(a, axis=0),
        "sort": lambda: np.sort(a, axis=-1),
        "topk": lambda: npx.topk(a, k=4),
        "batch_norm-like": lambda: (a - np.mean(a)) / np.sqrt(np.var(a) + 1e-5),
        "layer_norm": lambda: npx.layer_norm(
            a, np.ones((shape[-1],)), np.zeros((shape[-1],)), axis=-1),
    }
    return ops


def _full_surface_ops(mx):
    """Every op in the locked REF_NP/REF_NPX/REF_RANDOM/REF_LINALG tables
    (reference: benchmark/opperf runs every registered op, opperf.py:56).

    np-surface argument specs are borrowed from the numeric sweep
    (tests/test_numpy_op_sweep.ALL_FORWARD) so each op gets valid inputs;
    npx/linalg/random get spec tables here. Shapes are small, so e2e ~
    dispatch for most rows — which is the eager-path number the TPU design
    cares about (SURVEY §7 hard part #1); the hand-tuned larger-shape
    table covers the device-time hot set.
    """
    import importlib.util

    np, npx = mx.np, mx.npx
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    tests = os.path.join(here, "tests")
    sys.path.insert(0, tests)
    try:
        spec = importlib.util.spec_from_file_location(
            "op_sweep_cases", os.path.join(tests, "test_numpy_op_sweep.py"))
        sweep = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(sweep)
    finally:
        sys.path.remove(tests)

    ops = {}
    for name, cases in sorted(sweep.ALL_FORWARD.items()):
        args, kwargs = cases[0]
        mx_args = [sweep._to_mx(a) for a in args]
        fn = getattr(np, name, None)
        if fn is None:
            continue
        ops[f"np.{name}"] = (lambda f=fn, a=mx_args, k=kwargs: f(*a, **k))

    # npx layer/tensor op specs (REF_NPX minus control flow, which is not
    # a timed primitive)
    x = np.random.uniform(size=(8, 16))
    img = np.random.uniform(size=(2, 3, 16, 16))
    w = np.random.uniform(size=(8, 3, 3, 3))
    fc_w = np.random.uniform(size=(4, 16))
    idx = np.array(onp.random.randint(0, 8, (8,)), dtype="int32")
    gamma, beta = np.ones((16,)), np.zeros((16,))
    rnn_x = np.random.uniform(size=(4, 2, 8))
    rnn_p = np.random.uniform(size=(2 * (4 * 8 * (8 + 8 + 2)) // 2,))
    state = np.zeros((1, 2, 8))
    npx_specs = {
        "activation": lambda: npx.activation(x, act_type="relu"),
        "arange_like": lambda: npx.arange_like(x, axis=0),
        "batch_dot": lambda: npx.batch_dot(img.reshape(2, 3, 256),
                                           img.reshape(2, 256, 3)),
        "batch_norm": lambda: npx.batch_norm(
            img, np.ones((3,)), np.zeros((3,)), np.zeros((3,)),
            np.ones((3,))),
        "broadcast_like": lambda: npx.broadcast_like(x[:1], x),
        "convolution": lambda: npx.convolution(
            img, w, kernel=(3, 3), num_filter=8),
        "deconvolution": lambda: npx.deconvolution(
            img, np.random.uniform(size=(3, 8, 3, 3)), kernel=(3, 3),
            num_filter=8),
        "dropout": lambda: npx.dropout(x, p=0.5),
        "embedding": lambda: npx.embedding(idx, fc_w, input_dim=4,
                                           output_dim=16),
        "fully_connected": lambda: npx.fully_connected(
            x, fc_w, num_hidden=4, no_bias=True),
        "group_norm": lambda: npx.group_norm(
            img, np.ones((3,)), np.zeros((3,)), num_groups=3),
        "layer_norm": lambda: npx.layer_norm(x, gamma, beta, axis=-1),
        "leaky_relu": lambda: npx.leaky_relu(x, act_type="leaky"),
        "log_softmax": lambda: npx.log_softmax(x),
        "masked_log_softmax": lambda: npx.masked_log_softmax(
            x, np.ones(x.shape, dtype="bool")),
        "masked_softmax": lambda: npx.masked_softmax(
            x, np.ones(x.shape, dtype="bool")),
        "one_hot": lambda: npx.one_hot(idx, 8),
        "pick": lambda: npx.pick(x, np.array(onp.zeros((8,)), dtype="int32"),
                                 axis=-1),
        "pooling": lambda: npx.pooling(img, kernel=(2, 2), stride=(2, 2)),
        "rnn": lambda: npx.rnn(rnn_x, rnn_p, state, state_size=8,
                               num_layers=1, mode="rnn_tanh"),
        "softmax": lambda: npx.softmax(x),
        "topk": lambda: npx.topk(x, k=4),
        "reshape": lambda: npx.reshape(x, (-1,)),
        "constraint_check": lambda: npx.constraint_check(x > -100),
        "nonzero": lambda: npx.nonzero(x),
        "gamma": lambda: npx.gamma(x + 1.0),
        "sequence_mask": lambda: npx.sequence_mask(
            rnn_x, np.array([2.0, 3.0]), use_sequence_length=True),
    }
    for name, fn in npx_specs.items():
        ops[f"npx.{name}"] = fn

    m = np.random.uniform(size=(16, 16))
    spd = np.matmul(m, np.transpose(m)) + 16 * np.eye(16)
    linalg_specs = {
        "cholesky": lambda: np.linalg.cholesky(spd),
        "det": lambda: np.linalg.det(m),
        "eig": lambda: np.linalg.eig(m),
        "eigh": lambda: np.linalg.eigh(spd),
        "eigvals": lambda: np.linalg.eigvals(m),
        "eigvalsh": lambda: np.linalg.eigvalsh(spd),
        "inv": lambda: np.linalg.inv(spd),
        "lstsq": lambda: np.linalg.lstsq(m, m[:, 0], rcond=None),
        "matrix_power": lambda: np.linalg.matrix_power(m, 3),
        "matrix_rank": lambda: np.linalg.matrix_rank(m),
        "multi_dot": lambda: np.linalg.multi_dot([m, m, m]),
        "norm": lambda: np.linalg.norm(m),
        "pinv": lambda: np.linalg.pinv(m),
        "qr": lambda: np.linalg.qr(m),
        "slogdet": lambda: np.linalg.slogdet(spd),
        "solve": lambda: np.linalg.solve(spd, m[:, 0]),
        "svd": lambda: np.linalg.svd(m),
        "tensorinv": lambda: np.linalg.tensorinv(
            (np.random.uniform(size=(4, 4)) + 4 * np.eye(4)).reshape(
                2, 2, 2, 2), ind=2),
        "tensorsolve": lambda: np.linalg.tensorsolve(
            np.random.uniform(size=(2, 2, 2, 2)) + np.eye(4).reshape(
                2, 2, 2, 2) * 4, np.random.uniform(size=(2, 2))),
    }
    for name, fn in linalg_specs.items():
        ops[f"linalg.{name}"] = fn

    rnd = np.random
    random_specs = {
        "beta": lambda: rnd.beta(2.0, 3.0, size=(8, 8)),
        "chisquare": lambda: rnd.chisquare(3.0, size=(8, 8)),
        "choice": lambda: rnd.choice(8, size=(8,)),
        "exponential": lambda: rnd.exponential(1.0, size=(8, 8)),
        "f": lambda: rnd.f(3.0, 4.0, size=(8, 8)),
        "gamma": lambda: rnd.gamma(2.0, 1.0, size=(8, 8)),
        "gumbel": lambda: rnd.gumbel(0.0, 1.0, size=(8, 8)),
        "logistic": lambda: rnd.logistic(0.0, 1.0, size=(8, 8)),
        "lognormal": lambda: rnd.lognormal(0.0, 1.0, size=(8, 8)),
        "multinomial": lambda: rnd.multinomial(
            8, [0.25, 0.25, 0.5], size=(4,)),
        "multivariate_normal": lambda: rnd.multivariate_normal(
            np.zeros((2,)), np.eye(2), size=(8,)),
        "normal": lambda: rnd.normal(0.0, 1.0, size=(8, 8)),
        "pareto": lambda: rnd.pareto(2.0, size=(8, 8)),
        "power": lambda: rnd.power(2.0, size=(8, 8)),
        "randint": lambda: rnd.randint(0, 8, size=(8, 8)),
        "rayleigh": lambda: rnd.rayleigh(1.0, size=(8, 8)),
        "shuffle": lambda: rnd.shuffle(np.arange(8)),
        "uniform": lambda: rnd.uniform(0.0, 1.0, size=(8, 8)),
        "weibull": lambda: rnd.weibull(2.0, size=(8, 8)),
        "rand": lambda: rnd.rand(8, 8),
    }
    for name, fn in random_specs.items():
        ops[f"random.{name}"] = fn
    return ops


def run(ops=None, warmup=5, iters=100, shape=(128, 128), full=False):
    import mxnet_tpu as mx
    table = _default_ops(mx, shape)
    if full:
        table.update(_full_surface_ops(mx))
    if ops:
        table = {k: v for k, v in table.items() if k in ops}
    rows = []
    for name, fn in table.items():
        out = None
        try:
            for _ in range(warmup):
                out = fn()
            mx.nd.waitall()
            t0 = time.perf_counter()
            for _ in range(iters):
                out = fn()
            t_dispatch = time.perf_counter() - t0
            mx.nd.waitall()
            t_e2e = time.perf_counter() - t0
            rows.append({"op": name,
                         "dispatch_us": round(t_dispatch / iters * 1e6, 2),
                         "e2e_us": round(t_e2e / iters * 1e6, 2)})
        except Exception as e:
            rows.append({"op": name, "error": repr(e)[:120]})
        del out
    return rows


def main():
    p = argparse.ArgumentParser()
    p.add_argument("--ops", default=None,
                   help="comma-separated subset (default: all)")
    p.add_argument("--iters", type=int, default=100)
    p.add_argument("--shape", default="128,128")
    p.add_argument("--json", default=None, help="also write JSON here")
    p.add_argument("--full", action="store_true",
                   help="every op in the locked REF_* surfaces "
                        "(writes OPPERF.json by default)")
    args = p.parse_args()
    shape = tuple(int(s) for s in args.shape.split(","))
    ops = set(args.ops.split(",")) if args.ops else None
    if args.full and args.json is None:
        args.json = os.path.join(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))), "OPPERF.json")
    rows = run(ops=ops, iters=args.iters, shape=shape, full=args.full)
    print(f"{'Op':24s} {'dispatch(us)':>14s} {'e2e(us)':>12s}")
    for r in sorted(rows, key=lambda r: -r.get("e2e_us", 0)):
        if "error" in r:
            print(f"{r['op']:24s}  ERROR {r['error']}")
        else:
            print(f"{r['op']:24.24s} {r['dispatch_us']:14.2f} "
                  f"{r['e2e_us']:12.2f}")
    if args.json:
        with open(args.json, "w") as f:
            json.dump(rows, f, indent=1)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""mx.stream input-plane benchmark + host-loss drill (CI `stream` stage).

Two contracts from docs/FAULT_TOLERANCE.md "Streaming data plane":

1. THE STREAM KEEPS THE DEVICE FED: a streaming DataLoader (thread
   workers decoding checksummed shard records) feeding a jitted step
   through ``DevicePrefetcher`` must keep the measured
   ``pipeline.input_stall_seconds`` total well below the serial
   producer wait (all decodes back to back) — the overlap actually
   happened.  The measured epoch must trigger zero RecompileWarnings
   and leave the ``sync_guard`` per-site counts unchanged: streaming
   adds no hidden host syncs and no shape churn.

2. HOST LOSS IS EXACTLY-ONCE: the 2-process drill
   (tests/stream_worker.py) kills one host mid-epoch; the survivor
   adopts its unfinished shards from the last published cursor.  The
   union of the durable served-record logs must be the epoch with
   multiplicity 1.

The ``STREAM_DRILL_OK`` sentinel (what ci/run.sh greps) prints only
when EVERY gate above holds, so a failed stall/recompile/sync gate
fails the stage even though the pipeline exit status is grep's.

Usage: python benchmark/stream_input.py [--stall-ratio 0.5] [--json]
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import subprocess
import sys
import tempfile
import time
import warnings

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DECODE_MS = 1.0      # per-record decode cost (sleep = GIL released)
HOST_MS = 2.0        # host-side per-step work the prefetch overlaps
BATCH = 8
N_RECORDS = 256      # 32 full batches
N_SHARDS = 8
WORKERS = 4


def _build_shards(d, n=N_RECORDS, shards=N_SHARDS, dim=64):
    import numpy as onp
    from mxnet_tpu import stream
    rs = onp.random.RandomState(0)
    with stream.ShardWriter(d, shards) as w:
        for _ in range(n):
            w.append(stream.pack_sample(
                rs.standard_normal((dim, dim)).astype(onp.float32)))
    return d


def _step_fn():
    import jax
    import jax.numpy as jnp

    @jax.jit
    def step(x):
        y = jnp.tanh(x @ x.transpose(0, 2, 1))
        return jnp.sum(y) / x.size
    return step


def _decode(payload):
    from mxnet_tpu import stream
    time.sleep(DECODE_MS / 1000.0)     # the IO/decode cost under test
    return stream.unpack_sample(payload)


def _run_epoch(data, step):
    """One streamed epoch: thread workers decode, DevicePrefetcher
    overlaps H2D with compute.  Returns (stall_total_s, n_steps)."""
    from mxnet_tpu import pipeline, stream, telemetry
    from mxnet_tpu.gluon.data import DataLoader
    ds = stream.StreamDataset(data, transform=_decode)
    samp = stream.StreamSampler(data, batch_size=BATCH, seed=3)
    loader = DataLoader(ds, batch_sampler=samp, num_workers=WORKERS,
                        thread_pool=True, prefetch=2 * WORKERS)
    acc = []
    n = 0
    pf = pipeline.DevicePrefetcher(iter(loader), depth=2)
    for x in pf:
        acc.append(step(getattr(x, "_data", x)))
        n += 1
        time.sleep(HOST_MS / 1000.0)   # host-side step overhead
    for a in acc:
        a.block_until_ready()          # syncs paid once, at the end
    snap = telemetry.snapshot()
    stall = snap["histograms"].get("pipeline.input_stall_seconds", {})
    return stall.get("sum", float("inf")), n


def _host_loss_drill():
    """The 2-process kill-one-host drill; returns (ok, detail)."""
    from mxnet_tpu import stream
    import numpy as onp
    root = tempfile.mkdtemp(prefix="stream_drill_")
    data = os.path.join(root, "data")
    n = 96
    with stream.ShardWriter(data, 8) as w:
        for g in range(n):
            w.append(stream.pack_sample(
                onp.full((2,), g, dtype=onp.float32), onp.int32(0)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    worker = os.path.join(REPO, "tests", "stream_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, root, str(rank), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for rank in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    if procs[0].returncode != 0 or "STREAM_DRILL_DONE" not in outs[0]:
        return False, f"survivor failed: {outs[0]!r}"
    served = []
    for path in glob.glob(os.path.join(root, "served-*.jsonl")):
        with open(path) as f:
            for line in f:
                served.extend(json.loads(line))
    if sorted(served) != list(range(n)):
        return False, (f"multiset broke: {len(served)} served, "
                       f"{len(set(served))} unique of {n}")
    return True, f"{n} records exactly once across host loss"


def run(stall_ratio=0.5, json_out=False):
    from mxnet_tpu import pipeline, telemetry

    with tempfile.TemporaryDirectory() as d:
        data = _build_shards(d)
        step = _step_fn()
        telemetry.enable()
        telemetry.reset()
        _run_epoch(data, step)                   # warmup: compile + pools
        telemetry.reset()
        sites_before = dict(pipeline.sync_site_counts())
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            stall_total, steps = _run_epoch(data, step)
        sites_after = dict(pipeline.sync_site_counts())
        telemetry.disable()
    recompiles = [w for w in caught
                  if issubclass(w.category, telemetry.RecompileWarning)]
    serial_wait = N_RECORDS * DECODE_MS / 1000.0
    sync_same = sites_before == sites_after
    stall_ok = stall_total < stall_ratio * serial_wait
    drill_ok, drill_detail = _host_loss_drill()

    result = {
        "steps": steps,
        "input_stall_s": round(stall_total, 4),
        "serial_producer_wait_s": round(serial_wait, 4),
        "stall_ratio_limit": stall_ratio,
        "recompile_warnings": len(recompiles),
        "sync_sites_unchanged": sync_same,
        "host_loss_drill": drill_detail,
        "ok": bool(stall_ok and not recompiles and sync_same and drill_ok),
    }
    if json_out:
        print(json.dumps(result, indent=2))
    else:
        print(f"streamed {steps} batches; input stall "
              f"{stall_total * 1000:.1f} ms (serial producer wait "
              f"{serial_wait * 1000:.0f} ms, limit "
              f"{stall_ratio:.0%} of it)")
        print(f"recompile warnings: {len(recompiles)}   "
              f"sync_guard sites unchanged: {sync_same}")
        print(f"host-loss drill: {drill_detail}")
        print("PASS" if result["ok"] else "FAIL")
    if result["ok"]:
        print("STREAM_DRILL_OK")
    return result


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--stall-ratio", type=float, default=0.5,
                    help="max input stall as a fraction of the serial "
                         "producer wait")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args()
    result = run(stall_ratio=args.stall_ratio, json_out=args.json)
    sys.exit(0 if result["ok"] else 1)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""fp8 training + compressed-collective benchmark gate (CI `fp8` stage).

Contract from ISSUE 20 / docs/PRECISION.md, on a >=4-way dp mesh:

1. Loss-curve parity: a GPT-class step trained with ``precision="fp8"``
   (e4m3 fwd / e5m2 bwd, delayed scaling) plus int8 error-feedback
   gradient compression must track the fp32 reference loss curve within
   ``--parity-tol`` relative after ``--steps`` identical batches.
2. dp wire-byte cut: the ``mesh.collective_bytes_total{axis="dp"}``
   counter (wire bytes at the compressed width) must be at least
   ``--byte-cut``x below ``mesh.dp_gradient_bytes_total`` (the
   uncompressed fp32 payload).  int8 gives ~4x, so the 2x bar has slack
   for per-bucket scale overhead.
3. Zero post-warmup recompiles: the overlapped fp8+compressed step must
   stay ONE executable after its first call (delayed scaling keeps every
   scale a traced scalar — nothing retriggers tracing).
4. Checkpoint round-trip: amax histories + EF residuals survive
   save_states/load_states bitwise (the dp-resize elastic test lives in
   tests/test_fp8.py; this gate covers the same-layout path end-to-end).
5. MFU floor (``--mfu``, default 0.45): asserted only on accelerators —
   the CPU emulation backend has no meaningful MXU peak, so CI prints
   the measured value and skips the floor there.

Usage: python benchmark/fp8_train.py [--dp 4] [--steps 6]
           [--parity-tol 0.05] [--byte-cut 2.0] [--mfu 0.45] [--json]
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

VOCAB = 1000
UNITS = 64
LAYERS = 2
HEADS = 4
SEQ = 32
BATCH = 8


def _make_step(precision, compress, dp):
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM
    from mxnet_tpu.parallel import MeshConfig, ShardedTrainStep

    mx.random.seed(7)
    net = GPTForCausalLM(vocab_size=VOCAB, units=UNITS,
                         hidden_size=UNITS * 4, num_layers=LAYERS,
                         num_heads=HEADS, max_length=SEQ,
                         dropout=0.0, embed_dropout=0.0)
    net.initialize()
    net(mx.np.zeros((2, SEQ), dtype="int32"))

    def loss_fn(logits, labels):
        from mxnet_tpu.ops.xent import sparse_softmax_xent
        return jnp.mean(sparse_softmax_xent(logits, labels))

    cfg = MeshConfig(dp=dp)
    step = ShardedTrainStep(
        net, loss_fn, mx.optimizer.create("adam", learning_rate=1e-3),
        cfg, batch_specs=cfg.batch_specs(2, 2), n_labels=1,
        precision=precision, grad_compress=compress)
    n_params = sum(int(v.size) for v in step.trainable.values())
    return step, n_params


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--dp", type=int, default=4)
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--parity-tol", type=float, default=0.05,
                    help="max relative loss delta vs the fp32 reference")
    ap.add_argument("--byte-cut", type=float, default=2.0,
                    help="minimum dp wire-byte reduction factor")
    ap.add_argument("--mfu", type=float, default=0.45,
                    help="MFU floor (asserted on accelerators only)")
    ap.add_argument("--json", action="store_true")
    args = ap.parse_args(argv)

    import numpy as onp
    import jax
    from mxnet_tpu import telemetry

    if len(jax.devices()) < args.dp:
        print(f"SKIP: needs {args.dp} devices, have {len(jax.devices())}")
        return 0
    on_cpu = jax.devices()[0].platform == "cpu"

    rs = onp.random.RandomState(0)
    x = rs.randint(0, VOCAB, (BATCH, SEQ)).astype("int32")
    y = rs.randint(0, VOCAB, (BATCH, SEQ)).astype("int32")

    step8, n_params = _make_step("fp8", "int8", args.dp)
    stepref, _ = _make_step("fp32", "none", args.dp)

    # -- 1. loss-curve parity over identical batches --------------------
    l8 = lref = None
    for _ in range(args.steps):
        l8 = step8(x, y)
        lref = stepref(x, y)
    l8, lref = float(l8.asnumpy()), float(lref.asnumpy())
    parity = abs(l8 - lref) / max(abs(lref), 1e-8)

    # -- 2+5. wire bytes + throughput on the fp8 step -------------------
    telemetry.enable()
    telemetry.reset()
    compiles_before = telemetry.counters(
        prefix="compile.", aggregate=True)
    k = max(3, args.steps)
    t0 = time.perf_counter()
    for _ in range(k):
        loss = step8(x, y)
    float(loss.asnumpy())
    sec = (time.perf_counter() - t0) / k
    counters = telemetry.counters()
    compiles_after = telemetry.counters(prefix="compile.", aggregate=True)
    telemetry.disable()

    dp_wire = counters.get('mesh.collective_bytes_total{axis="dp"}', 0) / k
    dp_full = counters.get("mesh.dp_gradient_bytes_total", 0) / k
    cut = dp_full / dp_wire if dp_wire else 0.0

    # -- 3. zero post-warmup recompiles ----------------------------------
    recompiles = sum(compiles_after.values()) - sum(compiles_before.values())

    # -- 4. checkpoint round-trip (same layout) ---------------------------
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "fp8.safetensors")
        step8.save_states(path)
        before = {
            f"fp8/{s}/{kk}": onp.asarray(v)
            for s, h in step8.extra["fp8"].items() for kk, v in h.items()}
        before.update({f"efresid/{n}": onp.asarray(v).sum(axis=0)
                       for n, v in step8.extra["resid"].items()})
        step8.load_states(path)
        after = {
            f"fp8/{s}/{kk}": onp.asarray(v)
            for s, h in step8.extra["fp8"].items() for kk, v in h.items()}
        after.update({f"efresid/{n}": onp.asarray(v).sum(axis=0)
                      for n, v in step8.extra["resid"].items()})
        ckpt_ok = all(onp.array_equal(before[kk], after[kk]) for kk in before)

    flops = 6.0 * n_params * BATCH * SEQ
    peak = None
    mfu = None
    if not on_cpu:
        sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
        from bench import _chip_peak   # noqa: E402
        peak = _chip_peak(jax.devices()[0])
        if peak:
            mfu = flops / sec / peak

    report = {
        "dp": args.dp,
        "loss_fp8": round(l8, 6),
        "loss_ref": round(lref, 6),
        "parity_delta": round(parity, 6),
        "parity_tol": args.parity_tol,
        "dp_wire_bytes_per_step": int(dp_wire),
        "dp_uncompressed_bytes_per_step": int(dp_full),
        "dp_byte_cut": round(cut, 2),
        "required_byte_cut": args.byte_cut,
        "post_warmup_recompiles": int(recompiles),
        "checkpoint_roundtrip_bitwise": bool(ckpt_ok),
        "sec_per_step": round(sec, 6),
        "mfu": round(mfu, 4) if mfu is not None else None,
        "mfu_floor": args.mfu if not on_cpu else None,
    }
    if args.json:
        print(json.dumps(report, indent=2))
    else:
        print(f"dp={args.dp}  fp8 loss {l8:.5f} vs fp32 {lref:.5f} "
              f"(delta {parity:.2%}, tol {args.parity_tol:.0%})")
        print(f"dp bytes/step: wire {int(dp_wire):,} vs uncompressed "
              f"{int(dp_full):,} ({cut:.1f}x cut, bar {args.byte_cut}x)")
        print(f"post-warmup recompiles: {int(recompiles)}  "
              f"checkpoint bitwise: {ckpt_ok}")
        print("mfu: " + (f"{mfu:.3f} (floor {args.mfu})"
                         if mfu is not None else "n/a on this backend"))

    fail = []
    if parity > args.parity_tol:
        fail.append(f"parity delta {parity:.2%} > tol "
                    f"{args.parity_tol:.0%}")
    if cut < args.byte_cut:
        fail.append(f"dp byte cut {cut:.2f}x < required {args.byte_cut}x")
    if recompiles > 0:
        fail.append(f"{int(recompiles)} post-warmup recompiles")
    if not ckpt_ok:
        fail.append("fp8/EF checkpoint round-trip not bitwise")
    if mfu is not None and mfu < args.mfu:
        fail.append(f"MFU {mfu:.3f} < floor {args.mfu}")
    if fail:
        for f in fail:
            print(f"FAIL: {f}")
        return 1
    print("OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())

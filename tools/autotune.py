"""Autotune CLI: measured config search for the compiled step.

Runs ``mx.autotune.search`` on a synthetic workload and prints ONE JSON
summary line on stdout (diagnostics go to stderr).  Winners persist to
the autotune cache (``--cache-dir`` / ``MXNET_AUTOTUNE_CACHE`` /
next to ``MXNET_COMPILE_CACHE``): the second run with the same model
reloads the winner by fingerprint and executes zero trials.

Usage:
    # CPU-CI end-to-end: search, assert the acceptance bars
    JAX_PLATFORMS=cpu python tools/autotune.py --model mlp --assert

    # chaos: inject a device-OOM into trial 2; the search must survive
    JAX_PLATFORMS=cpu python tools/autotune.py --model mlp \
        --inject-oom-at 2 --assert

    # second run against the same cache: zero trials re-executed
    JAX_PLATFORMS=cpu python tools/autotune.py --model mlp \
        --cache-dir /tmp/tune --expect-reused

    # kernel-level search: tuned Pallas block shapes (flash attention,
    # int8/fp8 matmul, ln_residual) into the same winners.json
    JAX_PLATFORMS=cpu python tools/autotune.py --kernels --assert
    JAX_PLATFORMS=cpu python tools/autotune.py --kernels \
        --cache-dir /tmp/tune --expect-reused

``--assert`` enforces: >=50% of the grid pruned without compiling, the
winner's measured items/s >= the untuned default, zero RecompileWarnings
after the search, and (with --inject-oom-at) the OOM trial recorded.
With ``--kernels`` it enforces: a winner per searched bucket, zero
RecompileWarnings after the search, and the measured-trial cap
(autotune.kernel_trial_fraction) respected.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import warnings

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(name, seed=0):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn

    mx.random.seed(seed)
    if name == "mlp":
        net = nn.Sequential()
        net.add(nn.Dense(64, activation="relu"),
                nn.Dense(64, activation="relu"), nn.Dense(10))
        net.initialize()
        net(mx.np.zeros((2, 32)))
        feature_shape, n_classes = (32,), 10
    elif name == "tiny_gpt":
        from mxnet_tpu.gluon.model_zoo import gpt
        net = gpt.GPTForCausalLM(vocab_size=256, units=32, hidden_size=128,
                                 num_layers=2, num_heads=4, max_length=64,
                                 dropout=0.0, embed_dropout=0.0)
        net.initialize()
        net(mx.np.zeros((2, 8), dtype="int32"))
        feature_shape, n_classes = None, 256
    else:
        raise SystemExit(f"unknown model {name}")
    return net, feature_shape, n_classes


def make_batch(model, feature_shape, n_classes, batch, seq, seed=0):
    import numpy as onp
    rng = onp.random.RandomState(seed)
    if model == "mlp":
        x = rng.randn(batch, *feature_shape).astype("float32")
        y = rng.randint(0, n_classes, size=(batch,)).astype("int32")
    else:  # tiny_gpt: next-token LM on random ids
        x = rng.randint(1, n_classes, size=(batch, seq)).astype("int32")
        y = onp.roll(x, -1, axis=1).astype("int32")
    return x, y


def run_kernels(args):
    """The --kernels path: block-shape search, one JSON line, same
    acceptance discipline as the step search."""
    from mxnet_tpu import autotune, telemetry

    kernels = tuple(args.kernel) if args.kernel else None
    print(f"# autotune --kernels: {kernels or autotune.KERNELS} "
          f"cache={autotune.winners_path()}", file=sys.stderr, flush=True)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always", telemetry.RecompileWarning)
        result = autotune.search_kernels(
            kernels=kernels, force=args.force,
            trial_seconds=args.trial_seconds)
        post_warnings = [w for w in caught
                         if issubclass(w.category, telemetry.RecompileWarning)]

    summary = result.summary()
    summary["post_search_recompile_warnings"] = len(post_warnings)
    line = json.dumps(summary)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line, flush=True)

    failures = []
    if args.expect_reused:
        if result.n_trials or result.cache_hits != len(result.searches):
            failures.append("expected every bucket answered from the "
                            "cache with zero trials")
    if args.check:
        if post_warnings:
            failures.append(
                f"{len(post_warnings)} RecompileWarning(s) escaped the "
                "trial scope")
        missing = [s["key"] for s in result.searches if not s.get("blocks")]
        if missing:
            failures.append(f"no winner for {missing}")
        if args.inject_oom_at:
            oom = sum(1 for t in result.trials if t["status"] == "oom")
            if oom < 1:
                failures.append("injected OOM trial not recorded")
    for f in failures:
        print(f"ASSERT FAILED: {f}", file=sys.stderr)
    return 1 if failures else 0


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="mlp", choices=["mlp", "tiny_gpt"])
    p.add_argument("--batch", type=int, nargs="+", default=[32],
                   help="batch-size axis (first = untuned default)")
    p.add_argument("--steps-per-call", type=int, nargs="+", default=[1, 2, 4])
    p.add_argument("--grad-accum", type=int, nargs="+", default=[1, 2])
    p.add_argument("--zero", type=int, nargs="+", default=[0, 1, 2])
    p.add_argument("--remat", nargs="+", default=["off", "dots", "full"],
                   help="remat axis: off | dots | full")
    p.add_argument("--seq", type=int, default=16, help="tiny_gpt seq len")
    p.add_argument("--dp", type=int, default=1,
                   help="data-parallel mesh size for the trials")
    p.add_argument("--hbm-budget", type=int, default=None,
                   help="explicit per-device byte budget (default: auto "
                        "from PJRT memory_stats; None on CPU)")
    p.add_argument("--trial-seconds", type=float, default=None)
    p.add_argument("--cache-dir", default=None,
                   help="winners directory (sets autotune.cache_dir)")
    p.add_argument("--force", action="store_true",
                   help="ignore a cached winner; re-run the trials")
    p.add_argument("--inject-oom-at", type=int, default=0, metavar="N",
                   help="arm the autotune.trial_oom fault point for the "
                        "Nth trial (chaos: OOM survival)")
    p.add_argument("--out", default=None,
                   help="also write the JSON summary to this file")
    p.add_argument("--assert", dest="check", action="store_true",
                   help="enforce the acceptance bars (see module doc)")
    p.add_argument("--expect-reused", action="store_true",
                   help="fail unless the winner came from the cache with "
                        "zero trials (second-run check)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--kernels", action="store_true",
                   help="run the kernel-level block-shape search instead "
                        "of the step-config search")
    p.add_argument("--kernel", nargs="+", default=None, metavar="NAME",
                   help="with --kernels: restrict to these kernels "
                        "(default: all)")
    args = p.parse_args(argv)

    import mxnet_tpu as mx
    from mxnet_tpu import autotune, config, fault, telemetry
    from mxnet_tpu.parallel.mesh import make_mesh
    from jax.sharding import PartitionSpec as P
    import jax.numpy as jnp

    if args.cache_dir:
        config.set("autotune.cache_dir", args.cache_dir)
    telemetry.enable()
    if args.inject_oom_at:
        fault.configure(f"autotune.trial_oom:at={args.inject_oom_at},times=1")

    if args.kernels:
        return run_kernels(args)

    net, feature_shape, n_classes = build_model(args.model, args.seed)
    sample = make_batch(args.model, feature_shape, n_classes,
                        args.batch[0], args.seq, args.seed)

    from mxnet_tpu.ops.xent import sparse_softmax_xent

    def loss_fn(out, y):
        return jnp.mean(sparse_softmax_xent(out, y))

    mesh = make_mesh({"dp": args.dp})
    specs = (P("dp"), P("dp"))
    remat_axis = tuple({"off": False, "dots": "dots", "full": True}[r]
                       for r in args.remat)
    space = autotune.SearchSpace(
        batch_size=args.batch, steps_per_call=args.steps_per_call,
        grad_accum=args.grad_accum, zero=args.zero, remat=remat_axis)
    print(f"# autotune: model={args.model} grid={len(space)} dp={args.dp} "
          f"cache={autotune.winners_path()}", file=sys.stderr, flush=True)

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always", telemetry.RecompileWarning)
        result = autotune.search(
            net, loss_fn, "adam", mesh, specs, sample, space=space,
            hbm_budget=(args.hbm_budget if args.hbm_budget is not None
                        else "auto"),
            force=args.force, trial_seconds=args.trial_seconds)
        # post-search production steps: the winner config must run without
        # tripping the recompile detector (trial compiles were scoped)
        post_warnings = [w for w in caught
                         if issubclass(w.category, telemetry.RecompileWarning)]

    summary = result.summary()
    summary["post_search_recompile_warnings"] = len(post_warnings)
    line = json.dumps(summary)
    if args.out:
        with open(args.out, "w") as f:
            f.write(line + "\n")
    print(line, flush=True)

    failures = []
    if args.expect_reused:
        if not result.reused or result.trials:
            failures.append("expected a cached winner with zero trials")
    if args.check:
        if post_warnings:
            failures.append(
                f"{len(post_warnings)} RecompileWarning(s) escaped the "
                "trial scope")
        if not result.reused:
            if result.pruned_fraction < 0.5:
                failures.append(
                    f"cost model pruned only "
                    f"{result.pruned_fraction:.0%} of the grid (<50%)")
            if result.best is None:
                failures.append("no successful trial")
            elif (result.default is not None
                    and result.default.items_per_s is not None
                    and result.best.items_per_s
                    < result.default.items_per_s):
                failures.append("winner slower than the untuned default")
            if args.inject_oom_at and summary["trials_oom"] < 1:
                failures.append("injected OOM trial not recorded")
    for f in failures:
        print(f"ASSERT FAILED: {f}", file=sys.stderr)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Pack a directory (or a synthetic dataset) into N checksummed recordio
shards + a manifest JSON — the im2rec.py analog for mx.stream.

Every record carries the mx.stream envelope (global record id +
crc32), so a reader validates data integrity per record; --validate
re-reads the finished shard set and verifies every checksum.

Usage:
  # synthetic classification samples (payload = npz of (x, y)):
  python tools/make_shards.py --out DIR --num-shards 4 \
      --synthetic 512 --shape 8,8 --classes 10 --seed 0
  # one record per file of a directory (sorted, recursive):
  python tools/make_shards.py --out DIR --num-shards 4 --src SRCDIR
  # re-read and verify an existing shard set:
  python tools/make_shards.py --validate DIR_or_manifest
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as onp  # noqa: E402

from mxnet_tpu import stream  # noqa: E402


def _iter_src(src):
    """One payload per regular file, path-sorted for determinism."""
    paths = []
    for root, _dirs, files in os.walk(src):
        paths.extend(os.path.join(root, f) for f in files)
    for p in sorted(paths):
        with open(p, "rb") as f:
            yield f.read()


def _iter_synthetic(n, shape, classes, seed):
    rs = onp.random.RandomState(seed)
    for _ in range(int(n)):
        x = rs.standard_normal(shape).astype(onp.float32)
        y = onp.int32(rs.randint(0, classes))
        yield stream.pack_sample(x, y)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="pack records into checksummed mx.stream shards")
    ap.add_argument("--out", help="output directory for shards + manifest")
    ap.add_argument("--num-shards", type=int, default=4)
    ap.add_argument("--prefix", default="shard")
    ap.add_argument("--src", help="pack one record per file of this dir")
    ap.add_argument("--synthetic", type=int,
                    help="pack N synthetic (x, y) samples instead of --src")
    ap.add_argument("--shape", default="8,8",
                    help="synthetic sample shape, comma-separated")
    ap.add_argument("--classes", type=int, default=10)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--validate", nargs="?", const="", metavar="PATH",
                    help="re-read PATH (or --out) and verify every "
                         "record checksum; exits 1 on any corruption")
    args = ap.parse_args(argv)

    target = args.validate if args.validate else None
    if args.validate is not None and not target:
        target = args.out
    wrote = None
    if args.src or args.synthetic is not None:
        if not args.out:
            ap.error("--out is required when packing")
        records = (_iter_src(args.src) if args.src else
                   _iter_synthetic(args.synthetic,
                                   tuple(int(d) for d in
                                         args.shape.split(",")),
                                   args.classes, args.seed))
        with stream.ShardWriter(args.out, args.num_shards,
                                prefix=args.prefix) as w:
            for payload in records:
                w.append(payload)
        wrote = {"manifest": os.path.join(args.out, stream.MANIFEST_NAME),
                 "records": w.total, "shards": w.num_shards}
        print(json.dumps(wrote))
        target = target or (args.out if args.validate is not None else None)
    elif args.validate is None:
        ap.error("nothing to do: pass --src/--synthetic and/or --validate")

    if target:
        report = stream.validate_manifest(target)
        print(json.dumps({k: v for k, v in report.items() if k != "errors"}))
        for err in report["errors"][:20]:
            print(f"CORRUPT: {err}", file=sys.stderr)
        if not report["ok"]:
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

#!/usr/bin/env python
"""Parse training logs into a per-epoch metric table.

Reference parity: tools/parse_log.py (regex over the standard
``Epoch[N] Train-accuracy=...`` / ``Validation-accuracy=...`` /
``Epoch[N] Time cost=...`` lines the fit loops and Speedometer callback
emit; markdown table out).

Usage: python tools/parse_log.py train.log [--metric-names accuracy ...]
       [--format markdown|none]
"""
from __future__ import annotations

import argparse
import re


def parse(lines, metric_names):
    # metric names are escaped and anchored to their own '=' so
    # prefix-named metrics (accuracy vs accuracy_top5) don't contaminate
    # each other and extra 'key=value' text on the line is ignored
    pats = (
        [(f"train-{m}", re.compile(
            r".*Epoch\[(\d+)\] Train-" + re.escape(m) + r"=([.\d]+)"))
         for m in metric_names]
        + [(f"val-{m}", re.compile(
            r".*Epoch\[(\d+)\] Validation-" + re.escape(m) + r"=([.\d]+)"))
           for m in metric_names]
        + [("time", re.compile(r".*Epoch\[(\d+)\] Time[ a-z]*=([.\d]+)"))]
    )
    data = {}
    for line in lines:
        for name, pat in pats:
            m = pat.match(line)
            if m is None:
                continue
            epoch, val = int(m.group(1)), float(m.group(2))
            tot, cnt = data.setdefault(epoch, {}).get(name, (0.0, 0))
            data[epoch][name] = (tot + val, cnt + 1)
            break
    cols = [n for n, _ in pats]
    rows = []
    for epoch in sorted(data):
        row = [epoch]
        for c in cols:
            tot, cnt = data[epoch].get(c, (0.0, 0))
            row.append(tot / cnt if cnt else float("nan"))
        rows.append(row)
    return cols, rows


def main():
    p = argparse.ArgumentParser()
    p.add_argument("logfile")
    p.add_argument("--metric-names", type=str, nargs="+",
                   default=["accuracy"])
    p.add_argument("--format", choices=["markdown", "none"],
                   default="markdown")
    args = p.parse_args()
    with open(args.logfile) as f:
        cols, rows = parse(f.readlines(), args.metric_names)
    if args.format == "markdown":
        print("| epoch | " + " | ".join(cols) + " |")
        print("| --- " * (len(cols) + 1) + "|")
        for row in rows:
            print("| " + " | ".join(
                str(v) if i == 0 else f"{v:.6g}"
                for i, v in enumerate(row)) + " |")
    return rows


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Regenerate the .idx file for an existing RecordIO pack.

Reference parity: tools/rec2idx.py (walks the .rec sequentially, writing
``key\\toffset`` lines so MXIndexedRecordIO can seek). Keys are the
record ordinal, matching im2rec.py's packing order.

Usage: python tools/rec2idx.py data.rec data.idx
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mxnet_tpu.recordio import MXRecordIO  # noqa: E402


def build_index(rec_path, idx_path):
    reader = MXRecordIO(rec_path, "r")
    n = 0
    with open(idx_path, "w") as fidx:
        while True:
            pos = reader.tell()
            if reader.read() is None:
                break
            fidx.write(f"{n}\t{pos}\n")
            n += 1
    reader.close()
    return n


def main():
    p = argparse.ArgumentParser()
    p.add_argument("record", help="path to the .rec file")
    p.add_argument("index", help="path of the .idx file to write")
    args = p.parse_args()
    n = build_index(args.record, args.index)
    print(f"wrote {n} index entries to {args.index}")


if __name__ == "__main__":
    main()

"""TPU-attachment probe with a wedge-proof timeout.

Exit codes (consumed by ci/run.sh tpu stage):
  0 — a TPU backend is attached and responsive
  2 — probe TIMED OUT: a TPU environment exists but jax.devices() wedged
      (the axon tunnel can hang forever) — callers must treat this as a
      hardware FAILURE, not as "no TPU"
  3 — no TPU attached (probe ran, platform is not tpu)
"""
import subprocess
import sys

try:
    r = subprocess.run([sys.executable, "-c",
                        "import jax; print(jax.devices()[0].platform)"],
                       capture_output=True, text=True, timeout=240)
except subprocess.TimeoutExpired:
    sys.exit(2)
sys.exit(0 if (r.returncode == 0 and "tpu" in r.stdout) else 3)

"""Exit 0 iff a TPU backend is attached and responsive (subprocess probe
with a hard timeout — the axon tunnel can wedge jax.devices() forever)."""
import subprocess
import sys

try:
    r = subprocess.run([sys.executable, "-c",
                        "import jax; print(jax.devices()[0].platform)"],
                       capture_output=True, text=True, timeout=240)
except subprocess.TimeoutExpired:
    sys.exit(3)
sys.exit(0 if (r.returncode == 0 and "tpu" in r.stdout) else 3)

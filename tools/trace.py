"""Trace CLI: inspect and validate mx.trace Chrome-trace exports.

Works on the JSON ``mx.trace.export(path)`` writes (and on any
chrome://tracing / Perfetto "JSON trace event" file with complete
``ph: "X"`` events).  Prints ONE JSON summary line on stdout;
diagnostics go to stderr.

Usage:
    # per-name span counts + the tree of the first recorded trace
    python tools/trace.py summary mxtrace.json [--last N]

    # CI: well-formedness + structural assertions (exit 1 on failure)
    python tools/trace.py validate mxtrace.json \
        --expect train.step \
        --expect-child train.step=train.data_wait \
        --expect-child serve.request=serve.decode_step

``validate`` checks every event is a well-formed Chrome trace event
(name/ph/ts/dur/pid/tid), ``--expect NAME`` requires at least one span
with that name, and ``--expect-child PARENT=CHILD`` requires at least
one PARENT span with a CHILD span parented to it (via the
``args.span_id``/``args.parent_id`` links ``mx.trace`` records).
"""
from __future__ import annotations

import argparse
import json
import sys


def fail(msg):
    print(f"trace.py: {msg}", file=sys.stderr)
    raise SystemExit(1)


def load(path):
    """Load + structurally validate one export -> list of events."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError) as e:
        fail(f"{path}: not loadable as JSON ({e})")
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        fail(f"{path}: missing traceEvents (not a Chrome trace export)")
    events = doc["traceEvents"]
    if not isinstance(events, list):
        fail(f"{path}: traceEvents is not a list")
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            fail(f"{path}: traceEvents[{i}] is not an object")
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            fail(f"{path}: traceEvents[{i}] has no name")
        if ev.get("ph") not in ("X", "B", "E", "i", "C", "M"):
            fail(f"{path}: traceEvents[{i}] bad ph {ev.get('ph')!r}")
        for key in ("ts", "pid", "tid"):
            if not isinstance(ev.get(key), (int, float)):
                fail(f"{path}: traceEvents[{i}] missing numeric {key}")
        if ev["ph"] == "X" and not isinstance(ev.get("dur"), (int, float)):
            fail(f"{path}: traceEvents[{i}] complete event without dur")
        if ev["ph"] == "X" and ev["dur"] < 0:
            fail(f"{path}: traceEvents[{i}] negative dur")
    return events


def by_span_id(events):
    return {ev["args"]["span_id"]: ev for ev in events
            if isinstance(ev.get("args"), dict)
            and "span_id" in ev["args"]}


def children_of(events):
    """span_id -> [child events] via args.parent_id links."""
    out = {}
    for ev in events:
        args = ev.get("args")
        if isinstance(args, dict) and args.get("parent_id") is not None:
            out.setdefault(args["parent_id"], []).append(ev)
    return out


def has_parent_child(events, parent_name, child_name):
    kids = children_of(events)
    for ev in events:
        args = ev.get("args")
        if ev.get("name") != parent_name or not isinstance(args, dict):
            continue
        for child in kids.get(args.get("span_id"), ()):
            if child.get("name") == child_name:
                return True
    return False


def render_tree(events, root, kids, depth=0, lines=None):
    lines = [] if lines is None else lines
    lines.append("  " * depth + f"{root['name']} ({root.get('dur', 0)}us)")
    for child in sorted(kids.get(root["args"]["span_id"], ()),
                        key=lambda e: e.get("ts", 0)):
        render_tree(events, child, kids, depth + 1, lines)
    return lines


def summarize(events):
    counts = {}
    for ev in events:
        counts[ev["name"]] = counts.get(ev["name"], 0) + 1
    spans = by_span_id(events)
    roots = [ev for ev in spans.values()
             if ev["args"].get("parent_id") not in spans]
    return counts, roots


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("command", choices=("summary", "validate"))
    ap.add_argument("path")
    ap.add_argument("--last", type=int, default=None,
                    help="only consider the newest N events")
    ap.add_argument("--expect", action="append", default=[],
                    metavar="NAME", help="require >=1 span named NAME")
    ap.add_argument("--expect-child", action="append", default=[],
                    metavar="PARENT=CHILD",
                    help="require a CHILD span parented to a PARENT span")
    args = ap.parse_args(argv)

    events = load(args.path)
    if args.last is not None:
        events = sorted(events, key=lambda e: e.get("ts", 0))[-args.last:]
    counts, roots = summarize(events)

    if args.command == "summary":
        kids = children_of(events)
        roots.sort(key=lambda e: e.get("ts", 0))
        for root in roots[:8]:
            for line in render_tree(events, root, kids):
                print(line, file=sys.stderr)
        print(json.dumps({"events": len(events), "names": counts,
                          "roots": len(roots)}))
        return 0

    for name in args.expect:
        if name not in counts:
            fail(f"expected a span named {name!r}; have {sorted(counts)}")
    for pair in args.expect_child:
        parent, _, child = pair.partition("=")
        if not child:
            fail(f"--expect-child wants PARENT=CHILD, got {pair!r}")
        if not has_parent_child(events, parent, child):
            fail(f"no {child!r} span parented to a {parent!r} span")
    print(json.dumps({"ok": True, "events": len(events),
                      "checked": len(args.expect) + len(args.expect_child)}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

#!/usr/bin/env python
"""Pack an image folder / .lst file into RecordIO (reference:
tools/im2rec.py — list generation + pack modes; this covers the python
single-process path, the common case).

Usage:
    # generate a list file from a folder of class subdirs
    python tools/im2rec.py --make-list prefix image_root
    # pack images from prefix.lst into prefix.rec (+ prefix.idx)
    python tools/im2rec.py prefix image_root [--resize N] [--quality Q]
"""
from __future__ import annotations

import argparse
import os
import random
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def make_list(prefix, root, train_ratio=1.0, shuffle=True, exts=None):
    exts = exts or (".jpg", ".jpeg", ".png", ".bmp", ".npy")
    classes = sorted(d for d in os.listdir(root)
                     if os.path.isdir(os.path.join(root, d)))
    entries = []
    for label, cls in enumerate(classes):
        for dirpath, _, files in os.walk(os.path.join(root, cls)):
            for f in sorted(files):
                if f.lower().endswith(exts):
                    rel = os.path.relpath(os.path.join(dirpath, f), root)
                    entries.append((label, rel))
    if shuffle:
        random.shuffle(entries)
    n_train = int(len(entries) * train_ratio)
    for name, chunk in ((f"{prefix}.lst", entries[:n_train]),
                        (f"{prefix}_val.lst", entries[n_train:])):
        if not chunk and name.endswith("_val.lst"):
            continue
        with open(name, "w") as f:
            for i, (label, rel) in enumerate(chunk):
                f.write(f"{i}\t{label}\t{rel}\n")
    return classes


def pack(prefix, root, resize=0, quality=95, color=1):
    from mxnet_tpu import recordio, image

    record = recordio.MXIndexedRecordIO(f"{prefix}.idx", f"{prefix}.rec", "w")
    n = 0
    with open(f"{prefix}.lst") as f:
        for line in f:
            idx, label, rel = line.strip().split("\t")
            img = image.imread(os.path.join(root, rel), flag=color)
            if resize:
                img = image.resize_short(img, resize)
            header = recordio.IRHeader(0, float(label), int(idx), 0)
            payload = recordio.pack_img(header, img.asnumpy(),
                                        quality=quality)
            record.write_idx(int(idx), payload)
            n += 1
    record.close()
    print(f"packed {n} images into {prefix}.rec")


def main():
    p = argparse.ArgumentParser()
    p.add_argument("prefix")
    p.add_argument("root")
    p.add_argument("--make-list", action="store_true")
    p.add_argument("--train-ratio", type=float, default=1.0)
    p.add_argument("--no-shuffle", action="store_true")
    p.add_argument("--resize", type=int, default=0)
    p.add_argument("--quality", type=int, default=95)
    p.add_argument("--color", type=int, default=1)
    args = p.parse_args()
    if args.make_list:
        classes = make_list(args.prefix, args.root, args.train_ratio,
                            not args.no_shuffle)
        print(f"wrote {args.prefix}.lst ({len(classes)} classes)")
    else:
        if not os.path.exists(f"{args.prefix}.lst"):
            make_list(args.prefix, args.root)
        pack(args.prefix, args.root, args.resize, args.quality, args.color)


if __name__ == "__main__":
    main()

#!/bin/sh
# Regenerate mxnet_tpu/onnx/onnx_mxtpu_pb2.py from the schema.
set -e
cd "$(dirname "$0")/.."
protoc --python_out=mxnet_tpu/onnx -I mxnet_tpu/onnx mxnet_tpu/onnx/onnx_mxtpu.proto
echo "wrote mxnet_tpu/onnx/onnx_mxtpu_pb2.py"

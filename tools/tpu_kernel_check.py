"""Mosaic-compile + numerics check for every Pallas kernel on real TPU.

Round-4 verdict item #1: the fused kernels had only ever run in interpret
mode (the tunnel died before a hardware pass).  This script compiles each
kernel with interpret=False on the attached TPU and checks numerics
against the plain-jnp reference implementation.  Exit code 0 only if all
kernels compile AND match.

Usage:  python tools/tpu_kernel_check.py
"""
from __future__ import annotations

import os
import sys
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def _maxerr(a, b):
    return float(jnp.max(jnp.abs(a.astype(jnp.float32) - b.astype(jnp.float32))))


def _relerr(grads, refs):
    """Max per-tensor relative error: maxerr / (max|ref| per tensor)."""
    rel = []
    for a, b in zip(grads, refs):
        scale = float(jnp.max(jnp.abs(b.astype(jnp.float32)))) + 1e-6
        rel.append(_maxerr(a, b) / scale)
    return max(rel)


def check_flash_attention():
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention
    B, H, S, D = 2, 4, 2048, 64
    ks = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(ks[0], (B, H, S, D), jnp.bfloat16)
    k = jax.random.normal(ks[1], (B, H, S, D), jnp.bfloat16)
    v = jax.random.normal(ks[2], (B, H, S, D), jnp.bfloat16)

    def ref(q, k, v, causal):
        s = jnp.einsum("bhqd,bhkd->bhqk", q.astype(jnp.float32),
                       k.astype(jnp.float32)) / np.sqrt(D)
        if causal:
            m = jnp.tril(jnp.ones((S, S), bool))
            s = jnp.where(m, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32))

    results = {}
    for causal in (False, True):
        out = jax.jit(lambda q, k, v: flash_attention(q, k, v, causal=causal))(q, k, v)
        r = ref(q, k, v, causal)
        err = _maxerr(out, r)
        assert err < 0.05, f"flash fwd causal={causal} maxerr {err}"
        # backward
        def loss(q, k, v):
            return jnp.sum(flash_attention(q, k, v, causal=causal).astype(jnp.float32) ** 2)
        def loss_ref(q, k, v):
            return jnp.sum(ref(q, k, v, causal) ** 2)
        g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2)))(q, k, v)
        rel = _relerr(g, gr)
        assert rel < 0.05, f"flash bwd causal={causal} relerr {rel}"
        results[f"causal={causal}"] = {"fwd_maxerr": err, "bwd_relerr": rel}
    return results


def check_ln_residual():
    from mxnet_tpu.ops.pallas.ln_residual import ln_residual_dropout
    B, S, Dm = 8, 128, 768
    ks = jax.random.split(jax.random.PRNGKey(1), 5)
    x = jax.random.normal(ks[0], (B * S, Dm), jnp.bfloat16)
    h = jax.random.normal(ks[1], (B * S, Dm), jnp.bfloat16)
    gamma = jax.random.normal(ks[2], (Dm,), jnp.float32)
    beta = jax.random.normal(ks[3], (Dm,), jnp.float32)
    mask = (jax.random.uniform(ks[4], (B * S, Dm)) > 0.1)
    p = 0.1

    def ref(x, h, gamma, beta):
        s = x.astype(jnp.float32) + jnp.where(mask, h.astype(jnp.float32) / (1 - p), 0.0)
        mu = jnp.mean(s, -1, keepdims=True)
        var = jnp.mean((s - mu) ** 2, -1, keepdims=True)
        return ((s - mu) * jax.lax.rsqrt(var + 1e-5)) * gamma + beta

    out = jax.jit(lambda *a: ln_residual_dropout(*a, p=p, mask=mask))(x, h, gamma, beta)
    r = ref(x, h, gamma, beta)
    err = _maxerr(out, r)
    assert err < 0.05, f"ln_residual fwd maxerr {err}"

    def loss(x, h, gamma, beta):
        return jnp.sum(ln_residual_dropout(x, h, gamma, beta, p=p, mask=mask).astype(jnp.float32) ** 2)
    def loss_ref(x, h, gamma, beta):
        return jnp.sum(ref(x, h, gamma, beta) ** 2)
    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2, 3)))(x, h, gamma, beta)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2, 3)))(x, h, gamma, beta)
    rel = _relerr(g, gr)
    assert rel < 0.05, f"ln_residual bwd relerr {rel}"
    return {"fwd_maxerr": err, "bwd_relerr_max": rel}


def check_conv_bwd():
    from mxnet_tpu.ops.pallas_conv_bwd import (conv3x3_bn_relu_ref,
                                               fused_cbr_train)
    N, H, W, Cin, Cout = 8, 56, 56, 64, 64
    ks = jax.random.split(jax.random.PRNGKey(2), 4)
    x = jax.random.normal(ks[0], (N, H, W, Cin), jnp.bfloat16)
    w = jax.random.normal(ks[1], (3, 3, Cin, Cout), jnp.bfloat16) * 0.1
    gamma = jnp.abs(jax.random.normal(ks[2], (Cout,), jnp.float32)) + 0.5
    beta = jax.random.normal(ks[3], (Cout,), jnp.float32)

    def loss_fused(x, w, gamma, beta):
        return jnp.sum(fused_cbr_train(x, w, gamma, beta)[0].astype(jnp.float32) ** 2)
    def loss_ref(x, w, gamma, beta):
        return jnp.sum(conv3x3_bn_relu_ref(x, w, gamma, beta)[0].astype(jnp.float32) ** 2)

    g = jax.jit(jax.grad(loss_fused, argnums=(0, 1, 2, 3)))(x, w, gamma, beta)
    gr = jax.jit(jax.grad(loss_ref, argnums=(0, 1, 2, 3)))(x, w, gamma, beta)
    rel = _relerr(g, gr)
    assert rel < 0.06, f"conv_bwd relerr {rel}"
    return {"bwd_relerr_max": rel}


def main():
    dev = jax.devices()[0]
    print(f"device: {dev.platform} {getattr(dev, 'device_kind', '?')}", flush=True)
    if dev.platform != "tpu":
        print("NOT A TPU — this check is meaningless on CPU", flush=True)
        sys.exit(2)
    ok = True
    for name, fn in [("flash_attention", check_flash_attention),
                     ("ln_residual", check_ln_residual),
                     ("conv3x3_bn_relu_bwd", check_conv_bwd)]:
        try:
            res = fn()
            print(f"PASS {name}: {res}", flush=True)
        except Exception:
            ok = False
            print(f"FAIL {name}:", flush=True)
            traceback.print_exc()
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Environment diagnosis for bug reports.

Reference parity: tools/diagnose.py (prints platform/python/pip
versions, MXNet build features, and network reachability for issue
templates). The network checks are dropped (this environment is
zero-egress by design); device and feature discovery are the useful
part on TPU.

Usage: python tools/diagnose.py
"""
import os
import platform
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    print("----------Python Info----------")
    print("Version      :", platform.python_version())
    print("Compiler     :", platform.python_compiler())
    print("Build        :", platform.python_build())
    print("----------Platform Info----------")
    print("Platform     :", platform.platform())
    print("system       :", platform.system())
    print("machine      :", platform.machine())
    print("----------Environment----------")
    for k, v in sorted(os.environ.items()):
        if k.startswith(("MXNET_", "JAX_", "XLA_", "TPU_")):
            print(f"{k}={v}")
    print("----------MXNet-TPU Info----------")
    try:
        import mxnet_tpu as mx
        print("Version      :", getattr(mx, "__version__", "dev"))
        print("Directory    :", os.path.dirname(mx.__file__))
        feats = mx.runtime.feature_list()
        on = [f.name for f in feats if f.enabled]
        print("Features     :", ", ".join(on))
    except Exception as e:  # diagnosis must not crash on a broken install
        print("import failed:", repr(e))
        return
    print("----------Device Info----------")
    try:
        import jax
        for d in jax.devices():
            print(f"{d.id}: platform={d.platform} "
                  f"kind={getattr(d, 'device_kind', '?')}")
        print("default backend:", jax.default_backend())
        print("jax           :", jax.__version__)
    except Exception as e:
        print("device probe failed:", repr(e))


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""Merge and validate mx.goodput fleet ledgers.

Usage:
    python tools/goodput.py summary  <lease_dir>
    python tools/goodput.py validate <lease_dir> [--epsilon 0.05]
                                     [--expect-badput STATE]

``summary`` merges every ``goodput-<rank>.json`` snapshot in the lease
dir into the capacity-weighted fleet device-second waterfall (the same
merge ``GET /goodput`` serves) and prints it as one JSON document.

``validate`` re-checks the conservation oracle on every host ledger
(sum of buckets == elapsed wall clock within ``--epsilon`` seconds,
late-dropped time included) and, with ``--expect-badput``, asserts the
named state is the fleet's top attributed badput bucket — the
postmortem.py-style CI hook the chaos drills call after injecting a
known badput cause.

Diagnostics go to stderr; stdout carries exactly one JSON document.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def fail(msg):
    print(f"goodput: {msg}", file=sys.stderr)
    raise SystemExit(1)


def _load(lease_dir):
    from mxnet_tpu import goodput
    if not os.path.isdir(lease_dir):
        fail(f"{lease_dir!r} is not a directory")
    snaps = goodput.read_snapshots(lease_dir)
    if not snaps:
        fail(f"no {goodput.SNAPSHOT_PREFIX}*.json snapshots in "
             f"{lease_dir!r}")
    return goodput, snaps


def summary(lease_dir):
    goodput, snaps = _load(lease_dir)
    print(json.dumps(goodput.merge_snapshots(snaps)))
    return 0


def validate(lease_dir, epsilon=0.05, expect_badput=None):
    goodput, snaps = _load(lease_dir)
    problems = []
    for rank, payload in sorted(snaps.items()):
        s = payload.get("summary") or {}
        err = float(s.get("conservation_error_s", float("inf")))
        slack = epsilon + float(s.get("late_dropped_s", 0.0))
        if err > slack:
            problems.append(
                f"rank {rank}: conservation violated — "
                f"|elapsed - attributed| = {err:.6f}s > {slack:.6f}s")
    merged = goodput.merge_snapshots(snaps)
    top = [state for state, _sec in merged["badput_top"]]
    if expect_badput and (not top or top[0] != expect_badput):
        problems.append(
            f"expected top badput {expect_badput!r}, ledger attributes "
            f"{top or 'nothing'} (device-seconds: "
            f"{merged['device_seconds']})")
    out = {"ok": not problems, "hosts": merged["hosts"],
           "goodput_fraction": merged["goodput_fraction"],
           "badput_top": merged["badput_top"], "problems": problems}
    print(json.dumps(out))
    if problems:
        for p in problems:
            print(f"goodput: {p}", file=sys.stderr)
        return 1
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="merge/validate mx.goodput fleet ledger snapshots")
    ap.add_argument("command", choices=["summary", "validate"])
    ap.add_argument("path", help="fleet lease dir holding "
                                 "goodput-<rank>.json snapshots")
    ap.add_argument("--epsilon", type=float, default=0.05,
                    help="conservation tolerance in seconds (on top of "
                         "each ledger's accounted late-dropped time)")
    ap.add_argument("--expect-badput", default=None, metavar="STATE",
                    help="validate: require this state to be the "
                         "fleet's top attributed badput bucket")
    args = ap.parse_args(argv)
    if args.command == "summary":
        return summary(args.path)
    return validate(args.path, epsilon=args.epsilon,
                    expect_badput=args.expect_badput)


if __name__ == "__main__":
    raise SystemExit(main())

"""A/B the Pallas fusion levers on real TPU, using bench.py's own rows.

Round-4 verdict items #1/#2: the fused conv3x3+BN+ReLU backward and the
fused dropout+residual+LayerNorm kernels were built as the named levers
for the ResNet/BERT MFU targets but never measured on hardware. This
runs each affected bench row twice — fusion forced on, then off — and
prints a compact JSON comparison.

Usage: python tools/tpu_ab.py [resnet|bert|all]
"""
from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


def run_ab(flag, fn, kwargs, peak):
    from mxnet_tpu import config
    prior = config.get(flag)
    out = {}
    try:
        for mode in ("on", "off"):
            config.set(flag, mode)
            row = fn(on_cpu=False, peak=peak, **kwargs)
            out[mode] = {k: row[k] for k in
                         ("name", "items_per_s", "ms_per_step", "mfu")
                         if k in row}
    finally:
        config.set(flag, prior)
    if "on" in out and "off" in out:
        out["speedup_on_vs_off"] = round(
            out["on"]["items_per_s"] / out["off"]["items_per_s"], 4)
    return out


def main():
    which = sys.argv[1] if len(sys.argv) > 1 else "all"
    import jax
    dev = jax.devices()[0]
    assert dev.platform == "tpu", f"need TPU, got {dev.platform}"
    peak = bench._chip_peak(dev)
    res = {"device": getattr(dev, "device_kind", "?")}
    if which in ("bert", "all"):
        # both workloads: dropout off (XLA's fusion wins) and on (the
        # kernel's case) — the auto gate in transformer.py cites these.
        res["bert_bs32_fused_ln"] = run_ab(
            "fused_ln_residual", bench.bench_bert_train,
            dict(precision="bf16", bs=32), peak)
        res["bert_bs32_dropout0.1_fused_ln"] = run_ab(
            "fused_ln_residual", bench.bench_bert_train,
            dict(precision="bf16", bs=32, dropout=0.1), peak)
    if which in ("resnet", "all"):
        res["resnet50_bs32_fused_conv_bn"] = run_ab(
            "fused_conv_bn", bench.bench_resnet50_train,
            dict(precision="bf16"), peak)
    print(json.dumps(res), flush=True)


if __name__ == "__main__":
    main()

#!/usr/bin/env python
"""mxlint — the mx.analyze static-analysis CLI.

Usage:
    tools/mxlint.py [paths...] [--rule TRC001 --rule REG ...]
                    [--path SUBSTRING] [--baseline ci/lint_baseline.json]
                    [--write-baseline] [--assert-clean] [--json]
                    [--list-rules]

Default paths: the repo's own source roots (mxnet_tpu, tests,
benchmark, tools, example, bench.py).  With ``--baseline`` the listed
pre-existing findings are waived and only NEW findings count;
``--assert-clean`` exits 1 when any new finding remains (the CI gate).
``--write-baseline`` rewrites the baseline from the current findings.

``--json`` follows the bench.py machine-readability contract: the last
line on stdout is the one JSON document; everything human goes to
stderr.

The analyzer is stdlib-only, so this script loads it straight off the
source tree without importing (or paying for) the rest of mxnet_tpu.
"""

import argparse
import importlib.util
import json
import os
import sys

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_analyze():
    pkg_dir = os.path.join(ROOT, "mxnet_tpu", "analyze")
    spec = importlib.util.spec_from_file_location(
        "_mxlint_analyze", os.path.join(pkg_dir, "__init__.py"),
        submodule_search_locations=[pkg_dir])
    mod = importlib.util.module_from_spec(spec)
    sys.modules["_mxlint_analyze"] = mod
    spec.loader.exec_module(mod)
    return mod


def main(argv=None):
    ap = argparse.ArgumentParser(prog="mxlint", description=__doc__)
    ap.add_argument("paths", nargs="*",
                    help="files/dirs to analyze (default: repo roots)")
    ap.add_argument("--rule", action="append", default=[],
                    help="only rules matching this prefix (repeatable, "
                         "e.g. TRC or REG001)")
    ap.add_argument("--path", dest="path_filter", default=None,
                    help="only findings whose path contains this")
    ap.add_argument("--baseline", default=None,
                    help="baseline JSON of waived pre-existing findings")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite --baseline from the current findings")
    ap.add_argument("--assert-clean", action="store_true",
                    help="exit 1 if any new finding remains")
    ap.add_argument("--json", dest="as_json", action="store_true",
                    help="one JSON document on stdout, diagnostics on "
                         "stderr")
    ap.add_argument("--list-rules", action="store_true")
    args = ap.parse_args(argv)

    analyze = _load_analyze()

    if args.list_rules:
        for rule, desc in sorted(analyze.RULES.items()):
            print(f"{rule}  {desc}")
        return 0

    findings = analyze.run_suite(paths=args.paths or None, root=ROOT,
                                 rules=args.rule or None)
    if args.path_filter:
        findings = [f for f in findings if args.path_filter in f.path]

    if args.write_baseline:
        if not args.baseline:
            print("--write-baseline requires --baseline", file=sys.stderr)
            return 2
        analyze.write_baseline(os.path.join(ROOT, args.baseline)
                               if not os.path.isabs(args.baseline)
                               else args.baseline, findings)
        print(f"wrote {len(findings)} findings to {args.baseline}",
              file=sys.stderr)
        return 0

    waived = []
    new = findings
    if args.baseline:
        bp = args.baseline if os.path.isabs(args.baseline) else \
            os.path.join(ROOT, args.baseline)
        if os.path.isfile(bp):
            new, waived = analyze.apply_baseline(
                findings, analyze.load_baseline(bp))
        else:
            print(f"baseline {args.baseline} not found; treating all "
                  "findings as new", file=sys.stderr)

    human = sys.stderr if args.as_json else sys.stdout
    for f in new:
        print(f.render(), file=human)
    counts = {}
    for f in new:
        counts[f.rule] = counts.get(f.rule, 0) + 1
    summary = (f"mxlint: {len(new)} new finding(s), "
               f"{len(waived)} baselined")
    print(summary, file=human if new or waived else human)

    if args.as_json:
        doc = {"new": [f.to_dict() for f in new],
               "baselined": len(waived),
               "rule_counts": counts,
               "total_new": len(new),
               "clean": not new}
        # the contract: last stdout line is the single JSON document
        print(json.dumps(doc))

    if args.assert_clean and new:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())

"""Postmortem CLI: inspect, validate and merge mx.blackbox bundles.

Works on the checksummed ``blackbox-<rank>-<step>.json`` bundles the
flight recorder writes (docs/OBSERVABILITY.md "Postmortem forensics").
Prints ONE JSON summary line on stdout; diagnostics go to stderr.

Usage:
    # per-host digest of every readable bundle in a directory
    python tools/postmortem.py summary /path/to/bundles

    # fleet merge: one causal timeline across hosts (spans interleaved
    # on the shared CLOCK_MONOTONIC base), first-anomaly host flagged
    python tools/postmortem.py merge /path/to/bundles [--out merged.json]

    # CI: integrity + trigger assertion on one bundle (exit 1 on torn)
    python tools/postmortem.py validate bundle.json --expect worker_lost

``validate`` re-verifies the ``.sha256`` sidecar, the JSON, and the
schema tag; ``--expect TRIGGER`` additionally requires ``meta.trigger``
to match.  ``merge`` skips torn bundles (reported on stderr) and keeps
only the newest bundle per rank; the *first-anomaly host* is the rank
whose earliest terminal (non-shadow) bundle carries the smallest
``meta.clock_us`` — the host where things went wrong first.
"""
from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def fail(msg):
    print(f"postmortem.py: {msg}", file=sys.stderr)
    raise SystemExit(1)


def load(path):
    """Read one bundle with full integrity checks (checksum + JSON +
    schema); failures exit 1."""
    from mxnet_tpu import blackbox
    from mxnet_tpu.base import MXNetError
    try:
        return blackbox.read_bundle(path)
    except (MXNetError, OSError) as e:
        fail(f"{path}: {e}")


def scan(directory):
    """-> (readable {path: bundle}, torn [path]) over one bundle dir,
    newest per rank last."""
    from mxnet_tpu import blackbox
    from mxnet_tpu.base import MXNetError
    paths = blackbox.list_bundles(directory)
    if not paths:
        fail(f"{directory}: no blackbox-<rank>-<step>.json bundles")
    good, torn = {}, []
    for p in paths:
        try:
            good[p] = blackbox.read_bundle(p)
        except (MXNetError, OSError) as e:
            torn.append(p)
            print(f"postmortem.py: skipping torn bundle {p}: {e}",
                  file=sys.stderr)
    return good, torn


def newest_per_rank(bundles):
    """{rank: (path, bundle)} keeping each rank's newest bundle (the
    list_bundles order is (mtime, name) ascending)."""
    out = {}
    for path, doc in bundles.items():
        out[int(doc["meta"]["rank"])] = (path, doc)
    return out


def first_anomaly(per_rank):
    """(rank, meta) of the earliest terminal (non-shadow) bundle on the
    shared monotonic clock; falls back to the earliest shadow bundle
    when no host recorded a terminal trigger."""
    terminal = [(doc["meta"]["clock_us"], rank, doc["meta"])
                for rank, (_, doc) in per_rank.items()
                if not doc["meta"].get("shadow")]
    pool = terminal or [(doc["meta"]["clock_us"], rank, doc["meta"])
                        for rank, (_, doc) in per_rank.items()]
    pool.sort(key=lambda t: (t[0], t[1]))
    _, rank, meta = pool[0]
    return rank, meta


def merge(per_rank):
    """One causal fleet timeline: every host's spans interleaved on the
    shared CLOCK_MONOTONIC microsecond base, host label injected."""
    timeline = []
    for rank, (path, doc) in sorted(per_rank.items()):
        for ev in doc.get("spans", ()):
            ev = dict(ev)
            args = dict(ev.get("args") or {})
            args["host"] = rank
            ev["args"] = args
            timeline.append(ev)
    timeline.sort(key=lambda e: (e.get("ts", 0),
                                 e.get("args", {}).get("host", 0)))
    rank, meta = first_anomaly(per_rank)
    return {
        "schema": "mx.postmortem-merge/1",
        "hosts": {str(r): {"path": p, "trigger": d["meta"]["trigger"],
                           "reason": d["meta"].get("reason"),
                           "shadow": d["meta"].get("shadow", False),
                           "step": d["meta"]["step"],
                           "clock_us": d["meta"]["clock_us"]}
                  for r, (p, d) in sorted(per_rank.items())},
        "first_anomaly_host": rank,
        "first_anomaly": {"trigger": meta["trigger"],
                          "reason": meta.get("reason"),
                          "step": meta["step"],
                          "clock_us": meta["clock_us"]},
        "timeline": timeline,
    }


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("command", choices=("summary", "merge", "validate"))
    ap.add_argument("path", help="bundle directory (summary/merge) or "
                                 "one bundle file (validate)")
    ap.add_argument("--expect", action="append", default=[],
                    metavar="TRIGGER",
                    help="validate: require meta.trigger to be one of "
                         "the given values")
    ap.add_argument("--out", default=None,
                    help="merge: also write the merged document here")
    args = ap.parse_args(argv)

    if args.command == "validate":
        doc = load(args.path)
        meta = doc["meta"]
        if args.expect and meta.get("trigger") not in args.expect:
            fail(f"{args.path}: trigger {meta.get('trigger')!r} not in "
                 f"expected {args.expect}")
        print(json.dumps({"ok": True, "path": args.path,
                          "trigger": meta.get("trigger"),
                          "rank": meta.get("rank"),
                          "step": meta.get("step"),
                          "shadow": meta.get("shadow", False),
                          "spans": len(doc.get("spans", ())),
                          "events": len(doc.get("events", ()))}))
        return 0

    good, torn = scan(args.path)
    per_rank = newest_per_rank(good)

    if args.command == "summary":
        print(json.dumps({
            "dir": args.path, "bundles": len(good), "torn": len(torn),
            "hosts": {str(r): {"path": p,
                               "trigger": d["meta"]["trigger"],
                               "shadow": d["meta"].get("shadow", False),
                               "step": d["meta"]["step"],
                               "spans": len(d.get("spans", ())),
                               "events": len(d.get("events", ()))}
                      for r, (p, d) in sorted(per_rank.items())}}))
        return 0

    doc = merge(per_rank)
    doc["torn"] = torn
    if args.out:
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(doc, f)
    print(json.dumps({"ok": True, "hosts": len(per_rank),
                      "torn": len(torn),
                      "timeline_events": len(doc["timeline"]),
                      "first_anomaly_host": doc["first_anomaly_host"],
                      "first_anomaly": doc["first_anomaly"],
                      "out": args.out}))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

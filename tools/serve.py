"""Serve CLI: drive a mx.serve engine from the command line.

A harness for poking the continuous-batching engine (docs/SERVING.md)
without writing a script — token-id prompts in, generated ids + SLO
stats out. The framework ships no tokenizer, so prompts are
comma-separated token ids (`--prompt 12,40,7`, repeatable) or a random
demo workload (`--demo N`).

Usage:
    # tiny CPU demo: 12 random prompts through 4 slots
    JAX_PLATFORMS=cpu python tools/serve.py --demo 12 --slots 4

    # explicit prompts, greedy, int8 weights
    python tools/serve.py --prompt 3,14,15 --prompt 92,65 \
        --quantize int8_weights --max-new 32

    # int4 weights + int8 KV cache (the bandwidth-min decode config)
    python tools/serve.py --demo 8 --quantize int4_weights,int8_kv

    # radix prefix-cache KV reuse + speculative decoding
    JAX_PLATFORMS=cpu python tools/serve.py --demo 8 \
        --prefix-cache on --draft tiny

    # gpt2-124m shapes (accelerator-sized; slow on CPU)
    python tools/serve.py --model gpt2_124m --demo 8

    # multi-replica fleet: 3 replicas, kill one mid-stream, report
    JAX_PLATFORMS=cpu python tools/serve.py --demo 12 --replicas 3 \
        --fault serve.replica_crash:at=3
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_model(name):
    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo import gpt

    if name == "tiny":
        net = gpt.GPTForCausalLM(vocab_size=512, units=64, hidden_size=256,
                                 num_layers=2, num_heads=4, max_length=128,
                                 dropout=0.0, embed_dropout=0.0)
    else:  # gpt2_* builders return the backbone; serving wants logits
        net = gpt.GPTForCausalLM(backbone=getattr(gpt, name)(
            dropout=0.0, embed_dropout=0.0))
    net.initialize()
    net(mx.np.zeros((1, 2), dtype="int32"))
    return net


def main(argv=None):
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("--model", default="tiny",
                   choices=["tiny", "gpt2_124m", "gpt2_355m"],
                   help="model config (random weights; tiny is CPU-sized)")
    p.add_argument("--prompt", action="append", default=[],
                   help="comma-separated token ids; repeatable")
    p.add_argument("--demo", type=int, default=0, metavar="N",
                   help="add N random prompts (lengths 2..24)")
    p.add_argument("--slots", type=int, default=None)
    p.add_argument("--max-new", type=int, default=32)
    p.add_argument("--temperature", type=float, default=0.0)
    p.add_argument("--eos-id", type=int, default=None)
    p.add_argument("--quantize", default=None,
                   help="low-bit storage: int8_weights, int4_weights, "
                        "int8_kv — comma-combinable, e.g. "
                        "'int4_weights,int8_kv'")
    p.add_argument("--prefix-cache", default="off", choices=["on", "off"],
                   help="radix prefix-cache KV reuse: shared prompt "
                        "prefixes are row-copied instead of re-prefilled")
    p.add_argument("--draft", default=None, choices=["tiny", "self"],
                   help="speculative decoding draft: 'tiny' builds a "
                        "fresh tiny model, 'self' drafts with the served "
                        "model itself (perfect acceptance — a plumbing "
                        "check, not a speedup)")
    p.add_argument("--slo-class", default=None, metavar="CLS",
                   help="submit every request under this SLO class "
                        "(one of serve.slo_classes)")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--replicas", type=int, default=0, metavar="N",
                   help="serve through a mx.servefleet group of N "
                        "replicas (session-affinity router, failover, "
                        "exactly-once ledger) instead of one engine")
    p.add_argument("--min-replicas", type=int, default=None,
                   help="fleet mode: servefleet.min_replicas floor")
    p.add_argument("--fault", default=None, metavar="SPEC",
                   help="fleet mode: arm a mx.fault spec, e.g. "
                        "serve.replica_crash:at=3 or "
                        "serve.replica_stall:at=2")
    args = p.parse_args(argv)

    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import telemetry

    net = build_model(args.model)
    vocab = net.backbone.word_embed.weight.shape[0]
    prompts = [[int(t) for t in s.split(",")] for s in args.prompt]
    rng = onp.random.RandomState(args.seed)
    for _ in range(args.demo):
        prompts.append(
            rng.randint(1, vocab, size=rng.randint(2, 25)).tolist())
    if not prompts:
        p.error("no work: pass --prompt and/or --demo N")

    telemetry.enable()
    if args.replicas:
        return fleet_main(args, prompts)
    draft = None
    if args.draft == "self":
        draft = net
    elif args.draft == "tiny":
        draft = build_model("tiny")
    eng = mx.serve.load(net, max_slots=args.slots, eos_id=args.eos_id,
                        temperature=args.temperature, seed=args.seed,
                        quantize=args.quantize, draft=draft,
                        prefix_cache=(args.prefix_cache == "on"))
    t0 = time.perf_counter()
    eng.warmup()
    warmup_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    reqs = [eng.submit(ids, max_new_tokens=args.max_new,
                       slo_class=args.slo_class) for ids in prompts]
    eng.run()
    wall = time.perf_counter() - t0

    for r in reqs:
        print(json.dumps({"id": r.id, "prompt": r.prompt,
                          "output_ids": r.output_ids,
                          "ttft_ms": round(r.ttft * 1e3, 3),
                          "tpot_ms": round(r.tpot * 1e3, 3)}))
    st = eng.stats()
    st["warmup_s"] = round(warmup_s, 3)
    st["wall_s"] = round(wall, 4)
    st["tokens_per_s"] = round(st["tokens_out"] / wall, 1)
    hit_rate = st.get("prefix", {}).get("hit_rate")
    accept = st.get("spec", {}).get("acceptance_rate")
    print(json.dumps({"cache_hit_rate": hit_rate,
                      "spec_acceptance_rate": accept,
                      "tokens_per_s": st["tokens_per_s"]}))
    print(json.dumps(st))
    return 1 if st["post_warmup_compiles"] else 0


def fleet_main(args, prompts):
    """--replicas N path: the same workload through a mx.servefleet
    group, optionally with an armed chaos spec (--fault) so the
    failover path is drivable from the command line."""
    import mxnet_tpu as mx
    from mxnet_tpu import fault

    if args.fault:
        fault.configure(args.fault)
    fleet = mx.servefleet.ServeFleet(
        lambda: build_model(args.model), replicas=args.replicas,
        min_replicas=args.min_replicas, max_slots=args.slots,
        eos_id=args.eos_id, temperature=args.temperature,
        seed=args.seed, quantize=args.quantize)
    t0 = time.perf_counter()
    frs = [fleet.submit(ids, max_new_tokens=args.max_new,
                        session=f"cli-{i}", slo_class=args.slo_class)
           for i, ids in enumerate(prompts)]
    fleet.run(tick_interval=0.001)
    wall = time.perf_counter() - t0
    for fr in frs:
        print(json.dumps({"key": fr.key, "session": fr.session,
                          "prompt": fr.prompt, "tokens": fr.tokens,
                          "replica": fr.replica_id,
                          "redispatches": fr.redispatches}))
    report = fleet.report()
    report["wall_s"] = round(wall, 4)
    print(json.dumps(report))
    incomplete = report["pending"]
    compiles = sum(r["post_warmup_compiles"]
                   for r in report["replicas"])
    fleet.close()
    if args.fault:
        fault.clear()
    return 1 if (incomplete or compiles) else 0


if __name__ == "__main__":
    sys.exit(main())

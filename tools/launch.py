#!/usr/bin/env python
"""Multi-process training launcher (reference: tools/launch.py, the
dmlc-tracker CLI that spawns scheduler+servers+workers; local mode per
tests/nightly/test_distributed_training-gpu.sh:25-38).

TPU-native design: there are no server/scheduler roles — rendezvous is the
PJRT coordination service hosted by worker 0, so only workers are spawned.
Each worker gets DMLC-style env vars that mxnet_tpu.kvstore.dist reads:

    DMLC_PS_ROOT_URI / DMLC_PS_ROOT_PORT   coordinator address
    DMLC_NUM_WORKER / DMLC_WORKER_ID       world size / rank
    MXTPU_DIST_DEVICE=cpu                  (local launcher) force the CPU
                                           platform + gloo collectives

Usage:  python tools/launch.py -n 4 [--launcher local] python3 train.py ...
"""
from __future__ import annotations

import argparse
import os
import signal
import socket
import subprocess
import sys


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def main(argv=None):
    p = argparse.ArgumentParser(
        description="launch a multi-process mxnet_tpu job on this host")
    p.add_argument("-n", "--num-workers", type=int, required=True)
    p.add_argument("-s", "--num-servers", type=int, default=0,
                   help="accepted for reference-CLI parity; there are no "
                        "server processes (coordination is PJRT)")
    p.add_argument("--launcher", default="local", choices=["local"],
                   help="only 'local' (N processes on this host); multi-host "
                        "pods use the cluster scheduler's own launcher")
    p.add_argument("--port", type=int, default=None,
                   help="coordinator port (default: pick a free one)")
    p.add_argument("--env", action="append", default=[],
                   help="extra KEY=VALUE for workers (repeatable)")
    p.add_argument("command", nargs=argparse.REMAINDER)
    args = p.parse_args(argv)
    if not args.command:
        p.error("no command given")
    cmd = args.command[1:] if args.command[0] == "--" else args.command

    port = args.port or _free_port()
    procs = []
    for rank in range(args.num_workers):
        env = dict(os.environ)
        env.update({
            "DMLC_ROLE": "worker",
            "DMLC_PS_ROOT_URI": "127.0.0.1",
            "DMLC_PS_ROOT_PORT": str(port),
            "DMLC_NUM_WORKER": str(args.num_workers),
            "DMLC_WORKER_ID": str(rank),
            "MXTPU_DIST_DEVICE": "cpu",
        })
        for kv in args.env:
            k, _, v = kv.partition("=")
            env[k] = v
        procs.append(subprocess.Popen(cmd, env=env))

    def _kill_all(signum=None, frame=None):
        for pr in procs:
            if pr.poll() is None:
                pr.terminate()

    signal.signal(signal.SIGINT, _kill_all)
    signal.signal(signal.SIGTERM, _kill_all)

    rc = 0
    for pr in procs:
        pr.wait()
        if pr.returncode != 0:
            rc = pr.returncode
            _kill_all()  # one failed worker dooms the job; reap the rest
    return rc


if __name__ == "__main__":
    sys.exit(main())

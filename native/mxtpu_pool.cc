// Pooled host-memory allocator.
//
// Reference parity: src/storage/pooled_storage_manager.h
// (PooledStorageManager<RoundPower2/RoundMultiple>, per-device pools
// selected by MXNET_*_MEM_POOL_TYPE, stats via storage_profiler).  On the
// TPU stack device memory belongs to PJRT, so the pool's remaining real
// job is HOST staging buffers: batch assembly and IO readahead reuse
// aligned recycled blocks instead of hitting malloc for every batch.
//
// Strategy 0 ("naive"): pass-through aligned_alloc/free.
// Strategy 1 ("round_power2"): size rounded up to a power of two; freed
// blocks are kept in per-class free lists for reuse (DirectFree analog:
// mxtpu_pool_empty).
//
// Built on demand by mxnet_tpu.native (g++ -O3 -shared); no external deps.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <mutex>
#include <new>
#include <vector>

namespace {

constexpr uint64_t kAlign = 64;
constexpr int kClasses = 48;  // up to 2^47 bytes

// padded to kAlign so the payload after the header stays 64-byte aligned
struct alignas(kAlign) Header {
  uint64_t size_class;  // index into free lists, or raw size for naive
  uint64_t magic;
};
static_assert(sizeof(Header) == kAlign, "payload alignment relies on this");
constexpr uint64_t kMagic = 0x6d787470756f6c21ULL;  // "mxtpuol!"

struct Pool {
  int strategy;
  std::mutex mu;
  std::vector<void*> free_lists[kClasses];
  uint64_t in_use = 0;       // bytes handed out
  uint64_t cached = 0;       // bytes parked in free lists
  uint64_t hits = 0;
  uint64_t misses = 0;
};

int size_class_of(uint64_t nbytes) {
  int c = 0;
  uint64_t s = 1;
  while (s < nbytes && c < kClasses - 1) {
    s <<= 1;
    ++c;
  }
  return c;
}

void* raw_alloc(uint64_t payload) {
  uint64_t total = sizeof(Header) + payload;
  total = (total + kAlign - 1) / kAlign * kAlign;
  void* base = std::aligned_alloc(kAlign, total);
  return base;
}

}  // namespace

extern "C" {

void* mxtpu_pool_create(int strategy) {
  return new (std::nothrow) Pool{strategy};
}

void* mxtpu_pool_alloc(void* pool_, uint64_t nbytes) {
  auto* pool = static_cast<Pool*>(pool_);
  if (!pool || nbytes == 0) return nullptr;
  if (pool->strategy == 0) {
    void* base = raw_alloc(nbytes);
    if (!base) return nullptr;
    auto* h = static_cast<Header*>(base);
    h->size_class = nbytes;
    h->magic = kMagic;
    std::lock_guard<std::mutex> g(pool->mu);
    pool->in_use += nbytes;
    ++pool->misses;
    return static_cast<char*>(base) + sizeof(Header);
  }
  int cls = size_class_of(nbytes);
  uint64_t rounded = 1ULL << cls;
  {
    std::lock_guard<std::mutex> g(pool->mu);
    auto& fl = pool->free_lists[cls];
    if (!fl.empty()) {
      void* base = fl.back();
      fl.pop_back();
      pool->cached -= rounded;
      pool->in_use += rounded;
      ++pool->hits;
      auto* h = static_cast<Header*>(base);
      h->size_class = cls;
      h->magic = kMagic;
      return static_cast<char*>(base) + sizeof(Header);
    }
    ++pool->misses;
    pool->in_use += rounded;
  }
  void* base = raw_alloc(rounded);
  if (!base) {
    std::lock_guard<std::mutex> g(pool->mu);
    pool->in_use -= rounded;
    return nullptr;
  }
  auto* h = static_cast<Header*>(base);
  h->size_class = cls;
  h->magic = kMagic;
  return static_cast<char*>(base) + sizeof(Header);
}

int mxtpu_pool_free(void* pool_, void* ptr) {
  auto* pool = static_cast<Pool*>(pool_);
  if (!pool || !ptr) return -1;
  void* base = static_cast<char*>(ptr) - sizeof(Header);
  auto* h = static_cast<Header*>(base);
  // check-and-clear of the double-free guard must be atomic with the
  // free-list push, so the whole body runs under the pool mutex
  std::lock_guard<std::mutex> g(pool->mu);
  if (h->magic != kMagic) return -1;
  h->magic = 0;  // restored when reused from the list
  if (pool->strategy == 0) {
    pool->in_use -= h->size_class;
    std::free(base);
    return 0;
  }
  uint64_t cls = h->size_class;
  uint64_t rounded = 1ULL << cls;
  pool->in_use -= rounded;
  pool->cached += rounded;
  pool->free_lists[cls].push_back(base);
  return 0;
}

void mxtpu_pool_empty(void* pool_) {
  auto* pool = static_cast<Pool*>(pool_);
  if (!pool) return;
  std::lock_guard<std::mutex> g(pool->mu);
  for (auto& fl : pool->free_lists) {
    for (void* base : fl) std::free(base);
    fl.clear();
  }
  pool->cached = 0;
}

uint64_t mxtpu_pool_stat(void* pool_, int which) {
  auto* pool = static_cast<Pool*>(pool_);
  if (!pool) return 0;
  std::lock_guard<std::mutex> g(pool->mu);
  switch (which) {
    case 0: return pool->in_use;
    case 1: return pool->cached;
    case 2: return pool->hits;
    case 3: return pool->misses;
    default: return 0;
  }
}

void mxtpu_pool_destroy(void* pool_) {
  auto* pool = static_cast<Pool*>(pool_);
  if (!pool) return;
  mxtpu_pool_empty(pool);
  delete pool;
}

}  // extern "C"

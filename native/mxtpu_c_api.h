/* mxtpu C ABI — stable C89-compatible surface for non-Python bindings.
 *
 * Reference parity: include/mxnet/c_api.h (MXNDArrayCreate*,
 * MXImperativeInvoke, MXNDArraySyncCopyToCPU, MXGetLastError ...).
 * TPU-native design: the runtime is Python/JAX, so this library hosts an
 * embedded CPython interpreter (or attaches to the enclosing one when the
 * caller is itself Python) and forwards each call through
 * mxnet_tpu.capi_bridge. Handles are opaque; every function returns 0 on
 * success and -1 on failure with the message retrievable via
 * MXTpuGetLastError() (thread-local, like the reference's MXGetLastError).
 *
 * dtype codes follow the reference's mshadow enumeration:
 *   0=float32 1=float64 2=float16 3=uint8 4=int32 5=int8 6=int64
 */
#ifndef MXTPU_C_API_H_
#define MXTPU_C_API_H_

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef void* NDArrayHandle;

/* Start (or attach to) the runtime. Safe to call more than once. */
int MXTpuInit(void);
/* Tear down only an interpreter this library created itself. */
int MXTpuShutdown(void);
/* Thread-local message for the most recent failing call in this thread. */
const char* MXTpuGetLastError(void);

/* Runtime info: writes a NUL-terminated string ("platform=...;devices=N")
 * into buf (truncating at cap). */
int MXTpuRuntimeInfo(char* buf, uint64_t cap);

/* Seed the global RNG (reference: MXRandomSeed). */
int MXTpuRandomSeed(int seed);
/* Block until all dispatched work completes (MXNDArrayWaitAll). */
int MXTpuWaitAll(void);

/* Create an ndarray by copying `data` (may be NULL for zeros) of
 * `dtype` with `shape[ndim]`. */
int MXTpuNDArrayCreate(const void* data, uint64_t nbytes, int dtype,
                       const int64_t* shape, int ndim, NDArrayHandle* out);
int MXTpuNDArrayFree(NDArrayHandle h);
/* ndim is in/out: in = capacity of shape[], out = actual rank. */
int MXTpuNDArrayShape(NDArrayHandle h, int* ndim, int64_t* shape);
int MXTpuNDArrayDType(NDArrayHandle h, int* dtype);
/* Synchronously copy the full buffer to host memory (nbytes must match). */
int MXTpuNDArraySyncCopyToCPU(NDArrayHandle h, void* out, uint64_t nbytes);

/* Invoke an operator by name with positional ndarray inputs and string
 * keyword arguments (values parsed as python literals where possible).
 * `num_outputs` is in/out: in = capacity of outputs[], out = count.
 * Names resolve against mxnet_tpu.numpy_extension (npx), mxnet_tpu.numpy
 * and the legacy CamelCase table — the same registry python callers use. */
int MXTpuImperativeInvoke(const char* op_name,
                          NDArrayHandle* inputs, int num_inputs,
                          const char** keys, const char** vals, int num_kw,
                          NDArrayHandle* outputs, int* num_outputs);

#ifdef __cplusplus
}
#endif

#endif  /* MXTPU_C_API_H_ */

// Native IO runtime: RecordIO scanning/reading + threaded prefetch queue.
//
// Reference parity: src/io/ (8.4k LoC C++) — recordio iterators
// (iter_image_recordio_2.cc), the prefetcher (iter_prefetcher.h) and the
// Gluon 2.0 C++ datasets (dataset.cc RecordFileDataset). TPU-native note:
// decode/augment stays in Python (numpy/PIL) or on-device; what must be
// native is the byte plumbing — mmap'd zero-copy record access, index
// construction without a .idx file, and a multi-threaded readahead queue
// so the host keeps the accelerator fed.
//
// Format (dmlc recordio, bit-compatible with python/mxnet/recordio.py):
//   [u32 magic = 0xced7230a][u32 lrec: upper 3 bits cflag, lower 29 len]
//   [len bytes payload][pad to 4-byte boundary]
//
// Build: g++ -O3 -shared -fPIC -pthread mxtpu_io.cc -o libmxtpu_io.so

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

namespace {

constexpr uint32_t kMagic = 0xced7230a;
constexpr uint32_t kLenMask = (1u << 29) - 1;

struct RecordFile {
  int fd = -1;
  const uint8_t* data = nullptr;
  size_t size = 0;
  std::vector<uint64_t> offsets;  // payload offsets
  std::vector<uint32_t> lengths;  // payload lengths
  std::string error;
};

struct Prefetcher {
  RecordFile* file = nullptr;
  std::vector<int64_t> order;
  size_t next_submit = 0;
  size_t capacity = 0;
  std::deque<std::pair<int64_t, std::vector<uint8_t>>> queue;
  std::mutex mu;
  std::condition_variable cv_put, cv_get;
  std::vector<std::thread> workers;
  std::atomic<bool> stop{false};
  std::atomic<size_t> submitted{0};
  size_t delivered = 0;

  ~Prefetcher() { shutdown(); }

  void shutdown() {
    stop.store(true);
    cv_put.notify_all();
    cv_get.notify_all();
    for (auto& t : workers)
      if (t.joinable()) t.join();
    workers.clear();
  }
};

}  // namespace

extern "C" {

// ---- record file ----------------------------------------------------------

void* mxtpu_rio_open(const char* path) {
  auto* rf = new RecordFile();
  rf->fd = ::open(path, O_RDONLY);
  if (rf->fd < 0) {
    delete rf;
    return nullptr;
  }
  struct stat st;
  if (fstat(rf->fd, &st) != 0) {
    ::close(rf->fd);
    delete rf;
    return nullptr;
  }
  rf->size = static_cast<size_t>(st.st_size);
  if (rf->size > 0) {
    void* p = mmap(nullptr, rf->size, PROT_READ, MAP_PRIVATE, rf->fd, 0);
    if (p == MAP_FAILED) {
      ::close(rf->fd);
      delete rf;
      return nullptr;
    }
    rf->data = static_cast<const uint8_t*>(p);
    madvise(p, rf->size, MADV_SEQUENTIAL);
  }
  // scan all records (the index the reference needs a .idx sidecar for)
  size_t pos = 0;
  while (pos + 8 <= rf->size) {
    uint32_t magic, lrec;
    std::memcpy(&magic, rf->data + pos, 4);
    std::memcpy(&lrec, rf->data + pos + 4, 4);
    if (magic != kMagic) break;
    uint32_t len = lrec & kLenMask;
    if (pos + 8 + len > rf->size) break;
    rf->offsets.push_back(pos + 8);
    rf->lengths.push_back(len);
    pos += 8 + len;
    pos += (4 - (pos % 4)) % 4;  // alignment padding
  }
  return rf;
}

int64_t mxtpu_rio_count(void* handle) {
  return static_cast<RecordFile*>(handle)->offsets.size();
}

// zero-copy view of record i; returns payload pointer + length
const uint8_t* mxtpu_rio_get(void* handle, int64_t i, uint64_t* len) {
  auto* rf = static_cast<RecordFile*>(handle);
  if (i < 0 || static_cast<size_t>(i) >= rf->offsets.size()) {
    *len = 0;
    return nullptr;
  }
  *len = rf->lengths[i];
  return rf->data + rf->offsets[i];
}

// byte offset of record i's header (for .idx writing parity)
int64_t mxtpu_rio_offset(void* handle, int64_t i) {
  auto* rf = static_cast<RecordFile*>(handle);
  if (i < 0 || static_cast<size_t>(i) >= rf->offsets.size()) return -1;
  return static_cast<int64_t>(rf->offsets[i]) - 8;
}

void mxtpu_rio_close(void* handle) {
  auto* rf = static_cast<RecordFile*>(handle);
  if (rf->data) munmap(const_cast<uint8_t*>(rf->data), rf->size);
  if (rf->fd >= 0) ::close(rf->fd);
  delete rf;
}

// ---- threaded prefetcher --------------------------------------------------
// Workers copy records (in a caller-supplied order, e.g. shuffled) into an
// in-memory bounded queue ahead of consumption — iter_prefetcher.h's role.

void* mxtpu_prefetch_create(void* file_handle, const int64_t* order,
                            int64_t n, int64_t capacity, int64_t n_workers) {
  auto* pf = new Prefetcher();
  pf->file = static_cast<RecordFile*>(file_handle);
  pf->order.assign(order, order + n);
  pf->capacity = static_cast<size_t>(capacity);
  int64_t workers = n_workers < 1 ? 1 : n_workers;
  for (int64_t w = 0; w < workers; ++w) {
    pf->workers.emplace_back([pf]() {
      while (true) {
        size_t idx = pf->submitted.fetch_add(1);
        if (idx >= pf->order.size() || pf->stop.load()) return;
        int64_t rec = pf->order[idx];
        uint64_t len = 0;
        const uint8_t* ptr = mxtpu_rio_get(pf->file, rec, &len);
        std::vector<uint8_t> buf(ptr, ptr + len);
        std::unique_lock<std::mutex> lk(pf->mu);
        pf->cv_put.wait(lk, [pf]() {
          return pf->queue.size() < pf->capacity || pf->stop.load();
        });
        if (pf->stop.load()) return;
        pf->queue.emplace_back(rec, std::move(buf));
        pf->cv_get.notify_one();
      }
    });
  }
  return pf;
}

// Pop the next prefetched record. Returns record id (>=0), -1 when
// exhausted. Caller provides a buffer of at least *len bytes when *len>0;
// two-phase: first call with buf=null to learn the length.
int64_t mxtpu_prefetch_next_len(void* handle, uint64_t* len) {
  auto* pf = static_cast<Prefetcher*>(handle);
  std::unique_lock<std::mutex> lk(pf->mu);
  if (pf->delivered >= pf->order.size()) {
    *len = 0;
    return -1;
  }
  pf->cv_get.wait(lk, [pf]() {
    return !pf->queue.empty() || pf->stop.load();
  });
  if (pf->queue.empty()) {
    *len = 0;
    return -1;
  }
  *len = pf->queue.front().second.size();
  return pf->queue.front().first;
}

int64_t mxtpu_prefetch_pop(void* handle, uint8_t* buf, uint64_t buf_len) {
  auto* pf = static_cast<Prefetcher*>(handle);
  std::unique_lock<std::mutex> lk(pf->mu);
  if (pf->queue.empty()) return -1;
  auto& front = pf->queue.front();
  uint64_t n = front.second.size();
  if (buf_len < n) return -2;
  std::memcpy(buf, front.second.data(), n);
  int64_t rec = front.first;
  pf->queue.pop_front();
  pf->delivered += 1;
  pf->cv_put.notify_one();
  return rec;
}

void mxtpu_prefetch_destroy(void* handle) {
  delete static_cast<Prefetcher*>(handle);
}

}  // extern "C"

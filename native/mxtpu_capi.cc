// mxtpu C ABI implementation (see mxtpu_c_api.h).
//
// Reference parity: src/c_api/c_api.cc — but where the reference's C API
// fronts a C++ engine, this one fronts the Python/JAX runtime: it embeds
// CPython (or attaches, when the host process already runs one — e.g. a
// ctypes consumer) and forwards through mxnet_tpu/capi_bridge.py. All
// Python-touching paths hold the GIL via PyGILState_Ensure, so the ABI is
// callable from any host thread, matching the reference's thread-safe
// C API entry points (c_api.cc MXAPIThreadLocalEntry).
#include "mxtpu_c_api.h"

#include <Python.h>

#include <atomic>
#include <cstring>
#include <mutex>
#include <string>

namespace {

thread_local std::string g_last_error;
std::atomic<bool> g_we_initialized{false};
std::mutex g_init_mutex;
PyObject* g_bridge = nullptr;  // mxnet_tpu.capi_bridge module (owned ref)

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  g_last_error = "unknown python error";
  if (value) {
    PyObject* s = PyObject_Str(value);
    if (s) {
      const char* c = PyUnicode_AsUTF8(s);
      if (c) g_last_error = c;
      Py_DECREF(s);
    }
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

// RAII GIL hold: every exported function body runs inside one of these.
class Gil {
 public:
  Gil() : state_(PyGILState_Ensure()) {}
  ~Gil() { PyGILState_Release(state_); }

 private:
  PyGILState_STATE state_;
};

int fail(const char* msg) {
  g_last_error = msg;
  return -1;
}

// Call bridge.<method>(args...) returning a new reference (or null+err).
PyObject* bridge_call(const char* method, PyObject* args) {
  if (!g_bridge) {
    g_last_error = "MXTpuInit not called";
    return nullptr;
  }
  PyObject* fn = PyObject_GetAttrString(g_bridge, method);
  if (!fn) {
    set_error_from_python();
    return nullptr;
  }
  PyObject* out = PyObject_CallObject(fn, args);
  Py_DECREF(fn);
  if (!out) set_error_from_python();
  return out;
}

}  // namespace

extern "C" {

int MXTpuInit(void) {
  std::lock_guard<std::mutex> lock(g_init_mutex);
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    g_we_initialized = true;
    // the embedded interpreter starts with this thread holding the GIL;
    // release it so Gil{} below (and other host threads) can acquire it
    PyEval_SaveThread();
  }
  Gil gil;
  if (g_bridge) return 0;
  PyObject* mod = PyImport_ImportModule("mxnet_tpu.capi_bridge");
  if (!mod) {
    set_error_from_python();
    return -1;
  }
  g_bridge = mod;
  return 0;
}

int MXTpuShutdown(void) {
  if (!g_we_initialized.exchange(false)) return 0;  // attached: not ours
  {
    Gil gil;
    Py_XDECREF(g_bridge);
    g_bridge = nullptr;
  }
  // finalization must run on a thread holding the GIL
  PyGILState_Ensure();
  Py_Finalize();
  return 0;
}

const char* MXTpuGetLastError(void) { return g_last_error.c_str(); }

int MXTpuRuntimeInfo(char* buf, uint64_t cap) {
  if (!buf || cap == 0) return fail("null buffer");
  Gil gil;
  PyObject* out = bridge_call("runtime_info", nullptr);
  if (!out) return -1;
  const char* c = PyUnicode_AsUTF8(out);
  if (!c) {
    Py_DECREF(out);
    set_error_from_python();
    return -1;
  }
  std::strncpy(buf, c, cap - 1);
  buf[cap - 1] = '\0';
  Py_DECREF(out);
  return 0;
}

int MXTpuRandomSeed(int seed) {
  Gil gil;
  PyObject* args = Py_BuildValue("(i)", seed);
  PyObject* out = bridge_call("seed", args);
  Py_DECREF(args);
  if (!out) return -1;
  Py_DECREF(out);
  return 0;
}

int MXTpuWaitAll(void) {
  Gil gil;
  PyObject* out = bridge_call("wait_all", nullptr);
  if (!out) return -1;
  Py_DECREF(out);
  return 0;
}

int MXTpuNDArrayCreate(const void* data, uint64_t nbytes, int dtype,
                       const int64_t* shape, int ndim, NDArrayHandle* out) {
  if (!out || ndim < 0 || (ndim > 0 && !shape)) return fail("bad arguments");
  Gil gil;
  PyObject* shp = PyTuple_New(ndim);
  for (int i = 0; i < ndim; ++i)
    PyTuple_SET_ITEM(shp, i, PyLong_FromLongLong(shape[i]));
  PyObject* payload =
      data ? PyBytes_FromStringAndSize(static_cast<const char*>(data),
                                       static_cast<Py_ssize_t>(nbytes))
           : (Py_INCREF(Py_None), Py_None);
  PyObject* args = PyTuple_Pack(3, payload, shp, PyLong_FromLong(dtype));
  Py_DECREF(payload);
  Py_DECREF(shp);
  PyObject* nd = bridge_call("ndarray_from_bytes", args);
  Py_DECREF(args);
  if (!nd) return -1;
  *out = nd;  // handle owns the reference
  return 0;
}

int MXTpuNDArrayFree(NDArrayHandle h) {
  if (!h) return 0;
  Gil gil;
  Py_DECREF(static_cast<PyObject*>(h));
  return 0;
}

int MXTpuNDArrayShape(NDArrayHandle h, int* ndim, int64_t* shape) {
  if (!h || !ndim) return fail("bad arguments");
  Gil gil;
  PyObject* args = PyTuple_Pack(1, static_cast<PyObject*>(h));
  PyObject* shp = bridge_call("ndarray_shape", args);
  Py_DECREF(args);
  if (!shp) return -1;
  Py_ssize_t n = PyTuple_Check(shp) ? PyTuple_Size(shp) : -1;
  if (n < 0 || (n > 0 && (!shape || *ndim < n))) {
    Py_DECREF(shp);
    return fail("shape buffer too small");
  }
  for (Py_ssize_t i = 0; i < n; ++i)
    shape[i] = PyLong_AsLongLong(PyTuple_GET_ITEM(shp, i));
  *ndim = static_cast<int>(n);
  Py_DECREF(shp);
  return 0;
}

int MXTpuNDArrayDType(NDArrayHandle h, int* dtype) {
  if (!h || !dtype) return fail("bad arguments");
  Gil gil;
  PyObject* args = PyTuple_Pack(1, static_cast<PyObject*>(h));
  PyObject* out = bridge_call("ndarray_dtype_code", args);
  Py_DECREF(args);
  if (!out) return -1;
  *dtype = static_cast<int>(PyLong_AsLong(out));
  Py_DECREF(out);
  return 0;
}

int MXTpuNDArraySyncCopyToCPU(NDArrayHandle h, void* out, uint64_t nbytes) {
  if (!h || !out) return fail("bad arguments");
  Gil gil;
  PyObject* args = PyTuple_Pack(1, static_cast<PyObject*>(h));
  PyObject* b = bridge_call("ndarray_to_bytes", args);
  Py_DECREF(args);
  if (!b) return -1;
  char* src = nullptr;
  Py_ssize_t n = 0;
  if (PyBytes_AsStringAndSize(b, &src, &n) != 0 ||
      static_cast<uint64_t>(n) != nbytes) {
    Py_DECREF(b);
    return fail("size mismatch in SyncCopyToCPU");
  }
  std::memcpy(out, src, n);
  Py_DECREF(b);
  return 0;
}

int MXTpuImperativeInvoke(const char* op_name, NDArrayHandle* inputs,
                          int num_inputs, const char** keys,
                          const char** vals, int num_kw,
                          NDArrayHandle* outputs, int* num_outputs) {
  if (!op_name || !num_outputs || (num_inputs > 0 && !inputs) ||
      (num_kw > 0 && (!keys || !vals)))
    return fail("bad arguments");
  Gil gil;
  PyObject* ins = PyList_New(num_inputs);
  for (int i = 0; i < num_inputs; ++i) {
    PyObject* o = static_cast<PyObject*>(inputs[i]);
    Py_INCREF(o);
    PyList_SET_ITEM(ins, i, o);
  }
  PyObject* kw = PyDict_New();
  for (int i = 0; i < num_kw; ++i) {
    PyObject* v = PyUnicode_FromString(vals[i]);
    PyDict_SetItemString(kw, keys[i], v);
    Py_DECREF(v);
  }
  PyObject* args = Py_BuildValue("(sOO)", op_name, ins, kw);
  Py_DECREF(ins);
  Py_DECREF(kw);
  PyObject* outs = bridge_call("invoke", args);
  Py_DECREF(args);
  if (!outs) return -1;
  Py_ssize_t n = PyList_Check(outs) ? PyList_Size(outs) : -1;
  if (n < 0 || (n > 0 && (!outputs || *num_outputs < n))) {
    Py_DECREF(outs);
    return fail("outputs buffer too small");
  }
  for (Py_ssize_t i = 0; i < n; ++i) {
    PyObject* o = PyList_GET_ITEM(outs, i);
    Py_INCREF(o);
    outputs[i] = o;
  }
  *num_outputs = static_cast<int>(n);
  Py_DECREF(outs);
  return 0;
}

}  // extern "C"

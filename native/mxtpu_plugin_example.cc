// Example versioned operator plugin (reference: example/extensions/
// lib_custom_op over include/mxnet/lib_api.h — the reference's ABI-stable
// .so plugin surface; src/lib_api.cc version handshake).
//
// The mxtpu plugin ABI (v1) an extension .so must export:
//   int          mxtpu_plugin_abi_version(void);   // == 1
//   const char*  mxtpu_plugin_name(void);
//   int          mxtpu_plugin_num_ops(void);
//   const char*  mxtpu_plugin_op_name(int i);
//   void         mxtpu_plugin_op_call(int i,
//                    const float* in, float* out, long long n,
//                    const float* params, int n_params);
//
// Ops are elementwise float32 host kernels; the framework surfaces each
// as an eager/jit-capable operator via a host callback (library.py
// load_native_ops). Parameters arrive as a flat float vector.

#include <cmath>
#include <cstdint>

extern "C" {

int mxtpu_plugin_abi_version(void) { return 1; }

const char* mxtpu_plugin_name(void) { return "mxtpu_plugin_example"; }

int mxtpu_plugin_num_ops(void) { return 2; }

const char* mxtpu_plugin_op_name(int i) {
  switch (i) {
    case 0: return "plugin_softsign";
    case 1: return "plugin_scale_shift";
    default: return "";
  }
}

static void softsign(const float* in, float* out, long long n) {
  for (long long i = 0; i < n; ++i) out[i] = in[i] / (1.0f + std::fabs(in[i]));
}

static void scale_shift(const float* in, float* out, long long n,
                        const float* params, int n_params) {
  const float a = n_params > 0 ? params[0] : 1.0f;
  const float b = n_params > 1 ? params[1] : 0.0f;
  for (long long i = 0; i < n; ++i) out[i] = a * in[i] + b;
}

void mxtpu_plugin_op_call(int i, const float* in, float* out, long long n,
                          const float* params, int n_params) {
  switch (i) {
    case 0: softsign(in, out, n); break;
    case 1: scale_shift(in, out, n, params, n_params); break;
    default: break;
  }
}

}  // extern "C"

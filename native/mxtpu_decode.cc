// Native JPEG decode: the hot half of the reference's image pipeline.
//
// Reference parity: src/io/image_io.cc + iter_image_recordio_2.cc decode
// via OpenCV; here libjpeg directly (present in the base image) with a
// thread pool — Python-side PIL decoding holds the GIL per image, this
// decodes a whole ImageRecordIter batch in parallel C threads.
//
// API (two-phase, caller owns all buffers):
//   mxtpu_jpeg_dims(data, len, &h, &w, &c)      -> 0 ok
//   mxtpu_jpeg_decode(data, len, out, cap, gray)-> 0 ok (HWC uint8, RGB)
//   mxtpu_decode_batch(datas, lens, n, outs, caps, gray, threads) ->
//       number of successfully decoded images (per-image rc in rcs)
//
// Build: g++ -O3 -shared -fPIC -pthread mxtpu_decode.cc -o ... -ljpeg

#include <csetjmp>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <atomic>
#include <thread>
#include <vector>

#include <jpeglib.h>

namespace {

struct ErrMgr {
  jpeg_error_mgr pub;
  std::jmp_buf jump;
};

void on_error(j_common_ptr cinfo) {
  auto* err = reinterpret_cast<ErrMgr*>(cinfo->err);
  std::longjmp(err->jump, 1);
}

void silence(j_common_ptr, int) {}

}  // namespace

extern "C" {

int mxtpu_jpeg_dims(const uint8_t* data, uint64_t len, int* h, int* w,
                    int* c) {
  jpeg_decompress_struct cinfo;
  ErrMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = on_error;
  err.pub.emit_message = silence;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data), len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  *h = static_cast<int>(cinfo.image_height);
  *w = static_cast<int>(cinfo.image_width);
  *c = cinfo.num_components >= 3 ? 3 : 1;
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int mxtpu_jpeg_decode(const uint8_t* data, uint64_t len, uint8_t* out,
                      uint64_t cap, int gray) {
  jpeg_decompress_struct cinfo;
  ErrMgr err;
  cinfo.err = jpeg_std_error(&err.pub);
  err.pub.error_exit = on_error;
  err.pub.emit_message = silence;
  if (setjmp(err.jump)) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  jpeg_create_decompress(&cinfo);
  jpeg_mem_src(&cinfo, const_cast<uint8_t*>(data), len);
  if (jpeg_read_header(&cinfo, TRUE) != JPEG_HEADER_OK) {
    jpeg_destroy_decompress(&cinfo);
    return -1;
  }
  cinfo.out_color_space = gray ? JCS_GRAYSCALE : JCS_RGB;
  jpeg_start_decompress(&cinfo);
  const uint64_t row = static_cast<uint64_t>(cinfo.output_width) *
                       cinfo.output_components;
  if (cap < row * cinfo.output_height) {
    jpeg_destroy_decompress(&cinfo);
    return -2;
  }
  while (cinfo.output_scanline < cinfo.output_height) {
    uint8_t* rows[1] = {out + row * cinfo.output_scanline};
    jpeg_read_scanlines(&cinfo, rows, 1);
  }
  jpeg_finish_decompress(&cinfo);
  jpeg_destroy_decompress(&cinfo);
  return 0;
}

int mxtpu_decode_batch(const uint8_t* const* datas, const uint64_t* lens,
                       int n, uint8_t* const* outs, const uint64_t* caps,
                       int gray, int n_threads, int* rcs) {
  std::atomic<int> next{0};
  std::atomic<int> ok{0};
  int workers = n_threads < 1 ? 1 : n_threads;
  if (workers > n) workers = n;
  std::vector<std::thread> pool;
  for (int t = 0; t < workers; ++t) {
    pool.emplace_back([&]() {
      while (true) {
        int i = next.fetch_add(1);
        if (i >= n) return;
        int rc = mxtpu_jpeg_decode(datas[i], lens[i], outs[i], caps[i],
                                   gray);
        rcs[i] = rc;
        if (rc == 0) ok.fetch_add(1);
      }
    });
  }
  for (auto& t : pool) t.join();
  return ok.load();
}

}  // extern "C"

"""Fault-injection framework + resilience layer (docs/FAULT_TOLERANCE.md).

Every recovery path ships with the chaos test that proves it: worker
crash/hang -> bounded respawn -> threaded fallback; NaN gradients -> step
skipped and counted; torn checkpoint -> checksum rejection + auto-resume
from the previous valid one; hung collective -> structured timeout. The
CI `chaos` stage additionally runs the env_spec test under a small
MXNET_FAULT_SPEC matrix (ci/run.sh chaos).
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data import DataLoader


class _SynthDataset:
    """Picklable (spawn workers) linearly-separable classification set."""

    def __init__(self, n=128, dim=16, classes=3):
        rs = onp.random.RandomState(0)
        self.x = rs.rand(n, dim).astype(onp.float32)
        w = rs.rand(dim, classes).astype(onp.float32)
        self.y = (self.x @ w).argmax(axis=1).astype(onp.int32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


@pytest.fixture(autouse=True)
def _clean_fault_state():
    yield
    mx.fault.clear()
    mx.fault.reset_stats()
    mx.config.reset()


def _mlp(classes=3):
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(classes))
    net.initialize(mx.init.Xavier())
    return net


# ---------------------------------------------------------------------------
# framework basics
# ---------------------------------------------------------------------------

def test_spec_parse_and_api():
    armed = mx.fault.configure(
        "invoke.nan_output:at=3,times=1;serialization.torn_write:prob=0.5")
    assert armed == ["invoke.nan_output", "serialization.torn_write"]
    assert mx.fault.active()
    assert mx.fault.armed("invoke.nan_output")
    assert not mx.fault.armed("dataloader.worker_crash")
    assert "invoke.nan_output [at=3,times=1" in mx.fault.describe()
    mx.fault.clear()
    assert not mx.fault.active()

    with pytest.raises(MXNetError, match="unknown fault injection point"):
        mx.fault.configure("no.such.point:at=1")
    with pytest.raises(MXNetError, match="unknown key"):
        mx.fault.configure("invoke.nan_output:bogus=1")
    with pytest.raises(MXNetError, match="needs a trigger"):
        mx.fault.configure("invoke.nan_output")


def test_at_fires_exactly_once():
    mx.fault.configure("invoke.nan_output:at=3")
    fires = [mx.fault.fire("invoke.nan_output") for _ in range(6)]
    assert fires == [False, False, True, False, False, False]
    assert mx.fault.stats()["injected.invoke.nan_output"] == 1


def test_prob_stream_is_seeded_and_reproducible():
    mx.fault.configure("invoke.nan_output:prob=0.5,seed=7")
    first = [mx.fault.fire("invoke.nan_output") for _ in range(32)]
    mx.fault.configure("invoke.nan_output:prob=0.5,seed=7")
    again = [mx.fault.fire("invoke.nan_output") for _ in range(32)]
    assert first == again
    assert any(first) and not all(first)


def test_disabled_hooks_are_noops(tmp_path):
    assert not mx.fault.active()
    assert not mx.fault.fire("invoke.nan_output")
    # eager math unaffected
    out = (mx.np.ones((2, 2)) * 3).asnumpy()
    assert onp.isfinite(out).all()
    # serialization writes full bytes
    p = str(tmp_path / "x.bin")
    mx.serialization.atomic_write_bytes(p, b"abcdef" * 100)
    assert os.path.getsize(p) == 600
    assert mx.fault.stats() == {}


# ---------------------------------------------------------------------------
# DataLoader: crash -> bounded respawn -> threaded fallback; hang heartbeat
# ---------------------------------------------------------------------------

def _epoch_rows(loader):
    """Concatenate every batch's data rows, preserving batch order."""
    xs = [x.asnumpy() for x, _ in loader]
    return onp.concatenate(xs), len(xs)


def test_worker_crash_respawns_and_preserves_epoch(monkeypatch):
    monkeypatch.setenv("MXNET_FAULT_SPEC", "dataloader.worker_crash:at=2")
    ds = _SynthDataset(64)
    loader = DataLoader(ds, batch_size=8, num_workers=2, thread_pool=False,
                        timeout=60)
    rows, nbatches = _epoch_rows(loader)
    assert nbatches == 8
    # recovery re-queued the in-flight batches in order: identical epoch
    onp.testing.assert_array_equal(rows, ds.x)
    assert mx.fault.stats().get("dataloader.worker_respawn") == 1
    assert "dataloader.fallback_threaded" not in mx.fault.stats()


def test_worker_crash_storm_falls_back_to_threads(monkeypatch):
    monkeypatch.setenv("MXNET_FAULT_SPEC", "dataloader.worker_crash:prob=1.0")
    monkeypatch.setenv("MXNET_DATALOADER_MAX_RESPAWNS", "1")
    ds = _SynthDataset(16)
    loader = DataLoader(ds, batch_size=8, num_workers=2, thread_pool=False,
                        timeout=60)
    rows, nbatches = _epoch_rows(loader)
    assert nbatches == 2
    onp.testing.assert_array_equal(rows, ds.x)
    stats = mx.fault.stats()
    assert stats.get("dataloader.worker_respawn") == 1  # bounded
    assert stats.get("dataloader.fallback_threaded") == 1
    assert loader._force_threads
    # the degradation is permanent: the next epoch goes straight to threads
    rows2, _ = _epoch_rows(loader)
    onp.testing.assert_array_equal(rows2, ds.x)
    assert stats == mx.fault.stats()


def test_worker_hang_caught_by_heartbeat_deadline(monkeypatch):
    monkeypatch.setenv("MXNET_FAULT_SPEC", "dataloader.worker_hang:at=1")
    ds = _SynthDataset(16)
    loader = DataLoader(ds, batch_size=8, num_workers=2, thread_pool=False,
                        timeout=3)
    rows, nbatches = _epoch_rows(loader)
    assert nbatches == 2
    onp.testing.assert_array_equal(rows, ds.x)
    # at least one heartbeat miss was detected and recovered from; a loaded
    # host can miss the deadline again on the respawned pool (extra respawn
    # or even the threaded fallback) — the epoch contract above is what
    # matters
    assert mx.fault.stats().get("dataloader.worker_respawn", 0) >= 1


def test_worker_mode_auto_and_override(monkeypatch):
    ds = _SynthDataset(32)
    # cheap samples -> threads (BENCH_r05: shm transport ~4x slower)
    assert DataLoader(ds, batch_size=8,
                      num_workers=2)._resolve_worker_mode() == "threads"
    # a zero threshold makes any sample "expensive" -> processes
    mx.config.set("dataloader.mp_threshold_ms", 0.0)
    assert DataLoader(ds, batch_size=8,
                      num_workers=2)._resolve_worker_mode() == "processes"
    mx.config.reset("dataloader.mp_threshold_ms")
    # env override beats the probe
    monkeypatch.setenv("MXNET_DATALOADER_WORKER_MODE", "processes")
    assert DataLoader(ds, batch_size=8,
                      num_workers=2)._resolve_worker_mode() == "processes"
    monkeypatch.setenv("MXNET_DATALOADER_WORKER_MODE", "threads")
    assert DataLoader(ds, batch_size=8,
                      num_workers=2)._resolve_worker_mode() == "threads"
    # explicit constructor arg keeps its historical meaning
    monkeypatch.delenv("MXNET_DATALOADER_WORKER_MODE")
    assert DataLoader(ds, batch_size=8, num_workers=2,
                      thread_pool=True)._resolve_worker_mode() == "threads"
    assert DataLoader(ds, batch_size=8, num_workers=2,
                      thread_pool=False)._resolve_worker_mode() == "processes"


# ---------------------------------------------------------------------------
# Trainer: non-finite gradient guard
# ---------------------------------------------------------------------------

def test_nonfinite_grad_step_skipped_and_counted():
    mx.config.set("trainer.skip_nonfinite", True)
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.np.array(onp.random.RandomState(0).rand(4, 16).astype("float32"))
    y = mx.np.array(onp.array([0, 1, 2, 0], dtype="int32"))
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()

    # one clean step to settle initialization
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(4)
    assert trainer.nonfinite_steps == 0
    before = {k: v.data().asnumpy().copy()
              for k, v in net.collect_params().items()}

    # corrupt the first eager op of the next forward -> NaN gradients
    mx.fault.configure("invoke.nan_output:at=1,times=1")
    with autograd.record():
        loss = loss_fn(net(x), y)
    mx.fault.clear()
    loss.backward()
    trainer.step(4)

    assert trainer.nonfinite_steps == 1
    assert mx.fault.stats()["trainer.nonfinite_skip"] == 1
    for k, v in net.collect_params().items():
        onp.testing.assert_array_equal(v.data().asnumpy(), before[k],
                                       err_msg=f"{k} moved on skipped step")

    # a following clean step still updates
    with autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(4)
    assert trainer.nonfinite_steps == 1
    moved = any(not onp.array_equal(v.data().asnumpy(), before[k])
                for k, v in net.collect_params().items())
    assert moved


def test_nonfinite_guard_backs_off_amp_scaler():
    from mxnet_tpu.amp.loss_scaler import LossScaler
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    trainer._amp_loss_scaler = scaler = LossScaler()
    assert trainer._guard_active()
    scale0 = scaler.loss_scale
    x = mx.np.array(onp.random.RandomState(1).rand(4, 16).astype("float32"))
    mx.fault.configure("invoke.nan_output:at=1,times=1")
    with autograd.record():
        loss = net(x).square().sum()
    mx.fault.clear()
    loss.backward()
    trainer.step(4)
    assert trainer.nonfinite_steps == 1
    assert scaler.loss_scale < scale0


# ---------------------------------------------------------------------------
# checkpoints: crash-atomicity, checksums, auto-resume
# ---------------------------------------------------------------------------

def test_atomic_write_cleans_stale_temps(tmp_path):
    p = str(tmp_path / "ckpt.bin")
    stale = p + ".tmp-12345"
    with open(stale, "wb") as f:
        f.write(b"leftover from a crashed save")
    mx.serialization.atomic_write_bytes(p, b"payload")
    assert not os.path.exists(stale)
    with open(p, "rb") as f:
        assert f.read() == b"payload"
    assert not [fn for fn in os.listdir(tmp_path) if ".tmp-" in fn]


def test_torn_write_rejected_by_checksum(tmp_path):
    p = str(tmp_path / "w.params")
    net = _mlp()
    net(mx.np.ones((1, 16)))
    net.save_parameters(p)
    mx.serialization.write_checksum(p)
    assert mx.serialization.verify_checksum(p) is True

    # silent truncation on the next save: the sidecar no longer matches
    mx.fault.configure("serialization.torn_write:at=1,times=1")
    net.save_parameters(p)
    mx.fault.clear()
    assert mx.fault.stats()["injected.serialization.torn_write"] == 1
    with pytest.raises(MXNetError, match="checksum mismatch"):
        mx.serialization.verify_checksum(p)
    with pytest.raises(MXNetError, match="checksum mismatch"):
        net.load_parameters(p)


class _EstimatorStub:
    def __init__(self, net, trainer):
        self.net = net
        self.trainer = trainer


def test_checkpoint_handler_auto_resume_skips_torn(tmp_path):
    from mxnet_tpu.gluon.contrib.estimator.event_handler import \
        CheckpointHandler
    net = _mlp()
    net(mx.np.ones((1, 16)))
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    est = _EstimatorStub(net, trainer)

    h = CheckpointHandler(str(tmp_path), epoch_period=1)
    for _ in range(3):
        h.epoch_end(est)
    for suffix in (".params", ".params.sha256", ".states", ".states.sha256"):
        assert os.path.exists(str(tmp_path / f"model-epoch3{suffix}"))

    # tear the newest checkpoint behind the checksum's back
    newest = str(tmp_path / "model-epoch3.params")
    with open(newest, "rb") as f:
        blob = f.read()
    with open(newest, "wb") as f:
        f.write(blob[:len(blob) // 2])

    h2 = CheckpointHandler(str(tmp_path), resume_from_checkpoint=True)
    h2.train_begin(est)
    assert h2.current_epoch == 2  # newest valid, not newest on disk
    stats = mx.fault.stats()
    assert stats["checkpoint.rejected"] == 1
    assert stats["checkpoint.resume"] == 1


# ---------------------------------------------------------------------------
# dist collectives: watchdog raises a structured diagnostic, never hangs
# ---------------------------------------------------------------------------

def test_collective_watchdog_structured_timeout():
    from mxnet_tpu.kvstore import CollectiveTimeout, DistKVStore
    kv = DistKVStore()
    kv.init("weight", mx.np.array([1.0, 2.0]))
    mx.config.set("kvstore.async_timeout", 0.3)
    # this test asserts the RAW watchdog contract; disable the elastic
    # retry layer (tests/test_resilience.py covers it)
    mx.config.set("kvstore.retry_max", 0)
    mx.fault.configure("kvstore.collective_timeout:at=1")
    with pytest.raises(CollectiveTimeout) as ei:
        kv.push("weight", mx.np.array([0.5, 0.5]))
    e = ei.value
    assert (e.op, e.key, e.rank, e.nprocs) == ("allreduce", "weight", 0, 1)
    assert e.elapsed >= 0.3
    assert "kvstore.async_timeout" in str(e)
    assert mx.fault.stats()["kvstore.collective_timeout_raised"] == 1
    mx.fault.clear()
    mx.config.reset("kvstore.retry_max")
    # disarmed single-process store goes back to the wait-free fast path
    kv.push("weight", mx.np.array([0.5, 0.5]))


def test_dist_async_watchdog_diagnostic_names_key_rank_and_knob():
    from mxnet_tpu.kvstore import CollectiveTimeout, DistAsyncKVStore
    kv = DistAsyncKVStore()
    kv.init("emb", mx.np.array([3.0]))
    mx.config.set("kvstore.async_timeout", 0.3)
    mx.config.set("kvstore.retry_max", 0)  # raw watchdog contract
    mx.fault.configure("kvstore.collective_timeout:at=1")
    out = mx.np.zeros(1)
    with pytest.raises(CollectiveTimeout) as ei:
        kv.pull("emb", out=out)
    msg = str(ei.value)
    assert "'emb'" in msg                      # names the key
    assert "rank 0/1" in msg                   # names the rank
    assert "kvstore.async_timeout" in msg      # points at the knob
    assert "pull schedule" in msg              # reconcile-specific hint
    assert ei.value.op.startswith("reconcile#")
    mx.fault.clear()
    mx.config.reset("kvstore.retry_max")
    # the reconciling pull works once disarmed (nprocs=1: identity)
    kv.pull("emb", out=out)
    assert out.asnumpy()[0] == 3.0


# ---------------------------------------------------------------------------
# end-to-end chaos: train through crashes, one NaN step, and a mid-run
# checkpoint restart — final metrics must come out correct anyway
# ---------------------------------------------------------------------------

def test_chaos_train_completes_with_correct_metrics(monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_FAULT_SPEC", "dataloader.worker_crash:at=2")
    mx.config.set("trainer.skip_nonfinite", True)
    mx.random.seed(0)

    ds = _SynthDataset(256)
    loader = DataLoader(ds, batch_size=32, num_workers=2, thread_pool=False,
                        timeout=60)
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-2})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = gluon.metric.Accuracy()

    ckpt = str(tmp_path / "chaos")
    seen = 0
    for epoch in range(10):
        if epoch == 5:
            # simulate a restart: fresh model resumed from the checkpoint
            net = _mlp()
            net(mx.np.ones((1, 16)))
            net.load_parameters(ckpt + ".params")
            trainer = gluon.Trainer(net.collect_params(), "adam",
                                    {"learning_rate": 3e-2})
            trainer.load_states(ckpt + ".states")
        metric.reset()
        for i, (data, label) in enumerate(loader):
            if epoch == 1 and i == 2:
                # one poisoned forward; the guard must absorb it
                mx.fault.configure("invoke.nan_output:at=1,times=1")
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            mx.fault.clear()
            loss.backward()
            trainer.step(data.shape[0])
            metric.update([label], [out])
            seen += 1
        if epoch == 4:
            net.save_parameters(ckpt + ".params")
            trainer.save_states(ckpt + ".states")
            mx.serialization.write_checksum(ckpt + ".params")
            mx.serialization.write_checksum(ckpt + ".states")

    stats = mx.fault.stats()
    assert seen == 10 * len(loader)             # no batch lost to the chaos
    assert trainer.nonfinite_steps + stats.get(
        "trainer.nonfinite_skip", 0) >= 1      # the NaN step was skipped
    assert stats.get("dataloader.worker_respawn", 0) >= 1
    acc = metric.get()[1]
    assert acc > 0.9, f"chaos training diverged: accuracy {acc}"


# ---------------------------------------------------------------------------
# CI chaos matrix entrypoint: runs under whatever MXNET_FAULT_SPEC the
# stage exports (ci/run.sh chaos); skipped without one
# ---------------------------------------------------------------------------

def test_env_spec_chaos_smoke(tmp_path):
    spec = os.environ.get("MXNET_FAULT_SPEC", "")
    if not spec:
        pytest.skip("MXNET_FAULT_SPEC not set (CI chaos matrix only)")
    from mxnet_tpu.gluon.contrib.estimator.event_handler import \
        CheckpointHandler
    assert mx.fault.active()  # armed from the env at import
    mx.config.set("trainer.skip_nonfinite", True)

    ds = _SynthDataset(128)
    loader = DataLoader(ds, batch_size=32, num_workers=2, thread_pool=False,
                        timeout=60)
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1e-2})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    est = _EstimatorStub(net, trainer)
    handler = CheckpointHandler(str(tmp_path), epoch_period=1)

    seen = 0
    for _ in range(2):
        for data, label in loader:
            with autograd.record():
                loss = loss_fn(net(data), label)
            loss.backward()
            trainer.step(data.shape[0])
            seen += 1
        handler.epoch_end(est)
    assert seen == 2 * len(loader)

    resumer = CheckpointHandler(str(tmp_path), resume_from_checkpoint=True)
    resumer.train_begin(est)
    assert resumer.current_epoch >= 1  # some checkpoint validated

    stats = mx.fault.stats()
    recovery = ("dataloader.worker_respawn", "dataloader.fallback_threaded",
                "trainer.nonfinite_skip", "checkpoint.rejected")
    assert any(k.startswith("injected.") for k in stats) or \
        any(k in stats for k in recovery), f"no chaos observed: {stats}"
    mx.fault.log_stats()

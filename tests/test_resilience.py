"""Elastic training (mx.resilience): preemption-safe TrainState bundles,
deterministic mid-epoch resume, collective retry-with-rejoin.

The acceptance oracle is BITWISE resume: a run preempted at step K and
restored from its bundle must produce the identical loss sequence for
steps K+1..N as the uninterrupted run — not "close", identical floats.
"""
import os
import pickle
import signal
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import estimator as est
from mxnet_tpu.gluon.data import DataLoader
from mxnet_tpu.gluon.data.dataset import ArrayDataset
from mxnet_tpu.gluon.data.sampler import BatchSampler, RandomSampler

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_resilience_state():
    mx.fault.clear()
    mx.fault.reset_stats()
    mx.resilience.clear_preempt()
    yield
    mx.fault.clear()
    mx.resilience.clear_preempt()
    mx.resilience.uninstall_signal_handlers()
    for knob in ("kvstore.retry_max", "kvstore.retry_backoff",
                 "kvstore.async_timeout", "resilience.max_restarts"):
        mx.config.reset(knob)


# ---------------------------------------------------------------------------
# sampler / loader cursor state
# ---------------------------------------------------------------------------

def test_random_sampler_epoch_replay():
    """An epoch's permutation is a replayable pure function of its
    recorded seed — for fixed AND stochastic (seed=None) samplers."""
    for seed in (11, None):
        rs = RandomSampler(32, seed=seed)
        epoch1 = list(rs)
        state = rs.state_dict()
        rs2 = RandomSampler(32, seed=seed)
        rs2.load_state_dict(state)
        assert list(rs2) == epoch1
        # and the NEXT epoch continues the same sequence for seeded mode
        if seed is not None:
            assert list(rs2) == list(rs)


def test_batch_sampler_mid_epoch_resume():
    bs = BatchSampler(RandomSampler(20, seed=3), 6, "discard")
    it = iter(bs)
    consumed = [next(it), next(it)]
    state = bs.state_dict()
    remaining_truth = list(it)

    bs2 = BatchSampler(RandomSampler(20, seed=3), 6, "discard")
    bs2.load_state_dict(state)
    assert list(iter(bs2)) == remaining_truth
    # the epoch after the resumed one matches the uninterrupted epoch too
    assert list(iter(bs2)) == list(iter(bs))
    assert consumed  # sanity: we really were mid-epoch


def test_batch_sampler_rollover_carry_survives_resume():
    """Mid-epoch state must include the rollover carry the epoch started
    with, or the resumed epoch regenerates different batch boundaries."""
    bs = BatchSampler(RandomSampler(10, seed=5), 4, "rollover")
    list(iter(bs))          # epoch 0 leaves a 2-sample carry
    it = iter(bs)           # epoch 1 starts with the carry
    first = next(it)
    state = bs.state_dict()
    rest_truth = list(it)

    bs2 = BatchSampler(RandomSampler(10, seed=5), 4, "rollover")
    bs2.load_state_dict(state)
    assert list(iter(bs2)) == rest_truth
    assert len(first) == 4


def test_dataloader_served_cursor_is_authoritative(tmp_path):
    """The loader records batches SERVED to the loop, not generated into
    a prefetch queue; resume continues at the consumed position."""
    x = onp.arange(40, dtype="float32").reshape(20, 2)
    ds = ArrayDataset(x)
    loader = DataLoader(ds, batch_size=4,
                        sampler=RandomSampler(20, seed=9), num_workers=0)
    it = iter(loader)
    seen = [next(it).asnumpy() for _ in range(2)]
    state = loader.state_dict()
    assert state["cursor"] == 2
    rest_truth = [b.asnumpy() for b in it]

    loader2 = DataLoader(ds, batch_size=4,
                         sampler=RandomSampler(20, seed=9), num_workers=0)
    loader2.load_state_dict(state)
    rest = [b.asnumpy() for b in loader2]
    assert len(rest) == len(rest_truth)
    for a, b in zip(rest, rest_truth):
        onp.testing.assert_array_equal(a, b)
    assert seen  # consumed prefix existed


def test_dataloader_without_stateful_sampler_raises():
    ds = ArrayDataset(onp.zeros((4, 1), dtype="float32"))

    class Dumb:
        def __iter__(self):
            yield [0, 1]

        def __len__(self):
            return 1

    loader = DataLoader(ds, batch_sampler=Dumb())
    with pytest.raises(mx.base.MXNetError, match="state_dict"):
        loader.state_dict()


# ---------------------------------------------------------------------------
# trainer / scaler state
# ---------------------------------------------------------------------------

def _toy_net(lr=0.1, opt="adam"):
    net = nn.Sequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), opt,
                            {"learning_rate": lr})
    return net, trainer


def _step(net, trainer, x, y):
    loss_fn = gluon.loss.L2Loss()
    with mx.autograd.record():
        loss = loss_fn(net(x), y)
    loss.backward()
    trainer.step(x.shape[0])
    return float(loss.mean().asnumpy())


def test_trainer_state_roundtrip_bitwise():
    """Optimizer state (adam moments + step count) restored via
    state_dict must continue the EXACT update trajectory."""
    mx.random.seed(100)
    x = mx.np.array(onp.random.RandomState(0).randn(8, 4).astype("f"))
    y = mx.np.array(onp.random.RandomState(1).randn(8, 2).astype("f"))

    net_a, tr_a = _toy_net()
    for _ in range(3):
        _step(net_a, tr_a, x, y)
    state = tr_a.state_dict()
    params = {k: p.data().asnumpy()
              for k, p in net_a.collect_params().items()}
    truth = [_step(net_a, tr_a, x, y) for _ in range(3)]

    mx.random.seed(100)
    net_b, tr_b = _toy_net()
    net_b(x)  # materialize deferred shapes
    for k, p in net_b.collect_params().items():
        p.set_data(mx.np.array(params[k]))
    tr_b.load_state_dict(state)
    got = [_step(net_b, tr_b, x, y) for _ in range(3)]
    assert got == truth
    assert tr_b.nonfinite_steps == tr_a.nonfinite_steps


def test_loss_scaler_state_roundtrip():
    from mxnet_tpu.amp.loss_scaler import LossScaler
    s = LossScaler()
    s.loss_scale = 1024.0
    s._unskipped = 7
    s2 = LossScaler()
    s2.load_state_dict(s.state_dict())
    assert s2.loss_scale == 1024.0 and s2._unskipped == 7


# ---------------------------------------------------------------------------
# TrainState bundles: the bitwise mid-epoch resume oracle
# ---------------------------------------------------------------------------

def _make_run(bundle_path):
    """Deterministic toy run: seeded init, seeded shuffle, adam."""
    mx.random.seed(1234)
    onp.random.seed(1234)
    rng = onp.random.RandomState(7)
    x = rng.randn(24, 4).astype("f")
    y = rng.randn(24, 2).astype("f")
    ds = ArrayDataset(x, y)
    loader = DataLoader(ds, batch_size=4,
                        sampler=RandomSampler(24, seed=5), num_workers=0)
    net, trainer = _toy_net(lr=0.05)
    net(mx.np.array(x[:1]))  # materialize shapes
    state = mx.resilience.TrainState(net=net, trainer=trainer,
                                     loader=loader, path=bundle_path)
    return net, trainer, loader, state


def _train(net, trainer, loader, state, epochs=2, preempt_at=None):
    """Flat training loop; returns [(step, loss)].  ``preempt_at`` saves
    the bundle after that step and stops (the cooperative-preempt path)."""
    losses = []
    for _ in range(state.epoch, epochs):
        for bx, by in loader:
            loss = _step(net, trainer, bx, by)
            state.step += 1
            losses.append((state.step, loss))
            if preempt_at is not None and state.step == preempt_at:
                state.save()
                return losses
        state.epoch += 1
    return losses


def test_bitwise_identical_resume_mid_epoch(tmp_path):
    """THE tentpole oracle: preempt at step 4 of 12 (mid-epoch-0), restore
    in a fresh world, finish — the remaining 8 losses are float-identical
    to the uninterrupted run's."""
    bundle = str(tmp_path / "run.bundle")

    truth = _train(*_make_run(bundle), epochs=2)
    assert len(truth) == 12

    first = _train(*_make_run(bundle), epochs=2, preempt_at=4)
    assert [l for _, l in first] == [l for _, l in truth[:4]]
    assert os.path.exists(bundle) and os.path.exists(bundle + ".sha256")

    # "new process": fresh net/trainer/loader, different transient RNG use
    # before restore must not matter
    net, trainer, loader, state = _make_run(bundle)
    mx.np.random.uniform(size=(3,))  # perturb RNG pre-restore
    state.load()
    assert state.step == 4
    resumed = _train(net, trainer, loader, state, epochs=2)
    assert [s for s, _ in resumed] == [s for s, _ in truth[4:]]
    assert [l for _, l in resumed] == [l for _, l in truth[4:]], \
        "resumed losses diverged from the uninterrupted run"


def test_trainstate_rejects_torn_bundle(tmp_path):
    bundle = str(tmp_path / "t.bundle")
    net, trainer, loader, state = _make_run(bundle)
    state.step = 3
    state.save()
    blob = open(bundle, "rb").read()
    with open(bundle, "wb") as f:
        f.write(blob[:len(blob) // 2])  # torn write
    with pytest.raises(mx.base.MXNetError, match="checksum|corrupt"):
        mx.resilience.TrainState(net=net, trainer=trainer,
                                 loader=loader, path=bundle).load()


def test_trainstate_rejects_newer_format(tmp_path):
    bundle = str(tmp_path / "v.bundle")
    from mxnet_tpu import serialization
    serialization.atomic_write_bytes(
        bundle, pickle.dumps({"version": 99, "step": 1}))
    serialization.write_checksum(bundle)
    with pytest.raises(mx.base.MXNetError, match="newer"):
        mx.resilience.TrainState(path=bundle).load()


def test_trainstate_refuses_partial_param_restore(tmp_path):
    bundle = str(tmp_path / "p.bundle")
    net, trainer, loader, state = _make_run(bundle)
    d = state.state_dict()
    d["params"].popitem()
    from mxnet_tpu import serialization
    serialization.atomic_write_bytes(bundle, pickle.dumps(d))
    serialization.write_checksum(bundle)
    with pytest.raises(mx.base.MXNetError, match="missing parameter"):
        state.load()


# ---------------------------------------------------------------------------
# preemption: signals + injection + estimator handler
# ---------------------------------------------------------------------------

def test_signal_sets_preempt_flag():
    hooked = mx.resilience.install_signal_handlers()
    assert signal.SIGTERM in hooked
    assert not mx.resilience.preempt_requested()
    signal.raise_signal(signal.SIGTERM)
    assert mx.resilience.preempt_requested()
    mx.resilience.uninstall_signal_handlers()
    mx.resilience.clear_preempt()
    assert mx.fault.stats().get("resilience.preempt_signal") == 1


def test_preempt_injection_point_is_deterministic():
    mx.fault.configure("resilience.preempt:at=3")
    hits = [mx.resilience.preempt_requested(step=s) for s in (1, 2, 3)]
    assert hits == [False, False, True]


def test_estimator_resilience_handler_preempt_then_resume(tmp_path):
    """e2e through the fit loop: injection preempts at step 3, the bundle
    lands on disk, a fresh estimator auto-restores and finishes."""
    bundle = str(tmp_path / "est.bundle")
    rng = onp.random.RandomState(0)
    x = rng.randn(32, 4).astype("f")
    y = (rng.randn(32) > 0).astype("f")

    def make():
        mx.random.seed(7)
        ds = ArrayDataset(x, y)
        loader = DataLoader(ds, batch_size=8,
                            sampler=RandomSampler(32, seed=2),
                            num_workers=0)
        net = nn.Sequential()
        net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
        net.initialize()
        trainer = gluon.Trainer(net.collect_params(), "adam",
                                {"learning_rate": 0.05})
        e = est.Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                          trainer=trainer)
        rh = est.ResilienceHandler(bundle, loader=loader)
        return e, loader, rh

    e, loader, rh = make()
    mx.fault.configure("resilience.preempt:at=3")
    with pytest.raises(mx.resilience.Preempted) as ei:
        e.fit(loader, epochs=2, event_handlers=[rh])
    mx.fault.clear()
    assert ei.value.step == 3 and ei.value.path == bundle
    assert os.path.exists(bundle)

    e2, loader2, rh2 = make()
    e2.fit(loader2, epochs=2, event_handlers=[rh2])
    assert rh2.resumed
    assert rh2.state.step >= 8  # 2 epochs x 4 batches
    stats = mx.fault.stats()
    assert stats.get("resilience.bundle_save", 0) >= 1
    assert stats.get("resilience.bundle_restore", 0) >= 1


# ---------------------------------------------------------------------------
# collective retry-with-rejoin (single process; the 2-proc case is below)
# ---------------------------------------------------------------------------

def _solo_kv():
    from mxnet_tpu.kvstore.dist import DistKVStore
    kv = DistKVStore.__new__(DistKVStore)
    kv._nprocs, kv._rank, kv._gc = 1, 0, None
    kv._store, kv._updater = {}, None
    return kv


def test_collective_retry_recovers_one_timeout():
    kv = _solo_kv()
    mx.config.set("kvstore.async_timeout", 0.4)
    mx.config.set("kvstore.retry_backoff", 0.05)
    mx.fault.configure("kvstore.collective_timeout:at=1")
    kv.init("w", mx.np.zeros((3,)))
    out = mx.np.zeros((3,))
    kv.pushpull("w", mx.np.ones((3,)), out=out)
    onp.testing.assert_array_equal(out.asnumpy(), onp.ones(3, "f"))
    st = mx.fault.stats()
    assert st["resilience.collective_retry"] == 1
    assert st["kvstore.collective_timeout_raised"] == 1


def test_retry_max_zero_restores_raw_watchdog():
    from mxnet_tpu.kvstore.dist import CollectiveTimeout
    kv = _solo_kv()
    mx.config.set("kvstore.async_timeout", 0.3)
    mx.config.set("kvstore.retry_max", 0)
    mx.fault.configure("kvstore.collective_timeout:at=1")
    kv.init("w", mx.np.zeros((2,)))
    with pytest.raises(CollectiveTimeout):
        kv.push("w", mx.np.ones((2,)))
    assert "resilience.collective_retry" not in mx.fault.stats()


def test_exhausted_retries_escalate_worker_lost():
    kv = _solo_kv()
    mx.config.set("kvstore.async_timeout", 0.3)
    mx.config.set("kvstore.retry_backoff", 0.02)
    mx.config.set("kvstore.retry_max", 2)
    mx.fault.configure("kvstore.collective_timeout:prob=1.0")
    kv.init("w", mx.np.zeros((2,)))
    with pytest.raises(mx.resilience.WorkerLost) as ei:
        kv.push("w", mx.np.ones((2,)))
    e = ei.value
    assert (e.op, e.key, e.rank, e.nprocs) == ("allreduce", "w", 0, 1)
    assert e.attempts == 3  # initial + 2 retries
    assert isinstance(e.last, mx.base.MXNetError)
    assert mx.fault.stats()["resilience.collective_retry"] == 2


def test_collective_telemetry_counts_success_only():
    """Satellite fix: a failed allreduce must NOT inflate
    collective_total/payload_bytes; it lands in collective_errors."""
    from mxnet_tpu import telemetry
    from mxnet_tpu.kvstore.dist import CollectiveTimeout
    kv = _solo_kv()
    mx.config.set("kvstore.async_timeout", 0.3)
    mx.config.set("kvstore.retry_max", 0)
    kv.init("w", mx.np.zeros((2,)))
    telemetry.enable()
    telemetry.reset()
    try:
        mx.fault.configure("kvstore.collective_timeout:at=1")
        with pytest.raises(CollectiveTimeout):
            kv.push("w", mx.np.ones((2,)))
        mx.fault.clear()
        flat = telemetry.counters(aggregate=True)
        assert flat.get("kvstore.collective_total", 0) == 0
        assert flat.get("kvstore.payload_bytes_total", 0) == 0
        assert flat["kvstore.collective_errors_total"] == 1
        # armed-but-successful collective counts normally again
        mx.fault.configure("kvstore.collective_timeout:at=999")
        kv.push("w", mx.np.ones((2,)))
        flat = telemetry.counters(aggregate=True)
        assert flat["kvstore.collective_total"] == 1
        assert flat["kvstore.payload_bytes_total"] > 0
        assert flat["kvstore.collective_errors_total"] == 1
    finally:
        telemetry.disable()
        telemetry.reset()


# ---------------------------------------------------------------------------
# supervisor
# ---------------------------------------------------------------------------

def test_run_restarts_on_worker_lost_within_budget(tmp_path):
    bundle = str(tmp_path / "s.bundle")
    state = mx.resilience.TrainState(path=bundle)
    state.step = 5
    state.save()
    state.step = 99  # drift that the restore must undo

    calls = []

    def train_fn():
        calls.append(state.step)
        if len(calls) < 3:
            raise mx.resilience.WorkerLost("allreduce", "w", 0, 2,
                                           3, RuntimeError("gone"))
        return "done"

    assert mx.resilience.run(train_fn, state=state,
                             max_restarts=3) == "done"
    # first call saw the drifted step; each restart restored step=5
    assert calls == [99, 5, 5]
    st = mx.fault.stats()
    assert st["resilience.restart"] == 2


def test_run_reraises_past_budget():
    def always_lost():
        raise mx.resilience.WorkerLost("allreduce", "w", 0, 2,
                                       3, RuntimeError("gone"))

    with pytest.raises(mx.resilience.WorkerLost):
        mx.resilience.run(always_lost, max_restarts=1)
    assert mx.fault.stats()["resilience.restart_budget_exhausted"] == 1


def test_run_exit_on_preempt_uses_resume_sentinel():
    def preempted():
        raise mx.resilience.Preempted(path="x", step=1)

    with pytest.raises(SystemExit) as ei:
        mx.resilience.run(preempted, exit_on_preempt=True)
    assert ei.value.code == mx.resilience.RESUME_EXIT_CODE == 75
    # and without the flag the exception propagates for the caller
    with pytest.raises(mx.resilience.Preempted):
        mx.resilience.run(preempted)


# ---------------------------------------------------------------------------
# satellites: dist bring-up diagnostics; 2-process retry
# ---------------------------------------------------------------------------

def test_ensure_distributed_missing_rank_raises(monkeypatch):
    """`process_id=pid or 0` made every rank silently 0; now the missing
    env var is named instead."""
    from mxnet_tpu._dist_init import ensure_distributed
    monkeypatch.setenv("DMLC_PS_ROOT_URI", "127.0.0.1")
    monkeypatch.setenv("DMLC_NUM_WORKER", "2")
    monkeypatch.delenv("DMLC_WORKER_ID", raising=False)
    monkeypatch.delenv("JAX_PROCESS_ID", raising=False)
    with pytest.raises(mx.base.MXNetError,
                       match="DMLC_WORKER_ID.*JAX_PROCESS_ID"):
        ensure_distributed()


@pytest.mark.slow
def test_launch_two_process_collective_retry():
    """Real 2-process gloo world: rank 0's first collective is injected to
    time out; the retry layer re-barriers and the retried collective must
    complete with the exact sum on BOTH ranks."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"), "-n", "2",
         "--env", "MXTPU_DIST_RETRY_CASE=1",
         sys.executable, os.path.join(REPO, "tests", "dist_worker.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "RETRY_OK 0" in r.stdout and "RETRY_OK 1" in r.stdout, r.stdout

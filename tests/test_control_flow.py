"""npx control-flow operator value + gradient oracles.

Reference: src/operator/npx_control_flow.cc (foreach/while_loop/cond
subgraph ops) and tests/python/unittest/test_contrib_control_flow.py.
TPU-native: foreach lowers to lax.scan (jittable), while_loop/cond keep
the reference's dynamic eager semantics. Round-4 gap-fill: these ops only
had existence checks before.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np, npx
from mxnet_tpu.test_utils import check_numeric_gradient


def test_foreach_matches_python_loop():
    data = np.array(onp.random.RandomState(0).rand(5, 3).astype("float32"))
    init = np.zeros((3,))

    def body(x, state):
        new = state + x
        return new * 2, new

    outs, final = npx.foreach(body, data, init)
    # python-loop oracle
    st = onp.zeros(3, "float32")
    exp_outs = []
    for t in range(5):
        st = st + data.asnumpy()[t]
        exp_outs.append(st * 2)
    onp.testing.assert_allclose(outs.asnumpy(), onp.stack(exp_outs),
                                rtol=1e-6)
    onp.testing.assert_allclose(final.asnumpy(), st, rtol=1e-6)


def test_foreach_multiple_states():
    data = np.array(onp.arange(8, dtype="float32").reshape(4, 2))
    s0 = [np.zeros((2,)), np.ones((2,))]

    def body(x, states):
        a, b = states
        return x + a + b, [a + x, b * 1.0]

    outs, (fa, fb) = npx.foreach(body, data, s0)
    d = data.asnumpy()
    a, b = onp.zeros(2, "float32"), onp.ones(2, "float32")
    exp = []
    for t in range(4):
        exp.append(d[t] + a + b)
        a = a + d[t]
    onp.testing.assert_allclose(outs.asnumpy(), onp.stack(exp), rtol=1e-6)
    onp.testing.assert_allclose(fa.asnumpy(), a, rtol=1e-6)
    onp.testing.assert_allclose(fb.asnumpy(), b, rtol=1e-6)


def test_foreach_zero_length():
    outs, final = npx.foreach(lambda x, s: (x + s, s + x),
                              np.zeros((0, 3)), np.ones((3,)))
    assert outs.shape == (0, 3)
    onp.testing.assert_allclose(final.asnumpy(), 1.0)
    with mx.autograd.record():   # recorded path must behave identically
        outs, final = npx.foreach(lambda x, s: (x + s, s + x),
                                  np.zeros((0, 3)), np.ones((3,)))
    assert outs.shape == (0, 3)


def test_foreach_gradient():
    """Gradients flow through the scan (the subgraph-op backward the
    reference implements by unrolled-graph differentiation)."""
    data = onp.random.RandomState(1).rand(4, 3).astype("float32") + 0.1

    def f(xs):
        def body(x, state):
            return x * state, state + x
        outs, final = npx.foreach(body, xs[0], np.ones((3,)))
        return outs.sum() + final.sum()

    check_numeric_gradient(f, [np.array(data)], eps=1e-2, rtol=2e-2,
                           atol=1e-2)


def test_while_loop_semantics():
    """Dynamic trip count driven by data (reference while_loop has
    max_iterations + dynamic cond)."""
    outs, final = npx.while_loop(
        cond=lambda i, s: i < 5,
        func=lambda i, s: ((i * 10), (i + 1, s + i)),
        loop_vars=(np.array(0), np.array(0)),
        max_iterations=100)
    assert [int(o) for o in outs.asnumpy()] == [0, 10, 20, 30, 40]
    assert int(final[0].asnumpy()) == 5
    assert int(final[1].asnumpy()) == 0 + 1 + 2 + 3 + 4
    # max_iterations caps the loop
    outs, final = npx.while_loop(
        cond=lambda i: True,
        func=lambda i: (i, (i + 1,)),
        loop_vars=(np.array(0),), max_iterations=3)
    assert len(outs.asnumpy()) == 3


def test_cond_branches():
    x = np.array([2.0, -3.0])   # sum < 0 -> then-branch (a * 10)
    t = npx.cond(lambda a: a.sum() < 0, lambda a: a * 10, lambda a: a + 1,
                 [x])
    onp.testing.assert_allclose(t.asnumpy(), [20.0, -30.0])
    y = np.array([2.0, 3.0])    # sum > 0 -> else-branch (a + 1)
    e = npx.cond(lambda a: a.sum() < 0, lambda a: a * 10, lambda a: a + 1,
                 [y])
    onp.testing.assert_allclose(e.asnumpy(), [3.0, 4.0])
    # boolean predicate form
    r = npx.cond(True, lambda: np.ones((2,)), lambda: np.zeros((2,)))
    onp.testing.assert_allclose(r.asnumpy(), 1.0)


def test_foreach_under_jit():
    """foreach lowers to lax.scan, so a jitted wrapper compiles it."""
    import jax

    def step(xs_raw):
        def body(x, state):
            return x + state, state + x
        outs, final = npx.foreach(body, mx.np._wrap(xs_raw),
                                  np.zeros((2,)))
        return outs._data, final._data

    xs = onp.arange(6, dtype="float32").reshape(3, 2)
    outs, final = jax.jit(step)(xs)
    st = onp.zeros(2, "float32")
    exp = []
    for t in range(3):
        exp.append(xs[t] + st)
        st = st + xs[t]
    onp.testing.assert_allclose(onp.asarray(outs), onp.stack(exp),
                                rtol=1e-6)
    onp.testing.assert_allclose(onp.asarray(final), st, rtol=1e-6)


def test_foreach_closure_parameter_gradient():
    """Parameters the body closes over get gradients under record — the
    reference's imperative foreach semantics (round-4 review finding)."""
    w = np.array(onp.array([0.5, 2.0, 1.5], onp.float32))
    w.attach_grad()
    xs = np.array(onp.random.RandomState(2).rand(4, 3).astype("float32"))
    with mx.autograd.record():
        outs, final = npx.foreach(
            lambda x, s: (x * w + s, s + x), xs, np.zeros((3,)))
        loss = outs.sum()
    loss.backward()
    # d(loss)/dw = sum_t x_t (each out_t = x_t*w + s_t, s indep of w)
    onp.testing.assert_allclose(w.grad.asnumpy(),
                                xs.asnumpy().sum(axis=0), rtol=1e-5)

"""Multi-process dist_sync kvstore worker (run under tools/launch.py).

Mirrors the reference's tests/nightly/dist_sync_kvstore.py:40-50 check_diff:
every worker pushes known rank-dependent values and asserts the EXACT
reduced result, plus a gradient-compression case and an
optimizer-on-kvstore case. Prints DIST_OK <rank> on success.

With MXTPU_DIST_RETRY_CASE=1 the worker instead runs the elastic-retry
case: rank 0 arms the ``kvstore.collective_timeout`` chaos point so its
first collective "hangs" past a short watchdog deadline, the retry layer
backs off, re-barriers through the coordination service, and the retried
collective must complete with the exact sum.  Prints RETRY_OK <rank>.
"""
import os
import sys

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import kvstore


def check_eq(arr, expect, what):
    got = arr.asnumpy()
    assert onp.array_equal(got, onp.full(arr.shape, expect, got.dtype)), \
        f"{what}: expected {expect}, got {got.ravel()[:4]}"


def retry_main():
    """One injected timeout on rank 0 -> retry-with-rejoin -> exact sum."""
    kv = kvstore.create("dist_sync")
    n, rank = kv.num_workers, kv.rank
    assert n > 1, "launcher did not create a multi-process world"
    shape = (4, 3)
    if rank == 0:
        # rank 0's first collective times out fast and is retried; the
        # peers keep a long deadline so they simply wait out rank 0's
        # backoff+rejoin inside their own (single) collective attempt.
        mx.config.set("kvstore.async_timeout", 4.0)
        mx.config.set("kvstore.retry_backoff", 0.2)
        # the peer is already parked inside the collective, not at the
        # barrier — keep the best-effort rejoin wait short
        mx.config.set("kvstore.rejoin_timeout", 2.0)
        mx.fault.configure("kvstore.collective_timeout:at=1")
    else:
        mx.config.set("kvstore.async_timeout", 120.0)
    kv.init("r0", mx.np.zeros(shape))
    kv.push("r0", mx.np.full(shape, float(rank + 1)))
    out = mx.np.empty(shape)
    kv.pull("r0", out=out)
    check_eq(out, sum(range(1, n + 1)), "retried push/pull sum")
    if rank == 0:
        stats = mx.fault.stats()
        assert stats.get("resilience.collective_retry", 0) >= 1, stats
        assert stats.get("kvstore.collective_timeout_raised", 0) >= 1, stats
    print(f"RETRY_OK {rank}", flush=True)


def main():
    if os.environ.get("MXTPU_DIST_RETRY_CASE") == "1":
        retry_main()
        return
    kv = kvstore.create("dist_sync")
    n, rank = kv.num_workers, kv.rank
    assert n > 1, "launcher did not create a multi-process world"
    shape = (4, 3)

    # --- plain sync pushpull: exact sum across workers -------------------
    kv.init("w0", mx.np.zeros(shape))
    kv.push("w0", mx.np.full(shape, float(rank + 1)))
    out = mx.np.empty(shape)
    kv.pull("w0", out=out)
    check_eq(out, sum(range(1, n + 1)), "push/pull sum")

    kv.pushpull("w0", mx.np.ones(shape), out=out)
    check_eq(out, float(n), "pushpull")

    # --- gradient compression: 2-bit quantization + residual -------------
    kv2 = kvstore.DistKVStore("dist_sync")
    kv2.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv2.init("c0", mx.np.zeros(shape))
    # each worker pushes 0.3: below threshold -> quantized to 0, residual
    # keeps 0.3; second push of 0.3 crosses 0.5 -> quantized to +0.5 each
    kv2.push("c0", mx.np.full(shape, 0.3))
    out2 = mx.np.empty(shape)
    kv2.pull("c0", out=out2)
    check_eq(out2, 0.0, "2bit first push (all residual)")
    kv2.push("c0", mx.np.full(shape, 0.3))
    kv2.pull("c0", out=out2)
    check_eq(out2, 0.5 * n, "2bit second push (residual crossed threshold)")

    # --- optimizer on kvstore: identical state on every worker -----------
    kv3 = kvstore.DistKVStore("dist_sync")
    kv3.set_optimizer(mx.optimizer.create("sgd", learning_rate=0.1))
    kv3.init(3, mx.np.zeros(shape))
    kv3.push(3, mx.np.full(shape, 1.0))  # summed grad = n
    out3 = mx.np.empty(shape)
    kv3.pull(3, out=out3)
    check_eq(out3, -0.1 * n, "sgd on kvstore")

    # --- dist_async: immediate local updates, stale until pull -----------
    # (reference: kvstore_dist_server.h async ApplyUpdates — no
    # cross-worker aggregation at push time)
    kv4 = kvstore.create("dist_async")
    assert isinstance(kv4, kvstore.DistAsyncKVStore)
    kv4.set_optimizer(mx.optimizer.create("sgd", learning_rate=1.0))
    kv4.init("a0", mx.np.zeros(shape))
    # each worker pushes a DIFFERENT gradient; without a pull, the local
    # replica must reflect only the local update (staleness!)
    kv4.push("a0", mx.np.full(shape, float(rank + 1)))
    local = kv4._store["a0"].asnumpy()
    assert onp.allclose(local, -(rank + 1)), \
        f"async push leaked across workers: {local.ravel()[:3]}"
    # pull reconciles: every worker now sees the average of the replicas
    out4 = mx.np.empty(shape)
    kv4.pull("a0", out=out4)
    expect = -sum(range(1, n + 1)) / n
    check_eq(out4, expect, "async pull reconciliation")

    print(f"DIST_OK {rank}", flush=True)


if __name__ == "__main__":
    main()
    sys.exit(0)

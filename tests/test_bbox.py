"""Bounding-box op tests with numpy brute-force oracles
(reference: tests of src/operator/contrib/bounding_box.cc ops in
tests/python/unittest/test_contrib_operator.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import npx


def _iou_np(a, b):
    x1 = max(a[0], b[0]); y1 = max(a[1], b[1])
    x2 = min(a[2], b[2]); y2 = min(a[3], b[3])
    inter = max(0, x2 - x1) * max(0, y2 - y1)
    ua = (a[2] - a[0]) * (a[3] - a[1]) + (b[2] - b[0]) * (b[3] - b[1]) - inter
    return inter / ua if ua > 0 else 0.0


def test_box_iou_oracle():
    rs = onp.random.RandomState(0)
    a = rs.rand(5, 4).astype("float32"); a[:, 2:] += a[:, :2]
    b = rs.rand(7, 4).astype("float32"); b[:, 2:] += b[:, :2]
    got = npx.box_iou(mx.np.array(a), mx.np.array(b)).asnumpy()
    want = onp.array([[_iou_np(x, y) for y in b] for x in a])
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_box_iou_center_format():
    # center (0.75, 0.75, w=0.5, h=0.5) == corner (0.5, 0.5, 1.0, 1.0);
    # cross-compare against a half-overlapping corner box so a format
    # mix-up changes the answer
    center = onp.array([[0.75, 0.75, 0.5, 0.5]], "float32")
    corner = onp.array([[0.5, 0.5, 1.0, 1.0]], "float32")
    other = onp.array([[0.5, 0.5, 0.75, 1.0]], "float32")   # corner, IoU 0.5
    # box_iou converts BOTH args per `format`; pass `other` in center form
    other_center = onp.array([[0.625, 0.75, 0.25, 0.5]], "float32")
    got = npx.box_iou(mx.np.array(center), mx.np.array(other_center),
                      format="center")
    want = npx.box_iou(mx.np.array(corner), mx.np.array(other),
                       format="corner")
    onp.testing.assert_allclose(got.asnumpy(), want.asnumpy(), rtol=1e-6)
    onp.testing.assert_allclose(want.asnumpy(), [[0.5]], rtol=1e-6)


def test_box_nms_suppresses_overlaps():
    # rows: [cls_id, score, x1, y1, x2, y2]
    data = onp.array([
        [0, 0.9, 0.0, 0.0, 1.0, 1.0],
        [0, 0.8, 0.05, 0.05, 1.05, 1.05],   # overlaps the first -> suppressed
        [0, 0.7, 2.0, 2.0, 3.0, 3.0],       # far away -> kept
        [1, 0.6, 0.0, 0.0, 1.0, 1.0],       # other class -> kept
    ], "float32")
    out = npx.box_nms(mx.np.array(data), overlap_thresh=0.5,
                      id_index=0).asnumpy()
    # reference convention: rows sorted by score desc; suppressed rows
    # entirely -1
    assert out[0, 1] == pytest.approx(0.9)
    onp.testing.assert_allclose(out[1], -onp.ones(6))   # suppressed row
    assert out[2, 1] == pytest.approx(0.7)
    assert out[3, 1] == pytest.approx(0.6)
    onp.testing.assert_allclose(out[0, 2:], data[0, 2:])  # coords intact
    # force_suppress ignores class ids
    out2 = npx.box_nms(mx.np.array(data), overlap_thresh=0.5, id_index=0,
                       force_suppress=True).asnumpy()
    onp.testing.assert_allclose(out2[3], -onp.ones(6))


def test_box_nms_valid_thresh_and_topk():
    data = onp.array([
        [0.9, 0.0, 0.0, 1.0, 1.0],
        [0.5, 2.0, 2.0, 3.0, 3.0],
        [0.05, 4.0, 4.0, 5.0, 5.0],          # below valid_thresh
    ], "float32")
    out = npx.box_nms(mx.np.array(data), overlap_thresh=0.5,
                      valid_thresh=0.1, topk=2, coord_start=1,
                      score_index=0).asnumpy()
    assert out[0, 0] == pytest.approx(0.9)
    assert out[1, 0] == pytest.approx(0.5)
    onp.testing.assert_allclose(out[2], -onp.ones(5))


def test_box_nms_sorts_by_score():
    """Unsorted input comes back score-sorted (reference convention) so
    the post-NMS `slice first k` pattern works."""
    data = onp.array([
        [0.2, 5.0, 5.0, 6.0, 6.0],
        [0.9, 0.0, 0.0, 1.0, 1.0],
        [0.5, 2.0, 2.0, 3.0, 3.0],
    ], "float32")
    out = npx.box_nms(mx.np.array(data), overlap_thresh=0.5,
                      coord_start=1, score_index=0).asnumpy()
    onp.testing.assert_allclose(out[:, 0], [0.9, 0.5, 0.2])
    onp.testing.assert_allclose(out[0, 1:], data[1, 1:])


def test_box_decode_clips_in_log_space():
    """clip applies to the scaled log-delta before exp (reference
    BoxDecode), not to the decoded width."""
    anchors = onp.array([[[0.0, 0.0, 1.0, 1.0]]], "float32")
    pred = onp.array([[[0.0, 0.0, 30.0, 0.0]]], "float32")  # dw*std2 = 6
    out = npx.box_decode(mx.np.array(pred), mx.np.array(anchors),
                         clip=2.0, format="corner").asnumpy()
    w = out[0, 0, 2] - out[0, 0, 0]
    onp.testing.assert_allclose(w, onp.exp(2.0), rtol=1e-5)


def test_box_nms_batched():
    rs = onp.random.RandomState(1)
    data = rs.rand(2, 3, 10, 6).astype("float32")
    data[..., 2:4] *= 0.5
    data[..., 4:] = data[..., 2:4] + 0.5
    out = npx.box_nms(mx.np.array(data), overlap_thresh=0.9)
    assert out.shape == data.shape


def test_box_encode_decode_roundtrip():
    anchors = onp.array([[[0.0, 0.0, 1.0, 1.0],
                          [1.0, 1.0, 3.0, 2.0]]], "float32")
    refs = onp.array([[[0.1, 0.1, 1.2, 0.9],
                       [1.1, 0.8, 2.9, 2.2]]], "float32")
    samples = onp.ones((1, 2), "float32")
    matches = onp.array([[0, 1]], "float32")
    targets, masks = npx.box_encode(
        mx.np.array(samples), mx.np.array(matches),
        mx.np.array(anchors), mx.np.array(refs))
    assert masks.asnumpy().min() == 1.0
    decoded = npx.box_decode(targets, mx.np.array(anchors),
                             format="corner").asnumpy()
    onp.testing.assert_allclose(decoded, refs, rtol=1e-4, atol=1e-5)


def test_bipartite_matching():
    score = onp.array([[0.9, 0.2, 0.1],
                       [0.8, 0.7, 0.3]], "float32")
    rows, cols = npx.bipartite_matching(mx.np.array(score), threshold=0.05)
    # greedy: (0,0)=0.9 first, then (1,1)=0.7
    onp.testing.assert_allclose(rows.asnumpy(), [0.0, 1.0])
    onp.testing.assert_allclose(cols.asnumpy(), [0.0, 1.0, -1.0])


def test_bipartite_matching_threshold_blocks_weak():
    score = onp.array([[0.9, 0.0], [0.0, 0.01]], "float32")
    rows, cols = npx.bipartite_matching(mx.np.array(score), threshold=0.05)
    onp.testing.assert_allclose(rows.asnumpy(), [0.0, -1.0])
    onp.testing.assert_allclose(cols.asnumpy(), [0.0, -1.0])


def test_bbox_transform_utils():
    from mxnet_tpu.gluon.contrib.data.vision import (
        bbox_crop, bbox_flip, bbox_resize)
    boxes = onp.array([[10, 10, 30, 40, 1.0],
                       [50, 60, 90, 100, 2.0]], "float32")
    # flip x within a 100x120 image
    flipped = bbox_flip(boxes, (100, 120), flip_x=True)
    onp.testing.assert_allclose(flipped[0, :4], [70, 10, 90, 40])
    assert flipped[0, 4] == 1.0  # extra columns preserved
    # crop to window (0,0,60,80): second box clipped, translated
    cropped = bbox_crop(boxes, (0, 0, 60, 80))
    onp.testing.assert_allclose(cropped[1, :4], [50, 60, 60, 80])
    # crop dropping outside-center boxes
    tight = bbox_crop(boxes, (0, 0, 35, 45), allow_outside_center=False)
    assert len(tight) == 1
    # resize from 100x120 to 50x60 halves coordinates
    resized = bbox_resize(boxes, (100, 120), (50, 60))
    onp.testing.assert_allclose(resized[0, :4], [5, 5, 15, 20])


def test_image_bbox_transforms():
    from mxnet_tpu.gluon.contrib.data.vision import (
        ImageBboxCrop, ImageBboxResize, ImageBboxRandomFlipLeftRight)
    rs = onp.random.RandomState(0)
    img = rs.randint(0, 255, (40, 60, 3)).astype(onp.uint8)
    boxes = onp.array([[10, 10, 30, 30]], "float32")
    ci, cb = ImageBboxCrop((5, 5, 30, 30))(img, boxes)
    assert ci.shape == (30, 30, 3)
    onp.testing.assert_allclose(cb[0], [5, 5, 25, 25])
    ri, rb = ImageBboxResize(30, 20)(img, boxes)
    assert ri.shape[:2] == (20, 30)
    onp.testing.assert_allclose(rb[0], [5, 5, 15, 15])
    fi, fb = ImageBboxRandomFlipLeftRight(p=1.0)(img, boxes)
    onp.testing.assert_allclose(fb[0], [30, 10, 50, 30])
    onp.testing.assert_array_equal(fi, img[:, ::-1])


def test_box_decode_no_clip_by_default():
    """clip=-1 (default) must not cap large deltas (reference: clip<=0
    means no clipping in _contrib_box_decode)."""
    anchors = onp.array([[[0.0, 0.0, 1.0, 1.0]]], "float32")
    pred = onp.array([[[0.0, 0.0, 60.0, 0.0]]], "float32")  # dw*std2 = 12
    out = npx.box_decode(mx.np.array(pred), mx.np.array(anchors),
                         format="corner").asnumpy()
    w = out[0, 0, 2] - out[0, 0, 0]
    onp.testing.assert_allclose(w, onp.exp(12.0), rtol=1e-4)

"""NDArray core semantics (reference: tests/python/unittest/test_ndarray.py
+ test_numpy_ndarray.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np
from mxnet_tpu.test_utils import assert_almost_equal


def test_creation():
    a = np.array([[1, 2], [3, 4]], dtype="float32")
    assert a.shape == (2, 2)
    assert a.dtype == onp.float32
    assert a.size == 4
    assert a.ndim == 2
    b = np.zeros((3, 4))
    assert b.shape == (3, 4)
    assert float(b.sum()) == 0
    c = np.ones((2, 3), dtype="int32")
    assert c.dtype == onp.int32
    d = np.full((2, 2), 7.0)
    assert float(d[0, 0]) == 7.0
    e = np.arange(10)
    assert e.shape == (10,)
    f = np.eye(3)
    assert float(f.sum()) == 3.0


def test_arithmetic():
    a = np.array([1.0, 2.0, 3.0])
    b = np.array([4.0, 5.0, 6.0])
    assert_almost_equal(a + b, onp.array([5, 7, 9]))
    assert_almost_equal(a - b, onp.array([-3, -3, -3]))
    assert_almost_equal(a * b, onp.array([4, 10, 18]))
    assert_almost_equal(b / a, onp.array([4, 2.5, 2]))
    assert_almost_equal(a ** 2, onp.array([1, 4, 9]))
    assert_almost_equal(2 + a, onp.array([3, 4, 5]))
    assert_almost_equal(2 * a, onp.array([2, 4, 6]))
    assert_almost_equal(-a, onp.array([-1, -2, -3]))
    assert_almost_equal(a @ b, onp.array(32.0))


def test_comparison_ops():
    a = np.array([1.0, 2.0, 3.0])
    b = np.array([3.0, 2.0, 1.0])
    assert (a == b).asnumpy().tolist() == [False, True, False]
    assert (a < b).asnumpy().tolist() == [True, False, False]
    assert (a >= b).asnumpy().tolist() == [False, True, True]


def test_inplace_rebind_version():
    a = np.array([1.0, 2.0])
    v0 = a.version
    a += 1
    assert a.version == v0 + 1
    assert_almost_equal(a, onp.array([2.0, 3.0]))
    a[:] = 5.0
    assert_almost_equal(a, onp.array([5.0, 5.0]))
    assert a.version == v0 + 2


def test_setitem():
    a = np.zeros((3, 3))
    a[1, 1] = 5.0
    assert float(a[1, 1]) == 5.0
    a[0] = onp.array([1.0, 2.0, 3.0])
    assert_almost_equal(a[0], onp.array([1, 2, 3]))
    a[:, 2] = 9.0
    assert float(a[2, 2]) == 9.0


def test_indexing():
    a = np.arange(24).reshape(2, 3, 4)
    assert a[1, 2, 3].item() == 23
    assert a[0].shape == (3, 4)
    assert a[:, 1].shape == (2, 4)
    assert a[..., 0].shape == (2, 3)
    assert a[a > 10].shape == (13,)
    idx = np.array([0, 1], dtype="int32")
    assert a[idx].shape == (2, 3, 4)


def test_methods():
    a = np.arange(6, dtype="float32").reshape(2, 3)
    assert a.T.shape == (3, 2)
    assert a.reshape(3, 2).shape == (3, 2)
    assert a.reshape(-1).shape == (6,)
    assert a.flatten().shape == (6,)
    assert float(a.sum()) == 15
    assert float(a.mean()) == 2.5
    assert float(a.max()) == 5
    assert int(a.argmax()) == 5
    assert a.sum(axis=0).shape == (3,)
    assert a.sum(axis=1, keepdims=True).shape == (2, 1)
    assert a.expand_dims(0).shape == (1, 2, 3)
    assert a.squeeze(0).shape if False else True
    assert a.astype("int32").dtype == onp.int32
    assert a.copy().shape == (2, 3)


def test_asnumpy_wait():
    a = np.ones((4, 4))
    b = (a * 2).wait_to_read()
    assert_almost_equal(b, onp.full((4, 4), 2.0))
    mx.waitall()


def test_context_placement():
    a = np.ones((2, 2), ctx=mx.cpu())
    assert a.ctx.device_type in ("cpu", "tpu")
    b = a.as_in_ctx(mx.cpu(0))
    assert_almost_equal(a, b)


def test_copyto():
    a = np.ones((2, 2))
    b = np.zeros((2, 2))
    a.copyto(b)
    assert_almost_equal(b, onp.ones((2, 2)))


def test_generated_namespace():
    a = np.array([1.0, 4.0, 9.0])
    assert_almost_equal(np.sqrt(a), onp.array([1, 2, 3]))
    assert_almost_equal(np.exp(np.zeros(3)), onp.ones(3))
    assert_almost_equal(np.maximum(a, 5.0), onp.array([5, 5, 9]))
    assert_almost_equal(np.sin(np.zeros(2)), onp.zeros(2))
    out = np.split(np.arange(6), 3)
    assert len(out) == 3
    assert_almost_equal(np.concatenate([a, a]), onp.tile([1, 4, 9], 2))
    st = np.stack([a, a], axis=1)
    assert st.shape == (3, 2)
    assert np.linalg.norm(np.ones(4)).item() == pytest.approx(2.0)


def test_einsum_where():
    a = np.ones((2, 3))
    b = np.ones((3, 4))
    c = np.einsum("ij,jk->ik", a, b)
    assert c.shape == (2, 4)
    assert float(c[0, 0]) == 3.0
    w = np.where(np.array([True, False]), np.ones(2), np.zeros(2))
    assert w.asnumpy().tolist() == [1.0, 0.0]


def test_random():
    mx.random.seed(42)
    a = np.random.uniform(0, 1, size=(100,))
    mx.random.seed(42)
    b = np.random.uniform(0, 1, size=(100,))
    assert_almost_equal(a, b)
    c = np.random.normal(0, 1, size=(1000,))
    assert abs(float(c.mean())) < 0.2
    d = np.random.randint(0, 10, size=(50,))
    assert int(d.max()) < 10
    assert np.random.choice(5, size=(3,)).shape == (3,)


def test_save_load(tmp_path):
    from mxnet_tpu import npx
    arrs = {"w": np.ones((3, 3)), "b": np.zeros(3)}
    path = str(tmp_path / "params.npz")
    npx.save(path, arrs)
    loaded = npx.load(path)
    assert set(loaded) == {"w", "b"}
    assert_almost_equal(loaded["w"], onp.ones((3, 3)))


def test_dlpack_numpy_interop():
    a = np.ones((2, 2))
    n = onp.asarray(a)
    assert n.shape == (2, 2)
    t = np.array(onp.arange(4).reshape(2, 2))
    assert t.shape == (2, 2)


def test_grouped_deconvolution_vs_torch():
    """Grouped transposed conv vs torch oracle (reference:
    src/operator/nn/deconvolution.cc supports num_group)."""
    import torch
    from mxnet_tpu import npx
    for g, cin, cout, stride, pad in [(1, 4, 6, 2, 1), (2, 4, 6, 2, 1),
                                      (4, 8, 8, 3, 2)]:
        x = onp.random.randn(2, cin, 9, 9).astype("float32")
        w = onp.random.randn(cin, cout // g, 3, 3).astype("float32")
        b = onp.random.randn(cout).astype("float32")
        ref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=stride, padding=pad, groups=g).numpy()
        out = npx.deconvolution(
            np.array(x), np.array(w), np.array(b), kernel=(3, 3),
            stride=(stride, stride), pad=(pad, pad), num_filter=cout,
            num_group=g, no_bias=False).asnumpy()
        assert out.shape == ref.shape
        assert_almost_equal(out, ref, atol=1e-4, rtol=1e-4)


def test_rng_key_survives_external_jit():
    """Drawing keys inside an external jit trace must not clobber the
    process-global key (regression: tracer leak) and the fallback stream
    must not collide with the seeded eager stream."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu import random as r

    mx.random.seed(1)
    eager_key = onp.asarray(r._next_key())

    @jax.jit
    def f(x):
        return x * jax.random.uniform(r._next_key(), x.shape)

    f(jnp.ones((4,)))
    # global key still concrete and usable
    a = np.random.uniform(size=(8,)).asnumpy()
    b = np.random.uniform(size=(8,)).asnumpy()
    assert (a != b).any()
    # fallback stream disjoint from eager stream
    fb = onp.asarray(jax.random.fold_in(jax.random.PRNGKey(0x7A17BA5E), 1))
    assert not onp.array_equal(fb, eager_key)
    mx.random.seed(0)
    assert r._fallback_n == 0


def test_out_writes_through():
    """mx.np.op(..., out=c) must write the result into c's buffer —
    reference generated-wrapper semantics (ndarray/register.py:171).
    Round-3 verdict: silent drop is the worst option."""
    a = np.ones((3,))
    b = np.full((3,), 2.0)
    c = np.zeros((3,))
    alias = c
    r = np.add(a, b, out=c)
    assert r is c
    onp.testing.assert_allclose(alias.asnumpy(), 3.0)  # alias observes it
    assert c.version == 1

    # dtype cast on write-through: result cast to the destination dtype
    d = np.zeros((3,), dtype="int32")
    np.multiply(a, b, out=d)
    assert d.dtype == onp.int32
    onp.testing.assert_allclose(d.asnumpy(), 2)

    # shape mismatch raises (not silent)
    with pytest.raises(ValueError):
        np.add(a, b, out=np.zeros((4,)))
    # non-array destination raises
    with pytest.raises(TypeError):
        np.add(a, b, out=onp.zeros(3))


def test_out_on_explicit_and_legacy_ops():
    a = np.arange(6, dtype="float32").reshape(2, 3)
    dest = np.zeros((4, 3))
    r = np.concatenate([a, a], axis=0, out=dest)
    assert r is dest
    onp.testing.assert_allclose(dest.asnumpy(), onp.concatenate(
        [onp.arange(6).reshape(2, 3)] * 2, axis=0))

    d = mx.nd.zeros((2, 3))
    mx.nd.broadcast_add(a, np.ones((1, 3)), out=d)
    onp.testing.assert_allclose(
        d.asnumpy(), onp.arange(6).reshape(2, 3) + 1)


def test_out_under_autograd():
    """Gradients flow through an out= destination like any op output."""
    x = np.ones((3,))
    x.attach_grad()
    dest = np.zeros((3,))
    with mx.autograd.record():
        y = np.multiply(x, np.full((3,), 4.0), out=dest)
        z = (y * y).sum()
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), 2 * 4.0 * 4.0 * 1.0)


def test_ndarray_fluent_method_tail():
    """Legacy fluent methods (reference generates ~80 per-op NDArray
    methods); fixed allowlist keeps hasattr contracts intact."""
    a = mx.np.array([[1.0, 3.0], [2.0, 0.0]])
    onp.testing.assert_allclose(a.log_softmax().asnumpy(),
                                onp.log(onp.exp(a.asnumpy()) /
                                        onp.exp(a.asnumpy()).sum(-1,
                                                keepdims=True)),
                                rtol=1e-5)
    assert float(a.norm().asnumpy()) == pytest.approx(3.7416575)
    assert a.slice_axis(axis=1, begin=0, end=1).shape == (2, 1)
    onp.testing.assert_allclose(a.pick(mx.np.array([1, 0])).asnumpy(),
                                [3.0, 2.0])
    onp.testing.assert_allclose(a.flip(axis=1).asnumpy(),
                                [[3, 1], [0, 2]])
    assert not hasattr(a, "not_an_op")
    assert not hasattr(a, "dtype_")  # only the fixed list resolves
    # autograd flows through fluent calls
    a.attach_grad()
    with mx.autograd.record():
        out = a.sigmoid().sum()
    out.backward()
    assert a.grad is not None and a.grad.shape == a.shape

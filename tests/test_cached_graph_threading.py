"""Thread-safety + bounded signature cache for the compiled executor.

Reference: the reference ships a dedicated thread-safe cached op
(src/imperative/cached_op_threadsafe.cc) and engine concurrency tests
(tests/cpp/engine/threaded_engine_test.cc); CachedOpConfig bounds recompile
blowup (src/imperative/cached_op.h:412-459).
"""
import threading

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, np
from mxnet_tpu.gluon import nn, HybridBlock
from mxnet_tpu.test_utils import assert_almost_equal


class _ScaledDense(HybridBlock):
    def __init__(self):
        super().__init__()
        self.fc = nn.Dense(4)

    def forward(self, x, scale=1.0):
        return self.fc(x) * scale


def test_signature_cache_bounded():
    old = mx.config.get("cached_graph.max_signatures")
    mx.config.set("cached_graph.max_signatures", 4)
    try:
        net = _ScaledDense()
        net.initialize()
        net.hybridize()
        x = np.ones((2, 3))
        # 20 distinct python scalars -> 20 signatures without the bound
        for i in range(20):
            y = net(x, scale=float(i))
            assert_almost_equal(y, net.fc(x).asnumpy() * float(i), rtol=1e-5)
        cg = list(net._cached_graphs.values())[0]
        assert len(cg._signatures) <= 4
        assert len(cg._out_trees) <= 4
    finally:
        mx.config.set("cached_graph.max_signatures", old)


class _ListScaled(HybridBlock):
    def __init__(self):
        super().__init__()
        self.fc = nn.Dense(2)

    def forward(self, x, tag=""):
        # tag is a static python leaf: only its presence in the signature
        # matters (a long string must be digested, not kept verbatim)
        return self.fc(x) * (2.0 if tag.startswith("a") else 1.0)


def test_long_static_repr_hashed():
    net = _ListScaled()
    net.initialize()
    net.hybridize()
    x = np.ones((2, 3))
    long_static = "a" * 300  # repr >> 128 chars, single atomic leaf
    y = net(x, tag=long_static)
    y = net(x, tag=long_static)
    assert onp.isfinite(y.asnumpy()).all()
    cg = list(net._cached_graphs.values())[0]
    hashed = [tok for key in cg._signatures for tok in key[1]
              if tok.startswith("H")]
    assert hashed, "digest path never exercised"
    for key in cg._signatures:
        for tok in key[1]:
            assert len(tok) <= 129


from conftest import retry


@retry(3)  # load-sensitive: 4 threads x 8 shapes on a 1-core CI box can
# starve a replay long enough to trip the trace-retry budget; one
# full-suite flake observed, never reproduced in isolation (16 runs)
def test_concurrent_inference_many_shapes():
    net = nn.Dense(8, activation='relu')
    net.initialize()
    net.hybridize()
    shapes = [(1, 5), (2, 5), (3, 5), (4, 5), (5, 5), (6, 5), (7, 5), (8, 5)]
    inputs = {s: onp.random.RandomState(s[0]).rand(*s).astype(onp.float32)
              for s in shapes}
    # one warm-up forward: deferred parameter init must complete before
    # concurrent use (same contract as the reference's thread-safe CachedOp)
    net(np.array(inputs[shapes[0]]))
    # eager oracle with copied params
    net2 = nn.Dense(8, activation='relu')
    net2.initialize()
    net2(np.array(inputs[shapes[0]]))
    for (_, p1), (_, p2) in zip(net.collect_params().items(),
                                net2.collect_params().items()):
        p2.set_data(p1.data())
    want = {s: net2(np.array(v)).asnumpy() for s, v in inputs.items()}

    errors = []

    def worker(tid):
        try:
            for rep in range(6):
                for s in shapes:
                    y = net(np.array(inputs[s])).asnumpy()
                    onp.testing.assert_allclose(y, want[s], rtol=1e-5,
                                                atol=1e-6)
        except Exception as e:  # noqa: BLE001
            errors.append((tid, e))

    threads = [threading.Thread(target=worker, args=(t,)) for t in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errors, errors


def test_concurrent_with_cache_flushes():
    # threads race through repeated flushes: cap of 2 with 8 shapes forces
    # evictions mid-flight; the retry path must keep every result correct
    old = mx.config.get("cached_graph.max_signatures")
    mx.config.set("cached_graph.max_signatures", 2)
    try:
        net = nn.Dense(4)
        net.initialize()
        net.hybridize()
        xs = {k: onp.full((k, 3), 0.5, onp.float32) for k in range(1, 9)}
        net2 = nn.Dense(4)
        net2.initialize()
        net(np.array(xs[1]))  # warm-up: complete deferred init pre-threads
        net2(np.array(xs[1]))
        for (_, p1), (_, p2) in zip(net.collect_params().items(),
                                    net2.collect_params().items()):
            p2.set_data(p1.data())
        want = {k: net2(np.array(v)).asnumpy() for k, v in xs.items()}
        errors = []

        def worker(tid):
            try:
                for rep in range(4):
                    for k in range(1, 9):
                        y = net(np.array(xs[k])).asnumpy()
                        onp.testing.assert_allclose(y, want[k], rtol=1e-5,
                                                    atol=1e-6)
            except Exception as e:  # noqa: BLE001
                errors.append((tid, e))

        threads = [threading.Thread(target=worker, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, errors
    finally:
        mx.config.set("cached_graph.max_signatures", old)

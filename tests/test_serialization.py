"""Serialization tests: safetensors format + Block round-trips
(reference: src/serialization/cnpy.cc territory; safetensors is the
TPU-native portable replacement for the legacy NDArray binary format)."""
import struct, json, os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serialization as ser
from mxnet_tpu.gluon import nn


def test_safetensors_roundtrip(tmp_path):
    rs = onp.random.RandomState(0)
    tensors = {
        "a": rs.randn(3, 4).astype("float32"),
        "b": rs.randint(0, 100, (5,)).astype("int64"),
        "c": onp.asarray(True),
        "d": rs.randn(2, 2).astype("float16"),
    }
    p = str(tmp_path / "t.safetensors")
    ser.save_safetensors(p, tensors, metadata={"framework": "mxnet_tpu"})
    back, meta = ser.load_safetensors(p, return_metadata=True)
    assert meta["framework"] == "mxnet_tpu"
    assert set(back) == set(tensors)
    for k in tensors:
        assert back[k].dtype == tensors[k].dtype
        onp.testing.assert_array_equal(back[k], tensors[k])


def test_safetensors_bf16(tmp_path):
    import ml_dtypes
    arr = onp.arange(6, dtype=onp.float32).reshape(2, 3).astype(
        ml_dtypes.bfloat16)
    p = str(tmp_path / "b.safetensors")
    ser.save_safetensors(p, {"w": arr})
    back = ser.load_safetensors(p)["w"]
    assert back.dtype == arr.dtype
    onp.testing.assert_array_equal(back, arr)


def test_safetensors_wire_format(tmp_path):
    """The on-disk layout must follow the public spec: u64 header length,
    JSON header with dtype/shape/data_offsets, raw LE buffers."""
    x = onp.asarray([[1.5, -2.0]], "float32")
    p = str(tmp_path / "w.safetensors")
    ser.save_safetensors(p, {"x": x})
    raw = open(p, "rb").read()
    (hlen,) = struct.unpack("<Q", raw[:8])
    header = json.loads(raw[8:8 + hlen])
    assert header["x"]["dtype"] == "F32"
    assert header["x"]["shape"] == [1, 2]
    lo, hi = header["x"]["data_offsets"]
    vals = onp.frombuffer(raw[8 + hlen + lo:8 + hlen + hi], "<f4")
    onp.testing.assert_array_equal(vals, [1.5, -2.0])


def test_block_save_load_safetensors(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    x = mx.np.ones((2, 5))
    want = net(x).asnumpy()
    p = str(tmp_path / "model.safetensors")
    net.save_parameters(p)

    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net2.initialize()
    net2(x)
    net2.load_parameters(p)
    onp.testing.assert_allclose(net2(x).asnumpy(), want, rtol=1e-6)


def test_block_save_load_npz_still_works(tmp_path):
    net = nn.Dense(4)
    net.initialize()
    x = mx.np.ones((1, 3))
    want = net(x).asnumpy()
    p = str(tmp_path / "m.params")
    net.save_parameters(p)
    net2 = nn.Dense(4)
    net2.initialize()
    net2(x)
    net2.load_parameters(p)
    onp.testing.assert_allclose(net2(x).asnumpy(), want, rtol=1e-6)

"""Serialization tests: safetensors format + Block round-trips
(reference: src/serialization/cnpy.cc territory; safetensors is the
TPU-native portable replacement for the legacy NDArray binary format)."""
import struct, json, os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import serialization as ser
from mxnet_tpu.gluon import nn


def test_safetensors_roundtrip(tmp_path):
    rs = onp.random.RandomState(0)
    tensors = {
        "a": rs.randn(3, 4).astype("float32"),
        "b": rs.randint(0, 100, (5,)).astype("int64"),
        "c": onp.asarray(True),
        "d": rs.randn(2, 2).astype("float16"),
    }
    p = str(tmp_path / "t.safetensors")
    ser.save_safetensors(p, tensors, metadata={"framework": "mxnet_tpu"})
    back, meta = ser.load_safetensors(p, return_metadata=True)
    assert meta["framework"] == "mxnet_tpu"
    assert set(back) == set(tensors)
    for k in tensors:
        assert back[k].dtype == tensors[k].dtype
        onp.testing.assert_array_equal(back[k], tensors[k])


def test_safetensors_bf16(tmp_path):
    import ml_dtypes
    arr = onp.arange(6, dtype=onp.float32).reshape(2, 3).astype(
        ml_dtypes.bfloat16)
    p = str(tmp_path / "b.safetensors")
    ser.save_safetensors(p, {"w": arr})
    back = ser.load_safetensors(p)["w"]
    assert back.dtype == arr.dtype
    onp.testing.assert_array_equal(back, arr)


def test_safetensors_wire_format(tmp_path):
    """The on-disk layout must follow the public spec: u64 header length,
    JSON header with dtype/shape/data_offsets, raw LE buffers."""
    x = onp.asarray([[1.5, -2.0]], "float32")
    p = str(tmp_path / "w.safetensors")
    ser.save_safetensors(p, {"x": x})
    raw = open(p, "rb").read()
    (hlen,) = struct.unpack("<Q", raw[:8])
    header = json.loads(raw[8:8 + hlen])
    assert header["x"]["dtype"] == "F32"
    assert header["x"]["shape"] == [1, 2]
    lo, hi = header["x"]["data_offsets"]
    vals = onp.frombuffer(raw[8 + hlen + lo:8 + hlen + hi], "<f4")
    onp.testing.assert_array_equal(vals, [1.5, -2.0])


def test_block_save_load_safetensors(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net.initialize()
    x = mx.np.ones((2, 5))
    want = net(x).asnumpy()
    p = str(tmp_path / "model.safetensors")
    net.save_parameters(p)

    net2 = nn.HybridSequential()
    net2.add(nn.Dense(8, activation="relu"), nn.Dense(3))
    net2.initialize()
    net2(x)
    net2.load_parameters(p)
    onp.testing.assert_allclose(net2(x).asnumpy(), want, rtol=1e-6)


def test_block_save_load_npz_still_works(tmp_path):
    net = nn.Dense(4)
    net.initialize()
    x = mx.np.ones((1, 3))
    want = net(x).asnumpy()
    p = str(tmp_path / "m.params")
    net.save_parameters(p)
    net2 = nn.Dense(4)
    net2.initialize()
    net2(x)
    net2.load_parameters(p)
    onp.testing.assert_allclose(net2(x).asnumpy(), want, rtol=1e-6)


def test_legacy_params_roundtrip(tmp_path):
    rs = onp.random.RandomState(0)
    tensors = {
        "arg:weight": rs.randn(4, 3).astype("float32"),
        "arg:bias": rs.randn(4).astype("float64"),
        "aux:mean": rs.randint(0, 9, (2, 2)).astype("int64"),
        "scalar": onp.float32(2.5).reshape(()),   # 0-d -> V3 record
    }
    p = str(tmp_path / "legacy.params")
    ser.save_legacy_params(p, tensors)
    back = ser.load_legacy_params(p)
    assert set(back) == set(tensors)
    for k in tensors:
        assert back[k].dtype == tensors[k].dtype, k
        onp.testing.assert_array_equal(back[k], tensors[k])


def test_legacy_params_wire_layout(tmp_path):
    """Byte-level check against the reference layout
    (ndarray.cc: 0x112 header, V2 magic, stype, i32 ndim + i64 dims,
    context, type_flag, raw data, then names)."""
    x = onp.asarray([[1.0, 2.0]], "float32")
    p = str(tmp_path / "w.params")
    ser.save_legacy_params(p, {"x": x})
    raw = open(p, "rb").read()
    header, reserved, count = struct.unpack_from("<QQQ", raw, 0)
    assert header == 0x112 and reserved == 0 and count == 1
    off = 24
    magic, stype, ndim = struct.unpack_from("<Iii", raw, off)
    assert magic == 0xF993FAC9 and stype == 0 and ndim == 2
    off += 12
    dims = struct.unpack_from("<qq", raw, off)
    assert dims == (1, 2)
    off += 16
    dev_type, dev_id, type_flag = struct.unpack_from("<iii", raw, off)
    assert dev_type == 1 and type_flag == 0       # cpu, float32
    off += 12
    onp.testing.assert_array_equal(
        onp.frombuffer(raw, "<f4", count=2, offset=off), [1.0, 2.0])
    off += 8
    n_names, = struct.unpack_from("<Q", raw, off)
    assert n_names == 1
    ln, = struct.unpack_from("<Q", raw, off + 8)
    assert raw[off + 16:off + 16 + ln] == b"x"


def test_nd_save_load_list_and_dict(tmp_path):
    import mxnet_tpu as mx
    a = mx.np.array([[1.0, 2.0]])
    b = mx.np.arange(4)
    p1 = str(tmp_path / "list.params")
    mx.nd.save(p1, [a, b])
    back = mx.nd.load(p1)
    assert isinstance(back, list) and len(back) == 2
    onp.testing.assert_array_equal(back[0].asnumpy(), a.asnumpy())
    p2 = str(tmp_path / "dict.params")
    mx.nd.save(p2, {"a": a, "b": b})
    back2 = mx.nd.load(p2)
    onp.testing.assert_array_equal(back2["b"].asnumpy(), b.asnumpy())


def test_block_loads_mxnet1x_style_params(tmp_path):
    """A legacy .params with arg:/aux: prefixes loads into a Block."""
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    net = nn.Dense(3, in_units=2)
    net.initialize()
    x = mx.np.ones((1, 2))
    net(x)
    w = net.weight.data().asnumpy()
    legacy = {
        "arg:weight": (w * 2).astype("float32"),
        "arg:bias": onp.ones(3, "float32"),
    }
    p = str(tmp_path / "net.params")
    ser.save_legacy_params(p, legacy)
    net.load_parameters(p)
    onp.testing.assert_allclose(net.weight.data().asnumpy(), w * 2)
    onp.testing.assert_allclose(net.bias.data().asnumpy(), onp.ones(3))


def test_nd_save_rejects_raw_array(tmp_path):
    import mxnet_tpu as mx
    from mxnet_tpu.base import MXNetError
    import pytest
    with pytest.raises(MXNetError, match="nd.save expects"):
        mx.nd.save(str(tmp_path / "x.params"),
                   onp.array([1.0, 2.0, 3.0], "float32"))


def test_block_load_unnamed_legacy_raises(tmp_path):
    import mxnet_tpu as mx
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.gluon import nn
    import pytest
    p = str(tmp_path / "u.params")
    ser.save_legacy_params(p, [onp.ones((2, 2), "float32")])
    net = nn.Dense(2)
    net.initialize()
    net(mx.np.ones((1, 2)))
    with pytest.raises(MXNetError, match="unnamed"):
        net.load_parameters(p)


def test_truncated_legacy_file_raises_mxnet_error(tmp_path):
    import pytest
    from mxnet_tpu.base import MXNetError
    p = str(tmp_path / "t.params")
    ser.save_legacy_params(p, {"w": onp.ones((4, 4), "float32")})
    raw = open(p, "rb").read()
    for cut in (20, 40, len(raw) - 3):
        bad = str(tmp_path / f"cut{cut}.params")
        open(bad, "wb").write(raw[:cut])
        with pytest.raises(MXNetError, match="truncated"):
            ser.load_legacy_params(bad)

"""IO tests: native RecordIO reader/prefetcher + datasets + DataLoader.

Reference strategy: tests/python/unittest/test_recordio.py +
test_gluon_data.py (SURVEY §4); the native reader (native/mxtpu_io.cc) is
checked bit-for-bit against the python writer (recordio.py).
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu import numpy as np


@pytest.fixture()
def rec_file(tmp_path):
    path = str(tmp_path / "data.rec")
    idx = str(tmp_path / "data.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    payloads = []
    rng = onp.random.RandomState(0)
    for i in range(57):
        buf = bytes(rng.randint(0, 256, rng.randint(1, 200),
                                dtype=onp.uint8))
        payloads.append(buf)
        w.write_idx(i, buf)
    w.close()
    return path, idx, payloads


def test_python_recordio_roundtrip(rec_file):
    path, idx, payloads = rec_file
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    for i in (0, 10, 56):
        assert r.read_idx(i) == payloads[i]


def test_native_reader_matches_python_writer(rec_file):
    pytest.importorskip("ctypes")
    from mxnet_tpu.native import NativeRecordFile
    path, idx, payloads = rec_file
    try:
        nf = NativeRecordFile(path)
    except RuntimeError:
        pytest.skip("no native toolchain")
    assert len(nf) == len(payloads)
    for i in range(len(payloads)):
        assert nf.read(i) == payloads[i]
    # offsets identical to the .idx the python writer produced
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    for i in (0, 3, 56):
        assert nf.offset(i) == r.idx[i]
    nf.close()


def test_native_prefetch_shuffled(rec_file):
    from mxnet_tpu.native import NativeRecordFile
    path, _, payloads = rec_file
    try:
        nf = NativeRecordFile(path)
    except RuntimeError:
        pytest.skip("no native toolchain")
    order = onp.random.RandomState(1).permutation(len(payloads))
    seen = {}
    for rec, payload in nf.prefetch_iter(order, capacity=4, workers=3):
        seen[rec] = payload
    assert len(seen) == len(payloads)
    for rec, payload in seen.items():
        assert payload == payloads[rec]
    nf.close()


def test_record_file_dataset_and_loader(rec_file):
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import RecordFileDataset
    path, _, payloads = rec_file
    ds = RecordFileDataset(path)
    assert len(ds) == len(payloads)
    assert ds[5] == payloads[5]
    # decode payload length as the "sample"
    lengths = ds.transform(lambda b: onp.array([len(b)], dtype="float32"))
    loader = DataLoader(lengths, batch_size=8, num_workers=2)
    total = 0
    for batch in loader:
        total += batch.shape[0]
        assert batch.ndim == 2
    assert total == len(payloads)


def test_pack_unpack_header():
    hdr = recordio.IRHeader(0, 3.0, 7, 0)
    buf = recordio.pack(hdr, b"payload")
    h2, payload = recordio.unpack(buf)
    assert payload == b"payload"
    assert h2.id == 7 and float(h2.label) == 3.0
    # multi-label
    hdr = recordio.IRHeader(0, [1.0, 2.0, 3.0], 9, 0)
    buf = recordio.pack(hdr, b"x")
    h3, payload = recordio.unpack(buf)
    assert payload == b"x"
    onp.testing.assert_allclose(h3.label, [1.0, 2.0, 3.0])

"""IO tests: native RecordIO reader/prefetcher + datasets + DataLoader.

Reference strategy: tests/python/unittest/test_recordio.py +
test_gluon_data.py (SURVEY §4); the native reader (native/mxtpu_io.cc) is
checked bit-for-bit against the python writer (recordio.py).
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import recordio
from mxnet_tpu import numpy as np


@pytest.fixture()
def rec_file(tmp_path):
    path = str(tmp_path / "data.rec")
    idx = str(tmp_path / "data.idx")
    w = recordio.MXIndexedRecordIO(idx, path, "w")
    payloads = []
    rng = onp.random.RandomState(0)
    for i in range(57):
        buf = bytes(rng.randint(0, 256, rng.randint(1, 200),
                                dtype=onp.uint8))
        payloads.append(buf)
        w.write_idx(i, buf)
    w.close()
    return path, idx, payloads


def test_python_recordio_roundtrip(rec_file):
    path, idx, payloads = rec_file
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    for i in (0, 10, 56):
        assert r.read_idx(i) == payloads[i]


def test_native_reader_matches_python_writer(rec_file):
    pytest.importorskip("ctypes")
    from mxnet_tpu.native import NativeRecordFile
    path, idx, payloads = rec_file
    try:
        nf = NativeRecordFile(path)
    except RuntimeError:
        pytest.skip("no native toolchain")
    assert len(nf) == len(payloads)
    for i in range(len(payloads)):
        assert nf.read(i) == payloads[i]
    # offsets identical to the .idx the python writer produced
    r = recordio.MXIndexedRecordIO(idx, path, "r")
    for i in (0, 3, 56):
        assert nf.offset(i) == r.idx[i]
    nf.close()


def test_native_prefetch_shuffled(rec_file):
    from mxnet_tpu.native import NativeRecordFile
    path, _, payloads = rec_file
    try:
        nf = NativeRecordFile(path)
    except RuntimeError:
        pytest.skip("no native toolchain")
    order = onp.random.RandomState(1).permutation(len(payloads))
    seen = {}
    for rec, payload in nf.prefetch_iter(order, capacity=4, workers=3):
        seen[rec] = payload
    assert len(seen) == len(payloads)
    for rec, payload in seen.items():
        assert payload == payloads[rec]
    nf.close()


def test_record_file_dataset_and_loader(rec_file):
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import RecordFileDataset
    path, _, payloads = rec_file
    ds = RecordFileDataset(path)
    assert len(ds) == len(payloads)
    assert ds[5] == payloads[5]
    # decode payload length as the "sample"
    lengths = ds.transform(lambda b: onp.array([len(b)], dtype="float32"))
    loader = DataLoader(lengths, batch_size=8, num_workers=2)
    total = 0
    for batch in loader:
        total += batch.shape[0]
        assert batch.ndim == 2
    assert total == len(payloads)


def test_pack_unpack_header():
    hdr = recordio.IRHeader(0, 3.0, 7, 0)
    buf = recordio.pack(hdr, b"payload")
    h2, payload = recordio.unpack(buf)
    assert payload == b"payload"
    assert h2.id == 7 and float(h2.label) == 3.0
    # multi-label
    hdr = recordio.IRHeader(0, [1.0, 2.0, 3.0], 9, 0)
    buf = recordio.pack(hdr, b"x")
    h3, payload = recordio.unpack(buf)
    assert payload == b"x"
    onp.testing.assert_allclose(h3.label, [1.0, 2.0, 3.0])


def test_csv_iter(tmp_path):
    import numpy as onp
    data = onp.arange(20, dtype="float32").reshape(10, 2)
    labels = onp.arange(10, dtype="float32").reshape(10, 1)
    dp, lp = str(tmp_path / "d.csv"), str(tmp_path / "l.csv")
    onp.savetxt(dp, data, delimiter=",")
    onp.savetxt(lp, labels, delimiter=",")
    it = mx.io.CSVIter(data_csv=dp, data_shape=(2,), label_csv=lp,
                       batch_size=4)
    batches = list(it)
    assert len(batches) == 3
    onp.testing.assert_allclose(batches[0].data[0].asnumpy(), data[:4])
    onp.testing.assert_allclose(batches[0].label[0].asnumpy(), labels[:4])
    # round_batch wraps the tail
    assert batches[2].pad == 2
    onp.testing.assert_allclose(batches[2].data[0].asnumpy()[-1], data[1])
    it.reset()
    assert len(list(it)) == 3


def test_libsvm_iter(tmp_path):
    import numpy as onp
    p = str(tmp_path / "t.libsvm")
    with open(p, "w") as f:
        f.write("1 0:1.5 3:2.0\n")
        f.write("0 1:0.5\n")
        f.write("1 2:3.0 3:1.0\n")
        f.write("0 0:2.5\n")
    it = mx.io.LibSVMIter(data_libsvm=p, data_shape=(4,), batch_size=2)
    b = next(it)
    dense = b.data[0].tostype('default').asnumpy()
    onp.testing.assert_allclose(dense,
                                [[1.5, 0, 0, 2.0], [0, 0.5, 0, 0]])
    onp.testing.assert_allclose(b.label[0].asnumpy(), [1.0, 0.0])
    b2 = next(it)
    onp.testing.assert_allclose(b2.data[0].tostype('default').asnumpy(),
                                [[0, 0, 3.0, 1.0], [2.5, 0, 0, 0]])


def test_csv_iter_no_round_batch(tmp_path):
    import numpy as onp
    data = onp.arange(10, dtype="float32").reshape(5, 2)
    dp = str(tmp_path / "d.csv")
    onp.savetxt(dp, data, delimiter=",")
    it = mx.io.CSVIter(data_csv=dp, data_shape=(2,), batch_size=2,
                       round_batch=False)
    batches = list(it)
    assert len(batches) == 3
    assert batches[-1].data[0].shape == (1, 2)   # short tail, no wrap
    assert batches[-1].pad == 0
    onp.testing.assert_allclose(batches[-1].data[0].asnumpy(), data[4:])


def test_libsvm_iter_no_round_batch(tmp_path):
    p = str(tmp_path / "t.libsvm")
    with open(p, "w") as f:
        f.write("1 0:1.0\n0 1:2.0\n1 2:3.0\n")
    it = mx.io.LibSVMIter(data_libsvm=p, data_shape=(3,), batch_size=2,
                          round_batch=False)
    b1, b2 = list(it)
    assert b2.data[0].shape == (1, 3)
    assert b2.pad == 0
    onp.testing.assert_allclose(b2.data[0].tostype('default').asnumpy(),
                                [[0, 0, 3.0]])


def test_mnist_iter(tmp_path):
    import numpy as onp
    import struct
    rs = onp.random.RandomState(0)
    imgs = rs.randint(0, 255, (6, 4, 4)).astype(onp.uint8)
    labels = rs.randint(0, 10, (6,)).astype(onp.uint8)
    ip, lp = str(tmp_path / "imgs-idx3"), str(tmp_path / "labels-idx1")
    with open(ip, "wb") as f:
        f.write(struct.pack(">I", 0x00000803))
        f.write(struct.pack(">III", 6, 4, 4))
        f.write(imgs.tobytes())
    with open(lp, "wb") as f:
        f.write(struct.pack(">I", 0x00000801))
        f.write(struct.pack(">I", 6))
        f.write(labels.tobytes())
    it = mx.io.MNISTIter(image=ip, label=lp, batch_size=3)
    b = next(it)
    assert b.data[0].shape == (3, 1, 4, 4)
    onp.testing.assert_allclose(b.data[0].asnumpy(),
                                imgs[:3, None] / 255.0, rtol=1e-6)
    onp.testing.assert_allclose(b.label[0].asnumpy(), labels[:3])
    flat = mx.io.MNISTIter(image=ip, label=lp, batch_size=2, flat=True)
    assert next(flat).data[0].shape == (2, 16)


def test_image_record_iter(tmp_path):
    import numpy as onp
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "imgs.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    rs = onp.random.RandomState(0)
    for i in range(5):
        img = rs.randint(0, 255, (10, 12, 3)).astype(onp.uint8)
        header = recordio.IRHeader(0, float(i % 3), i, 0)
        rec.write(recordio.pack_img(header, img, img_fmt=".png"))
    rec.close()
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 8, 8),
                               batch_size=2)
    b = next(it)
    assert b.data[0].shape == (2, 3, 8, 8)
    assert b.label[0].shape in ((2,), (2, 1))


def test_iterators_provide_data_label(tmp_path):
    import numpy as onp
    p = str(tmp_path / "t.libsvm")
    with open(p, "w") as f:
        f.write("1 0:1.0\n")
    it = mx.io.LibSVMIter(data_libsvm=p, data_shape=(3,), batch_size=2)
    assert it.provide_data[0][1] == (2, 3)
    assert it.provide_label[0][1] == (2,)


def test_image_record_iter_partial_std(tmp_path):
    """Specifying one std channel must not zero-divide the others."""
    import numpy as onp
    from mxnet_tpu import recordio
    rec_path = str(tmp_path / "i.rec")
    rec = recordio.MXRecordIO(rec_path, "w")
    img = onp.full((8, 8, 3), 128, onp.uint8)
    rec.write(recordio.pack_img(recordio.IRHeader(0, 0.0, 0, 0), img,
                                img_fmt=".png"))
    rec.close()
    it = mx.io.ImageRecordIter(path_imgrec=rec_path, data_shape=(3, 8, 8),
                               batch_size=1, std_b=2.0)
    arr = next(it).data[0].asnumpy()
    assert onp.isfinite(arr).all()


def test_load_parameters_missing_safetensors_error(tmp_path):
    from mxnet_tpu.gluon import nn
    net = nn.Dense(2)
    net.initialize()
    net(mx.np.ones((1, 2)))
    missing = str(tmp_path / "nope.safetensors")
    try:
        net.load_parameters(missing)
        assert False, "expected FileNotFoundError"
    except FileNotFoundError as e:
        assert "nope.safetensors" in str(e) and ".npz" not in str(e)


def test_image_det_record_iter_surface(tmp_path):
    """mx.io.ImageDetRecordIter (reference: iter_image_det_recordio.cc
    surface) maps onto ImageDetIter over a real .rec file."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import recordio

    rec = str(tmp_path / "det.rec")
    idx = str(tmp_path / "det.idx")
    w = recordio.MXIndexedRecordIO(idx, rec, "w")
    for i in range(4):
        rs = onp.random.RandomState(i)
        img = rs.randint(0, 255, (40, 40, 3)).astype(onp.uint8)
        buf = mx.image.imencode(mx.np.array(img.astype(onp.float32)))
        header = recordio.IRHeader(
            0, [2.0, 5.0, float(i % 2), 0.1, 0.2, 0.8, 0.9], i, 0)
        w.write_idx(i, recordio.pack(header, buf))
    w.close()

    it = mx.io.ImageDetRecordIter(path_imgrec=rec, data_shape=(3, 24, 24),
                                  batch_size=2)
    b = next(it)
    assert b.data[0].shape == (2, 3, 24, 24)
    lab = b.label[0].asnumpy()
    assert lab.shape[0] == 2 and lab.shape[2] == 5
    assert (lab[:, 0, 0] >= 0).all()


def test_image_list_dataset(tmp_path):
    """ImageListDataset: .lst file + in-memory list forms
    (reference datasets.py:365; .lst format from tools/im2rec.py)."""
    import numpy as onp
    from PIL import Image

    from mxnet_tpu.gluon.data.vision import ImageListDataset

    root = tmp_path / "imgs"
    root.mkdir()
    rng = onp.random.RandomState(0)
    names = []
    for i in range(4):
        arr = rng.randint(0, 255, (8, 8, 3)).astype("uint8")
        name = f"im{i}.png"
        Image.fromarray(arr).save(root / name)
        names.append(name)
    # .lst file: idx \t label \t relpath (one multi-value label row)
    lst = "\n".join(f"{i}\t{i % 2}\t{n}" for i, n in enumerate(names[:3]))
    lst += f"\n3\t1\t2\t{names[3]}\n"  # 2-value label
    (root / "data.lst").write_text(lst)

    ds = ImageListDataset(root=str(root), imglist="data.lst")
    assert len(ds) == 4
    img, lab = ds[1]
    assert img.shape == (8, 8, 3) and lab == 1.0
    img3, lab3 = ds[3]
    assert tuple(onp.asarray(lab3)) == (1.0, 2.0)

    # in-memory list form
    ds2 = ImageListDataset(root=str(root),
                           imglist=[[0, names[0]], [1, names[1]]])
    assert len(ds2) == 2 and ds2[1][1] == 1.0

    # malformed line raises
    (root / "bad.lst").write_text("0\tonly_path_no_label")
    import pytest
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        ImageListDataset(root=str(root), imglist="bad.lst")

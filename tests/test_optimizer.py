"""Optimizers + Trainer + KVStore (reference: tests/python/unittest/
test_optimizer.py, test_kvstore.py, gluon Trainer tests)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np, optimizer as opt
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def _quadratic_min(optimizer, steps=150, **kwargs):
    """Minimize ||x - t||^2; returns final distance."""
    target = onp.array([1.0, -2.0, 3.0], dtype=onp.float32)
    x = np.array([0.0, 0.0, 0.0])
    x.attach_grad()
    o = optimizer
    state = o.create_state(0, x)
    for _ in range(steps):
        with autograd.record():
            loss = ((x - np.array(target)) ** 2).sum()
        loss.backward()
        o.update(0, x, x.grad, state)
    return float(onp.abs(x.asnumpy() - target).max())


@pytest.mark.parametrize("name,kwargs", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9}),
    ("nag", {"learning_rate": 0.05, "momentum": 0.9}),
    ("adam", {"learning_rate": 0.2}),
    ("adamw", {"learning_rate": 0.2}),
    ("adabelief", {"learning_rate": 0.2}),
    ("nadam", {"learning_rate": 0.2}),
    ("adagrad", {"learning_rate": 0.5}),
    ("adadelta", {"learning_rate": 1.0, "rho": 0.9}),
    ("rmsprop", {"learning_rate": 0.05}),
    ("ftrl", {"learning_rate": 0.5}),
    # lamb/lans step magnitude is lr*||w|| (trust ratio): small lr to settle
    ("lamb", {"learning_rate": 0.02}),
    ("lans", {"learning_rate": 0.02}),
    # lars scales steps by eta*||w||/||g||: toy problem needs a big lr/eta
    ("lars", {"learning_rate": 1.0, "momentum": 0.5, "eta": 0.1}),
    ("signum", {"learning_rate": 0.01}),
])
def test_optimizer_converges(name, kwargs):
    o = opt.create(name, **kwargs)
    # adadelta's effective lr ramps from ~0 (accumulator warmup): more steps
    steps = {"adadelta": 800, "lamb": 500, "lans": 500,
             "signum": 600}.get(name, 150)
    final = _quadratic_min(o, steps=steps)
    assert final < 0.25, f"{name} did not converge: {final}"


def test_sgd_matches_reference_formula():
    o = opt.SGD(learning_rate=0.1, momentum=0.9, wd=0.01)
    w = np.array([1.0])
    g = np.array([0.5])
    state = o.create_state(0, w)
    o.update(0, w, g, state)
    # mom = 0.9*0 - 0.1*(0.5 + 0.01*1); w += mom
    expected = 1.0 - 0.1 * (0.5 + 0.01)
    assert float(w) == pytest.approx(expected, rel=1e-5)


def test_lr_scheduler():
    from mxnet_tpu.lr_scheduler import FactorScheduler, CosineScheduler
    s = FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(25) == pytest.approx(0.25)
    c = CosineScheduler(max_update=100, base_lr=1.0, final_lr=0.0)
    assert c(0) == pytest.approx(1.0)
    assert c(100) == pytest.approx(0.0, abs=1e-6)


def test_multi_precision():
    o = opt.SGD(learning_rate=0.1, momentum=0.9, multi_precision=True)
    w = np.array([1.0, 2.0], dtype="float16")
    g = np.array([0.1, 0.1], dtype="float16")
    state = o.create_state_multi_precision(0, w)
    master, _ = state
    assert master.dtype == onp.float32
    o.update_multi_precision(0, w, g, state)
    assert w.dtype == onp.float16


def test_trainer_step():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = np.array([[1.0, 2.0], [3.0, 4.0]])
    w_before = net.weight.data().asnumpy().copy()
    with autograd.record():
        y = net(x).sum()
    y.backward()
    trainer.step(batch_size=2)
    w_after = net.weight.data().asnumpy()
    expected = w_before - 0.1 * x.asnumpy().sum(axis=0) / 2
    assert_almost_equal(w_after, expected, rtol=1e-4)


def test_trainer_lr():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    assert trainer.learning_rate == 0.5
    trainer.set_learning_rate(0.1)
    assert trainer.learning_rate == 0.1


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.01})
    x = np.ones((1, 2))
    with autograd.record():
        net(x).sum().backward()
    trainer.step(1)
    path = str(tmp_path / "trainer.states")
    trainer.save_states(path)
    trainer.load_states(path)


def test_kvstore_basic():
    kv = mx.kv.create("local")
    kv.init("w", np.ones((2, 2)))
    out = np.zeros((2, 2))
    kv.pull("w", out=out)
    assert_almost_equal(out, onp.ones((2, 2)))
    kv.push("w", [np.ones((2, 2)), np.ones((2, 2))])
    kv.pull("w", out=out)
    assert_almost_equal(out, onp.full((2, 2), 2.0))


def test_kvstore_pushpull():
    kv = mx.kv.create("device")
    kv.init(3, np.zeros(4))
    vals = [np.ones(4) * i for i in range(1, 4)]
    out = np.zeros(4)
    kv.pushpull(3, vals, out=out)
    assert_almost_equal(out, onp.full(4, 6.0))


def test_kvstore_updater():
    kv = mx.kv.create("local")
    kv.init(0, np.zeros(2))

    def updater(key, grad, weight):
        weight._rebind(weight._data + 2 * grad._data)
    kv.set_updater(updater)
    kv.push(0, [np.ones(2)])
    out = np.zeros(2)
    kv.pull(0, out=out)
    assert_almost_equal(out, onp.full(2, 2.0))


def test_kvstore_optimizer_on_store():
    kv = mx.kv.create("device")
    kv.init(0, np.ones(3))
    kv.set_optimizer(opt.SGD(learning_rate=0.1))
    kv.push(0, [np.ones(3)])
    out = np.zeros(3)
    kv.pull(0, out=out)
    assert_almost_equal(out, onp.full(3, 0.9), rtol=1e-5)


def test_kvstore_str_and_list_keys():
    kv = mx.kv.create("local")
    kv.init(["a", "b"], [np.ones(2), np.zeros(2)])
    outs = [np.zeros(2), np.ones(2)]
    kv.pull(["a", "b"], out=outs)
    assert_almost_equal(outs[0], onp.ones(2))
    assert_almost_equal(outs[1], onp.zeros(2))


def test_kvstore_broadcast():
    kv = mx.kv.create("device")
    outs = [np.zeros(3), np.zeros(3)]
    kv.broadcast("p", np.full(3, 5.0), out=outs)
    for o in outs:
        assert_almost_equal(o, onp.full(3, 5.0))


def test_trainer_update_on_kvstore():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1},
                            update_on_kvstore=True)
    x = np.ones((2, 2))
    w0 = net.weight.data().asnumpy().copy()
    with autograd.record():
        net(x).sum().backward()
    trainer.step(2)
    assert not onp.allclose(net.weight.data().asnumpy(), w0)


def test_dist_kvstore_single_process():
    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == 1
    kv.init(0, np.ones(2))
    out = np.zeros(2)
    kv.pushpull(0, [np.ones(2)], out=out)
    assert_almost_equal(out, onp.ones(2))


def test_group_adagrad():
    """Row-wise AdaGrad (reference optimizer/contrib.py:26): history is
    one cell per row; update matches a hand-rolled numpy transcription."""
    import pytest

    from mxnet_tpu.base import MXNetError

    o = opt.create("groupadagrad", learning_rate=0.5)
    w = np.array(onp.ones((3, 4), "float32"))
    state = o.create_state(0, w)
    assert state.shape == (3, 1)
    rng = onp.random.RandomState(0)
    wref = onp.ones((3, 4), "float32")
    href = onp.zeros((3, 1), "float32")
    for _ in range(3):
        g = rng.randn(3, 4).astype("float32")
        o.update(0, w, np.array(g), state)
        href += (g * g).mean(axis=1, keepdims=True)
        wref -= 0.5 * g / (onp.sqrt(href) + 1e-6)
    onp.testing.assert_allclose(w.asnumpy(), wref, rtol=1e-5)
    onp.testing.assert_allclose(state.asnumpy(), href, rtol=1e-5)
    # 1-D weights and weight decay are rejected like the reference
    with pytest.raises(MXNetError):
        o.create_state(0, np.array(onp.ones(3, "float32")))
    o2 = opt.create("groupadagrad", learning_rate=0.5, wd=0.1)
    with pytest.raises(MXNetError):
        o2.update(0, w, np.array(onp.ones((3, 4), "float32")), state)


def test_group_adagrad_lazy_sparse():
    """Row-sparse grads touch only their rows (O(nnz) path)."""
    from mxnet_tpu.ndarray import sparse

    o = opt.create("groupadagrad", learning_rate=0.5)
    w = np.array(onp.ones((5, 4), "float32"))
    state = o.create_state(0, w)
    g_rows = onp.array([[1.0] * 4, [2.0] * 4], "float32")
    rsp = sparse.row_sparse_array((np.array(g_rows),
                                   np.array(onp.array([1, 3], "int64"))),
                                  shape=(5, 4))
    o.update(0, w, rsp, state)
    wn, hn = w.asnumpy(), state.asnumpy()
    # untouched rows unchanged, zero history
    for r in (0, 2, 4):
        assert (wn[r] == 1.0).all() and hn[r] == 0.0
    # touched rows follow the dense formula
    for r, g in ((1, 1.0), (3, 2.0)):
        h = g * g
        assert abs(hn[r] - h) < 1e-6
        assert onp.allclose(wn[r], 1.0 - 0.5 * g / (onp.sqrt(h) + 1e-6),
                            rtol=1e-6)


def test_kvstore_teststore_and_server_pointer():
    """TestStore plugin backend (reference kvstore/base.py:246) +
    server-role fail-fast (kvstore_server.py)."""
    import pytest

    from mxnet_tpu.base import MXNetError

    kv = mx.kvstore.create("teststore")
    assert kv.type == "teststore" and kv.num_workers == 1
    a, b = np.ones(3), np.ones(3) * 2
    out = np.zeros(3)
    kv.pushpull("w", [a, b], out=out)
    onp.testing.assert_allclose(out.asnumpy(), [3, 3, 3])
    kv.pushpull("w", [a, b])  # in-place reduce writes back into inputs
    onp.testing.assert_allclose(a.asnumpy(), [3, 3, 3])
    o2 = np.zeros(2)
    kv.broadcast("w", np.ones(2) * 5, out=o2)
    onp.testing.assert_allclose(o2.asnumpy(), [5, 5])
    assert mx.kvstore.TestStore.is_capable(mx.kvstore.KVStoreBase.OPTIMIZER)

    srv = mx.kvstore.KVStoreServer(kv)
    with pytest.raises(MXNetError, match="worker"):
        srv.run()
    import os as _os
    from mxnet_tpu.kvstore.kvstore_server import init_server_module
    _os.environ["DMLC_ROLE"] = "server"
    try:
        with pytest.raises(MXNetError):
            init_server_module()
    finally:
        _os.environ.pop("DMLC_ROLE", None)
    init_server_module()  # no role: no-op

"""mx.servefleet — multi-replica serving control plane (docs/SERVING.md).

Oracles: the exactly-once ledger (every accepted request completes with
a result recorded exactly once, across crash AND stall failover — the
mx.stream multiplicity-1 discipline applied to serving), greedy token
parity against the full-forward reference after re-dispatch (replicas
share identical weights via a seeded factory), the PR 2 recompile
detector as the zero-compile rolling-update assertion, and the
rendezvous-hash minimal-movement property.

The chaos drills here arm the ``serve.replica_crash`` and
``serve.replica_stall`` injection points single-process; the
multi-process SIGKILL drill lives in tests/servefleet_worker.py (the CI
servefleet stage runs both).
"""
import json
import os
import shutil
import threading
import time
import urllib.error
import urllib.request

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fault, servefleet, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.fleet import HealthPlane
from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM
from mxnet_tpu.serve.engine import EngineBusy


def _factory():
    """Identical weights every call (seeded): replicas must agree so a
    re-dispatched request reproduces the same greedy tokens."""
    mx.random.seed(7)
    net = GPTForCausalLM(vocab_size=97, units=32, hidden_size=64,
                         num_layers=2, num_heads=2, max_length=32,
                         dropout=0.0, embed_dropout=0.0)
    net.initialize()
    net(mx.np.zeros((1, 2), dtype="int32"))
    return net


def _fleet(**kw):
    kw.setdefault("replicas", 2)
    kw.setdefault("max_slots", 2)
    kw.setdefault("buckets", "4,8")
    kw.setdefault("temperature", 0.0)
    return servefleet.ServeFleet(_factory, **kw)


def _ref_greedy(net, prompt, n):
    seq = list(prompt)
    for _ in range(n):
        lg = net(mx.np.array(onp.array([seq], dtype="int32"))).asnumpy()
        seq.append(int(lg[0, -1].argmax()))
    return seq[len(prompt):]


def _session_on(rid, replica_ids, prefix="s"):
    """A session name the rendezvous hash routes to ``rid``."""
    for i in range(10000):
        s = f"{prefix}{i}"
        if servefleet.rendezvous_route(s, replica_ids) == rid:
            return s
    raise AssertionError(f"no session found routing to {rid}")


@pytest.fixture
def metrics():
    telemetry.enable()
    telemetry.reset()
    fault.clear()
    fault.reset_stats()
    yield
    fault.clear()
    fault.reset_stats()
    telemetry.stop_http()
    telemetry.disable()
    telemetry.reset()
    mx.config.reset()


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.read().decode()


# -- rendezvous routing -----------------------------------------------------

def test_rendezvous_minimal_movement():
    """Removing one replica moves ONLY that replica's sessions — the
    property that makes failover cheap for every surviving session."""
    ids = [0, 1, 2, 3]
    sessions = [f"user-{i}" for i in range(300)]
    before = {s: servefleet.rendezvous_route(s, ids) for s in sessions}
    after = {s: servefleet.rendezvous_route(s, [0, 1, 3])
             for s in sessions}
    for s in sessions:
        if before[s] != 2:
            assert after[s] == before[s], s
        else:
            assert after[s] in (0, 1, 3)
    # and it is deterministic (the drill driver recomputes placement)
    assert before == {s: servefleet.rendezvous_route(s, ids)
                      for s in sessions}


def test_rendezvous_empty_raises():
    with pytest.raises(MXNetError):
        servefleet.rendezvous_route("s", [])


# -- basic fleet: affinity, parity, idempotent accept -----------------------

@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_fleet_completes_with_session_affinity(metrics):
    fleet = _fleet(replicas=2)
    try:
        net = _factory()
        frs = []
        for i in range(6):
            frs.append(fleet.submit(list(range(1, 5)), max_new_tokens=5,
                                    session=f"aff-{i}"))
        # affinity: the router honored the rendezvous placement
        live = [r.rid for r in fleet._live()]
        for fr in frs:
            assert fr.replica_id == servefleet.rendezvous_route(
                fr.session, live)
        fleet.run(max_ticks=300)
        ref = _ref_greedy(net, list(range(1, 5)), 5)
        for fr in frs:
            assert fr.done and fr.tokens == ref
        assert telemetry.counters(aggregate=True)[
            "servefleet.completed_total"] == 6
    finally:
        fleet.close()


@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_fleet_idempotent_accept_same_key(metrics):
    fleet = _fleet()
    try:
        a = fleet.submit([1, 2, 3], max_new_tokens=3, key="k1")
        b = fleet.submit([1, 2, 3], max_new_tokens=3, key="k1")
        assert a is b
        assert telemetry.counters(aggregate=True)[
            "servefleet.requests_total"] == 1
        fleet.run(max_ticks=100)
        assert a.done
    finally:
        fleet.close()


@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_fleet_spills_on_busy_and_raises_with_hint(metrics):
    """A full affine replica spills to the next rendezvous choice; an
    all-full fleet surfaces EngineBusy WITH the retry_after_hint so the
    caller backs off instead of hammering."""
    mx.config.set("serve.max_queue", 1)
    fleet = _fleet(replicas=2, max_slots=1)
    try:
        live = [r.rid for r in fleet._live()]
        s = _session_on(live[0], live, prefix="pin-")
        a = fleet.submit([1, 2], max_new_tokens=4, session=s)
        assert a.replica_id == live[0]
        # affinity replica's queue is full: spill to the survivor
        b = fleet.submit([1, 2], max_new_tokens=4, session=s)
        assert b.replica_id == live[1]
        with pytest.raises(EngineBusy) as ei:     # every replica full
            fleet.submit([1, 2], max_new_tokens=2, session=s)
        assert ei.value.reason == "queue_full"
        assert ei.value.retry_after_hint > 0
        fleet.run(max_ticks=300)
        assert a.done and b.done
    finally:
        fleet.close()
        mx.config.reset("serve.max_queue")


# -- crash failover ---------------------------------------------------------

def test_crash_failover_exactly_once_with_parity(metrics):
    """Kill a replica mid-stream (serve.replica_crash): every accepted
    request still completes EXACTLY once, re-prefilled from the
    original prompt on a survivor, with greedy token parity."""
    fault.configure("serve.replica_crash:at=2")
    fleet = _fleet(replicas=3, min_replicas=2)
    try:
        net = _factory()
        prompts = {}
        frs = []
        for i in range(8):
            pr = [1 + (i % 7), 2, 3, 4]
            fr = fleet.submit(pr, max_new_tokens=6, session=f"c{i}")
            prompts[fr.key] = pr
            frs.append(fr)
        fleet.run(max_ticks=500)
        counters = telemetry.counters(aggregate=True)
        assert counters["servefleet.failovers_total"] == 1
        assert counters["servefleet.redispatched_total"] >= 1
        assert counters["servefleet.completed_total"] == 8
        dead = [r for r in fleet._replicas.values() if r.state == "dead"]
        assert len(dead) == 1 and len(fleet._live()) == 2
        for fr in frs:
            assert fr.done
            assert fr.tokens == _ref_greedy(net, prompts[fr.key], 6), \
                fr.key
        # injected fault accounted like any chaos drill
        assert fault.stats()["injected.serve.replica_crash"] == 1
    finally:
        fleet.close()


# -- stall failover + duplicate suppression ---------------------------------

@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_stall_failover_suppresses_duplicate_completions(metrics):
    """The stall drill's signature race: the wedged replica's already-
    dispatched device work is drained AFTER its requests re-dispatch,
    so the same key can complete twice — the ledger must record exactly
    one result and count the other suppressed."""
    mx.config.set("servefleet.stall_deadline", 0.01)
    fleet = _fleet(replicas=2, max_slots=1, drain_window=32)
    try:
        net = _factory()
        live = [r.rid for r in fleet._live()]
        victim_rid = live[0]
        s = _session_on(victim_rid, live, prefix="stall-")
        fr = fleet.submit([1, 2, 3], max_new_tokens=4, session=s)
        assert fr.replica_id == victim_rid
        # dispatch every token into the deferred window (undrained:
        # drain_window=32 means nothing forces the fetch), then wedge
        # the victim exactly as the serve.replica_stall injection does
        for _ in range(8):
            fleet.step()
        victim = fleet._replicas[victim_rid]
        assert not fr.done and victim.engine.pending
        victim.wedged = True
        time.sleep(0.03)
        fleet.run(max_ticks=500, tick_interval=0.002)
        # the orphan won the race at drain time; the re-dispatched copy
        # is still decoding on the survivor — tick until it lands so
        # the ledger gets to suppress it
        for _ in range(200):
            if not any(r.engine.pending for r in fleet._live()):
                break
            fleet.step()
        counters = telemetry.counters(aggregate=True)
        assert fr.done and fr.tokens == _ref_greedy(net, [1, 2, 3], 4)
        assert counters["servefleet.completed_total"] == 1
        assert counters["servefleet.failovers_total"] == 1
        assert counters["servefleet.duplicates_suppressed_total"] >= 1
    finally:
        fleet.close()


@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_stall_injection_point_drives_failover(metrics):
    """End-to-end via the armed injection point: serve.replica_stall
    wedges the busiest replica, the stall deadline declares it dead,
    work re-dispatches, everything completes exactly once."""
    fault.configure("serve.replica_stall:at=2")
    mx.config.set("servefleet.stall_deadline", 0.02)
    fleet = _fleet(replicas=2, min_replicas=1)
    try:
        frs = [fleet.submit([2, 3, 4], max_new_tokens=6,
                            session=f"w{i}") for i in range(6)]
        fleet.run(max_ticks=1000, tick_interval=0.003)
        assert all(fr.done for fr in frs)
        counters = telemetry.counters(aggregate=True)
        assert counters["servefleet.completed_total"] == 6
        assert counters["servefleet.failovers_total"] == 1
        assert fault.stats()["injected.serve.replica_stall"] == 1
    finally:
        fleet.close()


# -- rolling weight updates -------------------------------------------------

def _published_params():
    """A 'trained' parameter tree: the factory weights, perturbed
    deterministically so the new generation is distinguishable."""
    from mxnet_tpu import functional
    net = _factory()
    net(mx.np.zeros((1, 2), dtype="int32"))  # materialize everything
    params = dict(functional.param_arrays(net))
    return {k: v + 0.5 for k, v in params.items()}, net


def test_rolling_update_zero_compiles_and_generation(metrics):
    fleet = _fleet(replicas=2, min_replicas=1)
    try:
        new_params, net = _published_params()
        # canary card computed by the publisher on the NEW weights
        # (a scratch engine, exactly what a training fleet would run)
        from mxnet_tpu.serve.engine import ServeEngine
        card_eng = ServeEngine(_factory(), max_slots=2, buckets="4,8",
                               temperature=0.0)
        card_eng.update_weights(new_params)
        card = servefleet.canary_card(card_eng, [[1, 2, 3, 4]], tokens=4)
        report = fleet.rolling_update(new_params, canary=card)
        assert report["rolled_back"] is False
        assert sorted(report["updated"]) == sorted(
            r.rid for r in fleet._live())
        assert report["generation"] == 1
        for r in fleet._live():
            assert r.generation == 1
            assert r.engine.post_warmup_compiles == 0
        # fleet serves the new generation: parity with the card
        fr = fleet.submit([1, 2, 3, 4], max_new_tokens=4, session="g1")
        fleet.run(max_ticks=200)
        assert fr.tokens == card["expected"][0]
        counters = telemetry.counters(aggregate=True)
        assert counters["servefleet.rolling_updates_total"] == 2
        assert "servefleet.rollbacks_total" not in counters
    finally:
        fleet.close()


@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_rolling_update_bad_canary_rolls_back_and_aborts(metrics):
    """A checkpoint whose canary diverges must stop at the FIRST
    replica: auto-rollback to the old weights, rollout aborted, every
    replica still serving the old generation with zero compiles."""
    fleet = _fleet(replicas=3, min_replicas=2)
    try:
        net = _factory()
        old_ref = _ref_greedy(net, [1, 2, 3], 4)
        good_card = {"prompts": [[1, 2, 3]], "tokens": 4,
                     "expected": [old_ref]}
        bad_params, _ = _published_params()  # diverges from good_card
        report = fleet.rolling_update(bad_params, canary=good_card)
        assert report["rolled_back"] is True
        assert report["updated"] == []
        assert "canary diverged" in report["reason"]
        assert all(r.generation == 0 for r in fleet._live())
        assert len(fleet._live()) == 3  # never dipped below the floor
        # old weights restored: still serving the old tokens
        fr = fleet.submit([1, 2, 3], max_new_tokens=4, session="after")
        fleet.run(max_ticks=200)
        assert fr.tokens == old_ref
        counters = telemetry.counters(aggregate=True)
        assert counters["servefleet.rollbacks_total"] == 1
        assert "servefleet.rolling_updates_total" not in counters
        for r in fleet._live():
            assert r.engine.post_warmup_compiles == 0
    finally:
        fleet.close()


@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_rolling_update_respects_min_replicas_floor(metrics):
    """With live == min_replicas and no scale-out capacity, taking a
    replica down for the update would breach the floor: refuse."""
    fleet = _fleet(replicas=2, min_replicas=2, max_replicas=2)
    try:
        params, _ = _published_params()
        with pytest.raises(MXNetError, match="min_replicas"):
            fleet.rolling_update(params)
        assert len(fleet._live()) == 2
    finally:
        fleet.close()


def test_rolling_update_covers_mid_rollout_scale_out(metrics):
    """A replica built by the floor-guard scale-out DURING the rollout
    comes up on the old generation — a successful rollout must roll it
    too, never reporting success while the fleet serves mixed weight
    generations."""
    fleet = _fleet(replicas=2, min_replicas=2, max_replicas=3)
    try:
        new_params, _ = _published_params()
        report = fleet.rolling_update(new_params)
        assert report["rolled_back"] is False
        live = fleet._live()
        assert len(live) == 3          # the floor guard built one
        assert all(r.generation == 1 for r in live)
        assert sorted(report["updated"]) == sorted(r.rid for r in live)
    finally:
        fleet.close()


def test_sole_replica_crash_queues_then_rebuilds(metrics):
    """min_replicas=1 and the only replica crashes mid-stream: the
    victims park in the overflow queue (never an exception from inside
    the failover loop), the next tick rebuilds capacity, and every
    accepted request still completes exactly once with parity."""
    fault.configure("serve.replica_crash:at=2")
    fleet = _fleet(replicas=1, min_replicas=1)
    try:
        net = _factory()
        frs = [fleet.submit([1, 2, 3], max_new_tokens=4,
                            session=f"solo{i}") for i in range(3)]
        fleet.run(max_ticks=500)
        ref = _ref_greedy(net, [1, 2, 3], 4)
        for fr in frs:
            assert fr.done and fr.tokens == ref
        counters = telemetry.counters(aggregate=True)
        assert counters["servefleet.completed_total"] == 3
        assert counters["servefleet.failovers_total"] == 1
        assert fault.stats()["servefleet.fleet_dead"] == 1
        # dead replicas are never revived: a fresh one took over
        assert len(fleet._live()) == 1
        assert sum(1 for r in fleet._replicas.values()
                   if r.state == "dead") == 1
    finally:
        fleet.close()


@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_ledger_evicts_completed_beyond_retain(metrics):
    """The exactly-once ledger stays bounded: settled requests move to
    an LRU capped at servefleet.ledger_retain, lifetime totals keep
    counting, and a retained key still absorbs a duplicate submit."""
    mx.config.set("servefleet.ledger_retain", 4)
    fleet = _fleet(replicas=2)
    try:
        frs = {}
        for i in range(10):
            frs[f"key-{i}"] = fleet.submit(
                [1, 2, 3], max_new_tokens=2, key=f"key-{i}",
                session=f"L{i}")
            fleet.run(max_ticks=200)
        assert all(fr.done for fr in frs.values())
        assert fleet._inflight == {}
        assert len(fleet._completed) == 4
        again = fleet.submit([1, 2, 3], max_new_tokens=2, key="key-9")
        assert again is frs["key-9"]
        rep = fleet.report()
        assert rep["requests"] == 10 and rep["completed"] == 10
        assert rep["ledger_retained"] == 4
    finally:
        fleet.close()


@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_rolling_update_validates_canary_at_entry(metrics):
    """A sampling engine or a malformed card aborts the rollout BEFORE
    any replica is drained or swapped — nothing is left live on
    un-canaried new weights and nothing needs rolling back."""
    fleet = _fleet(replicas=2, temperature=0.8)
    try:
        params, _ = _published_params()
        card = {"prompts": [[1, 2, 3]], "tokens": 2,
                "expected": [[1, 1]]}
        with pytest.raises(MXNetError, match="greedy"):
            fleet.rolling_update(params, canary=card)
        assert fleet._generation == 0
        assert all(r.generation == 0 and r.state == "live"
                   for r in fleet._live())
        counters = telemetry.counters(aggregate=True)
        assert "servefleet.rollbacks_total" not in counters
        assert "servefleet.rolling_updates_total" not in counters
    finally:
        fleet.close()
    fleet = _fleet(replicas=2)
    try:
        params, _ = _published_params()
        with pytest.raises(MXNetError, match="canary_card"):
            fleet.rolling_update(params, canary={"prompts": [[1]]})
        assert fleet._generation == 0
    finally:
        fleet.close()


def test_checkpoint_publish_swaps_symlink_never_missing(tmp_path,
                                                        metrics):
    """Publishing over an existing checkpoint is ONE os.replace of a
    prepared symlink — path always resolves to a complete versioned
    data dir, the superseded dir is removed, and a legacy real
    directory migrates into the symlink layout."""
    params, _ = _published_params()
    path = str(tmp_path / "ckpt")
    servefleet.publish_checkpoint(path, params, step=1)
    assert os.path.islink(path)
    first_target = os.path.realpath(path)
    servefleet.publish_checkpoint(path, params, step=2)
    assert os.path.islink(path)
    assert os.path.realpath(path) != first_target
    assert not os.path.exists(first_target)   # superseded dir removed
    loaded, _ = servefleet.load_checkpoint(path)
    assert sorted(loaded) == sorted(params)
    # legacy in-place directory (pre-symlink layout) migrates cleanly
    legacy = str(tmp_path / "legacy")
    shutil.copytree(os.path.realpath(path), legacy)
    assert os.path.isdir(legacy) and not os.path.islink(legacy)
    servefleet.publish_checkpoint(legacy, params, step=3)
    assert os.path.islink(legacy)
    loaded, _ = servefleet.load_checkpoint(legacy)
    assert sorted(loaded) == sorted(params)


def test_checkpoint_publish_load_roundtrip(tmp_path, metrics):
    """Staged publish: atomic rename, canary card in the manifest, and
    a second publish atomically replaces the first."""
    params, net = _published_params()
    card = {"prompts": [[1, 2, 3]], "tokens": 2, "expected": [[5, 5]]}
    path = str(tmp_path / "ckpt")
    servefleet.publish_checkpoint(path, params, canary=card, step=10)
    loaded, canary = servefleet.load_checkpoint(path)
    assert canary == card
    assert sorted(loaded) == sorted(params)
    for k in params:
        assert onp.array_equal(onp.asarray(loaded[k]),
                               onp.asarray(params[k])), k
    # re-publish over the same path (the rolling-update poll target)
    servefleet.publish_checkpoint(path, params, canary=None, step=11)
    _, canary2 = servefleet.load_checkpoint(path)
    assert canary2 is None
    with pytest.raises(MXNetError, match="manifest"):
        servefleet.load_checkpoint(str(tmp_path / "nope"))


# -- SLO-driven scaling -----------------------------------------------------

@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_scale_out_on_sustained_slo_burn(metrics):
    mx.config.set("serve.slo_ttft_ms", 0.0001)
    mx.config.set("serve.slo_target", 0.9)
    mx.config.set("servefleet.scale_patience", 2)
    fleet = _fleet(replicas=2, max_replicas=3)
    try:
        frs = [fleet.submit([1, 2, 3], max_new_tokens=3,
                            session=f"b{i}") for i in range(4)]
        fleet.run(max_ticks=300)
        assert all(fr.done for fr in frs)
        # every TTFT violated the micro-SLO: burn >> threshold on the
        # replicas that served; tick the supervisor past the patience
        for _ in range(6):
            fleet.step()
        assert len(fleet._live()) == 3
        counters = telemetry.counters()
        assert counters.get(
            'servefleet.scale_events_total{dir="out"}', 0) >= 1
    finally:
        fleet.close()


@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_scale_in_parks_and_burn_unparks(metrics):
    mx.config.set("servefleet.occupancy_floor", 1.0)  # idle < full
    mx.config.set("servefleet.scale_patience", 2)
    fleet = _fleet(replicas=3, min_replicas=2)
    try:
        for _ in range(6):   # idle ticks past patience
            fleet.step()
        assert len(fleet._live()) == 2
        parked = fleet._parked()
        assert len(parked) == 1
        counters = telemetry.counters()
        assert counters.get(
            'servefleet.scale_events_total{dir="in"}', 0) == 1
        # scale-out prefers unparking (grid still hot: no compiles)
        rep = fleet._scale_out(reason="test")
        assert rep is parked[0] and rep.state == "live"
        assert rep.engine.post_warmup_compiles == 0
        assert len(fleet._live()) == 3
        # parked floor respected: never below min_replicas
        mx.config.set("servefleet.occupancy_floor", 1.0)
        for _ in range(20):
            fleet.step()
        assert len(fleet._live()) >= 2
    finally:
        fleet.close()


# -- HealthPlane renewal-thread hygiene (the PR's bugfix) -------------------

def test_healthplane_tight_restart_loop_leaks_no_threads(tmp_path):
    """start()/stop() in a tight loop must never leak mx-fleet-heartbeat
    threads or revive an old loop via the shared stop event — the
    in-process restart pattern a serving supervisor runs."""
    plane = HealthPlane(rank=0, nprocs=1, lease_dir=str(tmp_path),
                        interval=0.005)
    for _ in range(30):
        plane.start()
        plane.stop()
    time.sleep(0.05)
    alive = [t for t in threading.enumerate()
             if t.name == "mx-fleet-heartbeat" and t.is_alive()]
    assert alive == [], alive
    plane.stop()          # double-stop is a no-op
    # start-start is idempotent: exactly one renewal thread
    plane.start()
    first = plane._thread
    plane.start()
    assert plane._thread is first
    alive = [t for t in threading.enumerate()
             if t.name == "mx-fleet-heartbeat" and t.is_alive()]
    assert len(alive) == 1
    plane.stop()
    time.sleep(0.05)
    assert not any(t.name == "mx-fleet-heartbeat" and t.is_alive()
                   for t in threading.enumerate())


def test_healthplane_stop_joins_renewal_thread(tmp_path):
    plane = HealthPlane(rank=0, nprocs=1, lease_dir=str(tmp_path),
                        interval=0.005)
    plane.start()
    t = plane._thread
    assert t.is_alive()
    plane.stop()
    assert not t.is_alive()      # joined, not abandoned
    assert plane._thread is None


# -- leases + ops endpoint --------------------------------------------------

@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_fleet_replicas_hold_leases_and_stale_lease_fails_over(
        tmp_path, metrics):
    """Each replica renews a host-<rid>.lease; a lease stale past the
    plane timeout is a detected crash (the multi-process drill's
    detection path, exercised in-process by stopping one plane)."""
    fleet = _fleet(replicas=2, min_replicas=1,
                   lease_dir=str(tmp_path))
    try:
        live = [r.rid for r in fleet._live()]
        for rid in live:
            path = tmp_path / f"host-{rid}.lease"
            for _ in range(200):  # daemon loop's first beat: async
                if path.exists():
                    break
                time.sleep(0.01)
            assert path.exists(), rid
        victim = fleet._replicas[live[0]]
        fr = fleet.submit([1, 2, 3], max_new_tokens=4,
                          session=_session_on(live[0], live, "lease-"))
        # freeze the victim's renewals and age its lease past timeout
        victim.plane._stop.set()
        victim.plane.timeout = 0.01
        stale = {"rank": victim.rid, "pid": 0, "step": 0,
                 "time": time.time() - 1.0}
        (tmp_path / f"host-{victim.rid}.lease").write_text(
            json.dumps(stale))
        fleet.run(max_ticks=300, tick_interval=0.002)
        assert victim.state == "dead"
        assert fr.done
        counters = telemetry.counters(aggregate=True)
        assert counters["servefleet.failovers_total"] == 1
    finally:
        fleet.close()


@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_servefleet_ops_endpoint(metrics):
    fleet = _fleet(replicas=2)
    try:
        fr = fleet.submit([1, 2, 3], max_new_tokens=3, session="ep")
        fleet.run(max_ticks=100)
        assert fr.done
        srv = telemetry.serve_http(0)
        port = srv.server_address[1]
        status, body = _get(port, "/servefleet")
        assert status == 200
        d = json.loads(body)
        assert d["active"] is True
        assert len(d["fleets"]) == 1
        rep = d["fleets"][0]
        assert rep["live"] == 2 and rep["completed"] == 1
        # and the 404 page advertises the path
        status, body = _get(port, "/nope")
        assert status == 404 and "/servefleet" in body
    finally:
        telemetry.stop_http()
        fleet.close()


@pytest.mark.slow  # full surface rides the servefleet CI stage (MXNET_TEST_SLOW=1)
def test_close_drops_hot_path_gate(metrics):
    fleet = _fleet(replicas=2)
    assert servefleet._active is True
    fleet.close()
    assert servefleet._active is False
    assert servefleet.endpoint_report()["fleets"] == []

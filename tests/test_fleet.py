"""mx.fleet — health-plane-driven elastic mesh degradation.

Oracles: layout re-planning against hand-computed factorization
preferences; the end-to-end chaos drill against an uninterrupted
same-layout run (per-step loss parity after a degrade + bitwise bundle
equality right after the rebuild); a real 2-process lease-expiry drill
via subprocess (tests/fleet_worker.py).

Chaos spec literals exercised here: "fleet.host_loss:at=4,times=1",
"fleet.slow_host:at=1", "fleet.lease_lost:at=1".
"""
import glob
import os
import subprocess
import sys
import time

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.fleet import FleetSupervisor, HealthPlane, plan_layout
from mxnet_tpu.parallel import ShardedTrainStep
from mxnet_tpu.parallel.mesh import MeshConfig
from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_fleet_state():
    mx.fault.clear()
    mx.fault.reset_stats()
    yield
    mx.fault.clear()
    mx.fault.reset_stats()
    telemetry.unregister_health("fleet")


@pytest.fixture
def metrics():
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.disable()


# -- layout re-planning ------------------------------------------------------

def test_plan_layout_preserves_tp_and_pp():
    cur = MeshConfig(dp=2, tp=2, pp=2)
    assert plan_layout(cur, 4) == MeshConfig(dp=1, tp=2, pp=2)
    assert plan_layout(cur, 8) == cur


def test_plan_layout_prefers_tp_over_pp_then_max_dp():
    cur = MeshConfig(dp=2, tp=2, pp=2)
    # 6 devices: tp=2 and pp=2 can't both survive (4 does not divide 6);
    # tp survives, and among {dp=1 pp=3, dp=3 pp=1} the larger dp wins
    assert plan_layout(cur, 6) == MeshConfig(dp=3, tp=2, pp=1)


def test_plan_layout_preserves_sp():
    cur = MeshConfig(dp=4, sp=2)
    planned = plan_layout(cur, 4)
    assert planned == MeshConfig(dp=2, sp=1).replace(sp=2)
    assert planned.sp == cur.sp


def test_plan_layout_parks_below_min_dp():
    cur = MeshConfig(dp=2, tp=2, pp=2)
    assert plan_layout(cur, 4, min_dp=2) is None
    # odd device counts with no sp-compatible factorization park too
    assert plan_layout(MeshConfig(dp=4, sp=2), 3) is None


def test_plan_layout_min_dp_defaults_to_config_knob():
    prev = mx.config.set("fleet.min_dp", 2)
    try:
        assert plan_layout(MeshConfig(dp=2, tp=2, pp=2), 4) is None
    finally:
        mx.config.set("fleet.min_dp", prev)


def test_meshconfig_replace():
    cfg = MeshConfig(dp=4, tp=2)
    assert cfg.replace(dp=1) == MeshConfig(dp=1, tp=2)
    assert cfg.replace(dp=1) is not cfg and cfg.dp == 4
    with pytest.raises(mx.base.MXNetError, match="unknown axis"):
        cfg.replace(ep=2)


# -- supervisor state machine (no real mesh needed) -------------------------

class _FakeStep:
    mesh_config = MeshConfig(dp=2)


def test_supervisor_parks_below_min_dp_and_unparks():
    state = mx.resilience.TrainState()
    sup = FleetSupervisor(_FakeStep(), state, n_hosts=2, min_dp=2)
    mx.fault.configure("fleet.host_loss:at=1")
    assert sup.probe(1) is False and sup.parked
    assert mx.fault.stats().get("fleet.park") == 1
    sup.restore_hosts()
    assert not sup.parked and sup.alive_hosts() == [0, 1]


def test_supervisor_marks_straggler_without_killing():
    state = mx.resilience.TrainState()
    sup = FleetSupervisor(_FakeStep(), state, n_hosts=2)
    mx.fault.configure("fleet.slow_host:at=1")
    assert sup.probe(1) is True          # slow, not wedged: nothing dies
    assert sup.alive_hosts() == [0, 1] and sup.degrades == 0
    assert mx.fault.stats().get("fleet.straggler") == 1


def test_supervisor_ignores_host_loss_with_nobody_to_lose():
    state = mx.resilience.TrainState()
    sup = FleetSupervisor(_FakeStep(), state, n_hosts=1)
    mx.fault.configure("fleet.host_loss:at=1")
    assert sup.probe(1) is True and not sup._lost


# -- health plane ------------------------------------------------------------

def test_lease_lost_turns_healthz_red_then_recovers(tmp_path):
    hp = HealthPlane(rank=0, nprocs=1, lease_dir=str(tmp_path))
    mx.fault.configure("fleet.lease_lost:at=1")
    assert hp.beat(step=1) is False      # renewal failed
    assert hp.healthz()["ok"] is False
    assert mx.fault.stats().get("fleet.lease_renew_failure") == 1
    assert hp.beat(step=2) is True       # the heartbeat keeps retrying
    assert hp.healthz()["ok"] is True


def test_health_plane_detects_stale_peer(tmp_path):
    a = HealthPlane(rank=0, nprocs=2, lease_dir=str(tmp_path),
                    timeout=0.2)
    b = HealthPlane(rank=1, nprocs=2, lease_dir=str(tmp_path))
    a.beat(step=1)
    b.beat(step=1)
    assert a.check_peers() == [1]
    time.sleep(0.3)                      # b stops renewing: lease rots
    with pytest.raises(mx.resilience.WorkerLost) as ei:
        a.check_peers()
    assert ei.value.op == "lease" and "host-1" in str(ei.value.key)
    assert a.healthz()["ok"] is False    # stale peer turns /healthz red


def test_health_plane_clean_stop_is_departure_not_loss(tmp_path):
    a = HealthPlane(rank=0, nprocs=2, lease_dir=str(tmp_path),
                    timeout=0.2)
    b = HealthPlane(rank=1, nprocs=2, lease_dir=str(tmp_path))
    b.beat(step=1)
    a.beat(step=1)
    assert a.peers()
    b.stop()                             # withdraws the lease file
    assert a.peers() == {}


def test_healthz_endpoint_surfaces_provider_state():
    telemetry.register_health("fleet", lambda: {"ok": False, "why": "x"})
    ok, checks = telemetry.health()
    assert ok is False and checks["fleet"]["why"] == "x"
    telemetry.unregister_health("fleet")
    assert telemetry.health()[0] is True


# -- resilience satellites ---------------------------------------------------

def test_bundle_retention_gc_keeps_last_k(tmp_path):
    path = str(tmp_path / "t.bundle")
    state = mx.resilience.TrainState(path=path)
    for s in range(1, 6):
        state.step = s
        state.save()
    gens = [os.path.basename(p) for p in state._history(path)]
    assert gens == ["t.bundle.g00000003", "t.bundle.g00000004",
                    "t.bundle.g00000005"]
    assert mx.fault.stats().get("resilience.bundle_gc") == 2


def test_load_latest_valid_falls_back_past_torn_primary(tmp_path):
    path = str(tmp_path / "t.bundle")
    state = mx.resilience.TrainState(path=path)
    for s in (1, 2):
        state.step = s
        state.save()
    # tear the primary the way a mid-save death does: bytes that no
    # longer match the sidecar (new inode, so the .g2 hard link survives)
    os.remove(path)
    with open(path, "wb") as f:
        f.write(b"torn")
    fresh = mx.resilience.TrainState(path=path)
    with pytest.raises(mx.base.MXNetError, match="checksum|corrupt"):
        fresh.load()                     # strict load still refuses
    restored = fresh.load_latest_valid()
    assert restored.endswith(".g00000002") and fresh.step == 2


def test_restart_budget_resets_after_healthy_window(tmp_path):
    prev = mx.config.set("resilience.restart_window_steps", 10)
    try:
        state = mx.resilience.TrainState(path=str(tmp_path / "b.bundle"))
        state.save()
        calls = []

        def train():
            calls.append(state.step)
            if len(calls) < 4:
                state.step += 100        # healthy progress, then a fault
                raise mx.resilience.WorkerLost(
                    "allreduce", "w", 0, 2, 3, "transient")
            return "done"

        # budget 1, but three spread-out faults: each restart is forgiven
        # because >= 10 steps of progress separated the losses
        assert mx.resilience.run(train, state=state,
                                 max_restarts=1) == "done"
        assert len(calls) == 4
        assert mx.fault.stats().get("resilience.restart_budget_reset") == 2
    finally:
        mx.config.set("resilience.restart_window_steps", prev)
        mx.resilience.clear_preempt()


def test_restart_budget_still_exhausts_in_a_tight_loop(tmp_path):
    prev = mx.config.set("resilience.restart_window_steps", 10)
    try:
        state = mx.resilience.TrainState(path=str(tmp_path / "b.bundle"))
        state.save()

        def train():                     # no progress between faults
            raise mx.resilience.WorkerLost("allreduce", "w", 0, 2, 3, "x")

        with pytest.raises(mx.resilience.WorkerLost):
            mx.resilience.run(train, state=state, max_restarts=1)
    finally:
        mx.config.set("resilience.restart_window_steps", prev)
        mx.resilience.clear_preempt()


# -- the end-to-end degrade drill (8 virtual devices) ------------------------

VOCAB, UNITS, LAYERS, HEADS, SEQ, BATCH = 64, 16, 2, 2, 8, 8

eight = pytest.mark.skipif(
    len(jax.devices()) < 8,
    reason="needs 8 devices (XLA_FLAGS=--xla_force_host_platform_"
           "device_count=8)")


def _batch(seed=0):
    rs = onp.random.RandomState(seed)
    x = rs.randint(0, VOCAB, size=(BATCH, SEQ)).astype(onp.int32)
    y = rs.randint(0, VOCAB, size=(BATCH, SEQ)).astype(onp.int32)
    return x, y


def _loss_fn(logits, labels):
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[..., None], axis=-1).mean()


def _gpt_step(cfg, x, lr=0.01):
    mx.random.seed(0)
    net = GPTForCausalLM(vocab_size=VOCAB, units=UNITS, num_layers=LAYERS,
                         num_heads=HEADS, max_length=SEQ, dropout=0.0,
                         embed_dropout=0.0)
    net.initialize()
    net(mx.np.array(x))                  # materialize deferred params
    opt = mx.optimizer.create("sgd", learning_rate=lr)
    return ShardedTrainStep(net, _loss_fn, opt, cfg,
                            cfg.batch_specs(2, 2), n_labels=1)


def _assert_bitwise(sd_a, sd_b):
    assert sd_a["n_step"] == sd_b["n_step"]
    assert set(sd_a["arrays"]) == set(sd_b["arrays"])
    for k, a in sd_a["arrays"].items():
        b = sd_b["arrays"][k]
        assert onp.asarray(a).shape == onp.asarray(b).shape, k
        assert onp.array_equal(onp.asarray(a), onp.asarray(b)), k


@eight
def test_degrade_drill_bitwise_and_loss_parity(tmp_path, metrics):
    """The tentpole drill: host loss at step 4 -> dp shrinks 2 -> 1 with
    tp/pp preserved -> bundle restores bitwise into the smaller mesh ->
    per-step losses stay on the uninterrupted oracle trajectory -> the
    host returns -> the mesh re-expands at the next checkpoint."""
    import warnings
    cfg = MeshConfig(dp=2, tp=2, pp=2)
    x0, _ = _batch(0)

    step_o = _gpt_step(cfg, x0)
    oracle = {}
    for s in range(1, 9):
        oracle[s] = float(step_o(*_batch(s)))

    step = _gpt_step(cfg, x0)
    state = mx.resilience.TrainState(path=str(tmp_path / "run.bundle"),
                                     sharded_step=step)
    sup = FleetSupervisor(step, state, n_hosts=2, host_index=0,
                          checkpoint_every=1)
    # times=1: a degrade rolls the step counter back, and the replayed
    # probe of step 4 must not kill a second host
    mx.fault.configure("fleet.host_loss:at=4,times=1")
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")  # 4-device mesh strands 4 of 8
        losses = sup.run(_batch, 6)
        assert sup.degrades == 1
        assert sup.current == MeshConfig(dp=1, tp=2, pp=2)
        # bitwise: the rebuilt step's canonical state == the bundle it
        # restored from (step counter, RNG and optimizer state included)
        import pickle
        bundle = pickle.loads(open(state.path, "rb").read())
        _assert_bitwise(sup.step.state_dict(), bundle["sharded_step"])

        sup.restore_hosts()              # the host rejoins
        losses.update(sup.run(_batch, 8))
    assert sup.reexpands == 1 and sup.current == cfg
    assert sorted(losses) == list(range(1, 9))
    for s, ref in oracle.items():
        assert abs(float(losses[s]) - ref) < 1e-5, (s, float(losses[s]), ref)
    counts = telemetry.counters(aggregate=True)
    assert counts.get("fleet.degrades_total", 0) >= 1
    assert counts.get("fleet.reexpands_total", 0) >= 1


# -- the 2-process lease drill ----------------------------------------------

def test_multiprocess_lease_expiry_raises_worker_lost(tmp_path):
    """Two real processes share a lease dir; rank 1 heartbeats, then
    vanishes without a clean stop.  Rank 0's health plane must observe
    the rotting lease and escalate the structured WorkerLost."""
    env = dict(os.environ,
               JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    worker = os.path.join(REPO, "tests", "fleet_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(tmp_path), str(rank), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for rank in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    assert procs[1].returncode == 0 and "FLEET_BEAT 1" in outs[1], outs[1]
    assert procs[0].returncode == 0, outs[0]
    assert "FLEET_LOST 0 lease host-1" in outs[0], outs[0]

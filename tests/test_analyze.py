"""mx.analyze / tools/mxlint.py — framework-aware static analysis
(docs/STATIC_ANALYSIS.md).

Every rule family gets positive AND negative fixtures (the positive
ones fail if the rule is deleted), plus the machinery tests: inline
waiver parsing, baseline round-trip and multiset semantics, the CLI
--json contract, the telemetry ``analyze`` plane, and the self-check
that the shipped tree is clean against the shipped baseline.
"""
import json
import os
import subprocess
import sys

import pytest

from mxnet_tpu import analyze, config, telemetry
from mxnet_tpu.analyze import core

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(tmp_path, tree, paths=None, rules=None):
    """Write a fixture tree and run the suite over it."""
    for rel, src in tree.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(src, encoding="utf-8")
    return analyze.run_suite(
        paths=paths or [str(tmp_path / rel) for rel in tree
                        if rel.endswith(".py")],
        root=str(tmp_path), rules=rules)


def _rules(findings):
    return [f.rule for f in findings]


# --- TRC: trace safety ----------------------------------------------------

def test_trc001_host_sync_inside_jit(tmp_path):
    bad = _run(tmp_path, {"a.py": (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x.item() + 1\n")})
    assert "TRC001" in _rules(bad)
    good = _run(tmp_path, {"b.py": (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    n = x.shape[0]\n"       # static read: no sync
        "    return x * n\n")})
    assert "TRC001" not in _rules(good)


def test_trc002_impure_call_inside_jit(tmp_path):
    bad = _run(tmp_path, {"a.py": (
        "import jax\n"
        "import time\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    return x + time.time()\n")})
    assert "TRC002" in _rules(bad)


def test_trc003_python_branch_on_traced_value(tmp_path):
    bad = _run(tmp_path, {"a.py": (
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    if x > 0:\n"
        "        return x\n"
        "    return -x\n")})
    assert "TRC003" in _rules(bad)
    # static_argnames params are concrete at trace time: branching is fine
    good = _run(tmp_path, {"b.py": (
        "import functools\n"
        "import jax\n"
        "@functools.partial(jax.jit, static_argnames=('mode',))\n"
        "def f(x, mode):\n"
        "    if mode == 'relu':\n"
        "        return x\n"
        "    return -x\n")})
    assert "TRC003" not in _rules(good)


def test_trc004_closure_capture_of_step_varying_value(tmp_path):
    bad = _run(tmp_path, {"a.py": (
        "import jax\n"
        "def train(data):\n"
        "    step = 0\n"
        "    out = []\n"
        "    for batch in data:\n"
        "        step += 1\n"
        "        def loss_fn(x):\n"
        "            return x * step\n"
        "        out.append(jax.jit(loss_fn)(batch))\n"
        "    return out\n")})
    assert "TRC004" in _rules(bad)
    good = _run(tmp_path, {"b.py": (
        "import jax\n"
        "SCALE = 2.0\n"
        "def train(data):\n"
        "    def loss_fn(x):\n"
        "        return x * SCALE\n"   # module constant: one trace
        "    return [jax.jit(loss_fn)(b) for b in data]\n")})
    assert "TRC004" not in _rules(good)


def test_trc005_per_batch_sync_in_hot_path(tmp_path):
    bad = _run(tmp_path, {"a.py": (
        "class ServeEngine:\n"
        "    def step(self):\n"
        "        return self._last.item()\n")})
    assert "TRC005" in _rules(bad)
    # an emit-interval gate (ancestor `if` computing a modulo) exempts
    good = _run(tmp_path, {"b.py": (
        "class ServeEngine:\n"
        "    def step(self):\n"
        "        if self._n % 10 == 0:\n"
        "            return self._last.item()\n"
        "        return None\n")})
    assert "TRC005" not in _rules(good)


def test_trc005_batch_end_handler(tmp_path):
    bad = _run(tmp_path, {"a.py": (
        "class LossLogger(EventHandler):\n"
        "    def batch_end(self, estimator, loss):\n"
        "        self._log(float(loss.item()))\n")})
    assert "TRC005" in _rules(bad)


# --- DON: buffer donation -------------------------------------------------

def test_don001_use_after_donation(tmp_path):
    bad = _run(tmp_path, {"a.py": (
        "import jax\n"
        "def _step(s):\n"
        "    return s\n"
        "step_fn = jax.jit(_step, donate_argnums=0)\n"
        "def loop(state):\n"
        "    out = step_fn(state)\n"
        "    return out + state\n")})     # state's buffer is dead here
    assert "DON001" in _rules(bad)
    # the safe idiom: rebind the donated name on the same statement
    good = _run(tmp_path, {"b.py": (
        "import jax\n"
        "def _step(s):\n"
        "    return s\n"
        "step_fn = jax.jit(_step, donate_argnums=0)\n"
        "def loop(state):\n"
        "    state = step_fn(state)\n"
        "    return state\n")})
    assert "DON001" not in _rules(good)


# --- LCK: lock discipline -------------------------------------------------

_LCK_CYCLE = (
    "import threading\n"
    "class Pool:\n"
    "    def __init__(self):\n"
    "        self._a_lock = threading.Lock()\n"
    "        self._b_lock = threading.Lock()\n"
    "    def forward(self):\n"
    "        with self._a_lock:\n"
    "            with self._b_lock:\n"
    "                return 1\n"
    "    def backward(self):\n"
    "        with self._b_lock:\n"
    "            with self._a_lock:\n"
    "                return 2\n")


def test_lck001_lock_order_cycle(tmp_path):
    bad = _run(tmp_path, {"a.py": _LCK_CYCLE})
    assert "LCK001" in _rules(bad)
    good = _run(tmp_path, {"b.py": _LCK_CYCLE.replace(
        "    def backward(self):\n"
        "        with self._b_lock:\n"
        "            with self._a_lock:\n",
        "    def backward(self):\n"
        "        with self._a_lock:\n"
        "            with self._b_lock:\n")})
    assert "LCK001" not in _rules(good)


def test_lck002_blocking_call_under_lock(tmp_path):
    bad = _run(tmp_path, {"a.py": (
        "import threading\n"
        "import time\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def poke(self):\n"
        "        with self._lock:\n"
        "            time.sleep(0.5)\n")})
    assert "LCK002" in _rules(bad)
    good = _run(tmp_path, {"b.py": (
        "import threading\n"
        "import time\n"
        "class W:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n"
        "    def poke(self):\n"
        "        with self._lock:\n"
        "            n = 1\n"
        "        time.sleep(0.5)\n"     # sleeps after release: fine
        "        return n\n")})
    assert "LCK002" not in _rules(good)


# --- REG: registry drift --------------------------------------------------

def test_reg001_undeclared_knob_read(tmp_path):
    findings = _run(tmp_path, {
        "mxnet_tpu/config.py":
            "declare('a.b', str, '', 'ENV_AB', 'a documented knob')\n",
        "user.py": (
            "from mxnet_tpu import config\n"
            "config.get('a.b')\n"
            "config.get('missing.knob')\n")})
    hits = [f for f in findings if f.rule == "REG001"]
    assert len(hits) == 1 and "missing.knob" in hits[0].message


def test_reg002_knob_without_doc(tmp_path):
    findings = _run(tmp_path, {"mxnet_tpu/config.py": (
        "declare('doc.ok', str, '', 'ENV_OK', 'documented')\n"
        "declare('doc.missing', str, '', 'ENV_MISS')\n")})
    hits = [f for f in findings if f.rule == "REG002"]
    assert len(hits) == 1 and "doc.missing" in hits[0].message


def test_reg003_undeclared_metric_record(tmp_path):
    findings = _run(tmp_path, {"user.py": (
        "from mxnet_tpu import telemetry\n"
        "declare_metric('ok.total', 'counter', 'declared')\n"
        "telemetry.inc('ok.total')\n"
        "telemetry.inc('nope.total')\n")})
    hits = [f for f in findings if f.rule == "REG003"]
    assert len(hits) == 1 and "nope.total" in hits[0].message


def test_reg004_reg008_fault_point_coverage(tmp_path):
    findings = _run(tmp_path, {
        "mxnet_tpu/fault.py": (
            "POINTS = {\n"
            "    'tested.point': 'covered',\n"
            "    'never.tested': 'not covered',\n"
            "}\n"),
        "tests/test_x.py": "SPEC = 'tested.point:at=2'\n",
        "docs/FAULT_TOLERANCE.md": "| `tested.point` | ... |\n"})
    r4 = [f for f in findings if f.rule == "REG004"]
    r8 = [f for f in findings if f.rule == "REG008"]
    assert len(r4) == 1 and "never.tested" in r4[0].message
    assert len(r8) == 1 and "never.tested" in r8[0].message


def test_reg005_unknown_fault_point_fired(tmp_path):
    findings = _run(tmp_path, {
        "mxnet_tpu/fault.py": "POINTS = {'known.point': 'doc'}\n",
        "tests/test_x.py": "S = 'known.point'\n",
        "docs/FAULT_TOLERANCE.md": "`known.point`\n",
        "user.py": (
            "from mxnet_tpu import fault\n"
            "fault.fire('known.point')\n"
            "fault.fire('unknown.point')\n")})
    hits = [f for f in findings if f.rule == "REG005"]
    assert len(hits) == 1 and "unknown.point" in hits[0].message


def test_reg006_ci_stage_drift(tmp_path):
    findings = _run(tmp_path, {
        "ci/matrix.yaml": (
            "matrix:\n"
            "  - stage: unit\n"
            "    platform: cpu\n"
            "  - stage: ghost\n"
            "    platform: cpu\n"
            "  - stage: nightly\n"
            "    platform: cpu\n"
            "    schedule: nightly\n"),
        "ci/run.sh": (
            'case "$stage" in\n'
            "    unit) unit ;;\n"
            "    extra) extra ;;\n"
            "    nightly) nightly ;;\n"
            "    all) unit ;;\n"
            "esac\n"),
        "m.py": "X = 1\n"})
    msgs = [f.message for f in findings if f.rule == "REG006"]
    assert any("ghost" in m for m in msgs)       # matrix -> no case
    assert any("extra" in m for m in msgs)       # case -> no matrix row
    assert not any("nightly" in m for m in msgs)  # scheduled: exempt


def test_reg007_metric_missing_from_doc(tmp_path):
    findings = _run(tmp_path, {
        "mxnet_tpu/m.py": (
            "declare_metric('doc.metric', 'counter', 'in the doc')\n"
            "declare_metric('ghost.metric', 'counter', 'not in it')\n"),
        "docs/OBSERVABILITY.md": "| `doc.metric` | counter | ... |\n"})
    hits = [f for f in findings if f.rule == "REG007"]
    assert len(hits) == 1 and "ghost.metric" in hits[0].message


# --- waivers --------------------------------------------------------------

def test_waiver_with_reason_suppresses(tmp_path):
    findings = _run(tmp_path, {"user.py": (
        "from mxnet_tpu import telemetry\n"
        "telemetry.inc('w.one')"
        "  # mxlint: disable=REG003(scratch metric, bench-only)\n")})
    assert _rules(findings) == []


def test_waiver_without_reason_is_its_own_finding(tmp_path):
    findings = _run(tmp_path, {"user.py": (
        "from mxnet_tpu import telemetry\n"
        "telemetry.inc('w.two')  # mxlint: disable=REG003\n")})
    assert _rules(findings) == ["WVR001"]


def test_waiver_standalone_comment_covers_next_line(tmp_path):
    findings = _run(tmp_path, {"user.py": (
        "from mxnet_tpu import telemetry\n"
        "# mxlint: disable=REG003(scratch)\n"
        "telemetry.inc('w.three')\n")})
    assert _rules(findings) == []


def test_waiver_only_suppresses_named_rule(tmp_path):
    findings = _run(tmp_path, {"user.py": (
        "from mxnet_tpu import telemetry\n"
        "telemetry.inc('w.four')  # mxlint: disable=TRC001(wrong rule)\n")})
    assert _rules(findings) == ["REG003"]


# --- baseline -------------------------------------------------------------

def test_baseline_round_trip(tmp_path):
    findings = _run(tmp_path, {"user.py": (
        "from mxnet_tpu import telemetry\n"
        "telemetry.inc('b.one')\n"
        "telemetry.inc('b.two')\n")})
    assert sorted(_rules(findings)) == ["REG003", "REG003"]
    bl = tmp_path / "baseline.json"
    core.write_baseline(str(bl), findings)
    new, waived = core.apply_baseline(findings, core.load_baseline(str(bl)))
    assert new == [] and len(waived) == 2
    # a fresh finding is NOT absorbed by the old baseline
    more = _run(tmp_path, {"user.py": (
        "from mxnet_tpu import telemetry\n"
        "telemetry.inc('b.one')\n"
        "telemetry.inc('b.two')\n"
        "telemetry.inc('b.three')\n")})
    new, waived = core.apply_baseline(more, core.load_baseline(str(bl)))
    assert len(new) == 1 and "b.three" in new[0].message
    assert len(waived) == 2


def test_baseline_is_count_based(tmp_path):
    # two identical findings, one baseline entry: one stays new
    findings = _run(tmp_path, {"user.py": (
        "from mxnet_tpu import telemetry\n"
        "def a():\n"
        "    telemetry.inc('dup.total')\n"
        "def b():\n"
        "    telemetry.inc('dup.total')\n")})
    assert len(findings) == 2
    assert findings[0].key() == findings[1].key()
    new, waived = core.apply_baseline(
        findings, {findings[0].key(): 1})
    assert len(new) == 1 and len(waived) == 1


def test_baseline_survives_line_drift(tmp_path):
    findings = _run(tmp_path, {"user.py": (
        "from mxnet_tpu import telemetry\n"
        "telemetry.inc('drift.total')\n")})
    bl = tmp_path / "baseline.json"
    core.write_baseline(str(bl), findings)
    moved = _run(tmp_path, {"user.py": (
        "from mxnet_tpu import telemetry\n"
        "\n\n\n"
        "telemetry.inc('drift.total')\n")})
    new, waived = core.apply_baseline(moved, core.load_baseline(str(bl)))
    assert new == [] and len(waived) == 1


# --- CLI ------------------------------------------------------------------

_MXLINT = os.path.join(_REPO, "tools", "mxlint.py")


def test_cli_json_contract_and_assert_clean():
    """bench.py contract: the last stdout line is the one JSON doc; the
    shipped tree is clean against the shipped baseline (exit 0)."""
    proc = subprocess.run(
        [sys.executable, _MXLINT, "--baseline",
         os.path.join(_REPO, "ci", "lint_baseline.json"),
         "--assert-clean", "--json"],
        capture_output=True, text=True, cwd=_REPO, timeout=300)
    assert proc.returncode == 0, proc.stderr
    doc = json.loads(proc.stdout.strip().rsplit("\n", 1)[-1])
    assert doc["clean"] is True and doc["new"] == []
    assert doc["baselined"] >= 1          # the baseline is not vestigial


def test_cli_list_rules():
    proc = subprocess.run(
        [sys.executable, _MXLINT, "--list-rules"],
        capture_output=True, text=True, cwd=_REPO, timeout=60)
    assert proc.returncode == 0
    for rule in ("TRC001", "DON001", "LCK001", "REG001", "WVR001"):
        assert rule in proc.stdout


def test_cli_rule_filter(tmp_path):
    src = tmp_path / "fix.py"
    src.write_text(
        "import jax\n"
        "@jax.jit\n"
        "def f(x):\n"
        "    import time\n"
        "    if x > 0:\n"
        "        return x + time.time()\n"
        "    return -x\n", encoding="utf-8")
    proc = subprocess.run(
        [sys.executable, _MXLINT, "--json", "--rule", "TRC003", str(src)],
        capture_output=True, text=True, cwd=_REPO, timeout=60)
    doc = json.loads(proc.stdout.strip().rsplit("\n", 1)[-1])
    assert set(doc["rule_counts"]) == {"TRC003"}


# --- the suite applied to itself ------------------------------------------

def test_shipped_tree_is_clean_against_shipped_baseline():
    """The acceptance gate the CI lint stage enforces, as a unit test:
    zero NEW findings over the whole shipped tree."""
    findings = analyze.run_suite(root=_REPO)
    baseline = core.load_baseline(
        os.path.join(_REPO, "ci", "lint_baseline.json"))
    new, _ = core.apply_baseline(findings, baseline)
    assert new == [], "\n".join(f.render() for f in new)


# --- telemetry plane ------------------------------------------------------

def test_run_report_carries_analyze_plane(tmp_path):
    src = tmp_path / "m.py"
    src.write_text(
        "from mxnet_tpu import telemetry\n"
        "telemetry.inc('plane.total')\n", encoding="utf-8")
    analyze.run_suite(paths=[str(src)], root=str(tmp_path))
    rep = telemetry.TrainingTelemetry(run_id="lint-plane").report()
    assert rep["analyze"]["total"] == 1
    assert rep["analyze"]["rules"] == {"REG003": 1}


def test_run_report_reads_saved_mxlint_json(tmp_path, monkeypatch):
    monkeypatch.setattr(core, "_last_summary", None)
    out = tmp_path / "lint.json"
    out.write_text(json.dumps(
        {"new": [], "baselined": 5,
         "rule_counts": {"REG003": 2}, "total_new": 2, "clean": False}),
        encoding="utf-8")
    prev = config.set("analyze.report_path", str(out))
    try:
        rep = telemetry.TrainingTelemetry(run_id="lint-file").report()
    finally:
        config.set("analyze.report_path", prev)
    assert rep["analyze"] == {"total": 2, "rules": {"REG003": 2}}

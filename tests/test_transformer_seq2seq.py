"""Transformer seq2seq example smoke (reference: gluon-nlp transformer
recipe over src/operator/contrib/transformer.cc attention ops)."""
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "example"))

from transformer_seq2seq import BOS, Seq2SeqTransformer, batch  # noqa: E402

import mxnet_tpu as mx  # noqa: E402
from mxnet_tpu import gluon  # noqa: E402


def test_seq2seq_transformer_learns_reversal():
    rng = onp.random.RandomState(0)
    mx.random.seed(0)  # param init draws from the global key stream
    net = Seq2SeqTransformer(units=32, heads=2, hidden=64, layers=1,
                             seq_len=5)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    losses = []
    for _ in range(200):
        xv, tv, yv = batch(rng, 32, 5)
        x, t, y = mx.np.array(xv), mx.np.array(tv), mx.np.array(yv)
        with mx.autograd.record():
            loss = loss_fn(net(x, t), y).mean()
        loss.backward()
        trainer.step(32)
        losses.append(float(loss))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    # greedy decode emits BOS-free sequences of the right shape
    xv, _, yv = batch(rng, 8, 5)
    pred = net.greedy_decode(mx.np.array(xv))
    assert pred.shape == yv.shape and (pred != BOS).all()

"""Autograd tape (reference: tests/python/unittest/test_autograd.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, np
from mxnet_tpu.test_utils import assert_almost_equal


def test_simple_backward():
    x = np.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain():
    x = np.array([0.5, 1.0])
    x.attach_grad()
    with autograd.record():
        y = np.exp(x) * x
        z = y.sum()
    z.backward()
    expected = onp.exp(x.asnumpy()) * (1 + x.asnumpy())
    assert_almost_equal(x.grad, expected, rtol=1e-5)


def test_multi_input():
    a = np.array([1.0, 2.0])
    b = np.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b + a).sum()
    c.backward()
    assert_almost_equal(a.grad, b.asnumpy() + 1)
    assert_almost_equal(b.grad, a.asnumpy())


def test_no_grad_outside_record():
    x = np.array([1.0])
    x.attach_grad()
    y = x * 2  # not recorded
    assert y._entry is None


def test_head_grad():
    x = np.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(np.array([1.0, 10.0]))
    assert_almost_equal(x.grad, onp.array([3.0, 30.0]))


def test_grad_req_add():
    x = np.array([1.0])
    x.attach_grad("add")
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward()
    assert float(x.grad) == 6.0


def test_grad_function():
    x = np.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    g = autograd.grad(y, x)
    assert_almost_equal(g, onp.array([12.0]))


def test_detach():
    x = np.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, onp.array([2.0]))  # only through 2nd factor


def test_pause():
    x = np.array([1.0])
    x.attach_grad()
    with autograd.record():
        with autograd.pause():
            y = x * 2
        z = x * 3
    assert y._entry is None
    z.backward()
    assert float(x.grad) == 3.0


def test_training_modes():
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_training()
        assert autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
            assert autograd.is_recording()
    with autograd.train_mode():
        assert autograd.is_training()
        assert not autograd.is_recording()


def test_retain_graph():
    x = np.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = float(x.grad)
    y.backward()
    assert float(x.grad) == g1  # write req overwrites


def test_double_backward_error_without_retain():
    x = np.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward()
    with pytest.raises(mx.MXNetError):
        y.backward()


def test_mark_variables():
    x = np.array([1.0, 1.0])
    g = np.zeros(2)
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = (x * 4).sum()
    y.backward()
    assert_almost_equal(x.grad, onp.array([4.0, 4.0]))


def test_custom_function():
    class Square(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return dy * 2 * x

    x = np.array([3.0])
    x.attach_grad()
    sq = Square()
    with autograd.record():
        y = sq(x)
    y.backward()
    assert_almost_equal(x.grad, onp.array([6.0]))


def test_through_reductions_and_reshape():
    x = np.arange(6, dtype="float32").reshape(2, 3)
    x.attach_grad()
    with autograd.record():
        y = (x.reshape(3, 2).T * 2).mean()
    y.backward()
    assert_almost_equal(x.grad, onp.full((2, 3), 2.0 / 6.0))


def test_nondiff_path_int():
    x = np.array([1.0, 5.0, 3.0])
    x.attach_grad()
    with autograd.record():
        idx = np.argmax(x)  # int output
        y = (x * 2).sum()
    y.backward()
    assert_almost_equal(x.grad, onp.full(3, 2.0))
    assert int(idx) == 1


def test_finite_difference_utility():
    from mxnet_tpu.test_utils import check_numeric_gradient

    def f(inputs):
        (x,) = inputs
        return (np.tanh(x) * x).sum()

    x = np.array([0.3, -0.7, 1.2])
    check_numeric_gradient(f, [x])

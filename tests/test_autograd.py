"""Autograd tape (reference: tests/python/unittest/test_autograd.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, np
from mxnet_tpu.test_utils import assert_almost_equal


def test_simple_backward():
    x = np.array([1.0, 2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x * x).sum()
    y.backward()
    assert_almost_equal(x.grad, 2 * x.asnumpy())


def test_chain():
    x = np.array([0.5, 1.0])
    x.attach_grad()
    with autograd.record():
        y = np.exp(x) * x
        z = y.sum()
    z.backward()
    expected = onp.exp(x.asnumpy()) * (1 + x.asnumpy())
    assert_almost_equal(x.grad, expected, rtol=1e-5)


def test_multi_input():
    a = np.array([1.0, 2.0])
    b = np.array([3.0, 4.0])
    a.attach_grad()
    b.attach_grad()
    with autograd.record():
        c = (a * b + a).sum()
    c.backward()
    assert_almost_equal(a.grad, b.asnumpy() + 1)
    assert_almost_equal(b.grad, a.asnumpy())


def test_no_grad_outside_record():
    x = np.array([1.0])
    x.attach_grad()
    y = x * 2  # not recorded
    assert y._entry is None


def test_head_grad():
    x = np.array([1.0, 2.0])
    x.attach_grad()
    with autograd.record():
        y = x * 3
    y.backward(np.array([1.0, 10.0]))
    assert_almost_equal(x.grad, onp.array([3.0, 30.0]))


def test_grad_req_add():
    x = np.array([1.0])
    x.attach_grad("add")
    for _ in range(3):
        with autograd.record():
            y = x * 2
        y.backward()
    assert float(x.grad) == 6.0


def test_grad_function():
    x = np.array([2.0])
    x.attach_grad()
    with autograd.record():
        y = x * x * x
    g = autograd.grad(y, x)
    assert_almost_equal(g, onp.array([12.0]))


def test_detach():
    x = np.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * 2
        z = y.detach() * x
    z.backward()
    assert_almost_equal(x.grad, onp.array([2.0]))  # only through 2nd factor


def test_pause():
    x = np.array([1.0])
    x.attach_grad()
    with autograd.record():
        with autograd.pause():
            y = x * 2
        z = x * 3
    assert y._entry is None
    z.backward()
    assert float(x.grad) == 3.0


def test_training_modes():
    assert not autograd.is_training()
    with autograd.record():
        assert autograd.is_training()
        assert autograd.is_recording()
        with autograd.predict_mode():
            assert not autograd.is_training()
            assert autograd.is_recording()
    with autograd.train_mode():
        assert autograd.is_training()
        assert not autograd.is_recording()


def test_retain_graph():
    x = np.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward(retain_graph=True)
    g1 = float(x.grad)
    y.backward()
    assert float(x.grad) == g1  # write req overwrites


def test_double_backward_error_without_retain():
    x = np.array([1.0])
    x.attach_grad()
    with autograd.record():
        y = x * x
    y.backward()
    with pytest.raises(mx.MXNetError):
        y.backward()


def test_mark_variables():
    x = np.array([1.0, 1.0])
    g = np.zeros(2)
    autograd.mark_variables([x], [g])
    with autograd.record():
        y = (x * 4).sum()
    y.backward()
    assert_almost_equal(x.grad, onp.array([4.0, 4.0]))


def test_custom_function():
    class Square(autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self.saved_tensors
            return dy * 2 * x

    x = np.array([3.0])
    x.attach_grad()
    sq = Square()
    with autograd.record():
        y = sq(x)
    y.backward()
    assert_almost_equal(x.grad, onp.array([6.0]))


def test_through_reductions_and_reshape():
    x = np.arange(6, dtype="float32").reshape(2, 3)
    x.attach_grad()
    with autograd.record():
        y = (x.reshape(3, 2).T * 2).mean()
    y.backward()
    assert_almost_equal(x.grad, onp.full((2, 3), 2.0 / 6.0))


def test_nondiff_path_int():
    x = np.array([1.0, 5.0, 3.0])
    x.attach_grad()
    with autograd.record():
        idx = np.argmax(x)  # int output
        y = (x * 2).sum()
    y.backward()
    assert_almost_equal(x.grad, onp.full(3, 2.0))
    assert int(idx) == 1


def test_finite_difference_utility():
    from mxnet_tpu.test_utils import check_numeric_gradient

    def f(inputs):
        (x,) = inputs
        return (np.tanh(x) * x).sum()

    x = np.array([0.3, -0.7, 1.2])
    check_numeric_gradient(f, [x])


# ---------------------------------------------------------------------------
# higher-order (create_graph) — reference taxonomy:
# python/mxnet/autograd.py:303 grad(create_graph=True) over
# src/imperative/imperative.cc:438; tests/python/unittest/test_higher_order_grad.py
# ---------------------------------------------------------------------------

def test_create_graph_sin_chain():
    # sin -> cos -> -sin -> -cos through repeated create_graph
    xs = onp.array([0.3, 1.1, -0.7], onp.float32)
    x = np.array(xs)
    x.attach_grad()
    with autograd.record():
        y = np.sin(x)
        g1 = autograd.grad(y, x, create_graph=True)
        g2 = autograd.grad(g1, x, create_graph=True)
        g3 = autograd.grad(g2, x)
    assert_almost_equal(g1, onp.cos(xs), rtol=1e-5)
    assert_almost_equal(g2, -onp.sin(xs), rtol=1e-5)
    assert_almost_equal(g3, -onp.cos(xs), rtol=1e-5)


def test_create_graph_then_backward():
    # reference pattern: grad(create_graph=True) then .backward() accumulates
    # the second-order gradient into x.grad
    x = np.array([2.0, 3.0])
    x.attach_grad()
    with autograd.record():
        y = (x ** 3).sum()
        g = autograd.grad(y, x, create_graph=True)
        gs = g.sum()
    gs.backward()
    assert_almost_equal(x.grad, onp.array([12.0, 18.0]), rtol=1e-5)


def test_create_graph_mixed_partial():
    # f = x*y^2: d/dy(df/dx) = 2y
    x = np.array([2.0])
    y = np.array([3.0])
    x.attach_grad()
    y.attach_grad()
    with autograd.record():
        f = x * y * y
        gx = autograd.grad(f, x, create_graph=True)
        gxy = autograd.grad(gx, y)
    assert_almost_equal(gxy, onp.array([6.0]), rtol=1e-5)


def test_create_graph_gradient_penalty():
    # WGAN-GP style: penalty on the gradient norm, differentiated wrt weights
    from mxnet_tpu.gluon import nn
    net = nn.Dense(4, activation='tanh')
    net.initialize()
    x = np.ones((2, 3)) * 0.5
    x.attach_grad()
    with autograd.record():
        out = net(x).sum()
        g = autograd.grad(out, x, create_graph=True)
        penalty = (g * g).sum()
    penalty.backward()
    w = list(net.collect_params().values())[0]
    assert onp.isfinite(w.grad().asnumpy()).all()
    assert onp.abs(w.grad().asnumpy()).sum() > 0


def test_create_graph_through_hybridized():
    # the CachedOp tape node re-linearizes through the jitted forward
    from mxnet_tpu.gluon import nn
    net = nn.Dense(3, activation='tanh')
    net.initialize()
    net.hybridize()
    x = np.array([[0.1, 0.2], [0.3, -0.4]])
    x.attach_grad()
    with autograd.record():
        y = net(x).sum()
        g = autograd.grad(y, x, create_graph=True)
        gn = (g * g).sum()
    gn.backward()
    # oracle: same computation fully eager (non-hybridized fresh net with
    # identical params)
    net2 = nn.Dense(3, activation='tanh')
    net2.initialize()
    for (n1, p1), (n2, p2) in zip(net.collect_params().items(),
                                  net2.collect_params().items()):
        p2.set_data(p1.data())
    x2 = np.array([[0.1, 0.2], [0.3, -0.4]])
    x2.attach_grad()
    with autograd.record():
        y2 = net2(x2).sum()
        g2 = autograd.grad(y2, x2, create_graph=True)
        gn2 = (g2 * g2).sum()
    gn2.backward()
    assert_almost_equal(x.grad, x2.grad.asnumpy(), rtol=1e-4, atol=1e-5)


def test_create_graph_function_fails_fast():
    # custom Function has only a user backward — no pure fn to re-linearize;
    # must raise, not silently return un-taped grads
    class Double(autograd.Function):
        def forward(self, x):
            return x * 2
        def backward(self, dy):
            return dy * 2

    f = Double()
    x = np.array([1.0])
    x.attach_grad()
    with pytest.raises(mx.base.MXNetError, match="create_graph"):
        with autograd.record():
            y = f(x)
            autograd.grad(y, x, create_graph=True)

"""Exception propagation and engine-semantics tests.

Analog of the reference's tests/python/unittest/test_exc_handling.py:
exceptions raised by (possibly asynchronous) work must surface at a
well-defined point, and the runtime must stay usable afterwards.

TPU-native semantics being locked down here: eager dispatch validates
shapes/dtypes synchronously at the call site (stronger than the
reference's async-engine model, where errors surface at WaitToRead —
threaded_engine.h:475-492); value-dependent failures surface at sync
points (asnumpy / wait_to_read / waitall); and after any failure the
dispatcher, autograd tape, and compiled-graph cache keep working.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, npx
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn


def test_shape_mismatch_raises_at_callsite():
    a = mx.np.ones((2, 3))
    b = mx.np.ones((4, 5))
    with pytest.raises((ValueError, TypeError, MXNetError)):
        mx.np.matmul(a, b)
    # dispatcher still healthy
    assert float(mx.np.sum(a).asnumpy()) == 6.0


def test_engine_usable_after_exception():
    a = mx.np.ones((3,))
    with pytest.raises(Exception):
        mx.np.concatenate([a, mx.np.ones((2, 2))], axis=0)
    mx.waitall()
    out = (a + a).asnumpy()
    onp.testing.assert_allclose(out, [2, 2, 2])


def test_constraint_check_raises_eagerly():
    ok = mx.np.array([1.0, 2.0])
    npx.constraint_check(ok > 0, "positive")  # passes
    with pytest.raises(ValueError, match="positive"):
        npx.constraint_check(ok < 0, "positive")


def test_custom_function_backward_exception():
    class Bad(autograd.Function):
        def forward(self, x):
            return x * 2
        def backward(self, dy):
            raise RuntimeError("bad backward")

    x = mx.np.ones((3,))
    x.attach_grad()
    with autograd.record():
        y = Bad()(x)
    with pytest.raises(RuntimeError, match="bad backward"):
        y.backward()
    # tape cleaned up: a fresh record/backward works
    with autograd.record():
        z = x * 3
    z.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [3, 3, 3])


def test_exception_inside_forward_of_hybridized_block():
    class Picky(nn.HybridBlock):
        def forward(self, x):
            if x.shape[-1] != 4:
                raise MXNetError("want 4 features")
            return x * 2

    net = Picky()
    net.hybridize()
    with pytest.raises(MXNetError, match="want 4"):
        net(mx.np.ones((2, 3)))
    # block remains usable with a valid input (trace restarts cleanly)
    out = net(mx.np.ones((2, 4)))
    onp.testing.assert_allclose(out.asnumpy(), 2 * onp.ones((2, 4)))
    out2 = net(mx.np.ones((2, 4)))  # compiled replay path
    onp.testing.assert_allclose(out2.asnumpy(), out.asnumpy())


def test_waitall_after_heavy_async_queue():
    """waitall returns only when queued device work is complete and does
    not wedge after hundreds of async dispatches."""
    a = mx.np.ones((64, 64))
    for _ in range(200):
        a = a @ mx.np.eye(64) * 1.0
    mx.waitall()
    onp.testing.assert_allclose(a.asnumpy()[0, 0], 1.0)


def test_dataloader_worker_exception_propagates():
    from mxnet_tpu.gluon.data import DataLoader, Dataset

    class Exploding(Dataset):
        def __len__(self):
            return 8
        def __getitem__(self, idx):
            if idx == 5:
                raise ValueError("poisoned sample")
            return onp.zeros(3, "float32")

    loader = DataLoader(Exploding(), batch_size=4, num_workers=2)
    with pytest.raises(Exception, match="poisoned"):
        for _ in loader:
            pass


def test_deferred_nan_does_not_raise_but_is_observable():
    """Value-level failures (inf/nan) are data, not control flow — parity
    with the reference where 1/0 on device produces inf, no exception."""
    x = mx.np.array([1.0, 0.0])
    y = 1.0 / x
    vals = y.asnumpy()
    assert onp.isinf(vals[1])
    assert not onp.isnan(vals[0])


def test_bulk_scope_preserves_results():
    """engine.bulk batches dispatches (reference: Engine::set_bulk_size,
    threaded_engine.h:433); semantics must be unchanged."""
    from mxnet_tpu import engine
    a = mx.np.ones((8,))
    with engine.bulk(16):
        for _ in range(10):
            a = a + 1
    onp.testing.assert_allclose(a.asnumpy(), 11 * onp.ones(8))

"""Graph-only export/reload (reference: HybridBlock.export block.py:1471 +
SymbolBlock.imports block.py:1638 — reload and run WITHOUT the original
python class). TPU-native artifact: serialized StableHLO via jax.export."""
import json
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.gluon import nn, SymbolBlock, Trainer


def _make_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.BatchNorm(), nn.Dense(4))
    net.initialize()
    return net


def test_export_writes_graph_artifact(tmp_path):
    net = _make_net()
    x = mx.np.random.uniform(size=(2, 8))
    net(x)
    sym_file, params_file = net.export(str(tmp_path / "model"))
    with open(sym_file) as f:
        meta = json.load(f)
    assert meta["format"] == "mxnet_tpu-hybrid-v2"
    assert (tmp_path / meta["stablehlo"]).exists()
    assert (tmp_path / meta["params"]).exists()
    assert meta["inputs"] == [[[2, 8], "float32"]] or \
        meta["inputs"] == [[(2, 8), "float32"]] or \
        meta["inputs"][0][1] == "float32"


def test_export_requires_forward_first(tmp_path):
    net = _make_net()
    with pytest.raises(mx.MXNetError):
        net.export(str(tmp_path / "m"))


def test_symbolblock_runs_without_class(tmp_path):
    net = _make_net()
    x = mx.np.random.uniform(size=(2, 8))
    ref = net(x).asnumpy()
    sym_file, params_file = net.export(str(tmp_path / "model"))

    loaded = SymbolBlock.imports(sym_file)
    assert type(loaded) is SymbolBlock  # no class reconstruction
    out = loaded(mx.np.array(x.asnumpy()))
    onp.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5, atol=1e-5)


def test_symbolblock_new_inputs_same_shape(tmp_path):
    net = _make_net()
    x = mx.np.random.uniform(size=(2, 8))
    net(x)
    sym_file, _ = net.export(str(tmp_path / "model"))
    loaded = SymbolBlock.imports(sym_file)
    x2 = mx.np.random.uniform(size=(2, 8))
    ref = net(x2).asnumpy()
    onp.testing.assert_allclose(loaded(x2).asnumpy(), ref,
                                rtol=1e-5, atol=1e-5)


def test_symbolblock_is_trainable(tmp_path):
    """The artifact carries a first-order VJP: backward + Trainer work."""
    net = _make_net()
    x = mx.np.random.uniform(size=(4, 8))
    net(x)
    sym_file, _ = net.export(str(tmp_path / "model"))
    loaded = SymbolBlock.imports(sym_file)
    params = loaded.collect_params()
    assert params
    tr = Trainer(params, "sgd", {"learning_rate": 0.5}, kvstore=None)
    before = {n: p.data().asnumpy().copy() for n, p in params.items()}
    with autograd.record():
        y = loaded(x)
        loss = (y ** 2).sum()
    loss.backward()
    tr.step(1)
    changed = [n for n, p in params.items()
               if not onp.allclose(before[n], p.data().asnumpy())]
    assert changed, "no parameter moved after SymbolBlock training step"


def test_symbolblock_missing_artifact_raises(tmp_path):
    meta = {"format": "mxnet_tpu-hybrid-v1", "block_class": "x.Y",
            "params": "p.npz"}
    f = tmp_path / "old-symbol.json"
    f.write_text(json.dumps(meta))
    with pytest.raises(mx.MXNetError):
        SymbolBlock.imports(str(f))


def test_export_import_conv_model(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, activation="relu"),
            nn.MaxPool2D(2), nn.Flatten(), nn.Dense(10))
    net.initialize()
    x = mx.np.random.uniform(size=(2, 3, 16, 16))
    ref = net(x).asnumpy()
    sym_file, _ = net.export(str(tmp_path / "conv"))
    loaded = SymbolBlock.imports(sym_file)
    onp.testing.assert_allclose(loaded(x).asnumpy(), ref, rtol=1e-5,
                                atol=1e-5)

"""Per-op numerical sweep: forward values + finite-difference gradients.

The reference's dominant test class (tests/python/unittest/test_numpy_op.py,
~10.9k LoC of per-op value/grad checks via test_utils.check_numeric_gradient
at python/mxnet/test_utils.py:1044). This sweep covers the WHOLE locked
mx.np surface from tests/test_op_coverage.py:

- forward oracle vs real NumPy over >=2 dtypes and an edge shape, for every
  name in REF_NP (names with framework-specific semantics are listed in
  SKIP_FORWARD with a one-line reason);
- finite-difference gradient check for every differentiable op.

The op surface is lazy jnp delegation, which is exactly why it needs value
locks: any place jnp diverges from NumPy semantics (dtype promotion, axis
handling, edge shapes) surfaces here.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np
from mxnet_tpu.test_utils import check_numeric_gradient

from test_op_coverage import REF_NP

RNG = onp.random.RandomState(42)


def _on_cpu():
    import jax
    return jax.default_backend() == "cpu"


# ops whose TPU implementation measurably exceeds the 2e-5 default vs
# libm (seeded from a full-sweep hardware run; extend on new failures)
_TPU_LOOSE_OPS = {"log1p"}


def _f(shape, lo=-2.0, hi=2.0):
    return (RNG.uniform(lo, hi, size=shape)).astype(onp.float32)


def _pos(shape, lo=0.5, hi=3.0):
    return _f(shape, lo, hi)


def _i(shape, lo=-4, hi=5):
    return RNG.randint(lo, hi, size=shape).astype(onp.int32)


def _b(shape):
    return RNG.rand(*shape) > 0.5


# Each case: (args, kwargs). Arrays in args are host-numpy; they are fed to
# BOTH numpy and mx.np. Default oracle is getattr(numpy, name).
A23 = _f((2, 3))
A34 = _f((3, 4))
B34 = _f((3, 4))
V4 = _f((4,))
W4 = _pos((4,))
P23 = _pos((2, 3))
I23 = _i((2, 3))
J23 = _i((2, 3))
BL23 = _b((2, 3))
BM23 = _b((2, 3))
SC = onp.float32(1.5)          # 0-d edge case

UNARY_SMOOTH = {
    "sin": A23, "cos": A23, "tan": _f((2, 3), -1.0, 1.0), "sinh": A23,
    "cosh": A23, "tanh": A23, "exp": A23, "expm1": A23, "log": P23,
    "log10": P23, "log1p": P23, "log2": P23, "sqrt": P23, "cbrt": P23,
    "square": A23, "negative": A23, "reciprocal": P23,
    "arcsin": _f((2, 3), -0.9, 0.9), "arccos": _f((2, 3), -0.9, 0.9),
    "arctan": A23, "arcsinh": A23, "arccosh": _pos((2, 3), 1.5, 3.0),
    "arctanh": _f((2, 3), -0.9, 0.9), "deg2rad": A23, "rad2deg": A23,
    "degrees": A23, "radians": A23,
}

UNARY_NONSMOOTH = {
    "abs": A23, "absolute": A23, "fabs": A23, "ceil": A23, "floor": A23,
    "rint": A23, "fix": A23, "trunc": A23, "sign": A23,
    "nan_to_num": onp.array([[1.0, onp.nan], [onp.inf, -onp.inf]],
                            onp.float32),
    "isfinite": onp.array([1.0, onp.nan, onp.inf], onp.float32),
    "isinf": onp.array([1.0, onp.nan, onp.inf], onp.float32),
    "isnan": onp.array([1.0, onp.nan, onp.inf], onp.float32),
    "isneginf": onp.array([1.0, -onp.inf, onp.inf], onp.float32),
    "isposinf": onp.array([1.0, -onp.inf, onp.inf], onp.float32),
    "logical_not": BL23,
}

BINARY = {
    "add": (A23, B34[:2, :3]), "subtract": (A23, B34[:2, :3]),
    "multiply": (A23, B34[:2, :3]), "divide": (A23, P23),
    "true_divide": (A23, P23), "power": (P23, _f((2, 3), -1.5, 1.5)),
    "maximum": (A23, B34[:2, :3]), "minimum": (A23, B34[:2, :3]),
    "fmax": (A23, B34[:2, :3]), "fmin": (A23, B34[:2, :3]),
    "copysign": (A23, B34[:2, :3]), "hypot": (P23, P23),
    "arctan2": (A23, P23), "mod": (A23, P23), "remainder": (A23, P23),
    "fmod": (A23, P23), "ldexp": (A23, _i((2, 3), -2, 3)),
}

BINARY_INT = {
    "gcd": (_i((2, 3), 1, 20), _i((2, 3), 1, 20)),
    "lcm": (_i((2, 3), 1, 10), _i((2, 3), 1, 10)),
    "bitwise_and": (I23, J23), "bitwise_or": (I23, J23),
    "bitwise_xor": (I23, J23),
}

COMPARISON = ["equal", "not_equal", "less", "less_equal", "greater",
              "greater_equal"]

LOGICAL = ["logical_and", "logical_or", "logical_xor"]

REDUCTIONS = {
    "sum": [((A34,), {}), ((A34,), {"axis": 0}),
            ((A34,), {"axis": 1, "keepdims": True}), ((SC,), {})],
    "mean": [((A34,), {}), ((A34,), {"axis": -1})],
    "prod": [((P23,), {}), ((P23,), {"axis": 0})],
    "max": [((A34,), {}), ((A34,), {"axis": 0})],
    "min": [((A34,), {}), ((A34,), {"axis": 1, "keepdims": True})],
    "amax": [((A34,), {"axis": 0})],
    "amin": [((A34,), {"axis": 0})],
    "std": [((A34,), {}), ((A34,), {"axis": 0, "ddof": 1})],
    "var": [((A34,), {}), ((A34,), {"axis": 0, "ddof": 1})],
    "all": [((BL23,), {}), ((BL23,), {"axis": 0})],
    "any": [((BL23,), {}), ((BL23,), {"axis": 1})],
    "nansum": [((onp.array([[1, onp.nan], [2, 3]], onp.float32),), {})],
    "nanprod": [((onp.array([[1, onp.nan], [2, 3]], onp.float32),), {})],
    "median": [((V4,), {}), ((A34,), {"axis": 0})],
    "average": [((A34,), {}), ((V4,), {"weights": W4})],
    "cumsum": [((A34,), {}), ((A34,), {"axis": 1})],
}

SHAPE_OPS = {
    "reshape": [((A34, (4, 3)), {}), ((A34, (-1,)), {})],
    "ravel": [((A34,), {})],
    "transpose": [((A34,), {}), ((_f((2, 3, 4)), (2, 0, 1)), {})],
    "swapaxes": [((A34, 0, 1), {})],
    "moveaxis": [((_f((2, 3, 4)), 0, -1), {})],
    "rollaxis": [((_f((2, 3, 4)), 2), {})],
    "squeeze": [((_f((1, 3, 1)),), {})],
    "expand_dims": [((A34, 1), {})],
    "broadcast_to": [((V4, (3, 4)), {})],
    "repeat": [((A34, 2), {}), ((A34, 2), {"axis": 0})],
    "tile": [((A34, (2, 1)), {})],
    "flip": [((A34,), {"axis": 0})],
    "fliplr": [((A34,), {})],
    "flipud": [((A34,), {})],
    "rot90": [((A34,), {})],
    "roll": [((A34, 1), {}), ((A34, 2), {"axis": 1})],
    "concatenate": [(([A34, B34],), {}), (([A34, B34],), {"axis": 1})],
    "stack": [(([A34, B34],), {}), (([A34, B34],), {"axis": -1})],
    "vstack": [(([A34, B34],), {})],
    "hstack": [(([A34, B34],), {})],
    "dstack": [(([A34, B34],), {})],
    "column_stack": [(([V4, W4],), {})],
    "row_stack": [(([A34, B34],), {})],
    "split": [((A34, 2), {"axis": 1})],
    "array_split": [((_f((5, 2)), 2), {})],
    "hsplit": [((A34, 2), {})],
    "vsplit": [((_f((4, 3)), 2), {})],
    "dsplit": [((_f((2, 3, 4)), 2), {})],
    "atleast_1d": [((SC,), {})],
    "atleast_2d": [((V4,), {})],
    "atleast_3d": [((A34,), {})],
    "append": [((A34, B34), {"axis": 0})],
    "delete": [((V4, 1), {})],
    "insert": [((V4, 1, 9.0), {})],
    "resize": [((A34, (2, 2)), {})],
    "pad": [((A34, ((1, 1), (0, 2))), {})],
}

INDEX_MISC = {
    "argmax": [((A34,), {}), ((A34,), {"axis": 1})],
    "argmin": [((A34,), {}), ((A34,), {"axis": 0})],
    "argsort": [((V4,), {}), ((A34,), {"axis": 1})],
    "sort": [((V4,), {}), ((A34,), {"axis": 0})],
    "take": [((A34, onp.array([0, 2], onp.int32)), {"axis": 1})],
    "where": [((BL23, A23, P23), {})],
    "nonzero": [((onp.array([[1, 0], [0, 2]], onp.int32),), {})],
    "flatnonzero": [((onp.array([1, 0, 2, 0], onp.int32),), {})],
    "unique": [((onp.array([3, 1, 3, 2], onp.int32),), {})],
    "unravel_index": [((onp.array([5, 7], onp.int32), (3, 4)), {})],
    "diag": [((A34,), {}), ((V4,), {})],
    "diagflat": [((V4,), {})],
    "diagonal": [((A34,), {})],
    "tril": [((A34,), {})],
    "triu": [((A34,), {})],
    "tri": [((3, 4), {})],
    "tril_indices": [((3,), {})],
    "triu_indices": [((3,), {})],
    "indices": [(((2, 3),), {})],
    "clip": [((A34, -0.5, 0.5), {})],
    "around": [((A34,), {}), ((A34, 1), {})],
    "round": [((A34, 1), {})],
    "diff": [((A34,), {}), ((A34,), {"axis": 0})],
    "ediff1d": [((V4,), {})],
    "bincount": [((onp.array([0, 1, 1, 3], onp.int32),), {})],
    "histogram": [((V4, 3), {})],
    "interp": [((onp.array([0.5, 1.5], onp.float32),
                 onp.array([0.0, 1.0, 2.0], onp.float32),
                 onp.array([0.0, 10.0, 20.0], onp.float32)), {})],
    "polyval": [((V4, W4), {})],
    "percentile": [((A34, 50.0), {}), ((A34, 25.0), {"axis": 0})],
    "quantile": [((A34, 0.5), {})],
    "gcd": [((_i((2, 3), 1, 20), _i((2, 3), 1, 20)), {})],
}

LINEAR = {
    "dot": [((A23, A34[:3, :2].T.copy().T), {})],
    "matmul": [((A23, A34), {}), ((_f((2, 2, 3)), _f((2, 3, 2))), {})],
    "inner": [((V4, W4), {})],
    "outer": [((V4, W4), {})],
    "vdot": [((V4, W4), {})],
    "kron": [((A23, _f((2, 2))), {})],
    "cross": [((_f((3,)), _f((3,))), {})],
    "tensordot": [((_f((2, 3, 4)), _f((4, 3, 2))), {"axes": ((2,), (0,))})],
    "trace": [((A34,), {})],
    "einsum": [(("ij,jk->ik", A23, A34), {})],
}

WINDOWS = {
    "blackman": [((5,), {})], "hamming": [((5,), {})],
    "hanning": [((5,), {})],
}

CREATION = {
    "zeros": [(((2, 3),), {})], "ones": [(((2, 3),), {})],
    "full": [(((2, 3), 7.0), {})], "eye": [((3,), {}), ((3, 4, 1), {})],
    "identity": [((3,), {})], "arange": [((5,), {}), ((1, 7, 2), {})],
    "linspace": [((0.0, 1.0, 5), {})],
    "logspace": [((0.0, 2.0, 4), {})],
    "zeros_like": [((A34,), {})], "ones_like": [((A34,), {})],
    "full_like": [((A34, 3.0), {})],
}

ALL_FORWARD = {}
for name, x in UNARY_SMOOTH.items():
    ALL_FORWARD[name] = [((x,), {}), ((x[0, :1],), {})]   # + edge slice
for name, x in UNARY_NONSMOOTH.items():
    ALL_FORWARD[name] = [((x,), {})]
for name, (a, b) in BINARY.items():
    ALL_FORWARD[name] = [((a, b), {}), ((a, b[:1]), {})]  # broadcast edge
for name, (a, b) in BINARY_INT.items():
    ALL_FORWARD[name] = [((a, b), {})]
for name in COMPARISON:
    ALL_FORWARD[name] = [((A23, B34[:2, :3]), {}), ((I23, J23), {})]
for name in LOGICAL:
    ALL_FORWARD[name] = [((BL23, BM23), {})]
for table in (REDUCTIONS, SHAPE_OPS, INDEX_MISC, LINEAR, WINDOWS, CREATION):
    for name, cases in table.items():
        ALL_FORWARD.setdefault(name, []).extend(cases)

# ops from REF_NP whose semantics are framework-specific or covered elsewhere
SKIP_FORWARD = {
    "array": "creation entry point, covered by test_ndarray",
    "empty": "uninitialized values; shape/dtype asserted below",
    "empty_like": "uninitialized values; shape/dtype asserted below",
    "fill_diagonal": "functional semantics differ (immutable); test_op_coverage",
    "invert": "alias of bitwise_not; bitwise ops covered",
    "bitwise_not": "covered via logical/bitwise family below",
    "bitwise_invert": "alias, same",
}

MISSING = [n for n in REF_NP
           if n not in ALL_FORWARD and n not in SKIP_FORWARD]
assert not MISSING, f"sweep does not cover: {MISSING}"

FORWARD_IDS = [f"{n}-{i}" for n, cs in sorted(ALL_FORWARD.items())
               for i in range(len(cs))]
FORWARD_CASES = [(n, c) for n, cs in sorted(ALL_FORWARD.items()) for c in cs]


def _to_mx(v):
    if isinstance(v, onp.ndarray):
        return np.array(v)
    if isinstance(v, (list, tuple)) and v and isinstance(v[0], onp.ndarray):
        return type(v)(np.array(x) for x in v)
    return v


def _to_np(res):
    if isinstance(res, (list, tuple)):
        return type(res)(_to_np(r) for r in res)
    return res.asnumpy() if hasattr(res, "asnumpy") else onp.asarray(res)


def _assert_match(got, want, name):
    if isinstance(want, (list, tuple)):
        assert isinstance(got, (list, tuple)) and len(got) == len(want), name
        for g, w in zip(got, want):
            _assert_match(g, w, name)
        return
    got = onp.asarray(got)
    want = onp.asarray(want)
    assert got.shape == want.shape, \
        f"{name}: shape {got.shape} vs numpy {want.shape}"
    # dtype kind must agree (value-dtype divergence); exact width may
    # differ (numpy promotes to 64-bit where the 32-bit default applies)
    kind_g = "f" if got.dtype.kind == "f" else got.dtype.kind
    kind_w = "f" if want.dtype.kind == "f" else want.dtype.kind
    if kind_w in "fiub":
        assert kind_g == kind_w or (kind_w in "iu" and kind_g in "iu"), \
            f"{name}: dtype kind {got.dtype} vs numpy {want.dtype}"
    if want.dtype.kind in "fc":
        # accelerator transcendentals differ from libm by ~1e-4 relative;
        # loosen ONLY the measured offenders (reference check_consistency
        # keeps per-op tolerance maps the same way, test_utils.py:1491) so
        # exactness-preserving ops stay tight everywhere
        tol = 2e-4 if (not _on_cpu() and name in _TPU_LOOSE_OPS) else 2e-5
        onp.testing.assert_allclose(got.astype(onp.float64),
                                    want.astype(onp.float64),
                                    rtol=tol, atol=tol, err_msg=name)
    else:
        onp.testing.assert_array_equal(got, want, err_msg=name)


@pytest.mark.parametrize("name,case", FORWARD_CASES, ids=FORWARD_IDS)
def test_forward_matches_numpy(name, case):
    args, kwargs = case
    want = getattr(onp, name)(*args, **kwargs)
    got = getattr(np, name)(*[_to_mx(a) for a in args], **kwargs)
    _assert_match(_to_np(got), want, name)


@pytest.mark.parametrize("name,dtype,tol", [
    ("exp", "float16", 2e-2), ("add", "float16", 2e-2),
    ("multiply", "float16", 2e-2), ("sum", "float16", 2e-2),
    ("matmul", "float16", 2e-2), ("tanh", "float16", 2e-2),
    ("maximum", "float16", 0.0), ("abs", "float16", 0.0),
    ("sqrt", "float16", 2e-2), ("mean", "float16", 2e-2),
    ("exp", "float64", 1e-6), ("sum", "float64", 1e-6),
    ("add", "int8", 0.0), ("multiply", "int8", 0.0),
    ("maximum", "uint8", 0.0), ("sum", "int64", 0.0),
])
def test_forward_second_dtype(name, dtype, tol):
    """Second-dtype pass: each op family computed in a non-default dtype."""
    kind = onp.dtype(dtype).kind
    if kind in "iu":
        a = RNG.randint(1, 5, size=(2, 3)).astype(dtype)
        b = RNG.randint(1, 5, size=(2, 3)).astype(dtype)
    else:
        a = RNG.uniform(0.5, 2.0, size=(2, 3)).astype(dtype)
        b = RNG.uniform(0.5, 2.0, size=(2, 3)).astype(dtype)
    fn = getattr(onp, name)
    if name == "matmul":
        want = onp.matmul(a, b.T)
        got = np.matmul(np.array(a), np.array(b).T)
    elif name in ("add", "multiply", "maximum"):
        want = fn(a, b)
        got = getattr(np, name)(np.array(a), np.array(b))
    else:
        want = fn(a)
        got = getattr(np, name)(np.array(a))
    g = got.asnumpy()
    assert g.dtype.kind == onp.asarray(want).dtype.kind or \
        onp.asarray(want).dtype.kind in "iu" and g.dtype.kind in "iu"
    eff = tol or 1e-7
    if not _on_cpu() and onp.dtype(dtype).kind == "f":
        eff = max(eff, 1e-5)  # device-aware floor (see _assert_match)
    onp.testing.assert_allclose(g.astype(onp.float64),
                                onp.asarray(want).astype(onp.float64),
                                rtol=eff, atol=eff)


def test_empty_shape_dtype():
    e = np.empty((2, 3), dtype="float16")
    assert e.shape == (2, 3) and e.dtype == onp.float16
    el = np.empty_like(np.zeros((2, 2), dtype="int32"))
    assert el.shape == (2, 2) and el.dtype == onp.int32


# ---------------------------------------------------------------------------
# gradient sweep: finite differences vs autograd for every differentiable op
# ---------------------------------------------------------------------------

GX = _f((2, 3), -1.5, 1.5)
GP = _pos((2, 3), 0.6, 2.0)
GY = _f((2, 3), -1.5, 1.5)

GRAD_CASES = {
    # unary smooth (input chosen inside the op's smooth domain)
    "sin": ([GX], lambda xs: np.sin(xs[0]).sum()),
    "cos": ([GX], lambda xs: np.cos(xs[0]).sum()),
    "tan": ([_f((2, 3), -1.0, 1.0)], lambda xs: np.tan(xs[0]).sum()),
    "tanh": ([GX], lambda xs: np.tanh(xs[0]).sum()),
    "sinh": ([GX], lambda xs: np.sinh(xs[0]).sum()),
    "cosh": ([GX], lambda xs: np.cosh(xs[0]).sum()),
    "exp": ([GX], lambda xs: np.exp(xs[0]).sum()),
    "expm1": ([GX], lambda xs: np.expm1(xs[0]).sum()),
    "log": ([GP], lambda xs: np.log(xs[0]).sum()),
    "log1p": ([GP], lambda xs: np.log1p(xs[0]).sum()),
    "log2": ([GP], lambda xs: np.log2(xs[0]).sum()),
    "log10": ([GP], lambda xs: np.log10(xs[0]).sum()),
    "sqrt": ([GP], lambda xs: np.sqrt(xs[0]).sum()),
    "cbrt": ([GP], lambda xs: np.cbrt(xs[0]).sum()),
    "square": ([GX], lambda xs: np.square(xs[0]).sum()),
    "reciprocal": ([GP], lambda xs: np.reciprocal(xs[0]).sum()),
    "negative": ([GX], lambda xs: np.negative(xs[0]).sum()),
    "abs": ([GP], lambda xs: np.abs(xs[0]).sum()),
    "arcsin": ([_f((2, 3), -0.8, 0.8)], lambda xs: np.arcsin(xs[0]).sum()),
    "arccos": ([_f((2, 3), -0.8, 0.8)], lambda xs: np.arccos(xs[0]).sum()),
    "arctan": ([GX], lambda xs: np.arctan(xs[0]).sum()),
    "arcsinh": ([GX], lambda xs: np.arcsinh(xs[0]).sum()),
    "arccosh": ([_pos((2, 3), 1.5, 3.0)], lambda xs: np.arccosh(xs[0]).sum()),
    "arctanh": ([_f((2, 3), -0.8, 0.8)], lambda xs: np.arctanh(xs[0]).sum()),
    "deg2rad": ([GX], lambda xs: np.deg2rad(xs[0]).sum()),
    "rad2deg": ([GX], lambda xs: np.rad2deg(xs[0]).sum()),
    # binary
    "add": ([GX, GY], lambda xs: np.add(xs[0], xs[1]).sum()),
    "subtract": ([GX, GY], lambda xs: np.subtract(xs[0], xs[1]).sum()),
    "multiply": ([GX, GY], lambda xs: np.multiply(xs[0], xs[1]).sum()),
    "divide": ([GX, GP], lambda xs: np.divide(xs[0], xs[1]).sum()),
    "power": ([GP, GY], lambda xs: np.power(xs[0], xs[1]).sum()),
    "hypot": ([GP, GP + 0.3], lambda xs: np.hypot(xs[0], xs[1]).sum()),
    "arctan2": ([GX, GP], lambda xs: np.arctan2(xs[0], xs[1]).sum()),
    "maximum": ([GX, GX + 0.3], lambda xs: np.maximum(xs[0], xs[1]).sum()),
    "minimum": ([GX, GX + 0.3], lambda xs: np.minimum(xs[0], xs[1]).sum()),
    "broadcast_binary": ([GX, _f((1, 3))],
                         lambda xs: (xs[0] * xs[1]).sum()),
    # reductions / compositions
    "sum_axis": ([GX], lambda xs: xs[0].sum(axis=1).sum()),
    "mean": ([GX], lambda xs: xs[0].mean(axis=0).sum()),
    "prod": ([GP], lambda xs: np.prod(xs[0], axis=1).sum()),
    "std": ([GX], lambda xs: np.std(xs[0], axis=1).sum()),
    "var": ([GX], lambda xs: np.var(xs[0], axis=1).sum()),
    "max": ([_f((2, 3)) + onp.arange(6, dtype=onp.float32).reshape(2, 3) * 10],
            lambda xs: xs[0].max(axis=1).sum()),
    "min": ([_f((2, 3)) + onp.arange(6, dtype=onp.float32).reshape(2, 3) * 10],
            lambda xs: xs[0].min(axis=1).sum()),
    "cumsum": ([GX], lambda xs: np.cumsum(xs[0], axis=1).sum()),
    "trace": ([_f((3, 3))], lambda xs: np.trace(xs[0]).sum()),
    "diff": ([GX], lambda xs: np.diff(xs[0], axis=1).sum()),
    "clip": ([_f((2, 3), -0.4, 0.4)],
             lambda xs: np.clip(xs[0], -0.5, 0.5).sum()),
    # linear algebra
    "dot": ([_f((2, 3)), _f((3, 2))], lambda xs: np.dot(xs[0], xs[1]).sum()),
    "matmul": ([_f((2, 3)), _f((3, 2))],
               lambda xs: np.matmul(xs[0], xs[1]).sum()),
    "inner": ([V4, W4], lambda xs: np.inner(xs[0], xs[1]).sum()),
    "outer": ([V4, W4], lambda xs: np.outer(xs[0], xs[1]).sum()),
    "tensordot": ([_f((2, 3)), _f((3, 2))],
                  lambda xs: np.tensordot(xs[0], xs[1], axes=1).sum()),
    "kron": ([_f((2, 2)), _f((2, 2))],
             lambda xs: np.kron(xs[0], xs[1]).sum()),
    "einsum": ([_f((2, 3)), _f((3, 2))],
               lambda xs: np.einsum("ij,jk->ik", xs[0], xs[1]).sum()),
    # shape ops (gradients must route through the layout change)
    "reshape": ([GX], lambda xs: (xs[0].reshape(3, 2) ** 2).sum()),
    "transpose": ([GX], lambda xs: (xs[0].T ** 2).sum()),
    "squeeze_expand": ([GX], lambda xs: (
        np.squeeze(np.expand_dims(xs[0], 1), 1) ** 2).sum()),
    "broadcast_to": ([_f((1, 3))], lambda xs: (
        np.broadcast_to(xs[0], (2, 3)) ** 2).sum()),
    "tile": ([GX], lambda xs: (np.tile(xs[0], (2, 1)) ** 2).sum()),
    "repeat": ([GX], lambda xs: (np.repeat(xs[0], 2, axis=0) ** 2).sum()),
    "concatenate": ([GX, GY], lambda xs: (
        np.concatenate([xs[0], xs[1]], axis=0) ** 2).sum()),
    "stack": ([GX, GY], lambda xs: (
        np.stack([xs[0], xs[1]]) ** 2).sum()),
    "split": ([GX], lambda xs: (np.split(xs[0], 3, axis=1)[1] ** 2).sum()),
    "flip": ([GX], lambda xs: (np.flip(xs[0], 0) * GY).sum()),
    "roll": ([GX], lambda xs: (np.roll(xs[0], 1, axis=1) * GY).sum()),
    "pad": ([GX], lambda xs: (np.pad(xs[0], ((1, 1), (0, 0))) ** 2).sum()),
    "where": ([GX, GY], lambda xs: np.where(
        np.array(BL23), xs[0], xs[1]).sum()),
    "take": ([GX], lambda xs: xs[0].take(
        np.array(onp.array([0, 2], onp.int32)), axis=1).sum()),
    "getitem": ([GX], lambda xs: (xs[0][:, 1:] ** 2).sum()),
}

GRAD_IDS = sorted(GRAD_CASES)


@pytest.mark.parametrize("name", GRAD_IDS)
def test_gradient_matches_finite_difference(name):
    arrays, f = GRAD_CASES[name]
    inputs = [np.array(a) for a in arrays]
    check_numeric_gradient(f, inputs, eps=1e-2, rtol=2e-2, atol=1e-2)

"""NumPy array-function/ufunc protocol interop (reference:
python/mxnet/numpy_dispatch_protocol.py + numpy/multiarray.py:318-413;
tests/python/unittest/test_numpy_interoperability.py)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, np
from mxnet_tpu.numpy.multiarray import ndarray


def test_ufunc_dispatch_returns_mx():
    a = np.array([1.0, 2.0])
    b = onp.array([3.0, 4.0], onp.float32)
    for expr in (lambda: onp.add(b, a), lambda: b * a, lambda: onp.exp(a),
                 lambda: b - a, lambda: onp.maximum(b, a)):
        r = expr()
        assert isinstance(r, ndarray), expr


def test_array_function_dispatch_returns_mx():
    a = np.array([[1.0, 2.0], [3.0, 4.0]])
    assert isinstance(onp.concatenate([a, a]), ndarray)
    assert isinstance(onp.mean(a), ndarray)
    assert isinstance(onp.transpose(a), ndarray)
    onp.testing.assert_allclose(onp.sum(a).asnumpy(), 10.0)


def test_grad_flows_through_dispatched_ufunc():
    a = np.array([1.0, 2.0])
    a.attach_grad()
    with autograd.record():
        y = onp.multiply(a, a).sum()
    y.backward()
    onp.testing.assert_allclose(a.grad.asnumpy(), [2.0, 4.0])


def test_fallback_refused_under_recording():
    # an op neither mx.np nor jnp provides falls back to host numpy —
    # which must refuse inside record() (grads cannot flow)
    a = np.array([1.0, 2.0])
    a.attach_grad()
    called = {}

    # force the fallback path directly
    with autograd.record():
        _ = a * a  # have an active tape
        with pytest.raises(mx.base.MXNetError, match="fall"):
            ndarray._np_fallback(onp.busday_count, ("2020-01-01",
                                                    "2020-01-05"), {})


def test_fallback_outside_recording_wraps():
    a = np.array([3.0, 1.0, 2.0])
    out = ndarray._np_fallback(onp.sort, (a,), {})
    assert isinstance(out, ndarray)
    onp.testing.assert_allclose(out.asnumpy(), [1.0, 2.0, 3.0])

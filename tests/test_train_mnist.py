"""End-to-end convergence smoke test — the SURVEY §7 stage-4 milestone:
Gluon LeNet on (synthetic) MNIST, eager + hybridized, DataLoader + Trainer.
Reference: tests/python/train/test_autograd.py (trains MNIST MLP, asserts
accuracy)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data.vision import transforms


def _lenet():
    net = nn.HybridSequential()
    net.add(
        nn.Conv2D(8, kernel_size=5, activation="relu"),
        nn.MaxPool2D(2, 2),
        nn.Conv2D(16, kernel_size=3, activation="relu"),
        nn.MaxPool2D(2, 2),
        nn.Flatten(),
        nn.Dense(64, activation="relu"),
        nn.Dense(10),
    )
    return net


def _train(hybridize, epochs=3, n=1024):
    mx.random.seed(0)
    onp.random.seed(0)
    dataset = gluon.data.vision.MNIST(train=True).take(n)
    transform = transforms.Compose([transforms.ToTensor()])
    dataset = dataset.transform_first(lambda x: transform(x))
    loader = gluon.data.DataLoader(dataset, batch_size=64, shuffle=True)

    net = _lenet()
    net.initialize(mx.init.Xavier())
    if hybridize:
        net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    metric = gluon.metric.Accuracy()
    for _ in range(epochs):
        metric.reset()
        for data, label in loader:
            with autograd.record():
                out = net(data)
                loss = loss_fn(out, label)
            loss.backward()
            trainer.step(data.shape[0])
            metric.update([label], [out])
    return metric.get()[1], net


@pytest.mark.parametrize("hybridize", [
    pytest.param(False, marks=pytest.mark.slow), True])
def test_lenet_mnist_converges(hybridize):
    acc, _ = _train(hybridize)
    assert acc > 0.75, f"accuracy too low: {acc}"


def test_eager_hybrid_same_predictions():
    mx.random.seed(3)
    net = _lenet()
    net.initialize()
    x = np.random.uniform(size=(4, 1, 28, 28))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    onp.testing.assert_allclose(eager, hybrid, rtol=1e-4, atol=1e-5)

"""Versioned native operator plugin ABI (reference: include/mxnet/
lib_api.h + src/lib_api.cc version handshake; example/extensions/
lib_custom_op)."""
import ctypes
import os
import subprocess

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _build_example():
    from mxnet_tpu import native
    src = os.path.join(REPO, "native", "mxtpu_plugin_example.cc")
    out = os.path.join(native._build_dir(), "libmxtpu_plugin_example.so")
    if not (os.path.exists(out)
            and os.path.getmtime(out) >= os.path.getmtime(src)):
        os.makedirs(native._build_dir(), exist_ok=True)
        r = subprocess.run(["g++", "-O2", "-shared", "-fPIC", src, "-o", out],
                           capture_output=True, text=True)
        if r.returncode != 0:
            pytest.skip(f"no toolchain: {r.stderr[-200:]}")
    return out


def test_plugin_loads_and_registers_ops():
    so = _build_example()
    mx.library.load(so)
    from mxnet_tpu.ops import registry
    info = registry.get("plugin_softsign")
    assert info is not None and "plugin" in info.source

    x = np.array(onp.array([-2.0, 0.0, 3.0], onp.float32))
    got = info.fn(x).asnumpy()
    want = x.asnumpy() / (1 + onp.abs(x.asnumpy()))
    onp.testing.assert_allclose(got, want, rtol=1e-6)

    ss = registry.get("plugin_scale_shift").fn
    got = ss(x, params=(2.0, 1.0)).asnumpy()
    onp.testing.assert_allclose(got, 2 * x.asnumpy() + 1, rtol=1e-6)


def test_plugin_op_under_jit():
    so = _build_example()
    mx.library.load(so)
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops import registry
    fn = registry.get("plugin_softsign").fn

    @jax.jit
    def f(v):
        return fn(v) * 2.0

    v = jnp.asarray([1.0, -1.0], jnp.float32)
    onp.testing.assert_allclose(onp.asarray(f(v)), [1.0, -1.0], rtol=1e-6)


def test_plugin_abi_mismatch_rejected(tmp_path):
    src = tmp_path / "bad.cc"
    src.write_text("""
extern "C" {
int mxtpu_plugin_abi_version(void) { return 999; }
const char* mxtpu_plugin_name(void) { return "bad"; }
int mxtpu_plugin_num_ops(void) { return 0; }
const char* mxtpu_plugin_op_name(int) { return ""; }
void mxtpu_plugin_op_call(int, const float*, float*, long long,
                          const float*, int) {}
}
""")
    so = str(tmp_path / "libbad.so")
    r = subprocess.run(["g++", "-O0", "-shared", "-fPIC", str(src),
                        "-o", so], capture_output=True, text=True)
    if r.returncode != 0:
        pytest.skip("no toolchain")
    with pytest.raises(mx.base.MXNetError, match="ABI v999"):
        mx.library.load(so)

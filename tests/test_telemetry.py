"""mx.telemetry — metrics registry, recompilation detector, run reports
(docs/OBSERVABILITY.md).

The contract under test: disabled hooks are strict no-ops (the CI
`telemetry` stage additionally bounds their cost at <2% of a tight eager
loop, benchmark/telemetry_overhead.py); enabled, every wired subsystem
lands live values in counters()/exposition() and the TrainingTelemetry
JSONL run report.
"""
import json
import threading
import warnings

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.data import DataLoader


class _SynthDataset:
    """Picklable (spawn workers) linearly-separable classification set."""

    def __init__(self, n=128, dim=16, classes=3):
        rs = onp.random.RandomState(0)
        self.x = rs.rand(n, dim).astype(onp.float32)
        w = rs.rand(dim, classes).astype(onp.float32)
        self.y = (self.x @ w).argmax(axis=1).astype(onp.int32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i], self.y[i]


@pytest.fixture(autouse=True)
def _clean_telemetry_state():
    telemetry.disable()
    telemetry.reset()
    yield
    telemetry.disable()
    telemetry.reset()
    mx.fault.clear()
    mx.fault.reset_stats()
    mx.config.reset()


def _mlp(classes=3):
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(classes))
    net.initialize(mx.init.Xavier())
    return net


# ---------------------------------------------------------------------------
# registry basics
# ---------------------------------------------------------------------------

def test_disabled_hooks_are_noops():
    assert not telemetry.active()
    telemetry.inc("trainer.steps_total")
    telemetry.set_gauge("dataloader.queue_depth", 3)
    telemetry.observe("trainer.step_seconds", 0.1)
    with telemetry.timed("trainer.step_seconds"):
        pass
    # instrumented subsystems run without recording anything
    out = (mx.np.ones((2, 2)) * 3).asnumpy()
    assert onp.isfinite(out).all()
    assert telemetry.counters() == {}
    assert telemetry.summary_line() == ""
    assert telemetry.snapshot() == {
        "counters": {}, "gauges": {}, "histograms": {}, "sync_sites": {}}
    assert telemetry.exposition() == ""


def test_counter_gauge_histogram_and_labels():
    telemetry.enable()
    telemetry.inc("kvstore.collective_total", op="allreduce")
    telemetry.inc("kvstore.collective_total", 2, op="reconcile")
    telemetry.set_gauge("dataloader.queue_depth", 4)
    for v in (0.0002, 0.003, 2.0):
        telemetry.observe("trainer.step_seconds", v)

    flat = telemetry.counters()
    assert flat['kvstore.collective_total{op="allreduce"}'] == 1
    assert flat['kvstore.collective_total{op="reconcile"}'] == 2
    agg = telemetry.counters(aggregate=True)
    assert agg["kvstore.collective_total"] == 3
    assert telemetry.counters(prefix="dataloader") == {}
    assert "kvstore.collective_total=3" in telemetry.summary_line()

    snap = telemetry.snapshot()
    hist = snap["histograms"]["trainer.step_seconds"]
    assert hist["count"] == 3
    assert hist["sum"] == pytest.approx(2.0032)
    # cumulative buckets, json-safe "+Inf" key
    assert hist["buckets"]["+Inf"] == 3
    assert hist["buckets"]["0.00025"] == 1
    json.dumps(snap)  # JSON-safe end to end


def test_exposition_prometheus_format():
    telemetry.enable()
    telemetry.inc("trainer.steps_total", 5)
    telemetry.observe("trainer.step_seconds", 0.002)
    telemetry.set_gauge("dataloader.queue_depth", 2)
    text = telemetry.exposition()
    assert "# HELP mxnet_trainer_steps_total" in text
    assert "# TYPE mxnet_trainer_steps_total counter" in text
    assert "mxnet_trainer_steps_total 5" in text
    assert "# TYPE mxnet_dataloader_queue_depth gauge" in text
    assert "# TYPE mxnet_trainer_step_seconds histogram" in text
    assert 'mxnet_trainer_step_seconds_bucket{le="+Inf"} 1' in text
    assert "mxnet_trainer_step_seconds_sum 0.002" in text
    assert "mxnet_trainer_step_seconds_count 1" in text
    # cumulative: every later bucket >= earlier
    counts = [int(line.rsplit(" ", 1)[1]) for line in text.splitlines()
              if line.startswith("mxnet_trainer_step_seconds_bucket")]
    assert counts == sorted(counts)


def test_metric_kind_mismatch_raises():
    telemetry.enable()
    telemetry.inc("trainer.steps_total")
    with pytest.raises(MXNetError, match="is a counter"):
        telemetry.observe("trainer.steps_total", 1.0)
    with pytest.raises(MXNetError, match="unknown metric kind"):
        telemetry.declare_metric("x.y", "summary", "nope")


def test_threaded_recording_is_exact():
    telemetry.enable()
    n_threads, per = 8, 500

    def work():
        for _ in range(per):
            telemetry.inc("trainer.steps_total")
            telemetry.observe("trainer.step_seconds", 0.001)

    threads = [threading.Thread(target=work) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert telemetry.counters()["trainer.steps_total"] == n_threads * per
    hist = telemetry.snapshot()["histograms"]["trainer.step_seconds"]
    assert hist["count"] == n_threads * per
    assert hist["sum"] == pytest.approx(n_threads * per * 0.001)


def test_timed_records_wall_time():
    telemetry.enable()
    with telemetry.timed("kvstore.collective_seconds", op="allreduce"):
        pass
    hist = telemetry.snapshot()["histograms"][
        'kvstore.collective_seconds{op="allreduce"}']
    assert hist["count"] == 1 and hist["sum"] >= 0


def test_config_knob_and_configure():
    mx.config.set("telemetry.enable", True)
    assert telemetry.configure() is True
    assert telemetry.active()
    mx.config.set("telemetry.enable", False)
    assert telemetry.configure() is False


# ---------------------------------------------------------------------------
# wired subsystems
# ---------------------------------------------------------------------------

def test_cached_graph_hit_miss_and_compile_metrics():
    telemetry.enable()
    net = _mlp()
    net.hybridize()
    x = mx.np.ones((4, 16))
    net(x)  # eager deferred-init pass
    net(x)  # first compiled call: traces the root block
    net(x)  # replay from the signature cache
    agg = telemetry.counters(aggregate=True)
    assert agg.get("cached_graph.cache_hit_total", 0) >= 1
    assert agg.get("cached_graph.cache_miss_total", 0) >= 1
    assert agg.get("cached_graph.compile_total", 0) >= 1
    hist = telemetry.snapshot()["histograms"][
        'cached_graph.compile_seconds{block="HybridSequential"}']
    assert hist["count"] >= 1 and hist["sum"] > 0


def test_recompile_detector_fires_exactly_once():
    mx.config.set("telemetry.recompile_limit", 2)
    telemetry.enable()
    net = _mlp()
    net.hybridize()
    net(mx.np.ones((2, 16)))  # eager deferred-init pass
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        # shape-polymorphic batch dim: every size is a fresh signature
        for bs in (1, 2, 3, 4, 5, 6):
            net(mx.np.ones((bs, 16)))
    recompiles = [w for w in caught
                  if issubclass(w.category, telemetry.RecompileWarning)]
    assert len(recompiles) == 1, \
        f"detector must warn exactly once, got {len(recompiles)}"
    w = recompiles[0].message
    assert w.block == "HybridSequential"
    assert w.limit == 2 and w.compiles > 2
    assert "recompile_limit" in str(w)
    agg = telemetry.counters(aggregate=True)
    assert agg["cached_graph.recompile_warnings_total"] == 1
    assert agg["cached_graph.compile_total"] > 2


def test_recompile_detector_quiet_under_limit():
    telemetry.enable()  # default limit 8
    net = _mlp()
    net.hybridize()
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        for _ in range(4):
            net(mx.np.ones((4, 16)))
    assert not [w for w in caught
                if issubclass(w.category, telemetry.RecompileWarning)]


def test_dataloader_metrics():
    telemetry.enable()
    ds = _SynthDataset(64)
    loader = DataLoader(ds, batch_size=8, num_workers=2)
    batches = sum(1 for _ in loader)
    assert batches == 8
    agg = telemetry.counters(aggregate=True)
    assert agg["dataloader.batches_total"] == batches
    snap = telemetry.snapshot()
    assert snap["histograms"]["dataloader.wait_seconds"]["count"] == batches
    assert "dataloader.queue_depth" in snap["gauges"]


def test_trainer_step_metrics_and_nonfinite_guard():
    mx.config.set("trainer.skip_nonfinite", True)
    telemetry.enable()
    net = _mlp()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1e-2})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.np.ones((8, 16))
    y = mx.np.zeros((8,))
    for i in range(3):
        if i == 1:
            mx.fault.configure("invoke.nan_output:at=1,times=1")
        with autograd.record():
            loss = loss_fn(net(x), y)
        mx.fault.clear()
        loss.backward()
        trainer.step(8)
    agg = telemetry.counters(aggregate=True)
    assert agg["trainer.steps_total"] == 3
    assert agg["trainer.nonfinite_total"] >= 1
    # grad norms accumulate on-device (sync-free step loop); the drain at
    # the epoch boundary folds them into the histogram
    trainer.drain_telemetry()
    snap = telemetry.snapshot()
    assert snap["histograms"]["trainer.step_seconds"]["count"] == 3
    # finite steps observed their global grad norm
    assert snap["histograms"]["trainer.grad_norm"]["count"] >= 1
    # the fault mirror carries the recovery event too
    assert agg["fault.events_total"] >= 1


def test_kvstore_collective_metrics():
    telemetry.enable()
    kv = mx.kv.create("dist_sync")
    kv.init("a", mx.np.zeros((32,)))
    out = mx.np.empty((32,))
    kv.pushpull("a", mx.np.full((32,), 2.0), out=out)
    onp.testing.assert_array_equal(out.asnumpy(), onp.full((32,), 2.0))
    flat = telemetry.counters()
    assert flat['kvstore.collective_total{op="allreduce"}'] >= 1
    assert flat["kvstore.payload_bytes_total"] >= 32 * 4
    hist = telemetry.snapshot()["histograms"][
        'kvstore.collective_seconds{op="allreduce"}']
    assert hist["count"] >= 1


def test_fault_events_mirror_into_telemetry():
    telemetry.enable()
    mx.fault.record("trainer.nonfinite_skip")
    mx.fault.record("checkpoint.rejected", 2)
    flat = telemetry.counters()
    assert flat['fault.events_total{event="trainer.nonfinite_skip"}'] == 1
    assert flat['fault.events_total{event="checkpoint.rejected"}'] == 2


# ---------------------------------------------------------------------------
# TrainingTelemetry reporter
# ---------------------------------------------------------------------------

def test_training_telemetry_jsonl_roundtrip(tmp_path):
    path = str(tmp_path / "run.jsonl")
    with telemetry.TrainingTelemetry(path=path, interval=2,
                                     run_id="t1") as rep:
        assert telemetry.active()  # constructing the reporter enables
        for i in range(4):
            rep.step(loss=0.5 - 0.1 * i)
        rep.mark("epoch", epoch=1)
    records = telemetry.TrainingTelemetry.read(path)
    kinds = [r["type"] for r in records]
    assert kinds == ["run_begin", "step", "step", "epoch", "run_report"]
    assert all(r["run_id"] == "t1" for r in records)
    steps = [r for r in records if r["type"] == "step"]
    assert steps[0]["step"] == 2 and steps[1]["step"] == 4
    assert steps[0]["loss"] == pytest.approx(0.4)
    assert "counters" in steps[0]
    report = records[-1]
    assert report["steps"] == 4
    assert report["wall_seconds"] >= 0
    assert report["metrics"]["histograms"]["train.iter_seconds"]["count"] == 4
    # close() restored the registry's prior (disabled) state
    assert not telemetry.active()


def test_training_telemetry_restores_enabled_state():
    telemetry.enable()
    rep = telemetry.TrainingTelemetry(run_id="t2")
    rep.step()
    rep.close()
    assert telemetry.active()  # was on before: stays on
    assert rep.close() is rep.close()  # idempotent


def test_telemetry_handler_drives_reporter(tmp_path):
    from mxnet_tpu.gluon.contrib.estimator import TelemetryHandler
    path = str(tmp_path / "est.jsonl")
    h = TelemetryHandler(path=path, run_id="est")
    h.train_begin(None)
    for _ in range(3):
        h.batch_end(None, loss=mx.np.ones((4,)))
    h.epoch_end(None)
    h.train_end(None)
    assert h.run_report["steps"] == 3
    kinds = [r["type"] for r in telemetry.TrainingTelemetry.read(path)]
    assert kinds == ["run_begin", "step", "step", "step", "epoch",
                     "run_report"]
    steps = [r for r in telemetry.TrainingTelemetry.read(path)
             if r["type"] == "step"]
    assert steps[0]["loss"] == pytest.approx(1.0)


def test_logging_handler_appends_telemetry_summary(caplog):
    import logging
    from mxnet_tpu.gluon.contrib.estimator import LoggingHandler
    telemetry.enable()
    telemetry.inc("trainer.steps_total", 7)
    h = LoggingHandler()
    with caplog.at_level(logging.INFO, logger="estimator"):
        h.epoch_end(None)
    assert "trainer.steps_total=7" in caplog.text


def test_profiler_run_auto_enables_telemetry():
    from mxnet_tpu import profiler
    assert not telemetry.active()
    profiler.set_state("run")
    try:
        assert telemetry.active()
    finally:
        profiler.set_state("stop")
    assert not telemetry.active()  # bridge-armed: stop disarms
    # an explicit enable survives a profiler cycle
    telemetry.enable()
    profiler.set_state("run")
    profiler.set_state("stop")
    assert telemetry.active()


def test_reporter_records_land_in_profiler(tmp_path):
    from mxnet_tpu import profiler
    profiler.set_state("run")
    try:
        rep = telemetry.TrainingTelemetry(run_id="prof", interval=1)
        rep.step(loss=1.0)
        rep.close()
        rows = json.loads(profiler.dumps(format="json", reset=True))
        names = {r["name"] for r in rows["aggregates"]}
        assert "telemetry.step" in names
        assert "telemetry.run_report" in names
    finally:
        profiler.set_state("stop")


# ---------------------------------------------------------------------------
# end to end: one training run covers every wired subsystem
# ---------------------------------------------------------------------------

def test_e2e_training_run_covers_all_subsystems(tmp_path):
    mx.config.set("trainer.skip_nonfinite", True)
    mx.config.set("telemetry.recompile_limit", 2)
    mx.random.seed(0)

    ds = _SynthDataset(128)
    loader = DataLoader(ds, batch_size=16, num_workers=2)
    net = _mlp()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-2}, kvstore="dist_sync")
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    path = str(tmp_path / "e2e.jsonl")

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        with telemetry.TrainingTelemetry(path=path, run_id="e2e") as rep:
            for epoch in range(2):
                for i, (data, label) in enumerate(loader):
                    if epoch == 0 and i == 2:
                        # poison this batch: the multiply is the injection's
                        # first probed eager op, so its output becomes
                        # all-NaN and taints the gradients of the compiled
                        # forward (the net itself replays inside XLA, where
                        # transient-fault injection does not reach)
                        mx.fault.configure("invoke.nan_output:at=1,times=1")
                        data = data * 1.0
                        mx.fault.clear()
                    with autograd.record():
                        loss = loss_fn(net(data), label)
                    loss.backward()
                    trainer.step(data.shape[0])
                    rep.step(loss=float(loss.mean().item()))
                # epoch boundary: fold the deferred on-device grad norms
                # into the histogram before marking/reporting
                trainer.drain_telemetry()
                rep.mark("epoch", epoch=epoch)
            # deliberately shape-polymorphic tail: trips the detector
            for bs in (1, 3, 5, 7):
                net(mx.np.ones((bs, 16)))
            report = rep.close()

    recompiles = [w for w in caught
                  if issubclass(w.category, telemetry.RecompileWarning)]
    assert len(recompiles) == 1

    # the exposition carries live metrics from all five subsystems
    text = telemetry.exposition()
    for marker in ("mxnet_cached_graph_compile_total",
                   "mxnet_cached_graph_cache_hit_total",
                   "mxnet_dataloader_batches_total",
                   "mxnet_trainer_steps_total",
                   "mxnet_trainer_grad_norm",
                   "mxnet_kvstore_collective_total",
                   "mxnet_fault_events_total",
                   "mxnet_cached_graph_recompile_warnings_total"):
        assert marker in text, f"exposition missing {marker}"

    # ... and so does the run report
    agg = report["metrics"]["counters"]

    def total(prefix):
        return sum(v for k, v in agg.items() if k.startswith(prefix))

    assert report["steps"] == 2 * len(loader)
    assert total("cached_graph.compile_total") > 2
    assert total("dataloader.batches_total") >= 2 * len(loader)
    assert total("trainer.steps_total") == 2 * len(loader)
    assert total("trainer.nonfinite_total") >= 1
    assert total("kvstore.collective_total") >= 1
    assert total("fault.events_total") >= 1
    records = telemetry.TrainingTelemetry.read(path)
    assert records[0]["type"] == "run_begin"
    assert records[-1]["type"] == "run_report"

"""Gluon Block/HybridBlock/Parameter (reference: tests/python/unittest/
test_gluon.py — incl. the implicit eager-vs-hybridized equivalence checks)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, np
from mxnet_tpu.gluon import nn
from mxnet_tpu.test_utils import assert_almost_equal


def test_dense():
    layer = nn.Dense(4, in_units=3)
    layer.initialize()
    x = np.random.uniform(size=(2, 3))
    out = layer(x)
    assert out.shape == (2, 4)
    w = layer.weight.data().asnumpy()
    b = layer.bias.data().asnumpy()
    assert_almost_equal(out, x.asnumpy() @ w.T + b, rtol=1e-5)


def test_dense_deferred_init():
    layer = nn.Dense(4)
    layer.initialize()
    x = np.random.uniform(size=(5, 7))
    out = layer(x)
    assert out.shape == (5, 4)
    assert layer.weight.shape == (4, 7)


def test_collect_params_names():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    params = net.collect_params()
    assert set(params) == {"0.weight", "0.bias", "1.weight", "1.bias"}
    sel = net.collect_params(".*weight")
    assert set(sel) == {"0.weight", "1.weight"}


def test_sequential_forward():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu", in_units=4), nn.Dense(3, in_units=8))
    net.initialize()
    x = np.random.uniform(size=(2, 4))
    assert net(x).shape == (2, 3)


def test_hybridize_equivalence():
    mx.random.seed(7)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="tanh", in_units=8), nn.Dense(4, in_units=16))
    net.initialize()
    x = np.random.uniform(size=(3, 8))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert_almost_equal(eager, hybrid, rtol=1e-5, atol=1e-6)
    # second call hits the executable cache
    hybrid2 = net(x).asnumpy()
    assert_almost_equal(hybrid, hybrid2)


def test_hybridize_grad():
    net = nn.Dense(1, in_units=3)
    net.initialize()
    x = np.array([[1.0, 2.0, 3.0]])
    with autograd.record():
        y = net(x).sum()
    y.backward()
    g_eager = net.weight.grad().asnumpy()

    net.hybridize()
    net.zero_grad()
    with autograd.record():
        y = net(x).sum()
    y.backward()
    g_hybrid = net.weight.grad().asnumpy()
    assert_almost_equal(g_eager, g_hybrid, rtol=1e-5)
    assert_almost_equal(g_eager, onp.tile(x.asnumpy(), (1, 1)), rtol=1e-5)


def test_conv2d():
    layer = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3)
    layer.initialize()
    x = np.random.uniform(size=(2, 3, 16, 16))
    out = layer(x)
    assert out.shape == (2, 8, 16, 16)
    layer_s = nn.Conv2D(4, kernel_size=3, strides=2)
    layer_s.initialize()
    assert layer_s(x).shape == (2, 4, 7, 7)


def test_conv_grouped_dilated():
    layer = nn.Conv2D(6, kernel_size=3, groups=3, dilation=2, in_channels=3)
    layer.initialize()
    x = np.random.uniform(size=(1, 3, 12, 12))
    assert layer(x).shape == (1, 6, 8, 8)


def test_conv_transpose():
    layer = nn.Conv2DTranspose(4, kernel_size=2, strides=2, in_channels=3)
    layer.initialize()
    x = np.random.uniform(size=(1, 3, 8, 8))
    assert layer(x).shape == (1, 4, 16, 16)


def test_pooling():
    x = np.random.uniform(size=(1, 2, 8, 8))
    assert nn.MaxPool2D(2)(x).shape == (1, 2, 4, 4)
    assert nn.AvgPool2D(2)(x).shape == (1, 2, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (1, 2, 1, 1)
    assert nn.GlobalMaxPool2D()(x).shape == (1, 2, 1, 1)
    mp = nn.MaxPool2D(3, 2, 1)(x)
    assert mp.shape == (1, 2, 4, 4)


def test_batchnorm_train_eval():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = np.random.uniform(1, 3, size=(8, 4, 5, 5))
    rm0 = bn.running_mean.data().asnumpy().copy()
    with autograd.record():
        out = bn(x)
    # training: batch stats used, running stats updated
    assert not onp.allclose(bn.running_mean.data().asnumpy(), rm0)
    assert abs(float(out.mean())) < 0.2
    # eval mode: running stats used
    out_eval = bn(x)
    assert out_eval.shape == x.shape


def test_batchnorm_hybrid_aux_update():
    bn = nn.BatchNorm(in_channels=3)
    bn.initialize()
    bn.hybridize()
    x = np.random.uniform(1, 2, size=(4, 3, 2, 2))
    _ = bn(x)  # first (eager path for deferred init)
    rm_before = bn.running_mean.data().asnumpy().copy()
    with autograd.record():
        _ = bn(x)
    rm_after = bn.running_mean.data().asnumpy()
    assert not onp.allclose(rm_before, rm_after)


def test_layernorm_groupnorm():
    ln = nn.LayerNorm(in_channels=6)
    ln.initialize()
    x = np.random.uniform(size=(4, 6))
    out = ln(x)
    assert_almost_equal(out.asnumpy().mean(axis=-1), onp.zeros(4), atol=1e-5)
    gn = nn.GroupNorm(num_groups=2, in_channels=4)
    gn.initialize()
    y = np.random.uniform(size=(2, 4, 3, 3))
    assert gn(y).shape == (2, 4, 3, 3)


def test_embedding():
    emb = nn.Embedding(10, 4)
    emb.initialize()
    idx = np.array([[1, 2], [3, 4]], dtype="int32")
    out = emb(idx)
    assert out.shape == (2, 2, 4)


def test_dropout():
    do = nn.Dropout(0.5)
    x = np.ones((100, 100))
    out_eval = do(x)
    assert_almost_equal(out_eval, x)  # identity outside training
    with autograd.record():
        out_train = do(x)
    frac_zero = float((out_train == 0).sum()) / out_train.size
    assert 0.3 < frac_zero < 0.7


def test_activation_blocks():
    x = np.array([-1.0, 0.0, 1.0])
    assert_almost_equal(nn.Activation("relu")(x), onp.array([0, 0, 1.0]))
    assert nn.LeakyReLU(0.1)(x).asnumpy()[0] == pytest.approx(-0.1)
    assert nn.ELU()(x).shape == (3,)
    assert nn.SELU()(x).shape == (3,)
    prelu = nn.PReLU()
    prelu.initialize()
    assert prelu(x).shape == (3,)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    path = str(tmp_path / "net.params")
    net.save_parameters(path)

    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net2.load_parameters(path)
    x = np.random.uniform(size=(2, 3))
    assert_almost_equal(net(x), net2(x))


def test_share_parameters():
    a = nn.Dense(4, in_units=3)
    b = nn.Dense(4, in_units=3)
    a.initialize()
    b.share_parameters(a.collect_params())
    x = np.random.uniform(size=(1, 3))
    assert_almost_equal(a(x), b(x))


def test_cast():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    net.cast("float16")
    assert net.weight.data().dtype == onp.float16


def test_losses():
    from mxnet_tpu.gluon import loss as gloss
    pred = np.array([[1.0, 2.0], [3.0, 4.0]])
    label = np.array([[1.5, 2.5], [2.0, 3.0]])
    l2 = gloss.L2Loss()(pred, label)
    assert_almost_equal(l2, onp.array([0.125, 0.5]))
    l1 = gloss.L1Loss()(pred, label)
    assert_almost_equal(l1, onp.array([0.5, 1.0]))

    logits = np.array([[10.0, 0.0, 0.0], [0.0, 10.0, 0.0]])
    lbl = np.array([0, 1])
    ce = gloss.SoftmaxCrossEntropyLoss()(logits, lbl)
    assert float(ce.sum()) < 0.01
    h = gloss.HuberLoss()(pred, label)
    assert h.shape == (2,)
    sbce = gloss.SigmoidBinaryCrossEntropyLoss()(pred, np.ones((2, 2)))
    assert sbce.shape == (2,)


def test_loss_backward():
    from mxnet_tpu.gluon import loss as gloss
    net = nn.Dense(3, in_units=4)
    net.initialize()
    x = np.random.uniform(size=(5, 4))
    y = np.array([0, 1, 2, 0, 1])
    lossfn = gloss.SoftmaxCrossEntropyLoss()
    with autograd.record():
        l = lossfn(net(x), y).mean()
    l.backward()
    g = net.weight.grad().asnumpy()
    assert g.shape == (3, 4)
    assert onp.abs(g).sum() > 0


def test_metrics():
    from mxnet_tpu.gluon import metric
    acc = metric.Accuracy()
    pred = np.array([[0.1, 0.9], [0.8, 0.2], [0.3, 0.7]])
    label = np.array([1, 0, 0])
    acc.update([label], [pred])
    assert acc.get()[1] == pytest.approx(2.0 / 3.0)
    mse = metric.MSE()
    mse.update([np.zeros(4)], [np.ones(4)])
    assert mse.get()[1] == pytest.approx(1.0)
    comp = metric.CompositeEvalMetric([metric.Accuracy(), metric.MSE()])
    assert len(comp.get()[0]) == 2


def test_block_repr_and_summary(capsys):
    net = nn.HybridSequential()
    net.add(nn.Dense(2, in_units=3))
    net.initialize()
    repr(net)
    net.summary()
    out = capsys.readouterr().out
    assert "Total params" in out


class _ExportNet(gluon.HybridBlock):
    def __init__(self):
        super().__init__()
        self.fc = nn.Dense(4, in_units=3)

    def forward(self, x):
        return self.fc(x)


def test_hybrid_export_import(tmp_path):
    net = _ExportNet()
    net.initialize()
    net.hybridize()
    x = np.ones((1, 3))
    y0 = net(x)
    sym_file, param_file = net.export(str(tmp_path / "model"))
    net2 = gluon.SymbolBlock.imports(sym_file, ["data"], param_file)
    assert_almost_equal(y0, net2(x))


def test_hybridize_kwargs_compile():
    """Keyword calls must use the compiled path, not fall back to eager
    (round-2 regression: BERT's encoder was called with kwargs and
    silently ran eagerly)."""
    import warnings

    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import gluon

    class KwNet(gluon.nn.HybridBlock):
        def __init__(self):
            super().__init__()
            self.dense = gluon.nn.Dense(4, in_units=6)

        def forward(self, x, scale=None, flag=True):
            out = self.dense(x)
            if scale is not None:
                out = out * scale
            return out if flag else -out

    net = KwNet()
    net.initialize()
    x = mx.np.array(onp.random.randn(2, 6).astype("float32"))
    s = mx.np.array(onp.float32(2.0))
    eager = net(x, scale=s, flag=True)
    net.hybridize()
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # any eager-fallback warning fails
        out = net(x, scale=s, flag=True)
    assert net._cached_graphs, "kwargs call did not reach the compiled path"
    onp.testing.assert_allclose(out.asnumpy(), eager.asnumpy(), rtol=1e-6)
    # different static kwarg -> distinct trace, correct result
    out2 = net(x, scale=s, flag=False)
    onp.testing.assert_allclose(out2.asnumpy(), -eager.asnumpy(), rtol=1e-6)
    # positional call still works against the same cache
    out3 = net(x)
    onp.testing.assert_allclose(out3.asnumpy(),
                                net.dense(x).asnumpy(), rtol=1e-6)


def test_model_zoo_bert_encoder_compiles():
    import warnings

    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu.gluon.model_zoo.bert import BERTForPretraining

    net = BERTForPretraining(vocab_size=100, units=16, hidden_size=32,
                             num_layers=1, num_heads=2, max_length=32,
                             dropout=0.0, embed_dropout=0.0)
    net.initialize()
    ids = mx.np.array(onp.random.randint(0, 100, (2, 8)), dtype="int32")
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        net(ids)  # first call: deferred init, eager
        net(ids)  # compiled; must not warn about eager fallback

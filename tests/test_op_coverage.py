"""Operator-coverage parity locks + oracles for npx extras.

Locks in the op-surface parity measured against the reference
(python/mxnet/numpy/multiarray.py public functions, the _npi/_npx
MXNET_REGISTER_API lists from src/api/, numpy/random.py, numpy/linalg.py)
so regressions in the lazy wrapper generation are caught.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import numpy as np

# public functions of the reference numpy frontend that must exist
REF_NP = [
    "abs", "absolute", "add", "all", "amax", "amin", "any", "append",
    "arange", "arccos", "arccosh", "arcsin", "arcsinh", "arctan", "arctan2",
    "arctanh", "argmax", "argmin", "argsort", "around", "array",
    "array_split", "atleast_1d", "atleast_2d", "atleast_3d", "average",
    "bincount", "bitwise_and", "bitwise_invert", "bitwise_not", "bitwise_or",
    "bitwise_xor", "blackman", "broadcast_to", "cbrt", "ceil", "clip",
    "column_stack", "concatenate", "copysign", "cos", "cosh", "cross",
    "cumsum", "deg2rad", "degrees", "delete", "diag", "diagflat", "diagonal",
    "diff", "divide", "dot", "dsplit", "dstack", "ediff1d", "einsum",
    "empty", "empty_like", "equal", "exp", "expand_dims", "expm1", "eye",
    "fabs", "fill_diagonal", "fix", "flatnonzero", "flip", "fliplr",
    "flipud", "floor", "fmax", "fmin", "fmod", "full", "full_like", "gcd",
    "greater", "greater_equal", "hamming", "hanning", "histogram", "hsplit",
    "hstack", "hypot", "identity", "indices", "inner", "insert", "interp",
    "invert", "isfinite", "isinf", "isnan", "isneginf", "isposinf", "kron",
    "lcm", "ldexp", "less", "less_equal", "linspace", "log", "log10",
    "log1p", "log2", "logical_and", "logical_not", "logical_or",
    "logical_xor", "logspace", "matmul", "max", "maximum", "mean", "median",
    "min", "minimum", "mod", "moveaxis", "multiply", "nan_to_num",
    "nanprod", "nansum", "negative", "nonzero", "not_equal", "ones",
    "ones_like", "outer", "pad", "percentile", "polyval", "power", "prod",
    "quantile", "rad2deg", "radians", "ravel", "reciprocal", "remainder",
    "repeat", "reshape", "resize", "rint", "roll", "rollaxis", "rot90",
    "round", "row_stack", "sign", "sin", "sinh", "sort", "split", "sqrt",
    "square", "squeeze", "stack", "std", "subtract", "sum", "swapaxes",
    "take", "tan", "tanh", "tensordot", "tile", "trace", "transpose", "tri",
    "tril", "tril_indices", "triu", "triu_indices", "true_divide", "trunc",
    "unique", "unravel_index", "var", "vdot", "vsplit", "vstack", "where",
    "zeros", "zeros_like",
]

REF_NPX = [
    "activation", "arange_like", "batch_dot", "batch_norm", "broadcast_like",
    "cond", "convolution", "deconvolution", "dropout", "embedding",
    "foreach", "fully_connected", "group_norm", "layer_norm", "leaky_relu",
    "log_softmax", "masked_log_softmax", "masked_softmax", "one_hot",
    "pick", "pooling", "rnn", "softmax", "topk", "while_loop", "reshape",
    "constraint_check", "nonzero", "gamma", "sequence_mask",
]

REF_RANDOM = [
    "beta", "chisquare", "choice", "exponential", "f", "gamma", "gumbel",
    "logistic", "lognormal", "multinomial", "multivariate_normal", "normal",
    "pareto", "power", "randint", "rayleigh", "shuffle", "uniform",
    "weibull", "rand",
]

REF_LINALG = [
    "cholesky", "det", "eig", "eigh", "eigvals", "eigvalsh", "inv",
    "lstsq", "matrix_power", "matrix_rank", "multi_dot", "norm", "pinv",
    "qr", "slogdet", "solve", "svd", "tensorinv", "tensorsolve",
]


def test_np_surface_parity():
    missing = [f for f in REF_NP if not hasattr(mx.np, f)]
    assert not missing, f"mx.np missing: {missing}"


def test_npx_surface_parity():
    missing = [f for f in REF_NPX if not hasattr(mx.npx, f)]
    assert not missing, f"mx.npx missing: {missing}"


def test_random_surface_parity():
    missing = [f for f in REF_RANDOM if not hasattr(mx.np.random, f)]
    assert not missing, f"mx.np.random missing: {missing}"


def test_linalg_surface_parity():
    missing = [f for f in REF_LINALG if not hasattr(mx.np.linalg, f)]
    assert not missing, f"mx.np.linalg missing: {missing}"


def test_batch_dot_oracle():
    a = onp.random.randn(2, 3, 4).astype("float32")
    b = onp.random.randn(2, 4, 5).astype("float32")
    r = mx.npx.batch_dot(np.array(a), np.array(b))
    onp.testing.assert_allclose(r.asnumpy(), onp.matmul(a, b), rtol=1e-5)
    bt = onp.random.randn(2, 5, 4).astype("float32")
    r = mx.npx.batch_dot(np.array(a), np.array(bt), transpose_b=True)
    onp.testing.assert_allclose(r.asnumpy(),
                                onp.matmul(a, bt.transpose(0, 2, 1)),
                                rtol=1e-5)


def test_npx_reshape_special_codes():
    x = np.zeros((2, 3, 4, 5))
    assert mx.npx.reshape(x, (-2,)).shape == (2, 3, 4, 5)
    assert mx.npx.reshape(x, (0, -3, 0)).shape == (2, 12, 5)
    assert mx.npx.reshape(x, (0, 0, -4, 2, 2, 0)).shape == (2, 3, 2, 2, 5)
    assert mx.npx.reshape(x, (-1, 5)).shape == (24, 5)
    assert mx.npx.reshape(x, (0, 0, -4, -1, 2, 0)).shape == (2, 3, 2, 2, 5)


def test_constraint_check():
    assert bool(mx.npx.constraint_check(
        np.array([1, 1], dtype="int32")).asnumpy())
    with pytest.raises(ValueError, match="nope"):
        mx.npx.constraint_check(np.array([1, 0], dtype="int32"), "nope")


def test_npx_nonzero_indices():
    idx = mx.npx.nonzero(np.array([[1, 0], [0, 2]]))
    assert idx.asnumpy().tolist() == [[0, 0], [1, 1]]


def test_new_random_samplers():
    mx.random.seed(0)
    assert mx.np.random.logistic(size=(100,)).shape == (100,)
    assert mx.np.random.f(2.0, 3.0, size=(10,)).shape == (10,)
    mvn = mx.np.random.multivariate_normal(onp.zeros(2), onp.eye(2),
                                           size=(50,))
    assert mvn.shape == (50, 2)


def test_fill_diagonal_functional():
    out = mx.np.fill_diagonal(np.zeros((3, 3)), 5.0)
    onp.testing.assert_allclose(onp.diagonal(out.asnumpy()), [5, 5, 5])


def test_ndarray_any_all_methods():
    a = np.array([[True, False]])
    assert bool(a.any().asnumpy())
    assert not bool(a.all().asnumpy())


def test_npx_random_tail():
    """bernoulli/uniform_n/normal_n/seed/savez (reference
    numpy_extension/random.py:27-252, utils.py savez)."""
    import os
    import tempfile

    import numpy as onp
    import pytest

    import mxnet_tpu as mx
    from mxnet_tpu import numpy_extension as npx
    from mxnet_tpu.base import MXNetError

    npx.seed(7)
    b = npx.bernoulli(prob=mx.np.array([0.0, 1.0]))
    onp.testing.assert_array_equal(b.asnumpy(), [0.0, 1.0])
    lb = npx.bernoulli(logit=mx.np.array([-100.0, 100.0]))
    onp.testing.assert_array_equal(lb.asnumpy(), [0.0, 1.0])
    with pytest.raises(MXNetError):
        npx.bernoulli(prob=0.5, logit=0.0)
    with pytest.raises(MXNetError):
        npx.bernoulli()
    # statistics + sample_n shape conventions
    npx.seed(0)
    u = npx.uniform_n(2.0, 4.0, batch_shape=(5000,))
    assert u.shape == (5000,)
    assert 2.9 < float(u.asnumpy().mean()) < 3.1
    assert float(u.asnumpy().min()) >= 2.0
    n = npx.normal_n(mx.np.array([0.0, 10.0]), 0.1, batch_shape=(2000,))
    assert n.shape == (2000, 2)
    m = n.asnumpy().mean(0)
    assert abs(m[0]) < 0.02 and abs(m[1] - 10.0) < 0.02
    # seeding reproduces
    npx.seed(3)
    a1 = npx.normal_n(batch_shape=4).asnumpy()
    npx.seed(3)
    a2 = npx.normal_n(batch_shape=4).asnumpy()
    onp.testing.assert_array_equal(a1, a2)
    f = os.path.join(tempfile.mkdtemp(), "t.npz")
    npx.savez(f, mx.np.ones(3), named=mx.np.zeros(2))
    d = npx.load(f)
    assert sorted(d) == ["arr_0", "named"]
    onp.testing.assert_array_equal(d["named"].asnumpy(), [0.0, 0.0])


def test_npx_random_submodule_and_savez_clash():
    import pytest

    import mxnet_tpu as mx
    from mxnet_tpu import numpy_extension as npx
    from mxnet_tpu.base import MXNetError

    assert npx.random.bernoulli is npx.bernoulli
    assert npx.random.uniform_n is npx.uniform_n
    npx.random.seed(2)
    assert npx.random.uniform(0, 1, size=(3,)).shape == (3,)  # fallthrough
    with pytest.raises(MXNetError, match="arr_0"):
        npx.savez("/tmp/clash.npz", mx.np.ones(2), arr_0=mx.np.zeros(2))


def test_npx_tensor_tail_ops():
    """rsqrt/rcbrt/shape_array/size_array/split_v2/space_to_depth/
    depth_to_space (reference: elemwise_unary_op_pow.cc, matrix_op.cc)."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu import numpy_extension as npx

    a = mx.np.array([4.0, 0.125])
    onp.testing.assert_allclose(npx.rsqrt(a).asnumpy(),
                                [0.5, 1 / onp.sqrt(0.125)], rtol=1e-6)
    onp.testing.assert_allclose(npx.rcbrt(a).asnumpy(),
                                [1 / onp.cbrt(4.0), 2.0], rtol=1e-6)
    onp.testing.assert_array_equal(
        npx.shape_array(mx.np.ones((2, 3))).asnumpy(), [2, 3])
    onp.testing.assert_array_equal(
        npx.size_array(mx.np.ones((2, 3))).asnumpy(), [6])
    parts = npx.split_v2(mx.np.ones((4, 2)), 2, axis=0, squeeze_axis=False)
    assert len(parts) == 2 and parts[0].shape == (2, 2)
    sq = npx.split_v2(mx.np.ones((2, 3)), 2, axis=0, squeeze_axis=True)
    assert sq[0].shape == (3,)
    x = mx.np.array(onp.arange(32, dtype="float32").reshape(1, 2, 4, 4))
    s = npx.space_to_depth(x, 2)
    assert s.shape == (1, 8, 2, 2)
    onp.testing.assert_array_equal(npx.depth_to_space(s, 2).asnumpy(),
                                   x.asnumpy())
    # fluent + reference-signature kwargs resolve to npx, not jax.nn
    onp.testing.assert_allclose(
        mx.np.array([[1.0, 3.0]]).softmax(temperature=0.5).asnumpy(),
        onp.exp([[2.0, 6.0]]) / onp.exp([[2.0, 6.0]]).sum(), rtol=1e-5)
    oh = mx.np.array([1]).one_hot(3, on_value=2.0)
    onp.testing.assert_array_equal(oh.asnumpy(), [[0, 2, 0]])
    sym_out = mx.sym.var("x").softmax(temperature=0.5).eval(
        x=mx.np.array([[1.0, 3.0]]))[0]
    onp.testing.assert_allclose(
        sym_out.asnumpy(),
        onp.exp([[2.0, 6.0]]) / onp.exp([[2.0, 6.0]]).sum(), rtol=1e-5)

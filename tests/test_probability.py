"""gluon.probability tests.

Reference strategy: tests/python/unittest/test_gluon_probability_v2.py
(sampling shapes, log_prob vs scipy oracle, KL closed forms). scipy isn't
in this image, so oracles are torch.distributions (torch cpu is baked in).
"""
import numpy as onp
import pytest
import torch

import mxnet_tpu as mx
from mxnet_tpu import numpy as np
from mxnet_tpu.gluon import probability as mgp


def setup_module():
    mx.random.seed(0)
    onp.random.seed(0)


def _assert_logprob(dist, tdist, values, atol=1e-4):
    got = dist.log_prob(np.array(values.astype("float32"))).asnumpy()
    want = tdist.log_prob(torch.tensor(values)).numpy()
    onp.testing.assert_allclose(got, want, atol=atol, rtol=1e-4)


def test_normal_against_torch():
    loc, scale = onp.array([0.0, 1.5]), onp.array([1.0, 2.0])
    d = mgp.Normal(loc, scale)
    t = torch.distributions.Normal(torch.tensor(loc), torch.tensor(scale))
    x = onp.array([[0.3, -1.2], [2.0, 0.0]])
    _assert_logprob(d, t, x)
    onp.testing.assert_allclose(d.mean.asnumpy(), loc)
    onp.testing.assert_allclose(d.variance.asnumpy(), scale ** 2)
    onp.testing.assert_allclose(d.entropy().asnumpy(),
                                t.entropy().numpy(), atol=1e-5)
    onp.testing.assert_allclose(
        d.cdf(np.array(x.astype("float32"))).asnumpy(),
        t.cdf(torch.tensor(x)).numpy(), atol=1e-5)
    assert d.sample((7,)).shape == (7, 2)


@pytest.mark.parametrize("mk_ours,mk_torch,values", [
    (lambda: mgp.Laplace(0.5, 2.0),
     lambda: torch.distributions.Laplace(0.5, 2.0),
     onp.array([0.1, -3.0, 4.0])),
    (lambda: mgp.Cauchy(0.0, 1.5),
     lambda: torch.distributions.Cauchy(0.0, 1.5),
     onp.array([0.4, -2.0])),
    (lambda: mgp.Exponential(2.0),
     lambda: torch.distributions.Exponential(2.0),
     onp.array([0.5, 3.0])),
    (lambda: mgp.Gamma(3.0, 0.5),
     lambda: torch.distributions.Gamma(3.0, 2.0),  # torch uses rate
     onp.array([0.7, 2.2])),
    (lambda: mgp.Beta(2.0, 3.0),
     lambda: torch.distributions.Beta(2.0, 3.0),
     onp.array([0.2, 0.8])),
    (lambda: mgp.Gumbel(1.0, 2.0),
     lambda: torch.distributions.Gumbel(1.0, 2.0),
     onp.array([0.0, 4.0])),
    (lambda: mgp.Poisson(3.0),
     lambda: torch.distributions.Poisson(3.0),
     onp.array([0.0, 2.0, 7.0])),
    (lambda: mgp.StudentT(5.0, 0.0, 1.0),
     lambda: torch.distributions.StudentT(5.0),
     onp.array([0.3, -2.0])),
    (lambda: mgp.HalfNormal(2.0),
     lambda: torch.distributions.HalfNormal(2.0),
     onp.array([0.5, 3.0])),
    (lambda: mgp.Uniform(-1.0, 3.0),
     lambda: torch.distributions.Uniform(-1.0, 3.0),
     onp.array([0.0, 2.9])),
    (lambda: mgp.Chi2(4.0),
     lambda: torch.distributions.Chi2(torch.tensor(4.0)),
     onp.array([1.0, 5.5])),
    (lambda: mgp.Pareto(2.5, 1.0),
     lambda: torch.distributions.Pareto(torch.tensor(1.0),
                                        torch.tensor(2.5)),
     onp.array([1.5, 4.0])),
    (lambda: mgp.HalfCauchy(1.5),
     lambda: torch.distributions.HalfCauchy(torch.tensor(1.5)),
     onp.array([0.4, 2.5])),
    (lambda: mgp.FisherSnedecor(4.0, 6.0),
     lambda: torch.distributions.FisherSnedecor(torch.tensor(4.0),
                                                torch.tensor(6.0)),
     onp.array([0.5, 2.0])),
    (lambda: mgp.Geometric(0.3),
     lambda: torch.distributions.Geometric(torch.tensor(0.3)),
     onp.array([0.0, 2.0, 6.0])),
    (lambda: mgp.Binomial(10, 0.4),
     lambda: torch.distributions.Binomial(10, torch.tensor(0.4)),
     onp.array([0.0, 4.0, 9.0])),
    # our prob is the stop probability (n*log p + x*log1p(-p), matching
    # the reference's log_prob); torch's probs is its complement
    (lambda: mgp.NegativeBinomial(5, 0.35),
     lambda: torch.distributions.NegativeBinomial(torch.tensor(5.0),
                                                  torch.tensor(0.65)),
     onp.array([0.0, 3.0, 8.0])),
    (lambda: mgp.Dirichlet(onp.array([2.0, 3.0, 4.0], onp.float32)),
     lambda: torch.distributions.Dirichlet(
         torch.tensor([2.0, 3.0, 4.0])),
     onp.array([[0.2, 0.3, 0.5], [0.1, 0.6, 0.3]], onp.float32)),
    (lambda: mgp.Weibull(2.0, 1.5),
     lambda: torch.distributions.Weibull(torch.tensor(1.5),
                                         torch.tensor(2.0)),
     onp.array([0.5, 1.0, 3.0])),
])
def test_logprob_oracles(mk_ours, mk_torch, values):
    _assert_logprob(mk_ours(), mk_torch(), values)


def test_bernoulli_categorical():
    p = onp.array([0.2, 0.7])
    d = mgp.Bernoulli(prob=p)
    t = torch.distributions.Bernoulli(torch.tensor(p))
    x = onp.array([[0.0, 1.0], [1.0, 0.0]])
    _assert_logprob(d, t, x)
    onp.testing.assert_allclose(d.entropy().asnumpy(), t.entropy().numpy(),
                                atol=1e-5)

    logits = onp.random.randn(4, 5)
    d = mgp.Categorical(logit=logits)
    t = torch.distributions.Categorical(logits=torch.tensor(logits))
    x = onp.array([0.0, 3.0, 1.0, 4.0])
    _assert_logprob(d, t, x)
    s = d.sample()
    assert s.shape == (4,)
    # one-hot variant
    d = mgp.OneHotCategorical(logit=logits)
    s = d.sample()
    assert s.shape == (4, 5)
    assert onp.allclose(s.asnumpy().sum(-1), 1.0)


def test_mvn_against_torch():
    loc = onp.zeros(3)
    a = onp.random.randn(3, 3)
    cov = a @ a.T + 3 * onp.eye(3)
    d = mgp.MultivariateNormal(loc, cov=cov)
    t = torch.distributions.MultivariateNormal(
        torch.tensor(loc), covariance_matrix=torch.tensor(cov))
    x = onp.random.randn(6, 3)
    _assert_logprob(d, t, x)
    assert d.sample((5,)).shape == (5, 3)


def test_kl_closed_forms():
    p = mgp.Normal(0.0, 1.0)
    q = mgp.Normal(1.0, 2.0)
    tp = torch.distributions.Normal(0.0, 1.0)
    tq = torch.distributions.Normal(1.0, 2.0)
    onp.testing.assert_allclose(
        mgp.kl_divergence(p, q).asnumpy(),
        torch.distributions.kl_divergence(tp, tq).numpy(), atol=1e-5)

    logits = onp.random.randn(3, 4)
    logits2 = onp.random.randn(3, 4)
    kl = mgp.kl_divergence(mgp.Categorical(logit=logits),
                           mgp.Categorical(logit=logits2))
    tkl = torch.distributions.kl_divergence(
        torch.distributions.Categorical(logits=torch.tensor(logits)),
        torch.distributions.Categorical(logits=torch.tensor(logits2)))
    onp.testing.assert_allclose(kl.asnumpy(), tkl.numpy(), atol=1e-5)


def test_sampling_statistics():
    mx.random.seed(3)
    s = mgp.Normal(2.0, 0.5).sample((20000,)).asnumpy()
    assert abs(s.mean() - 2.0) < 0.02
    assert abs(s.std() - 0.5) < 0.02
    s = mgp.Bernoulli(prob=0.3).sample((20000,)).asnumpy()
    assert abs(s.mean() - 0.3) < 0.02
    s = mgp.Gamma(2.0, 1.5).sample((20000,)).asnumpy()
    assert abs(s.mean() - 3.0) < 0.1


def test_rsample_gradient_flows():
    """Pathwise gradient through a reparameterized sampler."""
    import jax
    import jax.numpy as jnp

    def f(mu):
        mx.random.seed(0)
        d = mgp.Normal(mu, 1.0)
        return d.rsample((100,))._data.mean()

    g = jax.grad(lambda mu: f(mu))(jnp.float32(0.5))
    assert abs(float(g) - 1.0) < 1e-4  # d/dmu E[mu + eps] = 1


def test_transformed_distribution():
    base = mgp.Normal(0.0, 1.0)
    logn = mgp.TransformedDistribution(base, mgp.ExpTransform())
    t = torch.distributions.LogNormal(0.0, 1.0)
    x = onp.array([0.5, 1.5, 3.0])
    got = logn.log_prob(np.array(x.astype("float32"))).asnumpy()
    onp.testing.assert_allclose(got, t.log_prob(torch.tensor(x)).numpy(),
                                atol=1e-5)
    s = logn.sample((10,))
    assert bool((s.asnumpy() > 0).all())
    # affine + sigmoid compose
    comp = mgp.TransformedDistribution(
        base, mgp.ComposeTransform([
            mgp.AffineTransform(1.0, 2.0), mgp.SigmoidTransform()]))
    assert comp.sample((4,)).shape == (4,)


def test_independent():
    d = mgp.Independent(mgp.Normal(onp.zeros((3, 4)), onp.ones((3, 4))), 1)
    x = onp.random.randn(3, 4)
    lp = d.log_prob(np.array(x.astype("float32")))
    t = torch.distributions.Independent(
        torch.distributions.Normal(torch.zeros(3, 4), torch.ones(3, 4)), 1)
    onp.testing.assert_allclose(lp.asnumpy(),
                                t.log_prob(torch.tensor(x)).numpy(),
                                atol=1e-4)


def test_stochastic_block_vae_style():
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.probability import StochasticBlock

    class TinyVAE(StochasticBlock):
        def __init__(self):
            super().__init__()
            self.enc = nn.Dense(4, flatten=False)
            self.dec = nn.Dense(8, flatten=False)

        @StochasticBlock.collectLoss
        def forward(self, x):
            h = self.enc(x)
            q = mgp.Normal(h, 1.0)
            z = q.rsample()
            self.add_loss(mgp.kl_divergence(q, mgp.Normal(0.0, 1.0)))
            return self.dec(z)

    net = TinyVAE()
    net.initialize()
    out = net(np.ones((2, 8)))
    assert out.shape == (2, 8)
    assert len(net.losses) == 1
    assert net.losses[0].shape == (2, 4)


def test_weibull_moments_and_sampling():
    """Weibull mean/var/entropy vs torch; inverse-CDF sampler moments."""
    d = mgp.Weibull(2.0, 1.5)
    t = torch.distributions.Weibull(torch.tensor(1.5), torch.tensor(2.0))
    onp.testing.assert_allclose(float(d.mean), float(t.mean), rtol=1e-5)
    onp.testing.assert_allclose(float(d.variance), float(t.variance),
                                rtol=1e-5)
    onp.testing.assert_allclose(float(d.entropy()), float(t.entropy()),
                                rtol=1e-5)
    mx.random.seed(3)
    s = d.sample((20000,)).asnumpy()
    assert abs(s.mean() - float(t.mean)) < 0.02
    # cdf/icdf round-trip
    u = onp.array([0.1, 0.5, 0.9], "float32")
    x = d.icdf(np.array(u)).asnumpy()
    onp.testing.assert_allclose(d.cdf(np.array(x)).asnumpy(), u, atol=1e-5)


def test_constraints():
    """Constraint namespace (reference distributions/constraint.py)."""
    import pytest

    from mxnet_tpu.gluon.probability import constraint as C

    ok = np.array([0.5, 0.2])
    assert C.Positive().check(ok) is ok
    with pytest.raises(ValueError):
        C.Positive().check(np.array([0.0, 1.0]))  # open bound
    assert C.NonNegative().check(np.array([0.0, 1.0])) is not None
    with pytest.raises(ValueError):
        C.Real().check(np.array([onp.nan]))
    with pytest.raises(ValueError):
        C.Boolean().check(np.array([0.0, 2.0]))
    C.Interval(0, 1).check(np.array([0.0, 1.0]))
    with pytest.raises(ValueError):
        C.OpenInterval(0, 1).check(np.array([0.0]))
    C.IntegerInterval(0, 5).check(np.array([0.0, 5.0]))
    with pytest.raises(ValueError):
        C.IntegerInterval(0, 5).check(np.array([1.5]))
    C.Simplex().check(np.array([[0.2, 0.8], [0.5, 0.5]]))
    with pytest.raises(ValueError):
        C.Simplex().check(np.array([0.2, 0.3]))
    L = onp.array([[1.0, 0.0], [0.5, 2.0]], "float32")
    C.LowerCholesky().check(np.array(L))
    with pytest.raises(ValueError):
        C.LowerCholesky().check(np.array(-L))
    C.PositiveDefinite().check(np.array(L @ L.T))
    with pytest.raises(ValueError):
        C.PositiveDefinite().check(np.array([[0.0, 1.0], [1.0, 0.0]]))
    # Cat / Stack segment application
    C.Cat([C.Positive(), C.Interval(0, 1)], dim=0, lengths=[1, 1]).check(
        np.array([2.0, 0.5]))
    with pytest.raises(ValueError):
        C.Stack([C.Positive(), C.Boolean()], dim=0).check(
            np.array([2.0, 0.5]))
    # dependent constraints cannot be validated standalone
    with pytest.raises(ValueError):
        C.dependent.check(ok)
    assert C.is_dependent(C.dependent)


def test_weibull_zero_boundary():
    """log_prob(0): finite log(1/scale) at k==1, -inf at k>1, never NaN."""
    got = mgp.Weibull(1.0, 2.0).log_prob(np.array([0.0])).asnumpy()
    onp.testing.assert_allclose(got, [onp.log(0.5)], atol=1e-6)
    assert mgp.Weibull(2.0, 1.0).log_prob(np.array([0.0])).asnumpy() == -onp.inf
    assert mgp.Weibull(2.0, 1.0).log_prob(np.array([-1.0])).asnumpy() == -onp.inf


def test_domain_map_biject_to():
    """biject_to/transform_to map support constraints to bijections that
    land inside the constraint (reference transformation/domain_map.py)."""
    from mxnet_tpu.gluon.probability import biject_to, transform_to
    from mxnet_tpu.gluon.probability import constraint as C

    x = np.array([-2.0, 0.0, 3.0])
    y = biject_to(C.Positive())(x)
    assert (y.asnumpy() > 0).all()
    y = biject_to(C.GreaterThan(5.0))(x)
    assert (y.asnumpy() > 5).all()
    y = biject_to(C.LessThan(-1.0))(x)
    assert (y.asnumpy() < -1).all()
    y = biject_to(C.UnitInterval())(x)
    assert ((y.asnumpy() > 0) & (y.asnumpy() < 1)).all()
    t = biject_to(C.Interval(2.0, 6.0))
    y = t(x)
    assert ((y.asnumpy() > 2) & (y.asnumpy() < 6)).all()
    # inverse round-trips
    onp.testing.assert_allclose(t.inv(y).asnumpy(), x.asnumpy(),
                                atol=1e-5)
    import pytest
    with pytest.raises(NotImplementedError):
        transform_to(C.Simplex())
    # SoftmaxTransform lands on the simplex
    s = mgp.SoftmaxTransform()(np.array([[1.0, 2.0, 3.0]]))
    onp.testing.assert_allclose(s.asnumpy().sum(-1), 1.0, atol=1e-6)


def test_stochastic_sequential():
    """Child losses bubble to the stack (reference block/stochastic_block
    StochasticSequential)."""
    from mxnet_tpu.gluon import nn

    class KLLayer(mgp.StochasticBlock):
        def __init__(self):
            super().__init__()
            self.dense = nn.Dense(4, flatten=False)

        @mgp.StochasticBlock.collectLoss
        def forward(self, x):
            h = self.dense(x)
            self.add_loss((h ** 2).mean())
            return h

    seq = mgp.StochasticSequential()
    seq.add(KLLayer(), KLLayer())
    seq.initialize()
    out = seq(np.ones((2, 4)))
    assert out.shape == (2, 4)
    assert len(seq.losses) == 2 and len(seq[0].losses) == 1
    assert len(seq) == 2
    assert len(seq.collect_params()) == 4  # 2 layers x (weight, bias)


def test_stochastic_sequential_weight_sharing():
    """Adding the SAME block twice must keep both calls' losses."""
    from mxnet_tpu.gluon import nn

    class Marker(mgp.StochasticBlock):
        def __init__(self):
            super().__init__()
            self.dense = nn.Dense(3, flatten=False)

        @mgp.StochasticBlock.collectLoss
        def forward(self, x):
            h = self.dense(x)
            self.add_loss(h.sum())
            return h

    blk = Marker()
    seq = mgp.StochasticSequential()
    seq.add(blk, blk)  # weight-shared
    seq.initialize()
    seq(np.ones((1, 3)))
    assert len(seq.losses) == 2
    # the two entries are from DIFFERENT calls (different values)
    v0, v1 = float(seq.losses[0][0]), float(seq.losses[1][0])
    assert v0 != v1
    # shared block: both prefixes resolve to the same Parameter objects
    assert len({id(p) for p in seq.collect_params().values()}) == 2

"""gluon.contrib.estimator (reference: tests/python/unittest/
test_gluon_estimator.py + test_gluon_event_handler.py taxonomy)."""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn
from mxnet_tpu.gluon.contrib import estimator as est


def _toy_data(n=64, d=8, classes=3, bs=16, seed=0):
    rng = onp.random.RandomState(seed)
    x = rng.randn(n, d).astype("float32")
    w = rng.randn(d, classes).astype("float32")
    y = (x @ w).argmax(-1).astype("float32")
    return [(mx.np.array(x[i:i + bs]), mx.np.array(y[i:i + bs]))
            for i in range(0, n, bs)]


def _make_estimator(lr=0.1, **kwargs):
    mx.random.seed(0)
    net = nn.Sequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": lr})
    return est.Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         trainer=trainer, **kwargs)


def test_fit_learns_and_updates_metrics():
    e = _make_estimator()
    data = _toy_data()
    e.fit(data, epochs=20)
    names = dict(nv for m in e.train_metrics for nv in m.get_name_value())
    assert names["accuracy"] > 0.9, names
    assert 0 < names["train_loss"] < 1.0


def test_gradient_update_handler_is_the_stepper():
    """Removing GradientUpdateHandler must freeze the weights."""
    e = _make_estimator()
    data = _toy_data()
    e.net(data[0][0])  # materialize deferred shapes
    w0 = e.net.collect_params()["0.weight"].data().asnumpy().copy()

    class NoStep(est.GradientUpdateHandler):
        def batch_end(self, estimator, *args, **kwargs):
            pass  # swallow the step

    e.fit(data, epochs=2, event_handlers=[NoStep()])
    w1 = e.net.collect_params()["0.weight"].data().asnumpy()
    assert onp.allclose(w0, w1), "weights moved without an update handler"
    # while the default handler does move them
    e2 = _make_estimator()
    e2.net(data[0][0])  # materialize deferred shapes
    v0 = e2.net.collect_params()["0.weight"].data().asnumpy().copy()
    e2.fit(data, epochs=1)
    assert not onp.allclose(
        v0, e2.net.collect_params()["0.weight"].data().asnumpy())


def test_custom_batch_processor():
    calls = []

    class Recorder(est.BatchProcessor):
        def fit_batch(self, estimator, batch, batch_axis=0):
            calls.append("fit")
            return super().fit_batch(estimator, batch, batch_axis)

    e = _make_estimator(batch_processor=Recorder())
    data = _toy_data(n=32)
    e.fit(data, epochs=1)
    assert len(calls) == len(data)


def test_checkpoint_handler(tmp_path):
    e = _make_estimator()
    data = _toy_data(n=32)
    ckpt = est.CheckpointHandler(str(tmp_path), model_prefix="toy",
                                 epoch_period=1, max_checkpoints=2)
    e.fit(data, epochs=3, event_handlers=[ckpt])
    files = sorted(os.listdir(tmp_path))
    assert any("epoch3" in f for f in files)
    # max_checkpoints evicts the oldest
    assert not any("epoch1" in f for f in files)
    # reload round-trip
    net2 = nn.Sequential()
    net2.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    saved = [f for f in files if f.endswith((".params", ".params.npz"))][-1]
    net2.load_parameters(str(tmp_path / saved))
    x = data[0][0]
    onp.testing.assert_allclose(net2(x).asnumpy(), e.net(x).asnumpy(),
                                atol=1e-6)


def test_early_stopping_handler():
    loss_metric = mx.gluon.metric.Loss("train_loss")

    class Plateau(est.EpochEnd):
        """Force the monitored metric flat so patience triggers."""

        def epoch_end(self, estimator, *args, **kwargs):
            loss_metric.reset()
            loss_metric.update(None, [mx.np.array([1.0])])

    e = _make_estimator(lr=0.0)
    stopper = est.EarlyStoppingHandler(monitor=loss_metric, patience=2,
                                       mode="min")
    e.fit(_toy_data(n=32), epochs=50,
          event_handlers=[Plateau(), stopper])
    assert stopper.stop_training
    assert stopper.wait >= 2


def test_validation_handler_runs_eval():
    seen = []
    e = _make_estimator()
    val = _toy_data(n=16, seed=1)
    vh = est.ValidationHandler(val, eval_fn=lambda d: seen.append(len(d)),
                               epoch_period=1)
    e.fit(_toy_data(n=32), epochs=2, event_handlers=[vh])
    assert seen == [1, 1]


def test_evaluate_reports_accuracy():
    e = _make_estimator()
    data = _toy_data()
    e.fit(data, epochs=20)
    metrics = e.evaluate(data)
    acc = dict(nv for m in metrics for nv in m.get_name_value())["accuracy"]
    assert acc > 0.9


def test_priority_ordering():
    order = []

    class A(est.BatchEnd):
        priority = 10

        def batch_end(self, estimator, *args, **kwargs):
            order.append("late")

    class B(est.BatchEnd):
        priority = -5000

        def batch_end(self, estimator, *args, **kwargs):
            order.append("early")

    e = _make_estimator()
    e.fit(_toy_data(n=16), epochs=1, event_handlers=[A(), B()])
    assert order[0] == "early" and order[1] == "late"


def test_val_metrics_and_loss_reported():
    """val_metrics is honored and evaluate() feeds LossMetric; the
    training metrics are left untouched."""
    vm = [mx.gluon.metric.Accuracy(), mx.gluon.metric.Loss("val_loss")]
    e = _make_estimator(val_metrics=vm)
    data = _toy_data()
    e.fit(data, epochs=15)
    train_vals = dict(nv for m in e.train_metrics
                      for nv in m.get_name_value())
    out = e.evaluate(data)
    got = dict(nv for m in out for nv in m.get_name_value())
    assert got["accuracy"] > 0.8 and got["val_loss"] > 0
    # train metrics unchanged by evaluate
    after = dict(nv for m in e.train_metrics for nv in m.get_name_value())
    assert after == train_vals


def test_scalar_loss_step_normalization():
    """A mean-reduced (scalar) loss must still normalize by the DATA
    batch size, not by loss.shape."""
    class ScalarLossProcessor(est.BatchProcessor):
        def fit_batch(self, estimator, batch, batch_axis=0):
            from mxnet_tpu import autograd
            data, label = batch[0], batch[1]
            with autograd.record():
                pred = estimator.net(data)
                loss = estimator.loss(pred, label).mean()  # scalar
            loss.backward()
            return [data], [label], [pred], [loss]

    seen = []

    class SpyStep(est.GradientUpdateHandler):
        def batch_end(self, estimator, *args, **kwargs):
            super().batch_end(estimator, *args, **kwargs)
            seen.append(kwargs.get("num_samples"))

    e = _make_estimator(batch_processor=ScalarLossProcessor())
    e.fit(_toy_data(n=32, bs=16), epochs=1, event_handlers=[SpyStep()])
    assert seen == [16, 16]

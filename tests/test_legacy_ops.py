"""Legacy CamelCase op surface (mx.nd.* / mx.sym.*).

Reference parity: python/mxnet/ndarray/register.py:115-277 and
symbol/register.py generate one python function per registered op at
import; 1.x scripts use CamelCase layer names (FullyConnected,
Convolution, BatchNorm, SliceChannel, ...).  These tests parity-lock the
surface and check numerics against the np/npx implementations.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import nd, sym


LEGACY_NAMES = [
    # the CamelCase ops registered in the reference's src/operator/**.cc
    "Activation", "BatchNorm", "BlockGrad", "CTCLoss", "Cast", "Concat",
    "Convolution", "Crop", "Custom", "Deconvolution", "Dropout",
    "ElementWiseSum", "Embedding", "ExpandDims", "Flatten",
    "FullyConnected", "GroupNorm", "IdentityAttachKLSparseReg",
    "InstanceNorm", "L2Normalization", "LRN", "LayerNorm", "LeakyReLU",
    "LinearRegressionOutput", "LogisticRegressionOutput",
    "MAERegressionOutput", "MakeLoss", "Pad", "Pooling", "RNN",
    "ROIPooling", "Reshape", "SequenceLast", "SequenceMask",
    "SequenceReverse", "SliceChannel", "Softmax", "SoftmaxOutput",
    "SwapAxis", "UpSampling",
    # legacy snake_case names with no np analog
    "broadcast_add", "broadcast_sub", "broadcast_mul", "broadcast_div",
    "broadcast_maximum", "broadcast_minimum", "broadcast_power",
    "broadcast_greater", "broadcast_to", "broadcast_axis",
    "elemwise_add", "elemwise_sub", "elemwise_mul", "elemwise_div",
    "stop_gradient", "argmax_channel", "ones_like", "zeros_like",
    # tensor ops nd must expose (np or npx backed)
    "dot", "batch_dot", "one_hot", "pick", "topk", "gather_nd",
    "slice_axis", "slice_like", "sequence_mask", "clip", "take", "tile",
    "repeat", "where", "abs", "exp", "log", "sqrt", "square", "maximum",
    "minimum", "argmax", "argmin", "sum", "mean", "max", "min", "norm",
]


def test_legacy_surface_parity_lock():
    missing = []
    for name in LEGACY_NAMES:
        if not callable(getattr(nd, name, None)):
            missing.append(f"nd.{name}")
        if not callable(getattr(sym, name, None)):
            missing.append(f"sym.{name}")
    assert not missing, f"legacy names absent: {missing}"


def test_fully_connected_legacy_kwargs():
    x = nd.array(onp.random.randn(4, 10).astype("float32"))
    w = nd.array(onp.random.randn(3, 10).astype("float32"))
    b = nd.array(onp.random.randn(3).astype("float32"))
    out = nd.FullyConnected(x, w, b, num_hidden=3)
    ref = x.asnumpy() @ w.asnumpy().T + b.asnumpy()
    onp.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-5)
    out2 = nd.FullyConnected(x, w, num_hidden=3, no_bias=True)
    onp.testing.assert_allclose(out2.asnumpy(), ref - b.asnumpy(), rtol=1e-5)


def test_convolution_legacy_kwargs():
    x = nd.array(onp.random.randn(2, 3, 8, 8).astype("float32"))
    w = nd.array(onp.random.randn(4, 3, 3, 3).astype("float32") * 0.1)
    b = nd.array(onp.zeros(4, "float32"))
    out = nd.Convolution(x, w, b, kernel=(3, 3), num_filter=4,
                         stride=(1, 1), pad=(1, 1))
    assert out.shape == (2, 4, 8, 8)
    # string attrs (as found in serialized symbol json)
    out2 = nd.Convolution(x, w, b, kernel="(3, 3)", num_filter="4",
                          stride="(1, 1)", pad="(1, 1)")
    onp.testing.assert_allclose(out.asnumpy(), out2.asnumpy(), rtol=1e-6)


def test_batchnorm_pooling_activation_chain():
    x = nd.array(onp.random.randn(2, 4, 8, 8).astype("float32"))
    gamma = nd.ones(4)
    beta = nd.zeros(4)
    rmean = nd.zeros(4)
    rvar = nd.ones(4)
    y = nd.BatchNorm(x, gamma, beta, rmean, rvar, fix_gamma=True)
    y = nd.Activation(y, act_type="relu")
    y = nd.Pooling(y, kernel=(2, 2), stride=(2, 2), pool_type="max")
    assert y.shape == (2, 4, 4, 4)
    assert float(y.asnumpy().min()) >= 0.0


def test_slice_channel_and_concat_roundtrip():
    x = nd.array(onp.random.randn(2, 6, 4).astype("float32"))
    parts = nd.SliceChannel(x, num_outputs=3, axis=1)
    assert len(parts) == 3 and parts[0].shape == (2, 2, 4)
    back = nd.Concat(*parts, dim=1)
    onp.testing.assert_allclose(back.asnumpy(), x.asnumpy())
    sq = nd.SliceChannel(x, num_outputs=6, axis=1, squeeze_axis=True)
    assert sq[0].shape == (2, 4)


def test_reshape_legacy_codes():
    x = nd.array(onp.arange(24, dtype="float32").reshape(2, 3, 4))
    assert nd.Reshape(x, shape=(-1,)).shape == (24,)
    assert nd.Reshape(x, shape=(0, -1)).shape == (2, 12)
    assert nd.Reshape(x, shape=(-3, 0)).shape == (6, 4)


def test_softmax_output_loss_gradient():
    from mxnet_tpu import autograd
    x = nd.array(onp.random.randn(4, 3).astype("float32"))
    lab = nd.array(onp.array([0, 1, 2, 1], "float32"))
    x.attach_grad()
    with autograd.record():
        out = nd.SoftmaxOutput(x, lab)
    out.backward()
    p = out.asnumpy()
    onehot = onp.eye(3, dtype="float32")[lab.asnumpy().astype(int)]
    onp.testing.assert_allclose(x.grad.asnumpy(), p - onehot, rtol=1e-5,
                                atol=1e-6)


def test_upsampling_and_pad():
    x = nd.array(onp.random.randn(1, 2, 3, 3).astype("float32"))
    up = nd.UpSampling(x, scale=2, sample_type="nearest")
    assert up.shape == (1, 2, 6, 6)
    onp.testing.assert_allclose(up.asnumpy()[0, 0, :2, :2],
                                onp.full((2, 2), x.asnumpy()[0, 0, 0, 0]))
    padded = nd.Pad(x, mode="constant",
                    pad_width=(0, 0, 0, 0, 1, 1, 2, 2), constant_value=5)
    assert padded.shape == (1, 2, 5, 7)
    assert padded.asnumpy()[0, 0, 0, 0] == 5


def test_lrn_matches_formula():
    x = onp.random.randn(2, 8, 4, 4).astype("float32")
    out = nd.LRN(nd.array(x), alpha=1e-3, beta=0.75, knorm=2, nsize=3)
    sq = x ** 2
    acc = onp.zeros_like(x)
    for c in range(8):
        lo, hi = max(0, c - 1), min(8, c + 2)
        acc[:, c] = sq[:, lo:hi].sum(axis=1)
    ref = x * (2 + 1e-3 / 3 * acc) ** -0.75
    onp.testing.assert_allclose(out.asnumpy(), ref, rtol=1e-4, atol=1e-5)


def test_broadcast_and_elemwise_aliases():
    a = nd.array(onp.random.randn(2, 1, 4).astype("float32"))
    b = nd.array(onp.random.randn(1, 3, 4).astype("float32"))
    onp.testing.assert_allclose(nd.broadcast_add(a, b).asnumpy(),
                                a.asnumpy() + b.asnumpy(), rtol=1e-6)
    onp.testing.assert_allclose(
        nd.broadcast_to(a, shape=(2, 3, 0)).asnumpy(),
        onp.broadcast_to(a.asnumpy(), (2, 3, 4)), rtol=1e-6)
    onp.testing.assert_allclose(
        nd.broadcast_axis(a, axis=1, size=3).asnumpy(),
        onp.broadcast_to(a.asnumpy(), (2, 3, 4)), rtol=1e-6)


def test_sym_legacy_mlp_1x_style():
    """A 1.x-style symbol script: build MLP with CamelCase ops, bind,
    forward, backward, SGD step — the reference's classic mnist_mlp."""
    data = sym.Variable("data")
    label = sym.Variable("softmax_label")
    fc1 = sym.FullyConnected(data, sym.Variable("fc1_weight"),
                             sym.Variable("fc1_bias"), num_hidden=16,
                             name="fc1")
    act1 = sym.Activation(fc1, act_type="relu", name="relu1")
    fc2 = sym.FullyConnected(act1, sym.Variable("fc2_weight"),
                             sym.Variable("fc2_bias"), num_hidden=4,
                             name="fc2")
    out = sym.SoftmaxOutput(fc2, label, name="softmax")

    assert set(out.list_arguments()) == {
        "data", "softmax_label", "fc1_weight", "fc1_bias", "fc2_weight",
        "fc2_bias"}

    rng = onp.random.RandomState(0)
    args = {
        "data": nd.array(rng.randn(8, 10).astype("float32")),
        "softmax_label": nd.array(rng.randint(0, 4, 8).astype("float32")),
        "fc1_weight": nd.array(rng.randn(16, 10).astype("float32") * 0.1),
        "fc1_bias": nd.zeros(16),
        "fc2_weight": nd.array(rng.randn(4, 16).astype("float32") * 0.1),
        "fc2_bias": nd.zeros(4),
    }
    exe = out.bind(args=args)
    probs = exe.forward(is_train=True)[0]
    assert probs.shape == (8, 4)
    onp.testing.assert_allclose(probs.asnumpy().sum(-1),
                                onp.ones(8), rtol=1e-5)
    exe.backward()
    g = exe.grad_dict
    assert "fc1_weight" in g and g["fc1_weight"].shape == (16, 10)
    assert float(onp.abs(g["fc2_weight"].asnumpy()).sum()) > 0

    # json round-trip preserves legacy attrs
    js = out.tojson()
    out2 = sym.load_json(js)
    probs2 = out2.bind(args=args).forward()[0]
    onp.testing.assert_allclose(probs2.asnumpy(), probs.asnumpy(),
                                rtol=1e-5)


def test_sym_legacy_convnet_eval():
    data = sym.Variable("data")
    conv = sym.Convolution(data, sym.Variable("w"), sym.Variable("b"),
                           kernel=(3, 3), num_filter=2, pad=(1, 1))
    act = sym.Activation(conv, act_type="tanh")
    pool = sym.Pooling(act, kernel=(2, 2), stride=(2, 2), pool_type="avg")
    flat = sym.Flatten(pool)
    rng = onp.random.RandomState(1)
    out = flat.eval(
        data=nd.array(rng.randn(1, 1, 4, 4).astype("float32")),
        w=nd.array(rng.randn(2, 1, 3, 3).astype("float32")),
        b=nd.zeros(2))[0]
    assert out.shape == (1, 8)


def test_nd_npx_fallback():
    # tensor npx ops reachable through nd (legacy exposed them flat)
    x = nd.array(onp.random.randn(3, 4).astype("float32"))
    out = nd.slice_axis(x, axis=1, begin=1, end=3)
    assert out.shape == (3, 2)
    out = nd.topk(x, k=2)
    assert out.shape == (3, 2)


def test_legacy_linalg_family():
    """nd.linalg_* (reference: src/operator/tensor/la_op.cc) value locks."""
    rng = onp.random.RandomState(0)
    A = rng.randn(3, 3).astype(onp.float32)
    SPD = (A @ A.T + 3 * onp.eye(3)).astype(onp.float32)
    B = rng.randn(3, 2).astype(onp.float32)
    Br = rng.randn(2, 3).astype(onp.float32)
    L = nd.linalg_potrf(nd.array(SPD)).asnumpy()
    onp.testing.assert_allclose(L @ L.T, SPD, rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(
        nd.linalg_gemm2(nd.array(A), nd.array(B), alpha=2.0).asnumpy(),
        2 * A @ B, rtol=1e-5)
    C0 = rng.randn(3, 2).astype(onp.float32)
    onp.testing.assert_allclose(
        nd.linalg_gemm(nd.array(A), nd.array(B), nd.array(C0),
                       beta=0.5).asnumpy(), A @ B + 0.5 * C0, rtol=1e-5)
    onp.testing.assert_allclose(
        nd.linalg_potri(nd.array(L)).asnumpy() @ SPD, onp.eye(3), atol=1e-3)
    X = nd.linalg_trsm(nd.array(L), nd.array(B)).asnumpy()
    onp.testing.assert_allclose(L @ X, B, atol=1e-4)
    X = nd.linalg_trsm(nd.array(L), nd.array(Br), rightside=True,
                       transpose=True).asnumpy()
    onp.testing.assert_allclose(X @ L.T, Br, atol=1e-4)
    onp.testing.assert_allclose(
        nd.linalg_trmm(nd.array(L), nd.array(B)).asnumpy(), L @ B,
        rtol=1e-5)
    onp.testing.assert_allclose(
        float(nd.linalg_sumlogdiag(nd.array(SPD)).asnumpy()),
        onp.log(onp.diag(SPD)).sum(), rtol=1e-5)
    d = nd.linalg_extractdiag(nd.array(SPD)).asnumpy()
    onp.testing.assert_allclose(
        nd.linalg_makediag(nd.array(d)).asnumpy(),
        onp.diag(onp.diag(SPD)))
    tr = nd.linalg_extracttrian(nd.array(SPD)).asnumpy()
    onp.testing.assert_allclose(
        nd.linalg_maketrian(nd.array(tr)).asnumpy(), onp.tril(SPD),
        atol=1e-6)
    onp.testing.assert_allclose(
        nd.linalg_syrk(nd.array(B)).asnumpy(), B @ B.T, rtol=1e-5)
    Ut, w = nd.linalg_syevd(nd.array(SPD))
    onp.testing.assert_allclose(
        (Ut.asnumpy().T * w.asnumpy()) @ Ut.asnumpy(), SPD, atol=1e-3)
    Lq, Q = nd.linalg_gelqf(nd.array(Br))
    onp.testing.assert_allclose(Lq.asnumpy() @ Q.asnumpy(), Br, atol=1e-4)
    onp.testing.assert_allclose(Q.asnumpy() @ Q.asnumpy().T, onp.eye(2),
                                atol=1e-5)
    onp.testing.assert_allclose(
        nd.linalg_inverse(nd.array(SPD)).asnumpy() @ SPD, onp.eye(3),
        atol=1e-3)
    s, ld = nd.linalg_slogdet(nd.array(SPD))
    onp.testing.assert_allclose(float(ld.asnumpy()),
                                onp.linalg.slogdet(SPD)[1], rtol=1e-4)


def test_legacy_spatial_samplers():
    """BilinearSampler / GridGenerator / SpatialTransformer (reference:
    src/operator/bilinear_sampler.cc, grid_generator.cc,
    spatial_transformer.cc): identity-grid and shift oracles."""
    rng = onp.random.RandomState(0)
    x = rng.randn(1, 2, 5, 5).astype(onp.float32)
    ys, xs = onp.meshgrid(onp.linspace(-1, 1, 5), onp.linspace(-1, 1, 5),
                          indexing="ij")
    grid = onp.stack([xs, ys])[None].astype(onp.float32)
    out = nd.BilinearSampler(nd.array(x), nd.array(grid)).asnumpy()
    onp.testing.assert_allclose(out, x, atol=1e-5)
    theta = onp.array([[1, 0, 0, 0, 1, 0]], onp.float32)
    g = nd.GridGenerator(nd.array(theta), transform_type="affine",
                         target_shape=(5, 5)).asnumpy()
    onp.testing.assert_allclose(g[0, 0], xs, atol=1e-6)
    st = nd.SpatialTransformer(nd.array(x), nd.array(theta),
                               target_shape=(5, 5)).asnumpy()
    onp.testing.assert_allclose(st, x, atol=1e-5)
    # x-translation by one pixel (affine tx = 2/(W-1))
    theta_t = onp.array([[1, 0, 2.0 / 4, 0, 1, 0]], onp.float32)
    st = nd.SpatialTransformer(nd.array(x), nd.array(theta_t),
                               target_shape=(5, 5)).asnumpy()
    onp.testing.assert_allclose(st[..., :4], x[..., 1:], atol=1e-5)


def test_legacy_linalg_triangle_offsets():
    """maketrian/extracttrian roundtrip at nonzero offsets; trmm reads only
    the named triangle (BLAS contract) — round-4 review regressions."""
    rng = onp.random.RandomState(1)
    A = rng.randn(4, 4).astype(onp.float32)
    for o, lo in [(1, True), (-1, True), (1, False), (-2, False)]:
        tr = nd.linalg_extracttrian(nd.array(A), offset=o, lower=lo).asnumpy()
        mt = nd.linalg_maketrian(nd.array(tr), offset=o, lower=lo).asnumpy()
        want = onp.tril(A, o) if lo else onp.triu(A, o)
        onp.testing.assert_allclose(mt, want, atol=1e-6)
    B = rng.randn(4, 3).astype(onp.float32)
    onp.testing.assert_allclose(
        nd.linalg_trmm(nd.array(A), nd.array(B)).asnumpy(),
        onp.tril(A) @ B, rtol=1e-5)
    onp.testing.assert_allclose(
        nd.linalg_trmm(nd.array(A), nd.array(B), lower=False).asnumpy(),
        onp.triu(A) @ B, rtol=1e-5)

"""mx.np.linalg value + gradient locks.

Round-3 verdict Weak #3: linalg was a blind jnp passthrough with zero
linalg-specific tests. This file locks values against real numpy.linalg
(decomposition invariants where sign/phase conventions differ) and
gradients via finite differences for the differentiable entry points.
Reference analog: tests/python/unittest/test_numpy_op.py linalg sections
over the _npi linalg ops (src/operator/numpy/linalg/).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np
from mxnet_tpu.test_utils import check_numeric_gradient

RNG = onp.random.RandomState(7)


def _spd(n):
    a = RNG.randn(n, n).astype(onp.float32)
    return (a @ a.T + n * onp.eye(n, dtype=onp.float32))


def _sq(n):
    return (RNG.randn(n, n).astype(onp.float32)
            + 2 * onp.eye(n, dtype=onp.float32))


A = _sq(4)
SPD = _spd(4)
RECT = RNG.randn(5, 3).astype(onp.float32)


def test_det_slogdet():
    got = float(np.linalg.det(np.array(A)).asnumpy())
    onp.testing.assert_allclose(got, onp.linalg.det(A), rtol=1e-4)
    sign, logdet = np.linalg.slogdet(np.array(A))
    s_ref, l_ref = onp.linalg.slogdet(A)
    onp.testing.assert_allclose(float(sign.asnumpy()), s_ref, rtol=1e-5)
    onp.testing.assert_allclose(float(logdet.asnumpy()), l_ref, rtol=1e-4)


def test_inv_solve():
    inv = np.linalg.inv(np.array(A)).asnumpy()
    onp.testing.assert_allclose(inv @ A, onp.eye(4), atol=1e-4)
    b = RNG.randn(4, 2).astype(onp.float32)
    x = np.linalg.solve(np.array(A), np.array(b)).asnumpy()
    onp.testing.assert_allclose(A @ x, b, atol=1e-4)


def test_cholesky():
    L = np.linalg.cholesky(np.array(SPD)).asnumpy()
    onp.testing.assert_allclose(L @ L.T, SPD, rtol=1e-4, atol=1e-3)
    assert onp.allclose(L, onp.tril(L))  # lower triangular convention


def test_qr():
    q, r = np.linalg.qr(np.array(RECT))
    q, r = q.asnumpy(), r.asnumpy()
    onp.testing.assert_allclose(q @ r, RECT, atol=1e-4)
    onp.testing.assert_allclose(q.T @ q, onp.eye(3), atol=1e-4)
    assert onp.allclose(r, onp.triu(r), atol=1e-5)


def test_svd():
    u, s, vt = np.linalg.svd(np.array(RECT), full_matrices=False)
    u, s, vt = u.asnumpy(), s.asnumpy(), vt.asnumpy()
    onp.testing.assert_allclose(u @ onp.diag(s) @ vt, RECT, atol=1e-4)
    s_ref = onp.linalg.svd(RECT, compute_uv=False)
    onp.testing.assert_allclose(s, s_ref, rtol=1e-4)


def test_eigh_eigvalsh():
    w, v = np.linalg.eigh(np.array(SPD))
    w, v = w.asnumpy(), v.asnumpy()
    w_ref = onp.linalg.eigvalsh(SPD)
    onp.testing.assert_allclose(onp.sort(w), onp.sort(w_ref), rtol=1e-4)
    onp.testing.assert_allclose(SPD @ v, v @ onp.diag(w), atol=1e-2)
    w2 = np.linalg.eigvalsh(np.array(SPD)).asnumpy()
    onp.testing.assert_allclose(onp.sort(w2), onp.sort(w_ref), rtol=1e-4)


def test_eig_eigvals():
    w = np.linalg.eigvals(np.array(SPD)).asnumpy()
    w_ref = onp.linalg.eigvals(SPD)
    onp.testing.assert_allclose(onp.sort(w.real), onp.sort(w_ref.real),
                                rtol=1e-3)
    w2, v2 = np.linalg.eig(np.array(SPD))
    onp.testing.assert_allclose(onp.sort(w2.asnumpy().real),
                                onp.sort(w_ref.real), rtol=1e-3)


@pytest.mark.parametrize("ord_", [None, 1, 2, onp.inf, "fro"])
def test_norm_orders(ord_):
    got = float(np.linalg.norm(np.array(A), ord=ord_).asnumpy())
    onp.testing.assert_allclose(got, onp.linalg.norm(A, ord=ord_), rtol=1e-4)


def test_vector_norm_axis():
    v = RNG.randn(3, 4).astype(onp.float32)
    got = np.linalg.norm(np.array(v), axis=1).asnumpy()
    onp.testing.assert_allclose(got, onp.linalg.norm(v, axis=1), rtol=1e-5)


def test_pinv_lstsq():
    p = np.linalg.pinv(np.array(RECT)).asnumpy()
    onp.testing.assert_allclose(RECT @ p @ RECT, RECT, atol=1e-3)
    b = RNG.randn(5).astype(onp.float32)
    x, *_ = np.linalg.lstsq(np.array(RECT), np.array(b), rcond=None)
    x_ref = onp.linalg.lstsq(RECT, b, rcond=None)[0]
    onp.testing.assert_allclose(x.asnumpy(), x_ref, atol=1e-3)


def test_matrix_power_rank_multidot():
    onp.testing.assert_allclose(
        np.linalg.matrix_power(np.array(A), 3).asnumpy(),
        onp.linalg.matrix_power(A, 3), rtol=1e-3)
    low = onp.outer(onp.arange(4.0), onp.arange(4.0)).astype(onp.float32)
    assert int(np.linalg.matrix_rank(np.array(low)).asnumpy()) == \
        onp.linalg.matrix_rank(low)
    m1, m2, m3 = (RNG.randn(3, 4).astype(onp.float32),
                  RNG.randn(4, 2).astype(onp.float32),
                  RNG.randn(2, 5).astype(onp.float32))
    onp.testing.assert_allclose(
        np.linalg.multi_dot([np.array(m1), np.array(m2),
                             np.array(m3)]).asnumpy(),
        onp.linalg.multi_dot([m1, m2, m3]), rtol=1e-4, atol=1e-4)


def test_tensorinv_tensorsolve():
    t = RNG.randn(2, 3, 6).astype(onp.float32) + 1.0
    ti = np.linalg.tensorinv(np.array(t), ind=2).asnumpy()
    onp.testing.assert_allclose(ti, onp.linalg.tensorinv(t, ind=2),
                                rtol=1e-2, atol=1e-2)
    a = RNG.randn(6, 2, 3).astype(onp.float32) + onp.eye(6).reshape(6, 2, 3) \
        .astype(onp.float32)
    b = RNG.randn(6).astype(onp.float32)
    x = np.linalg.tensorsolve(np.array(a), np.array(b)).asnumpy()
    onp.testing.assert_allclose(x, onp.linalg.tensorsolve(a, b), rtol=1e-2,
                                atol=1e-2)


# -- gradients --------------------------------------------------------------

def test_det_gradient():
    check_numeric_gradient(
        lambda xs: np.linalg.det(xs[0]), [np.array(_sq(3))],
        eps=1e-2, rtol=3e-2, atol=1e-2)


def test_slogdet_gradient():
    check_numeric_gradient(
        lambda xs: np.linalg.slogdet(xs[0])[1], [np.array(_spd(3))],
        eps=1e-2, rtol=3e-2, atol=1e-2)


def test_inv_gradient():
    check_numeric_gradient(
        lambda xs: np.linalg.inv(xs[0]).sum(), [np.array(_sq(3))],
        eps=1e-2, rtol=3e-2, atol=2e-2)


def test_solve_gradient():
    b = np.array(RNG.randn(3).astype(onp.float32))
    check_numeric_gradient(
        lambda xs: np.linalg.solve(xs[0], b).sum(), [np.array(_sq(3))],
        eps=1e-2, rtol=3e-2, atol=2e-2)


def test_norm_gradient():
    check_numeric_gradient(
        lambda xs: np.linalg.norm(xs[0]), [np.array(_sq(3))],
        eps=1e-2, rtol=3e-2, atol=1e-2)


def test_cholesky_gradient():
    check_numeric_gradient(
        lambda xs: np.linalg.cholesky(xs[0] @ xs[0].T
                                      + 3 * np.eye(3)).sum(),
        [np.array(RNG.randn(3, 3).astype(onp.float32))],
        eps=1e-2, rtol=5e-2, atol=2e-2)

"""Fused dropout+residual+LayerNorm kernel (reference: the CUDA fused
transformer epilogues, src/operator/contrib/transformer.cc:675-828;
src/operator/nn/layer_norm.cu)."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, np
from mxnet_tpu.ops.pallas.ln_residual import ln_residual_dropout


def _ref(x, h, g, b, mask, p, eps=1e-5):
    s = x + h * mask / (1 - p) if p > 0 else x + h * mask
    mu = s.mean(-1, keepdims=True)
    var = ((s - mu) ** 2).mean(-1, keepdims=True)
    return (s - mu) * jax.lax.rsqrt(var + eps) * g + b


@pytest.mark.parametrize("p,rows,block_rows", [
    (0.0, 10, 256), (0.3, 7, 256), (0.0, 256, 256),
    # grid > 1: exercises the revisited (8, dim) dgamma/dbeta accumulator
    # (pl.when init on step 0, += on every step) incl. a padded tail block
    (0.3, 600, 64),
])
def test_kernel_fwd_and_grads(p, rows, block_rows):
    rs = onp.random.RandomState(1)
    D = 128
    x = jnp.asarray(rs.randn(rows, D).astype(onp.float32))
    h = jnp.asarray(rs.randn(rows, D).astype(onp.float32))
    g = jnp.asarray(rs.rand(D).astype(onp.float32) + 0.5)
    b = jnp.asarray(rs.randn(D).astype(onp.float32))
    mask = jnp.asarray((rs.rand(rows, D) > p).astype(onp.float32))

    kw = dict(p=p, mask=mask if p > 0 else None, interpret=True,
              block_rows=block_rows)
    out = ln_residual_dropout(x, h, g, b, **kw)
    want = _ref(x, h, g, b, mask if p > 0 else jnp.ones_like(x), p)
    onp.testing.assert_allclose(out, want, rtol=3e-5, atol=3e-5)

    gf = jax.grad(lambda a: (ln_residual_dropout(*a, **kw) ** 2).sum())(
        (x, h, g, b))
    gr = jax.grad(lambda a: (_ref(*a, mask if p > 0 else jnp.ones_like(x),
                                  p) ** 2).sum())((x, h, g, b))
    for got, want_, name in zip(gf, gr, "xhgb"):
        onp.testing.assert_allclose(got, want_, rtol=5e-4, atol=5e-4,
                                    err_msg=name)


def test_encoder_cell_fused_matches_unfused():
    # same params, fused on vs off: eval-mode forward must agree
    from mxnet_tpu.gluon.nn import TransformerEncoderCell
    old = mx.config.get("fused_ln_residual")
    try:
        mx.config.set("fused_ln_residual", "off")
        cell = TransformerEncoderCell(128, 256, 4, dropout=0.1)
        cell.initialize()
        x = np.array(onp.random.RandomState(0).randn(2, 6, 128)
                     .astype(onp.float32))
        want = cell(x).asnumpy()
        mx.config.set("fused_ln_residual", "on")
        got = cell(x).asnumpy()
        onp.testing.assert_allclose(got, want, rtol=3e-5, atol=3e-5)
    finally:
        mx.config.set("fused_ln_residual", old)


def test_encoder_cell_fused_trains():
    # gradient flow end to end with dropout active under the fused path
    from mxnet_tpu.gluon.nn import TransformerEncoderCell
    old = mx.config.get("fused_ln_residual")
    try:
        mx.config.set("fused_ln_residual", "on")
        cell = TransformerEncoderCell(128, 256, 4, dropout=0.2)
        cell.initialize()
        x = np.array(onp.random.RandomState(0).randn(2, 6, 128)
                     .astype(onp.float32))
        with autograd.record():
            y = (cell(x) ** 2).mean()
        y.backward()
        for name, prm in cell.collect_params().items():
            if prm.grad_req != "null":
                assert onp.isfinite(prm.grad().asnumpy()).all(), name
        lng = cell.attn_ln.gamma.grad().asnumpy()
        assert onp.abs(lng).sum() > 0
    finally:
        mx.config.set("fused_ln_residual", old)

"""Kernel-level autotuning: searched Pallas block shapes, the learned
cost model, and drift-triggered online re-tuning.

Strategy mirrors test_autotune.py: the search loop runs against a
deterministic fake measurer (convergence, fraction cap, persistence and
the retune drill are exact assertions); a parity oracle then proves
every candidate block shape computes the same function in interpret
mode (outputs allclose, grads for flash attention), so ANY winner the
search picks is numerically safe.
"""
import json
import math

import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autotune, config, fault, insight, telemetry
from mxnet_tpu.autotune import kernels as K
from mxnet_tpu.autotune.learned import (LearnedCostModel, rank_gate,
                                        spearman)
from mxnet_tpu.autotune.persist import append_trials, kernel_key

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path):
    """Every test gets its own winners file, a clean tuned table and
    clean counters."""
    prior = config.get("autotune.cache_dir")
    config.set("autotune.cache_dir", str(tmp_path / "autotune"))
    K.reset()
    telemetry.reset()
    telemetry.enable()
    try:
        yield
    finally:
        config.set("autotune.cache_dir", prior)
        config.set("autotune.retune_on_drift", False)
        K.reset()
        insight.reset()
        insight.disable()
        telemetry.reset()
        telemetry.disable()
        fault.configure(None)


def _planted(best, weight=1.0):
    """Deterministic fake measurer: seconds grow with the log-distance
    of every block axis from the planted optimum."""
    def measure(kernel, bucket, blocks):
        d = sum(abs(math.log2(v) - math.log2(best.get(k, v)))
                for k, v in blocks.items())
        return 1e-3 * (1.0 + weight * d)
    return measure


# ---------------------------------------------------------------------------
# routing: static defaults, buckets, tuned table
# ---------------------------------------------------------------------------

def test_static_defaults_cover_every_kernel_and_family():
    for fam in ("v4", "v5e", "v6", "cpu"):
        for kern in K.KERNELS:
            blocks = K._STATIC_DEFAULTS[fam][kern]
            assert set(blocks) == set(K._SPACE[kern])
    # the CPU row IS the historical one-size constants (interpret-mode
    # CI behavior must be bit-identical untuned)
    assert K._STATIC_DEFAULTS["cpu"]["flash_attention"] == {
        "block_q": 1024, "block_k": 512}
    assert K._STATIC_DEFAULTS["cpu"]["quantized_matmul"] == {
        "block_m": 256, "block_n": 256}
    assert K._STATIC_DEFAULTS["cpu"]["ln_residual"] == {"block_rows": 256}


def test_device_family_mapping():
    assert K._device_family("TPU v4") == "v4"
    assert K._device_family("TPU v3") == "v4"
    assert K._device_family("TPU v5e") == "v5e"
    assert K._device_family("TPU v5 lite") == "v5e"
    assert K._device_family("TPU v5p") == "v6"
    assert K._device_family("TPU v6e") == "v6"
    assert K._device_family("cpu") == "cpu"
    assert K._device_family() == "cpu"    # this CI host


def test_shape_bucket_rounds_to_powers_of_two():
    assert K.shape_bucket("flash_attention", (100, 120, 64)) == (128, 128, 64)
    assert K.shape_bucket("quantized_matmul", (1000, 512, 3000)) == (
        1024, 512, 4096)
    assert K.shape_bucket("ln_residual", (5000, 1024)) == (8192, 1024)
    with pytest.raises(mx.MXNetError):
        K.shape_bucket("nope", (1, 2))


def test_resolve_blocks_untuned_is_static_and_tuned_wins_per_bucket():
    assert K.resolve_blocks("flash_attention") == {
        "block_q": 1024, "block_k": 512}
    assert K.resolve_blocks("flash_attention", (300, 300, 64)) == {
        "block_q": 1024, "block_k": 512}
    K._TUNED[("flash_attention", (512, 512, 64))] = {
        "block_q": 256, "block_k": 128}
    # matching bucket -> tuned; other buckets stay static
    assert K.resolve_blocks("flash_attention", (300, 300, 64)) == {
        "block_q": 256, "block_k": 128}
    assert K.resolve_blocks("flash_attention", (2000, 2000, 64)) == {
        "block_q": 1024, "block_k": 512}
    K.reset()
    assert K.resolve_blocks("flash_attention", (300, 300, 64)) == {
        "block_q": 1024, "block_k": 512}


def test_kernel_candidates_dedup_by_clamped_blocks():
    full = K.kernel_candidates("flash_attention")
    assert len(full) == 16 and full == K.kernel_candidates("flash_attention")
    # a tiny bucket collapses the grid to ONE effective candidate
    assert len(K.kernel_candidates("flash_attention", (128, 128, 64))) == 1
    some = K.kernel_candidates("flash_attention", (512, 512, 64))
    assert 1 < len(some) < len(full)
    with pytest.raises(mx.MXNetError):
        K.kernel_candidates("flash_attention", axes={"block_z": (1,)})


# ---------------------------------------------------------------------------
# parity oracle: every candidate computes the same function
# ---------------------------------------------------------------------------

def test_flash_attention_parity_across_all_candidate_blocks():
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention
    rs = onp.random.RandomState(0)
    q = jnp.asarray(rs.randn(1, 2, 200, 64), jnp.float32)
    k = jnp.asarray(rs.randn(1, 2, 200, 64), jnp.float32)
    v = jnp.asarray(rs.randn(1, 2, 200, 64), jnp.float32)
    bucket = K.shape_bucket("flash_attention", (200, 200, 64))

    def run(blocks, bwd_blocks):
        def loss(q_, k_, v_):
            return flash_attention(
                q_, k_, v_, causal=True, interpret=True,
                block_q=blocks["block_q"], block_k=blocks["block_k"],
                bwd_block_q=bwd_blocks["block_q"],
                bwd_block_k=bwd_blocks["block_k"]).sum()
        out = flash_attention(q, k, v, causal=True, interpret=True,
                              **blocks)
        g = jax.grad(loss, argnums=(0, 1, 2))(q, k, v)
        return out, g

    fwd_cands = K.kernel_candidates("flash_attention", bucket)
    bwd_cands = K.kernel_candidates("flash_attention_bwd", bucket)
    assert len(fwd_cands) > 1 and len(bwd_cands) > 1
    ref_out, ref_g = run(fwd_cands[0], bwd_cands[0])
    for fb in fwd_cands[1:]:
        out, g = run(fb, bwd_cands[0])
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref_out),
                                    atol=2e-5)
        for a, b in zip(g, ref_g):
            onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                        atol=2e-4)
    for bb in bwd_cands[1:]:     # bwd tiles vary independently of the fwd
        _, g = run(fwd_cands[0], bb)
        for a, b in zip(g, ref_g):
            onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b),
                                        atol=2e-4)


@pytest.mark.parametrize("kernel", ["quantized_matmul", "fp8_matmul"])
def test_matmul_parity_across_all_candidate_blocks(kernel):
    from mxnet_tpu.ops.pallas.quant_matmul import (FP8_FORMATS, fp8_matmul,
                                                   quantized_matmul)
    rs = onp.random.RandomState(1)
    m = n = kk = 200
    x = jnp.asarray(rs.randn(m, kk), jnp.float32)
    ws = jnp.asarray(onp.abs(rs.randn(n)) / 127.0 + 1e-4, jnp.float32)
    xs = jnp.float32(0.05)
    if kernel == "quantized_matmul":
        w = jnp.asarray(rs.randint(-127, 128, (n, kk)), jnp.int8)
        mm = lambda **kw: quantized_matmul(x, w, ws, xs, interpret=True,
                                           **kw)
    else:
        w = jnp.asarray(rs.randn(n, kk), FP8_FORMATS["e4m3"][0])
        mm = lambda **kw: fp8_matmul(x, w, ws, xs, interpret=True, **kw)
    bucket = K.shape_bucket(kernel, (m, n, kk))
    cands = K.kernel_candidates(kernel, bucket)
    assert len(cands) > 1
    ref = mm(**cands[0])
    for blocks in cands[1:]:
        onp.testing.assert_allclose(onp.asarray(mm(**blocks)),
                                    onp.asarray(ref), rtol=1e-5, atol=1e-4)


def test_ln_residual_parity_across_all_candidate_blocks():
    from mxnet_tpu.ops.pallas.ln_residual import ln_residual_dropout
    rs = onp.random.RandomState(2)
    x = jnp.asarray(rs.randn(300, 128), jnp.float32)
    h = jnp.asarray(rs.randn(300, 128), jnp.float32)
    g = jnp.asarray(rs.randn(128), jnp.float32)
    b = jnp.asarray(rs.randn(128), jnp.float32)
    bucket = K.shape_bucket("ln_residual", (300, 128))
    cands = K.kernel_candidates("ln_residual", bucket)
    assert len(cands) > 1
    ref = ln_residual_dropout(x, h, g, b, interpret=True, **cands[0])
    for blocks in cands[1:]:
        out = ln_residual_dropout(x, h, g, b, interpret=True, **blocks)
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref),
                                    atol=2e-5)


# ---------------------------------------------------------------------------
# search: convergence, fraction cap, persistence
# ---------------------------------------------------------------------------

def _vmem_kept(kernel, bucket):
    from mxnet_tpu.autotune.cost import (VMEM_BYTES, VMEM_FRACTION,
                                         kernel_tile_bytes)
    budget = int(VMEM_BYTES * VMEM_FRACTION)
    return [b for b in K.kernel_candidates(kernel, bucket)
            if kernel_tile_bytes(kernel, bucket, b) <= budget]


def test_search_converges_to_planted_optimum():
    best = {"block_q": 512, "block_k": 256}
    bucket = (2048, 2048, 128)
    shapes = {"flash_attention": [bucket]}
    kept = _vmem_kept("flash_attention", bucket)
    assert len(kept) > 8        # a rich grid survives the VMEM budget
    res = K.search_kernels(kernels=("flash_attention",), shapes=shapes,
                           measure=_planted(best), fraction=1.0)
    assert res.n_trials == len(kept) and not res.cache_hits
    assert res.tuned[("flash_attention", bucket)] == best
    # published into the process-global table: call-site routing sees it
    assert K.resolve_blocks("flash_attention", (2000, 1500, 128)) == best
    assert telemetry.counters()[
        "autotune.kernel_trials_total"] == len(kept)
    assert telemetry.counters()[
        'autotune.pruned_total{reason="vmem"}'] == 16 - len(kept)


def test_second_search_is_answered_from_cache_with_zero_trials():
    best = {"block_q": 512, "block_k": 256}
    shapes = {"flash_attention": [(2048, 2048, 128)]}
    K.search_kernels(kernels=("flash_attention",), shapes=shapes,
                     measure=_planted(best), fraction=1.0)
    K.reset()   # fresh process simulation: table empty, file warm
    calls = []

    def measure(kernel, bucket, blocks):
        calls.append(blocks)
        return 1.0

    res = K.search_kernels(kernels=("flash_attention",), shapes=shapes,
                           measure=measure)
    assert not calls and res.n_trials == 0 and res.cache_hits == 1
    assert res.tuned[("flash_attention", (2048, 2048, 128))] == best
    assert K.resolve_blocks("flash_attention", (2048, 2048, 128)) == best
    assert telemetry.counters()["autotune.kernel_cache_hits_total"] == 1


def test_measured_fraction_respects_the_knob_and_includes_default():
    bucket = (2048, 2048, 128)
    shapes = {"flash_attention": [bucket]}
    kept = len(_vmem_kept("flash_attention", bucket))
    res = K.search_kernels(kernels=("flash_attention",), shapes=shapes,
                           measure=_planted({"block_q": 256,
                                             "block_k": 128}),
                           fraction=0.25)
    assert res.n_trials == max(1, int(0.25 * kept)) == 3
    # the static default is always one of the measured baselines
    default = K.static_blocks("flash_attention")
    eff = {tuple(sorted(t["blocks"].items())) for t in res.trials}
    assert tuple(sorted(default.items())) in eff
    counters = telemetry.counters()
    assert counters['autotune.pruned_total{reason="ranked_out"}'] == kept - 3


def test_winner_persists_with_kind_kernel_and_schema_2(tmp_path):
    # at dim 1024 the VMEM budget prunes block_rows >= 512, so plant 256
    shapes = {"ln_residual": [(4096, 1024)]}
    res = K.search_kernels(kernels=("ln_residual",), shapes=shapes,
                           measure=_planted({"block_rows": 256}),
                           fraction=1.0)
    with open(autotune.winners_path()) as f:
        doc = json.load(f)
    assert doc["schema"] == 2
    key = kernel_key("ln_residual", (4096, 1024), "cpu")
    rec = doc["winners"][key]
    assert rec["kind"] == "kernel"
    assert rec["blocks"] == {"block_rows": 256}
    assert len(doc["trials"]) == res.n_trials > 0
    # load_tuned restores the table in a fresh process
    K.reset()
    assert K.load_tuned() == 1
    assert K.resolve_blocks("ln_residual", (4000, 1024)) == {
        "block_rows": 256}


def test_schema_1_file_migrates_in_place_and_step_winner_survives():
    path = autotune.winners_path()
    import os
    os.makedirs(os.path.dirname(path), exist_ok=True)
    step_rec = {"config": {"batch_size": 32, "steps_per_call": 2,
                           "grad_accum": 1, "zero": 0, "remat": False,
                           "prefetch_depth": 2},
                "fingerprint": "abcd1234", "items_per_s": 100.0}
    with open(path, "w") as f:
        json.dump({"version": 1,
                   "winners": {"abcd1234|cpu|dp1": step_rec}}, f)
    # a kernel search writes into the SAME file; the v1 step winner
    # must survive verbatim with zero re-trials needed
    K.search_kernels(kernels=("ln_residual",),
                     shapes={"ln_residual": [(4096, 1024)]},
                     measure=_planted({"block_rows": 512}), fraction=1.0)
    with open(path) as f:
        doc = json.load(f)
    assert doc["schema"] == 2 and doc["version"] == 2
    assert doc["winners"]["abcd1234|cpu|dp1"] == step_rec
    assert autotune.load_winner("abcd1234|cpu|dp1") == step_rec
    assert kernel_key("ln_residual", (4096, 1024), "cpu") in doc["winners"]


def test_oom_trial_is_recorded_and_search_survives():
    fault.configure("autotune.trial_oom:at=2,times=1")
    res = K.search_kernels(kernels=("flash_attention",),
                           shapes={"flash_attention": [(2048, 2048, 128)]},
                           measure=_planted({"block_q": 512,
                                             "block_k": 256}),
                           fraction=1.0)
    by_status = {}
    for t in res.trials:
        by_status[t["status"]] = by_status.get(t["status"], 0) + 1
    n_kept = len(_vmem_kept("flash_attention", (2048, 2048, 128)))
    assert by_status.get("oom") == 1 and by_status["ok"] == n_kept - 1
    assert res.tuned   # a winner still emerged
    assert telemetry.counters()["autotune.trials_oom_total"] == 1


# ---------------------------------------------------------------------------
# learned cost model
# ---------------------------------------------------------------------------

def test_spearman_ranks_with_ties():
    assert spearman([1, 2, 3], [10, 20, 30]) == pytest.approx(1.0)
    assert spearman([1, 2, 3], [30, 20, 10]) == pytest.approx(-1.0)
    assert spearman([1, 1, 1], [1, 2, 3]) == 0.0
    assert spearman([], []) == 0.0
    with pytest.raises(mx.MXNetError):
        spearman([1], [1, 2])


def _synthetic_records(bucket=(1024, 1024, 1024)):
    """Ground truth the analytic model ranks BADLY: runtime grows with
    tile size (the analytic cost prefers big tiles — fewer launches)."""
    records = []
    for blocks in K.kernel_candidates("quantized_matmul"):
        sec = 1e-3 * (math.log2(blocks["block_m"])
                      + 0.5 * math.log2(blocks["block_n"]))
        records.append({"kernel": "quantized_matmul",
                        "bucket": list(bucket), "blocks": blocks,
                        "seconds": sec})
    return records


def test_learned_model_outranks_analytic_on_synthetic_trials():
    records = _synthetic_records()
    model = LearnedCostModel()
    assert model.fit(records) == len(records) >= 8
    use, lc, ac = rank_gate(model, records)
    assert use is True
    assert lc > 0.9          # near-perfect fit of a log-linear truth
    assert lc >= ac          # the asserted beats-or-ties bar


def test_search_ranks_by_learned_model_once_records_accumulate():
    append_trials(_synthetic_records())
    res = K.search_kernels(kernels=("quantized_matmul",),
                           shapes={"quantized_matmul": [(1024, 1024,
                                                         1024)]},
                           measure=_planted({"block_m": 64,
                                             "block_n": 128}),
                           fraction=0.5)
    assert res.ranked_by == "learned"
    assert res.learned_corr >= res.analytic_corr
    assert telemetry.snapshot()["gauges"][
        "autotune.learned_rank_corr"] == pytest.approx(res.learned_corr,
                                                       abs=1e-3)
    # the learned ranking (small tiles first, matching the synthetic
    # truth) put the planted optimum inside the measured half
    assert res.tuned[("quantized_matmul", (1024, 1024, 1024))] == {
        "block_m": 64, "block_n": 128}


def test_run_report_carries_kernel_trials_and_learned_reads_them_back(
        tmp_path):
    from mxnet_tpu.autotune.learned import load_telemetry_records
    K.search_kernels(kernels=("ln_residual",),
                     shapes={"ln_residual": [(4096, 1024)]},
                     measure=_planted({"block_rows": 512}), fraction=1.0)
    report_path = tmp_path / "report.jsonl"
    tt = telemetry.TrainingTelemetry(path=str(report_path), interval=1)
    tt.step(loss=1.0)
    report = tt.close()
    assert report["autotune"]["kernels"]["trials"] > 0
    assert report["autotune"]["kernel_trials"]
    # the fleet loop: JSONL report -> training records for the model
    records = load_telemetry_records(str(report_path))
    assert records and all(r["kernel"] == "ln_residual" for r in records)
    model = LearnedCostModel()
    assert model.fit(records) == len(records)


# ---------------------------------------------------------------------------
# drift-triggered online re-tune (the chaos drill)
# ---------------------------------------------------------------------------

def _dense_step(cfg):
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel.train import ShardedTrainStep
    mx.random.seed(3)
    net = nn.Dense(8, in_units=4)
    net.initialize()

    def loss_fn(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], -1))

    return ShardedTrainStep(net, loss_fn, "adam", cfg,
                            batch_specs=cfg.batch_specs(2, 1), n_labels=1)


def test_drift_event_triggers_background_retune_and_checkpoint_swap():
    from mxnet_tpu.parallel.mesh import MeshConfig
    cfg = MeshConfig(dp=8)
    step = _dense_step(cfg)
    rs = onp.random.RandomState(5)
    x = rs.randn(16, 4).astype("float32")
    y = rs.randint(0, 8, (16,)).astype("int32")
    losses = [float(step(x, y)) for _ in range(3)]

    retuner = autotune.Retuner(
        kernels=("flash_attention",),
        shapes={"flash_attention": [(2048, 2048, 128)]},
        measure=_planted({"block_q": 512, "block_k": 256}),
        fraction=1.0).arm()
    config.set("autotune.retune_on_drift", True)
    config.set("insight.drift_window", 8)
    insight.enable()
    for _ in range(8):
        telemetry.observe("trainer.step_seconds", 0.1)
    fault.configure("insight.drift:prob=1")     # stretch every sample 3x
    for _ in range(8):
        telemetry.observe("trainer.step_seconds", 0.1)
        if insight.drift_events():
            break
    assert insight.drift_events(), "chaos drift did not fire"
    fault.configure(None)

    retuner.join(timeout=30)
    assert retuner.pending and retuner.searches == 1
    # winners are STAGED, not live: the global table is untouched until
    # the checkpoint boundary
    assert K.resolve_blocks("flash_attention", (2048, 2048, 128)) == {
        "block_q": 1024, "block_k": 512}

    n_before = step._n_step
    swapped = retuner.checkpoint(step)
    assert swapped is not step and swapped._n_step == n_before
    assert not retuner.pending and retuner.applied == 1
    assert K.resolve_blocks("flash_attention", (2048, 2048, 128)) == {
        "block_q": 512, "block_k": 256}
    assert telemetry.counters()["autotune.retunes_total"] == 1
    # the loss trajectory continues uninterrupted on the same weights
    after = [float(swapped(x, y)) for _ in range(3)]
    assert all(onp.isfinite(after))
    assert after[-1] < losses[0]
    # idle checkpoint boundaries are free no-ops
    assert retuner.checkpoint(swapped) is swapped
    retuner.disarm()


def test_retune_hook_is_a_noop_while_the_knob_is_off():
    retuner = autotune.Retuner(measure=_planted({})).arm()
    config.set("autotune.retune_on_drift", False)
    retuner._on_drift("trainer.step", {"seconds": 0.3})
    assert retuner.searches == 0 and not retuner.pending
    assert retuner.checkpoint(None) is None
    retuner.disarm()


def test_insight_drift_hooks_fan_out_and_reset_clears():
    seen = []
    insight.on_drift(lambda s, e: seen.append(s))
    insight.on_drift(lambda s, e: 1 / 0)     # broken subscriber: swallowed
    insight._record_drift("trainer.step",
                          {"seconds": 0.3, "baseline": 0.1, "ewma": 0.3})
    assert seen == ["trainer.step"]
    insight.reset()
    insight._record_drift("trainer.step",
                          {"seconds": 0.3, "baseline": 0.1, "ewma": 0.3})
    assert seen == ["trainer.step"]          # hook gone after reset


def test_rebuild_defaults_to_own_mesh_config():
    from mxnet_tpu.parallel.mesh import MeshConfig
    step = _dense_step(MeshConfig(dp=8))
    rs = onp.random.RandomState(6)
    x = rs.randn(8, 4).astype("float32")
    y = rs.randint(0, 8, (8,)).astype("int32")
    float(step(x, y))
    rebuilt = step.rebuild()
    assert rebuilt.mesh_config == step.mesh_config
    assert rebuilt._n_step == step._n_step
    assert onp.isfinite(float(rebuilt(x, y)))

"""npx operator value + gradient sweep.

Complements test_numpy_op_sweep.py for the mx.npx surface: hand-rolled
numpy oracles for forward values (no jnp involved in the expected side) and
finite-difference gradient checks for the differentiable nn ops — the
composite-op class the round-3 verdict flagged as untested (grads of npx
compositions). Reference analog: tests/python/unittest/test_numpy_op.py's
npx sections + test_operator.py (check_softmax_grad etc.).
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np, npx
from mxnet_tpu.test_utils import check_numeric_gradient

RNG = onp.random.RandomState(11)


def _softmax_np(x, axis=-1):
    e = onp.exp(x - x.max(axis=axis, keepdims=True))
    return e / e.sum(axis=axis, keepdims=True)


def test_softmax_log_softmax_values():
    x = RNG.randn(3, 5).astype(onp.float32)
    onp.testing.assert_allclose(npx.softmax(np.array(x)).asnumpy(),
                                _softmax_np(x), rtol=1e-5, atol=1e-6)
    onp.testing.assert_allclose(npx.log_softmax(np.array(x)).asnumpy(),
                                onp.log(_softmax_np(x)), rtol=1e-4,
                                atol=1e-5)
    onp.testing.assert_allclose(npx.softmax(np.array(x), axis=0).asnumpy(),
                                _softmax_np(x, 0), rtol=1e-5, atol=1e-6)


def test_softmax_with_temperature_and_length():
    x = RNG.randn(2, 4).astype(onp.float32)
    t = 2.5
    onp.testing.assert_allclose(
        npx.softmax(np.array(x), temperature=t).asnumpy(),
        _softmax_np(x / t), rtol=1e-5, atol=1e-6)
    lengths = onp.array([2, 3], onp.int32)
    out = npx.softmax(np.array(x), length=np.array(lengths)).asnumpy()
    for i, L in enumerate(lengths):
        onp.testing.assert_allclose(out[i, :L], _softmax_np(x[i, :L]),
                                    rtol=1e-5, atol=1e-6)
        onp.testing.assert_allclose(out[i, L:], 0.0, atol=1e-6)


def test_masked_softmax_values():
    x = RNG.randn(2, 4).astype(onp.float32)
    mask = onp.array([[1, 1, 0, 0], [1, 1, 1, 0]], bool)
    out = npx.masked_softmax(np.array(x), np.array(mask)).asnumpy()
    for i in range(2):
        sel = mask[i]
        onp.testing.assert_allclose(out[i, sel], _softmax_np(x[i, sel]),
                                    rtol=1e-5, atol=1e-6)
        onp.testing.assert_allclose(out[i, ~sel], 0.0, atol=1e-6)


def test_layer_norm_value_oracle():
    x = RNG.randn(4, 6).astype(onp.float32)
    g = RNG.rand(6).astype(onp.float32) + 0.5
    b = RNG.randn(6).astype(onp.float32)
    got = npx.layer_norm(np.array(x), np.array(g), np.array(b),
                         eps=1e-5).asnumpy()
    mu = x.mean(-1, keepdims=True)
    var = x.var(-1, keepdims=True)
    want = (x - mu) / onp.sqrt(var + 1e-5) * g + b
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_group_norm_value_oracle():
    x = RNG.randn(2, 6, 3).astype(onp.float32)
    g = onp.ones(6, onp.float32)
    b = onp.zeros(6, onp.float32)
    got = npx.group_norm(np.array(x), np.array(g), np.array(b),
                         num_groups=2, eps=1e-5).asnumpy()
    xr = x.reshape(2, 2, 3 * 3)
    mu = xr.mean(-1, keepdims=True)
    var = xr.var(-1, keepdims=True)
    want = ((xr - mu) / onp.sqrt(var + 1e-5)).reshape(x.shape)
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_fully_connected_value_oracle():
    x = RNG.randn(3, 4).astype(onp.float32)
    w = RNG.randn(5, 4).astype(onp.float32)
    b = RNG.randn(5).astype(onp.float32)
    got = npx.fully_connected(np.array(x), np.array(w), np.array(b),
                              num_hidden=5).asnumpy()
    onp.testing.assert_allclose(got, x @ w.T + b, rtol=1e-4, atol=1e-5)
    # flatten=True collapses trailing dims (reference fully_connected.cc)
    x3 = RNG.randn(3, 2, 2).astype(onp.float32)
    got = npx.fully_connected(np.array(x3), np.array(w), np.array(b),
                              num_hidden=5, flatten=True).asnumpy()
    onp.testing.assert_allclose(got, x3.reshape(3, 4) @ w.T + b, rtol=1e-4,
                                atol=1e-5)


def test_pick_one_hot_values():
    x = RNG.randn(3, 5).astype(onp.float32)
    idx = onp.array([0, 2, 4], onp.int32)
    got = npx.pick(np.array(x), np.array(idx)).asnumpy()
    onp.testing.assert_allclose(got, x[onp.arange(3), idx], rtol=1e-6)
    oh = npx.one_hot(np.array(idx), 5).asnumpy()
    onp.testing.assert_allclose(oh, onp.eye(5, dtype=onp.float32)[idx])


def test_embedding_value():
    w = RNG.randn(7, 3).astype(onp.float32)
    ids = onp.array([[1, 6], [0, 3]], onp.int32)
    got = npx.embedding(np.array(ids), np.array(w), input_dim=7,
                        output_dim=3).asnumpy()
    onp.testing.assert_allclose(got, w[ids], rtol=1e-6)


def test_sequence_mask_value():
    x = onp.ones((2, 3, 2), onp.float32)  # (N, T, C) with axis=1
    out = npx.sequence_mask(np.array(x), np.array([1, 3], onp.int32),
                            use_sequence_length=True, axis=1).asnumpy()
    assert out[0, 1:].sum() == 0 and out[1].sum() == 6


def test_topk_values():
    x = onp.array([[3.0, 1.0, 2.0], [0.0, 5.0, 4.0]], onp.float32)
    idx = npx.topk(np.array(x), k=2).asnumpy()
    onp.testing.assert_array_equal(idx, [[0, 2], [1, 2]])


# -- gradients --------------------------------------------------------------

X34 = RNG.randn(3, 4).astype(onp.float32)
W54 = RNG.randn(5, 4).astype(onp.float32)


NPX_GRAD_CASES = {
    "softmax": ([X34], lambda xs: (npx.softmax(xs[0])
                                   * np.array(X34 + 2.0)).sum()),
    "log_softmax": ([X34], lambda xs: (npx.log_softmax(xs[0])
                                       * np.array(X34)).sum()),
    "masked_softmax": ([X34], lambda xs: (npx.masked_softmax(
        xs[0], np.array(onp.array([[1, 1, 0, 1]] * 3, bool)))
        * np.array(X34)).sum()),
    "activation_gelu": ([X34], lambda xs: npx.activation(
        xs[0], act_type="gelu").sum()),
    "activation_softrelu": ([X34], lambda xs: npx.activation(
        xs[0], act_type="softrelu").sum()),
    "leaky_relu": ([X34 + 3.0], lambda xs: npx.leaky_relu(
        xs[0], slope=0.1).sum()),
    "fully_connected": (
        [X34, W54],
        lambda xs: (npx.fully_connected(xs[0], xs[1], None, num_hidden=5,
                                        no_bias=True) ** 2).sum()),
    "layer_norm": (
        [X34, onp.abs(RNG.randn(4).astype(onp.float32)) + 0.5],
        lambda xs: (npx.layer_norm(xs[0], xs[1],
                                   np.zeros((4,)), eps=1e-5)
                    * np.array(X34)).sum()),
    "pick": ([X34], lambda xs: npx.pick(
        xs[0], np.array(onp.array([0, 1, 3], onp.int32))).sum()),
    "batch_dot": (
        [RNG.randn(2, 2, 3).astype(onp.float32),
         RNG.randn(2, 3, 2).astype(onp.float32)],
        lambda xs: (npx.batch_dot(xs[0], xs[1]) ** 2).sum()),
    "embedding_weight": (
        [RNG.randn(5, 2).astype(onp.float32)],
        lambda xs: (npx.embedding(
            np.array(onp.array([0, 2, 2], onp.int32)), xs[0],
            input_dim=5, output_dim=2) ** 2).sum()),
    "convolution": (
        [RNG.randn(1, 2, 5, 5).astype(onp.float32),
         RNG.randn(3, 2, 3, 3).astype(onp.float32)],
        lambda xs: (npx.convolution(xs[0], xs[1], kernel=(3, 3),
                                    num_filter=3, no_bias=True) ** 2).sum()),
    "pooling_avg": (
        [RNG.randn(1, 2, 4, 4).astype(onp.float32)],
        lambda xs: (npx.pooling(xs[0], kernel=(2, 2), stride=(2, 2),
                                pool_type="avg") ** 2).sum()),
}

_DCN_X = RNG.randn(1, 2, 5, 5).astype(onp.float32)
_DCN_W = RNG.randn(4, 2, 3, 3).astype(onp.float32)
# offsets fixed strictly between grid points: bilinear interpolation is
# smooth in the offset except AT integer crossings, so finite differences
# with eps < distance-to-integer are valid everywhere
NPX_GRAD_CASES["deformable_conv_offsets"] = (
    [onp.full((1, 18, 3, 3), 0.37, onp.float32)],
    lambda xs: (npx.deformable_convolution(
        np.array(_DCN_X), xs[0], np.array(_DCN_W),
        kernel=(3, 3), num_filter=4, no_bias=True) ** 2).sum())


@pytest.mark.parametrize("name", sorted(NPX_GRAD_CASES))
def test_npx_gradient_matches_finite_difference(name):
    arrays, f = NPX_GRAD_CASES[name]
    inputs = [np.array(a) for a in arrays]
    eps = 5e-3 if name == "deformable_conv_offsets" else 1e-2
    check_numeric_gradient(f, inputs, eps=eps, rtol=3e-2, atol=2e-1
                           if name == "deformable_conv_offsets" else 2e-2)

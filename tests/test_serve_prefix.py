"""Engine-level serving throughput features (docs/SERVING.md):
radix prefix-cache KV reuse, speculative decoding, multi-tenant SLO
classes.

Oracles: greedy token parity — a prefix-cache engine, a speculative
engine (any draft), and both combined must emit token-for-token what
the plain engine emits (the plain engine itself is pinned to the
full-forward reference in tests/test_serve.py); the radix index's
host-side invariants (strict-prefix match, block-granular split, LRU
leaf eviction, refcounts never negative); strict-priority admission
order with starvation aging; and ZERO post-warmup compiles in every
new mode — prefix on, draft attached, both, quantized — via the PR 2
recompile detector accounting.

The ``serve.prefix_evict`` chaos drill proves a vanished prefix
degrades to a full prefill (token parity intact), never a wrong
answer.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import fault, servefleet, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM
from mxnet_tpu.serve.engine import EngineBusy
from mxnet_tpu.serve.prefix import RadixIndex


def _tiny(seed=7, **kw):
    mx.random.seed(seed)
    cfg = dict(vocab_size=97, units=32, hidden_size=64, num_layers=2,
               num_heads=2, max_length=32, dropout=0.0, embed_dropout=0.0)
    cfg.update(kw)
    net = GPTForCausalLM(**cfg)
    net.initialize()
    net(mx.np.zeros((1, 2), dtype="int32"))
    return net


def _engine(net=None, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("buckets", "4,8")
    kw.setdefault("temperature", 0.0)
    return mx.serve.load(net if net is not None else _tiny(), **kw)


@pytest.fixture(scope="module")
def net():
    """One deterministic tiny GPT for the whole module — every engine
    warmup is an XLA compile bill, so the net is shared."""
    return _tiny()


@pytest.fixture(scope="module")
def plain(net):
    """One warmed cache-off/draft-off engine: the greedy-parity
    baseline for every prefix/spec variant in the module (deterministic
    greedy ⇒ safe to reuse across tests)."""
    return _engine(net, warmup=True)


@pytest.fixture
def block4():
    prev = mx.config.set("serve.prefix_block", 4)
    yield 4
    mx.config.set("serve.prefix_block", prev)


@pytest.fixture
def metrics():
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.disable()


def _shared_prefix_work(n=8, prefix_tokens=4, seed=0):
    """Prompts sharing one ``prefix_tokens``-token prefix + a 2..4-token
    random suffix — the prefix cache's bread and butter."""
    rng = onp.random.RandomState(seed)
    shared = rng.randint(1, 97, size=prefix_tokens).tolist()
    return [shared + rng.randint(1, 97, size=rng.randint(2, 5)).tolist()
            for _ in range(n)]


def _run(eng, prompts, max_new=6, **submit_kw):
    reqs = [eng.submit(p, max_new_tokens=max_new, **submit_kw)
            for p in prompts]
    eng.run()
    assert eng.post_warmup_compiles == 0, \
        f"{eng.post_warmup_compiles} post-warmup compiles"
    return [r.output_ids for r in reqs]


# -- radix index unit oracles ------------------------------------------------

def test_radix_insert_then_match_strict_prefix():
    idx = RadixIndex(block=4)
    tokens = list(range(1, 13))          # 3 full blocks
    path = idx.insert(tokens, slot=0)
    assert len(path) == 3 and len(idx) == 3
    # a longer prompt sharing the prefix matches all three blocks
    assert len(idx.match(tokens + [50])) == 3
    # strict: the SAME 12 tokens may only match 2 blocks — at least one
    # token must remain for the suffix prefill to forward
    assert len(idx.match(tokens)) == 2
    # partial blocks never index or match
    assert len(idx.match(tokens[:6])) == 1
    assert idx.match([99, 98, 97, 96]) == []


def test_radix_diverging_suffix_splits():
    idx = RadixIndex(block=4)
    a = [1, 2, 3, 4, 5, 6, 7, 8]
    b = [1, 2, 3, 4, 9, 9, 9, 9]         # shares block 0, diverges
    pa = idx.insert(a, slot=0)
    pb = idx.insert(b, slot=1)
    assert pa[0] is pb[0]                 # the shared block is one node
    assert pa[1] is not pb[1] and len(idx) == 3
    # dedup: the shared node keeps its original (slot, row) location
    assert pb[0].slot == 0 and pb[1].slot == 1


def test_radix_lru_evicts_only_unpinned_leaves():
    idx = RadixIndex(block=2, capacity=2)
    pa = idx.insert([1, 2, 3, 4], slot=0)     # fills capacity
    idx.acquire(pa)
    # pinned path cannot be evicted: the insert stops early instead
    pb = idx.insert([5, 6, 7, 8], slot=1)
    assert pb == [] and idx.evictions == 0
    idx.release(pa)
    idx.match([1, 2, 9])                      # bump block (1,2)'s LRU
    pb = idx.insert([5, 6], slot=1)
    # the cold leaf (3,4) went, the hot (1,2) stayed
    assert len(pb) == 1 and idx.evictions == 1
    assert len(idx.match([1, 2, 9])) == 1


def test_radix_refcount_underflow_raises():
    idx = RadixIndex(block=2)
    path = idx.insert([1, 2, 3, 4], slot=0)
    idx.acquire(path)
    idx.release(path)
    with pytest.raises(MXNetError, match="refcount"):
        idx.release(path)
    # released-then-evicted nodes are skipped, not raised on
    idx.acquire(path)
    idx.evict_slot(0)
    idx.release(path)


def test_radix_evict_slot_drops_whole_subtree():
    idx = RadixIndex(block=2)
    idx.insert([1, 2, 3, 4], slot=0)
    idx.insert([1, 2, 5, 6], slot=1)      # child of slot-0's block
    assert idx.evict_slot(0) == 3         # parent AND both children
    assert len(idx) == 0 and idx.match([1, 2, 9]) == []


# -- prefix-cache engine parity ----------------------------------------------

def test_prefix_cache_token_parity_and_hits(net, plain, block4, metrics):
    prompts = _shared_prefix_work()
    base = _run(plain, prompts)
    eng = _engine(net, prefix_cache=True, warmup=True)
    assert _run(eng, prompts) == base
    st = eng.stats()["prefix"]
    assert st["hits"] >= 4 and st["tokens_reused"] >= 4 * st["hits"]
    assert telemetry.counters()["serve.prefix_hits_total"] == st["hits"]
    assert telemetry.counters()["serve.prefix_tokens_reused_total"] \
        == st["tokens_reused"]


@pytest.mark.slow
def test_prefix_cache_disjoint_prompts_all_miss(net, plain, block4):
    rng = onp.random.RandomState(3)
    prompts = [rng.randint(1, 97, size=7).tolist() for _ in range(4)]
    eng = _engine(net, prefix_cache=True, warmup=True)
    base = _run(plain, prompts)
    assert _run(eng, prompts) == base
    st = eng.stats()["prefix"]
    assert st["hits"] == 0 and st["misses"] == 4


@pytest.mark.slow
def test_prefix_cache_with_int4_weights_int8_kv(net, block4):
    prompts = _shared_prefix_work()
    q = "int4_weights,int8_kv"
    base = _run(_engine(net, quantize=q, warmup=True), prompts)
    eng = _engine(net, quantize=q, prefix_cache=True, warmup=True)
    assert _run(eng, prompts) == base
    assert eng.stats()["prefix"]["hits"] >= 4


def test_prefix_cache_needs_suffix_surface(block4):
    class NoSuffix:
        max_length = 32
        init_cache = prefill = decode_step = staticmethod(
            lambda *a, **k: None)
        collect_params = staticmethod(dict)
    with pytest.raises(MXNetError, match="prefill_suffix"):
        mx.serve.ServeEngine(NoSuffix(), max_slots=2, prefix_cache=True)


# -- speculative decoding ----------------------------------------------------

@pytest.mark.slow
@pytest.mark.parametrize("k", [2, 4])
def test_spec_self_draft_greedy_parity(net, plain, k):
    rng = onp.random.RandomState(1)
    prompts = [rng.randint(1, 97, size=rng.randint(2, 9)).tolist()
               for _ in range(6)]
    base = _run(plain, prompts, max_new=8)
    prev = mx.config.set("serve.spec_tokens", k)
    try:
        eng = _engine(net, draft=net, warmup=True)
        assert eng._spec_k == k
        assert _run(eng, prompts, max_new=8) == base
        st = eng.stats()["spec"]
        # the correction token is never counted accepted, so even the
        # self-draft's perfect agreement caps at (k-1)/k
        assert 0.0 < st["acceptance_rate"] <= (k - 1) / k
    finally:
        mx.config.set("serve.spec_tokens", prev)


@pytest.mark.slow
def test_spec_foreign_draft_greedy_parity(net, plain):
    """A draft with DIFFERENT weights: acceptance drops, output must
    not — the verify pass is what decides every token."""
    draft = _tiny(seed=8)
    rng = onp.random.RandomState(2)
    prompts = [rng.randint(1, 97, size=rng.randint(2, 9)).tolist()
               for _ in range(6)]
    base = _run(plain, prompts, max_new=8)
    eng = _engine(net, draft=draft, warmup=True)
    assert _run(eng, prompts, max_new=8) == base


@pytest.mark.slow
def test_spec_fewer_dispatches_than_tokens(net):
    """The throughput mechanism, asserted structurally: at high
    acceptance (self-draft) one propose+verify dispatch emits multiple
    tokens, so decode rounds land well under tokens decoded.  (Wall
    clock is left to benchmark/serve_throughput.py --tenants: on CPU
    the draft's compute isn't cheaper than the target's, so the win is
    dispatch-bound, not FLOP-bound.)"""
    rng = onp.random.RandomState(4)
    prompts = [rng.randint(1, 97, size=4).tolist() for _ in range(4)]
    eng = _engine(net, draft=net, warmup=True)
    _run(eng, prompts, max_new=12)
    st = eng.stats()
    tokens = st["tokens_out"]
    rounds = st["spec"]["rounds"]
    assert rounds * 2 <= tokens, (rounds, tokens)


def test_spec_rejects_sampling_temperature(net):
    with pytest.raises(MXNetError, match="temperature"):
        _engine(net, draft=net, temperature=0.8)


@pytest.mark.slow
def test_prefix_and_spec_compose(net, plain, block4):
    prompts = _shared_prefix_work()
    base = _run(plain, prompts)
    eng = _engine(net, prefix_cache=True, draft=net, warmup=True)
    assert _run(eng, prompts) == base
    st = eng.stats()
    assert st["prefix"]["hits"] >= 4
    assert st["spec"]["rounds"] > 0


# -- SLO classes -------------------------------------------------------------

def _classes(spec, **extra):
    prev = {"serve.slo_classes": mx.config.set("serve.slo_classes", spec)}
    for k, v in extra.items():
        prev[k] = mx.config.set(k, v)
    return prev


def _restore(prev):
    for k, v in prev.items():
        mx.config.set(k, v)


def test_slo_strict_priority_admission_order(net):
    prev = _classes("gold,bronze")
    try:
        eng = _engine(net, max_slots=1, warmup=True)
        rng = onp.random.RandomState(5)
        bronze = [eng.submit(rng.randint(1, 97, size=3).tolist(),
                             max_new_tokens=2, slo_class="bronze")
                  for _ in range(3)]
        gold = [eng.submit(rng.randint(1, 97, size=3).tolist(),
                           max_new_tokens=2, slo_class="gold")
                for _ in range(3)]
        # untagged requests land in the LAST (lowest) class
        assert eng.submit([3, 5, 7], max_new_tokens=2).slo_class \
            == "bronze"
        eng.run()
        # every gold admission precedes every bronze one: on a 1-slot
        # engine nothing was admitted before the golds were queued
        assert max(r.t_admitted for r in gold) \
            < min(r.t_admitted for r in bronze)
        # FIFO within a class
        assert [r.t_admitted for r in gold] == sorted(
            r.t_admitted for r in gold)
        cls = eng.stats()["classes"]
        assert cls["gold"]["completed"] == 3
        assert cls["bronze"]["completed"] == 4   # 3 tagged + 1 untagged
    finally:
        _restore(prev)


def test_slo_unknown_class_rejected(net):
    prev = _classes("gold,bronze")
    try:
        eng = _engine(net, warmup=False)
        with pytest.raises(MXNetError, match="unknown slo_class"):
            eng.submit([3, 5, 7], slo_class="platinum")
    finally:
        _restore(prev)


@pytest.mark.slow
def test_slo_aging_overrides_strict_priority(net, metrics):
    """A bronze request older than serve.class_aging_ms must win one
    admission from a fresher gold — starvation is bounded."""
    import time
    prev = _classes("gold,bronze", **{"serve.class_aging_ms": 30.0})
    try:
        eng = _engine(net, max_slots=1, warmup=True)
        rng = onp.random.RandomState(6)
        br = eng.submit(rng.randint(1, 97, size=3).tolist(),
                        max_new_tokens=2, slo_class="bronze")
        time.sleep(0.05)                      # bronze crosses the knob
        g = eng.submit(rng.randint(1, 97, size=3).tolist(),
                       max_new_tokens=2, slo_class="gold")
        eng.run()
        assert br.t_admitted < g.t_admitted
        assert eng.stats()["aged_admissions"] >= 1
        assert telemetry.counters()["serve.aged_admissions_total"] >= 1
    finally:
        _restore(prev)


@pytest.mark.slow
def test_slo_per_class_queue_bound(net):
    prev = _classes("gold,bronze", **{"serve.class_max_queue": "gold=1"})
    try:
        eng = _engine(net, max_slots=1, warmup=True)
        eng.submit([3, 5, 7], max_new_tokens=2)   # occupies the slot
        eng.step()
        eng.submit([4, 6, 8], max_new_tokens=2, slo_class="gold")
        with pytest.raises(EngineBusy) as ei:
            eng.submit([5, 7, 9], max_new_tokens=2, slo_class="gold")
        assert ei.value.reason == "class_queue_full"
        # bronze is NOT bounded by gold's budget
        eng.submit([6, 8, 10], max_new_tokens=2, slo_class="bronze")
        eng.run()
    finally:
        _restore(prev)


# -- chaos: serve.prefix_evict ----------------------------------------------

@pytest.mark.slow
def test_prefix_evict_injection_falls_back_to_full_prefill(
        net, plain, block4, metrics):
    """Arm ``serve.prefix_evict``: every matched prefix vanishes
    between match and copy.  The engine must degrade to full prefills —
    zero hits, token parity intact — never serve stale or garbage KV."""
    prompts = _shared_prefix_work()
    base = _run(plain, prompts)
    fault.configure("serve.prefix_evict:prob=1")
    try:
        eng = _engine(net, prefix_cache=True, warmup=True)
        assert _run(eng, prompts) == base
        st = eng.stats()["prefix"]
        assert st["hits"] == 0
        assert fault.stats().get("injected.serve.prefix_evict", 0) >= 1
        assert telemetry.counters().get(
            "serve.prefix_evictions_total", 0) >= 1
    finally:
        fault.clear()


# -- servefleet prefix-fingerprint routing -----------------------------------

@pytest.mark.slow
def test_fleet_prefix_fingerprint_routing(block4, metrics):
    """Sessionless requests sharing a prompt prefix must land on the
    same replica (session derived from the first block's fingerprint),
    so the fleet concentrates each tenant's KV reuse."""
    def factory():
        return _tiny()

    fleet = servefleet.ServeFleet(factory, replicas=2, max_slots=2,
                                  buckets="4,8", temperature=0.0)
    try:
        prompts = _shared_prefix_work(n=6, prefix_tokens=4, seed=9)
        frs = [fleet.submit(p, max_new_tokens=2) for p in prompts]
        fleet.run(tick_interval=0.001)
        sessions = {fr.session for fr in frs}
        assert len(sessions) == 1 and sessions.pop().startswith("px-")
        assert len({fr.replica_id for fr in frs}) == 1
        assert telemetry.counters()["servefleet.prefix_routed_total"] == 6
        report = fleet.report()
        assert all("prefix_hits" in r for r in report["replicas"])
    finally:
        fleet.close()

"""mx.autotune: measured config search for the compiled step.

Strategy: the search loop runs against a deterministic fake-measurement
backend (same injection style as the fake-device ``memory_stats`` tests
in test_zero.py) so convergence, pruning, OOM survival and persistence
are exact assertions; a small number of real-trial tests then prove the
measured path is hermetic against the caller's params/optimizer.
"""
import json

import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import autotune, config, fault, telemetry
from mxnet_tpu.autotune import (
    Candidate, CostModel, ModelStats, SearchSpace, TrialOOM,
    model_fingerprint, winner_key,
)
from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.parallel.train import ShardedTrainStep

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


@pytest.fixture(autouse=True)
def _isolated_cache(tmp_path):
    """Every test gets its own winners file; counters start clean."""
    prior = config.get("autotune.cache_dir")
    config.set("autotune.cache_dir", str(tmp_path / "autotune"))
    telemetry.reset()
    telemetry.enable()
    try:
        yield
    finally:
        config.set("autotune.cache_dir", prior)
        telemetry.reset()
        telemetry.disable()
        fault.configure(None)


def _make_net(units=6, in_units=4, seed=7):
    from mxnet_tpu.gluon import nn
    mx.random.seed(seed)
    net = nn.Dense(units, in_units=in_units)
    net.initialize()
    return net


def _loss_fn(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], -1))


def _sample(n=16, in_units=4, classes=6, seed=1):
    rs = onp.random.RandomState(seed)
    return (rs.randn(n, in_units).astype("float32"),
            rs.randint(0, classes, (n,)).astype("int32"))


def _search(measure, space=None, dp=1, net=None, **kw):
    """Fake-measured search over a tiny Dense model."""
    mesh = make_mesh({"dp": dp})
    return autotune.search(
        net or _make_net(), _loss_fn, "adam", mesh, (P("dp"), P("dp")),
        _sample(), space=space or SearchSpace(batch_size=16),
        hbm_budget=None, measure=measure, **kw)


def _stats(dp=1, param_count=1000, act=1000, sample=64):
    return ModelStats(param_count=param_count, param_bytes=4 * param_count,
                      state_bytes=8 * param_count, dp=dp,
                      act_bytes_per_item=act, sample_item_bytes=sample)


# ---------------------------------------------------------------------------
# space
# ---------------------------------------------------------------------------

def test_space_grid_is_deterministic_and_contains_default():
    space = SearchSpace(batch_size=16)
    grid = space.candidates()
    assert len(grid) == len(space) == 3 * 2 * 3 * 3  # spc x ga x zero x remat
    assert grid == space.candidates()
    assert space.default_candidate() in grid
    d = space.default_candidate()
    assert (d.steps_per_call, d.grad_accum, d.zero, d.remat) == (1, 1, 0,
                                                                 False)


def test_candidate_config_roundtrips_json():
    c = Candidate(32, steps_per_call=4, grad_accum=2, zero=1, remat="dots",
                  prefetch_depth=3)
    back = Candidate.from_config(json.loads(json.dumps(c.config())))
    assert back == c and hash(back) == hash(c)


def test_precision_axis_enumerates_and_defaults():
    space = SearchSpace(batch_size=16, precision=("fp32", "int8_weights"))
    assert len(space) == 2 * 3 * 2 * 3 * 3
    precs = {c.precision for c in space.candidates()}
    assert precs == {"fp32", "int8_weights"}
    # default candidate takes the first precision — the measured baseline
    assert space.default_candidate().precision == "fp32"
    # train searches are unchanged: single-value axis by default
    assert len(SearchSpace(batch_size=16)) == 3 * 2 * 3 * 3
    with pytest.raises(mx.MXNetError):
        SearchSpace(batch_size=16, precision=())


def test_precision_roundtrips_and_loads_legacy_configs():
    c = Candidate(32, precision="int4_weights")
    back = Candidate.from_config(json.loads(json.dumps(c.config())))
    assert back == c and back.precision == "int4_weights"
    # winners persisted before the precision axis have no such key
    legacy = Candidate(32, steps_per_call=2).config()
    del legacy["precision"]
    assert Candidate.from_config(legacy).precision == "fp32"
    assert Candidate.from_config(legacy) == Candidate(32, steps_per_call=2)


def test_precision_never_pruned_by_dominance():
    """Different numeric formats have different numerics: the cost model
    may rank them (int8 cheaper) but must never analytically prune one
    in favor of another — only measured trials compare formats."""
    from mxnet_tpu.autotune.cost import PRECISION_COMPUTE_FACTOR
    model = CostModel(_stats(dp=1), hbm_budget=None)
    a = Candidate(16, prefetch_depth=0, precision="fp32")
    b = Candidate(16, prefetch_depth=0, precision="int8")
    assert model.compute_cost(b) < model.compute_cost(a)
    keep, pruned = model.plan([a, b])
    assert a in keep and b in keep and not pruned
    # factor table covers every advertised axis value
    from mxnet_tpu.autotune.space import PRECISION_VALUES
    assert set(PRECISION_VALUES) <= set(PRECISION_COMPUTE_FACTOR)


# ---------------------------------------------------------------------------
# cost model
# ---------------------------------------------------------------------------

def test_dominance_prunes_majority_without_budget():
    """>=50% of the grid must go analytically even when no HBM budget is
    known (CPU CI) — the acceptance bar for 'pruned without compiling'."""
    space = SearchSpace(batch_size=16)
    model = CostModel(_stats(dp=4), hbm_budget=None)
    keep, pruned = model.plan(space.candidates(), space.default_candidate())
    assert len(pruned) >= len(space) * 0.5
    assert space.default_candidate() in keep
    assert all(r in ("dominated", "invalid", "hbm") for _c, r in pruned)
    # nothing lost: keep + pruned partition the grid
    assert len(keep) + len(pruned) == len(space)


def test_memory_knobs_strictly_cost_compute():
    model = CostModel(_stats(dp=4), hbm_budget=None)
    base = Candidate(16, prefetch_depth=2)
    for knob in (dict(zero=1), dict(zero=2), dict(grad_accum=2),
                 dict(remat="dots"), dict(remat=True)):
        c = Candidate(16, prefetch_depth=2, **knob)
        assert model.compute_cost(c) > model.compute_cost(base), knob
        assert model.hbm_bytes(c) <= model.hbm_bytes(base), knob


def test_hbm_budget_rejects_fat_candidates():
    """With a budget only the memory-lean configs survive; the reasons
    say which rule fired."""
    model = CostModel(_stats(dp=4, act=10_000), hbm_budget=None)
    lean = Candidate(16, zero=2, grad_accum=2, remat=True, prefetch_depth=0)
    fat = Candidate(16, prefetch_depth=2)
    budget = (model.hbm_bytes(lean) + model.hbm_bytes(fat)) // 2
    tight = CostModel(_stats(dp=4, act=10_000), hbm_budget=budget)
    assert tight.fits(lean) and not tight.fits(fat)
    space = SearchSpace(batch_size=16)
    keep, pruned = tight.plan(space.candidates(), space.default_candidate())
    reasons = {r for _c, r in pruned}
    assert "hbm" in reasons
    assert all(tight.fits(c) or c == space.default_candidate()
               for c in keep)


def test_hbm_budget_auto_reads_fake_device_stats():
    """hbm_budget='auto' goes through the same PJRT memory_stats surface
    as the memory.* gauges (fake-device pattern from test_zero.py)."""
    class _Dev:
        def __init__(self, i, limit):
            self.id = i
            self._limit = limit

        def memory_stats(self):
            return {"bytes_in_use": 10, "peak_bytes_in_use": 20,
                    "bytes_limit": self._limit}

    budget = autotune.search.__globals__["_hbm_budget"](
        [_Dev(0, 1000), _Dev(1, 800)])
    # min over devices x autotune.hbm_fraction (0.9 default)
    assert budget == int(800 * config.get("autotune.hbm_fraction"))

    class _NoStats:
        id = 2

        def memory_stats(self):
            return None

    assert autotune.search.__globals__["_hbm_budget"]([_NoStats()]) is None


def test_invalid_geometry_is_pruned():
    model = CostModel(_stats(dp=4), hbm_budget=None)
    assert model.invalid_reason(Candidate(16, grad_accum=3)) == "invalid"
    assert model.invalid_reason(Candidate(6, grad_accum=2)) == "invalid"
    assert model.invalid_reason(Candidate(16, zero=1)) is None
    solo = CostModel(_stats(dp=1), hbm_budget=None)
    assert solo.invalid_reason(Candidate(16, zero=1)) == "dominated"
    no_zero = CostModel(_stats(dp=4), hbm_budget=None, zero_ok=False)
    assert no_zero.invalid_reason(Candidate(16, zero=1)) == "invalid"


def test_max_trials_caps_keep_but_spares_default():
    space = SearchSpace(batch_size=16)
    model = CostModel(_stats(dp=4), hbm_budget=None, max_trials=2)
    keep, pruned = model.plan(space.candidates(), space.default_candidate())
    assert len(keep) == 2
    assert space.default_candidate() in keep
    assert any(r == "ranked_out" for _c, r in pruned)


# ---------------------------------------------------------------------------
# search loop (deterministic fake measurements)
# ---------------------------------------------------------------------------

def _planted(best_spc=4):
    """Measurement backend with a planted optimum on the spc axis."""
    def measure(c):
        return 1000.0 + (500.0 if c.steps_per_call == best_spc else 0.0) \
            + c.steps_per_call
    return measure


def test_search_converges_to_planted_optimum():
    res = _search(_planted(best_spc=4))
    assert res.best.candidate.steps_per_call == 4
    assert res.best.items_per_s == pytest.approx(1504.0)
    assert res.speedup is not None and res.speedup > 1.0
    assert res.default is not None and res.default.status == "ok"
    assert res.pruned_fraction >= 0.5


def test_search_prunes_before_measuring():
    measured = []

    def measure(c):
        measured.append(c)
        return 100.0

    res = _search(measure)
    assert len(measured) == len(res.trials)
    assert len(measured) + len(res.pruned) == res.n_candidates
    assert len(res.pruned) >= res.n_candidates * 0.5


def test_oom_trial_recorded_not_fatal():
    """One exploding candidate must surface as status='oom' in telemetry
    and the result — and the search still produces a winner."""
    def measure(c):
        if c.steps_per_call == 2:
            raise TrialOOM("RESOURCE_EXHAUSTED: out of memory")
        return 100.0 + c.steps_per_call

    res = _search(measure)
    by_status = {t.status for t in res.trials}
    assert "oom" in by_status and "ok" in by_status
    assert res.best is not None
    assert res.best.candidate.steps_per_call != 2
    snap = telemetry.counters(aggregate=True)
    assert snap.get("autotune.trials_oom_total", 0) >= 1
    assert res.summary()["trials_oom"] >= 1


def test_injected_fault_point_ooms_one_trial():
    """The autotune.trial_oom chaos point (MXNET_FAULT_SPEC surface) fires
    inside the trial loop and is recorded as an OOM outcome."""
    fault.configure("autotune.trial_oom:at=1,times=1")
    res = _search(lambda c: 100.0)
    assert sum(1 for t in res.trials if t.status == "oom") == 1
    assert res.best is not None


def test_generic_trial_error_does_not_kill_search():
    def measure(c):
        if c.steps_per_call == 4:
            raise ValueError("trace blew up")
        return 100.0

    res = _search(measure)
    assert any(t.status == "error" for t in res.trials)
    assert res.best is not None


# ---------------------------------------------------------------------------
# persistence
# ---------------------------------------------------------------------------

def test_winner_persists_and_second_search_runs_zero_trials():
    calls = []

    def measure(c):
        calls.append(c)
        return 100.0 + c.steps_per_call

    net = _make_net(seed=3)
    first = _search(measure, net=net)
    assert not first.reused and calls
    n_first = len(calls)
    second = _search(measure, net=net)
    assert second.reused
    assert len(second.trials) == 0 and len(calls) == n_first
    assert second.config == first.config
    assert second.best.status == "cached"
    snap = telemetry.counters(aggregate=True)
    assert snap.get("autotune.cache_hits_total", 0) == 1


def test_fingerprint_invalidates_on_model_change():
    net_a, net_b = _make_net(units=6), _make_net(units=7)
    assert model_fingerprint(net_a) != model_fingerprint(net_b)
    first = _search(_planted(), net=net_a)
    second = _search(_planted(), net=net_b)
    assert not second.reused           # different fingerprint -> new search
    assert first.key != second.key
    # both live side by side in the same winners file
    winners = autotune.load_winner(first.key), autotune.load_winner(
        second.key)
    assert all(w is not None for w in winners)


def test_force_reruns_past_a_cached_winner():
    net = _make_net(seed=5)
    _search(_planted(), net=net)
    forced = _search(_planted(), net=net, force=True)
    assert not forced.reused and forced.trials


def test_winner_key_shape():
    key = winner_key("abcd", "TPU v4", 8)
    assert key == "abcd|TPU v4|dp8"


def test_winners_file_is_valid_json_with_version():
    net = _make_net(seed=9)
    res = _search(_planted(), net=net)
    with open(res.path) as f:
        data = json.load(f)
    # schema 2 (kernel winners + trials ring); "version" kept as an alias
    assert data["schema"] == 2 and data["version"] == 2
    rec = data["winners"][res.key]
    assert rec["config"] == res.config
    assert rec["fingerprint"] == res.key.split("|")[0]


# ---------------------------------------------------------------------------
# hermetic real trials
# ---------------------------------------------------------------------------

def test_real_trials_leak_no_state_into_caller():
    """Measured trials run the real ShardedTrainStep but must not move
    the block's parameters or the caller's optimizer clock."""
    net = _make_net()
    before = {n: onp.asarray(p.data()._data).copy()
              for n, p in net.collect_params().items()}
    opt = mx.optimizer.create("adam", learning_rate=0.05)
    space = SearchSpace(batch_size=16, steps_per_call=(1, 2),
                        grad_accum=(1,), zero=(0,), remat=(False,))
    mesh = make_mesh({"dp": 4})
    res = autotune.search(net, _loss_fn, opt, mesh, (P("dp"), P("dp")),
                          _sample(), space=space, hbm_budget=None,
                          trial_seconds=0.03, force=True)
    assert res.best is not None and res.best.status == "ok"
    assert opt.num_update == 0
    after = {n: onp.asarray(p.data()._data) for n, p in
             net.collect_params().items()}
    for n in before:
        onp.testing.assert_array_equal(before[n], after[n])


def test_step_autotune_returns_tuned_step_that_trains():
    net = _make_net()
    opt = mx.optimizer.create("adam", learning_rate=0.05)
    mesh = make_mesh({"dp": 4})
    step = ShardedTrainStep(net, _loss_fn, opt, mesh,
                            (P("dp"), P("dp")), n_labels=1)
    x, y = _sample()
    first = float(step(x, y))
    space = SearchSpace(batch_size=16, steps_per_call=(1, 2),
                        grad_accum=(1,), zero=(0,), remat=(False,))
    tuned, res = step.autotune(sample_batch=(x, y), space=space,
                               trial_seconds=0.03, force=True)
    assert res.best is not None
    cfg = res.config
    assert tuned.steps_per_call == cfg["steps_per_call"]
    # step counter carries over; the tuned step keeps training
    assert tuned._n_step == step._n_step
    batch = (onp.resize(x, (cfg["steps_per_call"] * 16, 4)),
             onp.resize(y, (cfg["steps_per_call"] * 16,)))
    if cfg["steps_per_call"] > 1:
        batch = tuple(b.reshape((cfg["steps_per_call"], 16) + b.shape[1:])
                      for b in batch)
    loss = float(tuned(*batch))
    assert onp.isfinite(first) and onp.isfinite(loss)


def test_search_survives_all_trials_failing():
    def measure(c):
        raise TrialOOM("out of memory")

    res = _search(measure)
    assert res.best is None and res.config is None
    assert all(t.status == "oom" for t in res.trials)


# ---------------------------------------------------------------------------
# recompile accounting
# ---------------------------------------------------------------------------

def test_trial_compile_scope_restores_detector_state():
    net = _make_net()
    prior_limit = config.get("telemetry.recompile_limit")
    telemetry.note_compile(net, "warmup", 0.01)
    baseline = net.__dict__["_telemetry_compiles"]
    with autotune.trial_compile_scope(net, limit=500):
        assert config.get("telemetry.recompile_limit") == 500
        for _ in range(5):
            telemetry.note_compile(net, "trial", 0.01)
        assert net.__dict__["_telemetry_compiles"] == baseline + 5
    assert net.__dict__["_telemetry_compiles"] == baseline
    assert not net.__dict__["_telemetry_recompile_warned"]
    assert config.get("telemetry.recompile_limit") == prior_limit


def test_search_emits_no_recompile_warnings(recwarn):
    """A full search's warmup compiles stay under the trial-scoped limit:
    zero RecompileWarning during or after."""
    net = _make_net()
    space = SearchSpace(batch_size=16, steps_per_call=(1, 2),
                        grad_accum=(1,), zero=(0,), remat=(False,))
    mesh = make_mesh({"dp": 4})
    autotune.search(net, _loss_fn, "adam", mesh, (P("dp"), P("dp")),
                    _sample(), space=space, hbm_budget=None,
                    trial_seconds=0.03, force=True, persist=False)
    assert not [w for w in recwarn.list
                if issubclass(w.category, telemetry.RecompileWarning)]


# ---------------------------------------------------------------------------
# surfaces: telemetry plane, estimator, bench
# ---------------------------------------------------------------------------

def test_run_report_carries_autotune_plane(tmp_path):
    _search(_planted())
    rep = telemetry.TrainingTelemetry(path=None)
    report = rep.close()
    assert "autotune" in report
    assert report["autotune"]["best"]["config"]["steps_per_call"] == 4
    counters = report["metrics"]["counters"]
    assert any(k.startswith("autotune.trials_total") for k in counters)


def test_estimator_fit_autotune_runs_search_before_loop():
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib import estimator as est
    from mxnet_tpu.gluon.data import DataLoader
    from mxnet_tpu.gluon.data.dataset import ArrayDataset

    mx.random.seed(11)
    x, y = _sample(n=32, in_units=4, classes=2)
    loader = DataLoader(ArrayDataset(x, y.astype("f")), batch_size=8,
                        num_workers=0)
    net = nn.Sequential()
    net.add(nn.Dense(8, activation="relu"), nn.Dense(2))
    net.initialize()
    e = est.Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                      trainer=gluon.Trainer(net.collect_params(), "adam",
                                            {"learning_rate": 0.05}))
    e.fit(loader, epochs=1,
          autotune=dict(measure=_planted(), persist=False))
    res = e.autotune_result
    assert res is not None and res.best is not None
    assert res.best.candidate.steps_per_call == 4


def test_bench_rows_carry_full_config_dict():
    import bench
    cfg = bench._config_dict(32, 4)
    assert cfg == {"batch": 32, "steps_per_call": 4, "zero": 0,
                   "grad_accum": 1, "remat": False, "prefetch_depth": None}


def test_bench_accepts_autotune_winners_file(tmp_path):
    """--config maps winners.json onto extra tuned train-family grid
    points (one per distinct winner config, per family)."""
    import bench
    winners = {"version": 1, "winners": {
        "fp|cpu|dp1": {"config": Candidate(16, steps_per_call=2).config(),
                       "items_per_s": 10.0},
        # duplicate config under another key must not double the grid
        "fp2|cpu|dp1": {"config": Candidate(16, steps_per_call=2).config()},
    }}
    path = tmp_path / "winners.json"
    path.write_text(json.dumps(winners))
    entries = bench._tuned_entries(str(path))
    assert len(entries) == len(bench._TRAIN_FAMILIES)
    for fn, kwargs in entries:
        assert kwargs["bs"] == 16 and kwargs["k_steps"] == 2
        assert kwargs["_tuned"]["steps_per_call"] == 2

    # plain {workload: config} mapping addresses one family directly
    plain = tmp_path / "plain.json"
    plain.write_text(json.dumps(
        {"gpt_train": Candidate(8, steps_per_call=4).config()}))
    entries = bench._tuned_entries(str(plain))
    assert len(entries) == 1
    assert entries[0][0] is bench.bench_gpt_train

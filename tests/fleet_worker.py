"""Subprocess body for the multi-process fleet lease drill.

Usage: python tests/fleet_worker.py <lease_dir> <rank> <nprocs>

Rank 0 is the survivor: it publishes its own lease, waits until it has
seen every peer, then watches the health plane until a peer's lease goes
stale and the structured WorkerLost escalation fires — printing the
``FLEET_LOST`` sentinel the test greps for.  Every other rank publishes
a few heartbeats and then exits WITHOUT ``stop()`` — a crash, not a
departure, so its lease is left behind to expire.
"""
import sys
import time

import mxnet_tpu as mx
from mxnet_tpu.fleet import HealthPlane

INTERVAL = 0.05
TIMEOUT = 0.6


def main(lease_dir, rank, nprocs):
    hp = HealthPlane(rank=rank, nprocs=nprocs, lease_dir=lease_dir,
                     interval=INTERVAL, timeout=TIMEOUT)
    if rank != 0:
        for step in range(1, 4):
            hp.beat(step=step)
            time.sleep(INTERVAL)
        print(f"FLEET_BEAT {rank}", flush=True)
        return 0    # vanish silently: no stop(), the lease stays to rot

    deadline = time.monotonic() + 30.0
    hp.beat(step=0)
    while len(hp.peers()) < nprocs - 1:     # wait for every peer's lease
        if time.monotonic() > deadline:
            print("FLEET_TIMEOUT waiting for peers", flush=True)
            return 1
        time.sleep(INTERVAL)
    while time.monotonic() < deadline:
        hp.beat(step=0)
        try:
            hp.check_peers()
        except mx.resilience.WorkerLost as e:
            assert not hp.healthz()["ok"], "stale peer must turn /healthz red"
            print(f"FLEET_LOST {rank} {e.op} {e.key}", flush=True)
            return 0
        time.sleep(INTERVAL)
    print("FLEET_TIMEOUT waiting for lease expiry", flush=True)
    return 1


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3])))

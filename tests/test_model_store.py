"""Pretrained-weights cache (reference: gluon/model_zoo/model_store.py
get_model_file — sha1-checked files under MXNET_HOME/models).

No egress in CI, so the tests provision fixture archives offline and
drive the full path: get_model_file -> sha1 verification ->
load_parameters -> identical forward outputs.
"""
import hashlib
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon.model_zoo import model_store
from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1


def _sha1(path):
    h = hashlib.sha1()
    with open(path, "rb") as f:
        h.update(f.read())
    return h.hexdigest()


def _provision(tmp_path, name, net):
    """Save net's params as the hash-named legacy archive for `name` and
    point the sha1 table at the fixture (the real table entries identify
    the official Apache artifacts, which offline CI cannot fetch)."""
    root = tmp_path / "models"
    root.mkdir(exist_ok=True)
    tmp = root / "tmp.params"
    net.save_parameters(str(tmp))
    sha = _sha1(str(tmp))
    target = root / f"{name}-{sha[:8]}.params"
    os.rename(tmp, target)
    model_store._model_sha1[name] = sha
    return str(root), str(target)


def test_sha1_table_populated():
    # parity with the reference's table (model_store.py:30-64)
    assert len(model_store._model_sha1) >= 34
    assert model_store._model_sha1["resnet18_v1"].startswith("a0666292")
    assert model_store.short_hash("resnet50_v1") == "0aee57f9"


def test_get_model_file_verifies_and_loads(tmp_path, monkeypatch):
    saved = dict(model_store._model_sha1)
    try:
        src = resnet18_v1(classes=10)
        src.initialize()
        x = mx.np.array(onp.random.randn(1, 3, 32, 32).astype("float32"))
        ref_out = src(x)
        root, path = _provision(tmp_path, "resnet18_v1", src)

        got = model_store.get_model_file("resnet18_v1", root=root)
        assert got == path

        net = resnet18_v1(pretrained=True, root=root, classes=10)
        out = net(x)
        onp.testing.assert_allclose(out.asnumpy(), ref_out.asnumpy(),
                                    rtol=1e-5, atol=1e-5)
    finally:
        model_store._model_sha1.clear()
        model_store._model_sha1.update(saved)


def test_get_model_file_rejects_corrupt(tmp_path):
    saved = dict(model_store._model_sha1)
    try:
        src = resnet18_v1(classes=10)
        src.initialize()
        root, path = _provision(tmp_path, "resnet18_v1", src)
        with open(path, "ab") as f:
            f.write(b"corruption")
        with pytest.raises(MXNetError, match="checksum mismatch"):
            model_store.get_model_file("resnet18_v1", root=root)
    finally:
        model_store._model_sha1.clear()
        model_store._model_sha1.update(saved)


def test_missing_weights_error_is_actionable(tmp_path, monkeypatch):
    monkeypatch.setenv("MXNET_GLUON_REPO", "file:///nonexistent")
    with pytest.raises(MXNetError, match="provision|Provision"):
        model_store.get_model_file("vgg16", root=str(tmp_path / "empty"))


def test_purge(tmp_path):
    root = tmp_path / "models"
    root.mkdir()
    (root / "x.params").write_bytes(b"1")
    (root / "y.zip").write_bytes(b"2")
    (root / "keep.txt").write_bytes(b"3")
    model_store.purge(str(root))
    assert sorted(os.listdir(root)) == ["keep.txt"]

"""Test configuration.

Mirrors the reference's tests/python/unittest/conftest.py (seed control +
repro logging) plus the TPU-CI trick from SURVEY §4: tests run on a virtual
8-device CPU mesh (xla_force_host_platform_device_count) so sharding/
collective paths are exercised without TPU hardware.
"""
import os

# must be set before jax import. MXNET_TEST_DEVICE=tpu opts into running the
# suite on real hardware (the reference's test_operator_gpu.py pattern);
# default is the 8-virtual-device CPU mesh for determinism + sharding tests.
if os.environ.get("MXNET_TEST_DEVICE", "cpu") == "cpu":
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = \
        flags + " --xla_force_host_platform_device_count=8"

import numpy as onp  # noqa: E402
import pytest  # noqa: E402

import jax  # noqa: E402

if os.environ.get("MXNET_TEST_DEVICE", "cpu") == "cpu":
    # the axon TPU plugin pins JAX_PLATFORMS=axon in the kernel env; the
    # config update (pre-backend-init) reliably forces the CPU mesh
    jax.config.update("jax_platforms", "cpu")
# numpy-oracle tests need true-f32 matmuls (TPU MXU defaults to bf16 passes)
jax.config.update("jax_default_matmul_precision", "float32")


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: nightly-bucket test (set MXNET_TEST_SLOW=1 to "
        "run; analog of the reference's tests/nightly split)")


def pytest_collection_modifyitems(config, items):
    if os.environ.get("MXNET_TEST_SLOW", "0") == "1":
        return
    skip = pytest.mark.skip(
        reason="nightly bucket: set MXNET_TEST_SLOW=1 to run")
    for item in items:
        if "slow" in item.keywords:
            item.add_marker(skip)


@pytest.fixture(autouse=True)
def _seed_everything(request):
    seed = int(os.environ.get("MXNET_TEST_SEED", 17))
    onp.random.seed(seed)
    import mxnet_tpu as mx
    mx.random.seed(seed)
    yield


def retry(n):
    """Retry up to n times for stochastic/load-sensitive tests
    (reference: tests/python/unittest/common.py:218)."""
    import functools

    assert n > 0

    def deco(orig_test):
        @functools.wraps(orig_test)
        def wrapped(*args, **kwargs):
            for i in range(n):
                try:
                    return orig_test(*args, **kwargs)
                except AssertionError:
                    if i == n - 1:
                        raise
                    import mxnet_tpu as mx
                    mx.nd.waitall()
        return wrapped
    return deco

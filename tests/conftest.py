"""Test configuration.

Mirrors the reference's tests/python/unittest/conftest.py (seed control +
repro logging) plus the TPU-CI trick from SURVEY §4: tests run on a virtual
8-device CPU mesh (xla_force_host_platform_device_count) so sharding/
collective paths are exercised without TPU hardware.
"""
import os

# must be set before jax import
os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = \
        flags + " --xla_force_host_platform_device_count=8"

import numpy as onp  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _seed_everything(request):
    seed = int(os.environ.get("MXNET_TEST_SEED", 17))
    onp.random.seed(seed)
    import mxnet_tpu as mx
    mx.random.seed(seed)
    yield

"""mx.goodput — wall-clock goodput ledger, badput attribution and SLO
error-budget burn rates (docs/OBSERVABILITY.md "Goodput & SLO budgets").

Oracles: **conservation** — the sum of ledger buckets equals elapsed
wall clock within epsilon, with zero overlapping intervals, held
through every injected-badput chaos drill (preempt -> restart, host
loss -> restart + degraded capacity, prefetch stall -> input_stall)
and through claim compaction; **priority** — synthetic overlapping
claims resolve to the highest-priority state exactly once; **merge** —
two hand-written host snapshots combine into capacity-weighted fleet
device-second totals; the ``/goodput`` endpoint, the burn-rate
``/healthz`` 503 and the run-report plane round-trip end-to-end.

Chaos spec literals exercised here: "resilience.preempt:at=1,times=1",
"fleet.host_loss:at=1", "pipeline.prefetch_stall:at=2,times=1".
"""
import json
import random
import subprocess
import sys
import time
import urllib.error
import urllib.request

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import goodput, pipeline, telemetry, trace
from mxnet_tpu.fleet import FleetSupervisor, HealthPlane
from mxnet_tpu.parallel.mesh import MeshConfig


@pytest.fixture(autouse=True)
def _clean_goodput_state():
    goodput.disable()
    goodput.reset()
    telemetry.stop_http()
    telemetry.disable()
    telemetry.reset()
    trace.disable()
    trace.clear()
    mx.fault.clear()
    mx.fault.reset_stats()
    yield
    goodput.disable()
    goodput.reset()
    telemetry.stop_http()
    telemetry.disable()
    telemetry.reset()
    trace.disable()
    trace.clear()
    mx.fault.clear()
    mx.fault.reset_stats()
    mx.config.reset()


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}", timeout=5) as r:
            return r.status, r.headers.get("Content-Type"), \
                r.read().decode()
    except urllib.error.HTTPError as e:
        return e.code, e.headers.get("Content-Type"), e.read().decode()


def _assert_conserved(s, epsilon=0.01):
    slack = epsilon + s["late_dropped_s"]
    assert s["conservation_error_s"] <= slack, s
    assert abs(sum(s["buckets"].values()) - s["elapsed_s"]) <= slack, s


# ---------------------------------------------------------------------------
# disabled fast path
# ---------------------------------------------------------------------------

def test_disabled_hooks_are_noops():
    assert not goodput.active()
    assert goodput.begin("restart") is None
    goodput.end(None)
    goodput.note("compute", 1.0)
    goodput.set_capacity(2, 4)
    with goodput.phase("checkpoint_save"):
        pass
    assert goodput.maybe_snapshot() is None
    assert goodput.last_summary() is None
    assert goodput.bench_fields() == {}
    assert goodput.healthz()["ok"] is True


# ---------------------------------------------------------------------------
# priority / no-overlap / conservation on synthetic claims
# ---------------------------------------------------------------------------

def test_resolve_claims_priority_and_no_overlap():
    # compute spans everything; restart and input_stall overlap it (and
    # each other at [3,4)): every instant counts exactly once, for the
    # highest-priority claimant
    b = goodput.resolve_claims(
        [(0, 10, "compute"), (2, 4, "restart"), (3, 6, "input_stall")],
        0, 12)
    assert abs(sum(b.values()) - 12) < 1e-9
    assert b["restart"] == pytest.approx(2)       # [2,4): beats both
    assert b["input_stall"] == pytest.approx(2)   # [4,6): beats compute
    assert b["compute"] == pytest.approx(6)       # the rest of [0,10)
    assert b["idle"] == pytest.approx(2)          # [10,12): unclaimed


def test_resolve_claims_capacity_split_and_parked_exemption():
    # capacity drops to 0.5 at t=4: compute after the drop is half
    # badput; a parked interval is NOT split (it is 100% parked already)
    b = goodput.resolve_claims(
        [(0, 8, "compute"), (8, 10, "parked")], 0, 10,
        cap_marks=[(0, 1.0), (4, 0.5)])
    assert abs(sum(b.values()) - 10) < 1e-9
    assert b["compute"] == pytest.approx(4 + 4 * 0.5)
    assert b["degraded_capacity"] == pytest.approx(2.0)
    assert b["parked"] == pytest.approx(2.0)


def test_conservation_oracle_random_overlaps():
    rng = random.Random(17)
    states = list(goodput.PRIORITY)
    claims = [(a := rng.uniform(0, 50), a + rng.uniform(0, 10),
               rng.choice(states)) for _ in range(200)]
    b = goodput.resolve_claims(claims, 0, 60,
                               cap_marks=[(0, 1.0), (20, 0.5), (40, 1.0)])
    assert abs(sum(b.values()) - 60) < 1e-6


def test_compaction_preserves_conservation():
    led = goodput._Ledger(now=0.0)
    rng = random.Random(5)
    t = 0.0
    for _ in range(3 * goodput._CLAIM_CAP):
        d = rng.uniform(0.001, 0.01)
        t += d
        led.claim(rng.choice(goodput.PRIORITY), t - d, t,
                  now=t + goodput._SETTLE_GRACE + 1.0)
    assert len(led.claims) <= goodput._CLAIM_CAP   # compaction ran
    buckets = led.resolve(t)
    assert abs(sum(buckets.values()) - t) < 1e-6 + led.late_dropped_s


# ---------------------------------------------------------------------------
# live ledger
# ---------------------------------------------------------------------------

def test_bracket_sample_and_idle_residual():
    goodput.enable()
    with goodput.phase("checkpoint_save"):
        time.sleep(0.03)
    goodput.note("compute", 0.02)
    time.sleep(0.02)
    s = goodput.summary()
    _assert_conserved(s)
    assert s["buckets"]["checkpoint_save"] >= 0.02
    assert s["buckets"]["idle"] > 0.0
    assert s["elapsed_s"] >= 0.05


def test_open_bracket_counts_up_to_now():
    goodput.enable()
    tok = goodput.begin("restore")
    time.sleep(0.03)
    s = goodput.summary()
    _assert_conserved(s)
    assert s["buckets"]["restore"] >= 0.025
    goodput.end(tok)


# ---------------------------------------------------------------------------
# injected-badput chaos drills (the attribution acceptance oracle)
# ---------------------------------------------------------------------------

def test_preempt_drill_attributes_restart_badput(tmp_path):
    """resilience.preempt fires -> run(resume_on_preempt=True) restores
    the bundle in-process -> the downtime lands in the restart bucket
    and tops the badput ranking, conservation intact."""
    state = mx.resilience.TrainState(path=str(tmp_path / "b.bundle"))
    state.step = 3
    state.save()                      # bundle exists before the ledger
    goodput.enable()
    mx.fault.configure("resilience.preempt:at=1,times=1")
    calls = []

    def train_fn():
        calls.append(1)
        for s in (1, 2):
            if mx.resilience.preempt_requested(step=s):
                raise mx.resilience.Preempted(step=s, origin="injected")
        goodput.note("compute", 0.001)
        return "done"

    assert mx.resilience.run(train_fn, state=state,
                             resume_on_preempt=True) == "done"
    assert len(calls) == 2            # preempted once, resumed once
    s = goodput.summary()
    _assert_conserved(s)
    assert s["buckets"]["restart"] > 0.0
    assert s["badput_top"][0][0] == "restart", s["badput_top"]
    assert mx.fault.stats().get("injected.resilience.preempt") == 1


class _ElasticFakeStep:
    """Supervisor-shaped step: carries a mesh_config and rebuilds with a
    measurable (20ms) transition, so restart badput is visible without
    an 8-device mesh (the real-mesh drill runs in the goodput CI
    stage)."""

    def __init__(self, cfg):
        self.mesh_config = cfg

    def rebuild(self, cfg, sync=False):
        time.sleep(0.02)
        return _ElasticFakeStep(cfg)


def test_host_loss_drill_attributes_restart_then_degraded_capacity():
    """fleet.host_loss fires -> degrade dp2->dp1: the transition is
    restart badput, every second at half capacity splits into
    degraded_capacity until the re-expand restores the target layout."""
    goodput.enable()
    state = mx.resilience.TrainState()
    sup = FleetSupervisor(_ElasticFakeStep(MeshConfig(dp=2)), state,
                          n_hosts=2)
    mx.fault.configure("fleet.host_loss:at=1")
    assert sup.probe(1) is True       # degraded, not parked
    assert sup.current == MeshConfig(dp=1)
    time.sleep(0.05)                  # wall time at 50% capacity
    mid = goodput.summary()
    _assert_conserved(mid)
    assert mid["capacity_ratio"] == pytest.approx(0.5)
    assert mid["buckets"]["restart"] >= 0.015
    assert mid["buckets"]["degraded_capacity"] >= 0.02
    top = [kv[0] for kv in mid["badput_top"]]
    assert set(top) <= {"restart", "degraded_capacity"}, mid["badput_top"]

    sup.restore_hosts()
    sup._maybe_reexpand()             # checkpoint boundary: re-expand
    assert sup.current == MeshConfig(dp=2)
    end = goodput.summary()
    _assert_conserved(end)
    assert end["capacity_ratio"] == pytest.approx(1.0)


def test_prefetch_stall_drill_attributes_input_stall():
    """pipeline.prefetch_stall wedges the producer -> the consumer's
    measured stall flows through the input-stall histogram listener
    into the ledger and tops the badput ranking."""
    goodput.enable()
    telemetry.enable()                # histogram feeds ride observe()
    mx.fault.configure("pipeline.prefetch_stall:at=2,times=1")
    src = [onp.full((4,), i, dtype=onp.float32) for i in range(5)]
    pf = pipeline.DevicePrefetcher(iter(src), depth=2, stall_timeout=0.4)
    out = [onp.asarray(b) for b in pf]
    assert len(out) == 5
    s = goodput.summary()
    _assert_conserved(s)
    assert s["buckets"]["input_stall"] >= 0.2
    assert s["badput_top"][0][0] == "input_stall", s["badput_top"]


def test_park_bracket_opens_and_closes():
    goodput.enable()
    state = mx.resilience.TrainState()
    sup = FleetSupervisor(_ElasticFakeStep(MeshConfig(dp=2)), state,
                          n_hosts=2, min_dp=2)
    mx.fault.configure("fleet.host_loss:at=1")
    assert sup.probe(1) is False and sup.parked
    time.sleep(0.03)
    mid = goodput.summary()
    assert mid["buckets"]["parked"] >= 0.025
    _assert_conserved(mid)
    sup.restore_hosts()
    parked_at_restore = goodput.summary()["buckets"]["parked"]
    time.sleep(0.02)                  # bracket closed: parked stops
    assert goodput.summary()["buckets"]["parked"] == pytest.approx(
        parked_at_restore, abs=5e-3)


# ---------------------------------------------------------------------------
# fleet merge + snapshots
# ---------------------------------------------------------------------------

def _host_snap(rank, devices, elapsed, buckets):
    frac = buckets.get("compute", 0.0) / elapsed
    return {"rank": rank, "pid": 1, "time": time.time(),
            "summary": {"devices": devices, "elapsed_s": elapsed,
                        "buckets": buckets, "goodput_fraction": frac,
                        "conservation_error_s": 0.0,
                        "late_dropped_s": 0.0}}


def test_two_host_merge_capacity_weighting_oracle():
    snaps = {0: _host_snap(0, 4, 10.0, {"compute": 8.0, "idle": 2.0}),
             1: _host_snap(1, 2, 10.0, {"compute": 3.0, "restart": 2.0,
                                        "idle": 5.0})}
    m = goodput.merge_snapshots(snaps)
    assert m["hosts"] == 2
    # device-seconds: host0 weighs 4 devices, host1 weighs 2
    assert m["device_seconds"]["compute"] == pytest.approx(8 * 4 + 3 * 2)
    assert m["device_seconds"]["restart"] == pytest.approx(2 * 2)
    assert m["elapsed_device_seconds"] == pytest.approx(10 * 4 + 10 * 2)
    assert m["goodput_fraction"] == pytest.approx(38 / 60)
    assert m["badput_top"][0][0] == "restart"


def test_heartbeat_publishes_rate_limited_snapshot(tmp_path):
    d = str(tmp_path)
    goodput.enable()
    goodput.note("compute", 0.01)
    hp = HealthPlane(rank=0, nprocs=1, lease_dir=d)
    assert hp.beat(step=1)
    snaps = goodput.read_snapshots(d)
    assert 0 in snaps and snaps[0]["summary"]["buckets"]["compute"] > 0
    first = snaps[0]["time"]
    assert hp.beat(step=2)            # inside goodput.snapshot_interval
    assert goodput.read_snapshots(d)[0]["time"] == first  # rate-limited


# ---------------------------------------------------------------------------
# endpoint, healthz burn, run report
# ---------------------------------------------------------------------------

def test_goodput_endpoint_content_type():
    goodput.enable()
    telemetry.enable()
    goodput.note("compute", 0.01)
    srv = telemetry.serve_http(0)
    port = srv.server_address[1]
    try:
        status, ctype, body = _get(port, "/goodput")
    finally:
        telemetry.stop_http()
    assert status == 200
    assert ctype == "application/json"
    d = json.loads(body)
    assert d["enabled"] is True
    assert d["local"]["buckets"]["compute"] > 0
    _assert_conserved(d["local"])


def test_burn_rate_breach_flips_healthz_503():
    goodput.enable()
    telemetry.enable()
    goodput.note("compute", 0.001)
    mx.config.set("goodput.target", 0.95)   # ~all idle: burn >> 2
    time.sleep(0.05)
    burn = goodput.burn_rates()
    assert burn and all(b > 2.0 for b in burn.values())
    assert goodput.healthz()["ok"] is False
    srv = telemetry.serve_http(0)
    port = srv.server_address[1]
    try:
        status, _ctype, body = _get(port, "/healthz")
    finally:
        telemetry.stop_http()
    assert status == 503
    assert json.loads(body)["checks"]["goodput"]["ok"] is False
    # clearing the objective clears the page
    mx.config.set("goodput.target", 0.0)
    assert goodput.healthz()["ok"] is True


def test_training_telemetry_report_gains_goodput_plane(tmp_path):
    path = str(tmp_path / "run.jsonl")
    goodput.enable()
    with telemetry.TrainingTelemetry(path=path, interval=2,
                                     run_id="gp") as rep:
        goodput.note("compute", 0.01)
        for _ in range(2):
            rep.step(loss=0.1)
    report = telemetry.TrainingTelemetry.read(path)[-1]
    assert report["type"] == "run_report"
    plane = report["goodput"]
    assert plane["buckets"]["compute"] > 0
    _assert_conserved(plane)


# ---------------------------------------------------------------------------
# tools/goodput.py
# ---------------------------------------------------------------------------

def test_tools_goodput_cli_summary_and_validate(tmp_path):
    d = str(tmp_path)
    goodput.enable()
    with goodput.phase("restart"):
        time.sleep(0.02)
    goodput.note("compute", 0.01)
    goodput.write_snapshot(d, 0)
    out = subprocess.run(
        [sys.executable, "tools/goodput.py", "summary", d],
        capture_output=True, text=True)
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)["hosts"] == 1
    ok = subprocess.run(
        [sys.executable, "tools/goodput.py", "validate", d,
         "--expect-badput", "restart"],
        capture_output=True, text=True)
    assert ok.returncode == 0, ok.stderr
    assert json.loads(ok.stdout)["ok"] is True
    bad = subprocess.run(
        [sys.executable, "tools/goodput.py", "validate", d,
         "--expect-badput", "input_stall"],
        capture_output=True, text=True)
    assert bad.returncode == 1
    assert json.loads(bad.stdout)["ok"] is False

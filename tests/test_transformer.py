"""Transformer / attention / BERT tests.

Mirrors the reference test strategy (SURVEY §4): numpy-oracle checks for the
attention op (reference op: src/operator/contrib/transformer.cc
interleaved_matmul_selfatt), eager-vs-hybrid equivalence, grad flow, and a
tiny convergence smoke test.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu import numpy as np


def _np_attention(q, k, v, heads, mask=None, causal=False):
    b, sq, hd = q.shape
    sk = k.shape[1]
    d = hd // heads
    qh = q.reshape(b, sq, heads, d).transpose(0, 2, 1, 3)
    kh = k.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
    vh = v.reshape(b, sk, heads, d).transpose(0, 2, 1, 3)
    s = onp.einsum("bhqd,bhkd->bhqk", qh, kh) / onp.sqrt(d)
    if causal:
        cm = onp.tril(onp.ones((sq, sk), bool))
        s = onp.where(cm, s, -1e30)
    if mask is not None:
        s = onp.where(mask, s, -1e30)
    e = onp.exp(s - s.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    out = onp.einsum("bhqk,bhkd->bhqd", p, vh)
    return out.transpose(0, 2, 1, 3).reshape(b, sq, hd)


def test_multi_head_attention_oracle():
    from mxnet_tpu.ops.attention import multi_head_attention
    onp.random.seed(0)
    q = onp.random.randn(2, 8, 32).astype("float32")
    k = onp.random.randn(2, 12, 32).astype("float32")
    v = onp.random.randn(2, 12, 32).astype("float32")
    out = multi_head_attention(np.array(q), np.array(k), np.array(v), 4)
    ref = _np_attention(q, k, v, 4)
    onp.testing.assert_allclose(out.asnumpy(), ref, atol=1e-5)


def test_multi_head_attention_causal_and_mask():
    from mxnet_tpu.ops.attention import multi_head_attention
    onp.random.seed(1)
    q = onp.random.randn(2, 8, 32).astype("float32")
    out = multi_head_attention(np.array(q), np.array(q), np.array(q), 4,
                               causal=True)
    ref = _np_attention(q, q, q, 4, causal=True)
    onp.testing.assert_allclose(out.asnumpy(), ref, atol=1e-5)

    mask = onp.zeros((2, 1, 1, 8), bool)
    mask[0, ..., :5] = True
    mask[1, ..., :8] = True
    out = multi_head_attention(np.array(q), np.array(q), np.array(q), 4,
                               mask=np.array(mask))
    ref = _np_attention(q, q, q, 4, mask=mask)
    onp.testing.assert_allclose(out.asnumpy(), ref, atol=1e-5)


def test_flash_attention_interpret_matches_reference():
    """Pallas kernel (interpret mode on CPU) vs composition, fwd + grads."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention

    onp.random.seed(2)
    b, h, s, d = 1, 2, 128, 32
    q = jnp.asarray(onp.random.randn(b, h, s, d).astype("float32"))
    k = jnp.asarray(onp.random.randn(b, h, s, d).astype("float32"))
    v = jnp.asarray(onp.random.randn(b, h, s, d).astype("float32"))

    def ref(q, k, v, causal):
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5)
        if causal:
            m = jnp.tril(jnp.ones((s, s), bool))
            s_ = jnp.where(m, s_, -1e30)
        p = jax.nn.softmax(s_, axis=-1)
        return jnp.einsum("bhqk,bhkd->bhqd", p, v)

    for causal in (False, True):
        out = flash_attention(q, k, v, causal=causal, interpret=True,
                              block_q=64, block_k=64)
        r = ref(q, k, v, causal)
        onp.testing.assert_allclose(onp.asarray(out), onp.asarray(r),
                                    atol=2e-5)
        gq, gk, gv = jax.grad(
            lambda *a: flash_attention(*a, causal=causal, interpret=True,
                                       block_q=64, block_k=64).sum(),
            argnums=(0, 1, 2))(q, k, v)
        rq, rk, rv = jax.grad(lambda *a: ref(*a, causal).sum(),
                              argnums=(0, 1, 2))(q, k, v)
        onp.testing.assert_allclose(onp.asarray(gq), onp.asarray(rq),
                                    atol=2e-4)
        onp.testing.assert_allclose(onp.asarray(gk), onp.asarray(rk),
                                    atol=2e-4)
        onp.testing.assert_allclose(onp.asarray(gv), onp.asarray(rv),
                                    atol=2e-4)


def test_encoder_eager_vs_hybrid():
    from mxnet_tpu.gluon.nn.transformer import (TransformerEncoder,
                                                valid_length_mask)
    enc = TransformerEncoder(2, 32, 64, 4)
    enc.initialize()
    x = np.array(onp.random.randn(2, 10, 32).astype("float32"))
    mask = valid_length_mask(np.array(onp.array([10, 6])), 10)
    y = enc(x, mask=mask)
    enc.hybridize()
    y2 = enc(x, mask=mask)
    onp.testing.assert_allclose(y.asnumpy(), y2.asnumpy(), atol=1e-5)


def test_encoder_masked_positions_do_not_affect_valid():
    """Changing tokens beyond valid_length must not change valid outputs."""
    from mxnet_tpu.gluon.nn.transformer import (TransformerEncoder,
                                                valid_length_mask)
    enc = TransformerEncoder(1, 32, 64, 4)
    enc.initialize()
    x = onp.random.randn(1, 10, 32).astype("float32")
    x2 = x.copy()
    x2[0, 6:] = 123.0
    mask = valid_length_mask(np.array(onp.array([6])), 10)
    y1 = enc(np.array(x), mask=mask).asnumpy()
    y2 = enc(np.array(x2), mask=mask).asnumpy()
    onp.testing.assert_allclose(y1[0, :6], y2[0, :6], atol=1e-5)


def test_bert_shapes_and_grad():
    from mxnet_tpu.gluon.model_zoo.bert import BERTForPretraining
    from mxnet_tpu import numpy_extension as npx
    net = BERTForPretraining(vocab_size=100, units=32, hidden_size=64,
                             num_layers=2, num_heads=4, max_length=32,
                             dropout=0.0, embed_dropout=0.0)
    net.initialize()
    ids = np.array(onp.random.randint(0, 100, (2, 12)).astype("int32"))
    vl = np.array(onp.array([12, 8]))
    mlm, nsp = net(ids, None, vl)
    assert mlm.shape == (2, 12, 100)
    assert nsp.shape == (2, 2)
    net.hybridize()
    mlm2, _ = net(ids, None, vl)
    onp.testing.assert_allclose(mlm.asnumpy(), mlm2.asnumpy(), atol=1e-4)

    with autograd.record():
        mlm3, nsp3 = net(ids, None, vl)
        lbl = np.array(onp.random.randint(0, 100, (2, 12)).astype("int32"))
        loss = -(npx.pick(npx.log_softmax(mlm3, axis=-1), lbl)).mean()
    loss.backward()
    g = net.backbone.word_embed.weight.grad()
    assert float(abs(g.asnumpy()).sum()) > 0


@pytest.mark.slow
def test_bert_tiny_convergence():
    """A tiny MLM task must overfit in a few steps (reference pattern:
    tests/python/train convergence smoke tests)."""
    from mxnet_tpu.gluon.model_zoo.bert import BERTForPretraining
    from mxnet_tpu.gluon import Trainer
    from mxnet_tpu import numpy_extension as npx

    mx.random.seed(0)
    onp.random.seed(0)
    net = BERTForPretraining(vocab_size=50, units=32, hidden_size=64,
                             num_layers=1, num_heads=4, max_length=16,
                             dropout=0.0, embed_dropout=0.0)
    net.initialize()
    net.hybridize()
    trainer = Trainer(net.collect_params(), "adam",
                      {"learning_rate": 1e-2})
    ids = np.array(onp.random.randint(0, 50, (4, 8)).astype("int32"))
    first = None
    for i in range(30):
        with autograd.record():
            mlm, _ = net(ids)
            loss = -(npx.pick(npx.log_softmax(mlm, axis=-1), ids)).mean()
        loss.backward()
        trainer.step(4)
        lv = float(loss.asnumpy())
        if first is None:
            first = lv
    assert lv < first * 0.5, f"no convergence: {first} -> {lv}"


@pytest.mark.parametrize("sq,sk,causal", [
    (8, 16, True), (129, 257, False),
    pytest.param(300, 300, False, marks=pytest.mark.slow),
    pytest.param(100, 36, False, marks=pytest.mark.slow)])
def test_flash_attention_ragged_shapes(sq, sk, causal):
    """Non-block-multiple seq lengths and sq != sk causal (regressions:
    clamped-pl.ds misalignment; bwd mask alignment)."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.flash_attention import flash_attention

    onp.random.seed(3)
    d = 32
    q = jnp.asarray(onp.random.randn(1, 2, sq, d).astype("float32"))
    k = jnp.asarray(onp.random.randn(1, 2, sk, d).astype("float32"))
    v = jnp.asarray(onp.random.randn(1, 2, sk, d).astype("float32"))

    def ref(q, k, v):
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5)
        if causal:
            m = jnp.tril(jnp.ones((sq, sk), bool))
            s_ = jnp.where(m, s_, -1e30)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s_, -1), v)

    out = flash_attention(q, k, v, causal=causal, interpret=True,
                          block_q=64, block_k=64)
    onp.testing.assert_allclose(onp.asarray(out), onp.asarray(ref(q, k, v)),
                                atol=1e-4)
    g = jax.grad(lambda *a: flash_attention(
        *a, causal=causal, interpret=True, block_q=64, block_k=64).sum(),
        argnums=(0, 1, 2))(q, k, v)
    r = jax.grad(lambda *a: ref(*a).sum(), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, r):
        onp.testing.assert_allclose(onp.asarray(a), onp.asarray(b), atol=1e-3)


def test_mha_routes_to_ring_attention_under_sp_scope():
    """MultiHeadAttention under an sp-sharded activation scope must produce
    the same values as the unsharded composition (ring attention path)."""
    import jax
    import numpy as onp
    from jax.sharding import PartitionSpec as P

    import mxnet_tpu as mx
    from mxnet_tpu import parallel
    from mxnet_tpu.gluon.nn.transformer import MultiHeadAttention

    mesh = parallel.make_mesh({"sp": 4})
    mha = MultiHeadAttention(units=32, num_heads=4, causal=True)
    mha.initialize()
    x = mx.np.array(onp.random.RandomState(0).randn(2, 16, 32)
                    .astype("float32"))
    ref = mha(x).asnumpy()  # no scope: XLA composition
    with parallel.activation_sharding(mesh, residual=P(None, "sp", None)):
        out = mha(x).asnumpy()
    onp.testing.assert_allclose(out, ref, rtol=2e-5, atol=2e-5)

"""GPT dp x tp pretraining example smoke (ShardedTrainStep end-to-end
through megatron specs; reference analog: distributed_training example)."""
import os
import subprocess
import sys

import pytest


@pytest.mark.slow
def test_train_gpt_dp_tp():
    script = os.path.join(os.path.dirname(__file__), "..", "example",
                          "train_gpt.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, script, "--cpu-devices", "8", "--dp", "4",
         "--tp", "2", "--steps", "120"],
        capture_output=True, text=True, timeout=1200, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "checkpoint save/load ok" in r.stdout


@pytest.mark.slow
def test_train_gpt_long_context_mode():
    """--long-context: the chunked-vocab-xent path ships and learns."""
    script = os.path.join(os.path.dirname(__file__), "..", "example",
                          "train_gpt.py")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, script, "--long-context", "--cpu-devices", "1",
         "--steps", "150", "--seq-len", "48", "--batch", "8",
         "--vocab-chunk", "32"],
        capture_output=True, text=True, timeout=1200, env=env)
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "logits never materialized" in r.stdout

"""mx.test_utils helper tail (reference test_utils.py: chi_square_check
:2108, verify_generator, check_speed, random helpers)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import test_utils as tu


def test_chi_square_discrete():
    p, obs, exp = tu.chi_square_check(
        lambda n: onp.random.RandomState(0).randint(0, 4, n),
        buckets=[0, 1, 2, 3], probs=[0.25] * 4, nsamples=100000)
    assert p > 0.05
    assert obs.sum() == 100000 and exp.sum() == pytest.approx(100000)
    pbad, _, _ = tu.chi_square_check(
        lambda n: onp.random.RandomState(0).randint(0, 3, n),
        buckets=[0, 1, 2, 3], probs=[0.25] * 4, nsamples=100000)
    assert pbad < 1e-6


def test_verify_generator_continuous():
    mx.random.seed(0)
    buckets, probs = tu.gen_buckets_probs_with_ppf(lambda q: q, 5)
    assert probs == [0.2] * 5 and buckets[0] == (0.0, 0.2)
    tu.verify_generator(lambda n: mx.np.random.uniform(0, 1, size=(n,)),
                        buckets, probs, nsamples=50000, nrepeat=3)
    with pytest.raises(AssertionError, match="chi-square"):
        tu.verify_generator(
            lambda n: mx.np.random.uniform(0, 0.5, size=(n,)),
            buckets, probs, nsamples=20000, nrepeat=2)


def test_small_helpers():
    assert tu.check_speed(lambda: mx.np.ones((8, 8)).sum(), n=3) > 0
    a = mx.np.ones(3)
    assert tu.same_array(a, a) and not tu.same_array(a, mx.np.ones(3))
    s2 = tu.rand_shape_2d(5, 5)
    assert len(s2) == 2 and all(1 <= d <= 5 for d in s2)
    assert len(tu.rand_shape_3d()) == 3
    x, y = tu.rand_coord_2d(0, 10, 20, 30)
    assert 0 <= x < 10 and 20 <= y < 30
    arrs = tu.random_arrays((2, 3), (4,))
    assert arrs[0].shape == (2, 3) and arrs[1].shape == (4,)
    assert tu.random_arrays((2, 2)).shape == (2, 2)
    assert sorted(tu.random_sample(range(10), 10)) == list(range(10))
    tu.assert_allclose([1.0, 2.0], [1.0, 2.0])
    tu.assert_exception(lambda: 1 / 0, ZeroDivisionError)
    with pytest.raises(AssertionError):
        tu.assert_exception(lambda: None, ValueError)


def test_chi_square_gap_buckets_and_int_shapes():
    from mxnet_tpu.base import MXNetError
    # gap samples (1 <= x < 2) must be excluded, not mis-tallied
    # probs are each bucket's TRUE probability mass: 1/3 each for
    # uniform(0,3); the gap third of the samples must be dropped
    p, obs, exp = tu.chi_square_check(
        lambda n: onp.random.RandomState(0).uniform(0, 3, n),
        buckets=[(0, 1), (2, 3)], probs=[1 / 3, 1 / 3], nsamples=30000)
    assert obs.sum() == pytest.approx(20000, rel=0.05)
    assert p > 0.01
    assert tu.random_arrays(5).shape == (5,)
    assert tu.random_arrays(()).shape == ()
    with pytest.raises(MXNetError):
        tu.random_arrays("bad")

"""npx.image operator namespace (reference: src/operator/image/ ops
behind gluon.data.vision.transforms)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import npx
from mxnet_tpu.gluon.data.vision import transforms as T


def _img(h=32, w=40, c=3, seed=0):
    return onp.random.RandomState(seed).randint(
        0, 255, (h, w, c)).astype("uint8")


def test_to_tensor_and_normalize():
    x = _img()
    t = npx.image.to_tensor(x)
    assert t.shape == (3, 32, 40) and str(t.dtype) == "float32"
    assert 0.0 <= float(t.asnumpy().min()) and float(t.asnumpy().max()) <= 1.0
    n = npx.image.normalize(t, mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2))
    onp.testing.assert_allclose(n.asnumpy(),
                                (t.asnumpy() - 0.5) / 0.2, rtol=1e-5)
    # batch NHWC -> NCHW
    tb = npx.image.to_tensor(onp.stack([x, x]))
    assert tb.shape == (2, 3, 32, 40)


def test_resize_modes():
    x = _img()
    assert npx.image.resize(x, (20, 16)).shape == (16, 20, 3)
    assert npx.image.resize(x, 16).shape == (16, 16, 3)
    kept = npx.image.resize(x, 16, keep_ratio=True)
    assert kept.shape == (16, 20, 3)  # short edge 32 -> 16, 40 -> 20
    assert str(kept.dtype) == "uint8"


def test_crop_ops():
    x = _img()
    c = npx.image.crop(x, 4, 2, 10, 8)
    onp.testing.assert_array_equal(c.asnumpy(), x[2:10, 4:14])
    cc = npx.image.random_crop(x, (0.5, 0.5), (0.5, 0.5),
                               width=16, height=16)
    onp.testing.assert_allclose(cc.asnumpy(), x[8:24, 12:28], atol=1)
    rrc = npx.image.random_resized_crop(x, width=16, height=16)
    assert rrc.shape == (16, 16, 3)
    # upsample when source smaller than target
    up = npx.image.random_crop(x, (0.5, 0.5), (0.5, 0.5),
                               width=64, height=64)
    assert up.shape == (64, 64, 3)


def test_flips():
    x = _img()
    onp.testing.assert_array_equal(
        npx.image.flip_left_right(x).asnumpy(), x[:, ::-1])
    onp.testing.assert_array_equal(
        npx.image.flip_top_bottom(x).asnumpy(), x[::-1])
    flipped = npx.image.random_flip_left_right(onp.stack([x] * 64))
    arr = flipped.asnumpy()
    n_flipped = sum(bool((arr[i] == x[:, ::-1]).all()) for i in range(64))
    assert 5 < n_flipped < 59  # ~Binomial(64, .5)


def test_color_ops_bounds_and_identity():
    x = _img()
    for fn in [lambda a: npx.image.random_brightness(a, 1.0, 1.0),
               lambda a: npx.image.random_contrast(a, 1.0, 1.0),
               lambda a: npx.image.random_saturation(a, 1.0, 1.0),
               lambda a: npx.image.random_hue(a, 1.0, 1.0)]:
        out = fn(x).asnumpy()
        onp.testing.assert_allclose(out, x, atol=1.01)  # identity factor
    j = npx.image.random_color_jitter(x, 0.4, 0.4, 0.4, 0.2).asnumpy()
    assert j.dtype == onp.uint8 and j.shape == x.shape
    lit = npx.image.adjust_lighting(x, (0.1, 0.1, 0.1))
    assert lit.shape == x.shape
    assert npx.image.random_lighting(x, 0.05).shape == x.shape


def test_transforms_compose_through_npx_image():
    x = mx.np.array(_img(50, 60))
    aug = T.Compose([
        T.Resize(40), T.RandomResizedCrop(32), T.RandomFlipLeftRight(),
        T.RandomColorJitter(0.2, 0.2, 0.2, 0.1), T.ToTensor(),
        T.Normalize((0.485, 0.456, 0.406), (0.229, 0.224, 0.225))])
    out = aug(x)
    assert out.shape == (3, 32, 32)
    assert onp.isfinite(out.asnumpy()).all()
    # batched input flows through the same chain
    xb = mx.np.array(onp.stack([_img(40, 40), _img(40, 40, seed=1)]))
    outb = aug(xb)
    assert outb.shape == (2, 3, 32, 32)


def test_random_crop_transform_with_pad():
    x = mx.np.array(_img(32, 32))
    out = T.RandomCrop(32, pad=4).forward(x)
    assert out.shape == (32, 32, 3)

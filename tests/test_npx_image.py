"""npx.image operator namespace (reference: src/operator/image/ ops
behind gluon.data.vision.transforms)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import npx
from mxnet_tpu.gluon.data.vision import transforms as T


def _img(h=32, w=40, c=3, seed=0):
    return onp.random.RandomState(seed).randint(
        0, 255, (h, w, c)).astype("uint8")


def test_to_tensor_and_normalize():
    x = _img()
    t = npx.image.to_tensor(x)
    assert t.shape == (3, 32, 40) and str(t.dtype) == "float32"
    assert 0.0 <= float(t.asnumpy().min()) and float(t.asnumpy().max()) <= 1.0
    n = npx.image.normalize(t, mean=(0.5, 0.5, 0.5), std=(0.2, 0.2, 0.2))
    onp.testing.assert_allclose(n.asnumpy(),
                                (t.asnumpy() - 0.5) / 0.2, rtol=1e-5)
    # batch NHWC -> NCHW
    tb = npx.image.to_tensor(onp.stack([x, x]))
    assert tb.shape == (2, 3, 32, 40)


def test_resize_modes():
    x = _img()
    assert npx.image.resize(x, (20, 16)).shape == (16, 20, 3)
    assert npx.image.resize(x, 16).shape == (16, 16, 3)
    kept = npx.image.resize(x, 16, keep_ratio=True)
    assert kept.shape == (16, 20, 3)  # short edge 32 -> 16, 40 -> 20
    assert str(kept.dtype) == "uint8"


def test_crop_ops():
    x = _img()
    c = npx.image.crop(x, 4, 2, 10, 8)
    onp.testing.assert_array_equal(c.asnumpy(), x[2:10, 4:14])
    cc = npx.image.random_crop(x, (0.5, 0.5), (0.5, 0.5),
                               width=16, height=16)
    onp.testing.assert_allclose(cc.asnumpy(), x[8:24, 12:28], atol=1)
    rrc = npx.image.random_resized_crop(x, width=16, height=16)
    assert rrc.shape == (16, 16, 3)
    # upsample when source smaller than target
    up = npx.image.random_crop(x, (0.5, 0.5), (0.5, 0.5),
                               width=64, height=64)
    assert up.shape == (64, 64, 3)


def test_flips():
    x = _img()
    onp.testing.assert_array_equal(
        npx.image.flip_left_right(x).asnumpy(), x[:, ::-1])
    onp.testing.assert_array_equal(
        npx.image.flip_top_bottom(x).asnumpy(), x[::-1])
    flipped = npx.image.random_flip_left_right(onp.stack([x] * 64))
    arr = flipped.asnumpy()
    n_flipped = sum(bool((arr[i] == x[:, ::-1]).all()) for i in range(64))
    assert 5 < n_flipped < 59  # ~Binomial(64, .5)


def test_color_ops_bounds_and_identity():
    x = _img()
    for fn in [lambda a: npx.image.random_brightness(a, 1.0, 1.0),
               lambda a: npx.image.random_contrast(a, 1.0, 1.0),
               lambda a: npx.image.random_saturation(a, 1.0, 1.0),
               lambda a: npx.image.random_hue(a, 1.0, 1.0)]:
        out = fn(x).asnumpy()
        onp.testing.assert_allclose(out, x, atol=1.01)  # identity factor
    j = npx.image.random_color_jitter(x, 0.4, 0.4, 0.4, 0.2).asnumpy()
    assert j.dtype == onp.uint8 and j.shape == x.shape
    lit = npx.image.adjust_lighting(x, (0.1, 0.1, 0.1))
    assert lit.shape == x.shape
    assert npx.image.random_lighting(x, 0.05).shape == x.shape


def test_transforms_compose_through_npx_image():
    x = mx.np.array(_img(50, 60))
    aug = T.Compose([
        T.Resize(40), T.RandomResizedCrop(32), T.RandomFlipLeftRight(),
        T.RandomColorJitter(0.2, 0.2, 0.2, 0.1), T.ToTensor(),
        T.Normalize((0.485, 0.456, 0.406), (0.229, 0.224, 0.225))])
    out = aug(x)
    assert out.shape == (3, 32, 32)
    assert onp.isfinite(out.asnumpy()).all()
    # batched input flows through the same chain
    xb = mx.np.array(onp.stack([_img(40, 40), _img(40, 40, seed=1)]))
    outb = aug(xb)
    assert outb.shape == (2, 3, 32, 32)


def test_random_crop_transform_with_pad():
    x = mx.np.array(_img(32, 32))
    out = T.RandomCrop(32, pad=4).forward(x)
    assert out.shape == (32, 32, 3)


def test_image_list_transform_tail():
    """CropResize / RandomGray / RandomApply family / HybridCompose
    (reference: transforms/__init__.py:81-196, transforms/image.py:260,664)."""
    x = mx.np.array(_img(16, 16))
    out = T.CropResize(2, 2, 8, 8, size=(4, 4))(x)
    assert out.shape == (4, 4, 3)
    outb = T.CropResize(2, 2, 8, 8)(mx.np.stack([x, x]))
    assert outb.shape == (2, 8, 8, 3)

    g = T.RandomGray(p=1.0)(x).asnumpy()
    lum = (x.asnumpy() * onp.array([0.2989, 0.587, 0.114])).sum(-1)
    assert onp.allclose(g[..., 0], lum, atol=1e-5)
    assert onp.allclose(g[..., 0], g[..., 2])  # replicated channels
    same = T.RandomGray(p=0.0)(x).asnumpy()
    assert onp.allclose(same, x.asnumpy(), atol=1e-6)

    ra = T.RandomApply(T.Compose([T.Cast("float32")]), p=1.0)
    assert ra(x).shape == x.shape
    hra = T.HybridRandomApply(T.Cast("float32"), p=0.0)
    assert onp.allclose(hra(x).asnumpy(), x.asnumpy(), atol=1e-6)
    hc = T.HybridCompose([T.ToTensor(), T.Normalize(0.5, 0.25)])
    assert hc(x).shape == (3, 16, 16)


def test_rotate_transforms():
    """imrotate grid sampling (reference image.py:618): 90deg == rot90,
    zero angle == identity, zoom flags scale; RandomRotation draws."""
    import pytest

    from mxnet_tpu.base import MXNetError

    a = onp.zeros((1, 5, 5), "float32")
    a[0, 0, :] = [1, 2, 3, 4, 5]
    rot = T.Rotate(90.0)(mx.np.array(a)).asnumpy()[0]
    assert onp.allclose(rot, onp.rot90(a[0], 1), atol=1e-4)
    ident = T.Rotate(0.0)(mx.np.array(a)).asnumpy()[0]
    assert onp.allclose(ident, a[0], atol=1e-5)

    # batch with per-image angles
    from mxnet_tpu.image import imrotate
    batch = mx.np.array(onp.stack([a, a]).reshape(2, 1, 5, 5))
    out = imrotate(batch, mx.np.array([0.0, 90.0])).asnumpy()
    assert onp.allclose(out[0], a, atol=1e-5)
    assert onp.allclose(out[1, 0], onp.rot90(a[0], 1), atol=1e-4)

    with pytest.raises(MXNetError):
        imrotate(mx.np.array(a), 10.0, zoom_in=True, zoom_out=True)
    with pytest.raises(MXNetError):  # uint8 rejected
        imrotate(mx.np.array(a.astype("uint8")), 10.0)

    rr = T.RandomRotation((-30, 30), rotate_with_proba=1.0)
    assert rr(mx.np.array(a)).shape == (1, 5, 5)
    skip = T.RandomRotation((-30, 30), rotate_with_proba=0.0)
    assert onp.allclose(skip(mx.np.array(a)).asnumpy(), a, atol=1e-6)
    with pytest.raises(ValueError):
        T.RandomRotation((30, -30))


def test_rotate_zoom_scaling():
    """zoom_in at 45deg crops to the inscribed region -> NO padding;
    zoom_out keeps the whole source visible -> rotated diamond with
    corner padding (reference image.py:693-711 semantics)."""
    n = 33  # odd so the center pixel is exact
    img = onp.ones((1, n, n), "float32")
    mid = n // 2
    # zoom_in: every output pixel samples inside the source
    zi = T.Rotate(45.0, zoom_in=True)(mx.np.array(img)).asnumpy()[0]
    assert zi.min() > 0.99, "zoom_in must show no padding"
    # plain 45deg rotation pads the corners with zeros
    plain = T.Rotate(45.0)(mx.np.array(img)).asnumpy()[0]
    assert plain[0, 0] < 0.01 and plain[0, -1] < 0.01
    # zoom_out: diamond touches the edge midpoints, corners are padding
    zo = T.Rotate(45.0, zoom_out=True)(mx.np.array(img)).asnumpy()[0]
    assert zo[0, 0] < 0.01 and (zo[mid] > 0.5).all()
    # zoom_in at 45deg shrinks the visible span by sqrt(2): a ramp's
    # outer values never reach the output
    ramp = onp.tile(onp.linspace(0, 1, n, dtype="float32"), (n, 1))[None]
    zi45 = T.Rotate(45.0, zoom_in=True)(mx.np.array(ramp)).asnumpy()[0]
    vals = zi45[mid]
    assert vals.min() > 0.1 and vals.max() < 0.95, \
        "zoom_in should crop away the ramp's outer ends"


def test_hybrid_compose_rejects_host_random_blocks():
    import pytest
    with pytest.raises(ValueError, match="HybridBlocks"):
        T.HybridCompose([T.RandomApply(T.Compose([T.Cast()]), p=0.5)])


def test_image_list_dataset_flat_multilabel(tmp_path):
    from PIL import Image

    from mxnet_tpu.gluon.data.vision import ImageListDataset
    arr = onp.zeros((4, 4, 3), "uint8")
    Image.fromarray(arr).save(tmp_path / "z.png")
    ds = ImageListDataset(root=str(tmp_path),
                          imglist=[[1.0, 2.0, "z.png"]])
    _, lab = ds[0]
    assert tuple(onp.asarray(lab)) == (1.0, 2.0)
    ds2 = ImageListDataset(root=str(tmp_path),
                           imglist=[[[3.0, 4.0], "z.png"]])
    assert tuple(onp.asarray(ds2[0][1])) == (3.0, 4.0)

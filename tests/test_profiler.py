"""Profiler: per-op attribution from the dispatcher + CachedOp spans +
chrome-trace dump (reference: tests/python/unittest/test_profiler.py over
src/engine/threaded_engine.h:356 engine-integrated ProfileOperator)."""
import json
import os

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import profiler
from mxnet_tpu.gluon import nn


def _reset():
    profiler._events.clear()
    profiler.set_state("stop")


def test_ops_recorded_when_running(tmp_path):
    _reset()
    profiler.set_state("run")
    a = mx.np.ones((8, 8))
    _ = mx.np.matmul(a, a)
    _ = a + a
    profiler.set_state("stop")
    names = [e["name"] for e in profiler._events]
    assert "matmul" in names, names
    stats = profiler.dumps()
    assert "matmul" in stats
    f = tmp_path / "trace.json"
    profiler.set_config(filename=str(f))
    profiler.dump()
    with open(f) as fh:
        trace = json.load(fh)
    assert any(ev["name"] == "matmul" for ev in trace["traceEvents"])


def test_nothing_recorded_when_stopped():
    _reset()
    a = mx.np.ones((4, 4))
    _ = mx.np.matmul(a, a)
    assert not profiler._events


def test_cachedop_span_recorded():
    _reset()
    net = nn.Dense(4)
    net.initialize()
    net.hybridize()
    x = mx.np.ones((2, 8))
    net(x)  # build cache before profiling
    profiler.set_state("run")
    net(x)
    profiler.set_state("stop")
    names = [e["name"] for e in profiler._events]
    assert any(n.startswith("CachedOp:") for n in names), names


def test_profile_imperative_flag_gates_op_spans():
    _reset()
    profiler.set_config(profile_imperative=False)
    try:
        profiler.set_state("run")
        a = mx.np.ones((4, 4))
        _ = mx.np.matmul(a, a)
        profiler.set_state("stop")
        assert not any(e["name"] == "matmul" for e in profiler._events)
    finally:
        profiler.set_config(profile_imperative=True)


def test_opperf_harness_runs():
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "benchmark"))
    import opperf
    rows = opperf.run(ops={"add", "matmul", "softmax"}, warmup=1, iters=3,
                      shape=(16, 16))
    assert len(rows) == 3
    for r in rows:
        assert "error" not in r, r
        assert r["e2e_us"] >= 0 and r["dispatch_us"] >= 0

"""Profiler: per-op attribution from the dispatcher + CachedOp spans +
chrome-trace dump (reference: tests/python/unittest/test_profiler.py over
src/engine/threaded_engine.h:356 engine-integrated ProfileOperator)."""
import json
import os

import numpy as onp

import mxnet_tpu as mx
from mxnet_tpu import profiler
from mxnet_tpu.gluon import nn


def _reset():
    profiler._events.clear()
    profiler.set_state("stop")


def test_ops_recorded_when_running(tmp_path):
    _reset()
    profiler.set_state("run")
    a = mx.np.ones((8, 8))
    _ = mx.np.matmul(a, a)
    _ = a + a
    profiler.set_state("stop")
    names = [e["name"] for e in profiler._events]
    assert "matmul" in names, names
    stats = profiler.dumps()
    assert "matmul" in stats
    f = tmp_path / "trace.json"
    profiler.set_config(filename=str(f))
    profiler.dump()
    with open(f) as fh:
        trace = json.load(fh)
    assert any(ev["name"] == "matmul" for ev in trace["traceEvents"])


def test_nothing_recorded_when_stopped():
    _reset()
    a = mx.np.ones((4, 4))
    _ = mx.np.matmul(a, a)
    assert not profiler._events


def test_cachedop_span_recorded():
    _reset()
    net = nn.Dense(4)
    net.initialize()
    net.hybridize()
    x = mx.np.ones((2, 8))
    net(x)  # build cache before profiling
    profiler.set_state("run")
    net(x)
    profiler.set_state("stop")
    names = [e["name"] for e in profiler._events]
    assert any(n.startswith("CachedOp:") for n in names), names


def test_profile_imperative_flag_gates_op_spans():
    _reset()
    profiler.set_config(profile_imperative=False)
    try:
        profiler.set_state("run")
        a = mx.np.ones((4, 4))
        _ = mx.np.matmul(a, a)
        profiler.set_state("stop")
        assert not any(e["name"] == "matmul" for e in profiler._events)
    finally:
        profiler.set_config(profile_imperative=True)


def test_opperf_harness_runs():
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), os.pardir,
                                    "benchmark"))
    import opperf
    rows = opperf.run(ops={"add", "matmul", "softmax"}, warmup=1, iters=3,
                      shape=(16, 16))
    assert len(rows) == 3
    for r in rows:
        assert "error" not in r, r
        assert r["e2e_us"] >= 0 and r["dispatch_us"] >= 0


def test_profiler_tail_events_scope_deprecated(tmp_path):
    """Event/scope + 1.x deprecated aliases (reference profiler.py:73,
    112,146,329)."""
    import pytest

    f = str(tmp_path / "p.json")
    with pytest.warns(DeprecationWarning):
        mx.profiler.profiler_set_config(mode="all", filename=f)
    with pytest.warns(DeprecationWarning):
        mx.profiler.profiler_set_state("run")
    ev = mx.profiler.Event("phase")
    ev.start()
    with mx.profiler.scope("block1:"):
        _ = mx.np.ones((4, 4)).sum()
    ev.stop()
    frame = mx.profiler.Frame(mx.profiler.Domain("d"), "f0")
    frame.start()
    frame.stop()
    with pytest.warns(DeprecationWarning):
        mx.profiler.dump_profile()
    import json
    evs = json.load(open(f))
    evs = evs["traceEvents"] if isinstance(evs, dict) else evs
    names = {e.get("name") for e in evs}
    assert "phase" in names and "block1" in names and "f0" in names
    # stop + restore default config so global state doesn't leak
    mx.profiler.set_state("stop")
    mx.profiler.set_config(filename="profile.json", profile_all=False)
    # stopped profiler: instrumentation must not accumulate events
    ev2 = mx.profiler.Event("orphan")
    ev2.start()
    ev2.stop()
    from mxnet_tpu.profiler import _events
    assert not any(e["name"] == "orphan" for e in _events)


def test_gpu_memory_info():
    import jax
    import pytest

    from mxnet_tpu.base import MXNetError

    if jax.devices()[0].platform == "cpu":
        with pytest.raises(MXNetError):
            mx.context.gpu_memory_info(0)
    else:
        free, total = mx.context.gpu_memory_info(0)
        assert 0 < free <= total


def test_scope_append_mode_and_event_tagging():
    _reset()
    profiler.set_state("run")
    try:
        with profiler.scope("outer:"):
            assert profiler.current_scope() == "outer"
            with profiler.scope("inner:", append_mode=True):
                assert profiler.current_scope() == "outer:inner"
                _ = mx.np.ones((4, 4)) + 1
            with profiler.scope("replaced:"):  # append_mode=False replaces
                assert profiler.current_scope() == "replaced"
        assert profiler.current_scope() == ""
    finally:
        profiler.set_state("stop")
    names = [e["name"] for e in profiler._events]
    assert "outer:inner" in names and "replaced" in names
    # op events recorded inside a scope carry it in their args
    tagged = [e for e in profiler._events
              if e["args"].get("scope") == "outer:inner"
              and e["cat"] == "operator"]
    assert tagged, profiler._events


def test_dumps_json_and_sort_by():
    import pytest

    from mxnet_tpu.base import MXNetError

    _reset()
    profiler.set_state("run")
    a = mx.np.ones((8, 8))
    for _ in range(3):
        _ = mx.np.matmul(a, a)
    _ = a + a
    profiler.set_state("stop")

    out = json.loads(profiler.dumps(format="json"))
    rows = {r["name"]: r for r in out["aggregates"]}
    assert rows["matmul"]["calls"] == 3
    assert rows["matmul"]["total_ms"] >= rows["matmul"]["max_ms"]
    # total/avg/max are rounded independently: compare with abs slack
    assert rows["matmul"]["avg_ms"] == pytest.approx(
        rows["matmul"]["total_ms"] / 3, abs=1e-5)

    by_name = json.loads(profiler.dumps(format="json", sort_by="name",
                                        ascending=True))["aggregates"]
    names = [r["name"] for r in by_name]
    assert names == sorted(names)
    by_calls = json.loads(profiler.dumps(format="json",
                                         sort_by="calls"))["aggregates"]
    assert by_calls[0]["name"] == "matmul"

    table = profiler.dumps()  # default stays the text table
    assert "Avg(ms)" in table and "matmul" in table

    with pytest.raises(MXNetError, match="sort_by"):
        profiler.dumps(sort_by="bogus")
    with pytest.raises(MXNetError, match="format"):
        profiler.dumps(format="yaml")
    profiler._events.clear()

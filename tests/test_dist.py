"""Multi-process distributed tests (reference taxonomy: tests/nightly/
dist_sync_kvstore.py launched via tools/launch.py local mode, SURVEY §4
'distributed tests are real multi-process on one box') and the gradient-
compression bitwise oracle (reference: src/kvstore/gradient_compression.h).
"""
import os
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_launch_two_process_dist_sync():
    """Spawn 2 real processes; workers assert exact reduced values."""
    env = dict(os.environ)
    env.pop("JAX_PLATFORMS", None)  # workers force cpu via MXTPU_DIST_DEVICE
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"), "-n", "2",
         sys.executable, os.path.join(REPO, "tests", "dist_worker.py")],
        capture_output=True, text=True, timeout=300, env=env, cwd=REPO)
    assert r.returncode == 0, f"stdout:\n{r.stdout}\nstderr:\n{r.stderr}"
    assert "DIST_OK 0" in r.stdout and "DIST_OK 1" in r.stdout, r.stdout


def test_gradient_compression_2bit_oracle():
    from mxnet_tpu.kvstore.gradient_compression import GradientCompression
    gc = GradientCompression(type="2bit", threshold=0.5)
    g = onp.array([0.3, -0.3, 0.7, -0.9, 0.0, 2.0], dtype="float32")
    q1 = onp.asarray(gc.quantize("k", g))
    # oracle: elementwise threshold quantization
    onp.testing.assert_array_equal(
        q1, onp.array([0.0, 0.0, 0.5, -0.5, 0.0, 0.5], dtype="float32"))
    res = onp.asarray(gc._residual["k"])
    onp.testing.assert_allclose(res, g - q1, rtol=1e-6)
    # error feedback: second quantize of zeros flushes accumulated residual
    q2 = onp.asarray(gc.quantize("k", onp.zeros_like(g)))
    onp.testing.assert_array_equal(
        q2, onp.array([0.0, 0.0, 0.0, 0.0, 0.0, 0.5], dtype="float32"))


def test_gradient_compression_1bit_oracle():
    from mxnet_tpu.kvstore.gradient_compression import GradientCompression
    gc = GradientCompression(type="1bit", threshold=0.5)
    g = onp.array([0.1, -0.1, 3.0], dtype="float32")
    q = onp.asarray(gc.quantize("k", g))
    onp.testing.assert_array_equal(
        q, onp.array([0.5, -0.5, 0.5], dtype="float32"))
    onp.testing.assert_allclose(onp.asarray(gc._residual["k"]), g - q,
                                rtol=1e-6)


@pytest.mark.parametrize("mode,per_byte", [("2bit", 4), ("1bit", 8)])
def test_pack_unpack_codes_bitwise(mode, per_byte):
    """Wire format: n values fit in ceil(n/per_byte) bytes, exact roundtrip."""
    from mxnet_tpu.kvstore.gradient_compression import (
        GradientCompression, pack_codes, unpack_codes)
    t = 0.5
    gc = GradientCompression(type=mode, threshold=t)
    rng = onp.random.RandomState(0)
    g = rng.uniform(-2, 2, size=(37,)).astype("float32")  # non-multiple of 8
    q = onp.asarray(gc.quantize("k", g))
    packed, n = pack_codes(q, t, mode=mode)
    assert packed.dtype == onp.uint8
    assert len(packed) == -(-37 // per_byte)  # ceil: the compression claim
    back = unpack_codes(packed, n, t, mode=mode)
    onp.testing.assert_array_equal(back, q)


def test_compression_rejects_bad_params():
    from mxnet_tpu.base import MXNetError
    from mxnet_tpu.kvstore.gradient_compression import GradientCompression
    with pytest.raises(MXNetError):
        GradientCompression(type="4bit")
    with pytest.raises(MXNetError):
        GradientCompression(threshold=0)


def test_local_kvstore_rejects_compression():
    from mxnet_tpu.base import MXNetError
    kv = mx.kv.create("device")
    with pytest.raises(MXNetError):
        kv.set_gradient_compression({"type": "2bit"})


def test_single_process_dist_kvstore_degenerates():
    """dist_sync with no peer env vars = world of 1; exact local behavior."""
    kv = mx.kv.create("dist_sync")
    assert kv.num_workers == 1 and kv.rank == 0
    kv.init("a", mx.np.zeros((3,)))
    kv.push("a", mx.np.full((3,), 2.0))
    out = mx.np.empty((3,))
    kv.pull("a", out=out)
    onp.testing.assert_array_equal(out.asnumpy(), onp.full((3,), 2.0))


def test_dist_async_watchdog_times_out():
    """A hung reconciling collective must raise with a schedule diagnostic
    (the documented dist_async divergence, kvstore/dist.py:121) instead of
    freezing. The hang is simulated: a real mismatched pull schedule
    blocks inside XLA exactly like this stand-in."""
    import time

    import pytest

    import mxnet_tpu as mx
    from mxnet_tpu import np
    from mxnet_tpu.kvstore.dist import DistAsyncKVStore

    kv = DistAsyncKVStore.__new__(DistAsyncKVStore)
    kv._store = {"w": np.zeros((4,))}
    kv._nprocs = 2
    kv._rank = 0

    def hang(merged):
        time.sleep(60)
        return merged

    kv._allreduce = hang
    old = mx.config.get("kvstore.async_timeout")
    mx.config.set("kvstore.async_timeout", 0.5)
    # a deterministic schedule mismatch must fail fast, not be retried:
    # pin the elastic retry layer off for the raw-diagnostic assertion
    mx.config.set("kvstore.retry_max", 0)
    try:
        t0 = time.time()
        with pytest.raises(mx.base.MXNetError, match="pull schedule"):
            kv._reconcile("w")
        assert time.time() - t0 < 5
    finally:
        mx.config.set("kvstore.async_timeout", old)
        mx.config.reset("kvstore.retry_max")


@pytest.mark.slow
def test_multiprocess_overhead_table_two_procs():
    """Real 2-process collective probe (reference:
    tests/nightly/dist_sync_kvstore.py launch taxonomy)."""
    from mxnet_tpu.parallel.scaling import multiprocess_overhead_table

    rows = multiprocess_overhead_table(ns=(2,), timeout=240)
    assert len(rows) == 1
    row = rows[0]
    assert "error" not in row, row
    assert row["n"] == 2
    assert row["compute_ms"] > 0
    assert len(row["allreduce"]) == 2
    for r in row["allreduce"]:
        assert r["allreduce_ms"] > 0 and r["bytes"] in (1 << 20, 1 << 24)

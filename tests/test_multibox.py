"""SSD multibox ops vs a direct numpy transcription of the reference
algorithm (src/operator/contrib/multibox_prior.cc, multibox_target.cc,
multibox_detection.cc)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np


# ---------------------------------------------------------------------------
# numpy oracles (independent re-implementation of the C++ loops)
# ---------------------------------------------------------------------------

def prior_oracle(h, w, sizes, ratios, steps=(-1, -1), offsets=(0.5, 0.5),
                 clip=False):
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    out = []
    for r in range(h):
        cy = (r + offsets[0]) * step_y
        for c in range(w):
            cx = (c + offsets[1]) * step_x
            ratio = onp.sqrt(ratios[0])
            for s in sizes:
                bw = s * h / w * ratio / 2
                bh = s / ratio / 2
                out.append([cx - bw, cy - bh, cx + bw, cy + bh])
            s = sizes[0]
            for rr in ratios[1:]:
                ratio = onp.sqrt(rr)
                bw = s * h / w * ratio / 2
                bh = s / ratio / 2
                out.append([cx - bw, cy - bh, cx + bw, cy + bh])
    out = onp.array(out, onp.float32)[None]
    return onp.clip(out, 0, 1) if clip else out


def _iou(a, b):
    lt = onp.maximum(a[:2], b[:2])
    rb = onp.minimum(a[2:], b[2:])
    wh = onp.maximum(rb - lt, 0)
    inter = wh[0] * wh[1]
    ua = max(a[2] - a[0], 0) * max(a[3] - a[1], 0)
    ub = max(b[2] - b[0], 0) * max(b[3] - b[1], 0)
    union = ua + ub - inter
    return inter / union if union > 0 else 0.0


def target_oracle(anchors, labels, cls_preds, overlap_threshold=0.5,
                  ignore_label=-1, negative_mining_ratio=-1,
                  negative_mining_thresh=0.5, variances=(0.1, 0.1, 0.2, 0.2)):
    N, M, _ = labels.shape
    A = anchors.shape[0]
    loc_t = onp.zeros((N, A * 4), onp.float32)
    loc_m = onp.zeros((N, A * 4), onp.float32)
    cls_t = onp.full((N, A), float(ignore_label), onp.float32)
    for n in range(N):
        lab = labels[n]
        nvalid = 0
        for i in range(M):
            if lab[i, 0] == -1:
                break
            nvalid += 1
        if nvalid == 0:
            continue
        ov = onp.array([[_iou(anchors[j], lab[k, 1:5])
                         for k in range(nvalid)] for j in range(A)])
        gt_flags = [False] * nvalid
        match = [(-1.0, -1)] * A
        aflag = [-1] * A
        npos = 0
        while not all(gt_flags):
            best_a, best_g, best = -1, -1, 1e-6
            for j in range(A):
                if aflag[j] == 1:
                    continue
                for k in range(nvalid):
                    if gt_flags[k]:
                        continue
                    if ov[j, k] > best:
                        best_a, best_g, best = j, k, ov[j, k]
            if best_a == -1:
                break
            match[best_a] = (best, best_g)
            gt_flags[best_g] = True
            aflag[best_a] = 1
            npos += 1
        if overlap_threshold > 0:
            for j in range(A):
                if aflag[j] == 1:
                    continue
                k = int(onp.argmax(ov[j]))
                match[j] = (ov[j, k], k)
                if ov[j, k] > overlap_threshold:
                    aflag[j] = 1
                    npos += 1
        if negative_mining_ratio > 0:
            C = cls_preds.shape[1]
            nneg = min(int(npos * negative_mining_ratio), A - npos)
            if nneg > 0:
                cand = []
                for j in range(A):
                    if aflag[j] == 1:
                        continue
                    if match[j][0] < 0:
                        k = int(onp.argmax(ov[j]))
                        match[j] = (ov[j, k], k)
                    if match[j][0] < negative_mining_thresh and aflag[j] == -1:
                        logits = cls_preds[n, :, j]
                        e = onp.exp(logits - logits.max())
                        # reference sorts SortElemDescend(-prob) descending:
                        # smallest background prob first (hardest negatives)
                        cand.append((e[0] / e.sum(), j))
                cand.sort(key=lambda t: t[0])  # stable on ties by j
                for _, j in cand[:nneg]:
                    aflag[j] = 0
        else:
            for j in range(A):
                if aflag[j] != 1:
                    aflag[j] = 0
        for j in range(A):
            if aflag[j] == 1:
                _, k = match[j]
                cls_t[n, j] = lab[k, 0] + 1
                loc_m[n, j * 4:j * 4 + 4] = 1
                al, at, ar, ab = anchors[j]
                aw, ah = ar - al, ab - at
                ax, ay = (al + ar) / 2, (at + ab) / 2
                gl, gt_, gr, gb = lab[k, 1:5]
                gw, gh = gr - gl, gb - gt_
                gx, gy = (gl + gr) / 2, (gt_ + gb) / 2
                loc_t[n, j * 4:j * 4 + 4] = [
                    (gx - ax) / aw / variances[0],
                    (gy - ay) / ah / variances[1],
                    onp.log(gw / aw) / variances[2],
                    onp.log(gh / ah) / variances[3]]
            elif aflag[j] == 0:
                cls_t[n, j] = 0
    return loc_t, loc_m, cls_t


def detect_oracle(cls_prob, loc_pred, anchors, threshold=0.01, clip=True,
                  variances=(0.1, 0.1, 0.2, 0.2), nms_threshold=0.5,
                  force_suppress=False, nms_topk=-1):
    N, C, A = cls_prob.shape
    out = onp.full((N, A, 6), -1.0, onp.float32)
    for n in range(N):
        rows = []
        for i in range(A):
            score, cid = -1.0, 0
            for j in range(1, C):
                if cls_prob[n, j, i] > score:
                    score, cid = cls_prob[n, j, i], j
            if cid > 0 and score < threshold:
                cid = 0
            al, at, ar, ab = anchors[i]
            aw, ah = ar - al, ab - at
            ax, ay = (al + ar) / 2, (at + ab) / 2
            px, py, pw, ph = loc_pred[n, i * 4:i * 4 + 4]
            ox = px * variances[0] * aw + ax
            oy = py * variances[1] * ah + ay
            ow = onp.exp(pw * variances[2]) * aw / 2
            oh = onp.exp(ph * variances[3]) * ah / 2
            box = [ox - ow, oy - oh, ox + ow, oy + oh]
            if clip:
                box = [min(1.0, max(0.0, v)) for v in box]
            rows.append([cid - 1, score] + box)
        valid = [r for r in rows if r[0] >= 0]
        valid.sort(key=lambda r: -r[1])  # stable
        if nms_topk > 0:
            valid = valid[:nms_topk]
        if 0 < nms_threshold <= 1:
            for i in range(len(valid)):
                if valid[i][0] < 0:
                    continue
                for j in range(i + 1, len(valid)):
                    if valid[j][0] < 0:
                        continue
                    if force_suppress or valid[i][0] == valid[j][0]:
                        iou = _iou(onp.array(valid[i][2:]),
                                   onp.array(valid[j][2:]))
                        if iou >= nms_threshold:
                            valid[j][0] = -1
        for i, r in enumerate(valid):
            out[n, i] = r
    return out


# ---------------------------------------------------------------------------
# tests
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("cfg", [
    dict(h=2, w=3, sizes=(0.5,), ratios=(1.0,)),
    dict(h=4, w=4, sizes=(0.4, 0.25), ratios=(1.0, 2.0, 0.5)),
    dict(h=3, w=5, sizes=(0.9,), ratios=(1.0, 3.0), clip=True),
    dict(h=2, w=2, sizes=(0.5,), ratios=(1.0,), steps=(0.3, 0.4),
         offsets=(0.0, 1.0)),
])
def test_multibox_prior(cfg):
    h, w = cfg.pop("h"), cfg.pop("w")
    data = np.zeros((1, 3, h, w))
    got = mx.npx.multibox_prior(data, **cfg).asnumpy()
    want = prior_oracle(h, w, cfg["sizes"], cfg["ratios"],
                        cfg.get("steps", (-1, -1)),
                        cfg.get("offsets", (0.5, 0.5)),
                        cfg.get("clip", False))
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def _rand_case(seed, N=3, A=24, M=4, C=3):
    rs = onp.random.RandomState(seed)
    data = np.zeros((1, 3, 2, 4))
    anchors = mx.npx.multibox_prior(
        data, sizes=(0.4, 0.2), ratios=(1.0, 2.0)).asnumpy()[0]
    A = anchors.shape[0]
    labels = onp.full((N, M, 5), -1.0, onp.float32)
    for n in range(N):
        k = rs.randint(0, M + 1) if n else 0  # sample 0: no valid gt
        for i in range(k):
            x1, y1 = rs.uniform(0, 0.6, 2)
            labels[n, i] = [rs.randint(0, 2), x1, y1,
                            x1 + rs.uniform(0.1, 0.4),
                            y1 + rs.uniform(0.1, 0.4)]
    cls_preds = rs.randn(N, C, A).astype(onp.float32)
    return anchors, labels, cls_preds


@pytest.mark.parametrize("seed,mining", [(0, -1), (1, -1), (2, 3.0),
                                         (3, 2.0)])
def test_multibox_target(seed, mining):
    anchors, labels, cls_preds = _rand_case(seed)
    got = mx.npx.multibox_target(
        np.array(anchors[None]), np.array(labels), np.array(cls_preds),
        overlap_threshold=0.5, negative_mining_ratio=mining,
        negative_mining_thresh=0.5)
    want = target_oracle(anchors, labels, cls_preds,
                         negative_mining_ratio=mining)
    for g, w, name in zip(got, want, ["loc_target", "loc_mask",
                                      "cls_target"]):
        onp.testing.assert_allclose(g.asnumpy(), w, rtol=1e-4, atol=1e-5,
                                    err_msg=name)


@pytest.mark.parametrize("seed,topk,force", [(0, -1, False), (1, 5, False),
                                             (2, -1, True)])
def test_multibox_detection(seed, topk, force):
    rs = onp.random.RandomState(seed + 10)
    anchors, _, _ = _rand_case(seed)
    A = anchors.shape[0]
    N, C = 2, 3
    logits = rs.randn(N, C, A).astype(onp.float32)
    e = onp.exp(logits)
    cls_prob = (e / e.sum(1, keepdims=True)).astype(onp.float32)
    loc_pred = (rs.randn(N, A * 4) * 0.5).astype(onp.float32)
    got = mx.npx.multibox_detection(
        np.array(cls_prob), np.array(loc_pred), np.array(anchors[None]),
        threshold=0.3, nms_threshold=0.45, nms_topk=topk,
        force_suppress=force).asnumpy()
    want = detect_oracle(cls_prob, loc_pred, anchors, threshold=0.3,
                         nms_threshold=0.45, nms_topk=topk,
                         force_suppress=force)
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_target_hand_case():
    # one anchor dead-on a gt, one far away: bipartite matches the first,
    # second becomes negative (no mining)
    anchors = onp.array([[0.1, 0.1, 0.5, 0.5], [0.6, 0.6, 0.9, 0.9]],
                        onp.float32)
    labels = onp.array([[[1.0, 0.1, 0.1, 0.5, 0.5]]], onp.float32)
    loc_t, loc_m, cls_t = mx.npx.multibox_target(
        np.array(anchors[None]), np.array(labels),
        np.array(onp.zeros((1, 3, 2), onp.float32)))
    onp.testing.assert_allclose(cls_t.asnumpy(), [[2.0, 0.0]])
    onp.testing.assert_allclose(loc_m.asnumpy(),
                                [[1, 1, 1, 1, 0, 0, 0, 0]])
    onp.testing.assert_allclose(loc_t.asnumpy()[0, :4], [0, 0, 0, 0],
                                atol=1e-6)


def test_detection_suppresses_same_class():
    # two overlapping boxes same class: lower score suppressed
    anchors = onp.array([[0.1, 0.1, 0.5, 0.5], [0.12, 0.12, 0.52, 0.52]],
                        onp.float32)
    cls_prob = onp.array([[[0.1, 0.2], [0.9, 0.8]]], onp.float32)
    loc_pred = onp.zeros((1, 8), onp.float32)
    out = mx.npx.multibox_detection(
        np.array(cls_prob), np.array(loc_pred), np.array(anchors[None]),
        nms_threshold=0.5).asnumpy()
    assert out[0, 0, 0] == 0.0 and abs(out[0, 0, 1] - 0.9) < 1e-6
    assert out[0, 1, 0] == -1.0

"""Subgraph/partition backend tests (optimize_for + registered transforms
over the traced forward — the analog of the reference's
MXNET_REGISTER_SUBGRAPH_BACKEND property API, subgraph_property.h:88)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, library
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn


def _mlp():
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(8))
    net.initialize()
    return net


def test_builtin_backends_registered():
    names = library.list_subgraph_backends()
    assert "checkpoint" in names and "bf16" in names


def test_unknown_backend_fails_fast():
    net = _mlp()
    with pytest.raises(MXNetError, match="unknown subgraph backend"):
        net.hybridize(backend="tensorrt")


def test_bf16_backend_changes_compute_dtype():
    net = _mlp()
    x = mx.np.array(
        onp.random.RandomState(0).randn(4, 16).astype("float32"))
    want = net(x).asnumpy()
    net.hybridize(backend="bf16")
    got = net(x)
    assert got.dtype == onp.float32           # cast back at the boundary
    gotn = got.asnumpy()
    # bf16 mantissa is 8 bits: close to fp32 but not bit-identical
    onp.testing.assert_allclose(gotn, want, rtol=3e-2, atol=3e-2)
    assert not onp.array_equal(gotn, want)


def test_checkpoint_backend_preserves_forward_and_grads():
    net = _mlp()
    x = mx.np.array(
        onp.random.RandomState(1).randn(4, 16).astype("float32"))
    def run():
        for p in net.collect_params().values():
            p.grad_req = "write"   # (re)attaches a zeroed grad buffer
            p.zero_grad()
        with autograd.record():
            y = net(x)
            loss = (y * y).sum()
        loss.backward()
        g = {n: p.grad().asnumpy().copy()
             for n, p in net.collect_params().items()}
        return y.asnumpy().copy(), g

    y0, g0 = run()
    net.hybridize(backend="checkpoint")
    y1, g1 = run()
    onp.testing.assert_allclose(y1, y0, rtol=1e-5, atol=1e-5)
    for n in g0:
        onp.testing.assert_allclose(g1[n], g0[n], rtol=1e-5, atol=1e-5,
                                    err_msg=n)


def test_custom_backend_transform_applied():
    calls = []

    @library.register_subgraph_backend("test-double")
    def double(pure_fn, block, **opts):
        calls.append(type(block).__name__)

        def wrapped(tr, aux, inputs, rng_key, sig_key):
            out, mutated = pure_fn(tr, aux, inputs, rng_key, sig_key)
            return [o * 2 for o in out], mutated
        return wrapped

    net = nn.Dense(4)
    net.initialize()
    x = mx.np.ones((2, 3))
    want = net(x).asnumpy()
    net.hybridize(backend="test-double")
    got = net(x).asnumpy()
    onp.testing.assert_allclose(got, want * 2, rtol=1e-6)
    assert calls  # transform ran at compile time


def test_optimize_for_compiles_and_runs():
    net = _mlp()
    x = mx.np.ones((2, 16))
    out = net.optimize_for(x, backend="checkpoint")
    assert out.shape == (2, 8)
    assert net._backend == "checkpoint"

"""Storage-manager tests (mx.storage over native/mxtpu_pool.cc —
reference: src/storage/pooled_storage_manager.h behavior: bucketed
reuse, DirectFree, stats)."""
import threading

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import storage


def _native_or_skip():
    if not storage.pool_stats().get("native"):
        pytest.skip("native toolchain unavailable")


def test_alloc_free_reuse_hits():
    _native_or_skip()
    before = storage.pool_stats()
    b1 = storage.alloc(1000)
    b1.free()
    b2 = storage.alloc(900)   # same power-of-two class -> pool hit
    after = storage.pool_stats()
    assert after["hits"] >= before["hits"] + 1
    b2.free()


def test_buffer_data_integrity():
    _native_or_skip()
    with storage.alloc(4096) as buf:
        arr = buf.as_numpy((32, 32), "float32")
        arr[:] = onp.arange(1024, dtype="float32").reshape(32, 32)
        again = buf.as_numpy((32, 32), "float32")
        onp.testing.assert_array_equal(
            again, onp.arange(1024, dtype="float32").reshape(32, 32))


def test_pinned_array_roundtrip():
    arr = storage.pinned_array((8, 16), "float32")
    arr[:] = 7.0
    assert arr.sum() == 8 * 16 * 7.0
    # usable as a device-transfer source
    dev = mx.np.array(onp.asarray(arr))
    assert float(dev.sum().asnumpy()) == 8 * 16 * 7.0


def test_empty_cache_releases():
    _native_or_skip()
    storage.alloc(2048).free()
    assert storage.pool_stats()["cached"] > 0
    storage.empty_cache()
    assert storage.pool_stats()["cached"] == 0


def test_view_overflow_rejected():
    _native_or_skip()
    with storage.alloc(64) as buf:
        with pytest.raises(Exception):
            buf.as_numpy((1024,), "float32")


def test_concurrent_alloc_free():
    _native_or_skip()
    errs = []

    def work(seed):
        try:
            rs = onp.random.RandomState(seed)
            for _ in range(200):
                n = int(rs.randint(1, 65536))
                b = storage.alloc(n)
                a = b.as_numpy((min(n, 16),), "uint8")
                a[:] = seed % 256
                assert (a == seed % 256).all()
                b.free()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=work, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs, errs


def test_double_free_is_safe():
    _native_or_skip()
    b = storage.alloc(128)
    b.free()
    b.free()   # idempotent


def test_pool_payload_64_byte_aligned():
    _native_or_skip()
    for n in (1, 63, 64, 1000, 4096):
        with storage.alloc(n) as b:
            assert b.ptr % 64 == 0, (n, b.ptr % 64)


def test_double_free_does_not_alias():
    """A rejected double free must not put the block on the free list
    twice (two subsequent allocs would alias)."""
    _native_or_skip()
    b = storage.alloc(512)
    ptr = b.ptr
    pool, lib = storage._ensure_pool()
    import ctypes
    assert lib.mxtpu_pool_free(pool, ctypes.c_void_p(ptr)) == 0
    assert lib.mxtpu_pool_free(pool, ctypes.c_void_p(ptr)) != 0  # rejected
    b._freed = True
    a1 = storage.alloc(512)
    a2 = storage.alloc(512)
    assert a1.ptr != a2.ptr
    a1.free(); a2.free()

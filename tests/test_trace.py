"""mx.trace — causal span API, context propagation (threads + worker
processes), Perfetto export, the live ops endpoint, and the two e2e
acceptance trees (docs/OBSERVABILITY.md "Tracing"):

- one training step: ``train.step`` with data_wait / h2d / dispatch /
  drain children, sync-free loop preserved (sync_guard count unchanged
  vs untraced, zero RecompileWarning with tracing on);
- one serve request: ``serve.request`` with enqueue -> prefill ->
  decode_step x N -> drain children carrying the same request id, zero
  post-warmup compiles.

When ``MXNET_TRACE_E2E_DIR`` is set, the e2e tests also export their
rings (e2e_train.json / e2e_serve.json) so the CI ``trace`` stage can
re-validate the trees with tools/trace.py.
"""
import importlib.util
import json
import os
import threading
import urllib.request
import warnings

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import config, telemetry, trace
from mxnet_tpu.gluon.data import DataLoader

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _trace_cli():
    spec = importlib.util.spec_from_file_location(
        "trace_cli", os.path.join(_REPO, "tools", "trace.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.fixture(autouse=True)
def _clean_trace():
    """Every test starts with the recorder off and an empty ring, and
    leaves the knob-derived defaults behind."""
    trace.disable()
    trace.clear()
    yield
    trace.clear()
    trace.configure()  # restore _active/_capacity from the knobs


def _children(events):
    kids = {}
    for ev in events:
        pid = ev["args"].get("parent_id")
        if pid is not None:
            kids.setdefault(pid, []).append(ev)
    return kids


# -- span API ---------------------------------------------------------------

def test_span_nesting_links_and_attrs():
    trace.enable()
    with trace.span("outer", category="test", step=1) as outer:
        assert trace.current_context() == (outer.trace_id, outer.span_id)
        with trace.span("inner", items=3):
            pass
    assert trace.current_context() is None
    inner, outer_ev = trace.spans()  # inner exits (records) first
    assert inner["name"] == "inner" and outer_ev["name"] == "outer"
    assert inner["ph"] == outer_ev["ph"] == "X"
    assert inner["args"]["parent_id"] == outer_ev["args"]["span_id"]
    assert inner["args"]["trace_id"] == outer_ev["args"]["trace_id"]
    # the root's trace_id is its own span_id
    assert outer_ev["args"]["trace_id"] == outer_ev["args"]["span_id"]
    assert "parent_id" not in outer_ev["args"]
    assert inner["args"]["items"] == 3
    assert outer_ev["args"]["step"] == 1 and outer_ev["cat"] == "test"
    assert inner["dur"] >= 0 and inner["ts"] >= outer_ev["ts"]


def test_disabled_is_a_cheap_noop():
    assert not trace.active()
    sp = trace.span("never", x=1)
    with sp as got:
        assert got.set(y=2) is got  # chainable no-op
    assert trace.begin("never") is None
    trace.emit("never", 0, 0)
    assert trace.spans() == []
    assert trace.stats() == {"active": False, "recorded": 0, "dropped": 0,
                             "capacity": trace.stats()["capacity"]}


def test_begin_end_async_handle_across_threads():
    trace.enable()
    root = trace.begin("req", category="test", request=7)
    child = trace.begin("phase", parent=root.context, request=7)
    # an async span may end on a different thread than it began
    t = threading.Thread(target=child.end, kwargs={"tokens": 3})
    t.start()
    t.join()
    root.end()
    root.end()  # idempotent: no duplicate record
    evs = trace.spans()
    assert [e["name"] for e in evs] == ["phase", "req"]
    phase, req = evs
    assert phase["args"]["parent_id"] == req["args"]["span_id"]
    assert phase["args"]["tokens"] == 3 and phase["args"]["request"] == 7


def test_emit_parents_to_explicit_context():
    trace.enable()
    root = trace.begin("root")
    trace.emit("leaf", trace.clock_us() - 50, 40, parent=root.context,
               category="test", n=1)
    root.end()
    leaf = trace.spans()[0]
    assert leaf["name"] == "leaf" and leaf["dur"] == 40
    assert leaf["args"]["parent_id"] == root.span_id
    assert leaf["cat"] == "test" and leaf["args"]["n"] == 1


def test_ring_eviction_counts_dropped(monkeypatch):
    telemetry.enable()
    telemetry.reset()
    try:
        trace.enable(buffer=8)
        for i in range(20):
            trace.emit(f"ev{i}", i, 1)
        evs = trace.spans()
        assert len(evs) == 8
        assert [e["name"] for e in evs] == [f"ev{i}" for i in range(12, 20)]
        assert trace.stats()["dropped"] == 12
        assert telemetry.counters(aggregate=True)["trace.dropped_total"] == 12
        trace.clear()
        assert trace.stats()["dropped"] == 0
    finally:
        telemetry.reset()
        telemetry.disable()


def test_knobs_arm_configure():
    prior_on, prior_buf = config.get("trace.enable"), config.get("trace.buffer")
    config.set("trace.enable", True)
    config.set("trace.buffer", 32)
    try:
        trace.configure()
        assert trace.active() and trace.stats()["capacity"] == 32
    finally:
        config.set("trace.enable", prior_on)
        config.set("trace.buffer", prior_buf)
        trace.configure()
    assert not trace.active()


# -- clock + profiler bridge ------------------------------------------------

def test_shared_clock_and_profiler_mirroring():
    from mxnet_tpu import profiler
    assert trace.clock_us is profiler.now_us
    trace.enable()
    profiler.set_state("run")
    try:
        with trace.span("mirrored", category="test"):
            pass
    finally:
        profiler.set_state("stop")
    ev = trace.spans()[-1]
    mirrored = [e for e in profiler._events if e["name"] == "mirrored"]
    assert mirrored and mirrored[-1]["cat"] == "trace:test"
    # same clock: the mirror carries the very same start timestamp
    assert mirrored[-1]["ts"] == ev["ts"]
    rows = json.loads(profiler.dumps(format="json", reset=True))
    assert any(r["name"] == "mirrored" for r in rows["aggregates"])


# -- propagation: prefetcher thread + worker processes ----------------------

def test_prefetcher_thread_spans_share_the_root_trace():
    trace.enable()
    src = [onp.full((4,), i, dtype="float32") for i in range(4)]
    with trace.span("epoch", category="test") as root:
        pf = mx.pipeline.DevicePrefetcher(iter(src))
        out = list(pf)
    assert len(out) == 4
    h2d = [e for e in trace.spans() if e["name"] == "pipeline.h2d"]
    assert len(h2d) == 4
    main_tid = threading.get_ident()
    for ev in h2d:
        assert ev["args"]["trace_id"] == root.trace_id
        assert ev["args"]["parent_id"] == root.span_id
        assert ev["tid"] != main_tid  # recorded on the prefetch thread


class _TraceDataset:
    """Picklable dataset for spawn-based worker processes."""

    def __init__(self, n=16, dim=8):
        rs = onp.random.RandomState(0)
        self.x = rs.rand(n, dim).astype(onp.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return self.x[i]


def test_worker_process_spans_survive_the_shm_path():
    """Span ids minted in a DataLoader worker process parent back to the
    consumer's context — perf_counter is system-wide on Linux, so the
    timestamps land on the parent timeline unadjusted."""
    ds = _TraceDataset()
    dl = DataLoader(ds, batch_size=8, num_workers=2, thread_pool=False)
    trace.enable()
    with trace.span("epoch", category="test") as root:
        batches = list(dl)
    assert len(batches) == 2
    wspans = [e for e in trace.spans()
              if e["name"] == "dataloader.worker_batch"]
    assert len(wspans) == 2
    for ev in wspans:
        assert ev["pid"] != os.getpid()  # minted in the worker process
        assert ev["args"]["worker_pid"] == ev["pid"]
        assert ev["args"]["trace_id"] == root.trace_id
        assert ev["args"]["parent_id"] == root.span_id
        assert ev["args"]["samples"] == 8
        assert ev["dur"] >= 0


def test_attach_scopes_a_foreign_context():
    trace.enable()
    root = trace.begin("root")
    with trace.attach(root.context):
        with trace.span("under"):
            pass
    assert trace.current_context() is None
    root.end()
    under = next(e for e in trace.spans() if e["name"] == "under")
    assert under["args"]["parent_id"] == root.span_id


# -- export + CLI -----------------------------------------------------------

def test_export_is_a_loadable_chrome_trace(tmp_path):
    trace.enable()
    with trace.span("outer"):
        with trace.span("inner"):
            pass
    path = trace.export(str(tmp_path / "t.json"))
    with open(path) as f:
        doc = json.load(f)
    assert doc["displayTimeUnit"] == "ms"
    assert {e["name"] for e in doc["traceEvents"]} == {"outer", "inner"}

    cli = _trace_cli()
    events = cli.load(path)
    assert cli.has_parent_child(events, "outer", "inner")
    assert not cli.has_parent_child(events, "inner", "outer")
    assert cli.main(["validate", path, "--expect", "outer",
                     "--expect-child", "outer=inner"]) == 0
    with pytest.raises(SystemExit):
        cli.main(["validate", path, "--expect", "missing.span"])
    with pytest.raises(SystemExit):
        cli.main(["validate", str(tmp_path / "nope.json")])
    assert cli.main(["summary", path]) == 0


# -- ops endpoint -----------------------------------------------------------

def _get(port, path):
    with urllib.request.urlopen(f"http://127.0.0.1:{port}{path}",
                                timeout=5) as r:
        return r.status, r.headers.get("Content-Type"), r.read().decode()


def test_http_ops_endpoint_serves_metrics_health_and_trace():
    telemetry.enable()
    telemetry.reset()
    trace.enable()
    with trace.span("served", category="test"):
        pass
    telemetry.inc("trace.dropped_total", 0)  # touch the registry
    srv = telemetry.serve_http(port=0)
    try:
        port = srv.server_address[1]
        status, ctype, body = _get(port, "/metrics")
        assert status == 200
        assert ctype == telemetry.EXPOSITION_CONTENT_TYPE
        assert ctype.startswith("text/plain; version=0.0.4")
        assert "scrape_duration" in body

        status, ctype, body = _get(port, "/healthz")
        health = json.loads(body)
        assert status == 200 and health["pid"] == os.getpid()
        assert health["trace"]["active"] and health["trace"]["recorded"] >= 1

        status, _, body = _get(port, "/trace?last=1")
        got = json.loads(body)
        assert status == 200 and got["dropped"] == 0
        assert [e["name"] for e in got["spans"]] == ["served"]

        with pytest.raises(urllib.error.HTTPError) as e:
            _get(port, "/trace?last=bogus")
        assert e.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as e:
            _get(port, "/nope")
        assert e.value.code == 404
        assert telemetry.serve_http(port=0) is srv  # idempotent
    finally:
        telemetry.stop_http()
        telemetry.reset()
        telemetry.disable()


# -- lifecycle instrumentation: serve, train, autotune ----------------------

def _tiny_gpt(**kw):
    from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM
    cfg = dict(vocab_size=97, units=32, hidden_size=64, num_layers=2,
               num_heads=2, max_length=32, dropout=0.0, embed_dropout=0.0)
    cfg.update(kw)
    net = GPTForCausalLM(**cfg)
    net.initialize()
    return net


def _maybe_export(name):
    out = os.environ.get("MXNET_TRACE_E2E_DIR")
    if out:
        trace.export(os.path.join(out, name))


def test_e2e_serve_request_span_tree():
    """Acceptance: one ServeEngine.run() with tracing on yields a
    complete serve.request tree (enqueue -> prefill -> decode_step x N ->
    drain) whose children all carry the root's request id, with zero
    post-warmup compiles and per-phase quantiles in stats()."""
    mx.random.seed(0)
    eng = mx.serve.load(_tiny_gpt(), max_slots=4, buckets="4,8",
                        warmup=True)
    trace.enable(buffer=8192)
    rs = onp.random.RandomState(3)
    reqs = [eng.submit(rs.randint(1, 97, (n,)).tolist(), max_new_tokens=4)
            for n in (3, 5)]
    eng.run()
    assert eng.stats()["post_warmup_compiles"] == 0
    _maybe_export("e2e_serve.json")
    trace.disable()

    evs = trace.spans()
    kids = _children(evs)
    roots = {e["args"]["request"]: e for e in evs
             if e["name"] == "serve.request"}
    assert sorted(roots) == sorted(r.id for r in reqs)
    for req in reqs:
        root = roots[req.id]
        assert root["args"]["trace_id"] == root["args"]["span_id"]
        assert root["args"]["prompt_tokens"] == len(req.prompt)
        assert root["args"]["tokens"] == len(req.generated)
        children = kids.get(root["args"]["span_id"], [])
        names = [c["name"] for c in children]
        assert names.count("serve.enqueue") == 1
        assert names.count("serve.prefill") == 1
        assert names.count("serve.drain") >= 1
        # first token comes out of prefill; the rest need one decode
        # step each (more may record: the slot stays live while its
        # final emits sit in the deferred drain window)
        assert names.count("serve.decode_step") >= len(req.generated) - 1
        for c in children:
            assert c["args"]["request"] == req.id
            assert c["args"]["trace_id"] == root["args"]["trace_id"]

    phases = eng.stats()["phases"]
    for key in ("queue_wait", "prefill", "decode_per_token"):
        q = phases[key]
        assert q is not None and 0 <= q["p50"] <= q["p95"] <= q["p99"]


def test_serve_phase_quantiles_absent_when_untraced():
    # with the always-on reservoir off (serve.phase_sampling=0), no
    # tracer means no phase quantiles — the pre-reservoir contract
    prev = mx.config.set("serve.phase_sampling", 0)
    try:
        mx.random.seed(0)
        eng = mx.serve.load(_tiny_gpt(), max_slots=2, buckets="4,8")
        eng.submit([5, 6, 7], max_new_tokens=3)
        eng.run()
        assert all(v is None for v in eng.stats()["phases"].values())
    finally:
        mx.config.set("serve.phase_sampling", prev)


def _toy_data(n=32, d=8, classes=3, bs=16, seed=0):
    rng = onp.random.RandomState(seed)
    x = rng.randn(n, d).astype("float32")
    w = rng.randn(d, classes).astype("float32")
    y = (x @ w).argmax(-1).astype("float32")
    return [(mx.np.array(x[i:i + bs]), mx.np.array(y[i:i + bs]))
            for i in range(0, n, bs)]


def _make_estimator():
    from mxnet_tpu import gluon
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.gluon.contrib import estimator as est
    mx.random.seed(0)
    net = nn.Sequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(3))
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 0.1})
    return est.Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                         trainer=trainer)


def test_e2e_train_step_span_tree():
    """Acceptance: one traced epoch yields a complete train.step tree
    (data_wait / h2d / dispatch / drain children) per batch, with zero
    RecompileWarning and the sync-free loop intact."""
    telemetry.enable()
    telemetry.reset()
    try:
        e = _make_estimator()
        data = _toy_data()
        e.fit(data, epochs=1)  # warmup: compiles happen untraced
        trace.enable(buffer=8192)
        with warnings.catch_warnings(record=True) as caught:
            warnings.simplefilter("always")
            e.fit(data, epochs=1)
        _maybe_export("e2e_train.json")
        trace.disable()
        recompiles = [w for w in caught
                      if issubclass(w.category, telemetry.RecompileWarning)]
        assert not recompiles, [str(w.message) for w in recompiles]
    finally:
        telemetry.reset()
        telemetry.disable()

    evs = trace.spans()
    kids = _children(evs)
    steps = [ev for ev in evs if ev["name"] == "train.step"]
    # the final iteration (the StopIteration pull) records a stub step
    # with only a data_wait child — full steps carry a dispatch
    full = [ev for ev in steps
            if any(c["name"] == "train.dispatch"
                   for c in kids.get(ev["args"]["span_id"], []))]
    assert len(full) == len(data)
    assert len(steps) == len(data) + 1
    for ev in full:
        children = kids[ev["args"]["span_id"]]
        names = {c["name"] for c in children}
        assert {"train.data_wait", "train.h2d", "train.dispatch",
                "train.drain"} <= names, names
        for c in children:
            assert c["args"]["trace_id"] == ev["args"]["trace_id"]
    assert sorted(ev["args"]["step"] for ev in full) == \
        list(range(1, len(data) + 1))


def _epoch_sync_count(traced):
    e = _make_estimator()
    data = _toy_data()
    e.fit(data, epochs=1)  # warmup so both runs are post-compile
    if traced:
        trace.enable(buffer=8192)
    try:
        with mx.pipeline.sync_guard() as g:
            e.fit(data, epochs=1)
    finally:
        trace.disable()
        trace.clear()
    return g.count


def test_tracing_adds_no_host_syncs():
    assert _epoch_sync_count(traced=True) == _epoch_sync_count(traced=False)


@pytest.mark.skipif(
    len(__import__("jax").devices()) < 8, reason="needs 8 (virtual) devices")
def test_autotune_trial_spans(tmp_path):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from mxnet_tpu import autotune
    from mxnet_tpu.autotune import SearchSpace
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import make_mesh

    def loss_fn(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], -1))

    prior = config.get("autotune.cache_dir")
    config.set("autotune.cache_dir", str(tmp_path / "autotune"))
    trace.enable(buffer=8192)
    try:
        mx.random.seed(7)
        net = nn.Dense(6, in_units=4)
        net.initialize()
        rs = onp.random.RandomState(1)
        sample = (rs.randn(16, 4).astype("float32"),
                  rs.randint(0, 6, (16,)).astype("int32"))
        autotune.search(net, loss_fn, "adam", make_mesh({"dp": 1}),
                        (P("dp"), P("dp")), sample,
                        space=SearchSpace(batch_size=16), hbm_budget=None,
                        measure=lambda c: 100.0)
    finally:
        config.set("autotune.cache_dir", prior)
        trace.disable()

    evs = trace.spans()
    root = next(e for e in evs if e["name"] == "autotune.search")
    trials = [e for e in evs if e["name"] == "autotune.trial"]
    assert trials and root["args"]["trials"] == len(trials)
    for t in trials:
        assert t["args"]["parent_id"] == root["args"]["span_id"]
        assert t["args"]["status"] in ("ok", "oom", "error")
        assert "batch_size" in t["args"] and "items_per_s" in t["args"]

"""ONNX export/import round-trip tests.

Model of the reference's tests/python/onnx/ suite (backend round-trips via
onnxruntime); here the oracle is our own jnp ONNX evaluator, which also
exercises the wire format through a real serialize/parse cycle.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon import nn


def _roundtrip(net, *inputs, tol=1e-5):
    import tempfile, os
    want = net(*inputs)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "m.onnx")
        mx.onnx.export_model(net, path, args=inputs)
        loaded = mx.onnx.import_model(path)
        got = loaded(*[i for i in inputs])
    wl = want if isinstance(want, (list, tuple)) else [want]
    gl = got if isinstance(got, (list, tuple)) else [got]
    assert len(wl) == len(gl)
    for w, g in zip(wl, gl):
        onp.testing.assert_allclose(g.asnumpy(), w.asnumpy(),
                                    rtol=tol, atol=tol)
    return path


def test_serde_tensor_roundtrip():
    from mxnet_tpu.onnx import serde
    for dtype in ["float32", "int32", "int64", "bool", "float16"]:
        arr = onp.arange(24).reshape(2, 3, 4).astype(dtype)
        t = serde.make_tensor("x", arr)
        back = serde.to_array(t)
        assert back.dtype == arr.dtype and back.shape == arr.shape
        onp.testing.assert_array_equal(back, arr)


def test_serde_model_parse():
    from mxnet_tpu.onnx import serde
    g = serde.GraphProto()
    g.name = "g"
    n = serde.make_node("Add", ["a", "b"], ["c"], alpha=1.5, axes=[0, 1],
                        mode="constant")
    g.node.add().CopyFrom(n)
    m = serde.make_model(g)
    m2 = serde.ModelProto()
    m2.ParseFromString(m.SerializeToString())
    attrs = serde.node_attrs(m2.graph.node[0])
    assert attrs["alpha"] == 1.5
    assert attrs["axes"] == [0, 1]
    assert attrs["mode"] == "constant"
    assert m2.opset_import[0].version == 17


def test_export_mlp():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(8, activation="tanh"),
            nn.Dense(4))
    net.initialize()
    x = mx.np.array(onp.random.RandomState(0).randn(3, 10).astype("float32"))
    net(x)
    _roundtrip(net, x)


def test_export_function():
    def fn(x):
        import jax.numpy as jnp
        return jnp.sum(x * 2.0 + 1.0, axis=-1)
    import tempfile, os, jax.numpy as jnp
    x = onp.random.RandomState(1).randn(4, 5).astype("float32")
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "f.onnx")
        mx.onnx.export_model(fn, p, args=(x,))
        outs = mx.onnx.run_model(p, [x])
    onp.testing.assert_allclose(outs[0].asnumpy(), (x * 2 + 1).sum(-1),
                                rtol=1e-5)


def test_export_lenet_conv_pool():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(6, kernel_size=5, padding=2, activation="relu"),
            nn.MaxPool2D(pool_size=2, strides=2),
            nn.Conv2D(16, kernel_size=5, activation="relu"),
            nn.AvgPool2D(pool_size=2, strides=2),
            nn.Flatten(), nn.Dense(32, activation="relu"), nn.Dense(10))
    net.initialize()
    x = mx.np.array(
        onp.random.RandomState(0).randn(2, 1, 28, 28).astype("float32"))
    net(x)
    _roundtrip(net, x, tol=1e-4)


def test_export_batchnorm_eval():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, kernel_size=3, padding=1), nn.BatchNorm(),
            nn.Activation("relu"))
    net.initialize()
    x = mx.np.array(
        onp.random.RandomState(0).randn(2, 3, 8, 8).astype("float32"))
    # run a few training steps so running stats are nontrivial
    from mxnet_tpu import autograd
    for _ in range(2):
        with autograd.record():
            net(x)
    _roundtrip(net, x, tol=1e-4)


def test_export_resnet18():
    from mxnet_tpu.gluon.model_zoo.vision import resnet18_v1
    net = resnet18_v1(classes=10)
    net.initialize()
    x = mx.np.array(
        onp.random.RandomState(0).randn(1, 3, 32, 32).astype("float32"))
    net(x)
    _roundtrip(net, x, tol=1e-3)


def test_export_bert_layer():
    from mxnet_tpu.gluon.model_zoo.bert import BERTForPretraining
    net = BERTForPretraining(vocab_size=50, units=16, hidden_size=32,
                             num_layers=1, num_heads=2, max_length=32,
                             dropout=0.0, embed_dropout=0.0)
    net.initialize()
    ids = mx.np.array(
        onp.random.RandomState(0).randint(0, 50, (2, 8)).astype("int32"))
    net(ids)
    _roundtrip(net, ids, tol=1e-4)


def test_export_symbol():
    import tempfile, os
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    c = (a + b) * a - 3.0
    xa = mx.np.array([[1.0, 2.0], [3.0, 4.0]])
    xb = mx.np.array([[0.5, 0.5], [1.0, 1.0]])
    want = ((xa + xb) * xa - 3.0).asnumpy()
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "s.onnx")
        mx.onnx.export_model(c, p, args={"a": xa, "b": xb})
        got = mx.onnx.run_model(p, [xa, xb])[0].asnumpy()
    onp.testing.assert_allclose(got, want, rtol=1e-6)


def test_exported_file_structure():
    """The emitted file must be a valid ONNX ModelProto: correct opset,
    initializers named by parameter path, graph inputs/outputs typed."""
    import tempfile, os
    from mxnet_tpu.onnx import serde
    net = nn.Dense(4)
    net.initialize()
    x = mx.np.ones((2, 3))
    net(x)
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "m.onnx")
        mx.onnx.export_model(net, p, args=(x,))
        m = serde.load_model(p)
    assert m.ir_version == 8
    assert m.opset_import[0].version == 17
    inits = {t.name: tuple(t.dims) for t in m.graph.initializer}
    # names must be associated with the right values (tree_flatten of a
    # dict is sorted-key order — regression: weight/bias were swapped)
    wname = [n for n in inits if "weight" in n]
    bname = [n for n in inits if "bias" in n]
    assert wname and inits[wname[0]] == (4, 3), inits
    assert bname and inits[bname[0]] == (4,), inits
    assert len(m.graph.input) == 1
    vi = m.graph.input[0]
    dims = [dd.dim_value for dd in vi.type.tensor_type.shape.dim]
    assert dims == [2, 3]
    assert len(m.graph.output) == 1


def test_onnxblock_param_reassignment():
    """Re-assigned weights must affect subsequent calls (re-jit)."""
    import tempfile, os
    net = nn.Dense(2, use_bias=False)
    net.initialize()
    x = mx.np.ones((1, 3))
    net(x)
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "m.onnx")
        mx.onnx.export_model(net, p, args=(x,))
        blk = mx.onnx.import_model(p)
    before = blk(x).asnumpy()
    (name,) = [n for n in blk.params if "weight" in n]
    blk.params[name] = blk.params[name] * 2.0
    after = blk(x).asnumpy()
    onp.testing.assert_allclose(after, before * 2.0, rtol=1e-6)


def test_export_callable_single_array_arg():
    import tempfile, os
    import jax.numpy as jnp
    x = onp.random.RandomState(0).randn(4, 5).astype("float32")
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "f.onnx")
        mx.onnx.export_model(lambda a: jnp.tanh(a), p, args=x)  # bare array
        out = mx.onnx.run_model(p, [x])[0].asnumpy()
    onp.testing.assert_allclose(out, onp.tanh(x), rtol=1e-5)


def test_export_dynamic_slice_oob_clamp():
    """lax.dynamic_slice clamps start into [0, dim-size]; the translated
    graph must match at the boundary."""
    import tempfile, os
    import jax
    import jax.numpy as jnp

    def fn(x, i):
        return jax.lax.dynamic_slice(x, (i,), (4,))

    x = onp.arange(10, dtype="float32")
    i = onp.asarray(8, "int32")
    want = onp.asarray(fn(jnp.asarray(x), jnp.asarray(i)))
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "f.onnx")
        mx.onnx.export_model(fn, p, args=(x, i))
        got = mx.onnx.run_model(p, [x, i])[0].asnumpy()
    onp.testing.assert_allclose(got, want)


def test_export_iota_emits_range_not_constant():
    """A large broadcast iota must not be baked as a dense initializer."""
    import tempfile, os
    import jax.numpy as jnp
    from mxnet_tpu.onnx import serde

    def fn(x):
        pos = jnp.arange(x.shape[-1], dtype=jnp.float32)
        return x + jnp.broadcast_to(pos, x.shape)

    x = onp.zeros((8, 512), "float32")
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "f.onnx")
        mx.onnx.export_model(fn, p, args=(x,))
        assert os.path.getsize(p) < 4096, os.path.getsize(p)
        got = mx.onnx.run_model(p, [x])[0].asnumpy()
    onp.testing.assert_allclose(got, onp.broadcast_to(
        onp.arange(512, dtype="float32"), (8, 512)))


def test_runtime_reduce_axes_as_input():
    """Opset-18-style ReduceMax with axes as an input tensor."""
    from mxnet_tpu.onnx import serde, make_fn
    g = serde.GraphProto()
    g.name = "r"
    g.initializer.add().CopyFrom(
        serde.make_tensor("axes", onp.asarray([1], onp.int64)))
    g.input.add().CopyFrom(serde.make_value_info("x", "float32", (2, 3)))
    g.node.add().CopyFrom(serde.make_node("ReduceMax", ["x", "axes"], ["y"],
                                          keepdims=0))
    g.output.add().CopyFrom(serde.make_value_info("y", "float32", (2,)))
    x = onp.asarray([[1.0, 2.0, 0.0], [5.0, 3.0, 4.0]], "float32")
    out = make_fn(serde.make_model(g, opset=18))(x)[0]
    onp.testing.assert_allclose(onp.asarray(out), [2.0, 5.0])


def test_import_external_style_model():
    """Models written by other producers (Gemm/Relu/Constant nodes) load."""
    from mxnet_tpu.onnx import serde
    from mxnet_tpu.onnx import make_fn
    g = serde.GraphProto()
    g.name = "ext"
    w = onp.random.RandomState(0).randn(3, 4).astype("float32")
    b = onp.zeros(4, "float32")
    g.initializer.add().CopyFrom(serde.make_tensor("w", w))
    g.initializer.add().CopyFrom(serde.make_tensor("b", b))
    g.input.add().CopyFrom(serde.make_value_info("x", "float32", (2, 3)))
    g.node.add().CopyFrom(serde.make_node("Gemm", ["x", "w", "b"], ["h"]))
    g.node.add().CopyFrom(serde.make_node("Relu", ["h"], ["y"]))
    g.output.add().CopyFrom(serde.make_value_info("y", "float32", (2, 4)))
    m = serde.make_model(g)
    fn = make_fn(m)
    x = onp.random.RandomState(1).randn(2, 3).astype("float32")
    out = fn(x)[0]
    onp.testing.assert_allclose(onp.asarray(out),
                                onp.maximum(x @ w + b, 0), rtol=1e-5)


def _vision_factories():
    from mxnet_tpu.gluon.model_zoo import vision as V
    return [
        ("alexnet", lambda: V.alexnet(classes=10), (1, 3, 64, 64)),
        ("vgg11", lambda: V.vgg11(classes=10), (1, 3, 32, 32)),
        ("resnet18_v2", lambda: V.resnet18_v2(classes=10), (1, 3, 32, 32)),
        ("squeezenet", lambda: V.squeezenet1_0(classes=10), (1, 3, 64, 64)),
        ("densenet121", lambda: V.densenet121(classes=10), (1, 3, 32, 32)),
        ("mobilenet", lambda: V.mobilenet0_25(classes=10), (1, 3, 32, 32)),
        ("mobilenet_v2", lambda: V.mobilenet_v2_0_25(classes=10),
         (1, 3, 32, 32)),
        ("inception_v3", lambda: V.inception_v3(classes=10), (1, 3, 80, 80)),
    ]


def _roundtrip_family(name):
    fac = dict((n, (c, s)) for n, c, s in _vision_factories())
    ctor, shape = fac[name]
    net = ctor()
    net.initialize()
    x = mx.np.array(onp.random.RandomState(0)
                    .randn(*shape).astype("float32"))
    net(x)  # materialize deferred shapes
    _roundtrip(net, x, tol=2e-4)


@pytest.mark.parametrize("name", ["resnet18_v2", "mobilenet_v2"])
def test_export_vision_families_fast(name):
    """Two representative families in the default run; the full grid is
    nightly-marked below (reference: tests/python/onnx model zoo
    coverage runs in its own CI bucket)."""
    _roundtrip_family(name)


@pytest.mark.slow
@pytest.mark.parametrize("name", [
    "alexnet", "vgg11", "squeezenet", "densenet121", "mobilenet",
    "inception_v3"])
def test_export_all_vision_families(name):
    _roundtrip_family(name)


def test_export_lstm_scan():
    """Fused RNN (lax.scan over time) exports through ONNX Scan and
    round-trips (reference: mx2onnx RNN translation)."""
    net = nn.HybridSequential()
    from mxnet_tpu.gluon import rnn as grnn
    lstm = grnn.LSTM(8, num_layers=1)
    lstm.initialize()
    x = mx.np.array(onp.random.RandomState(1).randn(5, 2, 4)
                    .astype("float32"))
    lstm(x)
    _roundtrip(lstm, x, tol=1e-4)


def test_export_gru_bidirectional_scan():
    from mxnet_tpu.gluon import rnn as grnn
    gru = grnn.GRU(6, num_layers=1, bidirectional=True)
    gru.initialize()
    x = mx.np.array(onp.random.RandomState(2).randn(4, 2, 3)
                    .astype("float32"))
    gru(x)
    _roundtrip(gru, x, tol=1e-4)


def test_export_topk_sort_scatter():
    import os
    import tempfile

    import jax.numpy as jnp

    from mxnet_tpu import npx

    x = mx.np.array(onp.random.RandomState(3).randn(4, 8).astype("float32"))

    def rt(fn, tol=1e-5):
        want = onp.asarray(fn(x))
        with tempfile.TemporaryDirectory() as d:
            p = os.path.join(d, "m.onnx")
            mx.onnx.export_model(fn, p, args=(x,))
            got = mx.onnx.import_model(p)(x)
        got = got[0] if isinstance(got, (list, tuple)) else got
        onp.testing.assert_allclose(onp.asarray(got.asnumpy()), want,
                                    rtol=tol, atol=tol)

    def raw(a):
        return a._data if hasattr(a, "_data") else a

    def unwrap(v):
        return v._data if hasattr(v, "_data") else v

    rt(lambda a: unwrap(npx.topk(a, k=3, ret_typ="value")))
    rt(lambda a: unwrap(mx.np.sort(a, axis=-1)))
    rt(lambda a: raw(a).at[jnp.asarray([0, 2])].set(
        jnp.ones((2, 8), jnp.float32)))
    rt(lambda a: raw(a).at[jnp.asarray([1, 1, 3])].add(
        jnp.ones((3, 8), jnp.float32)))


def test_export_duplicate_outputs_unique_names():
    from mxnet_tpu.onnx import serde

    def f(a):
        b = a * 2
        return b, b  # same traced value twice
    x = mx.np.array(onp.ones((2, 2), "float32"))
    import os
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "dup.onnx")
        mx.onnx.export_model(f, p, args=(x,))
        model = serde.load_model(p)
        names = [o.name for o in model.graph.output]
        assert len(names) == len(set(names)), names
        loaded = mx.onnx.import_model(p)
        g = loaded(x)
        onp.testing.assert_allclose(g[0].asnumpy(), 2 * onp.ones((2, 2)))
        onp.testing.assert_allclose(g[1].asnumpy(), 2 * onp.ones((2, 2)))


def test_export_unsigned_iota_range_cast():
    import jax.numpy as jnp

    def f(a):
        raw = a._data if hasattr(a, "_data") else a
        return (jnp.arange(6, dtype=jnp.uint32).reshape(1, 6) +
                raw.astype(jnp.uint32))
    x = mx.np.array(onp.zeros((1, 6), "float32"))
    from mxnet_tpu.onnx import serde
    import os
    import tempfile
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, "iota.onnx")
        mx.onnx.export_model(f, p, args=(x,))
        model = serde.load_model(p)
        # every Range node must generate in a Range-legal dtype
        legal = {serde.onnx_dtype(onp.dtype(t)) for t in
                 ("float32", "float64", "int16", "int32", "int64")}
        for node in model.graph.node:
            if node.op_type == "Range":
                ini = {t.name: t for t in model.graph.initializer}
                start = ini[node.input[0]]
                assert start.data_type in legal


# -- third-party-graph edges (round-4 verdict item 8) ------------------------

def _run_graph(nodes, inputs, outputs, feeds, initializers=()):
    """Build a hand-authored (third-party-style) graph and execute it."""
    from mxnet_tpu.onnx import make_fn, serde
    g = serde.GraphProto()
    for n in nodes:
        g.node.append(n)
    for name, arr in feeds.items():
        g.input.append(serde.make_value_info(name, arr.dtype, arr.shape))
    for t in initializers:
        g.initializer.append(t)
    for name in outputs:
        g.output.append(serde.make_value_info(name, onp.float32, ()))
    fn = make_fn(serde.make_model(g))
    res = fn(*feeds.values())
    return [onp.asarray(r) for r in res]


def test_onnx_conv_auto_pad_same():
    import torch
    from mxnet_tpu.onnx import serde
    x = onp.random.RandomState(0).randn(1, 2, 7, 7).astype(onp.float32)
    w = onp.random.RandomState(1).randn(3, 2, 3, 3).astype(onp.float32)
    for ap, (lo, hi) in (("SAME_UPPER", (1, 1)), ("SAME_LOWER", (1, 1))):
        node = serde.make_node("Conv", ["x", "w"], ["y"], auto_pad=ap,
                               strides=[1, 1], kernel_shape=[3, 3])
        (got,) = _run_graph([node], ["x", "w"], ["y"],
                            {"x": x, "w": w})
        want = torch.nn.functional.conv2d(
            torch.from_numpy(x), torch.from_numpy(w), padding=1).numpy()
        onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)
    # stride 2 with even input: SAME_UPPER pads the extra cell at the end
    node = serde.make_node("Conv", ["x", "w"], ["y"], auto_pad="SAME_UPPER",
                           strides=[2, 2], kernel_shape=[3, 3])
    x8 = onp.random.RandomState(2).randn(1, 2, 8, 8).astype(onp.float32)
    (got,) = _run_graph([node], ["x", "w"], ["y"], {"x": x8, "w": w})
    xp = torch.nn.functional.pad(torch.from_numpy(x8), (0, 1, 0, 1))
    want = torch.nn.functional.conv2d(xp, torch.from_numpy(w),
                                      stride=2).numpy()
    assert got.shape == (1, 3, 4, 4)
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-4)


def test_onnx_pool_ceil_mode():
    import torch
    from mxnet_tpu.onnx import serde
    x = onp.random.RandomState(0).randn(1, 2, 7, 7).astype(onp.float32)
    node = serde.make_node("MaxPool", ["x"], ["y"], kernel_shape=[3, 3],
                           strides=[2, 2], ceil_mode=1)
    (got,) = _run_graph([node], ["x"], ["y"], {"x": x})
    want = torch.nn.functional.max_pool2d(
        torch.from_numpy(x), 3, 2, ceil_mode=True).numpy()
    onp.testing.assert_allclose(got, want, rtol=1e-5)

    for cip in (0, 1):
        node = serde.make_node("AveragePool", ["x"], ["y"],
                               kernel_shape=[3, 3], strides=[2, 2],
                               pads=[1, 1, 1, 1], ceil_mode=1,
                               count_include_pad=cip)
        (got,) = _run_graph([node], ["x"], ["y"], {"x": x})
        want = torch.nn.functional.avg_pool2d(
            torch.from_numpy(x), 3, 2, padding=1, ceil_mode=True,
            count_include_pad=bool(cip)).numpy()
        onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_onnx_cumsum_reverse_exclusive():
    from mxnet_tpu.onnx import serde
    x = onp.arange(12, dtype=onp.float32).reshape(3, 4)
    for rev in (0, 1):
        for exc in (0, 1):
            node = serde.make_node("CumSum", ["x", "ax"], ["y"],
                                   reverse=rev, exclusive=exc)
            (got,) = _run_graph(
                [node], ["x", "ax"], ["y"],
                {"x": x, "ax": onp.array(1, onp.int64)})
            want = x[:, ::-1] if rev else x
            want = onp.cumsum(want, axis=1)
            if exc:
                want = want - (x[:, ::-1] if rev else x)
            if rev:
                want = want[:, ::-1]
            onp.testing.assert_allclose(got, want, rtol=1e-6)


def test_onnx_scatternd_reductions():
    from mxnet_tpu.onnx import serde
    data = onp.zeros((4,), onp.float32) + 2.0
    idx = onp.array([[1], [3]], onp.int64)
    upd = onp.array([5.0, 1.0], onp.float32)
    for red, want in (("max", [2, 5, 2, 2]), ("min", [2, 2, 2, 1]),
                      ("add", [2, 7, 2, 3]), ("mul", [2, 10, 2, 2])):
        node = serde.make_node("ScatterND", ["d", "i", "u"], ["y"],
                               reduction=red)
        (got,) = _run_graph([node], ["d", "i", "u"], ["y"],
                            {"d": data, "i": idx, "u": upd})
        onp.testing.assert_allclose(got, want, rtol=1e-6)


def test_onnx_resize_nearest_and_linear():
    import torch
    from mxnet_tpu.onnx import serde
    x = onp.random.RandomState(0).randn(1, 2, 4, 5).astype(onp.float32)
    # nearest x2, asymmetric + floor == numpy repeat
    node = serde.make_node("Resize", ["x", "", "s"], ["y"], mode="nearest",
                           coordinate_transformation_mode="asymmetric",
                           nearest_mode="floor")
    (got,) = _run_graph([node], ["x", "s"], ["y"],
                        {"x": x, "s": onp.array([1, 1, 2, 2], onp.float32)})
    want = x.repeat(2, axis=2).repeat(2, axis=3)
    onp.testing.assert_allclose(got, want, rtol=1e-6)
    # linear half_pixel == torch bilinear align_corners=False
    node = serde.make_node("Resize", ["x", "", "", "sz"], ["y"],
                           mode="linear",
                           coordinate_transformation_mode="half_pixel")
    (got,) = _run_graph([node], ["x", "sz"], ["y"],
                        {"x": x, "sz": onp.array([1, 2, 8, 10], onp.int64)})
    want = torch.nn.functional.interpolate(
        torch.from_numpy(x), size=(8, 10), mode="bilinear",
        align_corners=False).numpy()
    onp.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)


def test_onnx_nms():
    from mxnet_tpu.onnx import serde
    boxes = onp.array([[[0, 0, 10, 10], [0.5, 0.5, 10.5, 10.5],
                        [20, 20, 30, 30]]], onp.float32)
    scores = onp.array([[[0.9, 0.8, 0.7]]], onp.float32)
    node = serde.make_node("NonMaxSuppression",
                           ["b", "s", "m", "iou", "st"], ["y"])
    (got,) = _run_graph(
        [node], ["b", "s", "m", "iou", "st"], ["y"],
        {"b": boxes, "s": scores, "m": onp.array(10, onp.int64),
         "iou": onp.array(0.5, onp.float32),
         "st": onp.array(0.0, onp.float32)})
    # box 1 overlaps box 0 (IoU ~0.82) -> suppressed; box 2 kept
    onp.testing.assert_array_equal(got, [[0, 0, 0], [0, 0, 2]])


def test_onnx_roi_align():
    from mxnet_tpu.onnx import serde
    # linear ramp: bilinear avg pooling of a linear function = value at
    # the bin-center, exact in the interior
    H = W = 8
    ramp = onp.tile(onp.arange(W, dtype=onp.float32), (H, 1))
    x = ramp.reshape(1, 1, H, W)
    rois = onp.array([[1.0, 1.0, 5.0, 5.0]], onp.float32)  # x1 y1 x2 y2
    node = serde.make_node("RoiAlign", ["x", "r", "bi"], ["y"],
                           output_height=2, output_width=2,
                           sampling_ratio=2, spatial_scale=1.0,
                           coordinate_transformation_mode="half_pixel")
    (got,) = _run_graph([node], ["x", "r", "bi"], ["y"],
                        {"x": x, "r": rois,
                         "bi": onp.array([0], onp.int64)})
    assert got.shape == (1, 1, 2, 2)
    # roi [0.5, 4.5) after half_pixel offset; bins of size 2 -> x centers
    # at 1.5 and 3.5
    onp.testing.assert_allclose(got[0, 0, 0], [1.5, 3.5], atol=1e-5)
    onp.testing.assert_allclose(got[0, 0, 1], [1.5, 3.5], atol=1e-5)


def test_export_extended_unary_primitives():
    """tan/asinh/acosh/atanh/cbrt/exp2/is_finite jaxpr primitives export
    and round-trip (round-4 exporter-breadth widening)."""
    import jax.numpy as jnp
    from mxnet_tpu.onnx import make_fn, trace_to_onnx

    def fn(x):
        return (jnp.tan(x) * 0.1 + jnp.arcsinh(x) + jnp.arctanh(x * 0.3)
                + jnp.arccosh(x + 1.5) + jnp.cbrt(x) + jnp.exp2(x)
                + jnp.where(jnp.isfinite(1 / x), x, 0.0))

    x = onp.linspace(0.2, 0.9, 8).astype(onp.float32).reshape(1, 8)
    model = trace_to_onnx(fn, mx.np.array(x)._data)
    got = onp.asarray(make_fn(model)(x)[0])
    want = onp.asarray(fn(mx.np.array(x)._data))
    onp.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

"""Large-tensor / int64 support suite.

Analog of the reference's tests/nightly/test_large_array.py and
test_np_large_array.py (tensors beyond 2**32 elements, int64 indexing).
The >4-billion-element cases allocate gigabytes, so — like the
reference's nightly gating — they only run when MXNET_TEST_LARGE_TENSOR=1.
The always-on cases lock the int64-shape arithmetic paths (size/indexing
math must not overflow int32) at small memory cost.
"""
import os

import numpy as onp
import pytest

import mxnet_tpu as mx

LARGE = os.environ.get("MXNET_TEST_LARGE_TENSOR", "0") == "1"
large_only = pytest.mark.skipif(
    not LARGE, reason="set MXNET_TEST_LARGE_TENSOR=1 (allocates >4GB, "
    "nightly-gated like the reference; verified passing on the CPU backend)")


def test_explicit_int64_dtype_is_real():
    """dtype='int64' must produce a true int64 array (no silent int32
    truncation) — reference builds with MXNET_USE_INT64_TENSOR_SIZE;
    here 64-bit requests enter a scoped x64 dispatch."""
    import warnings
    with warnings.catch_warnings():
        warnings.simplefilter("error")  # the jax truncation warning -> fail
        x = mx.np.array([1, 2, 3], dtype="int64")
        assert x.dtype == onp.int64
        y = (x + 1) * 3_000_000_000
        assert y.dtype == onp.int64
    assert int(y[2].asnumpy()) == 12_000_000_000  # > 2**32: no wraparound


def test_int64_values_beyond_int32_range():
    x = mx.np.full((4,), 2**40, dtype="int64")
    s = x.sum()
    assert int(s.asnumpy()) == 4 * 2**40


def test_size_arithmetic_is_int64():
    """shape/size math must use python ints (arbitrary precision), not
    int32 — a (2**16, 2**16) array's size overflows int32."""
    x = mx.np.zeros((1, 1))
    big_shape = (2 ** 16, 2 ** 16)
    # metadata-level checks only: no allocation of the big array
    assert int(onp.prod(big_shape, dtype=onp.int64)) == 2 ** 32
    y = mx.np.zeros((3, 5))
    assert isinstance(y.size, int) and y.size == 15


def test_int64_indices_on_moderate_array():
    x = mx.np.arange(1_000_000, dtype="float32")
    idx = mx.np.array([0, 999_999], dtype="int64")
    out = x[idx].asnumpy()
    onp.testing.assert_allclose(out, [0.0, 999_999.0])


def test_reduction_does_not_overflow_with_int64_scope():
    # 70k * 70k overflows int32; inside the int64 scope (the analog of
    # the reference's MXNET_USE_INT64_TENSOR_SIZE flag) it must not
    from mxnet_tpu import util
    n = 70_000
    with util.int64_tensor_size():
        x = mx.np.full((n,), 70_000, dtype="int64")
        assert x.dtype == onp.int64
        total = int(x.sum().asnumpy())
    assert total == n * 70_000  # 4.9e9 > 2**32
    assert not util.int64_enabled()  # scope restored


@large_only
def test_elementwise_over_2_32_elements():
    from mxnet_tpu import util
    n = 2 ** 32 + 8
    with util.int64_tensor_size():   # >int32 indices need the int64 mode
        x = mx.np.zeros((n,), dtype="int8")
        y = x + 1
        assert y.shape == (n,)
        assert int(y[n - 1].asnumpy()) == 1
        del x, y
    mx.waitall()


@large_only
def test_indexing_beyond_2_32():
    from mxnet_tpu import util
    n = 2 ** 32 + 8
    with util.int64_tensor_size():
        x = mx.np.zeros((n,), dtype="int8")
        idx = n - 2
        x[idx] = 7
        assert int(x[idx].asnumpy()) == 7
        del x
    mx.waitall()


@large_only
def test_large_first_dim_slice():
    from mxnet_tpu import util
    n = 2 ** 31 + 2
    with util.int64_tensor_size():
        x = mx.np.zeros((n, 2), dtype="int8")
        assert x.shape[0] == n
        s = x[n - 1]
        assert tuple(s.shape) == (2,)
        del x
    mx.waitall()

"""Full-surface opperf harness (reference: benchmark/opperf/opperf.py:56
runs every registered op)."""
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "benchmark"))


@pytest.mark.slow
def test_opperf_covers_locked_surfaces():
    import opperf
    from test_op_coverage import REF_NPX, REF_LINALG, REF_RANDOM

    rows = opperf.run(full=True, warmup=1, iters=2)
    names = {r["op"] for r in rows}
    errs = [r for r in rows if "error" in r]
    assert not errs, errs[:5]
    for op in REF_NPX:
        if op in ("cond", "foreach", "while_loop"):  # control flow, untimed
            continue
        assert f"npx.{op}" in names, op
    for op in REF_LINALG:
        assert f"linalg.{op}" in names, op
    for op in REF_RANDOM:
        assert f"random.{op}" in names, op
    assert len(names) >= 290

"""ZeRO-sharded training: parity oracles, microbatch accumulation,
selective remat, topology-independent resume, memory telemetry.

Strategy (SURVEY §4 style): every optimization must be numerically
invisible — zero=1/2, grad_accum and remat each run against the plain
replicated step on the same seed/virtual CPU mesh and must reproduce
its parameters, not just its loss curve.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import numpy as np
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import make_mesh
from mxnet_tpu.parallel.train import ShardedTrainStep

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


def _make_net(units=10, in_units=8, seed=7):
    from mxnet_tpu.gluon import nn
    mx.random.seed(seed)
    net = nn.Dense(units, in_units=in_units)
    net.initialize()
    return net


def _loss_fn(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], -1))


def _data(n=16, in_units=8, classes=10, seed=1):
    rs = onp.random.RandomState(seed)
    x = rs.randn(n, in_units).astype("float32")
    y = rs.randint(0, classes, (n,)).astype("int32")
    return x, y


def _step(zero=0, mesh=None, opt=None, **kw):
    mesh = mesh or make_mesh({"dp": 4})
    opt = opt or mx.optimizer.create("adam", learning_rate=0.05)
    return ShardedTrainStep(_make_net(), _loss_fn, opt, mesh,
                            batch_specs=(P("dp"), P("dp")), n_labels=1,
                            zero=zero, **kw)


# ---------------------------------------------------------------------------
# parity oracles
# ---------------------------------------------------------------------------

def test_zero1_matches_replicated():
    """zero=1 must be numerically invisible: same seed, same batches,
    fp32-allclose params vs the replicated step after several updates."""
    x, y = _data()
    mx.random.seed(3)
    base = _step(zero=0)
    mx.random.seed(3)
    z1 = _step(zero=1)
    for _ in range(4):
        l0 = float(base(x, y).asnumpy())
        l1 = float(z1(x, y).asnumpy())
        onp.testing.assert_allclose(l1, l0, rtol=1e-5, atol=1e-6)
    for n in base.trainable:
        onp.testing.assert_allclose(
            onp.asarray(z1.trainable[n]), onp.asarray(base.trainable[n]),
            rtol=1e-5, atol=1e-6)


def test_zero1_state_is_dp_sharded():
    """The point of ZeRO-1: optimizer state lives in 1/dp flat shards."""
    z1 = _step(zero=1)
    dp = 4
    for n, leaves in ((n, jax.tree_util.tree_leaves(s))
                      for n, s in z1.states.items()):
        for leaf in leaves:
            assert leaf.sharding.spec == P("dp"), (n, leaf.sharding)
            shard = leaf.addressable_shards[0].data
            assert shard.size * dp == leaf.size, (n, shard.shape, leaf.shape)


def test_zero2_with_grad_accum_matches_replicated():
    """zero=2 (dp-sharded grads + accumulator) composed with grad_accum
    still reproduces the plain step on the equivalent big batch."""
    x, y = _data(n=16)
    mx.random.seed(5)
    base = _step(zero=0)
    mx.random.seed(5)
    z2 = _step(zero=2, grad_accum=2)
    xs = x.reshape(2, 8, 8)
    ys = y.reshape(2, 8)
    for _ in range(3):
        l0 = float(base(x, y).asnumpy())
        l2 = float(z2(xs, ys).asnumpy())
        onp.testing.assert_allclose(l2, l0, rtol=1e-5, atol=1e-6)
    for n in base.trainable:
        onp.testing.assert_allclose(
            onp.asarray(z2.trainable[n]), onp.asarray(base.trainable[n]),
            rtol=1e-5, atol=1e-5)


def test_grad_accum_matches_one_big_batch():
    """K microbatches + ONE update == one update on the concatenated
    batch (mean loss => grads average; distinct from steps_per_call,
    which applies K updates)."""
    x, y = _data(n=32)
    mx.random.seed(11)
    big = _step()
    mx.random.seed(11)
    accum = _step(grad_accum=4)
    for _ in range(3):
        lb = float(big(x, y).asnumpy())
        la = float(accum(x.reshape(4, 8, 8), y.reshape(4, 8)).asnumpy())
        onp.testing.assert_allclose(la, lb, rtol=1e-5, atol=1e-6)
    assert accum._n_step == 3  # 3 optimizer updates, not 12
    assert accum.fopt.opt.num_update == 3
    for n in big.trainable:
        onp.testing.assert_allclose(
            onp.asarray(accum.trainable[n]), onp.asarray(big.trainable[n]),
            rtol=1e-5, atol=1e-5)


def test_remat_output_equivalence():
    """jax.checkpoint changes memory, never values: remat='dots' and
    remat=True reproduce the un-remat step bitwise-close."""
    x, y = _data()
    results = {}
    for remat in (None, "dots", True):
        mx.random.seed(13)
        step = _step(remat=remat)
        losses = [float(step(x, y).asnumpy()) for _ in range(3)]
        results[remat] = (losses, {n: onp.asarray(v)
                                   for n, v in step.trainable.items()})
    for remat in ("dots", True):
        onp.testing.assert_allclose(results[remat][0], results[None][0],
                                    rtol=1e-6, atol=1e-7)
        for n, w in results[None][1].items():
            onp.testing.assert_allclose(results[remat][1][n], w,
                                        rtol=1e-6, atol=1e-7)


def test_hybridize_remat_flag_flows_into_step():
    """hybridize(remat=...) is the user-facing knob: the step inherits it
    and bad policy names fail fast at hybridize time."""
    from mxnet_tpu.gluon.block import resolve_remat_policy, _REMAT_OFF
    net = _make_net()
    net.hybridize(remat="dots")
    assert net._flags.get("remat") == "dots"
    mesh = make_mesh({"dp": 4})
    step = ShardedTrainStep(net, _loss_fn, "adam", mesh,
                            batch_specs=(P("dp"), P("dp")), n_labels=1)
    assert step._remat_on
    with pytest.raises(MXNetError):
        resolve_remat_policy("not_a_policy")
    assert resolve_remat_policy(False) is _REMAT_OFF


# ---------------------------------------------------------------------------
# schedules / guards
# ---------------------------------------------------------------------------

def test_lr_schedule_advances_in_compiled_step():
    """Regression: the compiled step used to leave num_update at 0, so
    warmup/decay schedules were frozen at their step-0 value forever."""
    sched = mx.lr_scheduler.FactorScheduler(step=1, factor=0.5)
    opt = mx.optimizer.create("sgd", learning_rate=0.1, lr_scheduler=sched)
    step = _step(opt=opt)
    x, y = _data()
    assert opt.num_update == 0
    seen = []
    for _ in range(3):
        seen.append(float(sched(opt.num_update + 1)))
        step(x, y)
    assert opt.num_update == 3
    onp.testing.assert_allclose(seen, [0.1, 0.05, 0.025], rtol=1e-6)


def test_steps_per_call_advances_update_count():
    opt = mx.optimizer.create("adam", learning_rate=0.05)
    step = _step(opt=opt, steps_per_call=3, zero=1)
    x, y = _data(n=24)
    step(x.reshape(3, 8, 8), y.reshape(3, 8))
    assert step._n_step == 3
    assert opt.num_update == 3


def test_zero_rejects_non_elementwise_optimizer():
    """Norm-based rules (LAMB/LARS: whole-tensor trust ratios) would be
    silently wrong on 1/dp shards — must refuse loudly."""
    with pytest.raises(MXNetError, match="not elementwise"):
        _step(zero=1, opt=mx.optimizer.create("lamb"))
    with pytest.raises(MXNetError, match="zero must be"):
        _step(zero=3)
    mesh = make_mesh({"tp": 4})
    with pytest.raises(MXNetError, match="mesh axis"):
        ShardedTrainStep(_make_net(), _loss_fn, "adam", mesh,
                         batch_specs=(P("tp"), P("tp")), n_labels=1, zero=1)


# ---------------------------------------------------------------------------
# topology-independent checkpoints
# ---------------------------------------------------------------------------

def test_zero_checkpoint_resume_bitwise_other_dp(tmp_path):
    """A zero=1 bundle saved at dp=4 restores bitwise at dp=2 (and into a
    replicated zero=0 step): the canonical gathered layout makes resume
    independent of the saving run's topology."""
    x, y = _data()
    mx.random.seed(21)
    src = _step(zero=1)
    for _ in range(2):
        src(x, y)
    fname = str(tmp_path / "zero.ckpt")
    src.save_states(fname)
    canon = src.state_dict()["arrays"]

    for dp, zero in ((2, 1), (4, 0)):
        mx.random.seed(99)  # different init; load must overwrite all of it
        dst = _step(zero=zero, mesh=make_mesh({"dp": dp}))
        dst.load_states(fname)
        assert dst._n_step == 2
        assert dst.fopt.opt.num_update == 2
        got = dst.state_dict()["arrays"]
        assert set(got) == set(canon)
        for k in canon:
            onp.testing.assert_array_equal(got[k], canon[k])

    # and the continuation matches: one more step on each topology
    mx.random.seed(33)
    cont_src = [float(src(x, y).asnumpy()) for _ in range(2)]
    mx.random.seed(33)
    dst = _step(zero=1, mesh=make_mesh({"dp": 2}))
    dst.load_states(fname)
    cont_dst = [float(dst(x, y).asnumpy()) for _ in range(2)]
    onp.testing.assert_allclose(cont_dst, cont_src, rtol=1e-5, atol=1e-6)


def test_trainstate_bundles_sharded_step(tmp_path):
    """mx.resilience.TrainState carries the sharded step's canonical
    state through its crash-atomic bundle — preemption-safe dp-sharded
    training, resumable at a different dp size."""
    x, y = _data()
    mx.random.seed(41)
    src = _step(zero=1)
    state = mx.resilience.TrainState(sharded_step=src,
                                     path=str(tmp_path / "run.bundle"))
    for _ in range(2):
        src(x, y)
        state.step += 1
    state.save()

    mx.random.seed(77)
    dst = _step(zero=1, mesh=make_mesh({"dp": 2}))
    state2 = mx.resilience.TrainState(sharded_step=dst,
                                      path=str(tmp_path / "run.bundle"))
    state2.load()
    assert state2.step == 2
    assert dst._n_step == 2
    canon, got = src.state_dict()["arrays"], dst.state_dict()["arrays"]
    for k in canon:
        onp.testing.assert_array_equal(got[k], canon[k])


# ---------------------------------------------------------------------------
# telemetry planes
# ---------------------------------------------------------------------------

def test_zero_collective_byte_counters():
    from mxnet_tpu import telemetry
    telemetry.enable()
    try:
        telemetry.reset()
        step = _step(zero=2, grad_accum=2)
        x, y = _data(n=16)
        step(x.reshape(2, 8, 8), y.reshape(2, 8))
        agg = telemetry.counters(aggregate=True)
        ag = agg["zero.all_gather_bytes_total"]
        rs = agg["zero.reduce_scatter_bytes_total"]
        # dense 8x10: weight 80 pad->80, bias 10 pad->12 => 92 f32 = 368 B
        assert ag == 368
        assert rs == 2 * ag  # zero=2: one reduce-scatter per microbatch
    finally:
        telemetry.disable()


def test_record_memory_gauges():
    """memory.* plane: backends that report PJRT memory_stats populate
    per-device gauges; stat-less backends (CPU) stay an empty no-op."""
    from mxnet_tpu import telemetry

    class _Dev:
        def __init__(self, i):
            self.id = i

        def memory_stats(self):
            return {"bytes_in_use": 100 + self.id,
                    "peak_bytes_in_use": 200 + self.id,
                    "bytes_limit": 1000}

    class _NoStats:
        id = 9

        def memory_stats(self):
            return None

    telemetry.enable()
    try:
        telemetry.reset()
        out = telemetry.record_memory([_Dev(0), _Dev(1), _NoStats()])
        assert out == {"0": {"live": 100, "peak": 200, "limit": 1000},
                       "1": {"live": 101, "peak": 201, "limit": 1000}}
        snap = telemetry.snapshot()
        assert snap["gauges"]['memory.bytes_in_use{device="1"}'] == 101
        assert snap["gauges"]['memory.peak_bytes_in_use{device="0"}'] == 200
        # CPU path inside a report: no stats, no crash, empty plane
        assert telemetry.record_memory() == {}
    finally:
        telemetry.disable()


def test_training_telemetry_report_has_memory_plane():
    from mxnet_tpu.telemetry import TrainingTelemetry
    tt = TrainingTelemetry()
    with tt:
        pass
    report = tt.report()
    assert "memory" in report
    assert isinstance(report["memory"], dict)

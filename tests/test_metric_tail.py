"""Metric-class tail (reference: gluon/metric.py BinaryAccuracy :877,
Fbeta :816, MeanPairwiseDistance :1202, MeanCosineSimilarity :1269,
PCC :1595, Torch :1745). Values oracle-checked by hand / numpy."""
import numpy as onp

import mxnet_tpu as mx

M = mx.gluon.metric


def test_binary_accuracy_threshold():
    m = M.BinaryAccuracy(threshold=0.6)
    m.update([mx.np.array([0.0, 1.0, 0.0])],
             [mx.np.array([0.7, 1.0, 0.55])])
    # 0.7>0.6 wrong, 1.0 right, 0.55<=0.6 right  (reference doctest)
    assert abs(m.get()[1] - 2.0 / 3.0) < 1e-9


def test_fbeta_reduces_to_f1_and_weights_recall():
    y = [mx.np.array([1, 1, 0, 0, 1])]
    p = [mx.np.array([1, 0, 0, 1, 1])]  # tp=2 fp=1 fn=1
    f1 = M.F1()
    f1.update(y, p)
    fb1 = M.Fbeta(beta=1)
    fb1.update(y, p)
    assert abs(f1.get()[1] - fb1.get()[1]) < 1e-9
    fb2 = M.Fbeta(beta=2)
    fb2.update(y, p)
    prec = rec = 2.0 / 3.0
    expect = 5 * prec * rec / (4 * prec + rec)
    assert abs(fb2.get()[1] - expect) < 1e-9


def test_mean_pairwise_distance():
    lab = onp.array([[0.0, 0.0], [1.0, 1.0]])
    pred = onp.array([[3.0, 4.0], [1.0, 1.0]])
    m = M.MeanPairwiseDistance()
    m.update([mx.np.array(lab)], [mx.np.array(pred)])
    assert abs(m.get()[1] - (5.0 + 0.0) / 2) < 1e-9  # L2 rows: 5, 0
    # a 1-D pair is ONE sample, not n scalar samples
    m1 = M.MeanPairwiseDistance()
    m1.update([mx.np.array([0.0, 0.0])], [mx.np.array([3.0, 4.0])])
    assert abs(m1.get()[1] - 5.0) < 1e-9


def test_mean_cosine_similarity():
    lab = onp.array([[1.0, 0.0], [1.0, 1.0]])
    pred = onp.array([[0.0, 1.0], [2.0, 2.0]])
    m = M.MeanCosineSimilarity()
    m.update([mx.np.array(lab)], [mx.np.array(pred)])
    assert abs(m.get()[1] - (0.0 + 1.0) / 2) < 1e-6


def test_pcc_binary_matches_mcc():
    rng = onp.random.RandomState(0)
    y = rng.randint(0, 2, 200)
    p = onp.where(rng.rand(200) < 0.8, y, 1 - y)  # 80% agree
    pcc = M.PCC()
    pcc.update([mx.np.array(y)], [mx.np.array(p)])
    mcc = M.MCC()
    mcc.update([mx.np.array(y)], [mx.np.array(p)])
    assert abs(pcc.get()[1] - mcc.get()[1]) < 1e-9


def test_pcc_multiclass_and_incremental():
    y1, p1 = onp.array([0, 1, 2, 2]), onp.array([0, 1, 2, 1])
    y2, p2 = onp.array([2, 0]), onp.array([2, 0])
    inc = M.PCC()
    inc.update([mx.np.array(y1)], [mx.np.array(p1)])
    inc.update([mx.np.array(y2)], [mx.np.array(p2)])
    allatonce = M.PCC()
    allatonce.update([mx.np.array(onp.concatenate([y1, y2]))],
                     [mx.np.array(onp.concatenate([p1, p2]))])
    assert abs(inc.get()[1] - allatonce.get()[1]) < 1e-12
    assert 0.5 < inc.get()[1] <= 1.0


def test_pcc_rejects_negative_ids():
    import pytest
    from mxnet_tpu.base import MXNetError
    m = M.PCC()
    with pytest.raises(MXNetError, match="non-negative"):
        m.update([mx.np.array([-1, 0, 1])], [mx.np.array([0, 0, 1])])


def test_torch_is_loss_alias():
    m = M.Torch()
    m.update(None, [mx.np.array([1.0, 3.0])])
    assert m.get()[0] == "torch" and abs(m.get()[1] - 2.0) < 1e-9


def test_registry_create_names():
    for name in ("binaryaccuracy", "fbeta", "meanpairwisedistance",
                 "meancosinesimilarity", "pcc", "torch"):
        m = M.create(name)
        assert isinstance(m, M.EvalMetric)


def test_hybrid_rnn_cell_aliases():
    from mxnet_tpu.gluon import rnn
    assert rnn.HybridRecurrentCell is rnn.RecurrentCell
    assert rnn.HybridSequentialRNNCell is rnn.SequentialRNNCell

"""Sparse NDArray (row_sparse/CSR) tests.

Reference taxonomy: tests/python/unittest/test_sparse_ndarray.py +
test_sparse_operator.py — construction, tostype round-trips, retain,
sparse dot vs dense oracle, kvstore row_sparse_pull.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.ndarray import sparse


def _rand_dense_rows(rows=8, cols=5, density=0.4, seed=0):
    rng = onp.random.RandomState(seed)
    d = rng.randn(rows, cols).astype("float32")
    mask = rng.rand(rows) < (1 - density)
    d[mask] = 0
    return d


def test_row_sparse_from_dense_roundtrip():
    d = _rand_dense_rows()
    rsp = sparse.row_sparse_array(d)
    assert rsp.stype == "row_sparse"
    onp.testing.assert_array_equal(rsp.asnumpy(), d)
    # indices are exactly the non-zero rows, sorted
    nz = onp.where(d.any(axis=1))[0]
    onp.testing.assert_array_equal(onp.asarray(rsp.indices._data), nz)


def test_row_sparse_from_components():
    data = onp.ones((2, 3), "float32")
    rsp = sparse.row_sparse_array((data, [1, 4]), shape=(6, 3))
    dense = rsp.tostype("default").asnumpy()
    expect = onp.zeros((6, 3), "float32")
    expect[[1, 4]] = 1
    onp.testing.assert_array_equal(dense, expect)


def test_ndarray_tostype():
    d = mx.np.array(_rand_dense_rows())
    rsp = d.tostype("row_sparse")
    assert rsp.stype == "row_sparse"
    onp.testing.assert_array_equal(rsp.asnumpy(), d.asnumpy())
    csr = d.tostype("csr")
    assert csr.stype == "csr"
    onp.testing.assert_array_equal(csr.asnumpy(), d.asnumpy())
    assert d.tostype("default") is d


def test_retain():
    data = onp.arange(9, dtype="float32").reshape(3, 3)
    rsp = sparse.row_sparse_array((data, [0, 2, 5]), shape=(6, 3))
    kept = sparse.retain(rsp, [2, 5])
    onp.testing.assert_array_equal(onp.asarray(kept.indices._data), [2, 5])
    dense = kept.asnumpy()
    assert (dense[0] == 0).all()
    onp.testing.assert_array_equal(dense[2], data[1])
    onp.testing.assert_array_equal(dense[5], data[2])


def test_csr_from_dense_and_dot_oracle():
    rng = onp.random.RandomState(3)
    d = rng.randn(6, 7).astype("float32")
    d[rng.rand(6, 7) < 0.6] = 0
    csr = sparse.csr_matrix(d)
    rhs = rng.randn(7, 4).astype("float32")
    out = sparse.dot(csr, mx.np.array(rhs))
    onp.testing.assert_allclose(out.asnumpy(), d @ rhs, rtol=1e-5, atol=1e-5)
    # transpose_a
    outT = sparse.dot(csr, mx.np.array(rng.randn(6, 2).astype("float32")),
                      transpose_a=True)
    assert outT.shape == (7, 2)


def test_csr_transpose_dot_oracle():
    rng = onp.random.RandomState(4)
    d = rng.randn(5, 6).astype("float32")
    d[rng.rand(5, 6) < 0.5] = 0
    rhs = rng.randn(5, 3).astype("float32")
    csr = sparse.csr_matrix(d)
    out = sparse.dot(csr, mx.np.array(rhs), transpose_a=True)
    onp.testing.assert_allclose(out.asnumpy(), d.T @ rhs, rtol=1e-5,
                                atol=1e-5)


def test_sparse_zeros():
    z = sparse.zeros("row_sparse", (4, 3))
    assert z.asnumpy().sum() == 0 and z.shape == (4, 3)
    zc = sparse.zeros("csr", (4, 3))
    assert zc.asnumpy().sum() == 0


def test_row_sparse_add():
    a = sparse.row_sparse_array((onp.ones((1, 2), "float32"), [1]), shape=(4, 2))
    b = sparse.row_sparse_array((2 * onp.ones((2, 2), "float32"), [1, 3]),
                                shape=(4, 2))
    c = sparse.add(a, b)
    assert c.stype == "row_sparse"
    expect = onp.zeros((4, 2), "float32")
    expect[1] = 3.0
    expect[3] = 2.0
    onp.testing.assert_array_equal(c.asnumpy(), expect)
    # sparse + dense falls back to dense
    dense = sparse.add(a, mx.np.ones((4, 2)))
    assert not isinstance(dense, sparse.BaseSparseNDArray)


def test_kvstore_row_sparse_pull():
    kv = mx.kv.create("device")
    w = onp.arange(12, dtype="float32").reshape(6, 2)
    kv.init("emb", mx.np.array(w))
    rsp = kv.row_sparse_pull("emb", row_ids=mx.np.array([4, 1, 1]))
    onp.testing.assert_array_equal(onp.asarray(rsp.indices._data), [1, 4])
    onp.testing.assert_array_equal(onp.asarray(rsp.data._data),
                                   w[[1, 4]])
    dense = rsp.tostype("default").asnumpy()
    assert (dense[[0, 2, 3, 5]] == 0).all()


def test_parameter_row_sparse_data():
    from mxnet_tpu.gluon import nn
    emb = nn.Embedding(10, 4, sparse_grad=True)
    emb.initialize()
    emb(mx.np.array([[1, 2]], dtype="int32"))
    rsp = emb.weight.row_sparse_data(mx.np.array([2, 7], dtype="int64"))
    assert rsp.stype == "row_sparse"
    onp.testing.assert_array_equal(onp.asarray(rsp.indices._data), [2, 7])
    onp.testing.assert_allclose(
        onp.asarray(rsp.data._data),
        emb.weight.data().asnumpy()[[2, 7]])


def test_sparse_embedding_training_smoke():
    """End-to-end: sparse-marked embedding trains (dense-grad fallback)."""
    from mxnet_tpu import autograd
    from mxnet_tpu.gluon import nn, Trainer
    emb = nn.Embedding(20, 4, sparse_grad=True)
    emb.initialize()
    tr = Trainer(emb.collect_params(), "sgd", {"learning_rate": 0.5},
                 kvstore=None)
    ids = mx.np.array([[1, 3, 1]], dtype="int32")
    before = emb.weight.data().asnumpy().copy()
    with autograd.record():
        loss = (emb(ids) ** 2).sum()
    loss.backward()
    tr.step(1)
    after = emb.weight.data().asnumpy()
    assert not onp.allclose(before[[1, 3]], after[[1, 3]])
    onp.testing.assert_array_equal(before[[0, 2, 4]], after[[0, 2, 4]])


def test_sparse_module_binary_tail():
    """subtract/multiply/divide/empty/array (reference sparse.py
    :1282-1596; ops densify via the storage-fallback dispatch)."""
    import numpy as onp

    from mxnet_tpu.ndarray import sparse

    a = sparse.row_sparse_array(
        (mx.np.ones((2, 3)), mx.np.array([0, 2], dtype="int64")),
        shape=(4, 3))
    b = sparse.row_sparse_array(
        (mx.np.ones((1, 3)) * 2, mx.np.array([2], dtype="int64")),
        shape=(4, 3))
    onp.testing.assert_allclose(sparse.subtract(a, b).asnumpy()[2],
                                [-1, -1, -1])
    onp.testing.assert_allclose(sparse.multiply(a, b).asnumpy()[2],
                                [2, 2, 2])
    d = sparse.divide(b, sparse.row_sparse_array(
        (mx.np.ones((4, 3)) * 4, mx.np.arange(4, dtype="int64")),
        shape=(4, 3)))
    onp.testing.assert_allclose(d.asnumpy()[2], [0.5, 0.5, 0.5])
    e = sparse.empty("row_sparse", (3, 2))
    assert e.asnumpy().sum() == 0 and e.stype == "row_sparse"
    c = sparse.array(a)
    assert c is not a
    onp.testing.assert_allclose(c.asnumpy(), a.asnumpy())
    # dtype override works for both stypes
    assert sparse.array(a, dtype="float16").dtype == onp.float16
    csr = sparse.csr_matrix(onp.eye(3, dtype="float32"))
    assert sparse.array(csr, dtype="float16").dtype == onp.float16
    # dense input is rejected like the reference
    import pytest
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError, match="tostype"):
        sparse.array(onp.ones((2, 2), "float32"))
    assert sparse.divide.__name__ == "divide"

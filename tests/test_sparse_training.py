"""Sparse training end-to-end (reference: row_sparse gradients from
Embedding(sparse_grad=True) -> lazy_update optimizers
(python/mxnet/optimizer/sgd.py lazy_update over
src/operator/optimizer_op.cc SGDUpdateRspImpl) -> kvstore row_sparse
push/pull).
"""
import jax
import jax.numpy as jnp
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd, gluon, nd, optimizer as opt
from mxnet_tpu.ndarray.sparse import (RowSparseNDArray, dedupe_coo,
                                      row_sparse_array)

VOCAB, DIM = 50, 4


def _embed_net(sparse_grad):
    net = gluon.nn.Embedding(VOCAB, DIM, sparse_grad=sparse_grad)
    net.initialize()
    return net


def test_dedupe_coo_sums_duplicates():
    idx = jnp.array([3, 1, 3, 7, 1, 3])
    vals = jnp.arange(6.0).reshape(6, 1)
    uidx, uvals = dedupe_coo(idx, vals, 10)
    assert uidx.shape == (6,)
    dense = jnp.zeros((10, 1)).at[uidx].add(uvals, mode="drop")
    ref = jnp.zeros((10, 1)).at[idx].add(vals)
    onp.testing.assert_allclose(onp.asarray(dense), onp.asarray(ref))
    # padding slots carry the sentinel index and zero values
    assert int(uidx[3]) == 10 and float(jnp.abs(uvals[3:]).sum()) == 0


def test_embedding_sparse_grad_is_row_sparse():
    net = _embed_net(sparse_grad=True)
    x = mx.np.array(onp.array([[1, 3], [3, 7]]), dtype="int32")
    with autograd.record():
        out = net(x)
        loss = (out * out).sum()
    loss.backward()
    g = net.weight.grad()
    assert isinstance(g, RowSparseNDArray)
    assert g.shape == (VOCAB, DIM)
    # matches the dense-path gradient when densified
    dense_net = _embed_net(sparse_grad=False)
    dense_net.weight.set_data(net.weight.data())
    with autograd.record():
        out2 = dense_net(x)
        loss2 = (out2 * out2).sum()
    loss2.backward()
    onp.testing.assert_allclose(g.tostype("default").asnumpy(),
                                dense_net.weight.grad().asnumpy(),
                                rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("optname,kw", [
    ("sgd", dict(learning_rate=0.1, momentum=0.0)),
    ("sgd", dict(learning_rate=0.1, momentum=0.9)),
    ("adam", dict(learning_rate=0.05)),
])
def test_sparse_vs_dense_training_converges_identically(optname, kw):
    """A tiny embedding classifier trained with sparse lazy updates must
    track the dense path exactly.  Every batch touches the same row set:
    on that set lazy and standard stateful updates coincide, and rows
    never touched keep zero state in both (wd=0) — the regime where the
    reference documents bitwise-equal results (sgd.py lazy_update note).
    """
    # each 5x3 batch covers ids 0..9 (some twice); repeated 4 times
    batch = onp.array([[0, 1, 0], [2, 3, 1], [4, 5, 2],
                       [6, 7, 3], [8, 9, 4]], dtype="int32")
    xs = onp.concatenate([batch] * 4, axis=0)
    ys = (xs.sum(-1) % 2).astype("float32")

    def train(sparse):
        net = _embed_net(sparse_grad=sparse)
        onp.random.seed(7)
        net.weight.set_data(mx.np.array(
            onp.random.RandomState(7).randn(VOCAB, DIM).astype("float32")))
        o = opt.create(optname, lazy_update=sparse, wd=0.0, **kw)
        trainer = gluon.Trainer(net.collect_params(), o)
        for i in range(0, 20, 5):
            x = mx.np.array(xs[i:i + 5])
            y = mx.np.array(ys[i:i + 5])
            with autograd.record():
                emb = net(x)
                score = emb.sum(axis=(1, 2))
                loss = ((score - y) ** 2).mean()
            loss.backward()
            trainer.step(1)
        return net.weight.data().asnumpy(), float(loss.asnumpy())

    w_sparse, l_sparse = train(True)
    w_dense, l_dense = train(False)
    onp.testing.assert_allclose(w_sparse, w_dense, rtol=1e-4, atol=1e-5)
    assert l_sparse == pytest.approx(l_dense, rel=1e-4)


def test_lazy_update_touches_only_nnz_rows():
    """O(nnz) assertion: jaxpr of the lazy SGD step must contain no
    elementwise math over the full (VOCAB, DIM) table — only gather,
    row-block math and scatter."""
    big_vocab = 10_000
    sgd = opt.create("sgd", learning_rate=0.1, momentum=0.9, lazy_update=True)
    w = jnp.zeros((big_vocab, DIM))
    from mxnet_tpu.numpy.multiarray import _wrap
    state = _wrap(jnp.zeros((big_vocab, DIM)))
    idx = jnp.array([5, 17, 123], dtype=jnp.int32)
    vals = jnp.ones((3, DIM))
    rsp = RowSparseNDArray(_wrap(vals), _wrap(idx), (big_vocab, DIM))

    jaxpr = jax.make_jaxpr(
        lambda w_, g_, m_: sgd._lazy_update_impl(
            w_, RowSparseNDArray(_wrap(g_), _wrap(idx), (big_vocab, DIM)),
            _wrap(m_), 0.1, 0.0)[0])(w, vals, state._data)
    full_size = big_vocab * DIM
    for eqn in jaxpr.jaxpr.eqns:
        if eqn.primitive.name in ("scatter", "scatter-set", "gather"):
            continue  # the O(nnz)-indexed table accesses themselves
        for v in eqn.outvars:
            size = 1
            for s in getattr(v.aval, "shape", ()):
                size *= s
            assert size < full_size, (
                f"{eqn.primitive.name} materializes a full-table temp "
                f"{v.aval.shape} — lazy update must be O(nnz)")

    # and the weight values behave: only idx rows change
    new_w, _ = sgd._lazy_update_impl(w + 1.0, rsp, state, 0.1, 0.0)
    changed = onp.nonzero(onp.abs(onp.asarray(new_w) - 1.0).sum(-1))[0]
    onp.testing.assert_array_equal(changed, [5, 17, 123])


def test_kvstore_row_sparse_training_loop():
    """update_on_kvstore-style loop: push row_sparse grads, optimizer runs
    on the store (lazy), row_sparse_pull fetches only needed rows."""
    kv = mx.kv.create("local")
    weight = mx.np.array(onp.random.RandomState(3).randn(VOCAB, DIM)
                         .astype("float32"))
    kv.init("emb", weight)
    kv.set_optimizer(opt.create("sgd", learning_rate=0.5, momentum=0.9,
                                lazy_update=True))
    w_ref = weight.asnumpy().copy()

    for step in range(3):
        ids = onp.array([2, 9, 2, 31])
        vals = onp.random.RandomState(step).randn(4, DIM).astype("float32")
        uidx, uvals = dedupe_coo(jnp.asarray(ids), jnp.asarray(vals), VOCAB)
        from mxnet_tpu.numpy.multiarray import _wrap
        g = RowSparseNDArray(_wrap(uvals), _wrap(uidx), (VOCAB, DIM))
        kv.push("emb", g)

    out = mx.np.zeros((VOCAB, DIM))
    kv.pull("emb", out=out)
    new_w = out.asnumpy()
    untouched = [i for i in range(VOCAB) if i not in (2, 9, 31)]
    onp.testing.assert_allclose(new_w[untouched], w_ref[untouched])
    assert onp.abs(new_w[[2, 9, 31]] - w_ref[[2, 9, 31]]).sum() > 0

    rows = kv.row_sparse_pull("emb", row_ids=mx.np.array([2, 31]))
    assert isinstance(rows, RowSparseNDArray)
    onp.testing.assert_allclose(rows.tostype("default").asnumpy()[[2, 31]],
                                new_w[[2, 31]], rtol=1e-6)

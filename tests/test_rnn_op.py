"""Fused npx.rnn value oracles vs torch (the cuDNN semantics the reference
wraps in src/operator/rnn-inl.h).

torch.nn.LSTM/GRU use the same cuDNN gate orders (LSTM [i,f,g,o], GRU
[r,z,n] with n = tanh(Wx x + bx + r*(Wh h + bh))), so weight-for-weight
agreement with torch locks the reference parity of the packed-parameter
layout AND the cell math in one shot. Round-4 gap-fill: npx.rnn previously
had only gluon-level convergence coverage.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np, npx

torch = pytest.importorskip("torch")

RNG = onp.random.RandomState(0)


def _pack_params(t_rnn, layers, ndir):
    """Flatten torch RNN weights into npx.rnn's cuDNN-style vector:
    all [Wx, Wh] layer-major first, then all [bx, bh]."""
    ws, bs = [], []
    for layer in range(layers):
        for d in range(ndir):
            sfx = f"_l{layer}{'_reverse' if d else ''}"
            ws.append(getattr(t_rnn, f"weight_ih{sfx}").detach().numpy().ravel())
            ws.append(getattr(t_rnn, f"weight_hh{sfx}").detach().numpy().ravel())
            bs.append(getattr(t_rnn, f"bias_ih{sfx}").detach().numpy().ravel())
            bs.append(getattr(t_rnn, f"bias_hh{sfx}").detach().numpy().ravel())
    return onp.concatenate(ws + bs).astype(onp.float32)


@pytest.mark.parametrize("bidirectional", [False, True])
@pytest.mark.parametrize("layers", [1, 2])
def test_lstm_matches_torch(bidirectional, layers):
    seq, batch, insz, hid = 5, 3, 4, 6
    ndir = 2 if bidirectional else 1
    t_rnn = torch.nn.LSTM(insz, hid, num_layers=layers,
                          bidirectional=bidirectional)
    x = RNG.randn(seq, batch, insz).astype(onp.float32)
    h0 = RNG.randn(layers * ndir, batch, hid).astype(onp.float32)
    c0 = RNG.randn(layers * ndir, batch, hid).astype(onp.float32)
    with torch.no_grad():
        t_out, (t_h, t_c) = t_rnn(torch.from_numpy(x),
                                  (torch.from_numpy(h0),
                                   torch.from_numpy(c0)))
    params = _pack_params(t_rnn, layers, ndir)
    out, h, c = npx.rnn(np.array(x), np.array(params), np.array(h0),
                        np.array(c0), mode="lstm", state_size=hid,
                        num_layers=layers, bidirectional=bidirectional)
    onp.testing.assert_allclose(out.asnumpy(), t_out.numpy(), rtol=1e-4,
                                atol=1e-5)
    onp.testing.assert_allclose(h.asnumpy(), t_h.numpy(), rtol=1e-4,
                                atol=1e-5)
    onp.testing.assert_allclose(c.asnumpy(), t_c.numpy(), rtol=1e-4,
                                atol=1e-5)


@pytest.mark.parametrize("mode,tcls", [("gru", torch.nn.GRU),
                                       ("rnn_tanh", torch.nn.RNN)])
def test_gru_rnn_match_torch(mode, tcls):
    seq, batch, insz, hid = 4, 2, 3, 5
    t_rnn = tcls(insz, hid, num_layers=1)
    x = RNG.randn(seq, batch, insz).astype(onp.float32)
    h0 = RNG.randn(1, batch, hid).astype(onp.float32)
    with torch.no_grad():
        t_out, t_h = t_rnn(torch.from_numpy(x), torch.from_numpy(h0))
    params = _pack_params(t_rnn, 1, 1)
    out, h = npx.rnn(np.array(x), np.array(params), np.array(h0),
                     mode=mode, state_size=hid, num_layers=1)
    onp.testing.assert_allclose(out.asnumpy(), t_out.numpy(), rtol=1e-4,
                                atol=1e-5)
    onp.testing.assert_allclose(h.asnumpy(), t_h.numpy(), rtol=1e-4,
                                atol=1e-5)


def test_rnn_gradients_flow():
    seq, batch, insz, hid = 3, 2, 3, 4
    nparams = 4 * hid * insz + 4 * hid * hid + 2 * 4 * hid
    params = np.array(RNG.randn(nparams).astype(onp.float32) * 0.2)
    params.attach_grad()
    x = np.array(RNG.randn(seq, batch, insz).astype(onp.float32))
    h0 = np.zeros((1, batch, hid))
    c0 = np.zeros((1, batch, hid))
    with mx.autograd.record():
        out, h, c = npx.rnn(x, params, h0, c0, mode="lstm",
                            state_size=hid, num_layers=1)
        loss = (out * out).sum()
    loss.backward()
    assert float(np.abs(params.grad).sum()) > 0

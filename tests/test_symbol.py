"""mx.sym tests (reference strategy: tests/python/unittest/test_symbol.py:
composition, list_arguments, infer_shape, eval-vs-imperative equality,
json round-trip, executor forward/backward)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import numpy as np
from mxnet_tpu import symbol as sym


def test_compose_and_eval_matches_imperative():
    a = sym.var("a")
    b = sym.var("b")
    c = (a + b) * a - 2.0 / (b + 1.0)
    av = np.array(onp.random.rand(3, 4).astype("float32"))
    bv = np.array(onp.random.rand(3, 4).astype("float32"))
    out = c.eval(a=av, b=bv)[0]
    want = (av + bv) * av - 2.0 / (bv + 1.0)
    onp.testing.assert_allclose(out.asnumpy(), want.asnumpy(), rtol=1e-6)


def test_list_arguments_and_ops():
    x = sym.var("x")
    w = sym.var("w")
    y = sym.dot(x, w)
    z = sym.tanh(y)
    assert z.list_arguments() == ["x", "w"]
    xv = np.array(onp.random.rand(2, 3).astype("float32"))
    wv = np.array(onp.random.rand(3, 5).astype("float32"))
    out = z.eval(x=xv, w=wv)[0]
    onp.testing.assert_allclose(out.asnumpy(),
                                onp.tanh(xv.asnumpy() @ wv.asnumpy()),
                                atol=1e-5)


def test_npx_ops_symbolic():
    x = sym.var("x")
    y = sym.softmax(x, axis=-1)
    xv = np.array(onp.random.rand(2, 5).astype("float32"))
    out = y.eval(x=xv)[0].asnumpy()
    onp.testing.assert_allclose(out.sum(-1), onp.ones(2), atol=1e-6)


def test_infer_shape():
    x = sym.var("x")
    w = sym.var("w")
    z = sym.dot(x, w)
    arg_shapes, out_shapes, _ = z.infer_shape(x=(2, 3), w=(3, 7))
    assert out_shapes == [(2, 7)]
    assert arg_shapes == [(2, 3), (3, 7)]


def test_json_roundtrip():
    a = sym.var("a")
    b = sym.var("b")
    c = sym.maximum(a * 2.0, b)
    js = c.tojson()
    c2 = sym.load_json(js)
    assert c2.list_arguments() == c.list_arguments()
    av = np.array(onp.random.rand(4).astype("float32"))
    bv = np.array(onp.random.rand(4).astype("float32"))
    onp.testing.assert_allclose(c.eval(a=av, b=bv)[0].asnumpy(),
                                c2.eval(a=av, b=bv)[0].asnumpy())


def test_executor_forward_backward():
    x = sym.var("x")
    w = sym.var("w")
    loss = sym.sum(sym.square(sym.dot(x, w)))
    xv = np.array(onp.random.rand(2, 3).astype("float32"))
    wv = np.array(onp.random.rand(3, 1).astype("float32"))
    exe = loss.bind(args={"x": xv, "w": wv})
    (out,) = exe.forward(is_train=True)
    exe.backward()
    # oracle: d/dw sum((xw)^2) = 2 x^T (x w)
    xw = xv.asnumpy() @ wv.asnumpy()
    onp.testing.assert_allclose(exe.grad_dict["w"].asnumpy(),
                                2 * xv.asnumpy().T @ xw, rtol=1e-4)


def test_group_outputs():
    a = sym.var("a")
    g = sym.Group([a + 1.0, a * 3.0])
    av = np.array(onp.ones(2, dtype="float32"))
    o1, o2 = g.eval(a=av)
    onp.testing.assert_allclose(o1.asnumpy(), [2, 2])
    onp.testing.assert_allclose(o2.asnumpy(), [3, 3])


def test_json_roundtrip_with_ndarray_constant():
    """sym + mx.np.array(...) constants must serialize by value."""
    a = sym.Variable("a")
    c = a + mx.np.array([1.0, 2.0, 3.0])
    js = c.tojson()
    c2 = sym.load_json(js)
    x = mx.np.array([10.0, 20.0, 30.0])
    onp.testing.assert_allclose(c2.eval(a=x)[0].asnumpy(),
                                [11.0, 22.0, 33.0])


def test_group_json_roundtrip():
    """Group serializes as multiple heads and reloads as a Group."""
    a = sym.Variable("a")
    b = sym.Variable("b")
    g = sym.Group([a + b, a * b])
    js = g.tojson()
    g2 = sym.load_json(js)
    x = mx.np.array([2.0, 3.0])
    y = mx.np.array([4.0, 5.0])
    outs = g2.eval(a=x, b=y)
    onp.testing.assert_allclose(outs[0].asnumpy(), [6.0, 8.0])
    onp.testing.assert_allclose(outs[1].asnumpy(), [8.0, 15.0])


def test_symbol_optimize_for_bf16():
    import numpy as onp
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    net = mx.sym.matmul(a, b)
    lp = net.optimize_for("bf16")
    xa = mx.np.array(onp.random.RandomState(0).rand(4, 4).astype("float32"))
    xb = mx.np.array(onp.random.RandomState(1).rand(4, 4).astype("float32"))
    got = lp.eval(a=xa, b=xb)[0]
    assert str(got.dtype) == "bfloat16"
    assert net.optimize_for("xla") is net
    import pytest
    from mxnet_tpu.base import MXNetError
    with pytest.raises(MXNetError):
        net.optimize_for("tensorrt")


def test_infer_type_and_partial():
    """Reference: symbol.py infer_type:898 / infer_type_partial:967."""
    a, b = sym.Variable("a"), sym.Variable("b")
    e = sym.Cast(a, dtype="float16") + b
    arg_t, out_t, aux_t = e.infer_type(a="float16", b="float32")
    assert out_t == [onp.float32] and aux_t == []
    _, out_t, _ = e.infer_type(a="float16", b="float16")
    assert out_t == [onp.float16]
    # defaults are float32 like the reference
    _, out_t, _ = (a + b).infer_type()
    assert out_t == [onp.float32]
    # comparison -> bool; argmax -> int
    _, out_t, _ = sym.argmax(a).infer_type_partial()
    assert out_t[0] == onp.int64
    _, out_t, _ = e.infer_type_partial(a="float16")
    assert out_t == [onp.float16]


def test_attr_mutation_surface():
    """Reference: _set_attr:665 / list_attr:611 / attr_dict:634."""
    a = sym.Variable("a")
    d = a * 2
    a._set_attr(__lr_mult__="2.0", __wd_mult__="0.5")
    assert a.attr("__lr_mult__") == "2.0"
    assert a.list_attr() == {"__lr_mult__": "2.0", "__wd_mult__": "0.5"}
    assert d.attr_dict()["a"]["__wd_mult__"] == "0.5"
    with pytest.raises(mx.MXNetError):
        a._set_attr(x=1)  # non-string rejected, like MXSymbolSetAttr


def test_symbol_gradient_eval():
    """gradient(): declared-but-unimplemented in the reference
    (symbol.py:1879); real here via jax.grad."""
    x, w = sym.Variable("x"), sym.Variable("w")
    loss = sym.sum((x * w) ** 2)
    g = loss.gradient(["x", "w"])
    xv = mx.np.array(onp.array([1.0, 2.0], onp.float32))
    wv = mx.np.array(onp.array([3.0, -1.0], onp.float32))
    gx, gw = g.eval(x=xv, w=wv)
    onp.testing.assert_allclose(gx.asnumpy(), 2 * (xv * wv * wv).asnumpy())
    onp.testing.assert_allclose(gw.asnumpy(), 2 * (xv * xv * wv).asnumpy())
    with pytest.raises(mx.MXNetError):
        (x * 2).gradient("nope")


def test_attrs_survive_json_roundtrip():
    a = sym.Variable("a")
    a._set_attr(__lr_mult__="2.0", ctx_group="dev1")  # non-dunder too
    d = a * 3 + 1
    d2 = sym.load_json(d.tojson())
    assert d2.attr_dict().get("a", {}).get("__lr_mult__") == "2.0"
    assert d2.attr_dict().get("a", {}).get("ctx_group") == "dev1"
    xv = mx.np.array(onp.array([1.0, 2.0], onp.float32))
    r2, r1 = d2.eval(a=xv), d.eval(a=xv)
    r2 = r2[0] if isinstance(r2, list) else r2
    r1 = r1[0] if isinstance(r1, list) else r1
    onp.testing.assert_allclose(r2.asnumpy(), r1.asnumpy())


def test_executor_surface_tail():
    """arg_arrays/grad_arrays/output_dict/copy_params_from
    (reference: executor.py:232-393)."""
    import numpy as onp
    import pytest

    a = sym.Variable("a")
    b = sym.Variable("b")
    c = (a * b).as_np_ndarray() if hasattr(a * b, "as_np_ndarray") else a * b
    args = {"a": np.array([1.0, 2.0]), "b": np.array([3.0, 4.0])}
    ex = c.bind(None, args) if hasattr(c, "bind") else None
    if ex is None:
        from mxnet_tpu.executor import Executor
        ex = Executor(c, args)
    ex.forward(is_train=True)
    ex.backward()
    assert len(ex.arg_arrays) == 2
    assert set(ex.output_dict) == set(c.list_outputs())
    assert ex.get_optimized_symbol() is c
    assert ex.aux_dict == {}
    g = ex.grad_arrays
    assert len(g) == 2 and g[0] is not None
    ex.copy_params_from({"a": np.array([5.0, 6.0])})
    onp.testing.assert_allclose(ex.arg_dict["a"].asnumpy(), [5.0, 6.0])
    with pytest.raises(ValueError):
        ex.copy_params_from({"zz": np.array([1.0])})
    ex.copy_params_from({"zz": np.array([1.0])}, allow_extra_params=True)


def test_symbol_fluent_methods():
    """Fluent op methods (reference symbol.py generates ~80 per-op
    methods: s.abs().argmax() etc.) resolve through the shared table."""
    import pytest

    x = mx.sym.var("x")
    out = x.abs().argmax(axis=0).eval(x=mx.np.array([-5.0, 1.0, 2.0]))[0]
    assert int(out.asnumpy()) == 0
    sq = x.square().sum()
    assert float(sq.eval(x=mx.np.array([2.0, 3.0]))[0].asnumpy()) == 13.0
    assert x.astype("float16").eval(x=mx.np.ones(2))[0].dtype == onp.float16
    assert x.as_np_ndarray() is x
    # detach blocks gradient flow (matches eager ndarray.detach)
    loss = (x.detach() * x).sum()
    g = loss.gradient("x").eval(x=mx.np.array([3.0]))[0]
    assert float(g.asnumpy()[0]) == 3.0  # d/dx [c*x], not 2x
    with pytest.raises(AttributeError, match="abstract"):
        x.asnumpy()
    with pytest.raises(AttributeError):
        x.not_an_op()
    # fluent and module spellings build identical graphs
    a = x.exp().tojson()
    b = mx.sym.exp(x).tojson()
    import json as _json
    na = _json.loads(a)["nodes"][-1]["op"]
    nb = _json.loads(b)["nodes"][-1]["op"]
    assert na == nb == "exp"

"""mx.stream — deterministic sharded streaming that survives host loss
and elastic dp resizes.

Oracles: the exactly-once epoch multiset (union of served record ids
across hosts, restarts and take-overs == the epoch's ids, multiplicity
1); bitwise batch parity between an uninterrupted epoch and a
cursor-resumed one; a real 2-process host-loss drill via subprocess
(tests/stream_worker.py) where the victim's un-checkpointed progress is
legitimately re-served by the survivor.

Chaos spec literals exercised here: "stream.torn_record:prob=1,times=3",
"stream.torn_record:prob=1,times=1", "stream.shard_unreadable:prob=1,times=3",
"stream.shard_unreadable:prob=1,times=1".
"""
import glob
import json
import os
import struct
import subprocess
import sys

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import blackbox, config, insight, recordio, stream, telemetry
from mxnet_tpu.base import MXNetError
from mxnet_tpu.fleet import FleetSupervisor
from mxnet_tpu.gluon.data import DataLoader
from mxnet_tpu.parallel.mesh import MeshConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

N_RECORDS = 53
N_SHARDS = 4


@pytest.fixture(autouse=True)
def _clean_stream_state():
    mx.fault.clear()
    mx.fault.reset_stats()
    yield
    mx.fault.clear()
    mx.fault.reset_stats()


@pytest.fixture
def metrics():
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.disable()


@pytest.fixture
def shards(tmp_path):
    d = str(tmp_path / "data")
    with stream.ShardWriter(d, N_SHARDS) as w:
        for g in range(N_RECORDS):
            w.append(stream.pack_sample(
                onp.full((3,), g, dtype=onp.float32), onp.int32(g % 5)))
    return d


def _ids(batches):
    return [g for b in batches for g in b]


# -- shard format + manifest -------------------------------------------------

def test_shard_writer_round_trips_through_manifest(shards):
    m = stream.ShardManifest.load(shards)
    assert m.num_shards == N_SHARDS and m.total_records == N_RECORDS
    # round-robin: record g lives in shard g % num_shards
    assert [m.records(s) for s in range(N_SHARDS)] == [14, 13, 13, 13]
    ds = stream.StreamDataset(m, transform=stream.unpack_sample)
    assert len(ds) == N_RECORDS
    for g in (0, 1, N_SHARDS, N_RECORDS - 1):
        x, y = ds[g]
        assert x[0] == float(g) and int(y) == g % 5
    report = stream.validate_manifest(shards)
    assert report["ok"] and report["records"] == N_RECORDS


def test_record_envelope_checksum_catches_a_flipped_byte():
    buf = stream.encode_record(7, b"payload bytes")
    assert stream.decode_record(buf) == (7, b"payload bytes")
    flipped = buf[:-3] + bytes([buf[-3] ^ 0xFF]) + buf[-2:]
    with pytest.raises(stream.CorruptRecord) as ei:
        stream.decode_record(flipped, shard="s0")
    assert ei.value.kind == "checksum" and ei.value.shard == "s0"


def test_validate_manifest_reports_on_disk_corruption(shards):
    rec = stream.ShardManifest.load(shards).rec_path(1)
    with open(rec, "r+b") as f:
        f.seek(os.path.getsize(rec) // 2)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    report = stream.validate_manifest(shards)
    assert not report["ok"] and report["errors"]
    assert "shard-00001" in report["errors"][0]


# -- recordio structured truncation (the satellite) --------------------------

def test_recordio_torn_tail_is_structured_and_resumable(tmp_path):
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(b"A" * 100)
    w.close()
    with open(path, "r+b") as f:
        f.truncate(50)                     # mid-payload: a torn tail
    r = recordio.MXRecordIO(path, "r")
    with pytest.raises(recordio.RecordIOCorrupt) as ei:
        r.read()
    assert ei.value.kind == "torn_tail" and ei.value.resumable
    assert ei.value.uri == path and ei.value.offset == 0
    r.close()


def test_recordio_torn_header_and_bad_magic(tmp_path):
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    w.write(b"first")
    w.write(b"second")
    w.close()
    first_len = 8 + len(b"first") + (-len(b"first") % 4)
    with open(path, "r+b") as f:
        f.truncate(first_len + 4)          # second record: header cut short
    r = recordio.MXRecordIO(path, "r")
    assert r.read() == b"first"            # the intact prefix still reads
    with pytest.raises(recordio.RecordIOCorrupt) as ei:
        r.read()
    assert ei.value.kind == "torn_tail" and ei.value.offset == first_len
    r.close()
    with open(path, "r+b") as f:           # now stomp the first magic
        f.write(struct.pack("<I", 0xdeadbeef))
    r = recordio.MXRecordIO(path, "r")
    with pytest.raises(recordio.RecordIOCorrupt) as ei:
        r.read()
    assert ei.value.kind == "bad_magic" and not ei.value.resumable
    r.close()


# -- epoch plan determinism --------------------------------------------------

def test_epoch_plan_is_deterministic_and_partitions_by_dp(shards):
    a = stream.EpochPlan(shards, seed=3, epoch=1)
    b = stream.EpochPlan(shards, seed=3, epoch=1)
    assert list(a.shard_order) == list(b.shard_order)
    assert [list(a.shard_records(s)) for s in range(N_SHARDS)] == \
        [list(b.shard_records(s)) for s in range(N_SHARDS)]
    for dp in (1, 2, 3):
        parts = [a.host_shards(r, dp) for r in range(dp)]
        flat = [s for p in parts for s in p]
        assert sorted(flat) == list(range(N_SHARDS))   # disjoint + complete
    # every record id appears exactly once across the shard orders
    all_gids = [g for s in range(N_SHARDS) for g in a.shard_records(s)]
    assert sorted(all_gids) == list(range(N_RECORDS))


def test_epoch_plan_reshuffles_across_epochs_and_seeds(shards):
    e1 = stream.EpochPlan(shards, seed=3, epoch=1)
    e2 = stream.EpochPlan(shards, seed=3, epoch=2)
    s9 = stream.EpochPlan(shards, seed=9, epoch=1)
    assert list(e1.shard_records(0)) != list(e2.shard_records(0))
    assert list(e1.shard_records(0)) != list(s9.shard_records(0))


# -- the sampler: exactly-once, cursors, elastic resume ----------------------

def test_single_host_epoch_is_exactly_once_and_reproducible(shards):
    a = list(iter(stream.StreamSampler(shards, batch_size=4, seed=11)))
    b = list(iter(stream.StreamSampler(shards, batch_size=4, seed=11)))
    assert a == b
    assert sorted(_ids(a)) == list(range(N_RECORDS))


def test_bitwise_resume_mid_epoch(shards):
    full = list(iter(stream.StreamSampler(shards, batch_size=4, seed=11)))
    s = stream.StreamSampler(shards, batch_size=4, seed=11)
    it = iter(s)
    head = [next(it) for _ in range(3)]
    st = s.state_dict(cursor=3)
    assert st["cursor"] == 3 and st["consumed"] == 12
    s2 = stream.StreamSampler(shards, batch_size=4, seed=11)
    s2.load_state_dict(st)
    assert head + list(iter(s2)) == full


def test_len_reflects_pending_resume(shards):
    s = stream.StreamSampler(shards, batch_size=4, seed=11)
    total = len(s)
    assert total == (N_RECORDS + 3) // 4
    it = iter(s)
    for _ in range(3):
        next(it)
    s2 = stream.StreamSampler(shards, batch_size=4, seed=11)
    s2.load_state_dict(s.state_dict(cursor=3))
    assert len(s2) == total - 3


def test_load_state_dict_rejects_mismatched_geometry(shards):
    s = stream.StreamSampler(shards, batch_size=4, seed=11)
    st = s.state_dict()
    other_bs = stream.StreamSampler(shards, batch_size=8, seed=11)
    with pytest.raises(MXNetError, match="batch_size"):
        other_bs.load_state_dict(st)
    other_seed = stream.StreamSampler(shards, batch_size=4, seed=12)
    with pytest.raises(MXNetError, match="seed"):
        other_seed.load_state_dict(st)


def test_dataloader_resume_is_bitwise(shards):
    def loader():
        ds = stream.StreamDataset(shards)
        samp = stream.StreamSampler(shards, batch_size=4, seed=5)
        return DataLoader(ds, batch_sampler=samp, num_workers=0,
                          batchify_fn=lambda x: x)
    full = list(loader())
    l1 = loader()
    it = iter(l1)
    head = [next(it) for _ in range(3)]
    st = l1.state_dict()
    assert st["cursor"] == 3               # consumer-side, not prefetch-side
    l2 = loader()
    l2.load_state_dict(st)
    assert head + list(l2) == full


def test_dataloader_thread_pool_matches_serial(shards):
    ds = stream.StreamDataset(shards)
    serial = list(DataLoader(
        ds, batch_sampler=stream.StreamSampler(shards, batch_size=4, seed=5),
        num_workers=0, batchify_fn=lambda x: x))
    threaded = list(DataLoader(
        ds, batch_sampler=stream.StreamSampler(shards, batch_size=4, seed=5),
        num_workers=2, thread_pool=True, batchify_fn=lambda x: x))
    assert threaded == serial


# -- host loss + elastic dp: the exactly-once take-over ----------------------

def test_dp_partition_is_disjoint_and_complete(shards):
    served = []
    for rank in range(2):
        s = stream.StreamSampler(shards, batch_size=4, seed=7, dp=2,
                                 rank=rank)
        served.extend(_ids(iter(s)))
    assert sorted(served) == list(range(N_RECORDS))


def test_take_over_resumes_from_published_cursor(shards, tmp_path, metrics):
    d = str(tmp_path / "cursors")
    dead = stream.StreamSampler(shards, batch_size=4, seed=7, dp=2, rank=1,
                                cursor_dir=d)
    it = iter(dead)
    dead_served = [next(it) for _ in range(2)]
    dead.publish_cursor(cursor=2)          # then the host dies

    surv = stream.StreamSampler(shards, batch_size=4, seed=7, dp=2, rank=0,
                                cursor_dir=d)
    surv_batches = list(iter(surv))        # own share done (partial tail ok)
    adopted = surv.take_over_host(1, survivors=[0])
    assert adopted > 0
    surv.load_state_dict(surv.state_dict(cursor=len(surv_batches)))
    takeover_batches = list(iter(surv))
    all_ids = _ids(dead_served) + _ids(surv_batches) + _ids(takeover_batches)
    assert sorted(all_ids) == list(range(N_RECORDS))
    assert telemetry.counters()["stream.shards_reassigned_total"] == adopted


def test_take_over_without_cursor_reserves_full_share(shards, tmp_path):
    d = str(tmp_path / "cursors")      # empty: the host died pre-checkpoint
    surv = stream.StreamSampler(shards, batch_size=4, seed=7, dp=2, rank=0,
                                cursor_dir=d)
    surv_batches = list(iter(surv))
    assert surv.take_over_host(1, survivors=[0]) > 0
    surv.load_state_dict(surv.state_dict(cursor=len(surv_batches)))
    all_ids = _ids(surv_batches) + _ids(iter(surv))
    assert sorted(all_ids) == list(range(N_RECORDS))


def test_take_over_reentry_is_a_no_op(shards, tmp_path):
    d = str(tmp_path / "cursors")
    surv = stream.StreamSampler(shards, batch_size=4, seed=7, dp=2, rank=0,
                                cursor_dir=d)
    list(iter(surv))
    assert surv.take_over_host(1, survivors=[0]) > 0
    assert surv.take_over_host(1, survivors=[0]) == 0    # exactly once


def test_take_over_splits_deterministically_across_survivors(shards):
    samplers = [stream.StreamSampler(shards, batch_size=4, seed=7, dp=3,
                                     rank=r) for r in range(3)]
    served = []
    for s in samplers:
        served.extend(_ids(iter(s)))
    # host 2 dies pre-checkpoint; survivors 0 and 1 each run the same
    # deterministic split — no shard lands on both, none is dropped
    dead_share = _ids(iter(stream.StreamSampler(shards, batch_size=4, seed=7,
                                                dp=3, rank=2)))
    again = []
    for s in samplers[:2]:
        n = s.take_over_host(2, survivors=[0, 1])
        assert n >= 0
        s.load_state_dict(s.state_dict())
        again.extend(_ids(iter(s)))
    assert sorted(again) == sorted(dead_share)


def test_resume_at_different_dp_size(shards, tmp_path):
    """The elastic resize: a dp=2 run checkpoints, the restart runs
    dp=1 — the new world adopts both cursors and finishes the SAME
    epoch, every record exactly once."""
    d = str(tmp_path / "cursors")
    world, cursors = [], {}
    for rank in range(2):
        s = stream.StreamSampler(shards, batch_size=4, seed=7, dp=2,
                                 rank=rank, cursor_dir=d)
        it = iter(s)
        world.extend(_ids([next(it) for _ in range(2)]))
        s.publish_cursor(cursor=2)
        cursors[rank] = s.state_dict(cursor=2)

    # restart: ONE host left, resuming host 0's cursor and adopting
    # host 1's published one
    s0 = stream.StreamSampler(shards, batch_size=4, seed=7, dp=2, rank=0,
                              cursor_dir=d)
    s0.load_state_dict(cursors[0])
    world.extend(_ids(iter(s0)))
    assert s0.take_over_host(1, survivors=[0]) > 0
    s0.load_state_dict(s0.state_dict())
    world.extend(_ids(iter(s0)))
    assert sorted(world) == list(range(N_RECORDS))


def test_fleet_supervisor_reassigns_dead_host_shards(shards, tmp_path,
                                                     metrics):
    class _FakeStep:
        mesh_config = MeshConfig(dp=2)

    d = str(tmp_path / "leases")
    dead = stream.StreamSampler(shards, batch_size=4, seed=7, dp=2, rank=1,
                                cursor_dir=d)
    it = iter(dead)
    next(it)
    dead.publish_cursor(cursor=1)

    surv = stream.StreamSampler(shards, batch_size=4, seed=7, dp=2, rank=0,
                                cursor_dir=d)
    iter(surv).__next__()                  # epoch live
    prev = config.set("fleet.lease_dir", d)
    try:
        sup = FleetSupervisor(_FakeStep(), mx.resilience.TrainState(),
                              n_hosts=2, min_dp=2, stream=surv)
        sup.lose_host(1)                   # parks the mesh, moves the data
    finally:
        config.set("fleet.lease_dir", prev)
    assert telemetry.counters().get("stream.shards_reassigned_total", 0) > 0
    assert sup.parked                      # min_dp floor: compute parked,
    #                                        but the shards are not lost


# -- corrupt-record drills ---------------------------------------------------

def test_corrupt_skip_policy_counts_and_shrinks(shards, metrics):
    prev = config.set("stream.on_corrupt", "skip")
    mx.fault.configure("stream.torn_record:prob=1,times=3")
    try:
        ds = stream.StreamDataset(shards)
        samp = stream.StreamSampler(shards, batch_size=4, seed=5)
        served = []
        for batch in samp:
            served.extend(ds.sample_batch(batch))
    finally:
        config.set("stream.on_corrupt", prev)
    counters = telemetry.counters()
    assert counters["stream.records_skipped_total"] == 3
    assert len(served) == N_RECORDS - 3
    assert counters["stream.records_served_total"] == N_RECORDS - 3
    assert mx.fault.stats().get("injected.stream.torn_record") == 3


def test_corrupt_raise_policy_lands_in_blackbox_bundle(shards, tmp_path):
    bdir = str(tmp_path / "bundles")
    prev = config.set("blackbox.dir", bdir)
    blackbox.enable()
    mx.fault.configure("stream.torn_record:prob=1,times=1")
    try:
        ds = stream.StreamDataset(shards)
        samp = stream.StreamSampler(shards, batch_size=4, seed=5)
        with pytest.raises(stream.CorruptRecord) as ei:
            for batch in samp:
                ds.sample_batch(batch)
        assert ei.value.kind == "checksum" and ei.value.record_id is not None
        path = blackbox.dump(trigger="exception", reason="corrupt record",
                             exc=ei.value)
        doc = blackbox.read_bundle(path)
        assert doc["exception"]["type"] == "CorruptRecord"
        assert "checksum" in doc["exception"]["message"]
    finally:
        blackbox.disable()
        config.set("blackbox.dir", prev)


def test_getitem_always_raises_on_corruption(shards):
    prev = config.set("stream.on_corrupt", "skip")   # policy is batch-only
    mx.fault.configure("stream.torn_record:prob=1,times=1")
    try:
        with pytest.raises(stream.CorruptRecord):
            stream.StreamDataset(shards)[0]
    finally:
        config.set("stream.on_corrupt", prev)


# -- shard-open failures: bounded retry, structured escalation ---------------

def test_shard_unreadable_escalates_after_retry_budget(shards, metrics):
    prev = config.set("stream.open_backoff", 0.001)
    mx.fault.configure("stream.shard_unreadable:prob=1,times=3")
    try:
        ds = stream.StreamDataset(shards)
        with pytest.raises(stream.ShardUnreadable) as ei:
            ds[0]                          # never hangs: bounded attempts
    finally:
        config.set("stream.open_backoff", prev)
    e = ei.value
    assert isinstance(e, mx.resilience.WorkerLost)   # supervisor-dispatchable
    assert e.op == "shard_open" and e.attempts == 3
    assert telemetry.counters()["stream.open_retries_total"] == 2
    assert mx.fault.stats().get("stream.shard_lost") == 1


def test_shard_open_retry_recovers_from_transient_failure(shards, metrics):
    prev = config.set("stream.open_backoff", 0.001)
    mx.fault.configure("stream.shard_unreadable:prob=1,times=1")
    try:
        ds = stream.StreamDataset(shards)
        assert ds[0] is not None           # retry after the injected miss
    finally:
        config.set("stream.open_backoff", prev)
    assert telemetry.counters()["stream.open_retries_total"] == 1


# -- insight: the input-bound verdict ----------------------------------------

def test_input_stall_flips_the_roofline_verdict(metrics):
    for _ in range(5):
        telemetry.observe("pipeline.input_stall_seconds", 0.08)
    assert insight.input_stall_p50() == pytest.approx(0.08, rel=0.2)
    # stall p50 (80ms) > input_bound_ratio (0.5) x step (100ms)? yes
    assert insight.roofline_verdict(1e12, 1e6, peak_flops=1e12,
                                    peak_bytes_per_s=1e12,
                                    step_seconds=0.1) == "input"
    # same costs without a measured step time: the plain roofline
    assert insight.roofline_verdict(1e12, 1e6, peak_flops=1e12,
                                    peak_bytes_per_s=1e12) == "compute"
    # a fed pipeline (stall well under the ratio) never reads "input"
    telemetry.reset()
    for _ in range(5):
        telemetry.observe("pipeline.input_stall_seconds", 0.01)
    assert insight.roofline_verdict(1e12, 1e6, peak_flops=1e12,
                                    peak_bytes_per_s=1e12,
                                    step_seconds=0.1) == "compute"


# -- tools/make_shards.py ----------------------------------------------------

def _cli(*args):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "make_shards.py"),
         *args], capture_output=True, text=True, env=env, timeout=120)


def test_make_shards_cli_packs_and_validates(tmp_path):
    out = str(tmp_path / "packed")
    p = _cli("--out", out, "--num-shards", "3", "--synthetic", "32",
             "--shape", "4,4", "--classes", "5", "--validate")
    assert p.returncode == 0, p.stdout + p.stderr
    lines = [json.loads(ln) for ln in p.stdout.strip().splitlines()]
    assert lines[0]["records"] == 32 and lines[0]["shards"] == 3
    assert lines[1]["ok"] is True
    rec = sorted(glob.glob(os.path.join(out, "*.rec")))[1]
    with open(rec, "r+b") as f:
        f.seek(os.path.getsize(rec) - 6)
        b = f.read(1)
        f.seek(-1, os.SEEK_CUR)
        f.write(bytes([b[0] ^ 0xFF]))
    p = _cli("--validate", out)
    assert p.returncode == 1 and "CORRUPT" in p.stderr


# -- the 2-process host-loss drill -------------------------------------------

def test_multiprocess_host_loss_is_exactly_once(tmp_path):
    """Kill one host mid-epoch (its lease rots, its cursor names only
    the checkpointed prefix); the survivor adopts the rest.  The union
    of the durable served-record logs is the epoch, multiplicity 1."""
    n = 96
    data = str(tmp_path / "data")
    with stream.ShardWriter(data, 8) as w:
        for g in range(n):
            w.append(stream.pack_sample(
                onp.full((2,), g, dtype=onp.float32), onp.int32(0)))
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH",
                                                             ""))
    worker = os.path.join(REPO, "tests", "stream_worker.py")
    procs = [subprocess.Popen(
        [sys.executable, worker, str(tmp_path), str(rank), "2"],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=env) for rank in (0, 1)]
    outs = []
    for p in procs:
        try:
            out, _ = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, _ = p.communicate()
        outs.append(out)
    assert procs[1].returncode == 0 and "STREAM_VICTIM_DOWN 1" in outs[1], \
        outs[1]
    assert procs[0].returncode == 0, outs[0]
    assert "STREAM_DRILL_DONE rank=0" in outs[0], outs[0]
    served = []
    for path in glob.glob(os.path.join(str(tmp_path), "served-*.jsonl")):
        with open(path) as f:
            for line in f:
                served.extend(json.loads(line))
    assert sorted(served) == list(range(n)), \
        f"multiset broke: {len(served)} served, {len(set(served))} unique"

"""SSD toy example end-to-end (reference: example/ssd smoke level —
tests/python/unittest/test_example off-tree equivalent)."""
import os
import sys

import numpy as onp

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "example"))

from train_ssd_toy import train, detect, make_batch  # noqa: E402


def test_ssd_toy_trains_and_detects():
    net, anchors, losses = train(steps=25, batch_size=8, lr=2e-3, log=False)
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
    rs = onp.random.RandomState(5)
    imgs, labels = make_batch(rs, 2)
    out = detect(net, anchors, imgs).asnumpy()
    assert out.shape[0] == 2 and out.shape[2] == 6
    # rows are [cls, score, x1, y1, x2, y2] sorted by score; invalid -1
    assert ((out[:, :, 0] >= -1) & (out[:, :, 0] < 3)).all()

"""GPT decoder-only family (reference analog: gluon-nlp gpt2 models over
src/operator/contrib/transformer.cc attention ops)."""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import gluon
from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM, GPTModel


def _tiny(**kw):
    cfg = dict(vocab_size=100, units=32, hidden_size=64, num_layers=2,
               num_heads=2, max_length=16, dropout=0.0, embed_dropout=0.0)
    cfg.update(kw)
    return GPTForCausalLM(**cfg)


def test_causality():
    """Logits at position i must not depend on tokens after i."""
    mx.random.seed(0)
    net = _tiny()
    net.initialize()
    rng = onp.random.RandomState(0)
    a = rng.randint(0, 100, (2, 8)).astype("int32")
    b = a.copy()
    b[:, 5:] = rng.randint(0, 100, (2, 3))  # perturb the future
    la = net(mx.np.array(a)).asnumpy()
    lb = net(mx.np.array(b)).asnumpy()
    assert onp.allclose(la[:, :5], lb[:, :5], atol=1e-5)
    assert not onp.allclose(la[:, 5:], lb[:, 5:], atol=1e-3)


def test_hybridize_matches_eager():
    mx.random.seed(1)
    net = _tiny()
    net.initialize()
    x = mx.np.array(onp.random.RandomState(1).randint(0, 100, (2, 8))
                    .astype("int32"))
    eager = net(x).asnumpy()
    net.hybridize()
    hybrid = net(x).asnumpy()
    assert onp.allclose(eager, hybrid, atol=1e-5)


@pytest.mark.slow
def test_lm_learns_induction():
    """Train on 'second half repeats first half' sequences — solvable only
    through causal attention to earlier positions."""
    mx.random.seed(2)
    net = _tiny(max_length=12)
    net.initialize()
    net.hybridize()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    rng = onp.random.RandomState(2)
    losses = []
    for _ in range(150):
        half = rng.randint(0, 100, (32, 6)).astype("int32")
        seq = onp.concatenate([half, half], axis=1)
        x, y = mx.np.array(seq[:, :-1]), mx.np.array(seq[:, 1:])
        with mx.autograd.record():
            loss = loss_fn(net(x), y).mean()
        loss.backward()
        trainer.step(32)
        losses.append(float(loss))
    # positions 6..10 are perfectly predictable: loss well below
    # uniform-vocab entropy (ln 100 ~ 4.6, repeated half floor ~ 2.3)
    assert losses[-1] < 3.0, (losses[0], losses[-1])


def test_named_configs():
    from mxnet_tpu.gluon.model_zoo.gpt import gpt2_124m, gpt2_355m
    m = GPTModel(vocab_size=128, num_layers=1, max_length=8)
    m.initialize()
    out = m(mx.np.zeros((1, 4), dtype="int32"))
    assert out.shape == (1, 4, 768)
    # config wiring of the named sizes (no init: deferred shapes)
    big = gpt2_355m(max_length=8)
    assert big._units == 1024
    assert len(big.decoder._layers) == 24
    assert big.decoder._layers[0].ffn.ffn_1._units == 4096
    small = gpt2_124m(max_length=8)
    assert small._units == 768 and len(small.decoder._layers) == 12

"""Multiprocess DataLoader workers (reference: gluon/data/dataloader.py
worker_loop + shared-memory transport, tests/python/unittest/
test_gluon_data.py test_multi_worker)."""
import glob
import os
import time

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu.gluon.data import DataLoader, ArrayDataset, SimpleDataset


def _slow_transform(x):
    # CPU-bound pure-python work: the GIL wall threads cannot cross
    s = 0.0
    for v in x[:64]:
        s += float(v) * 1.000001
    return x + onp.float32(s * 0)


class _PyTransformDataset:
    """Picklable dataset with a python transform."""

    def __init__(self, n=32, dim=128):
        rs = onp.random.RandomState(0)
        self.x = rs.rand(n, dim).astype(onp.float32)
        self.y = onp.arange(n).astype(onp.float32)

    def __len__(self):
        return len(self.x)

    def __getitem__(self, i):
        return _slow_transform(self.x[i]), self.y[i]


@pytest.mark.parametrize("workers,threads", [(0, True), (2, True),
                                             (2, False)])
def test_dataloader_paths_agree(workers, threads):
    ds = _PyTransformDataset()
    dl = DataLoader(ds, batch_size=8, num_workers=workers,
                    thread_pool=threads)
    batches = list(dl)
    assert len(batches) == 4
    ref = _PyTransformDataset()
    for bi, (bx, by) in enumerate(batches):
        want_x = onp.stack([ref[bi * 8 + i][0] for i in range(8)])
        want_y = onp.stack([ref[bi * 8 + i][1] for i in range(8)])
        onp.testing.assert_allclose(bx.asnumpy(), want_x, rtol=1e-6)
        onp.testing.assert_allclose(by.asnumpy(), want_y, rtol=1e-6)


def test_mp_loader_multiple_epochs_reuse_pool():
    ds = _PyTransformDataset(n=16)
    dl = DataLoader(ds, batch_size=8, num_workers=2, thread_pool=False)
    e1 = [b[0].asnumpy() for b in dl]
    pool = dl._proc_pool
    e2 = [b[0].asnumpy() for b in dl]
    assert dl._proc_pool is pool  # persistent workers across epochs
    for a, b in zip(e1, e2):
        onp.testing.assert_allclose(a, b)


def test_mp_loader_shm_cleanup():
    # the segment ring holds pooled blocks while the loader is alive;
    # close() must unlink every one (pool-internal semaphores die with
    # the worker processes)
    before = set(glob.glob("/dev/shm/psm_*"))
    ds = _PyTransformDataset(n=16)
    dl = DataLoader(ds, batch_size=4, num_workers=2, thread_pool=False)
    _ = [b[0].asnumpy() for b in dl]
    dl.close()
    time.sleep(0.2)
    after = set(glob.glob("/dev/shm/psm_*"))
    assert not (after - before), after - before


def test_mp_loader_shm_ring_reuse():
    """Epoch 2+ serves most batches from pooled segments: bounded creates,
    growing reuse counter (BENCH_r05 proc-vs-thread gap driver).  All
    leaves of a batch ride ONE packed segment, so the counters tick once
    per batch, not once per leaf."""
    from mxnet_tpu import telemetry
    telemetry.enable()
    try:
        ds = _PyTransformDataset(n=32)
        dl = DataLoader(ds, batch_size=8, num_workers=2, thread_pool=False)
        for _ in range(3):
            assert len(list(dl)) == 4
        agg = telemetry.counters(aggregate=True)
        created = agg.get("dataloader.shm_created_total", 0)
        reused = agg.get("dataloader.shm_reused_total", 0)
        # 3 epochs x 4 batches = 12 packed-segment transfers
        assert created + reused == 12
        assert reused > created, (created, reused)
        dl.close()
    finally:
        telemetry.disable()


def test_mp_loader_shm_ring_off_knob():
    """dataloader.shm_ring=False restores the one-shot create/unlink
    protocol (and still leaks nothing)."""
    before = set(glob.glob("/dev/shm/psm_*"))
    mx.config.set("dataloader.shm_ring", False)
    try:
        ds = _PyTransformDataset(n=16)
        dl = DataLoader(ds, batch_size=4, num_workers=2, thread_pool=False)
        batches = [b[0].asnumpy() for b in dl]
        assert len(batches) == 4
        dl.close()
    finally:
        mx.config.reset("dataloader.shm_ring")
    time.sleep(0.2)
    after = set(glob.glob("/dev/shm/psm_*"))
    assert not (after - before), after - before

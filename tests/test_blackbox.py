"""mx.blackbox — flight recorder, postmortem bundles, fleet merge.

One drill per trigger class (docs/OBSERVABILITY.md "Postmortem
forensics"): an injected mx.fault worker crash escalating WorkerLost, a
SIGTERM preemption through the exit-75 path, an uncaught exception in a
loader thread, a fleet host loss where the supervisor attaches the dead
host's bundle to the degrade event, and a torn bundle (the
"blackbox.torn_bundle" injection point) skipped by validate/merge.

Satellites covered here too: the warnings/log event ring, size-capped
JSONL report rotation (telemetry.report_max_bytes), and sync_guard
per-site counts in telemetry.snapshot().

Chaos spec literals exercised here: "blackbox.torn_bundle:at=1",
"resilience.preempt:at=3".
"""
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import warnings

import pytest

import mxnet_tpu as mx
from mxnet_tpu import blackbox, config, telemetry, trace
from mxnet_tpu.base import MXNetError
from mxnet_tpu.fleet import FleetSupervisor
from mxnet_tpu.parallel.mesh import MeshConfig

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_blackbox_state(tmp_path):
    """Every test gets an armed recorder pointed at its own bundle dir
    and leaves no hooks, flags or overrides behind."""
    mx.fault.clear()
    mx.fault.reset_stats()
    prev_dir = config.set("blackbox.dir", str(tmp_path / "bundles"))
    blackbox._snap_last = 0.0
    blackbox._last_exc_id = None
    blackbox.set_context(rank=None, step=None, mesh=None, checkpoint=None,
                         serve=None)
    yield
    blackbox.disable()
    blackbox.set_context(rank=None, step=None, mesh=None, checkpoint=None,
                         serve=None)
    config.set("blackbox.dir", prev_dir)
    mx.fault.clear()
    mx.fault.reset_stats()
    mx.resilience.uninstall_signal_handlers()
    mx.resilience.clear_preempt()


@pytest.fixture
def bundles(tmp_path):
    d = tmp_path / "bundles"
    d.mkdir(exist_ok=True)
    return str(d)


@pytest.fixture
def metrics():
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.disable()


def _cli(*args):
    """Run tools/postmortem.py; -> (returncode, stdout-json-or-None,
    stderr)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               PYTHONPATH=REPO + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    p = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "postmortem.py"),
         *args], capture_output=True, text=True, env=env, timeout=120)
    doc = json.loads(p.stdout) if p.returncode == 0 and p.stdout else None
    return p.returncode, doc, p.stderr


# -- bundle mechanics --------------------------------------------------------

def test_manual_dump_roundtrips_with_checksum(bundles):
    blackbox.enable()
    blackbox.set_context(run="unit")
    path = blackbox.dump(trigger="manual", reason="operator dump",
                         step=7, rank=3)
    assert os.path.basename(path) == "blackbox-3-00000007.json"
    assert os.path.exists(path + ".sha256")
    doc = blackbox.read_bundle(path)
    assert doc["schema"] == blackbox.BUNDLE_SCHEMA
    meta = doc["meta"]
    assert meta["trigger"] == "manual" and meta["reason"] == "operator dump"
    assert meta["rank"] == 3 and meta["step"] == 7 and not meta["shadow"]
    assert doc["context"]["run"] == "unit"
    # every evidence plane is present even when empty
    for key in ("spans", "telemetry", "counters_delta", "events", "fault",
                "insight", "sync_sites", "config"):
        assert key in doc, key
    assert doc["config"]["blackbox.window"] == config.get("blackbox.window")
    assert blackbox.latest_bundle(rank=3) == path


def test_dump_without_directory_is_a_safe_noop():
    config.set("blackbox.dir", "")
    prev = config.set("fleet.lease_dir", "")
    try:
        blackbox.enable()
        assert blackbox.dump(trigger="manual", reason="nowhere") is None
    finally:
        config.set("fleet.lease_dir", prev)


def test_retention_keeps_last_k_per_rank(bundles):
    prev = config.set("blackbox.keep", 2)
    try:
        blackbox.enable()
        for s in range(5):
            blackbox.dump(trigger="manual", step=s, rank=0)
        blackbox.dump(trigger="manual", step=9, rank=1)
        mine = blackbox.list_bundles(rank=0)
        assert [os.path.basename(p) for p in mine] == \
            ["blackbox-0-00000003.json", "blackbox-0-00000004.json"]
        # other ranks' evidence is never collected away
        assert len(blackbox.list_bundles(rank=1)) == 1
        leftovers = [f for f in os.listdir(bundles)
                     if f.endswith(".sha256")]
        assert len(leftovers) == 3       # sidecars follow their bundles
    finally:
        config.set("blackbox.keep", prev)


def test_disabled_gate_never_writes(bundles):
    assert not blackbox.active()
    if blackbox._active:                 # the one-attr-read hook pattern
        blackbox.dump(trigger="manual")
    assert blackbox.list_bundles() == []


# -- trigger drills ----------------------------------------------------------

def test_uncaught_exception_hits_excepthook(bundles, capfd):
    blackbox.enable()
    try:
        raise RuntimeError("host stepped on a rake")
    except RuntimeError:
        sys.excepthook(*sys.exc_info())  # what the interpreter does
    capfd.readouterr()                   # chained default hook's traceback
    path = blackbox.latest_bundle()
    doc = blackbox.read_bundle(path)
    assert doc["meta"]["trigger"] == "excepthook"
    assert doc["exception"]["type"] == "RuntimeError"
    assert "rake" in doc["exception"]["message"]
    assert any("RuntimeError" in ln
               for ln in doc["exception"]["traceback"])


def test_uncaught_exception_in_loader_thread(bundles, capfd):
    """Drill: a loader/prefetch thread dies uncaught; threading.excepthook
    must leave a bundle even though the main thread never sees the
    exception."""
    blackbox.enable()

    def loader():
        raise ValueError("batch 12 decode failed")

    t = threading.Thread(target=loader, name="loader-0")
    t.start()
    t.join()
    capfd.readouterr()
    doc = blackbox.read_bundle(blackbox.latest_bundle())
    assert doc["meta"]["trigger"] == "thread_excepthook"
    assert doc["exception"]["type"] == "ValueError"
    assert "thread=loader-0" in doc["meta"]["reason"]


def test_sigterm_preemption_exit75_leaves_bundle(bundles):
    """Drill: SIGTERM -> cooperative Preempted -> resilience.run exits
    with the resume sentinel (75) AND the recorder captured the preempt
    (SystemExit never reaches sys.excepthook, so the run() path must
    dump explicitly)."""
    blackbox.enable()
    mx.resilience.install_signal_handlers()

    def train_fn():
        signal.raise_signal(signal.SIGTERM)
        assert mx.resilience.preempt_requested()
        raise mx.resilience.Preempted(path="ckpt.bundle", step=12)

    with pytest.raises(SystemExit) as ei:
        mx.resilience.run(train_fn, exit_on_preempt=True)
    assert ei.value.code == mx.resilience.RESUME_EXIT_CODE == 75
    doc = blackbox.read_bundle(blackbox.latest_bundle())
    assert doc["meta"]["trigger"] == "preempt"
    assert doc["meta"]["step"] == 12
    assert "preempted (signal)" in doc["meta"]["reason"]


def test_injected_preempt_fault_drives_same_path(bundles):
    """Drill: the chaos injection ("resilience.preempt:at=3") produces
    the same preempt bundle as a real signal."""
    blackbox.enable()
    mx.fault.configure("resilience.preempt:at=3")

    def train_fn():
        for s in range(1, 6):
            if mx.resilience.preempt_requested(step=s):
                raise mx.resilience.Preempted(step=s, origin="injected")
        return "finished"

    with pytest.raises(mx.resilience.Preempted):
        mx.resilience.run(train_fn)
    doc = blackbox.read_bundle(blackbox.latest_bundle())
    assert doc["meta"]["trigger"] == "preempt" and doc["meta"]["step"] == 3


def test_worker_crash_past_budget_dumps_worker_lost(bundles):
    """Drill: an injected worker crash escalates WorkerLost past the
    restart budget; the terminal bundle names the op and the crash."""
    blackbox.enable()

    def always_lost():
        raise mx.resilience.WorkerLost("allreduce", "w", 0, 2, 3,
                                       RuntimeError("worker crashed"))

    with pytest.raises(mx.resilience.WorkerLost):
        mx.resilience.run(always_lost, max_restarts=1)
    doc = blackbox.read_bundle(blackbox.latest_bundle())
    assert doc["meta"]["trigger"] == "worker_lost"
    assert "WorkerLost(allreduce)" in doc["meta"]["reason"]
    assert doc["exception"]["type"] == "WorkerLost"


def test_supervisor_attaches_dead_hosts_bundle(bundles, metrics):
    """Drill: host 1 dies in a 2-host fleet; the supervisor finds its
    latest bundle and attaches it to the degrade trace span."""

    class _FakeStep:
        mesh_config = MeshConfig(dp=2)

        def rebuild(self, cfg, sync=False):
            new = _FakeStep()
            new.mesh_config = cfg
            return new

    blackbox.enable()
    blackbox.dump(trigger="worker_lost", reason="host 1 went dark",
                  step=4, rank=1)
    dead = blackbox.latest_bundle(rank=1)
    trace.enable(buffer=256)
    try:
        sup = FleetSupervisor(_FakeStep(), mx.resilience.TrainState(),
                              n_hosts=2)
        mx.fault.configure("fleet.host_loss:at=1")
        sup.probe(1)
        assert sup.degrades == 1
        assert sup.postmortems == {1: dead}
        spans = [s for s in trace.spans(category="fleet")
                 if s["name"] == "fleet.degrade"]
        assert spans and spans[-1]["args"]["postmortem"] == dead
        assert spans[-1]["args"]["postmortem_host"] == 1
    finally:
        trace.disable()
        trace.clear()


def test_torn_bundle_is_skipped_not_fatal(bundles):
    """Drill: the host dies mid-write ("blackbox.torn_bundle:at=1"); the
    torn file fails validation and every reader walks past it to the
    surviving evidence."""
    blackbox.enable()
    mx.fault.configure("blackbox.torn_bundle:at=1")
    torn = blackbox.dump(trigger="manual", reason="will be torn",
                         step=1, rank=0)
    assert mx.fault.stats().get("injected.blackbox.torn_bundle") == 1
    good = blackbox.dump(trigger="worker_lost", reason="real crash",
                         step=2, rank=0)
    with pytest.raises(MXNetError):
        blackbox.read_bundle(torn)
    assert blackbox.latest_bundle(rank=0) == good
    report = blackbox.endpoint_report()
    by_path = {e["path"]: e for e in report["bundles"]}
    assert by_path[torn]["valid"] is False
    assert by_path[good]["valid"] is True

    rc, _, err = _cli("validate", torn)
    assert rc == 1 and "torn" in err
    rc, doc, err = _cli("merge", os.path.dirname(good))
    assert rc == 0 and doc["torn"] == 1 and doc["hosts"] == 1
    assert "skipping torn bundle" in err
    assert doc["first_anomaly"]["reason"] == "real crash"


def test_drift_trigger_dumps_bundle(bundles, metrics):
    """insight.drift escalation doubles as a flight-recorder trigger."""
    from mxnet_tpu import insight
    blackbox.enable()
    insight._record_drift("step_time", {"step": 40, "ratio": 2.0})
    doc = blackbox.read_bundle(blackbox.latest_bundle())
    assert doc["meta"]["trigger"] == "drift"
    assert "step_time" in doc["meta"]["reason"]


# -- shadow checkpoints ------------------------------------------------------

def test_shadow_snapshot_rides_health_beat(tmp_path, bundles):
    from mxnet_tpu.fleet import HealthPlane
    blackbox.enable()
    hp = HealthPlane(rank=0, nprocs=1, lease_dir=str(tmp_path / "lease"))
    assert hp.beat(step=3) is True
    doc = blackbox.read_bundle(blackbox.latest_bundle(rank=0))
    assert doc["meta"]["shadow"] is True
    assert doc["meta"]["trigger"] == "shadow" and doc["meta"]["step"] == 3
    # rate limit: an immediate second beat does not write another bundle
    n = len(blackbox.list_bundles())
    hp.beat(step=4)
    assert len(blackbox.list_bundles()) == n


def test_shadow_loses_first_anomaly_to_terminal(bundles):
    """Merge semantics: a terminal bundle outranks any shadow, even an
    older one, when naming the first-anomaly host."""
    blackbox.enable()
    blackbox.dump(trigger="shadow", shadow=True, step=10, rank=0)
    blackbox.dump(trigger="excepthook", reason="boom", step=11, rank=1)
    rc, doc, _ = _cli("merge", blackbox.bundle_dir())
    assert rc == 0 and doc["first_anomaly_host"] == 1
    assert doc["first_anomaly"]["trigger"] == "excepthook"
    rc, doc, _ = _cli("summary", blackbox.bundle_dir())
    assert rc == 0 and doc["bundles"] == 2
    assert doc["hosts"]["0"]["shadow"] is True


def test_validate_expect_gates_trigger(bundles):
    blackbox.enable()
    path = blackbox.dump(trigger="manual", step=1, rank=0)
    rc, doc, _ = _cli("validate", path, "--expect", "manual")
    assert rc == 0 and doc["ok"] and doc["trigger"] == "manual"
    rc, _, err = _cli("validate", path, "--expect", "worker_lost")
    assert rc == 1 and "not in expected" in err


# -- satellite: warnings + log records land in the event ring ---------------

def test_event_ring_captures_warnings_and_logs(bundles, metrics):
    blackbox.enable()
    with warnings.catch_warnings():
        warnings.simplefilter("always")
        warnings.warn("grad clipped hard", RuntimeWarning)
    logging.getLogger("mxnet_tpu.test").warning("lease renew slow: %ds", 3)
    logging.getLogger("mxnet_tpu.test").debug("below threshold")
    kinds = {(e["kind"], e["message"]) for e in telemetry.events()}
    assert any(k == "warning" and "grad clipped hard" in m
               for k, m in kinds)
    assert any(k == "log" and "lease renew slow: 3s" in m
               for k, m in kinds)
    assert not any("below threshold" in m for _, m in kinds)
    counts = telemetry.counters(aggregate=False)
    assert counts.get('telemetry.events_total{kind="warning"}', 0) >= 1
    # the ring rides into bundles
    doc = blackbox.read_bundle(blackbox.dump(trigger="manual", step=1,
                                             rank=0))
    assert any(e["kind"] == "warning" for e in doc["events"])


def test_event_ring_is_bounded(metrics):
    prev = config.set("telemetry.event_ring", 4)
    try:
        telemetry.reset()                # re-latch the ring size
        for i in range(10):
            telemetry.note_event("log", f"record {i}")
        evs = telemetry.events()
        assert len(evs) == 4
        assert [e["message"] for e in evs] == \
            [f"record {i}" for i in range(6, 10)]
    finally:
        config.set("telemetry.event_ring", prev)
        telemetry.reset()


# -- satellite: size-capped JSONL report rotation ---------------------------

def test_report_rotates_at_size_cap_never_mid_record(tmp_path, metrics):
    path = str(tmp_path / "report.jsonl")
    prev = config.set("telemetry.report_max_bytes", 400)
    try:
        rep = telemetry.TrainingTelemetry(path=path, interval=1,
                                          run_id="rot")
        for _ in range(12):
            rep.step(loss=1.0)
        rep.close()
        gens = telemetry.TrainingTelemetry.generations(path)
        assert len(gens) > 1 and gens[-1] == path
        for g in gens:
            with open(g, encoding="utf-8") as f:
                size = 0
                for line in f:
                    json.loads(line)     # every line is a whole record
                    size += len(line)
            assert size <= 400 or sum(1 for _ in open(g)) == 1
        counts = telemetry.counters(aggregate=True)
        assert counts.get("telemetry.report_rotations_total", 0) == \
            len(gens) - 1
    finally:
        config.set("telemetry.report_max_bytes", prev)


def test_report_uncapped_never_rotates(tmp_path, metrics):
    path = str(tmp_path / "flat.jsonl")
    assert config.get("telemetry.report_max_bytes") == 0
    rep = telemetry.TrainingTelemetry(path=path, interval=1, run_id="flat")
    for _ in range(20):
        rep.step(loss=0.5)
    rep.close()
    assert telemetry.TrainingTelemetry.generations(path) == [path]


# -- satellite: sync_guard site counts in snapshot() ------------------------

def test_snapshot_exposes_sync_site_counts(metrics):
    from mxnet_tpu import pipeline
    before = telemetry.snapshot()["sync_sites"].get("ndarray.item", 0)
    a = mx.np.ones(())
    a.item()                             # telemetry arms the site counter
    snap = telemetry.snapshot()
    assert snap["sync_sites"]["ndarray.item"] == before + 1
    counts = telemetry.counters(aggregate=False)
    assert counts.get('pipeline.host_syncs_total{site="ndarray.item"}',
                      0) >= 1
    assert pipeline.sync_site_counts()["ndarray.item"] >= before + 1

"""Fused sparse softmax cross-entropy (reference:
src/operator/loss_binary_op.cc softmax_cross_entropy; gluon loss.py
SoftmaxCrossEntropyLoss sparse path)."""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import autograd, np
from mxnet_tpu.ops.xent import sparse_softmax_xent


def _naive(x, l, axis=-1):
    logp = jax.nn.log_softmax(x.astype(jnp.float32), axis)
    return -jnp.squeeze(
        jnp.take_along_axis(logp, jnp.expand_dims(l.astype(jnp.int32), axis),
                            axis), axis)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("shape,axis", [((7, 13), -1), ((4, 6, 11), -1),
                                        ((5, 9, 3), 1)])
def test_matches_naive_with_grads(dtype, shape, axis):
    rs = onp.random.RandomState(0)
    x = jnp.asarray(rs.randn(*shape).astype(onp.float32) * 3).astype(dtype)
    lshape = list(shape)
    v = lshape.pop(axis if axis >= 0 else len(shape) + axis)
    l = jnp.asarray(rs.randint(0, v, lshape))

    got = sparse_softmax_xent(x, l, axis)
    want = _naive(x, l, axis)
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    onp.testing.assert_allclose(got, want, rtol=tol, atol=tol)

    g = jax.grad(lambda x: jnp.sum(sparse_softmax_xent(x, l, axis) ** 2))(x)
    gw = jax.grad(lambda x: jnp.sum(_naive(x, l, axis) ** 2))(x)
    assert g.dtype == x.dtype
    onp.testing.assert_allclose(g.astype(jnp.float32), gw.astype(jnp.float32),
                                rtol=5e-2 if dtype == jnp.bfloat16 else 1e-4,
                                atol=5e-2 if dtype == jnp.bfloat16 else 1e-4)


def test_float_labels_backward():
    # MXNet data iters conventionally ship labels as float32; the label
    # input is differentiable-shaped through _invoke, so the VJP must
    # return a zero float cotangent (not float0) without crashing
    x = jnp.asarray(onp.random.RandomState(2).randn(4, 6), jnp.float32)
    l = jnp.array([0.0, 3.0, 5.0, 1.0], jnp.float32)
    g, gl = jax.grad(lambda x, l: sparse_softmax_xent(x, l).sum(),
                     argnums=(0, 1))(x, l)
    assert bool(jnp.isfinite(g).all()) and bool((gl == 0).all())

    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    pred = np.array(onp.random.RandomState(4).randn(4, 6).astype('float32'))
    lbl = np.array(l)
    pred.attach_grad()
    with autograd.record():
        out = SoftmaxCrossEntropyLoss()(pred, lbl).sum()
    out.backward()
    assert onp.isfinite(pred.grad.asnumpy()).all()


def test_out_of_range_labels_clip():
    # npx.pick(mode='clip') parity: -1 clamps to 0, >=V clamps to V-1,
    # finite loss and grads either way (no NaN poisoning from a corrupt
    # or padding label)
    x = jnp.asarray(onp.random.RandomState(1).randn(3, 5), jnp.float32)
    l_bad = jnp.array([-1, 2, 7])
    l_clip = jnp.array([0, 2, 4])
    onp.testing.assert_allclose(sparse_softmax_xent(x, l_bad),
                                sparse_softmax_xent(x, l_clip), rtol=1e-6)
    g = jax.grad(lambda x: sparse_softmax_xent(x, l_bad).sum())(x)
    assert bool(jnp.isfinite(g).all())


def test_extreme_logits_stable():
    # logsumexp shift must keep large logits finite in both directions
    x = jnp.array([[1e4, -1e4, 0.0], [88.0, 89.0, 90.0]], jnp.float32)
    l = jnp.array([0, 2])
    loss = sparse_softmax_xent(x, l)
    g = jax.grad(lambda x: sparse_softmax_xent(x, l).sum())(x)
    assert bool(jnp.isfinite(loss).all()) and bool(jnp.isfinite(g).all())
    onp.testing.assert_allclose(loss, _naive(x, l), rtol=1e-5, atol=1e-5)


def test_npx_softmax_cross_entropy_reference_example():
    # the documented example from loss_binary_op.cc:45-56
    import mxnet_tpu.numpy_extension as npx
    x = np.array([[1.0, 2.0, 3.0], [11.0, 7.0, 5.0]])
    label = np.array([2, 0])
    out = npx.softmax_cross_entropy(x, label)
    onp.testing.assert_allclose(out.asnumpy(), 0.4281871, rtol=1e-5)


def test_gluon_loss_fused_path_matches_dense_and_backprops():
    from mxnet_tpu.gluon.loss import SoftmaxCrossEntropyLoss
    rs = onp.random.RandomState(3)
    pred = np.array(rs.randn(6, 10).astype(onp.float32))
    lbl = np.array(rs.randint(0, 10, (6,)))
    dense = onp.eye(10, dtype=onp.float32)[lbl.asnumpy().astype(int)]

    sparse_loss = SoftmaxCrossEntropyLoss(sparse_label=True)
    dense_loss = SoftmaxCrossEntropyLoss(sparse_label=False)
    onp.testing.assert_allclose(sparse_loss(pred, lbl).asnumpy(),
                                dense_loss(pred, np.array(dense)).asnumpy(),
                                rtol=1e-5, atol=1e-6)

    pred.attach_grad()
    with autograd.record():
        out = sparse_loss(pred, lbl).sum()
    out.backward()
    g = pred.grad.asnumpy()
    assert onp.isfinite(g).all() and onp.abs(g).sum() > 0
    # d/dlogits of mean-CE sums to zero per row
    onp.testing.assert_allclose(g.sum(-1), onp.zeros(6), atol=1e-6)


def test_chunked_lm_xent_matches_dense():
    """Streaming-vocab LM xent == dense log_softmax pick, fwd and grads,
    incl. a vocab that is not a chunk multiple (padding tail masked)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.xent import chunked_lm_xent

    rng = onp.random.RandomState(0)
    N, D, V = 24, 16, 53
    h = jnp.asarray(rng.randn(N, D).astype("float32"))
    w = jnp.asarray(rng.randn(V, D).astype("float32"))
    lab = jnp.asarray(rng.randint(0, V, N))
    want = -jax.nn.log_softmax(h @ w.T, -1)[jnp.arange(N), lab]
    for chunk in (16, 53, 64, 7):
        got = chunked_lm_xent(h, w, lab, chunk)
        onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                    rtol=1e-5, atol=1e-5)

    weights = jnp.arange(N, dtype=jnp.float32)

    def ref(h, w):
        return jnp.sum(
            -jax.nn.log_softmax(h @ w.T, -1)[jnp.arange(N), lab] * weights)

    def ours(h, w):
        return jnp.sum(chunked_lm_xent(h, w, lab, 16) * weights)

    g_ref = jax.grad(ref, argnums=(0, 1))(h, w)
    g_our = jax.grad(ours, argnums=(0, 1))(h, w)
    onp.testing.assert_allclose(onp.asarray(g_our[0]),
                                onp.asarray(g_ref[0]), rtol=2e-4, atol=2e-4)
    onp.testing.assert_allclose(onp.asarray(g_our[1]),
                                onp.asarray(g_ref[1]), rtol=2e-4, atol=2e-4)
    # bf16 storage path stays finite and close
    hb, wb = h.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
    got16 = chunked_lm_xent(hb, wb, lab, 16)
    onp.testing.assert_allclose(onp.asarray(got16), onp.asarray(want),
                                rtol=0.05, atol=0.05)


def test_chunked_lm_xent_label_clip_parity():
    """Out-of-range labels clip exactly like sparse_softmax_xent
    (ignore-index -1 and off-by-one vocab mismatches stay finite)."""
    import jax
    import jax.numpy as jnp

    from mxnet_tpu.ops.xent import chunked_lm_xent, sparse_softmax_xent

    h = jnp.asarray(onp.random.RandomState(0).randn(4, 8).astype("float32"))
    w = jnp.asarray(onp.random.RandomState(1).randn(10, 8).astype("float32"))
    bad = jnp.asarray([10, -1, 3, 25])
    got = chunked_lm_xent(h, w, bad, 4)  # chunked so 10/25 land in pads
    ref = sparse_softmax_xent(h @ w.T, bad)
    onp.testing.assert_allclose(onp.asarray(got), onp.asarray(ref),
                                atol=1e-5)
    g = jax.grad(lambda a: jnp.sum(chunked_lm_xent(a, w, bad, 4)))(h)
    assert bool(jnp.isfinite(g).all())

"""AMP: dtype policy observably applied in eager + hybrid dispatch, loss
scaling under overflow.

Reference: python/mxnet/amp/amp.py:105-246 (wrapper-level input casts),
amp/loss_scaler.py:26-60, tests/python/gpu/test_amp.py.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import amp, autograd
from mxnet_tpu.gluon import nn


@pytest.fixture(autouse=True)
def _amp_off_after():
    yield
    amp._deactivate()


def test_amp_inactive_by_default():
    a = mx.np.ones((4, 4))
    assert mx.np.matmul(a, a).dtype == mx.np.float32


def test_amp_init_casts_matmul_eager():
    amp.init()
    a = mx.np.ones((4, 4), dtype="float32")
    out = mx.np.matmul(a, a)
    assert out.dtype == mx.np.bfloat16
    # numerics preserved at bf16 resolution
    onp.testing.assert_allclose(out.asnumpy().astype("float32"),
                                onp.full((4, 4), 4.0), rtol=1e-2)


def test_amp_fp32_ops_stay_fp32():
    amp.init()
    a = mx.np.ones((4, 4), dtype="bfloat16")
    from mxnet_tpu import npx
    assert npx.softmax(a).dtype == mx.np.float32


def test_amp_elementwise_unaffected():
    amp.init()
    a = mx.np.ones((4, 4), dtype="float32")
    assert (a + a).dtype == mx.np.float32


def test_amp_dense_eager_vs_hybrid():
    net = nn.Dense(8)
    net.initialize()
    x = mx.np.random.uniform(size=(2, 16))
    ref = net(x)  # fp32, pre-amp
    amp.init()
    eager = net(x)
    assert eager.dtype == mx.np.bfloat16
    net.hybridize()
    hybrid = net(x)
    assert hybrid.dtype == mx.np.bfloat16
    onp.testing.assert_allclose(eager.asnumpy().astype("float32"),
                                hybrid.asnumpy().astype("float32"),
                                rtol=2e-2, atol=2e-2)
    onp.testing.assert_allclose(ref.asnumpy(),
                                hybrid.asnumpy().astype("float32"),
                                rtol=5e-2, atol=5e-2)


def test_amp_policy_change_invalidates_hybrid_cache():
    net = nn.Dense(4)
    net.initialize()
    net.hybridize()
    x = mx.np.ones((2, 8))
    assert net(x).dtype == mx.np.float32
    amp.init()
    assert net(x).dtype == mx.np.bfloat16
    amp._deactivate()
    assert net(x).dtype == mx.np.float32


def test_amp_backward_master_weights_stay_fp32():
    amp.init()
    net = nn.Dense(4, in_units=8)
    net.initialize()
    x = mx.np.random.uniform(size=(2, 8))
    with autograd.record():
        y = net(x)
        loss = (y.astype("float32") ** 2).sum()
    loss.backward()
    g = net.weight.grad()
    assert net.weight.data().dtype == mx.np.float32  # master weights
    assert onp.isfinite(g.asnumpy()).all()
    assert g.asnumpy().astype("float32").any()


def test_amp_conv_eager_cast():
    amp.init()
    net = nn.Conv2D(4, kernel_size=3, in_channels=3)
    net.initialize()
    out = net(mx.np.ones((1, 3, 8, 8)))
    assert out.dtype == mx.np.bfloat16


def test_convert_hybrid_block_casts_params():
    net = nn.HybridSequential()
    net.add(nn.Dense(8), nn.BatchNorm(), nn.Dense(4))
    net.initialize()
    net(mx.np.ones((2, 16)))
    amp.convert_hybrid_block(net)
    params = net.collect_params()
    for name, p in params.items():
        if name.endswith(("gamma", "beta", "running_mean", "running_var")):
            assert p.data().dtype == mx.np.float32, name
        else:
            assert p.data().dtype == mx.np.bfloat16, name


def test_loss_scaler_overflow_cycle():
    from mxnet_tpu.amp import LossScaler
    s = LossScaler(init_scale=2 ** 8, scale_factor=2.0, scale_window=2)
    s.update_scale(True)
    assert s.loss_scale == 2 ** 7
    s.update_scale(False)
    s.update_scale(False)  # window reached -> grow
    assert s.loss_scale == 2 ** 8
    for _ in range(30):
        s.update_scale(True)
    assert s.loss_scale == 1  # floor


def test_loss_scaler_detects_inf_grads():
    from mxnet_tpu.amp import LossScaler
    net = nn.Dense(2, in_units=4)
    net.initialize()
    x = mx.np.full((1, 4), 1e38)
    with autograd.record():
        loss = (net(x) * 1e38).sum()
    loss.backward()
    params = list(net.collect_params().values())
    assert LossScaler().has_overflow(params)


def test_scale_loss_scope():
    net = nn.Dense(2, in_units=4)
    net.initialize()

    class FakeTrainer:
        _params = list(net.collect_params().values())
    tr = FakeTrainer()
    loss = mx.np.ones((2,))
    with amp.scale_loss(loss, tr) as scaled:
        assert float(scaled.sum()) == pytest.approx(2 * tr._amp_loss_scaler.loss_scale)


def test_convert_symbol_casts_matmul_inputs():
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import amp

    a = mx.sym.var("a")
    b = mx.sym.var("b")
    out = mx.sym.matmul(a, b) + 1.0
    lp = amp.convert_symbol(out, target_dtype="bfloat16")

    xa = mx.np.array(onp.random.RandomState(0).rand(8, 8).astype("float32"))
    xb = mx.np.array(onp.random.RandomState(1).rand(8, 8).astype("float32"))
    ref = out.eval(a=xa, b=xb)[0].asnumpy()
    got = lp.eval(a=xa, b=xb)[0]
    # matmul ran in bf16: close to fp32 but not bit-identical
    onp.testing.assert_allclose(got.asnumpy().astype("float32"), ref,
                                rtol=3e-2, atol=3e-2)
    assert not onp.array_equal(got.asnumpy().astype("float32"), ref)
    # original symbol untouched
    ref2 = out.eval(a=xa, b=xb)[0].asnumpy()
    onp.testing.assert_array_equal(ref2, ref)


def test_convert_symbol_fp32_ops_stay_fp32():
    import numpy as onp
    import mxnet_tpu as mx
    from mxnet_tpu import amp

    a = mx.sym.var("a")
    b = mx.sym.var("b")
    # matmul (bf16) feeding softmax (fp32): softmax input must be cast back
    net = mx.sym.softmax(mx.sym.matmul(a, b))
    lp = amp.convert_symbol(net, target_dtype="bfloat16")
    xa = mx.np.array(onp.random.RandomState(0).rand(4, 4).astype("float32"))
    xb = mx.np.array(onp.random.RandomState(1).rand(4, 4).astype("float32"))
    got = lp.eval(a=xa, b=xb)[0]
    assert str(got.dtype) == "float32"
    onp.testing.assert_allclose(got.asnumpy().sum(-1), onp.ones(4),
                                rtol=1e-3)


def test_amp_conditional_fp32_ops():
    """Conditional entries (op, attr, values) run fp32 only for the listed
    attr values (reference: CONDITIONAL_FP32_FUNCS)."""
    from mxnet_tpu import npx
    amp.init("bfloat16")
    try:
        x = mx.np.array(onp.random.randn(4, 8).astype("float32"))
        # softrelu is conditionally fp32; relu is not listed -> unchanged
        soft = npx.activation(x.astype("bfloat16"), "softrelu")
        assert str(soft.dtype) == "float32"
        rel = npx.activation(x.astype("bfloat16"), "relu")
        assert str(rel.dtype) == "bfloat16"
        # leaky_relu elu conditional; leaky not
        elu = npx.leaky_relu(x.astype("bfloat16"), act_type="elu")
        assert str(elu.dtype) == "float32"
        leaky = npx.leaky_relu(x.astype("bfloat16"), act_type="leaky")
        assert str(leaky.dtype) == "bfloat16"
        # user-supplied conditional triple
        amp.init("bfloat16",
                 conditional_fp32_ops=[("activation", "act_type", ["tanh"])])
        tanh = npx.activation(x.astype("bfloat16"), "tanh")
        assert str(tanh.dtype) == "float32"
    finally:
        amp._deactivate()


def test_amp_dtype_drift_oracle():
    """Drive a mixed net under amp.init() and assert every intermediate
    dtype against the policy oracle: MXU ops -> target dtype, fp32-listed
    ops -> fp32, unlisted elementwise -> input dtype, mixed elementwise ->
    widest (jnp promotion)."""
    from mxnet_tpu import npx
    amp.init("bfloat16")
    try:
        x = mx.np.array(onp.random.randn(2, 3, 8, 8).astype("float32"))
        w = mx.np.array(onp.random.randn(4, 3, 3, 3).astype("float32"))
        g = mx.np.ones(4)
        b = mx.np.zeros(4)
        rm = mx.np.zeros(4)
        rv = mx.np.ones(4)

        conv = npx.convolution(x, w, kernel=(3, 3), num_filter=4,
                               pad=(1, 1), no_bias=True)
        assert str(conv.dtype) == "bfloat16"          # TARGET op
        act = npx.activation(conv, "relu")
        assert str(act.dtype) == "bfloat16"           # unlisted: keep dtype
        bn = npx.batch_norm(act, g, b, rm, rv, use_global_stats=True)
        assert str(bn.dtype) == "float32"             # FP32 op upcasts
        pooled = npx.pooling(bn.astype("bfloat16"), kernel=(2, 2),
                             stride=(2, 2), pool_type="max")
        assert str(pooled.dtype) == "bfloat16"        # pooling:max unlisted
        mixed = pooled + bn[:, :, ::2, ::2]
        assert str(mixed.dtype) == "float32"          # widest-type combine
        flat = mixed.reshape((2, -1))
        wfc = mx.np.array(onp.random.randn(5, flat.shape[1]).astype("float32"))
        fc = npx.fully_connected(flat, wfc, num_hidden=5, no_bias=True)
        assert str(fc.dtype) == "bfloat16"            # TARGET op downcasts
        sm = npx.softmax(fc)
        assert str(sm.dtype) == "float32"             # FP32 op
    finally:
        amp._deactivate()

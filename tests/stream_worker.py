"""Subprocess body for the mx.stream host-loss exactly-once drill.

Usage: python tests/stream_worker.py <root> <rank> <nprocs>

``<root>/data`` holds the shard set; ``<root>`` doubles as the lease +
cursor directory.  The highest rank is the victim: it serves a few
batches, making some of them durable (publish_cursor + an fsync'd
append to its served-record log — the drill's stand-in for "those steps
landed in a checkpoint"), then makes MORE progress without
checkpointing and exits hard: a crash, its lease left to rot and its
cursor naming only the durable prefix.  Rank 0 is the survivor: it
serves its own share to completion (checkpointing as it goes), watches
the health plane until the victim's lease expires into the structured
WorkerLost escalation, adopts the victim's unfinished shards from the
published cursor and serves those too.  The parent test asserts the
union of the served-record logs is the epoch, every record exactly once
— the victim's un-checkpointed batches were never durable, so the
survivor re-serving them is the correct multiplicity, not a duplicate.
"""
import json
import os
import sys
import time

import mxnet_tpu as mx
from mxnet_tpu import stream
from mxnet_tpu.fleet import HealthPlane

BATCH = 4
CKPT_EVERY = 2       # batches per durable checkpoint
INTERVAL = 0.05
TIMEOUT = 0.6
SEED = 7


def _log_path(root, rank):
    return os.path.join(root, f"served-{rank}.jsonl")


def _checkpoint(samp, root, rank, buf, served):
    """One durable checkpoint: cursor first, then the served-id log —
    both land or the drill's oracle catches the difference."""
    samp.publish_cursor(cursor=served)
    with open(_log_path(root, rank), "a") as f:
        f.write(json.dumps(buf) + "\n")
        f.flush()
        os.fsync(f.fileno())
    buf.clear()


def main(root, rank, nprocs):
    samp = stream.StreamSampler(os.path.join(root, "data"),
                                batch_size=BATCH, seed=SEED,
                                dp=nprocs, rank=rank, cursor_dir=root)
    hp = HealthPlane(rank=rank, nprocs=nprocs, lease_dir=root,
                     interval=INTERVAL, timeout=TIMEOUT)
    hp.beat(step=0)
    buf, served = [], 0

    if rank == nprocs - 1 and nprocs > 1:
        # victim: 2 durable checkpoints, 2 more non-durable batches, crash
        crash_at = 2 * CKPT_EVERY + 2
        for batch in samp:
            buf.extend(batch)
            served += 1
            hp.beat(step=served)
            if served % CKPT_EVERY == 0 and served < crash_at:
                _checkpoint(samp, root, rank, buf, served)
            if served == crash_at:
                print(f"STREAM_VICTIM_DOWN {rank} served={served}",
                      flush=True)
                os._exit(0)   # crash: lease rots, tail batches not durable
            time.sleep(INTERVAL)
        # the test sized the dataset so the share outlives the crash point
        print(f"STREAM_VICTIM_UNDERFED {rank} served={served}", flush=True)
        return 1

    # survivor: own share first, checkpointing every CKPT_EVERY batches
    for batch in samp:
        buf.extend(batch)
        served += 1
        hp.beat(step=served)
        if served % CKPT_EVERY == 0:
            _checkpoint(samp, root, rank, buf, served)
    if buf:
        _checkpoint(samp, root, rank, buf, served)

    deadline = time.monotonic() + 30.0
    while len(hp.peers()) < nprocs - 1:     # wait for every peer's lease
        if time.monotonic() > deadline:
            print("STREAM_TIMEOUT waiting for peers", flush=True)
            return 1
        time.sleep(INTERVAL)
    dead = None
    while dead is None:
        if time.monotonic() > deadline:
            print("STREAM_TIMEOUT waiting for lease expiry", flush=True)
            return 1
        hp.beat(step=served)
        try:
            hp.check_peers()
        except mx.resilience.WorkerLost as e:
            dead = int(str(e.key).split("-", 1)[1])
        time.sleep(INTERVAL)

    adopted = samp.take_over_host(dead, survivors=[rank])
    # this epoch's generator already finished — re-enter it through the
    # cursor: the resume skips exactly the records already served, so
    # only the adopted work remains
    samp.load_state_dict(samp.state_dict(cursor=served))
    for batch in samp:
        buf.extend(batch)
        served += 1
        if served % CKPT_EVERY == 0:
            _checkpoint(samp, root, rank, buf, served)
    if buf:
        _checkpoint(samp, root, rank, buf, served)
    print(f"STREAM_DRILL_DONE rank={rank} adopted={adopted} "
          f"served={served}", flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1], int(sys.argv[2]), int(sys.argv[3])))

"""Pallas fused conv3x3+BN+ReLU backward — oracle suite.

Round-4 verdict item 1: the kernel (ops/pallas_conv_bwd.py) must match the
eager/XLA composition. Interpret mode on the CPU mesh; on TPU the same
kernel compiles natively (bench path). Note the e2e network-level
comparison uses a loss-level tolerance: an UNTRAINED ResNet at tiny batch
is chaotically ill-conditioned (near-zero BN variances amplify 1e-6
perturbations ~1e4x — measured, both paths), so elementwise output parity
is only asserted at the block level where conditioning is sane.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp

import mxnet_tpu as mx
from mxnet_tpu import config, gluon
from mxnet_tpu import numpy_extension as npx
from mxnet_tpu.gluon import nn
from mxnet_tpu.ops.pallas_conv_bwd import (
    conv3x3_bn_relu_ref, fused_conv3x3_bn_relu_bwd, fused_cbr_train)

RNG = onp.random.RandomState(0)


@pytest.mark.parametrize("shape", [
    (4, 8, 8, 16),     # single grid step
    (16, 8, 8, 8),     # multi-step grid (NB=4, grid=4): dw accumulation
    (2, 4, 4, 128),    # late-stage: big C, tiny spatial
])
def test_kernel_matches_jax_vjp(shape):
    N, H, W, C = shape
    O = C
    x = jnp.asarray(RNG.randn(N, H, W, C), jnp.float32)
    w = jnp.asarray(RNG.randn(3, 3, C, O) * 0.2, jnp.float32)
    gamma = jnp.asarray(RNG.rand(O) + 0.5, jnp.float32)
    beta = jnp.asarray(RNG.randn(O) * 0.1, jnp.float32)
    da = jnp.asarray(RNG.randn(N, H, W, O), jnp.float32)

    def f(x, w, gamma, beta):
        return conv3x3_bn_relu_ref(x, w, gamma, beta)[0]

    _, vjp = jax.vjp(f, x, w, gamma, beta)
    dx_ref, dw_ref, dg_ref, db_ref = vjp(da)
    _, y, mean, var = conv3x3_bn_relu_ref(x, w, gamma, beta)
    dx, dw, dg, db = fused_conv3x3_bn_relu_bwd(
        da, x, y, w, gamma, beta, mean, var, interpret=True)
    for name, got, want in [("dx", dx, dx_ref), ("dw", dw, dw_ref),
                            ("dgamma", dg, dg_ref), ("dbeta", db, db_ref)]:
        onp.testing.assert_allclose(onp.asarray(got), onp.asarray(want),
                                    rtol=5e-4, atol=5e-4, err_msg=name)


def test_custom_vjp_composite():
    """jax.vjp through fused_cbr_train uses the Pallas backward."""
    N, H, W, C = 2, 6, 6, 8
    x = jnp.asarray(RNG.randn(N, H, W, C), jnp.float32)
    w = jnp.asarray(RNG.randn(3, 3, C, C) * 0.2, jnp.float32)
    gamma = jnp.asarray(RNG.rand(C) + 0.5, jnp.float32)
    beta = jnp.asarray(RNG.randn(C) * 0.1, jnp.float32)
    da = jnp.asarray(RNG.randn(N, H, W, C), jnp.float32)

    def ref(x, w, g, b):
        return conv3x3_bn_relu_ref(x, w, g, b)[0]

    def fused(x, w, g, b):
        return fused_cbr_train(x, w, g, b, 1e-5, True)[0]

    _, vjp_r = jax.vjp(ref, x, w, gamma, beta)
    _, vjp_f = jax.vjp(fused, x, w, gamma, beta)
    for r, f_ in zip(vjp_r(da), vjp_f(da)):
        onp.testing.assert_allclose(onp.asarray(f_), onp.asarray(r),
                                    rtol=5e-4, atol=5e-4)


def _grads(blk, xv, fused):
    config.set("fused_conv_bn", "on" if fused else "off")
    try:
        x = mx.np.array(xv)
        x.attach_grad()
        with mx.autograd.record():
            out = blk(x)
            loss = (out * out).sum()
        loss.backward()
        return (out.asnumpy(), x.grad.asnumpy(),
                {k: p.grad().asnumpy() for k, p in
                 blk.collect_params().items() if p.grad_req != "null"})
    finally:
        config.set("fused_conv_bn", "auto")


def test_block_level_parity():
    """BasicBlockV1 fused vs unfused: forward, input grad, param grads."""
    from mxnet_tpu.gluon.model_zoo.vision.resnet import BasicBlockV1
    mx.random.seed(0)
    blk = BasicBlockV1(16, 1, False, 16)
    blk.initialize()
    xv = RNG.randn(2, 16, 10, 10).astype("float32")
    blk(mx.np.array(xv))
    o0, dx0, g0 = _grads(blk, xv, fused=False)
    o1, dx1, g1 = _grads(blk, xv, fused=True)
    onp.testing.assert_allclose(o1, o0, rtol=1e-4, atol=1e-4)
    onp.testing.assert_allclose(dx1, dx0, rtol=1e-3, atol=1e-3)
    for k in g0:
        onp.testing.assert_allclose(
            g1[k], g0[k], rtol=2e-3, atol=2e-3, err_msg=k)


def test_running_stats_update_matches():
    blk = nn.FusableSequential()
    blk.add(nn.Conv2D(8, 3, padding=1, use_bias=False), nn.BatchNorm(),
            nn.Activation("relu"))
    blk.initialize()
    xv = RNG.randn(2, 8, 6, 6).astype("float32")
    blk(mx.np.array(xv))
    bn = blk[1]
    config.set("fused_conv_bn", "on")
    try:
        with mx.autograd.record():
            blk(mx.np.array(xv))
        rm_f = bn.running_mean.data().asnumpy().copy()
        rv_f = bn.running_var.data().asnumpy().copy()
        bn.running_mean.set_data(mx.np.zeros((8,)))
        bn.running_var.set_data(mx.np.ones((8,)))
        config.set("fused_conv_bn", "off")
        with mx.autograd.record():
            blk(mx.np.array(xv))
        onp.testing.assert_allclose(bn.running_mean.data().asnumpy(), rm_f,
                                    rtol=1e-4, atol=1e-5)
        onp.testing.assert_allclose(bn.running_var.data().asnumpy(), rv_f,
                                    rtol=1e-4, atol=1e-5)
    finally:
        config.set("fused_conv_bn", "auto")


def test_eval_and_ineligible_fall_back():
    """Outside training the fused path must not run (running stats frozen,
    inference BN); stride-2 / 7x7 convs never fuse."""
    from mxnet_tpu.gluon.nn.fuse import _eligible_triplet
    c3 = nn.Conv2D(8, 3, padding=1, use_bias=False)
    c3s2 = nn.Conv2D(8, 3, strides=2, padding=1, use_bias=False)
    c7 = nn.Conv2D(8, 7, padding=3, use_bias=False)
    cb = nn.Conv2D(8, 3, padding=1, use_bias=True)
    bn, act = nn.BatchNorm(), nn.Activation("relu")
    assert _eligible_triplet(c3, bn, act)
    assert not _eligible_triplet(c3s2, bn, act)
    assert not _eligible_triplet(c7, bn, act)
    assert not _eligible_triplet(cb, bn, act)
    assert not _eligible_triplet(c3, bn, nn.Activation("tanh"))
    assert not _eligible_triplet(c3, nn.BatchNormReLU(), act)

    blk = nn.FusableSequential()
    blk.add(c3, bn, act)
    blk.initialize()
    xv = RNG.randn(2, 8, 6, 6).astype("float32")
    blk(mx.np.array(xv))
    rm0 = bn.running_mean.data().asnumpy().copy()
    config.set("fused_conv_bn", "on")
    try:
        out = blk(mx.np.array(xv))   # eval mode: no fusion, stats frozen
        onp.testing.assert_allclose(bn.running_mean.data().asnumpy(), rm0)
    finally:
        config.set("fused_conv_bn", "auto")


@pytest.mark.slow
def test_resnet_trains_with_fused_path():
    """Loss decreases over a few fused steps and stays finite (the e2e
    chaotic-conditioning caveat rules out elementwise parity here)."""
    from mxnet_tpu.gluon.model_zoo.vision import get_resnet
    config.set("fused_conv_bn", "on")
    try:
        mx.random.seed(0)
        net = get_resnet(1, 18)
        net.initialize()
        xv = RNG.uniform(size=(4, 3, 32, 32)).astype("float32")
        yv = onp.arange(4) % 3
        net(mx.np.array(xv))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.05, "momentum": 0.9})
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        first = None
        for _ in range(6):
            with mx.autograd.record():
                loss = loss_fn(net(mx.np.array(xv)), mx.np.array(yv)).mean()
            loss.backward()
            tr.step(4)
            first = first if first is not None else float(loss)
        assert onp.isfinite(float(loss))
        assert float(loss) < first
    finally:
        config.set("fused_conv_bn", "auto")


def test_small_fused_net_trains():
    """Cheap default-bucket stand-in for the resnet run (nightly): a two-
    triplet FusableSequential net converges through the fused backward."""
    config.set("fused_conv_bn", "on")
    try:
        mx.random.seed(0)
        net = nn.FusableSequential()
        net.add(nn.Conv2D(8, 3, padding=1, use_bias=False), nn.BatchNorm(),
                nn.Activation("relu"),
                nn.Conv2D(8, 3, padding=1, use_bias=False), nn.BatchNorm(),
                nn.Activation("relu"),
                nn.GlobalAvgPool2D(), nn.Dense(3))
        net.initialize()
        xv = RNG.uniform(size=(4, 8, 8, 8)).astype("float32")
        yv = onp.arange(4) % 3
        net(mx.np.array(xv))
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
        loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
        first = None
        for _ in range(6):
            with mx.autograd.record():
                loss = loss_fn(net(mx.np.array(xv)), mx.np.array(yv)).mean()
            loss.backward()
            tr.step(4)
            first = first if first is not None else float(loss)
        assert onp.isfinite(float(loss)) and float(loss) < first
    finally:
        config.set("fused_conv_bn", "auto")

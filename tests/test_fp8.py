"""fp8 training with delayed scaling + compressed gradient collectives
(docs/PRECISION.md).

Oracles: the fp8 step against the fp32 reference on the same seed and
batches (loss-curve parity, not bitwise — the format genuinely rounds),
the EF-compressed dp reduction against the uncompressed step (error
feedback telescopes, wire bytes provably cut), checkpoint round-trips
bitwise through an elastic dp resize, and the serve/autotune guards
that keep fp8 from shipping where it is unproven.

Note: seed BEFORE ``initialize()`` — Dense with ``in_units`` known
materializes weights immediately, so a seed set after construction
never reaches the initializer.
"""
import json
import os
import time

import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import config as mxconfig, telemetry
from mxnet_tpu.amp import fp8
from mxnet_tpu.base import MXNetError
from mxnet_tpu.parallel import compressed_allreduce, make_mesh
from mxnet_tpu.parallel.train import ShardedTrainStep

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")

UNITS, IN_UNITS = 32, 16   # weight 32x16 = 512 elems >= amp.fp8_min_elems


def _make_net(units=UNITS, in_units=IN_UNITS, seed=7):
    from mxnet_tpu.gluon import nn
    mx.random.seed(seed)
    net = nn.Dense(units, in_units=in_units)
    net.initialize()
    return net


def _loss_fn(logits, labels):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], -1))


def _data(n=16, in_units=IN_UNITS, classes=UNITS, seed=1):
    rs = onp.random.RandomState(seed)
    x = rs.randn(n, in_units).astype("float32")
    y = rs.randint(0, classes, (n,)).astype("int32")
    return x, y


def _step(precision="fp32", compress="none", mesh=None, opt=None, seed=7,
          **kw):
    mesh = mesh or make_mesh({"dp": 4})
    opt = opt or mx.optimizer.create("adam", learning_rate=0.05)
    return ShardedTrainStep(_make_net(seed=seed), _loss_fn, opt, mesh,
                            batch_specs=(P("dp"), P("dp")), n_labels=1,
                            precision=precision, grad_compress=compress,
                            **kw)


# ---------------------------------------------------------------------------
# the fp8 primitive + delayed-scaling state (no mesh)
# ---------------------------------------------------------------------------

def test_select_sites_filters_shape_and_floor():
    shapes = {"dense0.weight": (32, 16),    # 512 elems: eligible
              "dense0.bias": (32,),         # 1-D: never
              "tiny.weight": (8, 8),        # 64 < min_elems floor
              "emb.weight": (4, 8, 8)}      # not 2-D
    assert fp8.select_sites(shapes) == ["dense0.weight"]


def test_zero_history_means_identity_scales():
    state = fp8.init_state(["s"], history=4)
    xs, ws, gs = fp8.scales_from_state(state)["s"]
    assert float(xs) == 1.0 and float(ws) == 1.0 and float(gs) == 1.0


def test_roll_state_and_scale_formula():
    state = fp8.init_state(["s"], history=3)
    amax = jnp.float32(2.0)
    state = fp8.roll_state(state, {"s": (amax, amax)}, {"s": amax})
    h = state["s"]
    onp.testing.assert_allclose(onp.asarray(h["x"]), [2.0, 0.0, 0.0])
    onp.testing.assert_allclose(onp.asarray(h["g"]), [2.0, 0.0, 0.0])
    xs, ws, gs = fp8.scales_from_state(state, margin=1.0)["s"]
    _, fwd_max = fp8.FP8_FORMATS[fp8.FWD_FORMAT]
    _, bwd_max = fp8.FP8_FORMATS[fp8.BWD_FORMAT]
    onp.testing.assert_allclose(float(xs), fwd_max / 2.0, rtol=1e-6)
    onp.testing.assert_allclose(float(gs), bwd_max / 2.0, rtol=1e-6)
    # a second roll shifts the history window
    state = fp8.roll_state(state, {"s": (jnp.float32(1.0),) * 2},
                           {"s": jnp.float32(1.0)})
    onp.testing.assert_allclose(onp.asarray(state["s"]["x"]),
                                [1.0, 2.0, 0.0])


def test_merge_amax_takes_elementwise_max():
    a = {"s": (jnp.float32(1.0), jnp.float32(3.0))}
    b = {"s": (jnp.float32(2.0), jnp.float32(0.5)), "t": (jnp.float32(9.0),)}
    out = fp8.merge_amax(a, b)
    assert float(out["s"][0]) == 2.0 and float(out["s"][1]) == 3.0
    assert float(out["t"][0]) == 9.0


def test_fp8_linear_value_and_gradient_amax_cotangent():
    """fp8_linear == fp32 dot of fp8-snapped operands, and the g_scale
    slot's cotangent carries max |dy| out of the backward trace."""
    rs = onp.random.RandomState(0)
    x = jnp.asarray(rs.randn(4, 8).astype("float32"))
    w = jnp.asarray(rs.randn(6, 8).astype("float32"))
    b = jnp.asarray(rs.randn(6).astype("float32"))
    one = jnp.float32(1.0)
    y, vjp = jax.vjp(fp8.fp8_linear, x, w, b, one, one, one)
    dt, _ = fp8.FP8_FORMATS[fp8.FWD_FORMAT]
    ref = (x.astype(dt).astype(jnp.float32)
           @ w.astype(dt).astype(jnp.float32).T + b)
    onp.testing.assert_allclose(onp.asarray(y), onp.asarray(ref),
                                rtol=1e-5, atol=1e-5)
    dy = jnp.asarray(rs.randn(4, 6).astype("float32"))
    dx, dw, db, dxs, dws, g_amax = vjp(dy)
    assert float(g_amax) == pytest.approx(float(jnp.max(jnp.abs(dy))))
    assert float(dxs) == 0.0 and float(dws) == 0.0
    # gradients through the e5m2-snapped dy against the fp32 chain rule
    gdt, _ = fp8.FP8_FORMATS[fp8.BWD_FORMAT]
    qdy = dy.astype(gdt).astype(jnp.float32)
    onp.testing.assert_allclose(
        onp.asarray(dx),
        onp.asarray(qdy @ w.astype(dt).astype(jnp.float32)),
        rtol=1e-5, atol=1e-5)
    onp.testing.assert_allclose(onp.asarray(db), onp.asarray(dy.sum(0)),
                                rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# the fp8 training step
# ---------------------------------------------------------------------------

def test_fp8_step_tracks_fp32_loss_curve():
    x, y = _data()
    mx.random.seed(3)
    ref = _step("fp32")
    mx.random.seed(3)
    s8 = _step("fp8")
    assert s8._fp8_sites, "Dense weight must be an eligible fp8 site"
    for _ in range(4):
        l0 = float(ref(x, y).asnumpy())
        l8 = float(s8(x, y).asnumpy())
        assert abs(l8 - l0) / max(abs(l0), 1e-8) < 0.05, (l8, l0)
    assert getattr(s8.block, "_fp8_trained", False)


def test_fp8_amax_history_rolls_per_update():
    s8 = _step("fp8")
    x, y = _data()
    site = s8._fp8_sites[0]
    h0 = {k: onp.asarray(v) for k, v in s8.extra["fp8"][site].items()}
    assert all((v == 0).all() for v in h0.values())
    s8(x, y)
    s8(x, y)
    h = {k: onp.asarray(v) for k, v in s8.extra["fp8"][site].items()}
    for k in ("x", "w", "g"):
        assert h[k][0] > 0.0 and h[k][1] > 0.0, (k, h[k])
        assert (h[k][2:] == 0.0).all(), (k, h[k])


def test_fp8_with_grad_accum_and_steps_per_call():
    """fp8 composes with microbatch accumulation and fused multi-step
    calls: one history roll per OPTIMIZER update, counts advance."""
    opt = mx.optimizer.create("adam", learning_rate=0.05)
    s8 = _step("fp8", opt=opt, grad_accum=2, steps_per_call=2)
    x, y = _data(n=32)
    s8(x.reshape(2, 2, 8, IN_UNITS), y.reshape(2, 2, 8))
    assert s8._n_step == 2
    assert opt.num_update == 2
    site = s8._fp8_sites[0]
    h = onp.asarray(s8.extra["fp8"][site]["x"])
    assert h[0] > 0 and h[1] > 0 and (h[2:] == 0).all()


# ---------------------------------------------------------------------------
# compressed dp collectives (error feedback)
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("mode", ["int8", "bf16"])
def test_compressed_step_tracks_uncompressed(mode):
    x, y = _data()
    mx.random.seed(5)
    ref = _step("fp32", "none")
    mx.random.seed(5)
    comp = _step("fp32", mode)
    for _ in range(5):
        l0 = float(ref(x, y).asnumpy())
        lc = float(comp(x, y).asnumpy())
        # EF keeps the trajectory unbiased; per-step drift stays small
        assert abs(lc - l0) / max(abs(l0), 1e-8) < 0.05, (mode, lc, l0)


def test_int8_compression_cuts_dp_wire_bytes():
    telemetry.enable()
    try:
        telemetry.reset()
        comp = _step("fp32", "int8")
        x, y = _data()
        for _ in range(2):
            comp(x, y)
        c = telemetry.counters()   # aggregate=False keeps {axis="dp"}
        wire = c.get('mesh.collective_bytes_total{axis="dp"}', 0)
        full = c.get("mesh.dp_gradient_bytes_total", 0)
        assert full > 0 and wire > 0
        assert full / wire >= 2.0, (wire, full)
        assert c.get("comm.compressed_bytes_total", 0) == wire
        assert c.get("comm.uncompressed_bytes_total", 0) == full
    finally:
        telemetry.disable()


def test_error_feedback_residual_carries_quantization_error():
    comp = _step("fp32", "int8")
    x, y = _data()
    names = sorted(comp.extra["resid"])
    assert names and all(n.startswith("bucket") for n in names)
    before = [onp.asarray(comp.extra["resid"][n]) for n in names]
    assert all((b == 0).all() for b in before)
    comp(x, y)
    after = [onp.asarray(comp.extra["resid"][n]) for n in names]
    assert any(onp.abs(a).max() > 0 for a in after), \
        "int8 rounding error must land in the EF residual"


def test_fp8_plus_int8_compression_converges():
    """The headline config: e4m3/e5m2 matmuls + int8 EF dp reduction,
    loss strictly decreasing over a short run."""
    s = _step("fp8", "int8")
    x, y = _data()
    losses = [float(s(x, y).asnumpy()) for _ in range(6)]
    assert losses[-1] < losses[0], losses


def test_compressed_allreduce_free_function():
    mesh = make_mesh({"dp": 4})
    rs = onp.random.RandomState(2)
    x = jnp.asarray(rs.randn(4, 64).astype("float32"))
    exact = onp.asarray(x).mean(0)
    mean, res = compressed_allreduce(x, mesh, mode="int8")
    s = onp.abs(onp.asarray(x)).max() / 127.0
    onp.testing.assert_allclose(onp.asarray(mean), exact, atol=4 * s)
    assert res.shape == x.shape
    # EF telescopes: two steps' means with the residual carried recover
    # the exact two-step sum to within ONE step's quantization error
    mean2, _ = compressed_allreduce(x, mesh, residual=res)
    tot = onp.asarray(mean) + onp.asarray(mean2)
    onp.testing.assert_allclose(tot, 2 * exact, atol=4 * s)
    # bf16 carries ~8 mantissa bits: much tighter than int8
    mbf, _ = compressed_allreduce(x, mesh, mode="bf16")
    onp.testing.assert_allclose(onp.asarray(mbf), exact, atol=2e-2)
    with pytest.raises(ValueError, match="int8"):
        compressed_allreduce(x, mesh, mode="fp4")


def test_compress_validation_errors():
    with pytest.raises(MXNetError, match="pure-dp"):
        mesh = make_mesh({"dp": 2, "tp": 2})
        ShardedTrainStep(_make_net(), _loss_fn, "adam", mesh,
                         batch_specs=(P("dp"), P("dp")), n_labels=1,
                         grad_compress="int8")
    with pytest.raises(MXNetError, match="sharded over 'dp'"):
        ShardedTrainStep(_make_net(), _loss_fn, "adam", make_mesh({"dp": 4}),
                         batch_specs=(P("dp"), P()), n_labels=1,
                         grad_compress="int8")
    with pytest.raises(MXNetError, match="grad_compress"):
        _step("fp32", "int3")
    with pytest.raises(MXNetError, match="precision"):
        _step("fp16")


def test_zero_post_warmup_recompiles():
    s = _step("fp8", "int8")
    x, y = _data()
    s(x, y)  # trace + compile
    telemetry.enable()
    try:
        telemetry.reset()
        before = sum(telemetry.counters(prefix="compile.",
                                        aggregate=True).values())
        for _ in range(3):
            s(x, y)
        after = sum(telemetry.counters(prefix="compile.",
                                       aggregate=True).values())
        assert after - before == 0
    finally:
        telemetry.disable()


# ---------------------------------------------------------------------------
# checkpoints: amax histories + EF residuals through an elastic resize
# ---------------------------------------------------------------------------

def test_fp8_checkpoint_elastic_dp4_to_dp2_bitwise(tmp_path):
    """fp8 amax histories and EF residuals ride save_states/load_states
    and restore BITWISE at a different dp size (residuals re-enter in
    the canonical summed layout — the telescoped error is the sum)."""
    x, y = _data()
    mx.random.seed(21)
    src = _step("fp8", "int8")
    for _ in range(3):
        src(x, y)
    fname = str(tmp_path / "fp8.ckpt")
    src.save_states(fname)
    canon = src.state_dict()["arrays"]
    assert any(k.startswith("fp8/") for k in canon)
    assert any(k.startswith("efresid/") for k in canon)

    mx.random.seed(99)  # different init; load must overwrite everything
    dst = _step("fp8", "int8", mesh=make_mesh({"dp": 2}), seed=99)
    dst.load_states(fname)
    assert dst._n_step == 3
    got = dst.state_dict()["arrays"]
    assert set(got) == set(canon)
    for k in canon:
        onp.testing.assert_array_equal(got[k], canon[k], err_msg=k)
    assert getattr(dst.block, "_fp8_trained", False), \
        "load_states must re-tag the block from checkpoint metadata"
    # the restored step trains on the new topology
    l = float(dst(x, y).asnumpy())
    assert onp.isfinite(l)


def test_fp8_state_survives_plain_roundtrip_missing_keys_ok(tmp_path):
    """A pre-fp8 (fp32) checkpoint loads into an fp32 step unchanged,
    and an fp8 checkpoint refuses nothing when the dest has no fp8
    state to fill — forward/backward compatible key handling."""
    x, y = _data()
    src = _step("fp32", "none")
    src(x, y)
    fname = str(tmp_path / "fp32.ckpt")
    src.save_states(fname)
    dst = _step("fp32", "none", mesh=make_mesh({"dp": 2}))
    dst.load_states(fname)
    for n in src.trainable:
        onp.testing.assert_array_equal(onp.asarray(dst.trainable[n]),
                                       onp.asarray(src.trainable[n]))


# ---------------------------------------------------------------------------
# serve guard: low-bit serving on fp8-trained checkpoints
# ---------------------------------------------------------------------------

def _tiny_gpt():
    from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM
    mx.random.seed(0)
    net = GPTForCausalLM(vocab_size=97, units=32, hidden_size=64,
                         num_layers=1, num_heads=2, max_length=16,
                         dropout=0.0, embed_dropout=0.0)
    net.initialize()
    return net


def test_serve_int4_refuses_fp8_trained_checkpoint():
    net = _tiny_gpt()
    net._fp8_trained = True   # what ShardedTrainStep(precision="fp8") tags
    with pytest.raises(MXNetError, match="fp8-trained"):
        mx.serve.load(net, max_slots=2, buckets="4,8",
                      quantize="int4_weights")


def test_serve_int8_composes_with_fp8_trained():
    net = _tiny_gpt()
    net._fp8_trained = True
    for q in ("int8_weights", "int8_kv"):
        eng = mx.serve.load(net, max_slots=2, buckets="4,8", quantize=q)
        eng.stop()


def test_serve_int4_override_knob():
    net = _tiny_gpt()
    net._fp8_trained = True
    prev = mxconfig.set("serve.allow_fp8_requant", True)
    try:
        eng = mx.serve.load(net, max_slots=2, buckets="4,8",
                            quantize="int4_weights")
        eng.stop()
    finally:
        mxconfig.set("serve.allow_fp8_requant", prev)


# ---------------------------------------------------------------------------
# autotune: fp8 ships only where the parity probe passes
# ---------------------------------------------------------------------------

def test_autotune_parity_gate_rejects_and_admits_fp8():
    from mxnet_tpu.autotune import SearchSpace, search
    net = _make_net()
    mesh = make_mesh({"dp": 4})
    x, y = _data()
    space = SearchSpace(batch_size=16, steps_per_call=1, grad_accum=1,
                        zero=0, remat=False, precision=("fp32", "fp8"))

    # impossible tolerance: the fp8 trial must die with status "parity"
    # and the fp32 candidate wins
    prev = mxconfig.set("autotune.fp8_parity_tol", 1e-12)
    try:
        res = search(net, _loss_fn, "adam", mesh, (P("dp"), P("dp")),
                     (x, y), n_labels=1, space=space, persist=False,
                     force=True, trial_seconds=0.05, warmup=1)
        by_prec = {t.candidate.precision: t for t in res.trials}
        assert by_prec["fp8"].status == "parity"
        assert "parity probe failed" in by_prec["fp8"].error
        assert res.best.candidate.precision == "fp32"
    finally:
        mxconfig.set("autotune.fp8_parity_tol", prev)

    # generous tolerance: the same fp8 candidate measures cleanly
    prev = mxconfig.set("autotune.fp8_parity_tol", 0.5)
    try:
        res = search(net, _loss_fn, "adam", mesh, (P("dp"), P("dp")),
                     (x, y), n_labels=1, space=space, persist=False,
                     force=True, trial_seconds=0.05, warmup=1)
        by_prec = {t.candidate.precision: t for t in res.trials}
        assert by_prec["fp8"].status == "ok"
        assert by_prec["fp8"].items_per_s > 0
    finally:
        mxconfig.set("autotune.fp8_parity_tol", prev)


# ---------------------------------------------------------------------------
# telemetry exposition + insight fleet rollup of the new counters
# ---------------------------------------------------------------------------

def test_per_axis_collective_counters_exposed():
    telemetry.enable()
    try:
        telemetry.reset()
        s = _step("fp32", "int8")
        x, y = _data()
        s(x, y)
        c = telemetry.counters()
        assert 'mesh.collective_bytes_total{axis="dp"}' in c
        text = telemetry.exposition()
        assert 'mesh_collective_bytes_total{axis="dp"}' in text
    finally:
        telemetry.disable()


def test_insight_fleet_view_rolls_up_collective_traffic(tmp_path):
    from mxnet_tpu import insight
    d = str(tmp_path)
    for rank, dp, tp in ((0, 1000, 40), (1, 3000, 60)):
        payload = {"rank": rank, "time": time.time(), "counters": {
            'mesh.collective_bytes_total{axis="dp"}': dp,
            'mesh.collective_bytes_total{axis="tp"}': tp,
            'zero.collective_bytes_total{op="all_gather"}': 7,
            "comm.compressed_bytes_total": dp,
            "comm.uncompressed_bytes_total": 4 * dp,
        }, "gauges": {}}
        with open(os.path.join(d, f"insight-{rank}.json"), "w") as f:
            f.write(json.dumps(payload))
    m = insight.merge_snapshots(d)
    coll = m["collectives"]
    assert coll["by_axis"]["dp"] == 4000
    assert coll["by_axis"]["tp"] == 100
    assert coll["zero_by_op"]["all_gather"] == 14
    assert coll["compression_ratio"] == pytest.approx(4.0)

"""Stable C ABI (native/mxtpu_capi.cc + mxtpu_c_api.h; reference
include/mxnet/c_api.h + src/c_api/c_api.cc).

The library is exercised exactly as a foreign host would: dlopen via
ctypes, MXTpuInit (attaches to this interpreter), then raw C calls —
no python objects cross the boundary."""
import ctypes

import numpy as onp
import pytest

from mxnet_tpu import native


@pytest.fixture(scope="module")
def lib():
    lib = native.capi_lib()
    if lib is None:
        pytest.skip("toolchain unavailable")
    assert lib.MXTpuInit() == 0, native
    return lib


def _make(lib, arr):
    arr = onp.ascontiguousarray(arr)
    code = {"float32": 0, "float64": 1, "uint8": 3,
            "int32": 4, "int64": 6}[str(arr.dtype)]
    shape = (ctypes.c_int64 * arr.ndim)(*arr.shape)
    h = ctypes.c_void_p()
    rc = lib.MXTpuNDArrayCreate(
        arr.ctypes.data_as(ctypes.c_void_p), arr.nbytes, code, shape,
        arr.ndim, ctypes.byref(h))
    assert rc == 0, lib.MXTpuGetLastError()
    return h


def _fetch(lib, h, shape, dtype):
    out = onp.empty(shape, dtype)
    rc = lib.MXTpuNDArraySyncCopyToCPU(
        h, out.ctypes.data_as(ctypes.c_void_p), out.nbytes)
    assert rc == 0, lib.MXTpuGetLastError()
    return out


def test_runtime_info_and_seed(lib):
    buf = ctypes.create_string_buffer(256)
    assert lib.MXTpuRuntimeInfo(buf, 256) == 0
    assert b"platform=" in buf.value and b"devices=" in buf.value
    assert lib.MXTpuRandomSeed(7) == 0
    assert lib.MXTpuWaitAll() == 0


def test_ndarray_roundtrip_shape_dtype(lib):
    x = onp.arange(12, dtype=onp.float32).reshape(3, 4)
    h = _make(lib, x)
    nd = ctypes.c_int(8)
    shp = (ctypes.c_int64 * 8)()
    assert lib.MXTpuNDArrayShape(h, ctypes.byref(nd), shp) == 0
    assert list(shp[:nd.value]) == [3, 4]
    dt = ctypes.c_int()
    assert lib.MXTpuNDArrayDType(h, ctypes.byref(dt)) == 0
    assert dt.value == 0
    onp.testing.assert_array_equal(_fetch(lib, h, (3, 4), onp.float32), x)
    assert lib.MXTpuNDArrayFree(h) == 0


def test_create_zeros_when_data_null(lib):
    shape = (ctypes.c_int64 * 2)(2, 5)
    h = ctypes.c_void_p()
    assert lib.MXTpuNDArrayCreate(None, 0, 4, shape, 2,
                                  ctypes.byref(h)) == 0
    onp.testing.assert_array_equal(_fetch(lib, h, (2, 5), onp.int32),
                                   onp.zeros((2, 5), onp.int32))
    lib.MXTpuNDArrayFree(h)


def _invoke(lib, op, handles, kw=None, max_out=4):
    kw = kw or {}
    ins = (ctypes.c_void_p * max(1, len(handles)))(*[h.value for h in handles])
    keys = (ctypes.c_char_p * max(1, len(kw)))(*[k.encode() for k in kw])
    vals = (ctypes.c_char_p * max(1, len(kw)))(*[v.encode()
                                                for v in kw.values()])
    outs = (ctypes.c_void_p * max_out)()
    n_out = ctypes.c_int(max_out)
    rc = lib.MXTpuImperativeInvoke(op.encode(), ins, len(handles), keys,
                                   vals, len(kw), outs, ctypes.byref(n_out))
    got = ([ctypes.c_void_p(outs[i]) for i in range(n_out.value)]
           if rc == 0 else [])
    return rc, got


def test_imperative_invoke_add_and_activation(lib):
    a = onp.random.RandomState(0).randn(4, 5).astype(onp.float32)
    b = onp.random.RandomState(1).randn(4, 5).astype(onp.float32)
    ha, hb = _make(lib, a), _make(lib, b)
    rc, outs = _invoke(lib, "add", [ha, hb])
    assert rc == 0, lib.MXTpuGetLastError()
    onp.testing.assert_allclose(_fetch(lib, outs[0], (4, 5), onp.float32),
                                a + b, rtol=1e-6)
    rc, outs2 = _invoke(lib, "activation", [ha],
                        {"act_type": "'relu'"})
    assert rc == 0, lib.MXTpuGetLastError()
    onp.testing.assert_allclose(_fetch(lib, outs2[0], (4, 5), onp.float32),
                                onp.maximum(a, 0), rtol=1e-6)
    for h in (ha, hb, outs[0], outs2[0]):
        lib.MXTpuNDArrayFree(h)


def test_invoke_kwargs_literal_parsing(lib):
    x = onp.arange(6, dtype=onp.float32).reshape(2, 3)
    h = _make(lib, x)
    rc, outs = _invoke(lib, "reshape", [h], {"newshape": "(3, 2)"})
    assert rc == 0, lib.MXTpuGetLastError()
    onp.testing.assert_array_equal(_fetch(lib, outs[0], (3, 2), onp.float32),
                                   x.reshape(3, 2))
    lib.MXTpuNDArrayFree(h)
    lib.MXTpuNDArrayFree(outs[0])


def test_unknown_op_sets_last_error(lib):
    x = _make(lib, onp.zeros((2,), onp.float32))
    rc, _ = _invoke(lib, "definitely_not_an_op", [x])
    assert rc != 0
    assert b"definitely_not_an_op" in lib.MXTpuGetLastError()
    lib.MXTpuNDArrayFree(x)


def test_output_capacity_error(lib):
    a = _make(lib, onp.ones((2, 2), onp.float32))
    outs = (ctypes.c_void_p * 1)()
    n_out = ctypes.c_int(0)  # no capacity
    ins = (ctypes.c_void_p * 1)(a.value)
    keys = (ctypes.c_char_p * 1)()
    vals = (ctypes.c_char_p * 1)()
    rc = lib.MXTpuImperativeInvoke(b"relu", ins, 1, keys, vals, 0, outs,
                                   ctypes.byref(n_out))
    err = lib.MXTpuGetLastError()
    assert rc != 0 and (b"capacity" in err or b"buffer" in err)
    lib.MXTpuNDArrayFree(a)


def test_pure_c_host_end_to_end(tmp_path):
    """Compile example/capi_host.c with gcc and run it as a genuinely
    non-Python process: embeds CPython via the ABI, creates arrays,
    invokes add, copies results back."""
    import os
    import shutil
    import subprocess
    if shutil.which("gcc") is None:
        pytest.skip("no gcc")
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    native.capi_lib()  # ensure the .so is built
    exe = str(tmp_path / "capi_host")
    rc = subprocess.run(
        ["gcc", os.path.join(root, "example", "capi_host.c"),
         "-I" + os.path.join(root, "native"),
         "-L" + os.path.join(root, "native", "build"), "-lmxtpu_capi",
         "-Wl,-rpath," + os.path.join(root, "native", "build"), "-o", exe],
        capture_output=True, text=True)
    assert rc.returncode == 0, rc.stderr
    env = dict(os.environ, PYTHONPATH=root, JAX_PLATFORMS="cpu")
    env.pop("XLA_FLAGS", None)  # plain 1-device CPU for the child
    run = subprocess.run([exe], capture_output=True, text=True, env=env,
                         timeout=240)
    assert run.returncode == 0, run.stdout + run.stderr
    assert "C host OK" in run.stdout

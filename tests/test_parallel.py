"""Distributed/parallel tests on the virtual 8-device CPU mesh.

Reference strategy analog: tests/nightly/dist_sync_kvstore.py runs real
multi-process reduces and asserts exact equality (SURVEY §4) — here the
collectives run on a real 8-device mesh (xla_force_host_platform_device
_count) and are checked against numpy oracles.
"""
import numpy as onp
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import mxnet_tpu as mx
from mxnet_tpu import numpy as np
from mxnet_tpu.parallel import (allgather, allreduce, make_mesh,
                                reduce_scatter, ring_attention)

pytestmark = pytest.mark.skipif(
    len(jax.devices()) < 8, reason="needs 8 (virtual) devices")


@pytest.fixture(scope="module")
def mesh8():
    return make_mesh({"dp": 8})


def test_allreduce_oracle(mesh8):
    x = onp.arange(32, dtype="float32").reshape(8, 4)
    arr = jax.device_put(jnp.asarray(x), NamedSharding(mesh8, P("dp")))
    out = allreduce(arr, mesh8, axis="dp")
    # every shard holds the sum over the dp axis of its own block-row stack
    expect = onp.tile(x.sum(0, keepdims=True), (8, 1))
    onp.testing.assert_allclose(onp.asarray(out), expect, rtol=1e-6)


def test_allgather_reduce_scatter(mesh8):
    x = onp.arange(16, dtype="float32").reshape(8, 2)
    arr = jax.device_put(jnp.asarray(x), NamedSharding(mesh8, P("dp")))
    gathered = allgather(arr, mesh8, axis="dp")
    onp.testing.assert_allclose(onp.asarray(gathered), x)
    # replicated input: every device contributes a full copy, so the
    # reduced+scattered result is 8*x distributed over the axis
    rs = reduce_scatter(jnp.asarray(x), mesh8, axis="dp")
    onp.testing.assert_allclose(onp.asarray(rs), 8 * x)


def test_ring_attention_matches_reference():
    mesh = make_mesh({"sp": 8})
    b, h, s, d = 2, 4, 64, 16
    onp.random.seed(0)
    q = jnp.asarray(onp.random.randn(b, h, s, d).astype("float32"))
    k = jnp.asarray(onp.random.randn(b, h, s, d).astype("float32"))
    v = jnp.asarray(onp.random.randn(b, h, s, d).astype("float32"))

    def ref(causal):
        s_ = jnp.einsum("bhqd,bhkd->bhqk", q, k) / (d ** 0.5)
        if causal:
            m = jnp.tril(jnp.ones((s, s), bool))
            s_ = jnp.where(m, s_, -1e30)
        return jnp.einsum("bhqk,bhkd->bhqd", jax.nn.softmax(s_, -1), v)

    for causal in (False, True):
        out = ring_attention(q, k, v, mesh, axis="sp", causal=causal)
        onp.testing.assert_allclose(onp.asarray(out),
                                    onp.asarray(ref(causal)), atol=2e-5)


@pytest.mark.slow
def test_sharded_train_step_bert_dp_tp_sp():
    from mxnet_tpu.gluon.model_zoo.bert import BERTForPretraining
    from mxnet_tpu.parallel.mesh import activation_sharding
    from mxnet_tpu.parallel.train import ShardedTrainStep

    mesh = make_mesh({"dp": 2, "tp": 2, "sp": 2})
    net = BERTForPretraining(vocab_size=96, units=64, hidden_size=128,
                             num_layers=2, num_heads=4, max_length=32,
                             dropout=0.0, embed_dropout=0.0)
    net.initialize()
    net(np.zeros((4, 16), dtype="int32"))

    def loss_fn(outputs, labels):
        mlm, _ = outputs
        logp = jax.nn.log_softmax(mlm.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[..., None], -1))

    with activation_sharding(mesh, residual=P("dp", "sp", None)):
        step = ShardedTrainStep(net, loss_fn, "adam", mesh,
                                batch_specs=(P("dp", "sp"), P("dp", "sp")),
                                n_labels=1)
        ids = onp.random.randint(0, 96, (8, 16)).astype("int32")
        losses = [float(step(ids, ids).asnumpy()) for _ in range(6)]
    assert losses[-1] < losses[0], losses
    # megatron specs actually applied
    w = step.trainable[
        "backbone.encoder.layer0.attention.query_proj.weight"]
    assert w.sharding.spec == P("tp", None)
    w2 = step.trainable["backbone.encoder.layer0.attention.out_proj.weight"]
    assert w2.sharding.spec == P(None, "tp")
    step.sync_to_block()


def test_sharded_train_step_matches_single_device():
    """dp-sharded compiled step must match the eager Trainer numerically."""
    from mxnet_tpu.gluon import Trainer, nn
    from mxnet_tpu.parallel.train import ShardedTrainStep
    from mxnet_tpu import autograd

    def make_net():
        mx.random.seed(7)
        net = nn.Dense(4, in_units=8)
        net.initialize()
        return net

    mesh = make_mesh({"dp": 8})
    onp.random.seed(1)
    x = onp.random.randn(16, 8).astype("float32")
    y = onp.random.randint(0, 4, (16,)).astype("int32")

    def loss_fn(logits, labels):
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        return -jnp.mean(jnp.take_along_axis(logp, labels[:, None], -1))

    net1 = make_net()
    step = ShardedTrainStep(
        net1, loss_fn, mx.optimizer.create("sgd", learning_rate=0.1),
        mesh, batch_specs=(P("dp"), P("dp")), n_labels=1)
    for _ in range(3):
        step(x, y)
    step.sync_to_block()
    w_sharded = net1.weight.data().asnumpy()

    net2 = make_net()
    trainer = Trainer(net2.collect_params(), "sgd",
                      {"learning_rate": 0.1})
    from mxnet_tpu import numpy_extension as npx
    for _ in range(3):
        with autograd.record():
            logits = net2(np.array(x))
            loss = -(npx.pick(npx.log_softmax(logits, axis=-1),
                              np.array(y))).mean()
        loss.backward()
        trainer.step(1, ignore_stale_grad=True)
    w_eager = net2.weight.data().asnumpy()
    onp.testing.assert_allclose(w_sharded, w_eager, atol=1e-5)


def test_gpipe_matches_sequential():
    """Pipeline parallelism: fwd and grads equal the unpipelined stack."""
    from mxnet_tpu.parallel.pp import (gpipe, shard_stages,
                                       stack_stage_params)
    mesh = make_mesh({"pp": 4})
    S, M, mb, d = 4, 6, 2, 8
    onp.random.seed(0)
    Ws = [onp.random.randn(d, d).astype("float32") * 0.5 for _ in range(S)]
    params = shard_stages(stack_stage_params(
        [{"w": jnp.asarray(w)} for w in Ws]), mesh)
    xs = jnp.asarray(onp.random.randn(M, mb, d).astype("float32"))

    def stage(p, x):
        return jnp.tanh(x @ p["w"])

    ys = gpipe(stage, params, xs, mesh)
    ref = xs
    for w in Ws:
        ref = jnp.tanh(ref @ jnp.asarray(w))
    onp.testing.assert_allclose(onp.asarray(ys), onp.asarray(ref),
                                atol=1e-5)

    g = jax.grad(lambda p: gpipe(stage, p, xs, mesh).sum())(params)
    gref = jax.grad(lambda ws: _seq_loss(ws, xs))(
        jnp.stack([jnp.asarray(w) for w in Ws]))
    onp.testing.assert_allclose(onp.asarray(g["w"]), onp.asarray(gref),
                                atol=1e-4)


def _seq_loss(ws, xs):
    r = xs
    for i in range(ws.shape[0]):
        r = jnp.tanh(r @ ws[i])
    return r.sum()


def test_moe_top1_oracle_and_ep_sharding():
    import math
    from mxnet_tpu.gluon.nn.moe import MoEDense, moe_expert_specs
    from mxnet_tpu.parallel.train import ShardedTrainStep

    mx.random.seed(0)
    onp.random.seed(0)
    moe = MoEDense(16, 32, num_experts=4, num_experts_per_tok=1,
                   capacity_factor=8.0)
    moe.initialize()
    x = np.array(onp.random.randn(2, 6, 16).astype("float32"))
    out, aux = moe(x)
    assert out.shape == (2, 6, 16)

    g = moe.gate.data().asnumpy()
    wi = moe.w_in.data().asnumpy()
    wo = moe.w_out.data().asnumpy()
    toks = x.asnumpy().reshape(-1, 16)
    logits = toks @ g
    probs = onp.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    choice = probs.argmax(-1)
    ref = onp.zeros_like(toks)
    for t in range(toks.shape[0]):
        e = choice[t]
        h = toks[t] @ wi[e]
        h = 0.5 * h * (1 + onp.array([math.erf(v / 2 ** 0.5) for v in h]))
        ref[t] = probs[t, e] * (h @ wo[e])
    onp.testing.assert_allclose(out.asnumpy().reshape(-1, 16), ref,
                                atol=1e-4)

    # expert-parallel training over dp x ep
    mesh = make_mesh({"dp": 2, "ep": 4})

    def loss_fn(outputs, y):
        o, aux = outputs
        return jnp.mean((o - y) ** 2) + 0.01 * aux

    step = ShardedTrainStep(moe, loss_fn, "adam", mesh,
                            batch_specs=(P("dp"), P("dp")), n_labels=1,
                            param_specs=moe_expert_specs())
    xb = onp.random.randn(8, 6, 16).astype("float32")
    losses = [float(step(xb, xb).asnumpy()) for _ in range(5)]
    assert losses[-1] < losses[0]
    assert step.trainable["w_in"].sharding.spec == P("ep", None, None)


def test_moe_aux_loss_penalizes_collapse_under_tight_capacity():
    """Regression: f must come from pre-capacity-drop routing, so the
    balance loss still distinguishes collapse when the hot expert
    overflows (Switch formulation)."""
    from mxnet_tpu.gluon.nn.moe import MoEDense
    mx.random.seed(0)
    onp.random.seed(0)
    moe = MoEDense(8, 16, num_experts=4, num_experts_per_tok=1,
                   capacity_factor=1.0)
    moe.initialize()
    x = np.array(onp.abs(onp.random.randn(2, 8, 8)).astype("float32"))
    # all-positive tokens + one-hot gate column => full collapse to expert 0
    moe.gate.set_data(np.array(onp.concatenate(
        [onp.full((8, 1), 5.0), onp.zeros((8, 3))], 1).astype("float32")))
    _, aux_collapsed = moe(x)
    moe.gate.set_data(np.zeros((8, 4)))
    _, aux_balanced = moe(x)
    assert float(aux_collapsed.asnumpy()) > float(aux_balanced.asnumpy()) + 0.5


def test_gpipe_rejects_stage_count_mismatch():
    from mxnet_tpu.parallel.pp import gpipe, stack_stage_params
    mesh = make_mesh({"pp": 4})
    params8 = stack_stage_params([{"w": jnp.ones((4, 4))}
                                  for _ in range(8)])
    with pytest.raises(ValueError, match="pp axis size"):
        gpipe(lambda p, x: x @ p["w"], params8, jnp.ones((2, 2, 4)), mesh)


def test_moe_topk_validation():
    from mxnet_tpu.gluon.nn.moe import MoEDense
    with pytest.raises(ValueError, match="num_experts_per_tok"):
        MoEDense(8, 16, num_experts=2, num_experts_per_tok=3)


def test_moe_top2_oracle():
    """Top-2 routing with GShard gate renormalization vs a numpy oracle."""
    import math
    from mxnet_tpu.gluon.nn.moe import MoEDense

    mx.random.seed(3)
    onp.random.seed(3)
    moe = MoEDense(8, 16, num_experts=4, num_experts_per_tok=2,
                   capacity_factor=8.0)  # capacity high: no drops
    moe.initialize()
    x = np.array(onp.random.randn(1, 5, 8).astype("float32"))
    out, aux = moe(x)

    g = moe.gate.data().asnumpy()
    wi = moe.w_in.data().asnumpy()
    wo = moe.w_out.data().asnumpy()
    toks = x.asnumpy().reshape(-1, 8)
    logits = toks @ g
    probs = onp.exp(logits - logits.max(-1, keepdims=True))
    probs /= probs.sum(-1, keepdims=True)
    ref = onp.zeros_like(toks)
    for t in range(toks.shape[0]):
        top2 = onp.argsort(-probs[t])[:2]
        denom = probs[t, top2].sum() + 1e-9
        for e in top2:
            h = toks[t] @ wi[e]
            h = 0.5 * h * (1 + onp.array(
                [math.erf(v / 2 ** 0.5) for v in h]))
            ref[t] += (probs[t, e] / denom) * (h @ wo[e])
    onp.testing.assert_allclose(out.asnumpy().reshape(-1, 8), ref,
                                atol=1e-4)


def test_scan_steps_matches_sequential():
    """K fused steps (one executable) must equal K sequential step calls."""
    import jax
    import jax.numpy as jnp
    from mxnet_tpu.parallel import scan_steps

    def step(w, m, x, y):
        g = 2 * (w * x - y) * x
        m = 0.9 * m + g
        w = w - 0.1 * m
        return w, m, jnp.mean((w * x - y) ** 2)

    w0 = jnp.asarray(0.5)
    m0 = jnp.zeros(())
    xs = jnp.asarray([1.0, 2.0, 0.5, 1.5])
    ys = jnp.asarray([2.0, 4.0, 1.0, 3.0])

    # sequential oracle
    w, m = w0, m0
    losses = []
    for x, y in zip(xs, ys):
        w, m, l = step(w, m, x, y)
        losses.append(float(l))

    loop = jax.jit(scan_steps(step, n_state=2))
    w2, m2, lmean = loop(w0, m0, xs, ys)
    onp.testing.assert_allclose(float(w2), float(w), rtol=1e-6)
    onp.testing.assert_allclose(float(m2), float(m), rtol=1e-6)
    onp.testing.assert_allclose(float(lmean), onp.mean(losses), rtol=1e-6)


def test_sharded_train_step_steps_per_call():
    """steps_per_call=K over stacked batches matches K single-step calls."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import ShardedTrainStep, make_mesh
    from jax.sharding import PartitionSpec as P

    def build():
        net = nn.Dense(4, in_units=8)
        net.initialize()
        return net

    rs = onp.random.RandomState(0)
    xs = rs.randn(2, 8, 8).astype("float32")   # K=2 stacked batches
    ys = rs.randn(2, 8, 4).astype("float32")

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    mesh = make_mesh({"dp": min(2, len(jax.devices()))})

    mx.random.seed(7)
    a = build()
    s1 = ShardedTrainStep(a, loss_fn, "sgd", mesh, (P("dp"), P("dp")))
    for i in range(2):
        s1(xs[i], ys[i])

    mx.random.seed(7)   # same init as `a`
    b = build()
    s2 = ShardedTrainStep(b, loss_fn, "sgd", mesh, (P("dp"), P("dp")),
                          steps_per_call=2)
    s2(xs, ys)

    for n in s1.trainable:
        onp.testing.assert_allclose(
            onp.asarray(s2.trainable[n]), onp.asarray(s1.trainable[n]),
            rtol=1e-5, atol=1e-6, err_msg=n)


def test_sharded_train_step_checkpoint_resume(tmp_path):
    """save_states/load_states must make interrupted == uninterrupted
    training (reference: Trainer save/load_states round-trip)."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import ShardedTrainStep, make_mesh
    from jax.sharding import PartitionSpec as P

    rs = onp.random.RandomState(3)
    xs = [rs.randn(8, 6).astype("float32") for _ in range(3)]
    ys = [rs.randn(8, 4).astype("float32") for _ in range(3)]

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    mesh = make_mesh({"dp": 2})

    def build():
        mx.random.seed(11)
        net = nn.Dense(4, in_units=6)
        net.initialize()
        return ShardedTrainStep(net, loss_fn, "adam", mesh,
                                (P("dp"), P("dp")))

    # uninterrupted: 3 steps
    s_full = build()
    for i in range(3):
        s_full(xs[i], ys[i])

    # interrupted: 2 steps -> save -> fresh object -> load -> 1 step
    s_a = build()
    for i in range(2):
        s_a(xs[i], ys[i])
    ckpt = str(tmp_path / "step")
    s_a.save_states(ckpt)
    s_b = build()
    s_b.load_states(ckpt)
    assert s_b._n_step == 2
    s_b(xs[2], ys[2])

    for n in s_full.trainable:
        onp.testing.assert_allclose(
            onp.asarray(s_b.trainable[n]), onp.asarray(s_full.trainable[n]),
            rtol=1e-5, atol=1e-6, err_msg=n)


def test_batchnorm_is_sync_under_dp_mesh():
    """BatchNorm over a dp-sharded batch reduces over the GLOBAL batch
    (GSPMD one-program semantics) — the free SyncBatchNorm: running
    stats after a sharded step equal the single-device full-batch run."""
    import jax
    import jax.numpy as jnp
    import mxnet_tpu as mx
    from mxnet_tpu.gluon import nn
    from mxnet_tpu.parallel import ShardedTrainStep, make_mesh
    from jax.sharding import PartitionSpec as P

    rs = onp.random.RandomState(5)
    x = (rs.randn(16, 6) * 3 + 1).astype("float32")
    y = rs.randn(16, 4).astype("float32")

    def loss_fn(out, yy):
        return jnp.mean((out - yy) ** 2)

    def build():
        mx.random.seed(13)
        net = nn.HybridSequential()
        net.add(nn.Dense(4, in_units=6), nn.BatchNorm())
        net.initialize()
        net(mx.np.array(x))   # materialize BN params
        return net

    outs = {}
    for name, axes in [("sharded", {"dp": 8}), ("single", {"dp": 1})]:
        net = build()
        step = ShardedTrainStep(net, loss_fn, "sgd", make_mesh(axes),
                                (P("dp"), P("dp")))
        step(x, y)
        outs[name] = {n: onp.asarray(v) for n, v in step.aux.items()}
    for n in outs["single"]:
        onp.testing.assert_allclose(outs["sharded"][n], outs["single"][n],
                                    rtol=1e-5, atol=1e-6, err_msg=n)


def test_weak_scaling_table():
    """KVStore DP weak-scaling harness (BASELINE.md north star #3): rows at
    n=1/2/4 device-sublist meshes, fixed per-device batch, efficiency
    relative to n=1."""
    from mxnet_tpu.parallel.scaling import weak_scaling_table
    rows = weak_scaling_table(ns=[1, 2], per_device_batch=1, image=16,
                              iters=2, warmup=1)
    assert [r["n"] for r in rows] == [1, 2]
    assert rows[0]["efficiency"] == 1.0
    for r in rows:
        assert r["ms_per_step"] > 0
        assert r["global_batch"] == r["n"]
        assert 0 < r["efficiency"] <= 1.5

"""Detection augmenters + ImageDetIter
(reference: python/mxnet/image/detection.py; tests/python/unittest/
test_image.py TestImageDetIter)."""
import os
import random as pyrandom

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import np
from mxnet_tpu.image import (
    DetHorizontalFlipAug, DetRandomCropAug, DetRandomPadAug,
    DetRandomSelectAug, DetBorrowAug, CreateDetAugmenter,
    CreateMultiRandCropAugmenter, ImageDetIter)


def _img(h=40, w=60, seed=0):
    rs = onp.random.RandomState(seed)
    return np.array(rs.randint(0, 255, (h, w, 3)).astype(onp.float32))


def _label():
    # [cls, x1, y1, x2, y2]
    return onp.array([[0.0, 0.2, 0.3, 0.6, 0.8],
                      [1.0, 0.5, 0.1, 0.9, 0.4]], onp.float32)


def test_flip_label_math():
    pyrandom.seed(0)
    aug = DetHorizontalFlipAug(p=1.1)  # always flips
    src, lab = aug(_img(), _label())
    want = _label()
    x1 = 1.0 - want[:, 3].copy()
    x2 = 1.0 - want[:, 1].copy()
    onp.testing.assert_allclose(lab[:, 1], x1)
    onp.testing.assert_allclose(lab[:, 3], x2)
    # pixels mirrored
    onp.testing.assert_allclose(src.asnumpy(),
                                _img().asnumpy()[:, ::-1])


def test_crop_update_labels_formula():
    aug = DetRandomCropAug()
    lab = _label()
    out = aug._update_labels(lab, (12, 8, 30, 24), 40, 60)  # x,y,w,h
    # reference formula: shift by crop origin, scale by crop size, clip
    xmin, ymin, w, h = 12 / 60, 8 / 40, 30 / 60, 24 / 40
    want = lab.copy()
    want[:, (1, 3)] = onp.clip((want[:, (1, 3)] - xmin) / w, 0, 1)
    want[:, (2, 4)] = onp.clip((want[:, (2, 4)] - ymin) / h, 0, 1)
    for row in out:
        match = onp.isclose(want[:, 1:5], row[1:5], atol=1e-6).all(1)
        assert match.any()


def test_random_crop_constraints_hold():
    pyrandom.seed(3)
    aug = DetRandomCropAug(min_object_covered=0.3, max_attempts=40)
    applied = 0
    for trial in range(20):
        src, lab = aug(_img(seed=trial), _label())
        arr = src.asnumpy()
        assert lab.shape[1] == 5 and lab.shape[0] >= 1
        assert (lab[:, 1:5] >= -1e-6).all() and (lab[:, 1:5] <= 1 + 1e-6).all()
        assert (lab[:, 3] > lab[:, 1]).all() and (lab[:, 4] > lab[:, 2]).all()
        if arr.shape != (40, 60, 3):
            applied += 1
    assert applied > 0  # the crop actually fired at least once


def test_random_pad_geometry_and_labels():
    pyrandom.seed(1)
    aug = DetRandomPadAug(area_range=(1.5, 2.5), pad_val=(9, 9, 9),
                          max_attempts=50)
    src, lab = aug(_img(), _label())
    arr = src.asnumpy()
    assert arr.shape[0] >= 40 and arr.shape[1] >= 60
    assert arr.shape[0] * arr.shape[1] > 40 * 60  # actually padded
    # padded area exists and carries pad_val
    orig = _img().asnumpy()
    # labels stay normalized within the canvas, boxes shrink
    assert (lab[:, 1:5] >= 0).all() and (lab[:, 1:5] <= 1).all()
    w0 = _label()[:, 3] - _label()[:, 1]
    assert ((lab[:, 3] - lab[:, 1]) < w0 + 1e-6).all()
    # the original pixels appear somewhere intact: find offset via label
    # transform inverse is complex; instead check pad_val present
    assert (arr == 9.0).any()
    # original pixel content preserved (some row of original exists)
    assert onp.isclose(arr.sum(), orig.sum() +
                       9.0 * (arr.size - orig.size), rtol=1e-4)


def test_select_aug_skip_prob():
    pyrandom.seed(0)
    aug = DetRandomSelectAug([DetHorizontalFlipAug(2.0)], skip_prob=0)
    src, lab = aug(_img(), _label())
    onp.testing.assert_allclose(src.asnumpy(), _img().asnumpy()[:, ::-1])
    aug = DetRandomSelectAug([], skip_prob=0)  # empty -> always skip
    src, lab = aug(_img(), _label())
    onp.testing.assert_allclose(src.asnumpy(), _img().asnumpy())


def test_create_det_augmenter_chain():
    augs = CreateDetAugmenter((3, 32, 32), resize=48, rand_crop=0.5,
                              rand_pad=0.5, rand_mirror=True,
                              brightness=0.1, contrast=0.1, hue=0.05,
                              pca_noise=0.01, rand_gray=0.1,
                              mean=True, std=True)
    names = [type(a).__name__ for a in augs]
    assert names.count("DetRandomSelectAug") == 2  # crop + pad selectors
    assert "DetHorizontalFlipAug" in names
    # chain runs end to end per sample
    pyrandom.seed(0)
    src, lab = _img(64, 64), _label()
    for a in augs:
        src, lab = a(src, lab)
    assert src.asnumpy().shape == (32, 32, 3)
    d = a.dumps() if hasattr(a, "dumps") else None
    assert d is not None


def test_multi_rand_crop_augmenter():
    aug = CreateMultiRandCropAugmenter(
        min_object_covered=[0.1, 0.5], area_range=[(0.1, 1.0), (0.3, 1.0)],
        skip_prob=0)
    assert len(aug.aug_list) == 2
    assert aug.aug_list[1].min_object_covered == 0.5


def _write_dataset(tmpdir, n=6):
    paths, items = [], []
    for i in range(n):
        rs = onp.random.RandomState(i)
        img = rs.randint(0, 255, (50 + 4 * i, 60, 3)).astype(onp.uint8)
        path = os.path.join(tmpdir, f"im{i}.jpg")
        with open(path, "wb") as f:
            f.write(mx.image.imencode(np.array(img.astype(onp.float32))))
        # packed det label: header_w=2, obj_w=5, then (1 + i % 2) objects
        objs = [[float(i % 3), 0.1, 0.2, 0.7, 0.8]]
        if i % 2:
            objs.append([1.0, 0.3, 0.3, 0.9, 0.95])
        lab = [2.0, 5.0] + [v for o in objs for v in o]
        items.append(lab + [f"im{i}.jpg"])
    return items


def test_image_det_iter_end_to_end(tmp_path):
    items = _write_dataset(str(tmp_path))
    it = ImageDetIter(batch_size=4, data_shape=(3, 32, 32),
                      imglist=items, path_root=str(tmp_path),
                      rand_crop=0.5, rand_mirror=True, rand_pad=0.5,
                      brightness=0.1, mean=True, std=True)
    assert it.label_shape == (2, 5)
    assert it.provide_label[0][1] == (4, 2, 5)
    batches = list(it)
    assert len(batches) == 2
    for b in batches:
        data = b.data[0].asnumpy()
        lab = b.label[0].asnumpy()
        assert data.shape == (4, 3, 32, 32)
        assert lab.shape == (4, 2, 5)
        for s in range(4 - b.pad):
            valid = lab[s][lab[s, :, 0] >= 0]
            assert valid.shape[0] >= 1
            assert (valid[:, 3] > valid[:, 1]).all()
            assert (valid[:, 4] > valid[:, 2]).all()
        # -1 padding intact where no object
        assert (lab[lab[:, :, 0] < 0] == -1).all()


def test_image_det_iter_reshape_and_sync(tmp_path):
    items = _write_dataset(str(tmp_path))
    it = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                      imglist=items, path_root=str(tmp_path))
    it2 = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                       imglist=items[:1], path_root=str(tmp_path))
    assert it2.label_shape[0] <= it.label_shape[0]
    it.sync_label_shape(it2)
    assert it.label_shape == it2.label_shape
    with pytest.raises(ValueError):
        it.reshape(label_shape=(0, 5))
    it.reshape(data_shape=(3, 48, 48))
    b = next(it)
    assert b.data[0].shape == (2, 3, 48, 48)


def test_custom_aug_list_tail_split_keeps_label_augs(tmp_path):
    """A label-coupled augmenter AFTER the cast stage must still run
    per-sample, not be silently dropped from the batched tail."""
    from mxnet_tpu import image as _img

    items = _write_dataset(str(tmp_path))
    flip = DetHorizontalFlipAug(2.0)  # always flips
    aug_list = [
        DetBorrowAug(_img.ForceResizeAug((32, 32))),
        DetBorrowAug(_img.CastAug()),
        flip,
    ]
    it = ImageDetIter(batch_size=2, data_shape=(3, 32, 32),
                      imglist=items[:2], path_root=str(tmp_path),
                      aug_list=aug_list)
    # the flip is not a DetBorrowAug: it must be in the per-sample prefix
    assert it._batch_tail_start == len(aug_list)
    b = next(it)
    lab = b.label[0].asnumpy()
    # all written labels had x1=0.1, x2=0.7 (or the second object's):
    # after a guaranteed flip, x1 = 1-0.7 = 0.3 for the first object
    first = lab[0][lab[0, :, 0] >= 0][0]
    assert abs(first[1] - 0.3) < 1e-5 or abs(first[1] - 0.1) > 1e-5
    assert first[3] - first[1] > 0

"""INT8 quantization tests.

Model of the reference's tests/python/quantization/test_quantization.py:
quantize/dequantize numeric oracles, quantized FC/conv vs fp32, and the
quantize_net driver with each calibration mode.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import npx
from mxnet_tpu.contrib import quantization as qz
from mxnet_tpu.gluon import nn


def _rand(*shape, seed=0, scale=1.0):
    return (onp.random.RandomState(seed).randn(*shape) * scale).astype(
        "float32")


def test_quantize_dequantize_roundtrip():
    x = mx.np.array(_rand(4, 16))
    q, mn, mxr = npx.quantize_v2(x)
    assert q.dtype == onp.int8
    back = npx.dequantize(q, mn, mxr)
    err = onp.abs(back.asnumpy() - x.asnumpy()).max()
    # one int8 step of the symmetric range
    assert err <= float(mxr.asnumpy()) / 127 + 1e-6


def test_quantize_with_calib_range_clips():
    x = mx.np.array(onp.asarray([[-5.0, -1.0, 0.0, 1.0, 5.0]], "float32"))
    q, mn, mxr = npx.quantize_v2(x, -2.0, 2.0)
    qn = q.asnumpy()
    assert qn[0, 0] == -127 and qn[0, -1] == 127      # clipped
    assert qn[0, 2] == 0                               # symmetric zero
    back = npx.dequantize(q, mn, mxr).asnumpy()
    onp.testing.assert_allclose(back[0, 1], -1.0, atol=2.0 / 127)


def test_quantized_fully_connected_vs_fp32():
    x = _rand(8, 32, seed=1)
    w = _rand(16, 32, seed=2, scale=0.5)
    b = _rand(16, seed=3)
    want = x @ w.T + b
    qw, w_scale = qz._quantize_weight(w)
    T = float(onp.abs(x).max())
    xq, _, _ = npx.quantize_v2(mx.np.array(x), -T, T)
    out = npx.quantized_fully_connected(
        xq, mx.np.array(qw), T / 127, mx.np.array(w_scale),
        bias=mx.np.array(b))
    rel = onp.abs(out.asnumpy() - want).max() / onp.abs(want).max()
    assert rel < 0.05, rel


def test_quantized_conv_vs_fp32():
    import jax
    from jax import lax
    x = _rand(2, 3, 8, 8, seed=1)
    w = _rand(4, 3, 3, 3, seed=2, scale=0.3)
    want = onp.asarray(lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)]))
    qw, w_scale = qz._quantize_weight(w)
    T = float(onp.abs(x).max())
    xq, _, _ = npx.quantize_v2(mx.np.array(x), -T, T)
    out = npx.quantized_conv(
        xq, mx.np.array(qw), T / 127, mx.np.array(w_scale),
        kernel=(3, 3), pad=(1, 1), num_filter=4)
    rel = onp.abs(out.asnumpy() - want).max() / onp.abs(want).max()
    assert rel < 0.06, rel


def test_optimal_threshold_gaussian():
    """KL threshold of a heavy-tailed histogram must clip the tail."""
    rs = onp.random.RandomState(0)
    a = onp.abs(rs.randn(100000)).astype(onp.float32)
    a[0] = 40.0  # one extreme outlier
    hist, edges = onp.histogram(a, bins=2048, range=(0, 40.0))
    t = qz.optimal_threshold(hist, edges)
    assert 2.0 < t < 20.0, t


@pytest.mark.parametrize("mode", ["naive", "entropy", "percentile"])
def test_quantize_net_mlp(mode):
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(10))
    net.initialize()
    calib = [mx.np.array(_rand(64, 20, seed=i)) for i in range(8)]
    net(calib[0])
    qnet = qz.quantize_net(net, calib_data=calib, calib_mode=mode)
    x = mx.np.array(_rand(64, 20, seed=9))
    want = net(x).asnumpy()
    got = qnet(x).asnumpy()
    # entropy/percentile clip outliers by design: judge by mean error and
    # prediction stability; 'naive' (minmax) additionally bounds max error
    mean_rel = onp.abs(got - want).mean() / (onp.abs(want).mean() + 1e-9)
    # KL calibration deliberately clips ~2-3 sigma on gaussian-ish data,
    # so its numeric error is larger than minmax by construction
    assert mean_rel < (0.3 if mode != "naive" else 0.1), (mode, mean_rel)
    agree = (got.argmax(-1) == want.argmax(-1)).mean()
    assert agree >= 0.85, (mode, agree)
    if mode == "naive":
        rel = onp.abs(got - want).max() / (onp.abs(want).max() + 1e-9)
        assert rel < 0.1, rel
    # original net untouched
    assert isinstance(net[0], nn.Dense)
    assert isinstance(qnet[0], qz.QuantizedDense)


def test_quantize_net_convnet_and_exclude():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1, activation="relu"),
            nn.MaxPool2D(2, 2), nn.Flatten(),
            nn.Dense(16, activation="relu"), nn.Dense(10))
    net.initialize()
    calib = [mx.np.array(_rand(4, 3, 8, 8, seed=i)) for i in range(3)]
    net(calib[0])
    qnet = qz.quantize_net(net, calib_data=calib, calib_mode="naive",
                           exclude_layers=["4"])
    assert isinstance(qnet[0], qz.QuantizedConv)
    assert isinstance(qnet[3], qz.QuantizedDense)
    assert isinstance(qnet[4], nn.Dense)          # excluded stays fp32
    x = mx.np.array(_rand(4, 3, 8, 8, seed=7))
    rel = onp.abs(qnet(x).asnumpy() - net(x).asnumpy()).max() / \
        (onp.abs(net(x).asnumpy()).max() + 1e-9)
    assert rel < 0.15, rel


def test_quantize_net_int8_weights_stored():
    net = nn.HybridSequential()
    net.add(nn.Dense(4))
    net.initialize()
    calib = [mx.np.array(_rand(2, 6))]
    net(calib[0])
    qnet = qz.quantize_net(net, calib_data=calib)
    assert qnet[0].qweight.data().dtype == onp.int8


def test_quantize_net_hybridized_runs():
    """Quantized net must survive hybridize (jit compile) since the int8
    matmul path is pure lax."""
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    calib = [mx.np.array(_rand(4, 10, seed=i)) for i in range(2)]
    net(calib[0])
    qnet = qz.quantize_net(net, calib_data=calib)
    qnet.hybridize()
    x = mx.np.array(_rand(4, 10, seed=5))
    a = qnet(x).asnumpy()
    b = qnet(x).asnumpy()     # second call: compiled path
    onp.testing.assert_allclose(a, b, rtol=1e-6)


def test_quantize_net_of_hybridized_net():
    """Deep-copying a hybridized net must reset its compiled cache
    (locks/executables are process-local); quantize_net exercises it."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu.contrib import quantization as q
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1), nn.Activation("relu"),
            nn.Dense(3))
    net.initialize()
    x = mx.np.array(onp.random.RandomState(0)
                    .rand(2, 2, 8, 8).astype("float32"))
    net.hybridize()
    net(x)  # builds the compiled cache (incl. the RW lock)
    qnet = q.quantize_net(net, calib_data=[x], calib_mode="naive")
    out_q = qnet(x)
    assert out_q.shape == (2, 3)
    # the original still replays through its untouched cache
    assert net(x).shape == (2, 3)
    # and a plain deepcopy of a hybridized net works + retraces
    import copy
    net2 = copy.deepcopy(net)
    assert net2(x).shape == (2, 3)
    assert net2._cached_graphs is not net._cached_graphs


# -- calibration observers (satellite: explicit oracles) ---------------------

def test_percentile_threshold_clips_tail():
    rs = onp.random.RandomState(1)
    a = onp.abs(rs.randn(50000)).astype(onp.float32)
    a[0] = 30.0  # outlier that minmax would calibrate to
    hist, edges = onp.histogram(a, bins=2048, range=(0, 30.0))
    t = qz._percentile_threshold(hist, edges, percentile=99.99)
    inlier99 = onp.percentile(a[1:], 99)
    assert inlier99 < t < 30.0, t


@pytest.mark.parametrize("mode", ["entropy", "percentile"])
def test_observer_threshold_bounds_quantize_error(mode):
    """Quantize -> dequantize under a calibrated threshold: values inside
    the threshold err by at most one int8 step; the outlier-clipping step
    size must beat minmax's on the inlier mass."""
    rs = onp.random.RandomState(2)
    a = rs.randn(50000).astype(onp.float32)
    a[0] = 25.0
    amax = float(onp.abs(a).max())
    hist, edges = onp.histogram(onp.abs(a), bins=2048, range=(0, amax))
    t = (qz.optimal_threshold(hist, edges) if mode == "entropy"
         else qz._percentile_threshold(hist, edges))
    assert t < amax  # the whole point: clip the tail
    x = mx.np.array(a.reshape(100, 500))
    q, mn, mxr = npx.quantize_v2(x, -t, t)
    back = npx.dequantize(q, mn, mxr).asnumpy().ravel()
    inlier = onp.abs(a) <= t
    step = t / 127.0
    assert (onp.abs(back[inlier] - a[inlier]) <= step / 2 + 1e-6).all()
    assert step < amax / 127.0  # finer than the minmax grid
    # clipped values saturate at the threshold, not explode
    assert abs(back[0] - t) <= step


# -- fused low-bit dense path (tentpole) -------------------------------------

def _fused_inputs(m=24, k=40, n=12, seed=0, scale=1.0):
    x = _rand(m, k, seed=seed, scale=scale)
    w = _rand(n, k, seed=seed + 1, scale=0.5)
    qw, w_scale = qz._quantize_weight(w)
    x_scale = float(onp.abs(x).max()) / 127.0
    return (mx.np.array(x), mx.np.array(qw), x_scale,
            mx.np.array(w_scale), x @ w.T)


def _with_route(mode, fn):
    from mxnet_tpu import config
    prev = config.set("quantize.fused_matmul", mode)
    try:
        return fn()
    finally:
        config.set("quantize.fused_matmul", prev)


def test_route_knob_controls_pallas_dispatch():
    from mxnet_tpu.ops import quantization as oq
    assert _with_route("off", oq._route_fused) == (False, False)
    use, interpret = _with_route("on", oq._route_fused)
    assert use  # forced on: Pallas everywhere, interpret off-TPU
    import jax
    on_tpu = jax.devices()[0].platform == "tpu"
    assert interpret == (not on_tpu)
    use_auto, _ = _with_route("auto", oq._route_fused)
    assert use_auto == on_tpu  # auto never interprets off-TPU


def test_fused_dense_pallas_matches_fallback_bitwise():
    """The Pallas kernel (interpret on CPU) and the XLA fallback chain
    quantize identically and accumulate in exact int32 — without a bias
    the fused epilogue is a single multiply, so parity is bitwise."""
    x, qw, xs, ws, _ = _fused_inputs()
    a = _with_route("on", lambda: npx.quantized_dense_fused(
        x, qw, xs, ws)).asnumpy()
    b = _with_route("off", lambda: npx.quantized_dense_fused(
        x, qw, xs, ws)).asnumpy()
    assert (a == b).all()


def test_fused_dense_pallas_matches_fallback_with_bias():
    # with a bias the kernel may contract mul+add into an FMA: allow one
    # ulp, nothing more
    x, qw, xs, ws, _ = _fused_inputs(seed=3)
    b = mx.np.array(_rand(12, seed=5))
    out_p = _with_route("on", lambda: npx.quantized_dense_fused(
        x, qw, xs, ws, bias=b)).asnumpy()
    out_x = _with_route("off", lambda: npx.quantized_dense_fused(
        x, qw, xs, ws, bias=b)).asnumpy()
    onp.testing.assert_allclose(out_p, out_x, rtol=0, atol=1e-5)


def test_fused_dense_nonaligned_shapes_bitwise():
    """Zero padding to tile boundaries is exact for symmetric int8
    (0 quantizes to 0, contributes 0 to the dot): odd M/K/N must still be
    bitwise against the unpadded fallback."""
    for m, k, n in [(1, 7, 3), (5, 33, 7), (130, 257, 129)]:
        x, qw, xs, ws, _ = _fused_inputs(m=m, k=k, n=n, seed=m)
        a = _with_route("on", lambda: npx.quantized_dense_fused(
            x, qw, xs, ws)).asnumpy()
        b = _with_route("off", lambda: npx.quantized_dense_fused(
            x, qw, xs, ws)).asnumpy()
        assert (a == b).all(), (m, k, n)


@pytest.mark.parametrize("act", ["relu", "sigmoid", "tanh", "gelu"])
def test_fused_dense_activation_epilogue(act):
    x, qw, xs, ws, _ = _fused_inputs(seed=7)
    b = mx.np.array(_rand(12, seed=8))
    out = _with_route("on", lambda: npx.quantized_dense_fused(
        x, qw, xs, ws, bias=b, act=act)).asnumpy()
    ref = _with_route("off", lambda: npx.quantized_dense_fused(
        x, qw, xs, ws, bias=b, act=act)).asnumpy()
    onp.testing.assert_allclose(out, ref, rtol=0, atol=1e-5)
    if act == "relu":
        assert (out >= 0).all()


def test_fused_dense_rejects_unfusable_act():
    x, qw, xs, ws, _ = _fused_inputs()
    with pytest.raises(ValueError):
        npx.quantized_dense_fused(x, qw, xs, ws, act="softmax")


def test_fused_dense_matches_unfused_chain():
    """Fused single-op path reproduces the documented fallback pair
    (quantize_v2 -> quantized_fully_connected) it replaces."""
    x, qw, xs, ws, want = _fused_inputs(seed=9)
    fused = npx.quantized_dense_fused(x, qw, xs, ws).asnumpy()
    T = xs * 127.0
    xq, _, _ = npx.quantize_v2(x, -T, T)
    chain = npx.quantized_fully_connected(xq, qw, xs, ws).asnumpy()
    onp.testing.assert_allclose(fused, chain, rtol=0, atol=1e-5)
    rel = onp.abs(fused - want).max() / onp.abs(want).max()
    assert rel < 0.05, rel


def test_quantize_net_uses_fused_dense_path():
    """QuantizedDense forwards through quantized_dense_fused with the act
    folded into the epilogue; output must match the net built before the
    rewiring (same numerics as the fallback chain + eager act)."""
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(10))
    net.initialize()
    calib = [mx.np.array(_rand(16, 20, seed=i)) for i in range(4)]
    net(calib[0])
    qnet = qz.quantize_net(net, calib_data=calib, calib_mode="naive")
    assert qnet[0]._fused_act == "relu"
    x = mx.np.array(_rand(16, 20, seed=9))
    got = _with_route("on", lambda: qnet(x)).asnumpy()
    ref = _with_route("off", lambda: qnet(x)).asnumpy()
    onp.testing.assert_allclose(got, ref, rtol=0, atol=1e-4)


# -- fp8 variant -------------------------------------------------------------

def test_fp8_capable_is_gated_off_cpu():
    from mxnet_tpu.ops.pallas.quant_matmul import fp8_capable
    import jax
    if jax.devices()[0].platform != "tpu":
        assert not fp8_capable()


@pytest.mark.parametrize("fmt", ["e4m3", "e5m2"])
def test_fp8_dense_fused_error_bounds(fmt):
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.quant_matmul import FP8_FORMATS
    x = _rand(16, 64, seed=1)
    w = _rand(8, 64, seed=2, scale=0.5)
    dt, absmax = FP8_FORMATS[fmt]
    w_scale = onp.abs(w).max(axis=1) / absmax
    wq = mx.np.array(jnp.asarray(w / w_scale[:, None]).astype(dt))
    x_scale = float(onp.abs(x).max()) / absmax
    out = npx.fp8_dense_fused(mx.np.array(x), wq, x_scale,
                              mx.np.array(w_scale), fmt=fmt).asnumpy()
    want = x @ w.T
    rel = onp.abs(out - want).max() / onp.abs(want).max()
    # e4m3: 3 mantissa bits (~6% element error); e5m2: 2 bits (~12%) —
    # K=64 accumulation averages much of it out
    assert rel < (0.08 if fmt == "e4m3" else 0.2), (fmt, rel)


def test_fp8_dense_fused_pallas_matches_fallback():
    import jax.numpy as jnp
    from mxnet_tpu.ops.pallas.quant_matmul import FP8_FORMATS
    x = _rand(9, 33, seed=4)
    w = _rand(5, 33, seed=5, scale=0.5)
    dt, absmax = FP8_FORMATS["e4m3"]
    w_scale = onp.abs(w).max(axis=1) / absmax
    wq = mx.np.array(jnp.asarray(w / w_scale[:, None]).astype(dt))
    xs = float(onp.abs(x).max()) / absmax
    a = _with_route("on", lambda: npx.fp8_dense_fused(
        mx.np.array(x), wq, xs, mx.np.array(w_scale))).asnumpy()
    b = _with_route("off", lambda: npx.fp8_dense_fused(
        mx.np.array(x), wq, xs, mx.np.array(w_scale))).asnumpy()
    onp.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-6)


def test_fp8_dense_fused_rejects_unknown_format():
    x, qw, xs, ws, _ = _fused_inputs()
    with pytest.raises(ValueError):
        npx.fp8_dense_fused(x, qw, xs, ws, fmt="e3m4")


def test_fused_conv_matches_unfused_chain():
    from jax import lax
    x = _rand(2, 3, 8, 8, seed=1)
    w = _rand(4, 3, 3, 3, seed=2, scale=0.3)
    b = _rand(4, seed=3)
    qw, w_scale = qz._quantize_weight(w)
    T = float(onp.abs(x).max())
    fused = npx.quantized_conv_fused(
        mx.np.array(x), mx.np.array(qw), T / 127, mx.np.array(w_scale),
        bias=mx.np.array(b), act="relu", kernel=(3, 3), pad=(1, 1),
        num_filter=4).asnumpy()
    xq, _, _ = npx.quantize_v2(mx.np.array(x), -T, T)
    chain = npx.quantized_conv(
        xq, mx.np.array(qw), T / 127, mx.np.array(w_scale),
        kernel=(3, 3), pad=(1, 1), num_filter=4).asnumpy()
    ref = onp.maximum(chain + b[None, :, None, None], 0.0)
    onp.testing.assert_allclose(fused, ref, rtol=0, atol=1e-4)
    assert (fused >= 0).all()

"""INT8 quantization tests.

Model of the reference's tests/python/quantization/test_quantization.py:
quantize/dequantize numeric oracles, quantized FC/conv vs fp32, and the
quantize_net driver with each calibration mode.
"""
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import npx
from mxnet_tpu.contrib import quantization as qz
from mxnet_tpu.gluon import nn


def _rand(*shape, seed=0, scale=1.0):
    return (onp.random.RandomState(seed).randn(*shape) * scale).astype(
        "float32")


def test_quantize_dequantize_roundtrip():
    x = mx.np.array(_rand(4, 16))
    q, mn, mxr = npx.quantize_v2(x)
    assert q.dtype == onp.int8
    back = npx.dequantize(q, mn, mxr)
    err = onp.abs(back.asnumpy() - x.asnumpy()).max()
    # one int8 step of the symmetric range
    assert err <= float(mxr.asnumpy()) / 127 + 1e-6


def test_quantize_with_calib_range_clips():
    x = mx.np.array(onp.asarray([[-5.0, -1.0, 0.0, 1.0, 5.0]], "float32"))
    q, mn, mxr = npx.quantize_v2(x, -2.0, 2.0)
    qn = q.asnumpy()
    assert qn[0, 0] == -127 and qn[0, -1] == 127      # clipped
    assert qn[0, 2] == 0                               # symmetric zero
    back = npx.dequantize(q, mn, mxr).asnumpy()
    onp.testing.assert_allclose(back[0, 1], -1.0, atol=2.0 / 127)


def test_quantized_fully_connected_vs_fp32():
    x = _rand(8, 32, seed=1)
    w = _rand(16, 32, seed=2, scale=0.5)
    b = _rand(16, seed=3)
    want = x @ w.T + b
    qw, w_scale = qz._quantize_weight(w)
    T = float(onp.abs(x).max())
    xq, _, _ = npx.quantize_v2(mx.np.array(x), -T, T)
    out = npx.quantized_fully_connected(
        xq, mx.np.array(qw), T / 127, mx.np.array(w_scale),
        bias=mx.np.array(b))
    rel = onp.abs(out.asnumpy() - want).max() / onp.abs(want).max()
    assert rel < 0.05, rel


def test_quantized_conv_vs_fp32():
    import jax
    from jax import lax
    x = _rand(2, 3, 8, 8, seed=1)
    w = _rand(4, 3, 3, 3, seed=2, scale=0.3)
    want = onp.asarray(lax.conv_general_dilated(
        x, w, (1, 1), [(1, 1), (1, 1)]))
    qw, w_scale = qz._quantize_weight(w)
    T = float(onp.abs(x).max())
    xq, _, _ = npx.quantize_v2(mx.np.array(x), -T, T)
    out = npx.quantized_conv(
        xq, mx.np.array(qw), T / 127, mx.np.array(w_scale),
        kernel=(3, 3), pad=(1, 1), num_filter=4)
    rel = onp.abs(out.asnumpy() - want).max() / onp.abs(want).max()
    assert rel < 0.06, rel


def test_optimal_threshold_gaussian():
    """KL threshold of a heavy-tailed histogram must clip the tail."""
    rs = onp.random.RandomState(0)
    a = onp.abs(rs.randn(100000)).astype(onp.float32)
    a[0] = 40.0  # one extreme outlier
    hist, edges = onp.histogram(a, bins=2048, range=(0, 40.0))
    t = qz.optimal_threshold(hist, edges)
    assert 2.0 < t < 20.0, t


@pytest.mark.parametrize("mode", ["naive", "entropy", "percentile"])
def test_quantize_net_mlp(mode):
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu"), nn.Dense(10))
    net.initialize()
    calib = [mx.np.array(_rand(64, 20, seed=i)) for i in range(8)]
    net(calib[0])
    qnet = qz.quantize_net(net, calib_data=calib, calib_mode=mode)
    x = mx.np.array(_rand(64, 20, seed=9))
    want = net(x).asnumpy()
    got = qnet(x).asnumpy()
    # entropy/percentile clip outliers by design: judge by mean error and
    # prediction stability; 'naive' (minmax) additionally bounds max error
    mean_rel = onp.abs(got - want).mean() / (onp.abs(want).mean() + 1e-9)
    # KL calibration deliberately clips ~2-3 sigma on gaussian-ish data,
    # so its numeric error is larger than minmax by construction
    assert mean_rel < (0.3 if mode != "naive" else 0.1), (mode, mean_rel)
    agree = (got.argmax(-1) == want.argmax(-1)).mean()
    assert agree >= 0.85, (mode, agree)
    if mode == "naive":
        rel = onp.abs(got - want).max() / (onp.abs(want).max() + 1e-9)
        assert rel < 0.1, rel
    # original net untouched
    assert isinstance(net[0], nn.Dense)
    assert isinstance(qnet[0], qz.QuantizedDense)


def test_quantize_net_convnet_and_exclude():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, kernel_size=3, padding=1, activation="relu"),
            nn.MaxPool2D(2, 2), nn.Flatten(),
            nn.Dense(16, activation="relu"), nn.Dense(10))
    net.initialize()
    calib = [mx.np.array(_rand(4, 3, 8, 8, seed=i)) for i in range(3)]
    net(calib[0])
    qnet = qz.quantize_net(net, calib_data=calib, calib_mode="naive",
                           exclude_layers=["4"])
    assert isinstance(qnet[0], qz.QuantizedConv)
    assert isinstance(qnet[3], qz.QuantizedDense)
    assert isinstance(qnet[4], nn.Dense)          # excluded stays fp32
    x = mx.np.array(_rand(4, 3, 8, 8, seed=7))
    rel = onp.abs(qnet(x).asnumpy() - net(x).asnumpy()).max() / \
        (onp.abs(net(x).asnumpy()).max() + 1e-9)
    assert rel < 0.15, rel


def test_quantize_net_int8_weights_stored():
    net = nn.HybridSequential()
    net.add(nn.Dense(4))
    net.initialize()
    calib = [mx.np.array(_rand(2, 6))]
    net(calib[0])
    qnet = qz.quantize_net(net, calib_data=calib)
    assert qnet[0].qweight.data().dtype == onp.int8


def test_quantize_net_hybridized_runs():
    """Quantized net must survive hybridize (jit compile) since the int8
    matmul path is pure lax."""
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    calib = [mx.np.array(_rand(4, 10, seed=i)) for i in range(2)]
    net(calib[0])
    qnet = qz.quantize_net(net, calib_data=calib)
    qnet.hybridize()
    x = mx.np.array(_rand(4, 10, seed=5))
    a = qnet(x).asnumpy()
    b = qnet(x).asnumpy()     # second call: compiled path
    onp.testing.assert_allclose(a, b, rtol=1e-6)


def test_quantize_net_of_hybridized_net():
    """Deep-copying a hybridized net must reset its compiled cache
    (locks/executables are process-local); quantize_net exercises it."""
    import numpy as onp

    import mxnet_tpu as mx
    from mxnet_tpu.contrib import quantization as q
    from mxnet_tpu.gluon import nn

    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1), nn.Activation("relu"),
            nn.Dense(3))
    net.initialize()
    x = mx.np.array(onp.random.RandomState(0)
                    .rand(2, 2, 8, 8).astype("float32"))
    net.hybridize()
    net(x)  # builds the compiled cache (incl. the RW lock)
    qnet = q.quantize_net(net, calib_data=[x], calib_mode="naive")
    out_q = qnet(x)
    assert out_q.shape == (2, 3)
    # the original still replays through its untouched cache
    assert net(x).shape == (2, 3)
    # and a plain deepcopy of a hybridized net works + retraces
    import copy
    net2 = copy.deepcopy(net)
    assert net2(x).shape == (2, 3)
    assert net2._cached_graphs is not net._cached_graphs

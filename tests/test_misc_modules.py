"""Tests for the 1.x-parity top-level modules: viz, callback, model
checkpoints, operator (CustomOp), name/attribute scopes, error types,
dlpack, libinfo, rtc (reference: the same-named python/mxnet modules)."""
import logging
import os

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.base import MXNetError
from mxnet_tpu.gluon import nn


def test_print_summary_block(capsys):
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(4))
    net.initialize()
    net(mx.np.ones((2, 8)))
    total = mx.viz.print_summary(net, shape=(2, 8))
    out = capsys.readouterr().out
    assert "Dense" in out and "Total params" in out
    assert total == 8 * 16 + 16 + 16 * 4 + 4


def test_plot_network_dot_source():
    a = mx.sym.var("a")
    b = mx.sym.var("b")
    c = mx.sym.matmul(a, b)
    src = mx.visualization.dot_graph(c)
    assert src.startswith("digraph") and "matmul" in src
    out = mx.viz.plot_network(c)
    assert "matmul" in (out if isinstance(out, str) else out.source)


def test_speedometer_logs(caplog):
    from mxnet_tpu.callback import BatchEndParam, Speedometer
    metric = mx.gluon.metric.Accuracy()
    metric.update(mx.np.array([1, 0]), mx.np.array([[0.1, 0.9],
                                                    [0.2, 0.8]]))
    speedo = Speedometer(batch_size=2, frequent=2, auto_reset=False)
    with caplog.at_level(logging.INFO):
        for i in range(5):
            speedo(BatchEndParam(epoch=0, nbatch=i, eval_metric=metric,
                                 locals=None))
    assert any("samples/sec" in r.message and "accuracy" in r.message
               for r in caplog.records)


def test_model_checkpoint_roundtrip(tmp_path):
    prefix = str(tmp_path / "net")
    a = mx.sym.var("a")
    sym = mx.sym.tanh(a)
    arg = {"weight": mx.np.ones((2, 3))}
    aux = {"mean": mx.np.zeros((3,))}
    path = mx.model.save_checkpoint(prefix, 7, sym, arg, aux)
    assert path.endswith("-0007.params")
    sym2, arg2, aux2 = mx.model.load_checkpoint(prefix, 7)
    assert sym2 is not None
    onp.testing.assert_array_equal(arg2["weight"].asnumpy(),
                                   arg["weight"].asnumpy())
    onp.testing.assert_array_equal(aux2["mean"].asnumpy(),
                                   aux["mean"].asnumpy())
    # interchange check: the params file is the legacy binary format
    from mxnet_tpu import serialization
    assert serialization.is_legacy_params(f"{prefix}-0007.params")


def test_custom_op_forward_backward():
    class MyRelu(mx.operator.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0],
                        mx.np.maximum(in_data[0], 0.0))

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            mask = (in_data[0].asnumpy() > 0).astype("float32")
            self.assign(in_grad[0], req[0], out_grad[0] * mx.np.array(mask))

    @mx.operator.register("test_my_relu")
    class MyReluProp(mx.operator.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return MyRelu()

    x = mx.np.array([[-1.0, 2.0], [3.0, -4.0]])
    y = mx.nd.Custom(x, op_type="test_my_relu")
    onp.testing.assert_allclose(y.asnumpy(), [[0, 2], [3, 0]])

    x.attach_grad()
    with autograd.record():
        out = mx.nd.Custom(x, op_type="test_my_relu")
        loss = out.sum()
    loss.backward()
    onp.testing.assert_allclose(x.grad.asnumpy(), [[0, 1], [1, 0]])


def test_custom_op_unregistered_raises():
    with pytest.raises(MXNetError, match="not registered"):
        mx.nd.Custom(mx.np.ones(3), op_type="nope")


def test_name_manager_scopes():
    from mxnet_tpu.name import NameManager, Prefix
    with NameManager():
        s1 = mx.sym.var("x") + 1.0
        s2 = mx.sym.var("y") + 2.0
        assert s1.name != s2.name
    with Prefix("block_"):
        s3 = mx.sym.var("z") * 2.0
        assert s3.name.startswith("block_")


def test_attr_scope_nesting():
    from mxnet_tpu.attribute import AttrScope, current
    with AttrScope(ctx_group="dev1"):
        assert current().get()["ctx_group"] == "dev1"
        with AttrScope(stage="2"):
            got = current().get()
            assert got["ctx_group"] == "dev1" and got["stage"] == "2"
        assert "stage" not in current().get()
    assert "ctx_group" not in current().get()
    with pytest.raises(ValueError):
        AttrScope(bad=3)


def test_error_types_mix_with_builtins():
    from mxnet_tpu import error
    assert issubclass(error.ValueError, ValueError)
    assert issubclass(error.ValueError, MXNetError)
    with pytest.raises(ValueError):
        raise error.ValueError("boom")
    with pytest.raises(MXNetError):
        raise error.TypeError("boom")


def test_dlpack_interop_with_numpy_and_torch():
    x = mx.np.array([[1.0, 2.0], [3.0, 4.0]])
    back = mx.dlpack.from_dlpack(x._data)      # jax array speaks dlpack
    onp.testing.assert_array_equal(back.asnumpy(), x.asnumpy())
    try:
        import torch
    except ImportError:
        return
    t = torch.tensor([1.0, 5.0])
    got = mx.dlpack.from_dlpack(t)
    onp.testing.assert_allclose(got.asnumpy(), [1.0, 5.0])


def test_rtc_raises_with_pointer():
    with pytest.raises(MXNetError, match="Pallas"):
        mx.rtc.CudaModule("kernel source")


def test_libinfo_and_executor_module():
    assert isinstance(mx.libinfo.find_lib_path(), list)
    from mxnet_tpu.executor import Executor
    a = mx.sym.var("a")
    exe = (a * 2).bind(args={"a": mx.np.ones(3)})
    assert isinstance(exe, Executor)
    onp.testing.assert_allclose(exe.forward()[0].asnumpy(), [2, 2, 2])


def test_prefix_scope_does_not_corrupt_reload():
    """Explicit names must survive load_json inside a Prefix scope
    (only auto-generated names are managed)."""
    from mxnet_tpu.name import Prefix
    a = mx.sym.var("x")
    net = mx.sym.tanh(a)
    js = net.tojson()
    with Prefix("net_"):
        back = mx.symbol.symbol.load_json(js)
        assert back.list_arguments() == ["x"]
        out = back.eval(x=mx.np.array([0.0]))[0]
    onp.testing.assert_allclose(out.asnumpy(), [0.0])


def test_corrupt_negative_dim_raises(tmp_path):
    import struct
    from mxnet_tpu import serialization as ser
    p = str(tmp_path / "w.params")
    ser.save_legacy_params(p, {"x": onp.ones((2, 2), "float32")})
    raw = bytearray(open(p, "rb").read())
    # shape dims start at offset 24 (header) + 12 (magic+stype+ndim)
    struct.pack_into("<q", raw, 36, -1)
    bad = str(tmp_path / "bad.params")
    open(bad, "wb").write(bytes(raw))
    with pytest.raises(MXNetError, match="negative dim"):
        ser.load_legacy_params(bad)


def test_symbolblock_from_symbol_and_checkpoint(tmp_path):
    """model.load_checkpoint -> SymbolBlock(sym, inputs, params) runs the
    1.x deployment path end to end (reference: block.py:1638 +
    model.py load_checkpoint)."""
    from mxnet_tpu import gluon
    data = mx.sym.var("data")
    w = mx.sym.var("weight")
    b = mx.sym.var("bias")
    out = mx.sym.tanh(mx.sym.matmul(data, w) + b)

    rs = onp.random.RandomState(0)
    arg = {"weight": mx.np.array(rs.randn(3, 4).astype("float32")),
           "bias": mx.np.array(rs.randn(4).astype("float32"))}
    prefix = str(tmp_path / "m")
    mx.model.save_checkpoint(prefix, 0, out, arg, {})

    sym, arg2, aux2 = mx.model.load_checkpoint(prefix, 0)
    net = gluon.SymbolBlock(sym, mx.sym.var("data"),
                            params={**arg2, **aux2})
    x = mx.np.array(rs.randn(2, 3).astype("float32"))
    got = net(x).asnumpy()
    want = onp.tanh(x.asnumpy() @ arg["weight"].asnumpy()
                    + arg["bias"].asnumpy())
    onp.testing.assert_allclose(got, want, rtol=1e-5)
    # hybridized (compiled) path gives identical values
    net.hybridize()
    onp.testing.assert_allclose(net(x).asnumpy(), got, rtol=1e-6)


def test_symbolblock_wrong_input_count():
    from mxnet_tpu import gluon
    a = mx.sym.var("a")
    net = gluon.SymbolBlock(mx.sym.tanh(a), a, params={})
    with pytest.raises(MXNetError, match="expects 1 inputs"):
        net(mx.np.ones(2), mx.np.ones(2))


def test_symbolblock_params_trainable_and_input_precedence():
    from mxnet_tpu import gluon
    data = mx.sym.var("data")
    w = mx.sym.var("w")
    out = mx.sym.matmul(data, w)
    rs = onp.random.RandomState(1)
    # params dict deliberately includes the input name: it must be
    # ignored so the live input wins
    params = {"w": mx.np.array(rs.randn(3, 2).astype("float32")),
              "data": mx.np.zeros((2, 3))}
    net = gluon.SymbolBlock(out, data, params=params)
    x1 = mx.np.array(rs.randn(2, 3).astype("float32"))
    x2 = mx.np.array(rs.randn(2, 3).astype("float32"))
    y1, y2 = net(x1).asnumpy(), net(x2).asnumpy()
    assert not onp.allclose(y1, y2)      # input actually used
    # params are trainable (reference: arg_params grad_req 'write')
    assert net.collect_params()["w"].grad_req == "write"
    with autograd.record():
        loss = (net(x1) ** 2).sum()
    loss.backward()
    g = net.collect_params()["w"].grad().asnumpy()
    assert onp.abs(g).sum() > 0


def test_symbolblock_rejects_non_symbol_outputs():
    from mxnet_tpu import gluon
    with pytest.raises(MXNetError, match="must be a Symbol"):
        gluon.SymbolBlock(object(), None, params={})


def test_image_border_and_scale_down():
    """copyMakeBorder / scale_down (reference image.py:214,249)."""
    import numpy as onp
    import pytest

    from mxnet_tpu.base import MXNetError

    img = mx.np.array(onp.arange(12, dtype="float32").reshape(2, 2, 3))
    out = mx.image.copyMakeBorder(img, 1, 0, 0, 1, value=9.0)
    assert out.shape == (3, 3, 3)
    assert float(out[0, 0, 0]) == 9.0      # constant fill
    assert float(out[1, 0, 0]) == 0.0      # original top-left
    # OpenCV codes: 1 = REPLICATE (edge), 2 = REFLECT (mirror)
    repl = mx.image.copyMakeBorder(img, 1, 1, 1, 1, type=1).asnumpy()
    assert repl.shape == (4, 4, 3)
    assert (repl[0, 1] == img.asnumpy()[0, 0]).all()  # edge-replicated
    refl = mx.image.copyMakeBorder(img, 1, 1, 1, 1, type=2).asnumpy()
    assert (refl[0, 1] == img.asnumpy()[0, 0]).all()  # mirror of row 0
    with pytest.raises(MXNetError):
        mx.image.copyMakeBorder(img, 1, 1, 1, 1, type=9)
    assert mx.image.scale_down((640, 480), (720, 120)) == (640, 106)
    assert mx.image.scale_down((100, 100), (50, 50)) == (50, 50)


def test_util_env_and_compat_tail():
    """getenv/setenv/set_np_shape/np_default_dtype/set_module/
    set_flush_denorms (reference util.py)."""
    import pytest

    from mxnet_tpu.base import MXNetError

    mx.util.setenv("MXNET_UTIL_TEST", "7")
    assert mx.util.getenv("MXNET_UTIL_TEST") == "7"
    mx.util.setenv("MXNET_UTIL_TEST", None)
    assert mx.util.getenv("MXNET_UTIL_TEST") is None
    assert mx.util.set_np_shape(True)
    with pytest.raises(MXNetError):
        mx.util.set_np_shape(False)
    assert mx.util.np_default_dtype() == "float32"
    assert mx.util.set_np_default_dtype(False) is False
    with pytest.raises(MXNetError):
        mx.util.set_np_default_dtype(True)
    assert mx.util.set_flush_denorms() is False
    assert mx.util.np_ufunc_legal_option("casting", "same_kind")
    assert not mx.util.np_ufunc_legal_option("dtype", "not-a-dtype")
    assert mx.util.np_ufunc_legal_option("dtype", "float32")

    @mx.util.set_module("mxnet_tpu.numpy")
    def f():
        pass
    assert f.__module__ == "mxnet_tpu.numpy"
    assert not mx.util.np_ufunc_legal_option("nonsense", 1)
    assert mx.util.np_ufunc_legal_option("casting", "unsafe")


def test_tools_rec2idx_and_parse_log(tmp_path):
    """rec2idx rebuilds a seekable .idx; parse_log tables epoch metrics
    (reference tools/rec2idx.py, tools/parse_log.py)."""
    import subprocess
    import sys

    from mxnet_tpu.recordio import MXIndexedRecordIO, MXRecordIO

    rec = str(tmp_path / "d.rec")
    w = MXRecordIO(rec, "w")
    payloads = [f"payload-{i}".encode() * (i + 1) for i in range(5)]
    for pb in payloads:
        w.write(pb)
    w.close()
    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "rec2idx.py")
    idx = str(tmp_path / "d.idx")
    r = subprocess.run([sys.executable, tool, rec, idx],
                       capture_output=True, text=True)
    assert r.returncode == 0 and "5 index entries" in r.stdout
    reader = MXIndexedRecordIO(idx, rec, "r")
    assert reader.read_idx(3) == payloads[3]
    assert reader.read_idx(0) == payloads[0]
    reader.close()

    log = tmp_path / "t.log"
    log.write_text(
        "INFO Epoch[0] Train-accuracy=0.5\n"
        "INFO Epoch[0] Validation-accuracy=0.4\n"
        "INFO Epoch[0] Time cost=12.5\n"
        "INFO Epoch[1] Train-accuracy=0.8\n"
        "INFO Epoch[1] Time cost=11.0\n")
    ptool = os.path.join(os.path.dirname(__file__), "..", "tools",
                         "parse_log.py")
    r2 = subprocess.run([sys.executable, ptool, str(log)],
                        capture_output=True, text=True)
    assert r2.returncode == 0
    assert "| 0 | 0.5 | 0.4 | 12.5 |" in r2.stdout
    assert "| 1 | 0.8 |" in r2.stdout


def test_tools_diagnose():
    import subprocess
    import sys

    tool = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "diagnose.py")
    r = subprocess.run([sys.executable, tool], capture_output=True,
                       text=True, timeout=300,
                       env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0
    assert "MXNet-TPU Info" in r.stdout and "Features" in r.stdout


def test_parse_log_prefix_metric_isolation(tmp_path):
    """accuracy vs accuracy_top5 must not contaminate each other and
    extra key=value text on the line is ignored."""
    import sys

    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    from parse_log import parse

    lines = [
        "Epoch[0] Train-accuracy=0.5 lr=0.01\n",
        "Epoch[0] Train-accuracy_top5=0.9\n",
        "Epoch[0] Time cost=3.5\n",
    ]
    cols, rows = parse(lines, ["accuracy", "accuracy_top5"])
    row = dict(zip(["epoch"] + cols, rows[0]))
    assert row["train-accuracy"] == 0.5      # not 0.01, not 0.9
    assert row["train-accuracy_top5"] == 0.9
    assert row["time"] == 3.5


def test_initializer_load_and_initdesc(tmp_path):
    """mx.init.Load (arg:/aux: stripping, shape checks, default
    fallback) + InitDesc (reference initializer.py:36,316)."""
    src = {"arg:w": mx.np.ones((2, 2)) * 3, "b": mx.np.zeros(2)}
    init = mx.init.Load(src, default_init=mx.init.Zero())
    w = mx.np.zeros((2, 2))
    init("w", w)
    assert (w.asnumpy() == 3).all()
    other = mx.np.ones(4)
    init("unseen", other)
    assert (other.asnumpy() == 0).all()
    with pytest.raises(MXNetError, match="shape"):
        init("w", mx.np.zeros((3, 3)))
    no_default = mx.init.Load({"w": mx.np.ones(2)})
    with pytest.raises(MXNetError, match="default"):
        no_default("missing", mx.np.zeros(2))
    d = mx.init.InitDesc("fc_weight", {"lr_mult": "2"})
    assert d == "fc_weight" and d.attrs["lr_mult"] == "2"
    assert isinstance(d, str)
    # attrs['__init__'] overrides the calling initializer (1.x Variable
    # init= attribute path, reference initializer.py:137-142)
    arr = mx.np.zeros(3)
    mx.init.Xavier()(mx.init.InitDesc("w", {"__init__": "one"}), arr)
    assert (arr.asnumpy() == 1).all()
    desc = mx.init.InitDesc("w")
    mx.init.One()(desc, mx.np.zeros(2))
    assert desc.global_init is not None
    # file form round-trips through npx.save
    f = str(tmp_path / "p.npz")
    mx.npx.save(f, {"w": mx.np.full((2,), 7.0)})
    got = mx.np.zeros(2)
    mx.init.Load(f)("w", got)
    assert (got.asnumpy() == 7).all()


def test_conftest_retry_decorator():
    """retry(n) (reference tests common.py:218): flaky assertion passes
    on a later attempt; non-assertion errors propagate immediately."""
    from conftest import retry

    calls = []

    @retry(3)
    def sometimes():
        calls.append(1)
        if len(calls) < 3:
            raise AssertionError("flake")
        return "ok"

    assert sometimes() == "ok" and len(calls) == 3

    @retry(2)
    def always():
        raise AssertionError("real failure")

    with pytest.raises(AssertionError, match="real"):
        always()

    @retry(3)
    def hard_error():
        raise ValueError("not retried")

    with pytest.raises(ValueError):
        hard_error()

"""Mesh-native KVStore('device') reduce + fused multi-tensor Trainer update.

Reference: src/kvstore/comm.h:474 CommDevice::Reduce (one collective, no
host staging) and src/operator/optimizer_op.cc:352 multi_sgd_update (all
params in one kernel). Oracle: the eager per-param Updater path.
"""
import jax
import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import autograd
from mxnet_tpu.gluon import nn, Trainer


def _per_device_values(shape, scale_by_rank):
    """One ndarray per CPU device, value = (rank+1)*scale."""
    devs = jax.devices()
    vals = []
    for r, d in enumerate(devs):
        raw = jax.device_put(
            onp.full(shape, float(r + 1) * scale_by_rank, "float32"), d)
        v = mx.np.zeros(shape)
        v._rebind(raw)
        vals.append(v)
    return vals, devs


def test_device_kvstore_mesh_reduce_exact():
    kv = mx.kv.create("device")
    shape = (4, 3)
    vals, devs = _per_device_values(shape, 1.0)
    n = len(devs)
    kv.init("k", mx.np.zeros(shape))
    kv.push("k", vals)
    out = mx.np.empty(shape)
    kv.pull("k", out=out)
    expect = sum(range(1, n + 1))
    onp.testing.assert_array_equal(out.asnumpy(), onp.full(shape, expect))


def test_device_kvstore_pushpull_keeps_placement():
    kv = mx.kv.create("device")
    shape = (2, 2)
    vals, devs = _per_device_values(shape, 2.0)
    n = len(devs)
    kv.init("k", mx.np.zeros(shape))
    kv.pushpull("k", vals, out=vals)
    expect = 2.0 * sum(range(1, n + 1))
    for r, v in enumerate(vals):
        onp.testing.assert_array_equal(v.asnumpy(), onp.full(shape, expect))
        assert next(iter(v._data.devices())) == devs[r], \
            f"rank {r} result moved off its device"


def test_device_kvstore_same_device_fallback():
    kv = mx.kv.create("device")
    shape = (3,)
    vals = [mx.np.full(shape, 1.0), mx.np.full(shape, 2.0)]  # same device
    kv.init("k", mx.np.zeros(shape))
    kv.push("k", vals)
    out = mx.np.empty(shape)
    kv.pull("k", out=out)
    onp.testing.assert_array_equal(out.asnumpy(), onp.full(shape, 3.0))


def _train_pair(optimizer, opt_kwargs, steps=3, seed=7):
    """Train two identical nets: fused Trainer vs eager per-param updater."""
    results = []
    for fused in (True, False):
        onp.random.seed(seed)
        mx.random.seed(seed)
        net = nn.Dense(5, in_units=4)
        net.initialize()
        # deterministic params
        net.weight.set_data(mx.np.array(
            onp.random.RandomState(0).randn(5, 4).astype("float32")))
        net.bias.set_data(mx.np.zeros((5,)))
        params = net.collect_params()
        tr = Trainer(params, optimizer, dict(opt_kwargs), kvstore=None)
        if not fused:
            tr._fused_update = False  # force the eager per-param path
        x = mx.np.array(onp.random.RandomState(1).randn(8, 4).astype("float32"))
        for s in range(steps):
            with autograd.record():
                y = net(x)
                loss = ((y - 1.0) ** 2).mean()
            loss.backward()
            tr.step(batch_size=1)
        results.append({k: p.data().asnumpy() for k, p in params.items()})
    return results


@pytest.mark.parametrize("optimizer,kwargs", [
    ("sgd", {"learning_rate": 0.1}),
    ("sgd", {"learning_rate": 0.1, "momentum": 0.9, "wd": 1e-4}),
    ("nag", {"learning_rate": 0.05, "momentum": 0.9}),
    ("adam", {"learning_rate": 1e-2, "wd": 1e-4}),
    ("adamw", {"learning_rate": 1e-2, "wd": 1e-2}),
    ("nadam", {"learning_rate": 1e-2}),
])
def test_fused_matches_eager(optimizer, kwargs):
    fused, eager = _train_pair(optimizer, kwargs)
    assert fused.keys() == eager.keys()
    for k in fused:
        onp.testing.assert_allclose(fused[k], eager[k], rtol=2e-6, atol=2e-6,
                                    err_msg=k)


def test_fused_adam_bias_correction_advances():
    """t must be traced: step 1 vs step 5 give different effective lr without
    retracing producing stale constants."""
    net = nn.Dense(1, in_units=1, use_bias=False)
    net.initialize()
    net.weight.set_data(mx.np.ones((1, 1)))
    tr = Trainer(net.collect_params(), "adam",
                 {"learning_rate": 0.1}, kvstore=None)
    x = mx.np.ones((1, 1))
    deltas = []
    for _ in range(5):
        before = float(net.weight.data().asnumpy()[0, 0])
        with autograd.record():
            loss = (net(x) ** 2).sum()
        loss.backward()
        tr.step(1)
        deltas.append(before - float(net.weight.data().asnumpy()[0, 0]))
    # oracle: eager updater on an identical problem
    net2 = nn.Dense(1, in_units=1, use_bias=False)
    net2.initialize()
    net2.weight.set_data(mx.np.ones((1, 1)))
    tr2 = Trainer(net2.collect_params(), "adam",
                  {"learning_rate": 0.1}, kvstore=None)
    tr2._fused_update = False
    for _ in range(5):
        with autograd.record():
            loss = (net2(x) ** 2).sum()
        loss.backward()
        tr2.step(1)
    onp.testing.assert_allclose(net.weight.data().asnumpy(),
                                net2.weight.data().asnumpy(),
                                rtol=1e-4, atol=1e-5)


def test_fused_respects_lr_schedule_without_retrace():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    net(mx.np.ones((1, 3)))
    tr = Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1},
                 kvstore=None)
    x = mx.np.ones((4, 3))
    for step, lr in enumerate([0.1, 0.01, 0.001]):
        tr.set_learning_rate(lr)
        w_before = net.weight.data().asnumpy().copy()
        with autograd.record():
            loss = net(x).sum()
        loss.backward()
        tr.step(1)
        delta = onp.abs(net.weight.data().asnumpy() - w_before).max()
        # |dw| = lr * |grad|; grad = sum of x over batch = 4
        onp.testing.assert_allclose(delta, lr * 4.0, rtol=1e-5)
    # traced lr: one compiled program served all three learning rates
    if tr._fused_update:
        assert tr._fused_update._jit._cache_size() == 1


def test_unfused_optimizer_falls_back():
    net = nn.Dense(2, in_units=3)
    net.initialize()
    tr = Trainer(net.collect_params(), "lamb", {"learning_rate": 0.01},
                 kvstore=None)
    x = mx.np.ones((2, 3))
    with autograd.record():
        loss = net(x).sum()
    loss.backward()
    tr.step(1)  # must not raise; lamb has no fused family
    assert tr._fused_update is False
    assert onp.isfinite(net.weight.data().asnumpy()).all()

"""mx.serve continuous-batching engine (docs/SERVING.md).

Oracles: the KV-cache decode surface against the full forward (bitwise
class of numerics — same matmul precision, different reduction extent),
continuous batching against sequential generation, the PR 2 recompile
detector as the zero-post-warmup-compile assertion, and the pipeline
sync_guard proving the decode loop never touches the host.
"""
import warnings

import numpy as onp
import pytest

import mxnet_tpu as mx
from mxnet_tpu import telemetry
from mxnet_tpu.gluon.model_zoo.gpt import GPTForCausalLM
from mxnet_tpu.serve import quantize as squant
from mxnet_tpu.serve.engine import EngineBusy, _parse_buckets


def _tiny(**kw):
    cfg = dict(vocab_size=97, units=32, hidden_size=64, num_layers=2,
               num_heads=2, max_length=32, dropout=0.0, embed_dropout=0.0)
    cfg.update(kw)
    net = GPTForCausalLM(**cfg)
    net.initialize()
    return net


def _engine(net=None, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("buckets", "4,8")
    return mx.serve.load(net if net is not None else _tiny(), **kw)


def _ref_greedy(net, prompt, n):
    """Greedy continuation via the full forward — the no-cache oracle."""
    seq = list(prompt)
    for _ in range(n):
        lg = net(mx.np.array(onp.array([seq], dtype="int32"))).asnumpy()
        seq.append(int(lg[0, -1].argmax()))
    return seq[len(prompt):]


@pytest.fixture
def metrics():
    telemetry.enable()
    telemetry.reset()
    yield
    telemetry.reset()
    telemetry.disable()


# -- block-level KV-cache surface -------------------------------------------

def test_prefill_matches_full_forward():
    mx.random.seed(0)
    net = _tiny()
    prompt = onp.random.RandomState(0).randint(1, 97, (1, 6)).astype("int32")
    full = net(mx.np.array(prompt)).asnumpy()
    caches = net.init_cache(max_slots=3, max_seq=16)
    logits, _ = net.prefill(mx.np.array(prompt), caches, 1)
    assert onp.allclose(logits.asnumpy(), full, atol=1e-5)


def test_decode_step_matches_full_forward():
    """Cached single-token decode must reproduce the full forward's last
    position, step after step, in an arbitrary slot."""
    mx.random.seed(1)
    net = _tiny()
    prompt = [3, 14, 15, 9, 2]
    caches = net.init_cache(max_slots=4, max_seq=16)
    slot = 2
    logits, caches = net.prefill(
        mx.np.array(onp.array([prompt], dtype="int32")), caches, slot)
    seq = list(prompt) + [int(logits.asnumpy()[0, -1].argmax())]
    for _ in range(5):
        tokens = onp.zeros((4, 1), dtype="int32")
        tokens[slot, 0] = seq[-1]
        positions = onp.zeros((4,), dtype="int32")
        positions[slot] = len(seq) - 1
        lg, caches = net.decode_step(mx.np.array(tokens), caches,
                                     mx.np.array(positions))
        ref = net(mx.np.array(onp.array([seq], dtype="int32"))).asnumpy()
        assert onp.allclose(lg.asnumpy()[slot], ref[0, -1], atol=1e-4)
        seq.append(int(lg.asnumpy()[slot].argmax()))


def test_init_cache_rejects_beyond_position_table():
    net = _tiny(max_length=16)
    with pytest.raises(ValueError):
        net.init_cache(max_slots=2, max_seq=64)


# -- engine correctness -----------------------------------------------------

def test_engine_greedy_matches_reference():
    mx.random.seed(2)
    net = _tiny()
    eng = _engine(net)
    rng = onp.random.RandomState(2)
    reqs = [eng.submit(rng.randint(1, 97, size=rng.randint(2, 8)).tolist(),
                       max_new_tokens=6) for _ in range(7)]
    eng.run()
    for r in reqs:
        assert r.finished
        assert r.generated == _ref_greedy(net, r.prompt, 6), r.id


def test_slot_reuse_waves():
    """More requests than slots: completions must free slots mid-flight
    and later requests must decode correctly in the reused slots."""
    mx.random.seed(3)
    net = _tiny()
    eng = _engine(net, max_slots=2, drain_window=2)
    rng = onp.random.RandomState(3)
    reqs = [eng.submit(rng.randint(1, 97, size=3 + (i % 4)).tolist(),
                       max_new_tokens=3 + (i % 3)) for i in range(9)]
    eng.run()
    assert all(r.finished for r in reqs)
    for r in reqs:
        assert r.generated == _ref_greedy(net, r.prompt, r.max_new_tokens)
    assert eng.stats()["completed"] == 9


def test_max_new_tokens_and_eos():
    mx.random.seed(4)
    net = _tiny()
    eng = _engine(net)
    r1 = eng.submit([5, 9, 3], max_new_tokens=4)
    eng.run()
    assert len(r1.generated) == 4
    eos = r1.generated[1]
    eng2 = _engine(net, eos_id=eos)
    r2 = eng2.submit([5, 9, 3], max_new_tokens=50)
    eng2.run()
    assert r2.generated == r1.generated[:2]  # stopped at the eos token
    assert r2.output_ids == r1.generated[:1]  # eos stripped


def test_generation_capped_by_max_seq():
    net = _tiny(max_length=16)
    eng = mx.serve.load(net, max_slots=2, max_seq=12, buckets="4,8")
    r = eng.submit([1, 2, 3, 4], max_new_tokens=500)
    eng.run()
    # positions stop at max_seq-1: 4 prompt rows + 8 generated contents
    assert len(r.generated) == 12 - 4
    assert r.finished


def test_prompt_longer_than_buckets_rejected():
    eng = _engine()
    with pytest.raises(mx.MXNetError):
        eng.submit(list(range(1, 20)), max_new_tokens=2)
    with pytest.raises(mx.MXNetError):
        eng.submit([], max_new_tokens=2)


def test_parse_buckets_validation():
    assert _parse_buckets("8,4,8") == [4, 8]
    with pytest.raises(mx.MXNetError):
        _parse_buckets("a,b")
    with pytest.raises(mx.MXNetError):
        _parse_buckets("-4")


def test_temperature_sampling_seeded():
    mx.random.seed(5)
    net = _tiny()
    outs = []
    for _ in range(2):
        eng = _engine(net, temperature=1.0, seed=11)
        r = eng.submit([5, 9, 3], max_new_tokens=8)
        eng.run()
        outs.append(r.generated)
    assert outs[0] == outs[1]  # same engine seed -> same stream
    eng = _engine(net, temperature=1.0, seed=12)
    r = eng.submit([5, 9, 3], max_new_tokens=8)
    eng.run()
    assert r.generated != outs[0]


def test_engine_requires_cache_surface():
    from mxnet_tpu.gluon import nn
    net = nn.Dense(4)
    net.initialize()
    with pytest.raises(mx.MXNetError):
        mx.serve.ServeEngine(net, max_seq=8)


def test_engine_stays_usable_after_run():
    """The engine is a persistent server: a second batch of requests
    reuses the same executables and cache."""
    mx.random.seed(6)
    net = _tiny()
    eng = _engine(net)
    eng.submit([4, 4, 4], max_new_tokens=3)
    eng.run()
    compiles = eng.compiles
    r = eng.submit([7, 7, 7], max_new_tokens=3)
    eng.run()
    assert r.finished
    assert eng.compiles == compiles
    assert r.generated == _ref_greedy(net, [7, 7, 7], 3)


# -- recompile guard (satellite: PR 2 detector as the assertion) ------------

def test_zero_recompiles_after_warmup(metrics):
    """After warmup over the bucket grid, a mixed request stream must
    trigger zero RecompileWarnings — the detector limit is pinned to the
    warmup compile count, so ANY further compile would fire it."""
    mx.random.seed(7)
    net = _tiny()
    eng = _engine(net, max_slots=3, buckets="4,8,16", drain_window=2)
    eng.warmup()
    assert eng.compiles == 4  # decode + 3 prefill buckets
    mx.config.set("telemetry.recompile_limit", eng.compiles)
    try:
        rng = onp.random.RandomState(7)
        with warnings.catch_warnings():
            warnings.simplefilter("error", telemetry.RecompileWarning)
            for i in range(12):
                eng.submit(rng.randint(1, 97,
                                       size=rng.randint(2, 16)).tolist(),
                           max_new_tokens=1 + (i % 5))
            eng.run()
    finally:
        mx.config.reset("telemetry.recompile_limit")
    assert eng.post_warmup_compiles == 0
    assert telemetry.counters().get(
        "serve.post_warmup_compiles_total") is None


def test_unwarmed_bucket_trips_detector(metrics):
    """Sanity check the guard has teeth: a compile past the limit DOES
    warn when a prompt shape escapes the warmed grid."""
    mx.random.seed(8)
    net = _tiny()
    eng = _engine(net, buckets="4")
    eng.warmup()
    eng.buckets = [4, 8]  # simulate an unwarmed bucket joining the grid
    mx.config.set("telemetry.recompile_limit", eng.compiles)
    try:
        with pytest.warns(telemetry.RecompileWarning):
            eng.submit([1] * 7, max_new_tokens=2)
            eng.run()
    finally:
        mx.config.reset("telemetry.recompile_limit")
    assert eng.post_warmup_compiles == 1


# -- sync-free loop ---------------------------------------------------------

def test_decode_loop_is_sync_free():
    """With a roomy drain window, dispatching admissions + decode steps
    must not touch the host; the drain at the end is the only sync."""
    mx.random.seed(9)
    net = _tiny()
    eng = _engine(net, drain_window=64)
    eng.warmup()
    for i in range(3):
        eng.submit([2 + i, 5, 9], max_new_tokens=8)
    # 1 admission step + enough decode steps to finish all 8 tokens:
    # completion is only OBSERVED at drain, so the guarded phase is
    # step-bounded — exactly the production cadence
    with mx.pipeline.sync_guard() as g:
        for _ in range(10):
            eng.step()
    assert g.count == 0, g.sites
    eng.drain()
    assert eng.stats()["completed"] == 3
    assert all(len(r.generated) == 8 for r in eng._completed)


def test_starved_queue_drains_bounded():
    """When the queue is starved for slots the engine reclaims oldest
    window entries, bounded by the queue depth — not a full drain."""
    mx.random.seed(10)
    net = _tiny()
    eng = _engine(net, max_slots=1, drain_window=8)
    rng = onp.random.RandomState(10)
    for _ in range(4):
        eng.submit(rng.randint(1, 97, size=3).tolist(), max_new_tokens=2)
    eng.run()
    assert eng.stats()["completed"] == 4


# -- weight-only int8 (satellite) -------------------------------------------

def test_quantize_roundtrip_error_bound():
    rng = onp.random.RandomState(0)
    w = rng.randn(64, 128).astype("float32")
    pt, qt, qdt = squant.quantize_params_int8({"w": w}, min_elements=1)
    assert not pt and list(qt) == ["w"]
    deq = squant.dequantize_params(pt, qt, qdt)["w"]
    # symmetric per-row int8: error <= scale/2 per row
    scale = onp.abs(w).max(axis=1, keepdims=True) / 127.0
    assert (onp.abs(onp.asarray(deq) - w) <= scale / 2 + 1e-7).all()


def test_quantize_skips_small_and_non2d():
    rng = onp.random.RandomState(1)
    params = {"big": rng.randn(128, 64).astype("float32"),
              "small": rng.randn(4, 4).astype("float32"),
              "vec": rng.randn(8192).astype("float32")}
    pt, qt, _ = squant.quantize_params_int8(params, min_elements=1024)
    assert set(qt) == {"big"} and set(pt) == {"small", "vec"}


def test_int8_engine_generates_and_shrinks_weights():
    mx.random.seed(11)
    net = _tiny(units=64, hidden_size=128)
    e8 = _engine(net, quantize="int8_weights")
    r8 = e8.submit([5, 9, 3], max_new_tokens=5)
    e8.run()
    st = e8.stats()
    assert st["weight_bytes"] < 0.5 * st["weight_bytes_fp"]
    assert len(r8.generated) == 5
    # tiny-model sanity: weight-only int8 shouldn't derail greedy decode
    efp = _engine(net)
    rfp = efp.submit([5, 9, 3], max_new_tokens=5)
    efp.run()
    agree = sum(a == b for a, b in zip(r8.generated, rfp.generated))
    assert agree >= 3, (r8.generated, rfp.generated)


def test_engine_rejects_unknown_quantize():
    with pytest.raises(mx.MXNetError):
        _engine(quantize="int4")


# -- int4 weights + int8 KV cache (tentpole) ---------------------------------

def test_int4_pack_roundtrip_and_bytes():
    rng = onp.random.RandomState(0)
    w = rng.randn(64, 256).astype("float32")
    pt, qt, qdt = squant.quantize_params_int4({"w": w}, min_elements=1)
    assert not pt and list(qt) == ["w"]
    packed, scales = qt["w"]
    assert onp.asarray(packed).dtype == onp.uint8
    assert onp.asarray(packed).shape == (64, 128)     # two nibbles/byte
    assert qdt["w"]["mode"] == "int4"
    deq = onp.asarray(squant.dequantize_params(pt, qt, qdt)["w"])
    # group-wise symmetric int4: error <= half a step per group
    g = qdt["w"]["group"]
    gmax = onp.abs(w.reshape(64, -1, g)).max(axis=2, keepdims=True)
    step = onp.broadcast_to(gmax / 7.0, w.reshape(64, -1, g).shape)
    assert (onp.abs(deq - w) <= step.reshape(64, 256) / 2 + 1e-7).all()
    now, was = squant.quantized_bytes(pt, qt, qdt)
    assert now / was <= 0.15, now / was                # the CI gate's bound


def test_int4_skips_odd_cols_and_non2d():
    rng = onp.random.RandomState(1)
    params = {"odd": rng.randn(64, 129).astype("float32"),
              "vec": rng.randn(8192).astype("float32"),
              "ok": rng.randn(64, 128).astype("float32")}
    pt, qt, _ = squant.quantize_params_int4(params, min_elements=1)
    assert set(qt) == {"ok"} and set(pt) == {"odd", "vec"}


def test_int4_engine_generates_and_shrinks_weights():
    # greedy on an untrained net is argmax over near-uniform logits —
    # seed chosen so fp32 decode has enough margin to survive 4-bit
    # weights (a trained model's logit margins are far larger)
    mx.random.seed(29)
    net = _tiny(units=64, hidden_size=128)
    e4 = _engine(net, quantize="int4_weights")
    r4 = e4.submit([5, 9, 3], max_new_tokens=5)
    e4.run()
    st = e4.stats()
    assert st["weight_bytes"] < 0.25 * st["weight_bytes_fp"]
    assert st["quantized_params"] > 0
    assert st["quantized_params"] + st["passthrough_params"] == \
        st["quantized_params"] + len(e4._params[0])
    assert len(r4.generated) == 5
    efp = _engine(net)
    rfp = efp.submit([5, 9, 3], max_new_tokens=5)
    efp.run()
    # 4-bit weights on a tiny random net: most greedy tokens still agree
    agree = sum(a == b for a, b in zip(r4.generated, rfp.generated))
    assert agree >= 3, (r4.generated, rfp.generated)


def test_int8_kv_cache_greedy_parity():
    """int8 KV storage quantizes each written row against its own absmax:
    on a well-scaled tiny model greedy decode must match fp32 KV."""
    mx.random.seed(14)
    net = _tiny()
    rng = onp.random.RandomState(14)
    prompts = [rng.randint(1, 97, size=rng.randint(2, 8)).tolist()
               for _ in range(5)]
    ekv = _engine(net, quantize="int8_kv")
    assert ekv.cache_dtype == "int8"
    assert ekv.stats()["cache_dtype"] == "int8"
    rkv = [ekv.submit(p, max_new_tokens=6) for p in prompts]
    ekv.run()
    efp = _engine(net)
    rfp = [efp.submit(p, max_new_tokens=6) for p in prompts]
    efp.run()
    match = sum(a.generated == b.generated for a, b in zip(rkv, rfp))
    assert match >= 4, [(a.generated, b.generated)
                        for a, b in zip(rkv, rfp)]


def test_int8_kv_cache_arrays_are_int8():
    net = _tiny()
    eng = _engine(net, quantize="int8_kv")
    (kq, ks), (vq, vs) = eng._cache[0]
    assert onp.asarray(kq).dtype == onp.int8
    assert onp.asarray(vq).dtype == onp.int8
    assert onp.asarray(ks).dtype == onp.float32
    assert ks.shape == kq.shape[:3] + (1,)   # per-(slot, row, head) scales


def test_combined_int4_weights_int8_kv():
    mx.random.seed(15)
    net = _tiny(units=64, hidden_size=128)
    eng = _engine(net, quantize="int4_weights,int8_kv")
    assert eng.quantize == "int4_weights,int8_kv"
    assert eng.cache_dtype == "int8"
    r = eng.submit([7, 2, 9], max_new_tokens=5)
    eng.run()
    assert len(r.generated) == 5
    st = eng.stats()
    assert st["weight_bytes"] < 0.25 * st["weight_bytes_fp"]


def test_conflicting_weight_modes_rejected():
    with pytest.raises(mx.MXNetError):
        _engine(quantize="int8_weights,int4_weights")


def test_zero_recompiles_with_quantization(metrics):
    """The low-bit cache pytree and dequant-on-read must not change the
    traced signature per step: PR 2's detector stays at zero after
    warmup in every quantize mode."""
    mx.random.seed(16)
    for spec in ("int8_weights", "int4_weights,int8_kv"):
        telemetry.reset()
        eng = _engine(_tiny(), quantize=spec)
        eng.warmup()
        for p in ([3, 1, 4], [1, 5], [9, 2, 6, 5]):
            eng.submit(p, max_new_tokens=4)
        eng.run()
        assert eng.stats()["post_warmup_compiles"] == 0, spec


def test_quantize_eligibility_knobs():
    rng = onp.random.RandomState(2)
    params = {"mid": rng.randn(32, 32).astype("float32")}   # 1024 elems
    pt, qt, _ = squant.quantize_params_int8(params)         # default 4096
    assert set(pt) == {"mid"} and not qt
    prev = mx.config.set("serve.quantize_min_elems", 512)
    try:
        pt, qt, _ = squant.quantize_params_int8(params)
        assert set(qt) == {"mid"}
    finally:
        mx.config.set("serve.quantize_min_elems", prev)
    prev = mx.config.set("serve.quantize_ndim", 1)
    try:
        pt, qt, _ = squant.quantize_params_int8(
            {"vec": rng.randn(8192).astype("float32")})
        assert set(qt) == {"vec"}                            # 1-D now eligible
    finally:
        mx.config.set("serve.quantize_ndim", prev)


def test_int4_group_size_knob():
    rng = onp.random.RandomState(3)
    w = rng.randn(8, 256).astype("float32")
    prev = mx.config.set("serve.quantize_group_size", 64)
    try:
        _, qt, qdt = squant.quantize_params_int4({"w": w}, min_elements=1)
    finally:
        mx.config.set("serve.quantize_group_size", prev)
    assert qdt["w"]["group"] == 64
    assert qt["w"][1].shape == (8, 4)                        # 256/64 groups


def test_quantized_param_counts_in_telemetry(metrics):
    mx.random.seed(17)
    eng = _engine(_tiny(units=64, hidden_size=128),
                  quantize="int8_weights")
    g = telemetry.snapshot()["gauges"]
    st = eng.stats()
    assert g["serve.quantized_params"] == st["quantized_params"] > 0
    assert g["serve.passthrough_params"] == st["passthrough_params"]


# -- serve.* telemetry ------------------------------------------------------

def test_serve_metrics_recorded(metrics):
    mx.random.seed(12)
    eng = _engine(drain_window=2)
    for _ in range(3):
        eng.submit([3, 1, 4], max_new_tokens=4)
    eng.run()
    c = telemetry.counters()
    assert c["serve.requests_total"] == 3
    assert c["serve.admitted_total"] == 3
    assert c["serve.completed_total"] == 3
    assert c["serve.tokens_total"] == 12
    assert c["serve.steps_total"] >= 3
    snap = telemetry.snapshot()
    assert snap["histograms"]["serve.ttft_seconds"]["count"] == 3
    assert snap["histograms"]["serve.tpot_seconds"]["count"] == 3
    assert "serve.step_seconds" in snap["histograms"]
    q = telemetry.quantiles("serve.ttft_seconds")
    assert set(q) == {"p50", "p95", "p99"}
    assert 0 <= q["p50"] <= q["p95"] <= q["p99"]
    st = eng.stats()
    assert st["ttft"]["p50"] is not None
    assert st["tpot"]["p99"] >= st["tpot"]["p50"]


# -- histogram quantiles (satellite) ----------------------------------------

def test_hist_quantile_estimation(metrics):
    for v in [0.001] * 50 + [0.008] * 40 + [0.3] * 10:
        telemetry.observe("q.lat", v)
    q = telemetry.quantiles("q.lat")
    assert q["p50"] == pytest.approx(0.001, abs=1e-6)
    assert 0.25 <= q["p95"] <= 0.5   # interpolated inside the 0.3 bucket
    assert 0.25 <= q["p99"] <= 0.5
    assert telemetry.quantiles("q.lat", qs=(0.999,))["p99_9"] <= 0.5
    assert telemetry.quantiles("nope") is None


def test_quantiles_in_snapshot_and_exposition(metrics):
    telemetry.observe("q.x", 0.004)
    telemetry.observe("q.x", 0.07)
    snap = telemetry.snapshot()
    assert set(snap["histograms"]["q.x"]["quantiles"]) == {"50", "95", "99"}
    import json
    json.dumps(snap)  # stays JSON-safe
    text = telemetry.exposition()
    assert 'mxnet_q_x{quantile="0.5"}' in text
    assert 'mxnet_q_x{quantile="0.99"}' in text
    # quantile estimates stay within the recorded value range's bucket
    line = [l for l in text.splitlines() if 'quantile="0.99"' in l][0]
    assert float(line.split()[-1]) <= 0.1


def test_quantiles_ride_jsonl_reports(metrics, tmp_path):
    rep = telemetry.TrainingTelemetry(path=str(tmp_path / "run.jsonl"),
                                      interval=100)
    telemetry.observe("q.y", 0.01)
    rep.close()
    records = telemetry.TrainingTelemetry.read(str(tmp_path / "run.jsonl"))
    final = [r for r in records if r.get("type") == "run_report"][-1]
    hists = final["metrics"]["histograms"]
    assert "quantiles" in hists["q.y"]


# -- graceful drain, backpressure, /healthz ---------------------------------

def test_submit_backpressure_bounded_queue(metrics):
    prev = mx.config.set("serve.max_queue", 2)
    try:
        eng = _engine()
        eng.submit([1, 2, 3], max_new_tokens=2)
        eng.submit([4, 5], max_new_tokens=2)
        with pytest.raises(EngineBusy) as ei:
            eng.submit([6], max_new_tokens=2)
        assert ei.value.reason == "queue_full"
        assert ei.value.queued == 2 and ei.value.max_queue == 2
        assert telemetry.counters(aggregate=True).get(
            "serve.rejected_total") == 1
        eng.run()                        # queue drains: admission reopens
        assert eng.submit([7], max_new_tokens=1) is not None
        eng.stop()
    finally:
        mx.config.set("serve.max_queue", prev)


def test_stop_drain_finishes_in_flight_and_rejects_new(metrics):
    eng = _engine()
    reqs = [eng.submit([1, 2, 3], max_new_tokens=3) for _ in range(3)]
    eng.stop(drain=True)
    assert all(r.finished for r in reqs)
    with pytest.raises(EngineBusy) as ei:
        eng.submit([4], max_new_tokens=1)
    assert ei.value.reason == "stopping"
    eng.stop()                           # idempotent


def test_stop_no_drain_discards_queued(metrics):
    eng = _engine(max_slots=1)
    a = eng.submit([1, 2], max_new_tokens=2)
    b = eng.submit([3, 4], max_new_tokens=2)
    eng.stop(drain=False)
    assert not a.finished and not b.finished and not eng.pending
    assert telemetry.counters(aggregate=True).get(
        "serve.rejected_total") == 2


def test_stop_no_drain_every_queued_request_observes_rejection(metrics):
    """stop(drain=False) must leave NO queued request ambiguous: each
    one flips rejected=True with a machine-readable reason, so a caller
    holding the handle distinguishes 'discarded' from 'still running'
    without string-matching logs."""
    eng = _engine(max_slots=1)
    reqs = [eng.submit([1, 2, 3], max_new_tokens=2) for _ in range(5)]
    eng.stop(drain=False)
    queued = [r for r in reqs if not r.finished and r.slot is None]
    assert queued, "expected still-queued requests at stop time"
    for r in queued:
        assert r.rejected is True
        assert r.reject_reason == "stopping"
    # requests that reached a slot are unfinished but NOT rejected:
    # their state is 'abandoned in flight', a different contract
    for r in reqs:
        if r not in queued:
            assert not r.rejected
    by_reason = {k: v for k, v in telemetry.counters().items()
                 if k.startswith("serve.rejected_total")}
    assert any('reason="stopping"' in k for k in by_reason), by_reason
    assert sum(by_reason.values()) == len(queued)


def test_engine_busy_carries_retry_after_hint(metrics):
    """EngineBusy.retry_after_hint = queue depth x observed TPOT p50 —
    the machine-readable backoff the fleet router consumes instead of
    hammering a saturated replica."""
    prev = mx.config.set("serve.max_queue", 2)
    try:
        eng = _engine(max_slots=1)
        # one completed request seeds the TPOT p50 observation
        eng.submit([1, 2, 3], max_new_tokens=4)
        eng.run()
        p50 = eng._tpot_p50()
        assert p50 > 0
        eng.submit([1, 2], max_new_tokens=2)
        eng.submit([3, 4], max_new_tokens=2)
        with pytest.raises(EngineBusy) as ei:
            eng.submit([5], max_new_tokens=1)
        assert ei.value.reason == "queue_full"
        assert ei.value.retry_after_hint == pytest.approx(2 * p50)
        assert f"{ei.value.retry_after_hint:.3f}" in str(ei.value)
        eng.run()
        eng.stop()
        with pytest.raises(EngineBusy) as ei:
            eng.submit([6], max_new_tokens=1)
        assert ei.value.reason == "stopping"
        assert ei.value.retry_after_hint > 0  # floor: one p50 interval
    finally:
        mx.config.set("serve.max_queue", prev)


def test_engine_healthz_tracks_step_loop(metrics):
    eng = _engine()
    _, checks = telemetry.health()
    assert checks["serve"]["state"] == "idle" and checks["serve"]["ok"]
    eng.submit([1, 2], max_new_tokens=2)
    prev = mx.config.set("serve.health_window", 0.0)
    try:
        ok, checks = telemetry.health()
        assert ok is False and checks["serve"]["state"] == "serving"
    finally:
        mx.config.set("serve.health_window", prev)
    eng.run()
    assert telemetry.health()[1]["serve"]["ok"] is True
    eng.stop()
    assert "serve" not in telemetry.health()[1]


# -- SLO budgets + always-on phase reservoir (docs/OBSERVABILITY.md) --------

def test_slo_violations_counted_and_burn_gauge(metrics):
    prev = [mx.config.set("serve.slo_ttft_ms", 0.0001),
            mx.config.set("serve.slo_tpot_ms", 0.0001),
            mx.config.set("serve.slo_target", 0.9)]
    try:
        eng = _engine()
        eng.submit([5, 9, 3], max_new_tokens=4)
        eng.run()
        counters = telemetry.counters()
        viol = {k: v for k, v in counters.items()
                if k.startswith("serve.slo_violations_total")}
        assert sum(viol.values()) >= 1, counters
        assert any('kind="ttft"' in k for k in viol), viol
        burn = eng.slo_burn()
        assert burn and max(burn.values()) > 2.0
        slo = eng.stats()["slo"]
        assert slo["violations"]["ttft"] >= 1
        assert slo["burn"] == burn
        # a hot burn rate flips the engine health check red
        ok, checks = telemetry.health()
        assert ok is False and checks["serve"]["state"] == "slo_burn"
        eng.stop()
    finally:
        mx.config.set("serve.slo_ttft_ms", prev[0])
        mx.config.set("serve.slo_tpot_ms", prev[1])
        mx.config.set("serve.slo_target", prev[2])


def test_slo_disarmed_by_default(metrics):
    eng = _engine()
    eng.submit([5, 9], max_new_tokens=2)
    eng.run()
    assert eng.slo_burn() == {}
    assert "slo" not in eng.stats()
    assert not any(k.startswith("serve.slo_violations_total")
                   for k in telemetry.counters())
    eng.stop()


def test_phase_reservoir_without_tracer(metrics):
    # stats()["phases"] populates from the bounded reservoir even when
    # the request tracer is off
    eng = _engine()
    for _ in range(2):
        eng.submit([5, 9, 3], max_new_tokens=3)
    eng.run()
    phases = eng.stats()["phases"]
    for label in ("queue_wait", "prefill", "decode_per_token"):
        assert phases[label] is not None, phases
        assert phases[label]["p50"] >= 0.0
    eng.stop()


def test_phase_reservoir_disabled_and_bounded(metrics):
    prev = mx.config.set("serve.phase_sampling", 0)
    try:
        eng = _engine()
        eng.submit([5, 9], max_new_tokens=2)
        eng.run()
        assert all(v is None                   # off and no tracer
                   for v in eng.stats()["phases"].values())
        eng.stop()
    finally:
        mx.config.set("serve.phase_sampling", prev)
    prev = mx.config.set("serve.phase_sampling", 2)
    try:
        eng = _engine()
        req = eng.submit([5, 9, 3], max_new_tokens=6)
        eng.run()
        assert len(req.phases["decode_step"]) <= 2   # reservoir cap
        eng.stop()
    finally:
        mx.config.set("serve.phase_sampling", prev)
